"""Device-resident exploration campaigns — one host sync per generation.

The host driver (explore/driver.py) round-trips through numpy every
generation: corpus selection, mutation and admission all run on the
host while the accelerator idles, and the whole per-seed result state
crosses the PCIe boundary each dispatch. This module is the same
campaign loop restated as a device program:

* the **corpus lives in device memory** as fixed-capacity column arrays
  (plan rows, seeds, traces, coverage signatures, ids — one row per
  admitted entry);
* **mutation** is a vectorized jnp kernel (:func:`_mutate_child` under
  ``vmap``) that emulates the host edit script *draw for draw*: the
  same threefry counters, the same modulo reductions, the same
  branch structure as ``HostStream`` + ``mutate_plan`` — so a device
  campaign breeds bit-identical children (the parity test pins it);
* **admission** is one ``lax.scan`` over the generation in batch order
  (popcount-delta against the global map + the (seed, trace) violation
  dedup), with the winners scattered into the corpus arrays;
* the whole generation — derive keys, pick parents, mutate, simulate
  (``engine.make_sweep``), admit — is ONE jitted program per mode
  (uniform / breeding), built once per campaign *shape* and served
  from the generation-program cache (``_GEN_CACHE``, the
  ``engine.search._RUN_CACHE`` discipline): the campaign root seed and
  generation index are runtime arguments, so a multi-campaign session
  re-traces NOTHING (profiler-certified — ``obs.prof`` counts exactly
  one trace per cache key, where each campaign historically re-paid
  the full trace+lower+compile from fresh closures). With a ``mesh``,
  mutation and simulation run under ``shard_map`` across chips (corpus
  replicated, the (seed, plan) batch sharded — the multi-process pjit
  shape); the cross-shard metric/latency folds reuse
  ``parallel.merge_metrics`` / ``merge_latency``, and the admission
  scan consumes the gathered per-seed coverage rows without ever
  leaving the device.

The host sees exactly one synchronization point per generation: the
admission summary (corpus size, new-entry count, coverage bits,
violation count) and — when logging asks for them — the fresh
violation keys. Per-seed state never reaches the host until the final
report (or a checkpoint) materializes the corpus once.

Campaign outcomes are **bit-identical to the host driver** given the
same arguments: same corpus (ids, seeds, plans, traces, new-bit
scores), same coverage map, same violations, same replay keys — the
device path is a lowering, not a fork. ``checkpoint_path`` / ``resume``
interoperate with host-driver checkpoints in both directions.

History hunts go device-resident too: ``history_check`` (a
``check.device.HistoryScreen`` set) traces the vectorized batch
detectors INTO the generation program — the detector's verdict folds
into the violation mask right next to the sweep that recorded the
histories, the screen identity joins the ``_GEN_CACHE`` key, and a
guided hunt over history bugs (lost writes, election safety,
recovery regressions) runs end-to-end without a host round-trip.
Finds replay on the host driver via
``check.device.screens_invariant(screens)`` — bit-identical verdicts,
so the two drivers still agree corpus-for-corpus.

Limitations vs the host driver: the invariant must be a *traceable*
final-state predicate (jnp ops over the state view — it runs inside
the device program; numpy-only predicates and arbitrary host
``history_invariant`` callables beyond the screen set need the host
driver), and ``compact=True`` has no device equivalent (the sweep
runs ``make_run_while``).
"""

from __future__ import annotations

import os as _os
import time as _time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P_

from ..chaos.plan import FaultEvent, FaultPlan, LiteralPlan, stack_plan_rows
from ..engine.core import PlanRows, _resolve_time32
from ..engine.rng import PURPOSE_EXPLORE, threefry2x32
from ..engine.search import make_sweep
from .driver import CorpusEntry, ExploreReport, _pad_literal
from .mutate import (
    MODE_NODE,
    MODE_PAIR,
    MODE_RETIME,
    MODE_SKEW,
    MODE_SLOW,
    PlanSpace,
    inherit_threshold,
    mutation_table,
)

__all__ = ["gen_cache_stats", "run_device"]


def _kth_true(mask, k):
    """Index of the (k+1)-th True of ``mask`` — the device form of the
    host's ``index_list[k]`` pick (callers guarantee k < popcount)."""
    cum = jnp.cumsum(mask.astype(jnp.int32))
    return jnp.argmax(mask & (cum == k + 1)).astype(jnp.int32)


def _mk_seeds(k0s, k1s):
    return k0s.astype(jnp.uint64) | (k1s.astype(jnp.uint64) << jnp.uint64(32))


# ---------------------------------------------------------------------------
# the vectorized mutator — HostStream + mutate_plan, draw for draw
# ---------------------------------------------------------------------------


def _make_child_mutator(tb, max_ops: int, inherit_thresh: int):
    """Build ``child(k0, k1, fresh_seed, order, olen, cr) -> dict`` —
    the device form of one batch slot's host edit script:

        st = HostStream(k0, k1, PURPOSE_EXPLORE)
        pid = order[st.bits() % len(order)]          # draw 0
        inherit = st.bits() < inherit_thresh          # draw 1
        child = mutate_plan(parent, space, st, ...)   # draws 2..

    Every draw is ``threefry2x32(k0, k1, j, PURPOSE_EXPLORE)[0]`` at
    the same running counter ``j`` the HostStream would use; branches
    advance ``j`` by exactly the number of draws the host branch
    consumes (``mutate.RETARGET_DRAWS``), so the two edit scripts stay
    aligned no matter which ops fire.
    """
    X1 = jnp.uint32(PURPOSE_EXPLORE)
    t_lo, t_hi = tb["t_lo"], tb["t_hi"]
    mode, rt_d = tb["mode"], tb["rt_draws"]
    tgt, tcnt = tb["tgt"], tb["tcnt"]
    mult_lo, mult_hi = tb["mult_lo"], tb["mult_hi"]
    skew_lo, skew_hi = tb["skew_lo"], tb["skew_hi"]
    p_slots = int(t_lo.shape[0])

    def bits(k0, k1, j):
        a, _ = threefry2x32(k0, k1, j, X1)
        return a

    def child(k0, k1, fresh_seed, order, olen, cr):
        w0 = bits(k0, k1, jnp.uint32(0)).astype(jnp.int64)
        pslot = order[(w0 % olen).astype(jnp.int32)]
        w1 = bits(k0, k1, jnp.uint32(1)).astype(jnp.int64)
        inherit = w1 < jnp.int64(inherit_thresh)
        seed = jnp.where(inherit, cr["cs"][pslot], fresh_seed)
        halt = cr["chalt"][pslot]
        has_h = halt > 0
        w2 = bits(k0, k1, jnp.uint32(2)).astype(jnp.int64)
        n_ops = 1 + (w2 % max(max_ops, 1))

        def retime(sel, told, cw, vw):
            lo = t_lo[sel]
            hi0 = t_hi[sel]
            # the parent's causal window: an event past the halt clock
            # can never change the trajectory (mutate._retime)
            hi = jnp.where(has_h & (lo < halt) & (halt < hi0), halt, hi0)
            delta = jnp.maximum((hi - lo) // 8, 1)
            tf = jnp.clip(told + (-delta + vw % (2 * delta + 1)), lo, hi - 1)
            tc = lo + vw % jnp.maximum(hi - lo, 1)
            return jnp.where((cw % 2) == 0, tf, tc)

        def pick_tgt(sel, w):
            k = (w % jnp.maximum(tcnt[sel].astype(jnp.int64), 1)).astype(
                jnp.int32
            )
            return tgt[sel, k]

        def pick_tgt_ne(sel, a, w):
            # the host's [t for t in targets if t != a] pick: exclusion
            # is by VALUE, order preserved
            row = tgt[sel]
            ok = (jnp.arange(row.shape[0]) < tcnt[sel]) & (row != a)
            cnt = ok.sum().astype(jnp.int64)
            m = (w % jnp.maximum(cnt, 1)).astype(jnp.int32)
            return row[_kth_true(ok, m)]

        def body(it, carry):
            j, t, a0, a1, en = carry
            active = it < n_ops
            wlane, _ = threefry2x32(
                k0, k1, j + jnp.arange(7, dtype=jnp.uint32), X1
            )
            w = wlane.astype(jnp.int64)
            op = w[0] % 8
            n_on = en.sum().astype(jnp.int64)
            n_off = p_slots - n_on
            alive = en & (t < halt)
            n_alive = alive.sum().astype(jnp.int64)
            use_alive = has_h & (n_alive > 0)
            sel_mask = jnp.where(use_alive, alive, en)
            sel_cnt = jnp.where(use_alive, n_alive, n_on)
            # mutate_plan's if/elif chain, one branch per op
            b_add = (op == 0) & (n_off > 0)
            b_drop = (op == 1) & (n_on > 1)
            b_ret = ((op == 2) | (op == 3)) & (n_on > 0)
            b_time = ~(b_add | b_drop | b_ret) & (n_on > 0)
            b_fadd = ~(b_add | b_drop | b_ret | b_time) & (n_off > 0)
            any_add = b_add | b_fadd
            k_off = (w[1] % jnp.maximum(n_off, 1)).astype(jnp.int32)
            k_on = (w[1] % jnp.maximum(sel_cnt, 1)).astype(jnp.int32)
            sel = jnp.where(any_add, _kth_true(~en, k_off),
                            _kth_true(sel_mask, k_on))
            m = mode[sel]
            rd = rt_d[sel].astype(jnp.int64)
            is_fb = m == MODE_RETIME
            t_sel = t[sel]
            # add/force-add and plain-retime both draw (choose, value)
            # at w[2], w[3]; retarget draws start at w[4] after an add's
            # retime, at w[2] otherwise
            t_rt1 = retime(sel, t_sel, w[2], w[3])
            rw0 = jnp.where(any_add, w[4], w[2])
            rw1 = jnp.where(any_add, w[5], w[3])
            rw2 = jnp.where(any_add, w[6], w[4])
            # fallback retarget = a second retime (reading the time the
            # add's first retime just wrote, exactly like the host's
            # in-place event list)
            t_fb = retime(sel, jnp.where(any_add, t_rt1, t_sel), rw0, rw1)
            aa = pick_tgt(sel, rw0)
            bb = pick_tgt_ne(sel, aa, rw1)
            mult = mult_lo[sel] + rw2 % jnp.maximum(
                mult_hi[sel] + 1 - mult_lo[sel], 1
            )
            slow_a1 = ((bb + 1) & 0xFF) | (mult << 8)
            skew = skew_lo[sel] + rw1 % jnp.maximum(
                skew_hi[sel] + 1 - skew_lo[sel], 1
            )
            a0_sel = a0[sel].astype(jnp.int64)
            a1_sel = a1[sel].astype(jnp.int64)
            new_a0 = jnp.select(
                [m == MODE_NODE, m == MODE_PAIR, m == MODE_SLOW,
                 m == MODE_SKEW],
                [aa, aa, aa, aa], a0_sel,
            )
            new_a1 = jnp.select(
                [m == MODE_NODE, m == MODE_PAIR, m == MODE_SLOW,
                 m == MODE_SKEW],
                [a1_sel, bb, slow_a1, skew], a1_sel,
            )
            t_add = jnp.where(is_fb, t_fb, t_rt1)
            t_ret = jnp.where(is_fb, t_fb, t_sel)
            new_t = jnp.where(
                any_add, t_add,
                jnp.where(b_ret, t_ret,
                          jnp.where(b_time, t_rt1, t_sel)),
            )
            write_t = active & (any_add | b_ret | b_time)
            write_a = active & (any_add | b_ret)
            t2 = t.at[sel].set(jnp.where(write_t, new_t, t_sel))
            a02 = a0.at[sel].set(
                jnp.where(write_a, new_a0, a0_sel).astype(jnp.int32)
            )
            a12 = a1.at[sel].set(
                jnp.where(write_a, new_a1, a1_sel).astype(jnp.int32)
            )
            en2 = en.at[sel].set(
                jnp.where(active & any_add, True,
                          jnp.where(active & b_drop, False, en[sel]))
            )
            cost = jnp.where(
                any_add, 4 + rd,
                jnp.where(b_drop, 2,
                          jnp.where(b_ret, 2 + rd,
                                    jnp.where(b_time, 4, 0))),
            )
            j2 = j + jnp.where(active, cost, 0).astype(jnp.uint32)
            return j2, t2, a02, a12, en2

        t0 = cr["ct"][pslot]
        a0_0 = cr["ca"][pslot, :, 0]
        a1_0 = cr["ca"][pslot, :, 1]
        en0 = cr["cv"][pslot]
        _, t, a0, a1, en = lax.fori_loop(
            0, max(max_ops, 1), body, (jnp.uint32(3), t0, a0_0, a1_0, en0)
        )
        return dict(
            seed=seed,
            time=t,
            kind=cr["ck"][pslot],
            args=jnp.stack([a0, a1], axis=-1),
            valid=en,
            node=cr["cn"][pslot],
            parent=cr["cid"][pslot],
        )

    return child


# ---------------------------------------------------------------------------
# carry <-> host state
# ---------------------------------------------------------------------------

_ROW_KEYS = ("time", "kind", "args", "valid", "node")


def _empty_store(cap1, p, cw):
    """One entry store (corpus or violation) of ``cap1`` rows — the
    last row is scatter trash for refused candidates, never read."""
    return dict(
        time=jnp.zeros((cap1, p), jnp.int64),
        kind=jnp.zeros((cap1, p), jnp.int32),
        args=jnp.zeros((cap1, p, 2), jnp.int32),
        valid=jnp.zeros((cap1, p), jnp.bool_),
        node=jnp.zeros((cap1, p), jnp.int32),
        seed=jnp.zeros((cap1,), jnp.uint64),
        trace=jnp.zeros((cap1,), jnp.uint64),
        cov=jnp.zeros((cap1, cw), jnp.uint32),
        new_bits=jnp.zeros((cap1,), jnp.int32),
        id=jnp.full((cap1,), -1, jnp.int32),
        parent=jnp.full((cap1,), -1, jnp.int32),
        gen=jnp.zeros((cap1,), jnp.int32),
        viol=jnp.zeros((cap1,), jnp.bool_),
        halt=jnp.zeros((cap1,), jnp.int64),
        bslot=jnp.full((cap1,), -1, jnp.int32),
    )


def _fill_store(store, entries):
    """Load checkpointed CorpusEntry rows into a device store (slot i =
    entries[i], admission order — ids stay whatever the campaign
    assigned)."""
    if not entries:
        return store
    rows = stack_plan_rows([e.plan for e in entries])
    n = len(entries)
    out = dict(store)
    out["time"] = store["time"].at[:n].set(jnp.asarray(rows.time, jnp.int64))
    out["kind"] = store["kind"].at[:n].set(jnp.asarray(rows.kind, jnp.int32))
    out["args"] = store["args"].at[:n].set(jnp.asarray(rows.args, jnp.int32))
    out["valid"] = store["valid"].at[:n].set(
        jnp.asarray(rows.valid, jnp.bool_)
    )
    out["node"] = store["node"].at[:n].set(jnp.asarray(rows.node, jnp.int32))
    out["seed"] = store["seed"].at[:n].set(
        jnp.asarray([e.seed for e in entries], jnp.uint64)
    )
    out["trace"] = store["trace"].at[:n].set(
        jnp.asarray([e.trace for e in entries], jnp.uint64)
    )
    out["cov"] = store["cov"].at[:n].set(
        jnp.asarray(np.stack([np.asarray(e.cov, np.uint32) for e in entries]))
    )
    out["new_bits"] = store["new_bits"].at[:n].set(
        jnp.asarray([e.new_bits for e in entries], jnp.int32)
    )
    out["id"] = store["id"].at[:n].set(
        jnp.asarray([e.id for e in entries], jnp.int32)
    )
    out["parent"] = store["parent"].at[:n].set(
        jnp.asarray([e.parent for e in entries], jnp.int32)
    )
    out["gen"] = store["gen"].at[:n].set(
        jnp.asarray([e.generation for e in entries], jnp.int32)
    )
    out["viol"] = store["viol"].at[:n].set(
        jnp.asarray([e.violating for e in entries], jnp.bool_)
    )
    out["halt"] = store["halt"].at[:n].set(
        jnp.asarray([e.halt_t for e in entries], jnp.int64)
    )
    return out


def _store_entry(st_np, i, name) -> CorpusEntry:
    """Materialize store row ``i`` back into a CorpusEntry."""
    events = tuple(
        FaultEvent(
            t=int(st_np["time"][i, p]),
            kind=int(st_np["kind"][i, p]),
            a0=int(st_np["args"][i, p, 0]),
            a1=int(st_np["args"][i, p, 1]),
            node=int(st_np["node"][i, p]),
        )
        for p in range(st_np["time"].shape[1])
    )
    return CorpusEntry(
        id=int(st_np["id"][i]),
        generation=int(st_np["gen"][i]),
        parent=int(st_np["parent"][i]),
        seed=int(st_np["seed"][i]),
        plan=LiteralPlan(
            events=events,
            enabled=tuple(bool(x) for x in st_np["valid"][i]),
            name=name,
        ),
        trace=int(st_np["trace"][i]),
        cov=np.asarray(st_np["cov"][i], np.uint32).copy(),
        new_bits=int(st_np["new_bits"][i]),
        violating=bool(st_np["viol"][i]),
        halt_t=int(st_np["halt"][i]),
    )


# ---------------------------------------------------------------------------
# the generation-program cache
# ---------------------------------------------------------------------------

# generation-program cache, the engine.search._RUN_CACHE discipline at
# campaign scope: run_device historically rebuilt its uniform/breed
# programs from fresh closures EVERY call, so jit's function-identity
# cache missed and every campaign re-paid trace+lower+compile (ROADMAP
# item 1; the flight recorder measured it before this cache killed it).
# Keyed on (workload identity, config, space hash, batch, build flags,
# invariant identity, mesh, seed-corpus literals) — everything baked
# into the traced program. The ROOT SEED is deliberately NOT in the
# key: it enters the programs as a runtime argument, so a multi-
# campaign session over fresh root seeds reuses one compiled program
# per key (profiler-certified: retraces == 1). Entries hold
# obs.prof.AotProgram pairs, so every build is phase-timed and
# retrace-counted. Bounded LRU (compiled executables are not free, and
# a farm time-slicing N tenants in round-robin order would thrash a
# FIFO into evicting exactly the program it is about to need again);
# MADSIM_GEN_CACHE_MAX overrides the bound, evictions are counted
# loudly (gen_cache_stats -> flight_summary). Hold ONE
# workload/invariant object across campaigns to hit the cache, exactly
# like engine.search.
_GEN_CACHE: dict = {}
_GEN_CACHE_MAX = 8
_GEN_CACHE_EVICTIONS = 0


def _gen_cache_max() -> int:
    raw = _os.environ.get("MADSIM_GEN_CACHE_MAX")
    if raw is None:
        return _GEN_CACHE_MAX
    try:
        return max(int(raw), 1)
    except ValueError:
        raise ValueError(
            f"MADSIM_GEN_CACHE_MAX={raw!r} is not an integer"
        ) from None


def gen_cache_stats() -> dict:
    """Generation-program cache accounting: live entries, the effective
    bound (``MADSIM_GEN_CACHE_MAX``) and lifetime evictions. The flight
    recorder folds this into ``flight_summary`` — a growing eviction
    count in a farm session means more tenant shapes than cache slots,
    each switch re-paying trace+lower+compile; raise the knob."""
    return {
        "entries": len(_GEN_CACHE),
        "max": _gen_cache_max(),
        "evictions": _GEN_CACHE_EVICTIONS,
    }


def _mesh_key(mesh):
    """Value identity of a mesh: same devices + axes = same programs
    (mesh OBJECTS are routinely rebuilt between campaigns)."""
    if mesh is None:
        return None
    return (
        tuple(d.id for d in mesh.devices.flat),
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
    )


def _gen_programs(key, builder):
    global _GEN_CACHE_EVICTIONS
    progs = _GEN_CACHE.get(key)
    if progs is None:
        cap = _gen_cache_max()
        while len(_GEN_CACHE) >= cap:
            _GEN_CACHE.pop(next(iter(_GEN_CACHE)))
            _GEN_CACHE_EVICTIONS += 1
        progs = _GEN_CACHE[key] = builder()
    else:
        # LRU touch: re-insertion moves the entry to the back of the
        # eviction order (dicts iterate in insertion order)
        _GEN_CACHE[key] = _GEN_CACHE.pop(key)
    return progs[0], progs[1]


def _build_programs(
    wl, cfg, space, *, invariant, batch, max_steps, cov_words, layout,
    require_halt, select_top, max_corpus, vcap, max_ops, inherit_seed_p,
    cov_hitcount, metrics, latency, mesh, seed_corpus, cache_key,
    pool_index=None, history_check=None, causal=False, retry=None,
):
    """Build one cache entry: the (uniform, breed, refs) triple.

    Both programs take ``(carry, g, rk0, rk1)`` — the generation index
    and the campaign root key are runtime arguments (same threefry
    coordinates as the host driver's ``_derive_keys``), so one compiled
    program serves every root seed and every generation. ``refs`` pins
    the objects whose id() participates in the cache key.
    """
    from ..obs.prof import AotProgram

    n_dev = int(mesh.devices.size) if mesh is not None else 1
    b_loc = batch // n_dev
    axes = mesh.axis_names if mesh is not None else None
    p_slots = space.slots
    dup = space.uses_dup()
    cmax1 = max_corpus + 1
    vcap1 = vcap + 1
    tb = {k: jnp.asarray(v) for k, v in mutation_table(space).items()}
    mutator = _make_child_mutator(
        tb, max_ops, inherit_threshold(inherit_seed_p)
    )
    sweep = make_sweep(
        wl, cfg, max_steps, layout=layout, plan_slots=p_slots,
        dup_rows=dup, cov_words=cov_words, metrics=metrics,
        timeline_cap=0, cov_hitcount=cov_hitcount, latency=latency,
        pool_index=pool_index, causal=causal, retry=retry,
    )
    k_ov = len(seed_corpus)
    if k_ov:
        ov = stack_plan_rows([_pad_literal(lp, p_slots) for lp in seed_corpus])
        ov = {f: jnp.asarray(getattr(ov, f)) for f in _ROW_KEYS}

    def derive_keys(g, jglob, rk0, rk1):
        # driver._derive_keys: x0 = generation, x1 = PURPOSE_EXPLORE+slot
        return threefry2x32(
            rk0, rk1, g, jnp.uint32(PURPOSE_EXPLORE) + jglob.astype(jnp.uint32)
        )

    def run_children(seeds, rows):
        view = sweep(seeds, rows)
        if invariant is not None:
            ok = jnp.asarray(invariant(view), jnp.bool_)
            if ok.shape != seeds.shape:
                raise ValueError(
                    f"invariant must return a {seeds.shape} boolean "
                    f"array, got shape {ok.shape}"
                )
        else:
            ok = jnp.ones(seeds.shape, jnp.bool_)
        if history_check is not None:
            # the device history screen, traced WITH the sweep into the
            # generation program: verdicts fold into the violation mask
            # right where the histories were recorded — per-seed
            # history columns never leave the device
            from ..check.device import screen_ok as _screen_ok

            ok = ok & _screen_ok(
                history_check, view["hist_word"], view["hist_t"],
                view["hist_count"], view["hist_drop"],
            )
        if require_halt:
            ok = ok & view["halted"]
        over = view["overflow"] > 0
        if wl.history is not None:
            over = over | (view["hist_drop"] > 0)
        cols = dict(
            trace=view["trace"],
            halt=view["halt_time"],
            failing=(~ok) & (~over),
            # overflowed seeds are quarantined from guidance too: their
            # trajectories dropped events, so their bitmaps are artifacts
            cov=jnp.where(over[:, None], jnp.uint32(0), view["cov"]),
        )
        if metrics:
            cols["met"] = view["met"]
        if latency is not None:
            cols["lat_hist"] = view["lat_hist"]
        return cols

    def _jglob():
        dev = lax.axis_index(axes) if mesh is not None else 0
        return dev * b_loc + jnp.arange(b_loc)

    def shard_uniform(g, rk0, rk1):
        jglob = _jglob()
        k0s, k1s = derive_keys(g, jglob, rk0, rk1)
        seeds = _mk_seeds(k0s, k1s)
        rows = space.plan.compile_batch(seeds, device=True)
        row_d = {f: jnp.asarray(getattr(rows, f)) for f in _ROW_KEYS}
        if k_ov:
            is_ov = (jglob < k_ov) & (g == jnp.uint32(0))
            gi = jnp.minimum(jglob, k_ov - 1)
            for f in _ROW_KEYS:
                sel = is_ov.reshape((-1,) + (1,) * (row_d[f].ndim - 1))
                row_d[f] = jnp.where(sel, ov[f][gi], row_d[f])
        out = dict(
            seed=seeds,
            parent=jnp.full((b_loc,), -1, jnp.int32),
            bslot=jglob.astype(jnp.int32),
            **row_d,
        )
        out.update(run_children(seeds, PlanRows(**row_d)))
        return out

    def shard_breed(cr, g, rk0, rk1):
        jglob = _jglob()
        k0s, k1s = derive_keys(g, jglob, rk0, rk1)
        fresh = _mk_seeds(k0s, k1s)
        # frontier-first parent order: violating entries before clean
        # ones, newest (largest slot == largest id) first — computed
        # replicated on every device from the replicated corpus
        slot = jnp.arange(cmax1)
        valid = slot < cr["count"]
        nv = (~cr["c"]["viol"]).astype(jnp.int64)
        key = jnp.where(
            valid,
            nv * jnp.int64(2 * cmax1)
            + (cr["count"].astype(jnp.int64) - slot),
            jnp.int64(1) << 60,
        )
        order = jnp.argsort(key)
        olen = jnp.minimum(
            jnp.int64(select_top), cr["count"].astype(jnp.int64)
        )
        crm = dict(
            ct=cr["c"]["time"], ck=cr["c"]["kind"], ca=cr["c"]["args"],
            cv=cr["c"]["valid"], cn=cr["c"]["node"], cs=cr["c"]["seed"],
            chalt=cr["c"]["halt"], cid=cr["c"]["id"],
        )
        ch = jax.vmap(
            lambda a, b, c: mutator(a, b, c, order, olen, crm)
        )(k0s, k1s, fresh)
        out = dict(
            seed=ch["seed"],
            parent=ch["parent"],
            bslot=jglob.astype(jnp.int32),
            **{f: ch[f] for f in _ROW_KEYS},
        )
        out.update(
            run_children(ch["seed"], PlanRows(**{f: ch[f] for f in _ROW_KEYS}))
        )
        return out

    if mesh is not None:
        from ..parallel import shard_map_nocheck

        spec_b = P_(axes)
        sm_uniform = shard_map_nocheck(
            shard_uniform, mesh, in_specs=(P_(), P_(), P_()),
            out_specs=spec_b,
        )
        sm_breed = shard_map_nocheck(
            shard_breed, mesh, in_specs=(P_(), P_(), P_(), P_()),
            out_specs=spec_b,
        )
    else:
        sm_uniform, sm_breed = shard_uniform, shard_breed

    def admission(cr, g, out):
        varange = jnp.arange(vcap1)

        def body(acc, x):
            gm, cnt, nid, vc, vs, vt, over = acc
            row, fail, seed, trace = x
            fresh_bits = (
                lax.population_count(row & ~gm).sum().astype(jnp.int32)
            )
            gm2 = gm | row
            # a violation is counted once per distinct (seed, trace)
            # trajectory (driver seen_viol) — the store IS the set
            dup_v = jnp.any((vs == seed) & (vt == trace) & (varange < vc))
            fresh_viol = fail & ~dup_v
            qualify = (fresh_bits > 0) | fresh_viol
            idj = jnp.where(qualify, nid, -1)
            vslot = jnp.where(fresh_viol, jnp.minimum(vc, vcap), -1)
            wv = jnp.minimum(vc, vcap)
            vs2 = vs.at[wv].set(jnp.where(fresh_viol, seed, vs[wv]))
            vt2 = vt.at[wv].set(jnp.where(fresh_viol, trace, vt[wv]))
            over2 = over | (fresh_viol & (vc >= vcap))
            cslot = jnp.where(qualify & (cnt < max_corpus), cnt, -1)
            acc2 = (
                gm2,
                cnt + (qualify & (cnt < max_corpus)).astype(jnp.int32),
                nid + qualify.astype(jnp.int32),
                vc + fresh_viol.astype(jnp.int32),
                vs2, vt2, over2,
            )
            return acc2, (fresh_bits, idj, cslot, vslot)

        (gm2, cnt2, nid2, vc2, _, _, over2), ys = lax.scan(
            body,
            (
                cr["gmap"], cr["count"], cr["next_id"], cr["vcount"],
                cr["v"]["seed"], cr["v"]["trace"], cr["over"],
            ),
            (out["cov"], out["failing"], out["seed"], out["trace"]),
        )
        fresh_bits, ids, cslot, vslot = ys
        gen_col = jnp.full((batch,), g.astype(jnp.int32))

        def scatter(store, slots, trash):
            idx = jnp.where(slots >= 0, slots, trash)
            s2 = dict(store)
            for f in _ROW_KEYS:
                s2[f] = store[f].at[idx].set(out[f])
            s2["seed"] = store["seed"].at[idx].set(out["seed"])
            s2["trace"] = store["trace"].at[idx].set(out["trace"])
            s2["cov"] = store["cov"].at[idx].set(out["cov"])
            s2["new_bits"] = store["new_bits"].at[idx].set(fresh_bits)
            s2["id"] = store["id"].at[idx].set(ids)
            s2["parent"] = store["parent"].at[idx].set(out["parent"])
            s2["gen"] = store["gen"].at[idx].set(gen_col)
            s2["viol"] = store["viol"].at[idx].set(out["failing"])
            s2["halt"] = store["halt"].at[idx].set(out["halt"])
            s2["bslot"] = store["bslot"].at[idx].set(out["bslot"])
            return s2

        cr2 = dict(
            c=scatter(cr["c"], cslot, max_corpus),
            v=scatter(cr["v"], vslot, vcap),
            gmap=gm2,
            count=cnt2,
            next_id=nid2,
            vcount=vc2,
            over=over2,
        )
        summary = dict(
            count=cnt2,
            next_id=nid2,
            vcount=vc2,
            admitted=(cslot >= 0).sum().astype(jnp.int32),
            cov_bits=lax.population_count(gm2).sum().astype(jnp.int32),
            over=over2,
        )
        return cr2, summary

    def prog(cr, g, rk0, rk1, breed: bool):
        out = (
            sm_breed(cr, g, rk0, rk1) if breed
            else sm_uniform(g, rk0, rk1)
        )
        rep = NamedSharding(mesh, P_()) if mesh is not None else None
        if mesh is not None:
            # gather the generation's per-seed rows onto every device
            # before the admission scan: the scan is inherently
            # sequential (batch-order semantics), and scanning over
            # batch-sharded xs trips the SPMD partitioner (mixed-width
            # index arithmetic in the per-iteration slices). One
            # all-gather of (batch, slots) rows per generation — still
            # device-resident, never the host. The met/lat_hist tap
            # columns stay SHARDED: the admission scan never reads
            # them, and merge_metrics/merge_latency fold them as
            # per-device local sums (D rows to the host, no gather).
            out = {
                k: (v if k in ("met", "lat_hist")
                    else lax.with_sharding_constraint(v, rep))
                for k, v in out.items()
            }
        cr2, summary = admission(cr, g, out)
        if mesh is not None:
            # pin the carry's output shardings to replicated — the
            # compiled program's carry feeds straight back in next
            # generation, and an AOT executable (unlike jit) does not
            # silently recompile on a sharding drift
            cr2 = jax.tree.map(
                lambda a: lax.with_sharding_constraint(a, rep), cr2
            )
        extras = {
            k: out[k] for k in ("met", "lat_hist") if k in out
        }
        return cr2, summary, extras

    refs = (wl, invariant, mesh, latency, space)
    return (
        AotProgram(
            "explore.device.uniform", (cache_key, "uniform"),
            lambda cr, g, rk0, rk1: prog(cr, g, rk0, rk1, False),
        ),
        AotProgram(
            "explore.device.breed", (cache_key, "breed"),
            lambda cr, g, rk0, rk1: prog(cr, g, rk0, rk1, True),
        ),
        refs,
    )


# ---------------------------------------------------------------------------
# the campaign
# ---------------------------------------------------------------------------


class _CampaignSession:
    """Everything a device campaign shares between schedules.

    ``run_device`` below and the pipelined driver
    (``madsim_tpu.farm.pipeline.run_pipelined``) are the SAME campaign
    — argument validation, checkpoint resume, the device carry, the
    cached generation programs, host mirrors, telemetry and report
    assembly all live here; the two drivers differ only in *when* they
    block on a generation's admission summary. Keeping the semantics in
    one place is what makes the pipelined schedule a scheduling change
    rather than a semantic fork (the bit-identity tests lean on it).
    """

    def __init__(
        self, wl, cfg, space, *, invariant, generations, batch, root_seed,
        max_steps, cov_words, layout, require_halt, seed_corpus, select_top,
        max_corpus, max_ops, inherit_seed_p, log, cov_hitcount, telemetry,
        resume, checkpoint_path, latency, metrics, mesh, viol_cap,
        pool_index, history_check, causal=False,
    ):
        if isinstance(space, FaultPlan):
            space = PlanSpace(space)
        if history_check is not None:
            from ..check.device import as_screens

            history_check = as_screens(history_check)
            if wl.history is None:
                raise ValueError(
                    f"history_check judges operation histories, but workload "
                    f"{wl.name!r} has Workload.history=None"
                )
        if invariant is None and history_check is None:
            raise ValueError(
                "run_device needs a traceable final-state invariant and/or a "
                "history_check screen set (both run inside the device "
                "program); arbitrary host-side history_invariant callables "
                "need the host driver — use explore.run for those hunts"
            )
        if cov_words < 1:
            raise ValueError(
                "exploration needs cov_words >= 1 (the guidance)"
            )
        if generations < 1 or batch < 1:
            raise ValueError("need generations >= 1 and batch >= 1")
        if len(seed_corpus) > batch:
            raise ValueError(
                f"{len(seed_corpus)} seed-corpus plans exceed batch={batch}"
            )
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        if batch % n_dev:
            raise ValueError(
                f"batch={batch} does not split over {n_dev} mesh devices"
            )
        vcap = int(viol_cap) if viol_cap is not None else int(max_corpus)
        # derive the engine retry build flag from the space plan's
        # ClientArmy policy (the host driver's rule; LiteralPlan spaces
        # have no retry_spec and run fire-and-forget)
        retry = (
            space.plan.retry_spec() if hasattr(space.plan, "retry_spec")
            else None
        )
        p_slots = space.slots
        cmax1 = int(max_corpus) + 1
        vcap1 = vcap + 1

        # host-side validations the host driver gets from search_seeds:
        # plan targets/user kinds against the workload, and the time32
        # horizon (checked statically over the template windows —
        # mutation and compilation both stay inside them)
        space.plan.compile_batch(np.zeros(1, np.uint64), wl=wl)
        if _resolve_time32(wl, cfg, None):
            from ..engine.core import _T32_LIMIT

            tb_np = mutation_table(space)
            lim = _T32_LIMIT - cfg.proc_max_ns - 1
            worst = int(tb_np["t_hi"].max(initial=1)) - 1
            if seed_corpus:
                worst = max(
                    worst,
                    max(e.t for lp in seed_corpus for e in lp.events),
                )
            if worst > lim:
                raise ValueError(
                    f"plan-space window reaches t={worst} ns, past the int32 "
                    f"time horizon ({lim} ns) active for this (workload, "
                    f"config); shrink the windows or disable time32"
                )

        # ---- resumed / fresh host mirrors ----
        loaded_corpus: list = []
        loaded_viol: list = []
        if resume is not None:
            from .persist import resolve_resume

            st = resolve_resume(resume, wl, space, cfg, root_seed, batch,
                                cov_words, cov_hitcount)
            if len(st.corpus) > max_corpus:
                raise ValueError(
                    f"checkpoint carries {len(st.corpus)} corpus entries; "
                    f"max_corpus={max_corpus} cannot hold them"
                )
            if len(st.violations) > vcap:
                raise ValueError(
                    f"checkpoint carries {len(st.violations)} violations; "
                    f"raise viol_cap (now {vcap})"
                )
            loaded_corpus = list(st.corpus)
            loaded_viol = list(st.violations)
            gmap0 = np.asarray(st.cov_map, np.uint32)
            self.curve = list(st.curve)
            self.viol_curve = list(st.viol_curve)
            next_id0 = st.next_id
            self.sims = st.sims
            self.g_start = st.generations_done
        else:
            gmap0 = np.zeros((cov_words,), np.uint32)
            self.curve = []
            self.viol_curve = []
            next_id0 = 0
            self.sims = 0
            self.g_start = 0

        carry = dict(
            c=_fill_store(
                _empty_store(cmax1, p_slots, cov_words), loaded_corpus
            ),
            v=_fill_store(
                _empty_store(vcap1, p_slots, cov_words), loaded_viol
            ),
            gmap=jnp.asarray(gmap0),
            count=jnp.int32(len(loaded_corpus)),
            next_id=jnp.int32(next_id0),
            vcount=jnp.int32(len(loaded_viol)),
            over=jnp.bool_(False),
        )
        if mesh is not None:
            # commit the carry replicated up front: the cached generation
            # programs are AOT executables pinned to their input shardings
            # (obs.prof.AotProgram), and their outputs are constrained
            # replicated to match — input placement must agree from the
            # first call
            carry = jax.device_put(carry, NamedSharding(mesh, P_()))
        self.carry = carry
        self.count = len(loaded_corpus)  # host mirror (uniform vs breed)

        # materialized-entry caches: slot -> CorpusEntry. Loaded entries
        # are returned as the same objects (names and identity survive
        # resume); new slots materialize once and are reused by every
        # later checkpoint/report build.
        self._c_cache = {i: e for i, e in enumerate(loaded_corpus)}
        self._v_cache = {i: e for i, e in enumerate(loaded_viol)}

        # ---- the device programs (built once per cache key) ----
        k_ov = len(seed_corpus)
        key = (
            id(wl), id(invariant), cfg.hash(), space.hash(), batch,
            max_steps, cov_words, layout, require_halt, select_top,
            int(max_corpus), vcap, max_ops, float(inherit_seed_p),
            bool(cov_hitcount), bool(metrics), latency, _mesh_key(mesh),
            tuple(lp.hash() for lp in seed_corpus), pool_index,
            bool(causal), retry,
            # invariant identity of the device history screen: screens
            # are value-hashable literals, so equal screen sets share
            # programs across campaigns (the ROADMAP "invariant
            # identity" key component)
            history_check,
        )
        self.prog_uniform, self.prog_breed = _gen_programs(
            key,
            lambda: _build_programs(
                wl, cfg, space, invariant=invariant, batch=batch,
                max_steps=max_steps, cov_words=cov_words, layout=layout,
                require_halt=require_halt, select_top=select_top,
                max_corpus=int(max_corpus), vcap=vcap, max_ops=max_ops,
                inherit_seed_p=inherit_seed_p, cov_hitcount=cov_hitcount,
                metrics=metrics, latency=latency, mesh=mesh,
                seed_corpus=seed_corpus, cache_key=key,
                pool_index=pool_index, history_check=history_check,
                causal=causal, retry=retry,
            ),
        )

        self.wl = wl
        self.cfg = cfg
        self.space = space
        self.generations = generations
        self.batch = batch
        self.root_seed = int(root_seed)
        self.max_steps = max_steps
        self.cov_words = cov_words
        self.cov_hitcount = cov_hitcount
        self.log = log
        self.telemetry = telemetry
        self.checkpoint_path = checkpoint_path
        self.mesh = mesh
        self.n_dev = n_dev
        self.vcap = vcap
        self.seed_corpus = seed_corpus
        self.k_ov = k_ov
        self.next_id = next_id0  # host mirror for snapshots
        self.vcount_host = len(loaded_viol)
        self.log_label = "device"
        # the campaign root key enters the cached programs as a RUNTIME
        # argument (same threefry coordinates as driver._derive_keys),
        # so one compiled program serves every root seed
        self.rk0 = jnp.uint32(self.root_seed & 0xFFFFFFFF)
        self.rk1 = jnp.uint32((self.root_seed >> 32) & 0xFFFFFFFF)

    # ---- scheduling primitives -----------------------------------------
    def runner(self, breed: bool):
        return self.prog_breed if breed else self.prog_uniform

    def fleet(self, extras) -> dict:
        """Fold a generation's sharded tap columns into fleet totals."""
        fleet: dict = {}
        if extras:
            from .. import parallel as _par

            if "met" in extras:
                fleet["met_total"] = [
                    int(x)
                    for x in _par.merge_metrics(extras["met"], self.mesh)
                ]
            if "lat_hist" in extras:
                fleet["lat_total_ops"] = int(
                    _par.merge_latency(extras["lat_hist"], self.mesh).sum()
                )
        return fleet

    def consume(self, g: int, s, fleet: dict, walls: dict,
                carry=None) -> None:
        """Fold generation ``g``'s admission summary into the host
        mirrors: curve/corpus-count/violation bookkeeping, the
        generation telemetry record (``walls`` carries the driver's
        wall split), the log line, and the per-generation checkpoint.
        ``carry`` is the carry AS OF after ``g`` — the pipelined driver
        passes it explicitly because its ``self.carry`` has already
        speculated ahead."""
        if bool(s["over"]):
            raise RuntimeError(
                f"device violation store overflowed (viol_cap={self.vcap}) "
                f"at generation {g}: the (seed, trace) dedup can no longer "
                f"match the host driver — raise viol_cap"
            )
        self.sims += self.batch
        self.count = int(s["count"])
        self.next_id = int(s["next_id"])
        new_viol = int(s["vcount"]) - self.vcount_host
        self.vcount_host = int(s["vcount"])
        self.curve.append(int(s["cov_bits"]))
        self.viol_curve.append(self.vcount_host)
        if self.log is not None:
            self.log(
                f"explore[{self.log_label}] g{g}: {self.curve[-1]} "
                f"coverage bits (+{int(s['admitted'])} corpus entries, "
                f"corpus {self.count}), {self.vcount_host} violations"
            )
        self.emit({
            "event": "generation", "generation": g, "sims": self.sims,
            "cov_bits": self.curve[-1], "new_entries": int(s["admitted"]),
            "corpus_size": self.count, "violations": self.vcount_host,
            "new_violations": new_viol, **walls, "host_syncs": 1, **fleet,
        })
        if self.checkpoint_path is not None:
            self.snapshot(g + 1, carry=carry).save(self.checkpoint_path)

    # ---- materialization ------------------------------------------------
    def _entry_name(self, gen, parent, bslot, seed):
        if parent >= 0:
            return f"g{gen}p{parent}"
        if gen == 0 and 0 <= bslot < self.k_ov:
            return self.seed_corpus[bslot].name
        return f"{self.space.plan.name}@{seed}"

    def _materialize(self, carry_host):
        cn = {k: np.asarray(v) for k, v in carry_host["c"].items()}
        vn = {k: np.asarray(v) for k, v in carry_host["v"].items()}
        n_c = int(carry_host["count"])
        n_v = int(carry_host["vcount"])
        c_cache, v_cache = self._c_cache, self._v_cache
        for i in range(len(c_cache), n_c):
            c_cache[i] = _store_entry(
                cn, i,
                self._entry_name(int(cn["gen"][i]), int(cn["parent"][i]),
                                 int(cn["bslot"][i]), int(cn["seed"][i])),
            )
        corpus = [c_cache[i] for i in range(n_c)]
        by_id = {e.id: e for e in corpus}
        for i in range(len(v_cache), min(n_v, self.vcap)):
            eid = int(vn["id"][i])
            # a violating entry that also joined the corpus is the SAME
            # object in both lists (the host driver's sharing)
            v_cache[i] = by_id.get(eid) or _store_entry(
                vn, i,
                self._entry_name(int(vn["gen"][i]), int(vn["parent"][i]),
                                 int(vn["bslot"][i]), int(vn["seed"][i])),
            )
        violations = [v_cache[i] for i in range(min(n_v, self.vcap))]
        return corpus, violations, np.asarray(carry_host["gmap"], np.uint32)

    def snapshot(self, gens_done: int, carry=None):
        from .persist import CampaignState

        corpus, violations, gm = self._materialize(
            jax.device_get(self.carry if carry is None else carry)
        )
        return CampaignState(
            workload=self.wl.name, config_hash=self.cfg.hash(),
            plan_hash=self.space.hash(), root_seed=self.root_seed,
            batch=self.batch, cov_words=self.cov_words,
            cov_hitcount=self.cov_hitcount, generations_done=gens_done,
            next_id=self.next_id, sims=self.sims, curve=list(self.curve),
            viol_curve=list(self.viol_curve), cov_map=gm.copy(),
            corpus=list(corpus), violations=list(violations),
        )

    # ---- telemetry + report ---------------------------------------------
    def emit(self, record: dict) -> None:
        if self.telemetry is not None:
            self.telemetry(record)

    def start(self, driver: str, **extra) -> None:
        self.emit({
            "event": "campaign_start", "workload": self.wl.name,
            "config_hash": self.cfg.hash(), "plan_hash": self.space.hash(),
            "root_seed": self.root_seed, "batch": self.batch,
            "generations": self.generations, "cov_words": self.cov_words,
            "cov_hitcount": self.cov_hitcount,
            "resumed_at_generation": self.g_start,
            "driver": driver, "mesh_devices": self.n_dev, **extra,
        })

    def report(self, *, wall_dispatch, wall_sync, wall_compile, host_syncs,
               wall_queue=0.0, wall_idle=0.0) -> ExploreReport:
        corpus, violations, gm = self._materialize(
            jax.device_get(self.carry)
        )
        return ExploreReport(
            workload=self.wl.name,
            config_hash=self.cfg.hash(),
            plan_hash=self.space.hash(),
            root_seed=self.root_seed,
            generations=self.g_start + self.generations,
            batch=self.batch,
            max_steps=self.max_steps,
            cov_words=self.cov_words,
            sims=self.sims,
            corpus=corpus,
            violations=violations,
            cov_map=gm,
            curve=self.curve,
            viol_curve=self.viol_curve,
            next_id=self.next_id,
            cov_hitcount=self.cov_hitcount,
            wall_dispatch_s=wall_dispatch,
            wall_host_s=wall_sync,
            wall_compile_s=wall_compile,
            host_syncs=host_syncs,
            wall_gens=self.generations,
            wall_queue_s=wall_queue,
            wall_idle_s=wall_idle,
        )


def run_device(
    wl,
    cfg,
    space,
    *,
    invariant,
    generations: int = 8,
    batch: int = 256,
    root_seed: int = 0,
    max_steps: int = 1000,
    cov_words: int = 32,
    layout: str | None = None,
    require_halt: bool = False,
    seed_corpus=(),
    select_top: int = 32,
    max_corpus: int = 4096,
    max_ops: int = 3,
    inherit_seed_p: float = 0.75,
    log=None,
    cov_hitcount: bool = False,
    telemetry=None,
    resume=None,
    checkpoint_path: str | None = None,
    latency=None,
    metrics: bool = False,
    mesh=None,
    viol_cap: int | None = None,
    pool_index: bool | None = None,
    history_check=None,
    causal: bool = False,
) -> ExploreReport:
    """Run one exploration campaign with every generation device-resident.

    Same contract and bit-identical outcomes as :func:`explore.run`
    (module docstring), with these differences:

    * ``invariant`` must be jnp-traceable over the final state view
      (``{field: array} -> (S,) bool``) — it runs inside the device
      program. ``history_check`` (a ``check.device.HistoryScreen`` or
      tuple) is the device form of a ``history_invariant`` hunt: the
      batch detectors trace into the cached generation program (the
      screen tuple is a ``_GEN_CACHE`` key component) and their
      verdicts mark violations exactly like the host driver running
      ``check.device.screens_invariant(history_check)`` — the two
      campaigns are bit-identical, and a device find replays/shrinks
      on the host driver through that same invariant. At least one of
      the two must be given; arbitrary host-side ``history_invariant``
      callables still need the host driver.
    * ``mesh`` (a ``parallel.make_mesh`` Mesh) shards mutation and the
      sweep across chips with ``shard_map``; ``batch`` must divide over
      the device count. Sharded and unsharded campaigns are identical.
    * ``metrics=True`` folds per-generation fleet-metric totals into the
      telemetry records (``parallel.merge_metrics`` — per-device sums,
      device-count rows to the host); ``latency`` likewise folds fleet
      sketches via ``parallel.merge_latency``. Both are derived state:
      campaign outcomes are unchanged.
    * ``causal=True`` runs the generations with the engine's causal
      columns on (``explore.run`` docstring): the causal-depth/width
      coverage feature class joins the guidance, at the cost of the
      per-seed provenance columns riding the sweep. The flag is a
      ``_GEN_CACHE`` key component — on/off campaigns never share a
      compiled program.
    * ``viol_cap`` bounds the device violation store (default
      ``max_corpus``); a campaign that finds more raises instead of
      silently breaking the (seed, trace) dedup.
    * ``checkpoint_path`` materializes the corpus to the host after
      every generation (that is what a checkpoint IS) — set it only
      when resumability is worth the extra transfer.

    The per-generation host sync transfers only the admission summary
    (corpus size, new entries, coverage bits, violation count) and the
    fresh violation keys; telemetry records carry the
    dispatch/compile/sync wall split and ``host_syncs: 1`` so the
    claim is checkable from the artifact. ``compile_wall_s`` is
    nonzero only when the generation-program cache was cold for this
    campaign shape — hold one workload/invariant object across
    campaigns (the ``engine.search`` rule) and every later campaign
    runs compile-free.
    """
    sess = _CampaignSession(
        wl, cfg, space, invariant=invariant, generations=generations,
        batch=batch, root_seed=root_seed, max_steps=max_steps,
        cov_words=cov_words, layout=layout, require_halt=require_halt,
        seed_corpus=seed_corpus, select_top=select_top,
        max_corpus=max_corpus, max_ops=max_ops,
        inherit_seed_p=inherit_seed_p, log=log, cov_hitcount=cov_hitcount,
        telemetry=telemetry, resume=resume,
        checkpoint_path=checkpoint_path, latency=latency, metrics=metrics,
        mesh=mesh, viol_cap=viol_cap, pool_index=pool_index,
        history_check=history_check, causal=causal,
    )
    sess.start("device")

    wall_dispatch = 0.0
    wall_sync = 0.0
    wall_compile = 0.0
    host_syncs = 0

    for g in range(sess.g_start, sess.g_start + generations):
        t0 = _time.monotonic()  # lint: allow(wall-clock)
        breed = g > 0 and sess.count > 0
        runner = sess.runner(breed)
        sess.carry, summary, extras = runner(
            sess.carry, jnp.uint32(g), sess.rk0, sess.rk1
        )
        jax.block_until_ready(summary)
        t1 = _time.monotonic()  # lint: allow(wall-clock)
        # trace/lower/compile share of this generation (0.0 on a warm
        # program cache — the certified steady state), split out of
        # dispatch so warm-vs-cold comparisons compare like with like
        compile_wall = runner.last_build_s
        # THE host sync: admission summary + banner counters only —
        # per-seed state stays on device
        s = jax.device_get(summary)
        host_syncs += 1
        fleet = sess.fleet(extras)
        t2 = _time.monotonic()  # lint: allow(wall-clock)
        wall_dispatch += (t1 - t0) - compile_wall
        wall_sync += t2 - t1
        wall_compile += compile_wall
        sess.consume(g, s, fleet, {
            "dispatch_wall_s": round((t1 - t0) - compile_wall, 3),
            "compile_wall_s": round(compile_wall, 3),
            "sync_wall_s": round(t2 - t1, 3),
            # the pipeline wall split, zero by construction on the
            # blocking schedule (the driver never enqueues ahead)
            "queue_wall_s": 0.0,
            "idle_wall_s": 0.0,
        })

    sess.emit({
        "event": "campaign_end", "generations": sess.g_start + generations,
        "generations_run": generations,
        "sims": sess.sims,
        "cov_bits": sess.curve[-1] if sess.curve else 0,
        "corpus_size": sess.count, "violations": sess.vcount_host,
        "wall_dispatch_s": round(wall_dispatch, 3),
        "wall_sync_s": round(wall_sync, 3),
        "wall_compile_s": round(wall_compile, 3),
        "wall_queue_s": 0.0,
        "wall_idle_s": 0.0,
        "host_syncs": host_syncs,
    })
    return sess.report(
        wall_dispatch=wall_dispatch, wall_sync=wall_sync,
        wall_compile=wall_compile, host_syncs=host_syncs,
    )
