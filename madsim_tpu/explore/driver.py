"""The coverage-guided exploration loop (the AFL shape, batched).

One campaign = ``generations`` batched sweeps of ``batch`` candidate
``(seed, plan)`` pairs each:

* **generation 0** is the uniform baseline: fresh threefry-derived
  seeds, each running the plan space's FaultPlan exactly as
  ``search_seeds(plan=...)`` would (optionally spiked with
  ``seed_corpus`` literals — targeted hunt knowledge);
* **every later generation** breeds candidates from the corpus:
  parents are picked frontier-first (violating entries before clean
  ones, newest first within each group), each child gets a mutated plan
  (explore/mutate.py) plus either its parent's engine seed (tune the
  fault alignment) or a fresh one, and the whole generation executes
  as ONE vmapped batch through ``search_seeds``'s compiled-run cache —
  same slot count every time, so the XLA program compiles once;
* after each generation the on-device admission scan
  (explore/coverage.py) scores every candidate by the bits it newly
  set; entries with fresh coverage (or a violation) join the corpus.

Everything — seeds, mutation draws, parent picks — derives from ONE
root seed via counter-based threefry, so the entire campaign is
replayable: same root, same corpus, same coverage map, same violations,
across runs and across engine layouts. Each violation's
``(root_seed, generation, entry id)`` is a complete repro key; the
entry's stored ``(seed, LiteralPlan)`` replays to the identical trace
hash (:func:`replay_entry`), and feeds ``chaos.shrink_plan`` directly.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..chaos.plan import (
    FaultEvent,
    FaultPlan,
    LiteralPlan,
    stack_plan_rows,
)
from ..engine.core import KIND_NOP
from ..engine.rng import PURPOSE_EXPLORE, np_threefry2x32v
from ..engine.search import SearchReport, search_seeds
from .coverage import admit, popcount
from .mutate import HostStream, PlanSpace, inherit_threshold, mutate_plan

__all__ = ["CorpusEntry", "ExploreReport", "replay_entry", "run"]


@dataclasses.dataclass
class CorpusEntry:
    """One interesting ``(seed, plan)`` pair.

    ``(root seed, generation, id)`` identifies the entry within its
    campaign; ``(seed, plan)`` + the sweep parameters replay its exact
    trajectory (``trace`` is the hash the replay must reproduce)."""

    id: int
    generation: int
    parent: int  # corpus id of the parent entry; -1 for generation 0
    seed: int  # engine seed (threefry-derived from the root)
    plan: LiteralPlan
    trace: int  # uint64 trace hash of the run
    cov: np.ndarray  # (CW,) uint32 coverage signature
    new_bits: int  # bits this entry set first (admission score)
    violating: bool
    halt_t: int = 0  # halt clock ns (0 = ran to the step cap) — the
    # causal horizon the mutators respect when breeding from this entry


@dataclasses.dataclass
class ExploreReport:
    """Outcome of one exploration campaign."""

    workload: str
    config_hash: str
    plan_hash: str  # the plan space (generation-0 FaultPlan) hash
    root_seed: int
    generations: int  # ABSOLUTE campaign length (resumed runs include
    # the generations a loaded checkpoint already executed)
    batch: int
    max_steps: int
    cov_words: int
    sims: int  # total simulations executed (the budget spent)
    corpus: list  # admitted CorpusEntry list, admission order
    violations: list  # violating CorpusEntry list (also in corpus)
    cov_map: np.ndarray  # (CW,) uint32 final global coverage map
    curve: list  # coverage bits after each generation
    viol_curve: list  # cumulative violation count after each generation
    # next CorpusEntry id — ids are consumed even by entries the full
    # corpus refused, so persist (explore/persist.py) stores it rather
    # than re-deriving from max(id)
    next_id: int = 0
    # whether the campaign's bitmaps used AFL hit-count bucketing
    # (engine cov_hitcount): bucketed and set-only bitmaps are different
    # coordinate systems, so resume refuses a flag mismatch
    cov_hitcount: bool = False
    # per-generation wall split, summed over the campaign: time inside
    # the batched device dispatch vs time the host spent driving it
    # (mutation + admission + corpus bookkeeping on the host driver;
    # the one summary fetch on the device driver). The split is also in
    # every telemetry "generation" record, so the one-host-sync claim
    # of the device driver is measurable from the artifact.
    wall_dispatch_s: float = 0.0
    wall_host_s: float = 0.0
    # trace/lower/compile wall, split OUT of dispatch (historically the
    # first generation's compile was billed to dispatch, skewing
    # warm-vs-cold comparisons): nonzero only on generations that paid
    # a program build — a warmed program cache makes this 0.0 for the
    # whole campaign, which is exactly what the flight recorder
    # certifies
    wall_compile_s: float = 0.0
    # summary-only host synchronization points (explore.run_device: one
    # per generation). 0 = host-driven campaign, where every generation
    # moves per-seed state to the host and the notion does not apply.
    host_syncs: int = 0
    # generations the wall split / host_syncs cover: a RESUMED
    # campaign's timers cover only the resumed run, while
    # ``generations`` counts from generation 0 — the banner pairs
    # syncs against this, not the absolute total
    wall_gens: int = 0
    # pipelined-schedule wall split (madsim_tpu.farm.pipeline): queue =
    # host time spent ENQUEUEING dispatches ahead of the consume point,
    # idle = host time blocked waiting for a generation the device had
    # not finished. Both 0.0 on the blocking drivers — a nonzero split
    # is the measured proof that host-side work (checkpointing,
    # telemetry) overlapped device compute instead of serializing after
    # it. On the pipelined driver wall_dispatch_s == queue + idle.
    wall_queue_s: float = 0.0
    wall_idle_s: float = 0.0

    @property
    def coverage_bits(self) -> int:
        return popcount(self.cov_map)

    def banner(self, limit: int = 5) -> str:
        lines = [
            f"explore over {self.workload!r}: {self.sims} sims "
            f"({self.generations} generations x {self.batch}), root_seed="
            f"{self.root_seed} space={self.plan_hash} "
            f"config_hash={self.config_hash}",
            f"  coverage: {self.coverage_bits} bits "
            f"({self.cov_words * 32} max), corpus {len(self.corpus)} "
            f"entries, curve {self.curve}",
            f"  violations: {len(self.violations)}",
        ]
        if self.wall_dispatch_s or self.wall_host_s:
            total = self.wall_dispatch_s + self.wall_host_s
            frac = self.wall_host_s / total if total else 0.0
            gens = max(self.wall_gens or self.generations, 1)
            compile_note = (
                f" + {self.wall_compile_s:.2f}s compile (cold)"
                if self.wall_compile_s else ""
            )
            if self.host_syncs:
                lines.append(
                    f"  wall: {self.wall_dispatch_s:.2f}s device dispatch "
                    f"+ {self.wall_host_s:.2f}s host sync{compile_note} "
                    f"({frac:.1%} host; {self.host_syncs} summary syncs "
                    f"/ {gens} generations)"
                )
            else:
                lines.append(
                    f"  wall: {self.wall_dispatch_s:.2f}s batched dispatch "
                    f"+ {self.wall_host_s:.2f}s host-driven loop"
                    f"{compile_note} ({frac:.1%} host)"
                )
        if self.wall_queue_s or self.wall_idle_s:
            lines.append(
                f"  pipeline: {self.wall_queue_s:.2f}s enqueue + "
                f"{self.wall_idle_s:.2f}s idle at consume (host work "
                f"overlapped device compute)"
            )
        for e in self.violations[:limit]:
            lines.append(
                f"  violation g{e.generation} id{e.id}: seed {e.seed} "
                f"plan_hash={e.plan.hash()} trace={e.trace:#x}"
            )
        if len(self.violations) > limit:
            lines.append(f"  ... and {len(self.violations) - limit} more")
        return "\n".join(lines)


def _derive_keys(root_seed: int, generation: int, batch: int):
    """Child threefry keys for one generation: key = threefry(root,
    (generation, PURPOSE_EXPLORE + batch-slot)) — the (corpus-id,
    generation, slot) derivation of the design, order-independent
    coordinates like every other stream in the repo."""
    root = np.uint64(root_seed)
    k0 = np.uint32(root & np.uint64(0xFFFFFFFF))
    k1 = np.uint32(root >> np.uint64(32))
    j = np.arange(batch, dtype=np.uint32)
    a, b = np_threefry2x32v(
        k0, k1, np.uint32(generation & 0xFFFFFFFF),
        np.uint32(PURPOSE_EXPLORE) + j,
    )
    return a, b


def _child_seeds(k0s, k1s) -> np.ndarray:
    return k0s.astype(np.uint64) | (k1s.astype(np.uint64) << np.uint64(32))


def _literal_from_rows(rows, j: int, name: str) -> LiteralPlan:
    """Row ``j`` of a compiled PlanRows batch as an exactly-replaying
    LiteralPlan (all slots kept, invalid ones disabled — the
    FaultPlan.literalize layout rule)."""
    time = np.asarray(rows.time)
    kind = np.asarray(rows.kind)
    args = np.asarray(rows.args)
    valid = np.asarray(rows.valid)
    # every in-loop PlanRows source (compile_batch, stack_plan_rows)
    # materializes the node column; None only exists for hand-built
    # rows at the make_init boundary
    node = np.asarray(rows.node)
    events = tuple(
        FaultEvent(
            t=int(time[j, p]), kind=int(kind[j, p]),
            a0=int(args[j, p, 0]), a1=int(args[j, p, 1]),
            node=int(node[j, p]),
        )
        for p in range(time.shape[1])
    )
    return LiteralPlan(
        events=events, enabled=tuple(bool(x) for x in valid[j]), name=name
    )


def _pad_literal(lp: LiteralPlan, slots: int) -> LiteralPlan:
    if lp.slots > slots:
        raise ValueError(
            f"seed-corpus plan {lp.name!r} has {lp.slots} slots; the plan "
            f"space has only {slots}"
        )
    pad = slots - lp.slots
    return LiteralPlan(
        events=tuple(lp.events) + tuple(
            FaultEvent(t=0, kind=KIND_NOP) for _ in range(pad)
        ),
        enabled=tuple(lp._mask()) + (False,) * pad,
        name=lp.name,
    )


def replay_entry(
    wl,
    cfg,
    entry: CorpusEntry,
    *,
    invariant=None,
    history_invariant=None,
    max_steps: int = 1000,
    require_halt: bool = False,
    layout: str | None = None,
    compact: bool = False,
    cov_words: int = 0,
    dup_rows: bool | None = None,
    metrics: bool = False,
    timeline_cap: int = 0,
    latency=None,
    causal: bool = False,
    retry=None,
) -> SearchReport:
    """Re-execute one corpus entry's exact ``(seed, plan)`` pair.

    With the campaign's sweep parameters (``max_steps`` etc.) the
    returned report's trace equals ``entry.trace`` and its verdict
    reproduces the stored violation — the per-entry determinism
    guarantee tests and the soak assert. ``dup_rows`` defaults to what
    the entry's plan needs (the shrink_plan rule) — pass it explicitly
    only to replay under a differently compiled step on purpose.
    ``metrics``/``timeline_cap``/``causal`` turn on the observability
    taps (madsim_tpu.obs) for the replay — the forensics path: derived
    state only, so the replayed trace still equals ``entry.trace``
    (``causal=True`` + ``timeline_cap`` is how a banked violation
    becomes an ``obs.causal_slice`` happens-before cone).

    ``retry``: the ``engine.RetrySpec`` the campaign ran under (the
    hunt derives it from the plan space's ClientArmy policy). A banked
    entry's plan is a LiteralPlan — raw pool rows that no longer carry
    the army's RetryPolicy — so a retried campaign's entries must be
    replayed with the campaign's spec passed explicitly here, or the
    replay runs the fire-and-forget engine and the trace diverges.
    """
    if dup_rows is None:
        dup_rows = bool(entry.plan.uses_dup())
    if invariant is None and history_invariant is None:
        invariant = lambda view: np.ones(  # noqa: E731 — replay-only
            np.asarray(view["halted"]).shape[0], bool
        )
    return search_seeds(
        wl, cfg, invariant,
        seeds=np.asarray([entry.seed], np.uint64),
        max_steps=max_steps, require_halt=require_halt, layout=layout,
        compact=compact, history_invariant=history_invariant,
        plan_rows=stack_plan_rows([entry.plan]),
        plan_hash=entry.plan.hash(), dup_rows=dup_rows,
        cov_words=cov_words, metrics=metrics, timeline_cap=timeline_cap,
        latency=latency, causal=causal, retry=retry,
    )


def run(
    wl,
    cfg,
    space,
    *,
    invariant=None,
    history_invariant=None,
    generations: int = 8,
    batch: int = 256,
    root_seed: int = 0,
    max_steps: int = 1000,
    cov_words: int = 32,
    layout: str | None = None,
    compact: bool = False,
    require_halt: bool = False,
    seed_corpus=(),
    select_top: int = 32,
    max_corpus: int = 4096,
    max_ops: int = 3,
    inherit_seed_p: float = 0.75,
    log=None,
    cov_hitcount: bool = False,
    telemetry=None,
    resume=None,
    checkpoint_path: str | None = None,
    latency=None,
    pool_index: bool | None = None,
    energy=None,
    causal: bool = False,
) -> ExploreReport:
    """Run one coverage-guided exploration campaign.

    ``space`` is a :class:`PlanSpace` (or a bare :class:`FaultPlan`,
    wrapped automatically). ``invariant`` / ``history_invariant`` follow
    the ``search_seeds`` contract; ``require_halt`` defaults to False —
    a safety hunt judges the recorded history, not liveness (the
    ``shrink_plan`` rule). ``seed_corpus`` literals (padded to the
    space's slot count) replace the first generation-0 rows: targeted
    hunt knowledge enters the loop as corpus seeds, the greybox-fuzzing
    idiom. ``inherit_seed_p`` is the fraction of children that keep
    their parent's engine seed (tune the fault alignment against a
    fixed protocol trajectory) instead of drawing a fresh one (explore
    seed space). ``log`` (callable, e.g. ``print``) gets one line per
    generation.

    ``cov_hitcount=True`` runs the engine's AFL-style hit-count
    bucketing (make_step docstring): recurrence-magnitude changes
    become fresh coverage, at the cost of a per-seed counter column.

    ``telemetry`` (callable, e.g. ``obs.JsonlSink(path)``) receives one
    structured record per campaign event: a ``campaign_start``, one
    ``generation`` per generation (coverage bits, corpus size,
    violations, dispatch wall seconds), and a ``campaign_end``.

    ``resume`` (an ``explore.CampaignState`` or a path to one)
    continues a checkpointed campaign: THIS call runs ``generations``
    MORE generations on top of the loaded corpus/coverage/dedup state.
    Draw keys are addressed by absolute generation index, so a resumed
    campaign is bit-identical to the uninterrupted one given the same
    (root seed, batch, space, config) — all validated against the
    checkpoint. ``checkpoint_path`` saves the campaign state after
    every generation (and is the natural ``resume`` input later).

    ``latency`` (an ``engine.LatencySpec``) runs every generation with
    the tail-latency tap on — the SLO hunt: with a ``chaos.ClientArmy``
    in the plan space and ``check.slo_bounded`` as the invariant,
    latency-bucket coverage bits steer the campaign toward schedules
    that move the tail, and p99 breaches are violations like any other
    (dedup, shrink, replay all apply).

    ``energy`` (a ``madsim_tpu.farm.EnergySchedule``) replaces the
    uniform parent pick with an AFLFast-style power schedule: per-entry
    energy decays with times-picked and boosts rare-path coverage and
    violations, and seed inheritance becomes per-parent. Energy draws
    come from the dedicated ``farm`` threefry lane, so the explore-lane
    mutation stream is untouched draw-for-draw — ``energy=None`` (or a
    uniform-mode schedule) is bit-identical to the historical behavior
    (test-pinned), which keeps ``select_top``/``inherit_seed_p`` as the
    reproducible defaults.

    ``causal=True`` runs every generation with the engine's causal
    columns on, which activates the causal-depth/width coverage
    feature class (make_step feature tag 7): schedules that build
    DEEPER happens-before chains or larger emit-jumps set fresh
    coverage bits, so "more intricate causality" steers the hunt the
    way branch coverage does — and every banked violation replays
    straight into an ``obs.causal_slice`` cone (``replay_entry`` with
    ``causal=True, timeline_cap=...``).
    """
    import time as _time

    if isinstance(space, FaultPlan):
        space = PlanSpace(space)
    # the army's retry policy is an ENGINE build flag, not plan rows:
    # mutated children are LiteralPlans whose attempt-0 tokens are plain
    # op ids either way, so one spec (the space plan's) serves every
    # generation — and replay_entry must be handed the same spec
    retry = (
        space.plan.retry_spec() if hasattr(space.plan, "retry_spec")
        else None
    )
    if cov_words < 1:
        raise ValueError("exploration needs cov_words >= 1 (the guidance)")
    if generations < 1 or batch < 1:
        raise ValueError("need generations >= 1 and batch >= 1")
    if len(seed_corpus) > batch:
        raise ValueError(
            f"{len(seed_corpus)} seed-corpus plans exceed batch={batch}"
        )
    dup = space.uses_dup()
    # per-campaign mutable energy state (times-picked counters); None
    # means the uniform schedule — the historical, bit-pinned path
    est = energy.state() if energy is not None and energy.active else None
    if resume is not None:
        from .persist import resolve_resume

        st = resolve_resume(resume, wl, space, cfg, root_seed, batch,
                            cov_words, cov_hitcount)
        global_map = np.asarray(st.cov_map, np.uint32).copy()
        corpus = list(st.corpus)
        by_id = {e.id: e for e in corpus}
        violations = list(st.violations)
        seen_viol = {(e.seed, e.trace) for e in violations}
        curve = list(st.curve)
        viol_curve = list(st.viol_curve)
        next_id = st.next_id
        sims = st.sims
        g_start = st.generations_done
    else:
        global_map = np.zeros((cov_words,), np.uint32)
        corpus = []
        by_id = {}
        violations = []
        seen_viol = set()  # (seed, trace) — a violation is counted once
        curve = []
        viol_curve = []
        next_id = 0
        sims = 0
        g_start = 0

    def _snapshot(gens_done: int):
        from .persist import CampaignState

        return CampaignState(
            workload=wl.name, config_hash=cfg.hash(),
            plan_hash=space.hash(), root_seed=int(root_seed), batch=batch,
            cov_words=cov_words, cov_hitcount=cov_hitcount,
            generations_done=gens_done, next_id=next_id, sims=sims,
            curve=list(curve), viol_curve=list(viol_curve),
            cov_map=global_map.copy(), corpus=list(corpus),
            violations=list(violations),
        )

    def _emit(record: dict):
        if telemetry is not None:
            telemetry(record)

    _emit({
        "event": "campaign_start", "workload": wl.name,
        "config_hash": cfg.hash(), "plan_hash": space.hash(),
        "root_seed": int(root_seed), "batch": batch,
        "generations": generations, "cov_words": cov_words,
        "cov_hitcount": cov_hitcount, "resumed_at_generation": g_start,
    })

    wall_dispatch = 0.0
    wall_host = 0.0
    wall_compile = 0.0
    for g in range(g_start, g_start + generations):
        t_gen = _time.monotonic()  # lint: allow(wall-clock)
        k0s, k1s = _derive_keys(root_seed, g, batch)
        seeds = _child_seeds(k0s, k1s)
        overrides: dict[int, LiteralPlan] = {}
        if g == 0 or not corpus:
            # uniform generation: the plan space's own per-seed draws
            # (identical to what search_seeds(plan=space.plan) runs)
            rows = space.plan.compile_batch(seeds, wl=wl)
            plans = None
            parents = [-1] * batch
            if g == 0:
                for j, lp in enumerate(seed_corpus):
                    padded = _pad_literal(lp, space.slots)
                    overrides[j] = padded
                    time = np.asarray(rows.time)
                    time[j] = [e.t for e in padded.events]
                    np.asarray(rows.kind)[j] = [e.kind for e in padded.events]
                    np.asarray(rows.args)[j] = [
                        (e.a0, e.a1) for e in padded.events
                    ]
                    np.asarray(rows.valid)[j] = padded._mask()
                    np.asarray(rows.node)[j] = [
                        e.node for e in padded.events
                    ]
        else:
            # parent pool: violating entries first, NEWEST first — the
            # frontier keeps drifting into fresh trajectory
            # neighborhoods instead of re-mining generation 0 (whose
            # traces the dedup has already seen); the newest
            # non-violating entries fill the remainder (recency over
            # new-bit count won the kvchaos equal-budget measurement)
            order = [
                e.id
                for e in sorted(
                    corpus,
                    key=lambda e: (not e.violating, -e.id),
                )[:select_top]
            ]
            plans = []
            parents = []
            seeds = seeds.copy()
            if est is not None:
                pool, cum = est.pool(corpus, select_top)
            for j in range(batch):
                st = HostStream(int(k0s[j]), int(k1s[j]), PURPOSE_EXPLORE)
                # draw 0 of the explore stream is ALWAYS consumed: under
                # an energy schedule the parent pick moves to the farm
                # lane, but the mutation draws that follow (j >= 2) must
                # stay at the same counters either way
                w0 = st.bits()
                if est is None:
                    pid = order[w0 % len(order)]
                    thresh = inherit_threshold(inherit_seed_p)
                else:
                    pid = est.choose(int(k0s[j]), int(k1s[j]), pool, cum)
                    thresh = est.inherit_threshold(
                        by_id[pid], inherit_seed_p
                    )
                parents.append(pid)
                # inheriting children keep the parent's engine seed:
                # protocol timing stays fixed while the plan mutates,
                # so a near-miss fault alignment can be tuned instead
                # of re-rolled (the rest re-key both, keeping
                # seed-space exploration alive)
                if st.bits() < thresh:
                    seeds[j] = np.uint64(by_id[pid].seed)
                parent = by_id[pid]
                plans.append(
                    mutate_plan(
                        parent.plan, space, st, max_ops=max_ops,
                        name=f"g{g}p{pid}",
                        horizon=parent.halt_t if parent.halt_t > 0 else None,
                    )
                )
            rows = stack_plan_rows(plans)

        t_disp = _time.monotonic()  # lint: allow(wall-clock)
        report = search_seeds(
            wl, cfg, invariant,
            seeds=seeds, max_steps=max_steps, require_halt=require_halt,
            layout=layout, compact=compact,
            history_invariant=history_invariant,
            plan_rows=rows, plan_hash=space.hash(), dup_rows=dup,
            cov_words=cov_words, cov_hitcount=cov_hitcount,
            latency=latency, pool_index=pool_index, causal=causal,
            retry=retry,
        )
        t_after = _time.monotonic()  # lint: allow(wall-clock)
        # the trace/lower/compile share of this dispatch (nonzero only
        # when the compiled-run cache was cold for this sweep shape) is
        # billed to compile_wall, NOT dispatch — mixing them skewed
        # every warm-vs-cold generations/s comparison
        compile_wall = report.build_wall_s
        dispatch_wall = (t_after - t_disp) - compile_wall
        sims += batch
        failing = ~report.ok & ~report.overflowed
        # overflowed seeds are quarantined from guidance too: their
        # trajectories dropped events, so their bitmaps are artifacts
        cov_in = np.where(report.overflowed[:, None], np.uint32(0), report.cov)
        new_bits, global_map = admit(cov_in, global_map)
        admitted = 0
        for j in range(batch):
            key = (int(seeds[j]), int(report.traces[j]))
            fresh_viol = bool(failing[j]) and key not in seen_viol
            if not (new_bits[j] > 0 or fresh_viol):
                continue
            if plans is not None:
                plan = plans[j]
            else:
                plan = overrides.get(j) or _literal_from_rows(
                    rows, j, name=f"{space.plan.name}@{int(seeds[j])}"
                )
            entry = CorpusEntry(
                id=next_id, generation=g, parent=parents[j],
                seed=int(seeds[j]), plan=plan,
                trace=int(report.traces[j]), cov=report.cov[j].copy(),
                new_bits=int(new_bits[j]), violating=bool(failing[j]),
                halt_t=int(report.halt_times[j]),
            )
            next_id += 1
            if fresh_viol:
                # a violation is counted once per distinct (seed, trace)
                # trajectory — an inherited-seed child replaying its
                # parent's exact run is a duplicate, not a find
                seen_viol.add(key)
                violations.append(entry)
            if len(corpus) < max_corpus:
                corpus.append(entry)
                by_id[entry.id] = entry
                admitted += 1
        curve.append(popcount(global_map))
        viol_curve.append(len(violations))
        if log is not None:
            log(
                f"explore g{g}: {curve[-1]} coverage bits (+{admitted} "
                f"corpus entries, corpus {len(corpus)}), "
                f"{len(violations)} violations"
            )
        # host-side share of this generation's wall: parent selection,
        # mutation, plan stacking, admission bookkeeping — everything
        # that is NOT the batched dispatch (the split the device driver
        # collapses to one summary sync). mutate/admit are its two
        # measured components (plan breeding before the dispatch,
        # corpus bookkeeping after), so the campaign-Perfetto
        # generation spans can show where the host share goes.
        t_end = _time.monotonic()  # lint: allow(wall-clock)
        mutate_wall = t_disp - t_gen
        admit_wall = t_end - t_after
        host_wall = (t_end - t_gen) - (t_after - t_disp)
        wall_dispatch += dispatch_wall
        wall_host += host_wall
        wall_compile += compile_wall
        _emit({
            "event": "generation", "generation": g, "sims": sims,
            "cov_bits": curve[-1], "new_entries": admitted,
            "corpus_size": len(corpus), "violations": len(violations),
            "dispatch_wall_s": round(dispatch_wall, 3),
            "compile_wall_s": round(compile_wall, 3),
            "mutate_wall_s": round(mutate_wall, 3),
            "admit_wall_s": round(admit_wall, 3),
            "host_wall_s": round(host_wall, 3),
            # pipeline wall split: structurally zero on the host-driven
            # blocking loop (same schema as the pipelined driver)
            "queue_wall_s": 0.0,
            "idle_wall_s": 0.0,
        })
        if checkpoint_path is not None:
            _snapshot(g + 1).save(checkpoint_path)

    _emit({
        "event": "campaign_end", "generations": g_start + generations,
        "generations_run": generations,
        "sims": sims, "cov_bits": curve[-1] if curve else 0,
        "corpus_size": len(corpus), "violations": len(violations),
        "wall_dispatch_s": round(wall_dispatch, 3),
        "wall_host_s": round(wall_host, 3),
        "wall_compile_s": round(wall_compile, 3),
        "wall_queue_s": 0.0,
        "wall_idle_s": 0.0,
    })
    return ExploreReport(
        workload=wl.name,
        config_hash=cfg.hash(),
        plan_hash=space.hash(),
        root_seed=int(root_seed),
        generations=g_start + generations,
        batch=batch,
        max_steps=max_steps,
        cov_words=cov_words,
        sims=sims,
        corpus=corpus,
        violations=violations,
        cov_map=global_map,
        curve=curve,
        viol_curve=viol_curve,
        next_id=next_id,
        cov_hitcount=cov_hitcount,
        wall_dispatch_s=wall_dispatch,
        wall_host_s=wall_host,
        wall_compile_s=wall_compile,
        wall_gens=generations,
    )
