"""Deterministic plan mutation over a declarative plan space.

The corpus loop (explore/driver.py) keeps *interesting* ``(seed,
LiteralPlan)`` entries and breeds new candidates from them. This module
owns the breeding: a :class:`PlanSpace` pairs a :class:`FaultPlan` with
its per-slot :class:`~madsim_tpu.chaos.plan.SlotTemplate` metadata, and
:func:`mutate_plan` applies 1..max_ops structural perturbations to a
parent plan:

* **retime** — redraw an event's time inside its slot's template window
  (line up a kill with the commit it should interrupt);
* **retarget** — redraw the event's node args from the template's
  target set (hit the OTHER replica; cut a different edge);
* **drop** — disable a slot (ddmin's move, applied generatively);
* **add** — re-enable a disabled slot with freshly drawn time/args
  (partitions compile one slot pair per node-subset edge, most of them
  disabled, so "add" grows cuts edge by edge).

Every draw comes from a :class:`HostStream` — scalar threefry on the
child's key, which the driver derives from ``(root seed, generation,
batch slot)``. No global RNG anywhere: the whole campaign is a pure
function of the root seed.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..chaos.plan import FaultPlan, LiteralPlan
from ..engine.core import pack_slow_arg
from ..engine.rng import np_threefry2x32

__all__ = ["HostStream", "PlanSpace", "mutate_plan", "mutation_table"]

# Effective retarget modes, one per plan slot — the static resolution of
# _retarget's condition chain (arg_kind x target-count). MODE_RETIME is
# the fallback: args are fixed for the slot, so a retarget op perturbs
# the time instead. The device mutator (explore/device.py) branches on
# these the same way the host chain does; mutation_table() is the one
# place the resolution happens, so the two implementations cannot
# disagree about which slot takes which branch.
MODE_NODE, MODE_PAIR, MODE_SLOW, MODE_SKEW, MODE_RETIME = range(5)

# draws a retarget consumes per mode (node: 1 pick; pair: 2 picks;
# slow: 2 picks + mult; skew: pick + skew; fallback: retime's 2) — the
# device mutator advances its draw counter by exactly these amounts so
# its stream stays draw-for-draw aligned with HostStream's edit script
RETARGET_DRAWS = (1, 2, 3, 2, 2)


def inherit_threshold(inherit_seed_p: float) -> int:
    """The 32-bit draw threshold below which a child inherits its
    parent's engine seed. Parity-critical like RETARGET_DRAWS: both
    campaign drivers compare the same draw against this SAME integer,
    so the probability->threshold mapping must resolve in one place."""
    return int(inherit_seed_p * (1 << 32))


class HostStream:
    """Sequential scalar draws from one threefry key (host-side).

    Unlike the engine's coordinate-addressed draws, mutation is an
    inherently sequential host edit script, so a running draw index is
    the natural counter — determinism holds because the edit script
    itself is deterministic. ``x1`` namespaces the stream (the driver
    passes PURPOSE_EXPLORE, far above every in-simulation purpose).
    """

    def __init__(self, k0: int, k1: int, x1: int):
        self._k0 = np.uint32(k0)
        self._k1 = np.uint32(k1)
        self._x1 = np.uint32(x1)
        self._j = 0

    def bits(self) -> int:
        a, _ = np_threefry2x32(self._k0, self._k1, np.uint32(self._j), self._x1)
        self._j += 1
        return int(a)

    def uniform(self, lo: int, hi: int) -> int:
        """Uniform int in [lo, hi) — the engine's modulo reduction."""
        return int(lo) + self.bits() % max(int(hi) - int(lo), 1)

    def pick(self, options):
        return options[self.bits() % len(options)]


class PlanSpace:
    """A :class:`FaultPlan` viewed as a search space.

    The FaultPlan supplies generation 0 (uniform per-seed compilation —
    exactly what ``search_seeds(plan=...)`` sweeps) and, through its
    ``slot_templates()``, the legal perturbation ranges for every slot.
    All plans in the campaign share the FaultPlan's slot count, so one
    compiled XLA program serves every generation.
    """

    def __init__(self, plan: FaultPlan):
        if not isinstance(plan, FaultPlan):
            raise TypeError(
                f"PlanSpace wraps a FaultPlan, got {type(plan).__name__}"
            )
        self.plan = plan
        self.templates = plan.slot_templates()
        if len(self.templates) != plan.slots:
            raise ValueError(
                f"plan {plan.name!r} exposes {len(self.templates)} slot "
                f"templates for {plan.slots} slots"
            )
        for i, t in enumerate(self.templates):
            # a pair/slow retarget draws "some OTHER target": with one
            # distinct value the host stream would pick from an empty
            # list (ZeroDivisionError) while the device mutator would
            # silently breed b == a — refuse the space up front so both
            # drivers fail identically and loudly
            if t.arg_kind in ("pair", "slow") and len(t.targets) >= 2 \
                    and len(set(t.targets)) < 2:
                raise ValueError(
                    f"plan {plan.name!r} slot {i} ({t.arg_kind}) needs "
                    f">= 2 distinct targets to retarget, got "
                    f"{tuple(t.targets)}"
                )

    @property
    def slots(self) -> int:
        return self.plan.slots

    def uses_dup(self) -> bool:
        return self.plan.uses_dup()

    def hash(self) -> str:
        return self.plan.hash()


def _effective_mode(tmpl) -> int:
    """Static resolution of _retarget's branch for one slot template."""
    kind = tmpl.arg_kind
    if kind == "node" and tmpl.targets:
        return MODE_NODE
    if kind == "pair" and len(tmpl.targets) >= 2:
        return MODE_PAIR
    if kind == "slow" and len(tmpl.targets) >= 2:
        return MODE_SLOW
    if kind == "skew" and tmpl.targets:
        return MODE_SKEW
    return MODE_RETIME


def mutation_table(space: PlanSpace) -> dict:
    """The space's SlotTemplate tuple as static per-slot numpy arrays —
    the device-resident form of the mutation surface.

    ``explore.device``'s vectorized mutator reads windows, target sets
    and retarget modes from these arrays while the host mutators above
    read the templates directly; both resolve the retarget branch
    through :func:`_effective_mode`, and the draw-parity test pins the
    two implementations draw-for-draw. Targets are padded to the widest
    slot (``tcnt`` holds the live count; padding is never selected
    because every pick reduces modulo the count).
    """
    tm = space.templates
    p = len(tm)
    width = max((len(t.targets) for t in tm), default=0) or 1
    tgt = np.zeros((p, width), np.int64)
    for i, t in enumerate(tm):
        if t.targets:
            tgt[i, : len(t.targets)] = np.asarray(t.targets, np.int64)
    mode = np.asarray([_effective_mode(t) for t in tm], np.int32)
    return {
        "t_lo": np.asarray([t.t_min_ns for t in tm], np.int64),
        # the host _retime floor: hi = max(t_max, t_min + 1)
        "t_hi": np.asarray(
            [max(t.t_max_ns, t.t_min_ns + 1) for t in tm], np.int64
        ),
        "mode": mode,
        "rt_draws": np.asarray([RETARGET_DRAWS[m] for m in mode], np.int32),
        "tgt": tgt,
        "tcnt": np.asarray([len(t.targets) for t in tm], np.int32),
        "mult_lo": np.asarray([t.mult_min for t in tm], np.int64),
        "mult_hi": np.asarray([t.mult_max for t in tm], np.int64),
        "skew_lo": np.asarray([t.skew_min_ns for t in tm], np.int64),
        "skew_hi": np.asarray([t.skew_max_ns for t in tm], np.int64),
    }


def _retime(events, i, tmpl, stream, horizon=None):
    lo, hi = tmpl.t_min_ns, max(tmpl.t_max_ns, tmpl.t_min_ns + 1)
    if horizon is not None and lo < horizon < hi:
        # keep the redraw inside the parent's causal window: an event
        # past the halt clock can never change the trajectory
        hi = horizon
    # fine/coarse mix (the AFL havoc idiom): half the retimes jitter
    # locally around the parent's value — a near-miss fault alignment
    # is TUNED, not re-rolled — and half redraw over the whole window
    if stream.bits() % 2 == 0:
        delta = max((hi - lo) // 8, 1)
        t = events[i].t + stream.uniform(-delta, delta + 1)
        t = min(max(t, lo), hi - 1)
    else:
        t = stream.uniform(lo, hi)
    events[i] = dataclasses.replace(events[i], t=t)


def _retarget(events, i, tmpl, stream, horizon=None):
    kind = tmpl.arg_kind
    if kind == "node" and tmpl.targets:
        events[i] = dataclasses.replace(events[i], a0=int(stream.pick(tmpl.targets)))
    elif kind == "pair" and len(tmpl.targets) >= 2:
        a = int(stream.pick(tmpl.targets))
        b = int(stream.pick([t for t in tmpl.targets if t != a]))
        events[i] = dataclasses.replace(events[i], a0=a, a1=b)
    elif kind == "slow" and len(tmpl.targets) >= 2:
        a = int(stream.pick(tmpl.targets))
        b = int(stream.pick([t for t in tmpl.targets if t != a]))
        mult = stream.uniform(tmpl.mult_min, tmpl.mult_max + 1)
        events[i] = dataclasses.replace(
            events[i], a0=a, a1=int(pack_slow_arg(b, mult))
        )
    elif kind == "skew" and tmpl.targets:
        a = int(stream.pick(tmpl.targets))
        skew = stream.uniform(tmpl.skew_min_ns, tmpl.skew_max_ns + 1)
        events[i] = dataclasses.replace(events[i], a0=a, a1=skew)
    else:  # args are fixed for this slot: perturb the time instead
        _retime(events, i, tmpl, stream, horizon)


def mutate_plan(
    parent: LiteralPlan,
    space: PlanSpace,
    stream: HostStream,
    max_ops: int = 3,
    name: str = "mut",
    horizon: int | None = None,
) -> LiteralPlan:
    """Breed one child plan from ``parent`` (same slot count as the
    space). Applies 1..max_ops draws-driven perturbations; always
    returns a NEW LiteralPlan (the parent is never modified).

    ``horizon`` is the parent run's halt clock (ns): slots whose events
    fired after it are causally dead — perturbing them replays the
    parent bit-for-bit, a wasted simulation — so ops target the live
    region when a horizon is known (AFL's input-trimming economy).
    """
    if parent.slots != space.slots:
        raise ValueError(
            f"parent has {parent.slots} slots, space has {space.slots}"
        )
    events = list(parent.events)
    enabled = list(parent._mask())
    templates = space.templates

    def live(idx):
        if horizon is None:
            return idx
        alive = [i for i in idx if events[i].t < horizon]
        return alive or idx

    n_ops = 1 + stream.bits() % max(max_ops, 1)
    for _ in range(n_ops):
        # op weights (out of 8): retime 4, retarget 2, drop 1, add 1 —
        # retiming dominates because it is the gentlest move (a
        # violating parent's structure survives), while the structural
        # ops keep the plan-shape space reachable
        op = stream.bits() % 8
        on_idx = [i for i, e in enumerate(enabled) if e]
        off_idx = [i for i, e in enumerate(enabled) if not e]
        if op == 0 and off_idx:  # add: enable a reserved slot afresh
            i = stream.pick(off_idx)
            enabled[i] = True
            _retime(events, i, templates[i], stream, horizon)
            _retarget(events, i, templates[i], stream, horizon)
        elif op == 1 and len(on_idx) > 1:  # drop (keep at least one)
            enabled[stream.pick(live(on_idx))] = False
        elif op in (2, 3) and on_idx:
            i = stream.pick(live(on_idx))
            _retarget(events, i, templates[i], stream, horizon)
        elif on_idx:
            i = stream.pick(live(on_idx))
            _retime(events, i, templates[i], stream, horizon)
        elif off_idx:  # degenerate all-disabled parent: force an add
            i = stream.pick(off_idx)
            enabled[i] = True
            _retime(events, i, templates[i], stream, horizon)
            _retarget(events, i, templates[i], stream, horizon)
    return LiteralPlan(
        events=tuple(events), enabled=tuple(enabled), name=name
    )
