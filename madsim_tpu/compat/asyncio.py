"""asyncio API surface with per-call sim/real dispatch.

The analog of madsim-tokio (reference madsim-tokio/src/lib.rs): code
written against asyncio runs unmodified inside the deterministic
simulator. The reference's cfg-switch picks the implementation at build
time; Python has no build-time cfg, so every entry point here checks
``context.in_simulation()`` at call time — inside a simulated task it
uses the deterministic runtime (virtual time, seeded scheduling), outside
it delegates to the real asyncio module.

Note: since the loop-level interposition landed
(:mod:`madsim_tpu.runtime.aio`), even code importing the REAL asyncio
module works inside sims — the stdlib primitives run against a
sim-backed loop installed in the running-loop slot. This module remains
the explicit-import surface (stable API, per-call dual dispatch for
code that must run in both worlds).

Covered surface (the part madsim-tokio simulates: task/time/sync —
lib.rs:4-52; io/fs/signal are delegated):
  sleep, wait_for, timeout, create_task, ensure_future, gather, wait,
  current_task, CancelledError, TimeoutError, Queue, LifoQueue,
  PriorityQueue, Lock, Event, Condition, Semaphore, BoundedSemaphore,
  run, get_event_loop (minimal).

Like the reference's insight that tokio's sync primitives are "already
deterministic given deterministic scheduling" (SURVEY §2 C21), the sim
implementations here are thin maps onto madsim_tpu.sync.
"""

from __future__ import annotations

import asyncio as _real
import heapq
from typing import Any, Coroutine, Optional

from ..runtime import context
from ..runtime.future import SimFuture
from ..sync import Notify
from ..sync import Semaphore as _SimSemaphore

__all__ = [
    "CancelledError",
    "TimeoutError",
    "sleep",
    "wait_for",
    "timeout",
    "create_task",
    "ensure_future",
    "gather",
    "wait",
    "FIRST_COMPLETED",
    "ALL_COMPLETED",
    "run",
    "Queue",
    "LifoQueue",
    "PriorityQueue",
    "QueueEmpty",
    "QueueFull",
    "Lock",
    "Event",
    "Condition",
    "Semaphore",
    "BoundedSemaphore",
]

CancelledError = _real.CancelledError
TimeoutError = _real.TimeoutError
QueueEmpty = _real.QueueEmpty
QueueFull = _real.QueueFull
FIRST_COMPLETED = _real.FIRST_COMPLETED
ALL_COMPLETED = _real.ALL_COMPLETED
FIRST_EXCEPTION = _real.FIRST_EXCEPTION


def _sim() -> bool:
    return context.in_simulation()


# ---------------------------------------------------------------------------
# time
# ---------------------------------------------------------------------------


async def sleep(delay: float, result: Any = None) -> Any:
    if not _sim():
        return await _real.sleep(delay, result)
    from ..runtime.time_ import sleep as sim_sleep

    await sim_sleep(delay)
    return result


async def wait_for(aw, timeout: Optional[float]):
    if not _sim():
        return await _real.wait_for(aw, timeout)
    from ..runtime.time_ import Elapsed
    from ..runtime.time_ import timeout as sim_timeout

    if timeout is None:
        return await _ensure_sim_future(aw)
    try:
        return await sim_timeout(timeout, _ensure_sim_future(aw))
    except Elapsed:
        raise TimeoutError from None


class timeout:
    """``async with asyncio.timeout(5):`` — py3.11 API. In simulation a
    virtual-time timer injects TimeoutError into the task at whatever
    await point it is parked on when the deadline expires — the same
    cancel-the-body semantics as real asyncio, so liveness guards keep
    working on code that blocks forever."""

    def __init__(self, delay: Optional[float]):
        self._delay = delay
        self._real_cm = None
        self._armed = False

    async def __aenter__(self):
        if not _sim():
            self._real_cm = _real.timeout(self._delay)
            return await self._real_cm.__aenter__()
        if self._delay is not None:
            handle = context.current_handle()
            task = context.current_task()
            self._armed = True

            def fire() -> None:
                if self._armed and not task.finished:
                    self._armed = False
                    task.throw_soon(TimeoutError())
                    handle.executor._schedule(task)

            handle.time.add_timer(max(self._delay, 0.0), fire)
        return self

    async def __aexit__(self, et, ev, tb):
        if self._real_cm is not None:
            return await self._real_cm.__aexit__(et, ev, tb)
        self._armed = False
        return False


# ---------------------------------------------------------------------------
# tasks
# ---------------------------------------------------------------------------


class _TaskWrapper:
    """asyncio.Task-like facade over a sim JoinHandle."""

    def __init__(self, handle):
        self._handle = handle

    def __await__(self):
        return self._handle.__await__()

    def done(self) -> bool:
        return self._handle.done()

    def cancel(self) -> bool:
        self._handle.abort()
        return True

    def result(self):
        fut = self._handle._fut
        if not fut.done():
            raise _real.InvalidStateError("result is not set")
        return fut.result()

    def exception(self):
        return self._handle._fut.exception()


def create_task(coro: Coroutine, *, name: Optional[str] = None):
    if not _sim():
        return _real.get_event_loop().create_task(coro, name=name)
    from ..runtime.task import spawn

    return _TaskWrapper(spawn(coro, name=name or ""))


def ensure_future(aw):
    if not _sim():
        return _real.ensure_future(aw)
    if isinstance(aw, (_TaskWrapper, SimFuture)):
        return aw
    return create_task(aw)


async def gather(*aws, return_exceptions: bool = False):
    if not _sim():
        return await _real.gather(*aws, return_exceptions=return_exceptions)
    tasks = [ensure_future(a) for a in aws]
    results = []
    for t in tasks:
        try:
            results.append(await t)
        except BaseException as e:  # noqa: BLE001 - mirrors asyncio.gather
            if return_exceptions:
                results.append(e)
            else:
                raise
    return results


async def wait(aws, *, timeout: Optional[float] = None,
               return_when: str = ALL_COMPLETED):
    if not _sim():
        return await _real.wait(aws, timeout=timeout, return_when=return_when)
    from ..runtime.future import select
    from ..runtime.time_ import sleep as sim_sleep

    tasks = [ensure_future(a) for a in aws]
    deadline = None
    if timeout is not None:
        deadline = create_task(sleep(timeout))
    pending = list(tasks)
    done: list = []
    while pending:
        futs = [t._handle._fut if isinstance(t, _TaskWrapper) else t for t in pending]
        if deadline is not None:
            futs = futs + [deadline._handle._fut]
        idx, _ = await select(*futs)
        if deadline is not None and idx == len(pending):
            break
        t = pending.pop(idx)
        done.append(t)
        if return_when == FIRST_COMPLETED:
            break
        if return_when == FIRST_EXCEPTION and t.exception() is not None:
            break
    if deadline is not None:
        deadline.cancel()
    return set(done), set(pending)


def _ensure_sim_future(aw):
    if hasattr(aw, "__await__"):
        return aw
    raise TypeError(f"not awaitable: {aw!r}")


def run(main: Coroutine, *, debug: Optional[bool] = None):
    """Outside a sim: real asyncio.run. (Inside a sim you are already in
    a runtime; just await.) A top-level run() under MADSIM_TEST_* env
    vars goes through the seeded Builder, so existing asyncio programs
    gain deterministic replay with one import change."""
    if _sim():
        raise RuntimeError(
            "asyncio.run() called inside a simulation; await the coroutine"
        )
    import os

    if any(k.startswith("MADSIM_TEST_") for k in os.environ):
        from ..runtime.builder import Builder

        b = Builder.from_env()
        if callable(main):
            # factory form: each seed gets a fresh coroutine
            return b.run(main)
        if b.count > 1 or b.check_determinism:
            raise TypeError(
                "asyncio.run(coro) cannot replay one coroutine object for "
                "multiple seeds; pass the async function itself "
                "(asyncio.run(main_fn)) or use @madsim_tpu.test"
            )
        return b.run(lambda: main)
    if callable(main):
        main = main()
    return _real.run(main, debug=debug)


def get_event_loop():
    if not _sim():
        return _real.get_event_loop()
    return _SimLoop()


class _SimLoop:
    """Minimal loop facade for code that calls loop.create_task etc."""

    def create_task(self, coro: Coroutine, *, name: Optional[str] = None):
        return create_task(coro, name=name)

    def time(self) -> float:
        from ..runtime.time_ import now_ns

        return now_ns() / 1e9

    def call_later(self, delay: float, callback, *args):
        from ..runtime import context as _ctx

        _ctx.current_handle().time.add_timer(delay, lambda: callback(*args))


# ---------------------------------------------------------------------------
# sync primitives — deterministic given deterministic scheduling (C21)
# ---------------------------------------------------------------------------


class Queue:
    """asyncio.Queue over sim futures (unbounded when maxsize<=0)."""

    _REAL = None  # set below per class; subclasses keep their own order

    def __init__(self, maxsize: int = 0):
        if not _sim():
            self.__class__ = type(self)._REAL  # construct the real one
            type(self).__init__(self, maxsize)
            return
        self._maxsize = maxsize
        self._items: list = []
        self._getters: list[SimFuture] = []
        self._putters: list[tuple[SimFuture, Any]] = []
        self._unfinished_tasks = 0
        self._join_waiters: list[SimFuture] = []

    # -- sim implementation --
    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return self._maxsize > 0 and len(self._items) >= self._maxsize

    def _pop_item(self):
        return self._items.pop(0)

    def _push_item(self, item) -> None:
        self._items.append(item)

    async def put(self, item) -> None:
        while self.full():
            fut = SimFuture(name="queue.put")
            self._putters.append((fut, None))
            await fut
        self.put_nowait(item)

    def put_nowait(self, item) -> None:
        if self.full():
            raise QueueFull
        self._push_item(item)
        self._unfinished_tasks += 1
        while self._getters:
            g = self._getters.pop(0)
            if not g.done():
                g.set_result(None)
                break

    async def get(self):
        while self.empty():
            fut = SimFuture(name="queue.get")
            self._getters.append(fut)
            await fut
        return self.get_nowait()

    def get_nowait(self):
        if self.empty():
            raise QueueEmpty
        item = self._pop_item()
        while self._putters:
            p, _ = self._putters.pop(0)
            if not p.done():
                p.set_result(None)
                break
        return item

    async def join(self) -> None:
        """Block until every item ever put has been marked task_done.

        The real asyncio contract (unfinished-task count, not queue
        emptiness): the reference's tokio shim gets this for free by
        reusing real tokio sync types (madsim-tokio/src/lib.rs:39-52 —
        "tokio::sync is designed for single thread"); the sim Queue
        implements the same counter semantics directly.
        """
        while self._unfinished_tasks > 0:
            fut = SimFuture(name="queue.join")
            self._join_waiters.append(fut)
            await fut

    def task_done(self) -> None:
        if self._unfinished_tasks <= 0:
            raise ValueError("task_done() called too many times")
        self._unfinished_tasks -= 1
        if self._unfinished_tasks == 0:
            waiters, self._join_waiters = self._join_waiters, []
            for w in waiters:
                if not w.done():
                    w.set_result(None)


class LifoQueue(Queue):
    def _pop_item(self):
        return self._items.pop()


class PriorityQueue(Queue):
    def _push_item(self, item) -> None:
        heapq.heappush(self._items, item)

    def _pop_item(self):
        return heapq.heappop(self._items)


Queue._REAL = _real.Queue
LifoQueue._REAL = _real.LifoQueue
PriorityQueue._REAL = _real.PriorityQueue


class Lock:
    def __init__(self):
        if not _sim():
            self.__class__ = _real.Lock
            _real.Lock.__init__(self)
            return
        self._sem = _SimSemaphore(1)

    async def acquire(self) -> bool:
        await self._sem.acquire()
        return True

    def release(self) -> None:
        self._sem.release()

    def locked(self) -> bool:
        return self._sem._permits == 0

    async def __aenter__(self):
        await self.acquire()
        return None

    async def __aexit__(self, *exc):
        self.release()
        return False


class Event:
    def __init__(self):
        if not _sim():
            self.__class__ = _real.Event
            _real.Event.__init__(self)
            return
        self._set = False
        self._waiters: list[SimFuture] = []

    def is_set(self) -> bool:
        return self._set

    def set(self) -> None:
        self._set = True
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            if not w.done():
                w.set_result(None)

    def clear(self) -> None:
        self._set = False

    async def wait(self) -> bool:
        while not self._set:
            fut = SimFuture(name="event.wait")
            self._waiters.append(fut)
            await fut
        return True


class Semaphore:
    def __init__(self, value: int = 1):
        if not _sim():
            self.__class__ = _real.Semaphore
            _real.Semaphore.__init__(self, value)
            return
        self._sem = _SimSemaphore(value)

    async def acquire(self) -> bool:
        await self._sem.acquire()
        return True

    def release(self) -> None:
        self._sem.release()

    def locked(self) -> bool:
        return self._sem._permits == 0

    async def __aenter__(self):
        await self.acquire()
        return None

    async def __aexit__(self, *exc):
        self.release()
        return False


class BoundedSemaphore(Semaphore):
    def __init__(self, value: int = 1):
        if not _sim():
            self.__class__ = _real.BoundedSemaphore
            _real.BoundedSemaphore.__init__(self, value)
            return
        super().__init__(value)
        self._bound = value

    def release(self) -> None:
        if self._sem._permits >= self._bound:
            raise ValueError("BoundedSemaphore released too many times")
        super().release()


class Condition:
    def __init__(self, lock: Optional[Lock] = None):
        if not _sim():
            self.__class__ = _real.Condition
            _real.Condition.__init__(self, lock)
            return
        self._lock = lock or Lock()
        # plain waiter list (not Notify): asyncio semantics say a notify
        # with no waiters is a no-op, never a stored permit
        self._waiters: list[SimFuture] = []

    async def __aenter__(self):
        await self._lock.acquire()
        return self

    async def __aexit__(self, *exc):
        self._lock.release()
        return False

    async def wait(self) -> bool:
        fut = SimFuture(name="condition.wait")
        self._waiters.append(fut)
        self._lock.release()
        await fut
        await self._lock.acquire()
        return True

    def notify(self, n: int = 1) -> None:
        woken = 0
        while self._waiters and woken < n:
            w = self._waiters.pop(0)
            if not w.done():
                w.set_result(None)
                woken += 1

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


def __getattr__(name: str):
    """Anything not simulated falls through to the real asyncio module
    (the lib.rs:39-52 'not simulated: reuse real' list)."""
    return getattr(_real, name)
