"""Ecosystem compatibility shims.

The reference ships madsim-tokio: the same ``tokio::`` API surface that
transparently switches between the real runtime and the simulator at
build time (reference madsim-tokio/src/lib.rs:1-52). The Python analog is
:mod:`madsim_tpu.compat.asyncio`: the asyncio API surface that dispatches
per call — inside a simulation it maps onto the deterministic runtime;
outside it delegates to the real asyncio, so one import works in tests
and in production:

    from madsim_tpu.compat import asyncio   # instead of `import asyncio`

``install()`` registers the shim under the name ``asyncio`` in
``sys.modules`` for code you cannot edit (the Cargo-patch analog); call
``uninstall()`` to undo.
"""

import sys

from . import asyncio  # noqa: F401

_real_asyncio = None


def install() -> None:
    """Replace ``sys.modules['asyncio']`` with the dispatching shim."""
    global _real_asyncio
    import asyncio as real

    if real is not asyncio:
        _real_asyncio = real
        sys.modules["asyncio"] = asyncio


def uninstall() -> None:
    global _real_asyncio
    if _real_asyncio is not None:
        sys.modules["asyncio"] = _real_asyncio
        _real_asyncio = None
