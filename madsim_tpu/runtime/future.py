"""One-shot futures and combinators for the deterministic executor.

This is the waker substrate of the simulator: the analog of Rust's
``std::future::Future`` + waker protocol that the reference executor drives
(reference: madsim/src/sim/task.rs polls `async_task` runnables). Here a
coroutine awaits a :class:`SimFuture`; the executor receives the yielded
future and registers a waker callback that re-schedules the task when the
future resolves.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

__all__ = [
    "SimFuture",
    "select",
    "join_all",
    "Cancelled",
]


class Cancelled(Exception):
    """Raised when awaiting a future whose producer was cancelled/killed."""


class SimFuture:
    """A one-shot future usable with ``await`` inside the simulation.

    Not thread-safe by design: a whole simulation runs on one OS thread
    (reference: madsim/src/sim/task.rs:142-216 single-threaded executor).
    """

    __slots__ = ("_done", "_result", "_exc", "_wakers", "name")

    def __init__(self, name: str = ""):
        self._done = False
        self._result: Any = None
        self._exc: BaseException | None = None
        self._wakers: list[Callable[[], None]] = []
        self.name = name

    # -- producer side ----------------------------------------------------
    def set_result(self, value: Any = None) -> None:
        if self._done:
            return
        self._done = True
        self._result = value
        self._wake()

    def set_exception(self, exc: BaseException) -> None:
        if self._done:
            return
        self._done = True
        self._exc = exc
        self._wake()

    def _wake(self) -> None:
        wakers, self._wakers = self._wakers, []
        for w in wakers:
            w()

    # -- consumer side ----------------------------------------------------
    def done(self) -> bool:
        return self._done

    def result(self) -> Any:
        if not self._done:
            raise RuntimeError("future not ready")
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self) -> BaseException | None:
        return self._exc

    def add_waker(self, waker: Callable[[], None]) -> None:
        """Register a completion callback. Fires immediately if already done."""
        if self._done:
            waker()
        else:
            self._wakers.append(waker)

    def __await__(self):
        # Loop guards against spurious wakeups (e.g. select losers).
        while not self._done:
            yield self
        if self._exc is not None:
            raise self._exc
        return self._result


def _as_future(f) -> SimFuture:
    """Accept a SimFuture or anything wrapping one (JoinHandle's _fut) —
    tokio's combinators take JoinHandles because JoinHandle: Future;
    the duck-typed unwrap is the analog (task.rs:569-609)."""
    return f if isinstance(f, SimFuture) else getattr(f, "_fut", f)


def select(*futures) -> SimFuture:
    """Future resolving to ``(index, input)`` of the first completed input.

    The deterministic analog of ``tokio::select!`` / ``futures::select``.
    Accepts SimFutures or spawn() JoinHandles; the winner is returned
    AS PASSED (a JoinHandle input resolves to that JoinHandle, so e.g.
    ``loser.abort()`` / identity checks against the inputs work).
    """
    out = SimFuture(name="select")

    def mk(i: int, orig) -> Callable[[], None]:
        def on_done() -> None:
            if not out._done:
                out.set_result((i, orig))

        return on_done

    for i, orig in enumerate(futures):
        _as_future(orig).add_waker(mk(i, orig))
    return out


def join_all(futures: Iterable) -> SimFuture:
    """Future resolving to the list of all results (analog of join_all).

    Accepts SimFutures or spawn() JoinHandles, like tokio's join_all
    over JoinHandles (JoinHandle: Future)."""
    futs = [_as_future(f) for f in futures]
    out = SimFuture(name="join_all")
    remaining = len(futs)
    if remaining == 0:
        out.set_result([])
        return out
    state = {"n": remaining}

    def mk(f: SimFuture) -> Callable[[], None]:
        def on_done() -> None:
            if out._done:
                return
            if f._exc is not None:
                out.set_exception(f._exc)
                return
            state["n"] -= 1
            if state["n"] == 0:
                out.set_result([x.result() for x in futs])

        return on_done

    for f in futs:
        f.add_waker(mk(f))
    return out
