"""Single-seed deterministic simulation runtime (the madsim-core parity
layer; reference: /root/reference/madsim/src/sim/)."""

from .builder import Builder, main, test
from .config import Config, NetConfig, TcpConfig
from .context import current_handle, in_simulation, try_current_handle
from .future import Cancelled, SimFuture, join_all, select
from .intercept import available_parallelism
from .plugin import Simulator, node, simulator
from .rand import DeterminismError, GlobalRng, random, thread_rng
from .runtime import DEFAULT_SIMULATORS, Handle, NodeBuilder, NodeHandle, Runtime
from .trace import SimContextFilter, SimFormatter, init_logger, span
from .task import (
    DeadlockError,
    FallibleTask,
    JoinError,
    JoinHandle,
    TimeLimitError,
    spawn,
    spawn_blocking,
    spawn_local,
    yield_now,
)
from .time_ import (
    Elapsed,
    Instant,
    Interval,
    MissedTickBehavior,
    SystemTime,
    interval,
    now,
    now_ns,
    sleep,
    sleep_until,
    timeout,
)

__all__ = [
    "Builder",
    "Cancelled",
    "Config",
    "DEFAULT_SIMULATORS",
    "DeadlockError",
    "DeterminismError",
    "Elapsed",
    "GlobalRng",
    "Handle",
    "Instant",
    "Interval",
    "JoinError",
    "JoinHandle",
    "MissedTickBehavior",
    "NetConfig",
    "NodeBuilder",
    "NodeHandle",
    "Runtime",
    "SimFuture",
    "SimContextFilter",
    "SimFormatter",
    "Simulator",
    "SystemTime",
    "TcpConfig",
    "TimeLimitError",
    "available_parallelism",
    "current_handle",
    "in_simulation",
    "init_logger",
    "interval",
    "join_all",
    "main",
    "node",
    "now",
    "now_ns",
    "random",
    "select",
    "simulator",
    "span",
    "sleep",
    "sleep_until",
    "FallibleTask",
    "spawn",
    "spawn_blocking",
    "spawn_local",
    "yield_now",
    "test",
    "thread_rng",
    "timeout",
    "try_current_handle",
]
