"""Simulation configuration.

Parity with reference madsim/src/sim/config.rs: a small typed config
(``{net, tcp}``, config.rs:15-23) that can be parsed from TOML
(config.rs:35-48) and hashed stably (config.rs:27-31) so a failing test can
print a full repro recipe of ``seed + config hash``
(reference sim/runtime/mod.rs:193-200).
"""

from __future__ import annotations

import dataclasses
import hashlib

try:
    import tomllib  # Python 3.11+
except ImportError:  # 3.10: the installed tomli backport is API-identical
    import tomli as tomllib

from dataclasses import dataclass, field

__all__ = ["NetConfig", "TcpConfig", "Config"]


@dataclass
class NetConfig:
    """Network fault model (reference sim/net/network.rs:75-95).

    * ``packet_loss_rate`` — probability each message is dropped.
    * ``send_latency`` — (min_s, max_s) uniform one-way latency range;
      the reference default is 1-10 ms.
    """

    packet_loss_rate: float = 0.0
    send_latency: tuple[float, float] = (0.001, 0.010)

    @classmethod
    def from_dict(cls, d: dict) -> "NetConfig":
        cfg = cls()
        if "packet_loss_rate" in d:
            cfg.packet_loss_rate = float(d["packet_loss_rate"])
        if "send_latency" in d:
            lo, hi = d["send_latency"]
            cfg.send_latency = (float(lo), float(hi))
        return cfg


@dataclass
class TcpConfig:
    """Placeholder, matching the reference's empty TcpConfig
    (sim/net/tcp/config.rs)."""

    @classmethod
    def from_dict(cls, d: dict) -> "TcpConfig":
        return cls()


@dataclass
class Config:
    net: NetConfig = field(default_factory=NetConfig)
    tcp: TcpConfig = field(default_factory=TcpConfig)

    def hash(self) -> int:
        """Stable content hash (reference config.rs:27-31).

        Uses sha256 over the canonical dataclass repr — independent of
        PYTHONHASHSEED so the printed repro recipe is portable.
        """
        canon = repr(dataclasses.asdict(self)).encode()
        return int.from_bytes(hashlib.sha256(canon).digest()[:8], "big")

    @classmethod
    def from_toml(cls, text: str) -> "Config":
        d = tomllib.loads(text)
        return cls(
            net=NetConfig.from_dict(d.get("net", {})),
            tcp=TcpConfig.from_dict(d.get("tcp", {})),
        )

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_toml(f.read())
