"""Determinism substrate: stdlib interposition.

The reference achieves "user code is unchanged" determinism by overriding
libc symbols — ``getrandom``/``getentropy`` (madsim/src/sim/rand.rs:174-240),
``gettimeofday``/``clock_gettime`` (sim/time/system_time.rs:6-109) — and by
*forbidding thread creation* inside a simulation (``pthread_attr_init``
panics, sim/task.rs:711-725). Each override checks whether the calling
thread is inside a madsim context and either serves a simulated value or
falls through to the real implementation.

The Python analog interposes at the stdlib layer: module-level functions of
:mod:`random`, :mod:`time`, :mod:`os` entropy/CPU introspection, and
``threading.Thread.start`` are replaced once with dispatchers that check
:func:`madsim_tpu.runtime.context.in_simulation` per call — simulated
behavior inside a runtime, the original behavior everywhere else. This
makes unmodified user code calling ``random.random()`` / ``time.time()`` /
``os.urandom()`` deterministic per seed, including :mod:`uuid` (which draws
from ``os.urandom``).

Known gap (documented, matches the spirit of the reference's ignored Linux
``SYS_getrandom`` test, rand.rs:248-252): C extensions that read entropy or
clocks directly (e.g. ``datetime.datetime.now``) bypass this layer.
"""

from __future__ import annotations

import contextlib
import os
import random as _random_mod
import threading
import time as _time_mod
from typing import Iterator

from . import context

__all__ = ["install", "deterministic_stdlib", "available_parallelism"]

_installed = False
_originals: dict = {}


def _sim_handle():
    return context.try_current_handle()


def available_parallelism() -> int:
    """Core count of the current simulated node (the analog of the
    ``sched_getaffinity``/``sysconf`` overrides, task.rs:659-710)."""
    task = context.try_current_task()
    if task is not None:
        return task.node.cores
    return os.cpu_count() or 1


def _make_random_dispatch(name: str):
    orig = getattr(_random_mod, name)

    def dispatch(*args, **kwargs):
        h = _sim_handle()
        if h is None:
            return orig(*args, **kwargs)
        value = getattr(h.rng._rng, name)(*args, **kwargs)
        h.rng._observe(value if not isinstance(value, list) else tuple(value))
        return value

    dispatch.__name__ = name
    dispatch.__qualname__ = f"madsim_intercept.{name}"
    return orig, dispatch


_RANDOM_FNS = [
    "random",
    "randint",
    "randrange",
    "uniform",
    "choice",
    "getrandbits",
    "randbytes",
    "gauss",
    "normalvariate",
    "expovariate",
    "betavariate",
    "triangular",
    "sample",
    "choices",
]


def install() -> None:
    """Install the dispatchers (idempotent, process-wide).

    Out-of-simulation callers always reach the original implementations,
    mirroring the reference's ``dlsym(RTLD_NEXT, ...)`` passthrough."""
    global _installed
    if _installed:
        return
    _installed = True

    # --- random module (rand.rs:174-240 analog) -------------------------
    for name in _RANDOM_FNS:
        if not hasattr(_random_mod, name):
            continue
        orig, dispatch = _make_random_dispatch(name)
        _originals[("random", name)] = orig
        setattr(_random_mod, name, dispatch)

    # random.shuffle routes through the observed Fisher-Yates
    orig_shuffle = _random_mod.shuffle
    _originals[("random", "shuffle")] = orig_shuffle

    def shuffle(seq):
        h = _sim_handle()
        if h is None:
            return orig_shuffle(seq)
        return h.rng.shuffle(seq)

    _random_mod.shuffle = shuffle

    # random.seed inside a simulation re-seeds the *global* sim RNG stream;
    # forbid it to protect determinism bookkeeping.
    orig_seed = _random_mod.seed
    _originals[("random", "seed")] = orig_seed

    def seed(*args, **kwargs):
        h = _sim_handle()
        if h is None:
            return orig_seed(*args, **kwargs)
        raise RuntimeError(
            "random.seed() is forbidden inside a simulation; the RNG is "
            "seeded by the runtime (use a local random.Random instead)"
        )

    _random_mod.seed = seed

    # --- os entropy / CPU topology --------------------------------------
    orig_urandom = os.urandom
    _originals[("os", "urandom")] = orig_urandom

    def urandom(n: int) -> bytes:
        h = _sim_handle()
        if h is None:
            return orig_urandom(n)
        return h.rng.randbytes(n)

    os.urandom = urandom

    orig_cpu_count = os.cpu_count
    _originals[("os", "cpu_count")] = orig_cpu_count

    def cpu_count():
        t = context.try_current_task()
        if t is not None:
            return t.node.cores
        return orig_cpu_count()

    os.cpu_count = cpu_count

    # --- time module (system_time.rs:6-109 analog) ----------------------
    def _patch_time(name: str, fn):
        orig = getattr(_time_mod, name)
        _originals[("time", name)] = orig

        def dispatch():
            h = _sim_handle()
            if h is None:
                return orig()
            return fn(h)

        dispatch.__name__ = name
        setattr(_time_mod, name, dispatch)

    _patch_time("time", lambda h: (h.time.base_unix_ns + h.time.now_ns()) / 1e9)
    _patch_time("time_ns", lambda h: h.time.base_unix_ns + h.time.now_ns())
    _patch_time("monotonic", lambda h: h.time.now_ns() / 1e9)
    _patch_time("monotonic_ns", lambda h: h.time.now_ns())
    _patch_time("perf_counter", lambda h: h.time.now_ns() / 1e9)
    _patch_time("perf_counter_ns", lambda h: h.time.now_ns())

    # Blocking sleep inside the sim advances the virtual clock
    # synchronously (there is only one OS thread; really sleeping would
    # deadlock the whole simulation).
    orig_sleep = _time_mod.sleep
    _originals[("time", "sleep")] = orig_sleep

    def t_sleep(seconds: float):
        h = _sim_handle()
        if h is None:
            return orig_sleep(seconds)
        h.time._rt.advance(round(seconds * 1e9))

    _time_mod.sleep = t_sleep

    # --- asyncio.as_completed: the ONE stdlib asyncio API whose spawn
    # order is memory-address-dependent (it dedups through set(fs));
    # inside a sim it must spawn in input order or replays diverge —
    # caught by the determinism checker. Everything else in asyncio
    # runs unmodified through the loop interposition (runtime/aio.py).
    import asyncio as _aio_mod

    orig_as_completed = _aio_mod.as_completed
    _originals[("asyncio", "as_completed")] = orig_as_completed

    def as_completed(fs, *, timeout=None):
        if context.in_simulation():
            from . import aio as _aio_impl

            return _aio_impl.deterministic_as_completed(fs, timeout=timeout)
        return orig_as_completed(fs, timeout=timeout)

    _aio_mod.as_completed = as_completed
    _aio_mod.tasks.as_completed = as_completed

    # --- forbid real threads inside the sim (task.rs:711-725) -----------
    orig_start = threading.Thread.start
    _originals[("threading", "start")] = orig_start

    def start(self):
        if context.in_simulation():
            raise RuntimeError(
                "cannot create system threads inside a simulation; "
                "use madsim_tpu.spawn instead"
            )
        return orig_start(self)

    threading.Thread.start = start


@contextlib.contextmanager
def deterministic_stdlib() -> Iterator[None]:
    """Ensure the dispatchers are installed for the duration of a run.

    Installation is permanent and process-wide (dispatch is per-call), so
    this is effectively an install-on-first-use hook with a stable name at
    the runtime entry point."""
    install()
    yield
