"""Pluggable simulator framework.

Parity with reference madsim/src/sim/plugin.rs: a ``Simulator`` is a
per-runtime singleton registered on the Handle and keyed by its type
(plugin.rs:18-54, runtime/mod.rs:68-79); it receives node-lifecycle
callbacks so it can allocate per-node state on ``create_node`` and wipe it
on ``reset_node`` (= node kill / power failure). ``simulator(cls)`` looks
up the instance for the current runtime; ``node()`` returns the current
node id (plugin.rs:45-57).
"""

from __future__ import annotations

from typing import Type, TypeVar

__all__ = ["Simulator", "simulator", "node"]


class Simulator:
    """Base class for device simulators (NetSim, FsSim, user plugins).

    Constructed once per runtime with the runtime's rng/time/config plus
    the supervisor handle (the reference passes the Handle into
    ``Simulator::new``, plugin.rs:20-24)."""

    def __init__(self, rng, time, config, handle):
        self.rng = rng
        self.time = time
        self.config = config
        self.handle = handle

    def create_node(self, node_id: int) -> None:  # noqa: B027 - optional hook
        pass

    def reset_node(self, node_id: int) -> None:  # noqa: B027 - optional hook
        pass


S = TypeVar("S", bound=Simulator)


def simulator(cls: Type[S]) -> S:
    """The current runtime's instance of simulator type ``cls``."""
    from . import context

    return context.current_handle().simulator(cls)


def node() -> int:
    """Current node id (plugin.rs:57)."""
    from . import context

    return context.current_task().node.id
