"""Deterministic global RNG — every random decision in the simulator flows
through one seeded stream.

Parity with reference madsim/src/sim/rand.rs:
  * ``GlobalRng`` seeded from a u64 (rand.rs:30-61)
  * op-log + replay-check used by the determinism checker (rand.rs:64-110):
    in log mode every draw appends ``hash(value) ^ hash(now_ns)``; in check
    mode each draw is compared against the recorded log and the first
    divergence raises :class:`DeterminismError` naming the simulated time —
    the analog of rand.rs:77-85 "non-determinism detected".
  * free functions ``thread_rng()`` / ``random()`` resolve the RNG through
    the thread-local context (rand.rs:115-146).

The reference additionally interposes libc ``getrandom``/``getentropy``
(rand.rs:174-240) so *std* entropy is deterministic; our Python analog is
:mod:`madsim_tpu.runtime.intercept`, which patches :mod:`random`,
``os.urandom``, ``uuid`` and :mod:`time` while a simulation is entered.
"""

from __future__ import annotations

import random as _pyrandom
from typing import Callable, MutableSequence, Sequence, TypeVar

T = TypeVar("T")

__all__ = ["GlobalRng", "DeterminismError", "thread_rng", "random"]

_MASK64 = (1 << 64) - 1


class DeterminismError(RuntimeError):
    """Raised by the determinism checker when two same-seed runs diverge."""


class GlobalRng:
    """Single seeded RNG shared by the whole simulation run."""

    def __init__(self, seed: int):
        self.seed = seed
        self._rng = _pyrandom.Random(seed)
        self._log: list[int] | None = None
        self._check: list[int] | None = None
        self._check_pos = 0
        # Wired by TimeRuntime after construction; draws made before that
        # observe time 0 (ordering is still deterministic).
        self.now_ns: Callable[[], int] = lambda: 0

    # ---- determinism log / check (rand.rs:64-110) -----------------------
    def enable_log(self) -> None:
        self._log = []

    def take_log(self) -> list[int]:
        log, self._log = self._log, None
        assert log is not None, "enable_log was not called"
        return log

    def enable_check(self, log: list[int]) -> None:
        self._check = log
        self._check_pos = 0

    def _observe(self, value: object) -> None:
        if self._log is None and self._check is None:
            return
        t = self.now_ns()
        try:
            vh = hash(value)
        except TypeError:
            # Unhashable draw (e.g. random.choice over lists): fall back to
            # repr, which is deterministic within a process.
            vh = hash(repr(value))
        entry = (vh ^ hash(t)) & _MASK64
        if self._log is not None:
            self._log.append(entry)
        if self._check is not None:
            i = self._check_pos
            self._check_pos += 1
            if i >= len(self._check) or self._check[i] != entry:
                raise DeterminismError(
                    f"non-determinism detected at {t / 1e9:.9f}s "
                    f"(draw #{i}): the same seed produced a different "
                    f"random-op stream on replay"
                )

    # ---- draws ----------------------------------------------------------
    def randrange(self, lo: int, hi: int) -> int:
        """Uniform int in [lo, hi) — the analog of Rust gen_range(lo..hi)."""
        v = self._rng.randrange(lo, hi)
        self._observe(v)
        return v

    def random_float(self) -> float:
        v = self._rng.random()
        self._observe(v)
        return v

    def random_bool(self, p: float) -> bool:
        if p <= 0.0:
            return False
        if p >= 1.0:
            return True
        v = self._rng.random() < p
        self._observe(v)
        return v

    def randbytes(self, n: int) -> bytes:
        v = self._rng.randbytes(n)
        self._observe(v)
        return v

    def getrandbits(self, n: int) -> int:
        v = self._rng.getrandbits(n)
        self._observe(v)
        return v

    def gauss(self, mu: float = 0.0, sigma: float = 1.0) -> float:
        v = self._rng.gauss(mu, sigma)
        self._observe(v)
        return v

    def choice(self, seq: Sequence[T]) -> T:
        i = self.randrange(0, len(seq))
        return seq[i]

    def shuffle(self, seq: MutableSequence[T]) -> None:
        # Fisher-Yates through our observed randrange so shuffles are logged.
        for i in range(len(seq) - 1, 0, -1):
            j = self.randrange(0, i + 1)
            seq[i], seq[j] = seq[j], seq[i]


def thread_rng() -> GlobalRng:
    """The current simulation's RNG (reference rand.rs:115-137)."""
    from . import context

    return context.current_handle().rng


def random() -> float:
    """Uniform float in [0, 1) from the simulation RNG (rand.rs:139-146)."""
    return thread_rng().random_float()
