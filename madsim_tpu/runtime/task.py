"""Deterministic single-threaded task executor with chaos semantics.

Parity with reference madsim/src/sim/task.rs:
  * discrete-event hot loop: drain the ready queue in *random* order, poll
    each task, advance virtual time by a random 50-100 ns per poll, then
    jump the clock to the next timer event (task.rs:142-216, the loop in
    SURVEY §3.2).
  * nodes (simulated machines) own tasks; ``kill`` cancels every task on
    the node so their cleanup runs, bumps the node epoch, and resets each
    registered simulator's per-node state (task.rs:255-276).
  * ``restart`` = kill + re-run the node's stored init coroutine
    (task.rs:279-291); ``pause``/``resume`` stash and release ready tasks
    (task.rs:294-314).
  * a panicking task on a ``restart_on_panic`` node is caught and the node
    restarts after a random 1-10 s delay (task.rs:187-206); a panic in an
    un-awaited task anywhere else fails the whole simulation, matching the
    reference where the unwind propagates through ``block_on``.

The reference also interposes ``sched_getaffinity``/``sysconf``/
``pthread_attr_init`` and *forbids thread creation* inside a simulation
(task.rs:659-725); our analog lives in
:mod:`madsim_tpu.runtime.intercept` (thread-spawn guard + per-node
``available_parallelism``).
"""

from __future__ import annotations

import asyncio as _real_asyncio

from typing import Any, Callable, Coroutine, Optional

from . import aio, context
from .future import SimFuture
from .mpsc import RandomQueue
from .rand import GlobalRng
from .time_ import TimeRuntime

__all__ = [
    "Executor",
    "NodeInfo",
    "Task",
    "JoinHandle",
    "FallibleTask",
    "JoinError",
    "DeadlockError",
    "TimeLimitError",
    "spawn",
    "spawn_local",
]

MAIN_NODE_ID = 0


class JoinError(Exception):
    """Awaiting a killed/aborted/panicked task (task.rs:608-631).

    ``is_cancelled()``/``is_panic()`` mirror the reference's accessors:
    kill/abort produce a cancelled JoinError; a task that raised
    produces a panic one (with the original exception as __cause__)."""

    def __init__(self, msg: str, *, panic: bool = False):
        super().__init__(msg)
        self._panic = panic

    def is_panic(self) -> bool:
        return self._panic

    def is_cancelled(self) -> bool:
        return not self._panic


class DeadlockError(RuntimeError):
    """No runnable task and no pending timer (task.rs:164)."""


class TimeLimitError(RuntimeError):
    """Virtual time exceeded the configured limit (task.rs:165-171)."""


class NodeInfo:
    """Per-node bookkeeping. Killing a node retires this object and installs
    a fresh one under the same id — the epoch semantics of task.rs:255-276
    (stale tasks still point at the retired info and get dropped)."""

    __slots__ = (
        "id",
        "name",
        "ip",
        "cores",
        "init",
        "restart_on_panic",
        "killed",
        "paused",
        "paused_tasks",
        "tasks",
    )

    def __init__(
        self,
        node_id: int,
        name: str,
        init: Optional[Callable[[], Coroutine]] = None,
        restart_on_panic: bool = False,
        cores: int = 1,
        ip: Optional[str] = None,
    ):
        self.id = node_id
        self.name = name
        self.ip = ip
        self.cores = cores
        self.init = init
        self.restart_on_panic = restart_on_panic
        self.killed = False
        self.paused = False
        self.paused_tasks: list[Task] = []
        self.tasks: list[Task] = []

    def __repr__(self) -> str:
        return f"NodeInfo(id={self.id}, name={self.name!r})"


class Task:
    __slots__ = (
        "id",
        "coro",
        "node",
        "name",
        "_fut",
        "scheduled",
        "finished",
        "_close_pending",
        "_pending_throw",
        "_aio_shim",
        "_aio_bridge",
        "_aio_ctx",
    )

    def __init__(self, task_id: int, coro: Coroutine, node: NodeInfo, name: str):
        self.id = task_id
        self.coro = coro
        self.node = node
        self.name = name
        self._fut = SimFuture(name=f"join:{name}")
        self.scheduled = False
        self.finished = False
        self._close_pending = False
        # lazily-built asyncio.current_task() stand-in (runtime/aio.py)
        self._aio_shim = None
        # the asyncio.Future returned by a raw asyncio.create_task, if
        # this task was spawned that way — switches exception routing to
        # asyncio semantics (runtime/aio.py, _on_panic)
        self._aio_bridge = None
        # contextvars.Context every poll runs under, when the task was
        # created with asyncio.create_task(..., context=ctx)
        self._aio_ctx = None
        # exception injected at the task's next poll (the cancellation
        # mechanism behind compat asyncio.timeout(): the timer arms this
        # and reschedules the task, and the executor throws it into the
        # coroutine at its current await point)
        self._pending_throw: Optional[BaseException] = None

    def throw_soon(self, exc: BaseException) -> None:
        """Arrange for ``exc`` to be raised inside the coroutine at its
        current suspension point on the next poll. Caller must schedule
        the task."""
        self._pending_throw = exc

    def kill(self) -> None:
        """Cancel: close the coroutine (finally blocks run — the analog of
        dropping the future, task.rs:270-271) and fail the join future."""
        if self.finished:
            return
        self.finished = True
        try:
            self.coro.close()
        except (ValueError, RuntimeError):
            # A task killing itself (or its own node) mid-poll: the
            # coroutine is currently running and cannot be closed here.
            # The executor closes it at the task's next suspension point
            # so its finally-block cleanup still runs.
            self._close_pending = True
        self._fut.set_exception(JoinError(f"task {self.name!r} was killed"))

    def __repr__(self) -> str:
        return f"Task(id={self.id}, name={self.name!r}, node={self.node.id})"


class JoinHandle:
    """Handle to a spawned task (task.rs:569-609)."""

    __slots__ = ("_task",)

    def __init__(self, task: Task):
        self._task = task

    @property
    def _fut(self) -> SimFuture:
        return self._task._fut

    def __await__(self):
        return self._task._fut.__await__()

    def done(self) -> bool:
        return self._task.finished

    def abort(self) -> None:
        """Cancel the task (tokio-style abort; kill-drops-future semantics)."""
        self._task.kill()

    # tokio parity alias
    cancel = abort

    def cancel_on_drop(self) -> "FallibleTask":
        """Scope-bound task (the JoinHandle::cancel_on_drop analog,
        task.rs:581-607). Python has no deterministic drop, so the drop
        point is an ``async with`` scope exit::

            async with handle.cancel_on_drop() as h:
                ...            # task aborted here if still running
        """
        return FallibleTask(self)


class FallibleTask:
    """Async context manager aborting its task at scope exit if still
    running — the deterministic analog of the reference's drop-based
    cancellation (task.rs:581-616)."""

    __slots__ = ("_handle",)

    def __init__(self, handle: JoinHandle):
        self._handle = handle

    async def __aenter__(self) -> JoinHandle:
        return self._handle

    async def __aexit__(self, *_exc) -> None:
        if not self._handle.done():
            self._handle.abort()

    def __await__(self):
        return self._handle.__await__()


class Executor:
    """Single-threaded discrete-event executor (task.rs:33-216)."""

    def __init__(self, rng: GlobalRng, time: TimeRuntime):
        self.rng = rng
        self.time = time
        self.queue: RandomQueue[Task] = RandomQueue()
        self.nodes: dict[int, NodeInfo] = {}
        self.main_node = NodeInfo(MAIN_NODE_ID, "main")
        self.nodes[MAIN_NODE_ID] = self.main_node
        self._next_node_id = 1
        self._next_task_id = 1
        self.time_limit_ns: Optional[int] = None
        # list of Simulator instances, installed by Runtime; consulted on
        # node create/reset (runtime/mod.rs:68-79 sims registry).
        self.simulators: list = []
        self._pending_panic: Optional[BaseException] = None
        # raw-asyncio interposition (runtime/aio.py): installed in the
        # running-loop slot around every poll so unmodified asyncio code
        # runs on simulated time
        self.aio_loop = aio.SimEventLoop(self)

    # ---- spawning -------------------------------------------------------
    def spawn_on(self, node: NodeInfo, coro: Coroutine, name: str = "") -> JoinHandle:
        if node.killed:
            coro.close()
            raise RuntimeError(f"cannot spawn on killed node {node.id}")
        task = Task(self._next_task_id, coro, node, name or coro.__name__)
        self._next_task_id += 1
        node.tasks.append(task)
        self._schedule(task)
        return JoinHandle(task)

    def _schedule(self, task: Task) -> None:
        if not task.finished and not task.scheduled:
            task.scheduled = True
            self.queue.push(task)

    def _waker(self, task: Task) -> Callable[[], None]:
        return lambda: self._schedule(task)

    # ---- the hot loop ---------------------------------------------------
    def block_on(self, coro: Coroutine) -> Any:
        main = self.spawn_on(self.main_node, coro, "main")
        main_fut = main._fut
        while True:
            self.run_all_ready()
            if self._pending_panic is not None:
                exc, self._pending_panic = self._pending_panic, None
                raise exc
            if main_fut.done():
                self._report_unretrieved_aio()
                return main_fut.result()
            if not self.time.advance_to_next_event():
                raise DeadlockError(
                    "all tasks will block forever: no runnable task and no "
                    "pending timer event"
                )
            if self.time_limit_ns is not None and self.time.now_ns() > self.time_limit_ns:
                raise TimeLimitError(
                    f"time limit of {self.time_limit_ns / 1e9}s exceeded"
                )

    def run_all_ready(self) -> None:
        """Drain the ready queue in random order (task.rs:176-216)."""
        while True:
            task = self.queue.try_pop_random(self.rng)
            if task is None:
                return
            task.scheduled = False
            if task.finished:
                continue
            node = task.node
            if node.killed:
                task.kill()
                continue
            if node.paused:
                node.paused_tasks.append(task)
                continue
            self._poll(task)
            # Each poll costs a random 50-100 ns of virtual time
            # (task.rs:213-214).
            self.time.advance(self.rng.randrange(50, 100))

    def _poll(self, task: Task) -> None:
        try:
            with context.enter_task(task):
                prev_loop = aio.enter_poll(self.aio_loop, task)
                try:
                    if task._pending_throw is not None:
                        exc_in, task._pending_throw = task._pending_throw, None
                        if task._aio_ctx is not None:
                            yielded = task._aio_ctx.run(task.coro.throw, exc_in)
                        else:
                            yielded = task.coro.throw(exc_in)
                    elif task._aio_ctx is not None:
                        # asyncio.Task parity: every poll runs under the
                        # task's contextvars Context (create_task context=)
                        yielded = task._aio_ctx.run(task.coro.send, None)
                    else:
                        yielded = task.coro.send(None)
                finally:
                    aio.exit_poll(self.aio_loop, task, prev_loop)
        except StopIteration as stop:
            task.finished = True
            task._fut.set_result(stop.value)
        except BaseException as exc:  # noqa: BLE001 - panic path
            self._on_panic(task, exc)
        else:
            if task._close_pending:
                # The task was killed during its own poll (self-kill); now
                # that it is suspended, drop it so finally blocks run.
                task._close_pending = False
                try:
                    task.coro.close()
                except RuntimeError:
                    pass
                return
            if task.node.killed:
                task.kill()
            elif isinstance(yielded, SimFuture):
                yielded.add_waker(self._waker(task))
            elif yielded is None:
                # a bare `yield` — asyncio.sleep(0)'s __sleep0 / yield-now:
                # hand the scheduler one turn, resume on a later drain
                self._schedule(task)
            elif aio.is_asyncio_future(yielded):
                # raw asyncio await (stdlib Future/Queue/Event/...): the
                # executor side of the asyncio await protocol — resume the
                # task when the future resolves (runtime/aio.py)
                aio.bridge_asyncio_future(yielded, self._waker(task))
            else:
                task.finished = True
                err = TypeError(
                    f"task {task.name!r} awaited a non-simulation awaitable "
                    f"({type(yielded).__name__}); only madsim_tpu futures "
                    f"and asyncio awaitables can be awaited inside the "
                    f"simulator"
                )
                self._pending_panic = err
                return

    def _report_unretrieved_aio(self) -> None:
        """End-of-sim debugging aid: a raw ``asyncio.create_task`` task
        that died with an exception nobody awaited would otherwise be
        perfectly silent (asyncio semantics store it in the future; the
        GC-time "never retrieved" hook is deliberately a no-op because
        GC timing is nondeterministic). The END of the simulation IS a
        deterministic point, so report each one on stderr here —
        iteration order (node id, task creation order) is seeded-stable."""
        import sys as _sys

        for node_id in sorted(self.nodes):
            for task in self.nodes[node_id].tasks:
                fut = task._aio_bridge
                if (
                    fut is not None
                    and fut.done()
                    and not fut.cancelled()
                    # flag FIRST: .exception() clears _log_traceback
                    and getattr(fut, "_log_traceback", False)
                    and fut.exception() is not None
                ):
                    print(
                        f"note: asyncio task {task.name!r} (node {node_id}) "
                        f"died with an unretrieved exception: "
                        f"{fut.exception()!r}",
                        file=_sys.stderr,
                    )

    def _on_panic(self, task: Task, exc: BaseException) -> None:
        task.finished = True
        node = task.node
        if isinstance(exc, _real_asyncio.CancelledError):
            # asyncio-style cancellation ends ONLY the cancelled task —
            # the analog of tokio JoinHandle::abort (task.rs:611), which
            # does not panic the runtime. (Uncaught real exceptions still
            # fail the whole simulation below.)
            je = JoinError(f"task {task.name!r} was cancelled")
            je.__cause__ = exc
            task._fut.set_exception(je)
            return
        if node.restart_on_panic and node.id != MAIN_NODE_ID:
            # Kill the node *immediately* (sibling tasks stop, simulator
            # per-node state resets), then restart after a random 1-10 s
            # delay (task.rs:187-206, runtime/mod.rs:319-325).
            delay_ns = self.rng.randrange(1_000_000_000, 10_000_000_000)
            node_id = node.id
            je = JoinError(f"task {task.name!r} panicked: {exc!r}", panic=True)
            je.__cause__ = exc
            task._fut.set_exception(je)
            self.kill_node(node_id)
            self.time.add_timer_at(
                self.time.now_ns() + delay_ns,
                lambda: self.restart_node(node_id),
            )
            return
        if task._aio_bridge is not None:
            # the task was created via RAW asyncio.create_task: asyncio
            # exception semantics — the exception is stored for the
            # awaiter (gather/await/return_exceptions all behave as in
            # real asyncio) instead of failing the whole simulation
            je = JoinError(f"task {task.name!r} raised", panic=True)
            je.__cause__ = exc
            task._fut.set_exception(je)
            return
        # A panic in any other task fails the whole simulation, exactly like
        # the reference where the unwind propagates through block_on. (To
        # handle expected errors, return them as values from the task.)
        # This is deliberately independent of whether anyone is awaiting the
        # JoinHandle — error routing must not depend on scheduling order.
        je = JoinError(f"task {task.name!r} panicked", panic=True)
        je.__cause__ = exc
        task._fut.set_exception(je)
        self._pending_panic = exc

    # ---- node lifecycle (task.rs:255-332) -------------------------------
    def create_node(
        self,
        name: Optional[str] = None,
        init: Optional[Callable[[], Coroutine]] = None,
        restart_on_panic: bool = False,
        cores: int = 1,
        ip: Optional[str] = None,
    ) -> NodeInfo:
        node_id = self._next_node_id
        self._next_node_id += 1
        info = NodeInfo(node_id, name or f"node-{node_id}", init, restart_on_panic, cores, ip)
        self.nodes[node_id] = info
        for sim in self.simulators:
            sim.create_node(node_id)
        return info

    def _retire(self, info: NodeInfo) -> NodeInfo:
        info.killed = True
        for t in list(info.tasks):
            t.kill()
        info.tasks.clear()
        info.paused_tasks.clear()
        fresh = NodeInfo(
            info.id, info.name, info.init, info.restart_on_panic, info.cores, info.ip
        )
        self.nodes[info.id] = fresh
        for sim in self.simulators:
            sim.reset_node(info.id)
        return fresh

    def kill_node(self, node_id: int) -> None:
        if node_id == MAIN_NODE_ID:
            raise ValueError("cannot kill the main node")
        self._retire(self.nodes[node_id])

    def restart_node(self, node_id: int) -> None:
        if node_id == MAIN_NODE_ID:
            raise ValueError("cannot restart the main node")
        fresh = self._retire(self.nodes[node_id])
        if fresh.init is not None:
            self.spawn_on(fresh, fresh.init(), name=f"init:{fresh.name}")

    def pause_node(self, node_id: int) -> None:
        if node_id == MAIN_NODE_ID:
            raise ValueError("cannot pause the main node")
        self.nodes[node_id].paused = True

    def resume_node(self, node_id: int) -> None:
        info = self.nodes[node_id]
        info.paused = False
        for t in info.paused_tasks:
            self._schedule(t)
        info.paused_tasks.clear()


# ---- free functions -----------------------------------------------------


def spawn(coro: Coroutine, name: str = "") -> JoinHandle:
    """Spawn a task on the current node (task.rs:480-488)."""
    handle = context.current_handle()
    cur = context.try_current_task()
    node = cur.node if cur is not None else handle.executor.main_node
    return handle.executor.spawn_on(node, coro, name)


def spawn_local(coro: Coroutine, name: str = "") -> JoinHandle:
    """Alias of :func:`spawn` — the whole simulation is single-threaded
    (task.rs:490-497)."""
    return spawn(coro, name)


def spawn_blocking(f: Callable[[], Any], name: str = "") -> JoinHandle:
    """Run a sync closure in a task (task.rs:498-511). The reference
    deprecates this in simulation — real blocking would stall virtual
    time — so like it, the closure simply runs inline on the task."""

    async def runner():
        return f()

    return spawn(runner(), name or "spawn_blocking")


def yield_now() -> "SimFuture":
    """Cooperative yield: reschedule after other ready tasks/timers at
    the current instant (the tokio ``task::yield_now`` re-exported by
    the sim, madsim-tokio/src/lib.rs:25-27). Implemented as a zero
    sleep — a timer at *now* fires without advancing the clock."""
    return context.current_handle().time.sleep(0.0)
