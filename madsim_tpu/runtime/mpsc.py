"""Random-pick ready queue — the source of schedule randomization.

Parity with reference madsim/src/sim/utils/mpsc.rs: the executor's ready
queue is drained by popping a *uniformly random* element via swap-remove
(mpsc.rs:73-83), so every run explores a different task interleaving and
the interleaving is fully determined by the seed.
"""

from __future__ import annotations

from typing import Generic, TypeVar

from .rand import GlobalRng

T = TypeVar("T")

__all__ = ["RandomQueue"]


class RandomQueue(Generic[T]):
    __slots__ = ("_items",)

    def __init__(self) -> None:
        self._items: list[T] = []

    def push(self, item: T) -> None:
        self._items.append(item)

    def try_pop_random(self, rng: GlobalRng) -> T | None:
        """Pop a uniformly random element (swap-remove; mpsc.rs:73-83)."""
        items = self._items
        n = len(items)
        if n == 0:
            return None
        i = rng.randrange(0, n) if n > 1 else 0
        items[i], items[-1] = items[-1], items[i]
        return items.pop()

    def __len__(self) -> int:
        return len(self._items)

    def clear(self) -> None:
        self._items.clear()
