"""Raw-asyncio interposition: unmodified ``import asyncio`` code runs
deterministically inside the simulator.

The reference achieves "user code unchanged" by swapping tokio for the
simulator at build time (``--cfg madsim``; madsim-tokio re-exports the
sim, madsim-tokio/src/lib.rs:4-52). Python has no build-time cfg swap,
and the compat shim (:mod:`madsim_tpu.compat.asyncio`) still requires
changing an import. This module closes the remaining gap at the
*event-loop seam* instead: while the executor polls a simulated task,
asyncio's thread-local running-loop slot (``_set_running_loop`` — the
same slot ``asyncio.run`` uses) points at a :class:`SimEventLoop`
whose ``call_soon``/``call_later``/``call_at``/``create_future``/
``create_task`` are backed by the deterministic executor and the
virtual clock. The stdlib's OWN pure-Python machinery — ``sleep``,
``Future``, ``Queue``, ``Event``, ``Lock``, ``Semaphore``,
``Condition``, ``gather``, ``timeout``, ``wait_for``, ``wait``,
``shield`` — then runs unmodified on simulated time with seeded
scheduling. ``asyncio.current_task()`` works through the documented
``_enter_task`` registration hook with a :class:`_TaskShim` carrying
tokio-abort-style cancellation (``cancel`` delivers ``CancelledError``
at the task's await point; ``cancelling``/``uncancel`` implement the
3.11+ cancellation-count protocol that ``asyncio.timeout`` relies on).

Semantics notes (parity choices, not accidents):
* Exception routing follows the API the user chose. A task spawned
  through the runtime's own surface (``spawn``/compat) keeps madsim
  semantics: an uncaught exception fails the whole simulation (the
  reference's unwind-through-``block_on``, task.rs:187-206). A task
  created via RAW ``asyncio.create_task`` gets asyncio semantics: the
  exception is stored in the returned future for its awaiter —
  ``gather(return_exceptions=True)`` and awaited-task propagation work
  exactly as in real asyncio. ``CancelledError`` ends only the
  cancelled task in both worlds (tokio ``JoinHandle::abort`` parity).
* ``cancel()`` on a raw task REQUESTS cancellation (CancelledError at
  the task's await point); a task that legally suppresses it still
  completes with its result, as in real asyncio.
* ``call_soon`` callbacks run when the executor next drains timers,
  in deterministic FIFO order per timestamp — reproducible, though not
  interleaved identically to a real asyncio loop (which no seeded
  scheduler is).
* Out-of-simulation asyncio is untouched: the running-loop slot is set
  only around simulated-task polls, so the std backends' real loops
  (std/net.py) are unaffected.
"""

from __future__ import annotations

import asyncio as _aio
import contextvars
from typing import Any, Callable, Coroutine, Optional

from . import context

__all__ = ["SimEventLoop", "enter_poll", "exit_poll", "bridge_asyncio_future"]

_enter_task = getattr(_aio.tasks, "_enter_task", None)
_leave_task = getattr(_aio.tasks, "_leave_task", None)
_set_running_loop = _aio.events._set_running_loop


class _SimHandle:
    """asyncio.Handle stand-in for callbacks scheduled on the sim clock."""

    __slots__ = ("_cb", "_args", "_context", "_cancelled")

    def __init__(self, cb, args, ctx):
        self._cb = cb
        self._args = args
        self._context = ctx
        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True

    def cancelled(self) -> bool:
        return self._cancelled

    def _run(self) -> None:
        if self._cancelled:
            return
        if self._context is not None:
            self._context.run(self._cb, *self._args)
        else:
            self._cb(*self._args)


class _SimTimerHandle(_SimHandle):
    __slots__ = ("_when",)

    def __init__(self, when, cb, args, ctx):
        super().__init__(cb, args, ctx)
        self._when = when

    def when(self) -> float:
        return self._when


class _TaskShim:
    """What ``asyncio.current_task()`` returns inside the sim.

    Carries exactly the surface the stdlib's task-facing helpers use:
    the 3.11+ cancellation-count protocol (``asyncio.timeout``'s
    ``cancelling``/``uncancel`` accounting), ``get_loop`` (used by
    ``Timeout._reschedule``), and name/done introspection.
    ``cancel`` is the asyncio cancel: ``CancelledError`` is thrown into
    the coroutine at its current await point (the executor's
    ``throw_soon`` seam, the same mechanism compat.asyncio.timeout
    uses).
    """

    __slots__ = ("_task", "_loop", "_cancel_requests")

    def __init__(self, task, loop):
        self._task = task
        self._loop = loop
        self._cancel_requests = 0

    def get_loop(self):
        return self._loop

    def get_name(self) -> str:
        return self._task.name

    def done(self) -> bool:
        return self._task.finished

    def cancelled(self) -> bool:
        return False

    def cancel(self, msg: Optional[str] = None) -> bool:
        if self._task.finished:
            return False
        self._cancel_requests += 1
        exc = _aio.CancelledError() if msg is None else _aio.CancelledError(msg)
        self._task.throw_soon(exc)
        self._loop._executor._schedule(self._task)
        return True

    def cancelling(self) -> int:
        return self._cancel_requests

    def uncancel(self) -> int:
        if self._cancel_requests > 0:
            self._cancel_requests -= 1
        return self._cancel_requests


class SimEventLoop:
    """The deterministic loop object behind ``asyncio.get_running_loop()``
    inside a simulation. Not a real event loop — it never runs a loop of
    its own; it only translates the loop surface the stdlib primitives
    use onto the executor (ready queue) and TimeRuntime (timer heap)."""

    def __init__(self, executor):
        self._executor = executor

    # -- introspection the stdlib consults --------------------------------
    def get_debug(self) -> bool:
        return False

    def is_running(self) -> bool:
        return True

    def is_closed(self) -> bool:
        return False

    def time(self) -> float:
        return self._executor.time.now_ns() / 1e9

    # -- callback scheduling ----------------------------------------------
    def call_soon(self, callback, *args, context=None):
        h = _SimHandle(callback, args, context)
        t = self._executor.time
        t.add_timer_at(t.now_ns(), h._run)
        return h

    def call_later(self, delay, callback, *args, context=None):
        return self.call_at(self.time() + delay, callback, *args, context=context)

    def call_at(self, when, callback, *args, context=None):
        h = _SimTimerHandle(when, callback, args, context)
        self._executor.time.add_timer_at(round(when * 1e9), h._run)
        return h

    # -- futures & tasks ---------------------------------------------------
    def create_future(self) -> _aio.Future:
        return _aio.Future(loop=self)

    class _BridgeFuture(_aio.Future):
        """The object ``asyncio.create_task`` returns in a sim: a Future
        bridged to the sim task, plus the name surface the stdlib's
        ``_set_task_name`` hook expects (it silently skips objects
        without ``set_name``, which would drop user task names)."""

        _sim_task = None

        def set_name(self, name) -> None:
            if self._sim_task is not None and name is not None:
                self._sim_task.name = str(name)

        def get_name(self) -> str:
            return self._sim_task.name if self._sim_task is not None else ""

        def cancel(self, msg: Optional[str] = None) -> bool:
            # asyncio.Task.cancel contract: REQUEST cancellation — the
            # CancelledError is delivered at the task's await point, and
            # a task that legally suppresses it still completes with its
            # result (the future settles from the task outcome, via
            # on_sim_done). Plain Future.cancel would settle NOW and
            # discard a suppressed-cancel result.
            if self.done():
                return False
            task = self._sim_task
            if task is None or task.finished:
                return super().cancel(msg)
            exc = (
                _aio.CancelledError()
                if msg is None
                else _aio.CancelledError(msg)
            )
            task.throw_soon(exc)
            self.get_loop()._executor._schedule(task)
            return True

        def _settle_cancelled(self) -> None:
            if not self.done():
                super(SimEventLoop._BridgeFuture, self).cancel()

    def create_task(self, coro: Coroutine, *, name=None, context=None):
        """Spawn on the current node; return an ``asyncio.Future`` bridged
        to the sim task's join future. ``fut.cancel()`` requests
        cancellation asyncio-style (CancelledError at the task's await
        point; a suppressed cancel still yields the task's result)."""
        ex = self._executor
        cur = context_try_current()
        node = cur.node if cur is not None else ex.main_node
        handle = ex.spawn_on(
            node, coro, name or getattr(coro, "__name__", "aio-task")
        )
        task = handle._task
        # asyncio.Task parity: every poll runs under the task's Context —
        # the supplied one, or (as asyncio.Task does) a COPY of the
        # current context, so a child's contextvar mutations never leak
        # into the parent or siblings (the executor's _poll honors
        # _aio_ctx)
        task._aio_ctx = (
            context if context is not None else contextvars.copy_context()
        )
        fut = SimEventLoop._BridgeFuture(loop=self)
        fut._sim_task = task
        task._aio_bridge = fut
        sim_fut = handle._fut

        def on_sim_done() -> None:
            if fut.done():
                return
            exc = sim_fut.exception()
            if exc is None:
                fut.set_result(sim_fut._result)
            else:
                cause = exc.__cause__
                if isinstance(exc, _aio.CancelledError) or isinstance(
                    cause, _aio.CancelledError
                ):
                    fut._settle_cancelled()
                else:
                    fut.set_exception(cause if cause is not None else exc)

        sim_fut.add_waker(on_sim_done)
        return fut

    # -- network (asyncio.open_connection / start_server) ------------------
    async def create_connection(self, protocol_factory, host=None, port=None,
                                *, ssl=None, **kwargs):
        """Backs raw ``asyncio.open_connection`` with the simulated TCP
        (net/aio_streams.py adapts TcpStream to the Transport contract;
        lazy import — runtime must not import net at module load)."""
        if ssl is not None:
            raise NotImplementedError("ssl is not simulated")
        from ..net import aio_streams

        return await aio_streams.create_connection(
            self, protocol_factory, host, port, **kwargs
        )

    async def create_server(self, protocol_factory, host=None, port=None,
                            *, ssl=None, **kwargs):
        """Backs raw ``asyncio.start_server`` with the simulated TCP."""
        if ssl is not None:
            raise NotImplementedError("ssl is not simulated")
        from ..net import aio_streams

        return await aio_streams.create_server(
            self, protocol_factory, host, port, **kwargs
        )

    async def create_datagram_endpoint(self, protocol_factory,
                                       local_addr=None, remote_addr=None,
                                       **kwargs):
        """Backs raw datagram protocols with the simulated UDP."""
        from ..net import aio_streams

        return await aio_streams.create_datagram_endpoint(
            self, protocol_factory, local_addr, remote_addr, **kwargs
        )

    async def getaddrinfo(self, host, port, *, family=0, type=0, proto=0,
                          flags=0):
        """Deterministic resolver (net/addr.py lookup_host — simulated
        node names resolve; no real DNS), in getaddrinfo result shape."""
        import socket as _socket

        from ..net.addr import lookup_host

        # host=None is the stdlib idiom for the wildcard address
        return [
            (_socket.AF_INET, type or _socket.SOCK_STREAM, proto, "", a)
            for a in await lookup_host(
                ("" if host is None else host, port if port else 0)
            )
        ]

    def run_in_executor(self, executor, func, *args):
        """Simulated ``run_in_executor``: real worker threads are
        forbidden inside a sim (the thread-spawn guard, intercept.py),
        so the callable runs synchronously at the current virtual
        instant — any ``time.sleep`` it performs advances the virtual
        clock via the interposed stdlib. This also powers
        ``asyncio.to_thread``. Only the default executor (None) is
        meaningful; a custom executor object is accepted and ignored
        (there is exactly one simulated "thread")."""
        fut = self.create_future()
        try:
            fut.set_result(func(*args))
        except BaseException as exc:  # noqa: BLE001 - mirror real executor
            fut.set_exception(exc)
        return fut

    # -- misc hooks stdlib code may touch ----------------------------------
    def call_exception_handler(self, ctx: dict) -> None:
        # called mostly from Future.__del__ ("exception was never
        # retrieved") at GC time. It must be a no-op: GC timing is
        # nondeterministic, and a real task exception already failed the
        # whole simulation loudly through the executor's panic path —
        # anything raised here would be swallowed as an unraisable.
        pass

    def default_exception_handler(self, ctx: dict) -> None:  # pragma: no cover
        self.call_exception_handler(ctx)


def context_try_current():
    return context.try_current_task()


def enter_poll(loop: SimEventLoop, task):
    """Executor hot-path hook, called before every coroutine poll:
    install the sim loop in asyncio's running-loop slot and register
    the task shim for ``asyncio.current_task()``. Returns the previous
    slot value for :func:`exit_poll` — save + restore rather than
    reset-to-None, because a simulation run synchronously from inside a
    REAL asyncio coroutine must not clobber the outer loop's slot.
    Plain functions (no context-manager allocation): this runs once per
    poll of every task in every sim."""
    shim = task._aio_shim
    if shim is None:
        shim = _TaskShim(task, loop)
        task._aio_shim = shim
    prev = _aio.events._get_running_loop()
    _set_running_loop(loop)
    if _enter_task is not None:
        _enter_task(loop, shim)
    return prev


def exit_poll(loop: SimEventLoop, task, prev) -> None:
    if _leave_task is not None:
        try:
            _leave_task(loop, task._aio_shim)
        except RuntimeError:  # pragma: no cover - mismatched nesting
            pass
    _set_running_loop(prev)


def deterministic_as_completed(fs, *, timeout: Optional[float] = None):
    """Replacement for ``asyncio.as_completed`` inside simulations.

    CPython's implementation dedups the inputs through ``set(fs)`` and
    spawns them while iterating that set — i.e. in MEMORY-ADDRESS
    order, which consumes scheduling RNG in a different order on every
    replay. The determinism checker (MADSIM_TEST_CHECK_DETERMINISM)
    caught this as a genuine op-stream divergence, so the interposition
    layer (runtime/intercept.py) swaps in this version during sims:
    identical semantics — dedup by identity, completion-ordered
    awaitables, TimeoutError after ``timeout`` — but tasks spawn in
    INPUT order.
    """
    loop = _aio.events.get_running_loop()
    seen: set = set()
    todo: list = []
    for f in fs:
        # identity-dedup replicates set(fs) EQUALITY semantics; the
        # address value never orders anything (spawn stays input-order)
        if id(f) in seen:  # lint: allow(id-hash-branch)
            continue
        seen.add(id(f))
        todo.append(_aio.ensure_future(f, loop=loop))
    done: _aio.Queue = _aio.Queue()
    timeout_handle = None

    def _on_timeout():
        for f in todo:
            f.remove_done_callback(_on_completion)
            done.put_nowait(None)  # wake every waiter with TimeoutError
        todo.clear()

    def _on_completion(f):
        if not todo:
            return  # timeout already fired
        todo.remove(f)
        done.put_nowait(f)
        if timeout_handle is not None and not todo:
            timeout_handle.cancel()

    async def _wait_for_one():
        f = await done.get()
        if f is None:
            raise TimeoutError
        return f.result()

    for f in todo:
        f.add_done_callback(_on_completion)
    if todo and timeout is not None:
        timeout_handle = loop.call_later(timeout, _on_timeout)
    for _ in range(len(todo)):
        yield _wait_for_one()


def is_asyncio_future(obj: Any) -> bool:
    """The ``isfuture`` protocol check (asyncio.futures.isfuture):
    anything with ``_asyncio_future_blocking`` is awaited the asyncio
    way — yield the future itself, resume when done."""
    return getattr(obj, "_asyncio_future_blocking", None) is not None


def bridge_asyncio_future(fut: Any, waker: Callable[[], None]) -> None:
    """Register ``waker`` to run when the yielded asyncio future
    resolves — the executor-side half of the await protocol (what a
    real asyncio.Task.__step does with a yielded future)."""
    fut._asyncio_future_blocking = False
    fut.add_done_callback(lambda _f: waker())
