"""Tracing: structured, simulation-aware logging.

Parity with the reference's tracing integration (SURVEY.md §5): the
reference threads ``tracing`` spans through everything — a per-node span
(task.rs:119,266,327), a per-task span entered on every poll
(runtime/context.rs:58-69), ``#[instrument]`` on network ops, and a
subscriber initialized once by the test macro (runtime/mod.rs:385-389).

Here the same context comes from a logging.Filter that stamps every
record emitted inside a simulation with the *virtual* time, the current
node and task, and the seed — so interleaved multi-node logs read like
the reference's span-annotated output and, because time is simulated,
two same-seed runs produce byte-identical logs (useful with the
determinism checker).

    import madsim_tpu as ms
    ms.init_logger()                # or MADSIM_LOG=debug via @ms.test
    log = logging.getLogger("myapp")
    log.info("leader elected")      # -> [12.304986s node=2(srv) task=elect seed=7] leader elected

``span(name)`` pushes a nested context segment (the #[instrument]
analog) onto the current task's span stack.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
from typing import Iterator, Optional

from . import context

__all__ = ["init_logger", "span", "SimContextFilter", "SimFormatter"]

# span stacks are per (handle, task) — stored on the TaskInfo via a
# plain attribute dict keyed by task id to avoid touching __slots__
_SPANS: dict[int, list[str]] = {}


class SimContextFilter(logging.Filter):
    """Stamp records with simulated time / node / task / seed."""

    def filter(self, record: logging.LogRecord) -> bool:
        handle = context.try_current_handle()
        if handle is None:
            record.sim = ""
            return True
        parts = [f"{handle.time.now_ns() / 1e9:.9f}s"]
        task = context.try_current_task()
        if task is not None:
            node = task.node
            name = f"({node.name})" if node.name else ""
            parts.append(f"node={node.id}{name}")
            parts.append(f"task={task.name}")
            spans = _SPANS.get(task.id)
            if spans:
                parts.append(":".join(spans))
        parts.append(f"seed={handle.seed}")
        record.sim = "[" + " ".join(parts) + "] "
        return True


class SimFormatter(logging.Formatter):
    def __init__(self) -> None:
        super().__init__("%(levelname).1s %(sim)s%(name)s: %(message)s")


_installed: Optional[logging.Handler] = None


def init_logger(level: "str | int | None" = None) -> None:
    """Install the simulation-aware log handler once (the analog of the
    test macro's subscriber init, runtime/mod.rs:385-389).

    Level comes from the argument or ``MADSIM_LOG`` (error/warn/info/
    debug/trace, default warn — mirroring RUST_LOG-style env control).
    """
    global _installed
    if _installed is not None:
        return
    if level is None:
        level = os.environ.get("MADSIM_LOG", "warning")
    if isinstance(level, str):
        level = {
            "error": logging.ERROR,
            "warn": logging.WARNING,
            "warning": logging.WARNING,
            "info": logging.INFO,
            "debug": logging.DEBUG,
            "trace": logging.DEBUG,
        }.get(level.lower(), logging.WARNING)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(SimFormatter())
    handler.addFilter(SimContextFilter())
    root = logging.getLogger()
    root.addHandler(handler)
    if root.level > level or root.level == logging.NOTSET:
        root.setLevel(level)
    _installed = handler


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Push a named span segment for the current task (#[instrument]
    analog): log records inside the block carry task=...:name."""
    task = context.try_current_task()
    if task is None:
        yield
        return
    stack = _SPANS.setdefault(task.id, [])
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()
        if not stack:
            _SPANS.pop(task.id, None)
