"""Runtime, Handle, NodeBuilder, NodeHandle — the supervisor API.

Parity with reference madsim/src/sim/runtime/mod.rs:
  * ``Runtime`` owns GlobalRng + TimeRuntime + Executor and registers the
    default device simulators (FsSim, NetSim) (mod.rs:31-79).
  * ``Runtime.block_on`` enters the context and drives the executor
    (mod.rs:122-125); ``set_time_limit`` (mod.rs:143) bounds virtual time.
  * ``check_determinism`` runs the workload twice with the RNG op-log
    (mod.rs:165-190 + rand.rs:64-110) and raises on the first divergence.
  * ``Handle`` is the cloneable supervisor: seed accessor, kill / restart /
    pause / resume (mod.rs:204-263), node creation.
  * ``NodeBuilder`` configures name/ip/cores/init/restart_on_panic
    (mod.rs:277-360); ``NodeHandle.spawn`` runs tasks on that simulated
    machine (mod.rs:364-383).
"""

from __future__ import annotations

from typing import Any, Callable, Coroutine, Optional, Type, TypeVar

from . import context
from .config import Config
from .plugin import Simulator
from .rand import GlobalRng
from .task import Executor, JoinHandle, NodeInfo
from .time_ import TimeHandle, TimeRuntime

__all__ = ["Runtime", "Handle", "NodeBuilder", "NodeHandle", "DEFAULT_SIMULATORS"]

S = TypeVar("S", bound=Simulator)

# Simulator classes auto-registered on every new Runtime, in registration
# order. The net/fs modules append to this at import time — the analog of
# the reference registering FsSim and NetSim by default
# (runtime/mod.rs:62-64).
DEFAULT_SIMULATORS: list[Type[Simulator]] = []


class Handle:
    """Supervisor handle to a running simulation (mod.rs:204-275)."""

    def __init__(self, runtime: "Runtime"):
        self._runtime = runtime
        self.sims: dict[Type[Simulator], Simulator] = {}

    # -- accessors --------------------------------------------------------
    @property
    def seed(self) -> int:
        return self._runtime.seed

    @property
    def rng(self) -> GlobalRng:
        return self._runtime.rng

    @property
    def time(self) -> TimeHandle:
        return self._runtime.time

    @property
    def config(self) -> Config:
        return self._runtime.config

    @property
    def executor(self) -> Executor:
        return self._runtime.executor

    @staticmethod
    def current() -> "Handle":
        return context.current_handle()

    def simulator(self, cls: Type[S]) -> S:
        return self.sims[cls]  # type: ignore[return-value]

    # -- chaos API (mod.rs:242-263) --------------------------------------
    def _node_id(self, node: "int | str | NodeHandle") -> int:
        """Resolve a node id, handle, or name — the ToNodeId analog
        (task.rs:366-397; unknown names raise like the reference's
        panic)."""
        if isinstance(node, NodeHandle):
            return node.id
        if isinstance(node, str):
            for nid, info in self.executor.nodes.items():
                if info.name == node:
                    return nid
            raise LookupError(f"node not found: {node}")
        return node

    def get_node(self, node: "int | str | NodeHandle") -> "Optional[NodeHandle]":
        """Look up a live node by id/name/handle (mod.rs:271-273)."""
        try:
            nid = self._node_id(node)
        except LookupError:
            return None
        if nid not in self.executor.nodes:
            return None
        return NodeHandle(nid, self)

    def kill(self, node: "int | str | NodeHandle") -> None:
        self.executor.kill_node(self._node_id(node))

    def restart(self, node: "int | str | NodeHandle") -> None:
        self.executor.restart_node(self._node_id(node))

    def pause(self, node: "int | str | NodeHandle") -> None:
        self.executor.pause_node(self._node_id(node))

    def resume(self, node: "int | str | NodeHandle") -> None:
        self.executor.resume_node(self._node_id(node))

    def set_clock_skew(self, node: "int | str | NodeHandle", skew_ns: int) -> None:
        """Chaos: skew the node's wall clock — SystemTime.now() on that
        node reads true time + skew_ns (madsim_tpu.chaos KIND_SKEW)."""
        self.time.set_skew(self._node_id(node), skew_ns)

    def create_node(self) -> "NodeBuilder":
        return NodeBuilder(self)


class NodeHandle:
    """Handle to one simulated machine (mod.rs:364-383)."""

    __slots__ = ("id", "_handle")

    def __init__(self, node_id: int, handle: Handle):
        self.id = node_id
        self._handle = handle

    @property
    def _info(self) -> NodeInfo:
        return self._handle.executor.nodes[self.id]

    @property
    def name(self) -> str:
        return self._info.name

    @property
    def ip(self) -> Optional[str]:
        return self._info.ip

    def spawn(self, coro: Coroutine, name: str = "") -> JoinHandle:
        return self._handle.executor.spawn_on(self._info, coro, name)

    def __repr__(self) -> str:
        return f"NodeHandle(id={self.id}, name={self.name!r})"


class NodeBuilder:
    """Builder for a simulated machine (mod.rs:277-360)."""

    def __init__(self, handle: Handle):
        self._handle = handle
        self._name: Optional[str] = None
        self._ip: Optional[str] = None
        self._cores: int = 1
        self._init: Optional[Callable[[], Coroutine]] = None
        self._restart_on_panic = False

    def name(self, name: str) -> "NodeBuilder":
        self._name = name
        return self

    def ip(self, ip: str) -> "NodeBuilder":
        self._ip = ip
        return self

    def cores(self, cores: int) -> "NodeBuilder":
        if cores < 1:
            raise ValueError("cores must be >= 1")
        self._cores = cores
        return self

    def init(self, factory: Callable[[], Coroutine]) -> "NodeBuilder":
        """Store an init-task factory, re-run on every (re)start
        (mod.rs:307-318). Must be a zero-arg callable returning a fresh
        coroutine (a coroutine object itself is single-use)."""
        if not callable(factory):
            raise TypeError("init expects a zero-arg callable returning a coroutine")
        self._init = factory
        return self

    def restart_on_panic(self, flag: bool = True) -> "NodeBuilder":
        self._restart_on_panic = flag
        return self

    def build(self) -> NodeHandle:
        ex = self._handle.executor
        info = ex.create_node(
            name=self._name,
            init=self._init,
            restart_on_panic=self._restart_on_panic,
            cores=self._cores,
            ip=self._ip,
        )
        if info.init is not None:
            ex.spawn_on(info, info.init(), name=f"init:{info.name}")
        return NodeHandle(info.id, self._handle)


class Runtime:
    """A deterministic simulation runtime for one seed (mod.rs:31-200)."""

    def __init__(self, seed: int = 0, config: Optional[Config] = None):
        self.seed = seed
        self.config = config or Config()
        self.rng = GlobalRng(seed)
        self._time_rt = TimeRuntime(self.rng)
        self.time = TimeHandle(self._time_rt)
        self.executor = Executor(self.rng, self._time_rt)
        self.handle = Handle(self)
        for cls in DEFAULT_SIMULATORS:
            self.add_simulator(cls)

    def add_simulator(self, cls: Type[S]) -> S:
        """Register a device simulator (mod.rs:68-79). Existing nodes get
        their ``create_node`` callback immediately."""
        sim = cls(self.rng, self.time, self.config, self.handle)
        self.handle.sims[cls] = sim
        self.executor.simulators = list(self.handle.sims.values())
        for node_id in self.executor.nodes:
            sim.create_node(node_id)
        return sim

    def create_node(self) -> NodeBuilder:
        return NodeBuilder(self.handle)

    def set_time_limit(self, seconds: float) -> None:
        self.executor.time_limit_ns = round(seconds * 1_000_000_000)

    def block_on(self, coro: Coroutine) -> Any:
        from . import intercept

        with context.enter(self.handle), intercept.deterministic_stdlib():
            return self.executor.block_on(coro)

    @staticmethod
    def check_determinism(
        seed: int,
        workload: Callable[[], Coroutine],
        config: Optional[Config] = None,
        time_limit: Optional[float] = None,
    ) -> Any:
        """Run twice with the RNG op-log; raise DeterminismError on
        divergence (mod.rs:165-190)."""
        from .rand import DeterminismError

        rt1 = Runtime(seed, config)
        if time_limit is not None:
            rt1.set_time_limit(time_limit)
        rt1.rng.enable_log()
        rt1.block_on(workload())
        log = rt1.rng.take_log()

        rt2 = Runtime(seed, config)
        if time_limit is not None:
            rt2.set_time_limit(time_limit)
        rt2.rng.enable_check(log)
        result = rt2.block_on(workload())
        if rt2.rng._check_pos != len(log):
            raise DeterminismError(
                f"non-determinism detected: replay made {rt2.rng._check_pos} "
                f"random draws but the recording has {len(log)}"
            )
        return result
