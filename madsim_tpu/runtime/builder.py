"""Multi-seed test harness.

Parity with reference madsim/src/sim/runtime/builder.rs and
madsim-macros/src/lib.rs:
  * ``Builder.from_env`` reads ``MADSIM_TEST_SEED`` / ``MADSIM_TEST_NUM`` /
    ``MADSIM_TEST_JOBS`` / ``MADSIM_TEST_CONFIG`` /
    ``MADSIM_TEST_TIME_LIMIT`` / ``MADSIM_TEST_CHECK_DETERMINISM``
    (builder.rs:23-107).
  * ``Builder.run`` executes the workload for ``count`` consecutive seeds,
    one OS thread per simulation for context isolation, up to ``jobs``
    concurrently (builder.rs:110-148).
  * A failing seed prints the repro banner with the seed and the config
    hash before re-raising (runtime/mod.rs:193-200 ``panic_with_info``).
  * ``@madsim_tpu.test`` / ``@madsim_tpu.main`` are the analogs of
    ``#[madsim::test]`` / ``#[madsim::main]`` (madsim-macros/src/lib.rs:
    36-113): the decorated ``async def`` becomes a plain callable that
    pytest (or ``__main__``) invokes directly.
"""

from __future__ import annotations

import functools
import os
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Coroutine, Optional

from .config import Config
from .runtime import Runtime

__all__ = ["Builder", "test", "main"]


class Builder:
    def __init__(
        self,
        seed: Optional[int] = None,
        count: int = 1,
        jobs: int = 1,
        config: Optional[Config] = None,
        time_limit: Optional[float] = None,
        check_determinism: bool = False,
    ):
        if seed is None:
            # Default seed comes from real OS entropy, like the reference
            # (builder.rs:58-60); set MADSIM_TEST_SEED to pin it.
            # real entropy is the POINT here (builder.rs:58-60); every
            # in-sim draw then derives from this one pinned seed
            seed = int.from_bytes(os.urandom(8), "little") % (1 << 32)  # lint: allow(ambient-entropy)
        self.seed = seed
        self.count = count
        self.jobs = jobs
        self.config = config or Config()
        self.time_limit = time_limit
        self.check_determinism = check_determinism

    @classmethod
    def from_env(cls) -> "Builder":
        seed_s = os.environ.get("MADSIM_TEST_SEED")
        config = None
        config_path = os.environ.get("MADSIM_TEST_CONFIG")
        if config_path:
            config = Config.from_file(config_path)
        time_limit_s = os.environ.get("MADSIM_TEST_TIME_LIMIT")
        return cls(
            seed=int(seed_s) if seed_s else None,
            count=int(os.environ.get("MADSIM_TEST_NUM", "1")),
            jobs=int(os.environ.get("MADSIM_TEST_JOBS", "1")),
            config=config,
            time_limit=float(time_limit_s) if time_limit_s else None,
            check_determinism=bool(os.environ.get("MADSIM_TEST_CHECK_DETERMINISM")),
        )

    def _run_one(self, seed: int, workload: Callable[[], Coroutine]) -> Any:
        try:
            if self.check_determinism:
                return Runtime.check_determinism(
                    seed, workload, config=self.config, time_limit=self.time_limit
                )
            rt = Runtime(seed, self.config)
            if self.time_limit is not None:
                rt.set_time_limit(self.time_limit)
            return rt.block_on(workload())
        except BaseException:
            # Repro banner (runtime/mod.rs:193-200).
            print(
                f"\nnote: rerun with `MADSIM_TEST_SEED={seed}` to reproduce"
                f" this failure\n      config hash: {self.config.hash():016x}",
                file=sys.stderr,
            )
            raise

    def run(self, workload: Callable[[], Coroutine]) -> Any:
        """Run ``count`` consecutive seeds; returns the last result."""
        seeds = [self.seed + i for i in range(self.count)]
        if self.jobs <= 1 or len(seeds) == 1:
            result = None
            for s in seeds:
                result = self._run_one(s, workload)
            return result
        # One simulation per worker thread — thread-local context gives the
        # same isolation as the reference's thread-per-seed model
        # (builder.rs:118-136).
        with ThreadPoolExecutor(max_workers=self.jobs) as pool:
            futures = [pool.submit(self._run_one, s, workload) for s in seeds]
            result = None
            for f in futures:
                result = f.result()
            return result


def test(fn: Optional[Callable[..., Coroutine]] = None, **builder_kwargs):
    """Decorator: turn an ``async def`` test into a seeded simulation run.

    Analog of ``#[madsim::test]`` (madsim-macros/src/lib.rs:88-96). Keyword
    arguments override the env-derived :class:`Builder` fields, e.g.
    ``@madsim_tpu.test(count=16, time_limit=300)``.
    """

    def deco(f: Callable[..., Coroutine]):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            from .trace import init_logger

            init_logger()  # the test macro inits the subscriber once
            b = Builder.from_env()
            for k, v in builder_kwargs.items():
                setattr(b, k, v)
            return b.run(lambda: f(*args, **kwargs))

        wrapper.__madsim_test__ = True  # type: ignore[attr-defined]
        return wrapper

    return deco(fn) if fn is not None else deco


def main(fn: Callable[..., Coroutine]):
    """Decorator analog of ``#[madsim::main]`` (madsim-macros/src/lib.rs:
    36-86): run the body once on the env-selected seed."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        b = Builder.from_env()
        b.count = 1
        return b.run(lambda: fn(*args, **kwargs))

    return wrapper
