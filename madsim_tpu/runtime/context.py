"""Thread-local simulation context.

Parity with reference madsim/src/sim/runtime/context.rs: a thread-local
current ``Handle`` + current ``Task`` is how free functions (``spawn``,
``sleep``, ``thread_rng``, the interposed stdlib functions) find the
runtime they belong to (context.rs:9-77). One OS thread hosts at most one
simulation at a time; multi-seed test runs use one thread per seed
(reference sim/runtime/builder.rs:118-136), which this TLS design supports
unchanged.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import TYPE_CHECKING, Iterator

if TYPE_CHECKING:  # pragma: no cover - import cycle guard, typing only
    from .runtime import Handle
    from .task import Task

__all__ = [
    "current_handle",
    "try_current_handle",
    "current_task",
    "try_current_task",
    "enter",
    "enter_task",
    "in_simulation",
]

_tls = threading.local()


class NoContextError(RuntimeError):
    pass


def try_current_handle() -> "Handle | None":
    return getattr(_tls, "handle", None)


def current_handle() -> "Handle":
    h = try_current_handle()
    if h is None:
        raise NoContextError(
            "there is no simulation context on this thread; "
            "this API must be called from within a madsim_tpu Runtime"
        )
    return h


def try_current_task() -> "Task | None":
    return getattr(_tls, "task", None)


def current_task() -> "Task":
    t = try_current_task()
    if t is None:
        raise NoContextError("not inside a simulated task")
    return t


def in_simulation() -> bool:
    """True when the calling thread is inside a simulation context.

    The analog of the reference's "is this thread in a madsim context"
    check that gates every libc interposition (e.g. rand.rs:178-186).
    """
    return try_current_handle() is not None


@contextmanager
def enter(handle: "Handle") -> Iterator[None]:
    """Set the current runtime handle for this thread (context.rs:41-56)."""
    prev = getattr(_tls, "handle", None)
    _tls.handle = handle
    try:
        yield
    finally:
        _tls.handle = prev


@contextmanager
def enter_task(task: "Task") -> Iterator[None]:
    """Set the current task while the executor polls it (context.rs:58-77)."""
    prev = getattr(_tls, "task", None)
    _tls.task = task
    try:
        yield
    finally:
        _tls.task = prev
