"""Virtual time: timer heap + mock clock.

Parity with reference madsim/src/sim/time/:
  * ``TimeRuntime`` owns the clock and timer wheel (time/mod.rs:21-75);
    the base wall-clock time is randomized per seed to land in ~2022
    (time/mod.rs:26-37) so tests can't depend on real dates.
  * ``advance_to_next_event`` jumps the clock to the next timer deadline
    plus a 50 ns epsilon and fires all due timers (time/mod.rs:45-60).
  * ``TimeHandle`` is the user API: sleep/sleep_until/timeout/interval
    (time/mod.rs:78-149), ``Instant``/``SystemTime`` mocks
    (time/system_time.rs), and ``interval`` with tick semantics
    (time/interval.rs).

Internally time is an integer count of nanoseconds since simulation start —
exact arithmetic, no float drift, trivially mirrored by the batched JAX
engine (int64) and the C++ oracle.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Coroutine

from .future import SimFuture, select
from .rand import GlobalRng

__all__ = [
    "NANOS_PER_SEC",
    "TimeRuntime",
    "TimeHandle",
    "Instant",
    "SystemTime",
    "Elapsed",
    "Interval",
    "MissedTickBehavior",
    "sleep",
    "sleep_until",
    "timeout",
    "interval",
    "now",
    "now_ns",
]

NANOS_PER_SEC = 1_000_000_000
# Epsilon added when jumping the clock to the next timer (time/mod.rs:53).
_JUMP_EPSILON_NS = 50


def _to_ns(seconds: float | int) -> int:
    return round(seconds * NANOS_PER_SEC)


class Elapsed(Exception):
    """Deadline elapsed — the analog of tokio/madsim time::error::Elapsed."""


class Instant:
    """Monotonic instant: ns since simulation start (time/system_time.rs)."""

    __slots__ = ("ns",)

    def __init__(self, ns: int):
        self.ns = ns

    @staticmethod
    def now() -> "Instant":
        from . import context

        return Instant(context.current_handle().time.now_ns())

    def elapsed(self) -> float:
        from . import context

        return (context.current_handle().time.now_ns() - self.ns) / NANOS_PER_SEC

    def __sub__(self, other: "Instant") -> float:
        return (self.ns - other.ns) / NANOS_PER_SEC

    def __add__(self, seconds: float) -> "Instant":
        return Instant(self.ns + _to_ns(seconds))

    def __lt__(self, o: "Instant") -> bool:
        return self.ns < o.ns

    def __le__(self, o: "Instant") -> bool:
        return self.ns <= o.ns

    def __eq__(self, o: object) -> bool:
        return isinstance(o, Instant) and self.ns == o.ns

    def __hash__(self) -> int:
        return hash(self.ns)

    def __repr__(self) -> str:
        return f"Instant({self.ns}ns)"


class SystemTime:
    """Mock wall clock; base randomized per seed (time/mod.rs:26-37)."""

    __slots__ = ("unix_ns",)

    def __init__(self, unix_ns: int):
        self.unix_ns = unix_ns

    @staticmethod
    def now() -> "SystemTime":
        from . import context

        t = context.current_handle().time
        task = context.try_current_task()
        skew = t.skew_of(task.node.id) if task is not None else 0
        return SystemTime(t.base_unix_ns + t.now_ns() + skew)

    def timestamp(self) -> float:
        return self.unix_ns / NANOS_PER_SEC

    def __sub__(self, other: "SystemTime") -> float:
        return (self.unix_ns - other.unix_ns) / NANOS_PER_SEC

    def __repr__(self) -> str:
        return f"SystemTime({self.unix_ns}ns)"


class TimeRuntime:
    """The timer heap + virtual clock driven by the executor."""

    def __init__(self, rng: GlobalRng):
        # Randomized base wall time within calendar year 2022
        # (parity: time/mod.rs:26-37 randomizes the epoch per seed).
        self.base_unix_ns = (
            rng.randrange(1_640_995_200, 1_672_531_199) * NANOS_PER_SEC
            + rng.randrange(0, NANOS_PER_SEC)
        )
        self._now_ns = 0
        self._heap: list[tuple[int, int, Callable[[], None]]] = []
        self._seq = 0  # deterministic FIFO tiebreak for equal deadlines
        rng.now_ns = self.now_ns  # wire the determinism-log clock
        # chaos clock skew (madsim_tpu.chaos, KIND_SKEW analog): per-node
        # wall-clock offsets observed by SystemTime.now() on that node's
        # tasks. The simulation clock itself (timers, sleeps) is shared —
        # skew is what the *application* reads, the classic drifted-NTP
        # fault; it never shifts scheduling, so determinism is untouched.
        self.node_skew: dict[int, int] = {}

    def now_ns(self) -> int:
        return self._now_ns

    def skew_of(self, node_id: int | None) -> int:
        if node_id is None:
            return 0
        return self.node_skew.get(node_id, 0)

    def advance(self, delta_ns: int) -> None:
        """Advance the clock without firing timers (per-poll cost,
        task.rs:213-214)."""
        self._now_ns += delta_ns

    def add_timer_at(self, deadline_ns: int, callback: Callable[[], None]) -> None:
        """Register a timer callback (time/mod.rs:138-149)."""
        self._seq += 1
        heapq.heappush(self._heap, (deadline_ns, self._seq, callback))

    def next_deadline(self) -> int | None:
        return self._heap[0][0] if self._heap else None

    def advance_to_next_event(self) -> bool:
        """Jump to the next timer (+50 ns epsilon) and fire all due timers.

        Returns False when no timers remain (deadlock condition for the
        executor). Parity: time/mod.rs:45-60.
        """
        if not self._heap:
            return False
        deadline = self._heap[0][0]
        if deadline > self._now_ns:
            self._now_ns = deadline + _JUMP_EPSILON_NS
        self.fire_due()
        return True

    def fire_due(self) -> None:
        while self._heap and self._heap[0][0] <= self._now_ns:
            _, _, cb = heapq.heappop(self._heap)
            cb()


class MissedTickBehavior:
    """Interval catch-up policy (reference time/interval.rs:62-110)."""

    BURST = "burst"
    DELAY = "delay"
    SKIP = "skip"


class Interval:
    """Periodic ticks (reference time/interval.rs:112-160)."""

    def __init__(self, handle: "TimeHandle", period: float, start_ns: int):
        if period <= 0:
            raise ValueError("interval period must be > 0")
        self._handle = handle
        self._period_ns = _to_ns(period)
        self._next_ns = start_ns
        self.missed_tick_behavior = MissedTickBehavior.BURST

    async def tick(self) -> Instant:
        now = self._handle.now_ns()
        if self._next_ns > now:
            await self._handle.sleep_until_ns(self._next_ns)
        fired = self._next_ns
        behavior = self.missed_tick_behavior
        if behavior == MissedTickBehavior.BURST:
            self._next_ns = fired + self._period_ns
        elif behavior == MissedTickBehavior.DELAY:
            self._next_ns = self._handle.now_ns() + self._period_ns
        else:  # SKIP: next multiple of period after now
            now2 = self._handle.now_ns()
            missed = max(0, (now2 - fired) // self._period_ns)
            self._next_ns = fired + (missed + 1) * self._period_ns
        return Instant(fired)


class TimeHandle:
    """User-facing time API bound to one runtime (time/mod.rs:78-149)."""

    def __init__(self, rt: TimeRuntime):
        self._rt = rt

    @property
    def base_unix_ns(self) -> int:
        return self._rt.base_unix_ns

    def now_ns(self) -> int:
        return self._rt.now_ns()

    def skew_of(self, node_id: int | None) -> int:
        return self._rt.skew_of(node_id)

    def set_skew(self, node_id: int, skew_ns: int) -> None:
        """Set the node's wall-clock skew (chaos KIND_SKEW analog):
        SystemTime.now() on that node reads true time + skew_ns."""
        self._rt.node_skew[node_id] = int(skew_ns)

    def now(self) -> Instant:
        return Instant(self._rt.now_ns())

    def system_time(self) -> SystemTime:
        return SystemTime(self._rt.base_unix_ns + self._rt.now_ns())

    def add_timer_at(self, deadline_ns: int, cb: Callable[[], None]) -> None:
        self._rt.add_timer_at(deadline_ns, cb)

    def add_timer(self, delay_s: float, cb: Callable[[], None]) -> None:
        self._rt.add_timer_at(self._rt.now_ns() + _to_ns(delay_s), cb)

    def sleep_until_ns(self, deadline_ns: int) -> SimFuture:
        fut = SimFuture(name="sleep")
        self._rt.add_timer_at(deadline_ns, fut.set_result)
        return fut

    def sleep(self, seconds: float) -> SimFuture:
        """Sleep future (time/mod.rs:110-114, sleep.rs:20-55)."""
        return self.sleep_until_ns(self._rt.now_ns() + _to_ns(seconds))

    def sleep_until(self, instant: Instant) -> SimFuture:
        return self.sleep_until_ns(instant.ns)

    async def timeout(self, seconds: float, awaitable) -> Any:
        """Await with a deadline; raises :class:`Elapsed` on expiry
        (time/mod.rs:124-136).

        Accepts a SimFuture or a coroutine. A timed-out coroutine is
        cancelled (its finally blocks run), matching the reference where
        the inner future is dropped.
        """
        from . import task as _task

        if isinstance(awaitable, Coroutine):
            inner = _task.spawn(awaitable, name="timeout-inner")
            inner_fut: SimFuture = inner._fut
            cancel = inner.abort
        elif isinstance(awaitable, SimFuture):
            inner_fut = awaitable
            cancel = lambda: None  # noqa: E731 - dropping a bare future has no owner to cancel
        else:
            raise TypeError(f"timeout() expects a coroutine or SimFuture, got {type(awaitable)!r}")
        timer = self.sleep(seconds)
        idx, _ = await select(inner_fut, timer)
        if idx == 0:
            return inner_fut.result()
        cancel()
        raise Elapsed(f"deadline of {seconds}s elapsed")

    def interval(self, period: float) -> Interval:
        """Ticks immediately, then every ``period`` (interval.rs:38-60)."""
        return Interval(self, period, self._rt.now_ns())

    def interval_at(self, start: Instant, period: float) -> Interval:
        return Interval(self, period, start.ns)


# ---- free functions bound to the current context ------------------------


def _handle() -> TimeHandle:
    from . import context

    return context.current_handle().time


def sleep(seconds: float) -> SimFuture:
    return _handle().sleep(seconds)


def sleep_until(instant: Instant) -> SimFuture:
    return _handle().sleep_until(instant)


def timeout(seconds: float, awaitable) -> Any:
    return _handle().timeout(seconds, awaitable)


def interval(period: float) -> Interval:
    return _handle().interval(period)


def now() -> Instant:
    return _handle().now()


def now_ns() -> int:
    return _handle().now_ns()
