"""Real-filesystem backend with the simulated fs API surface.

Parity with reference madsim/src/std/fs.rs (C29): the same ``File`` /
``read`` / ``metadata`` names as madsim_tpu.fs, over the real OS
filesystem, so application code moves between sim and production
unchanged.
"""

from __future__ import annotations

import os
from typing import Union

__all__ = ["File", "read", "metadata", "Metadata"]

PathLike = Union[str, os.PathLike]


class Metadata:
    __slots__ = ("len",)

    def __init__(self, length: int):
        self.len = length


class File:
    def __init__(self, fh, path: str):
        self._fh = fh
        self.path = path

    @classmethod
    async def create(cls, path: PathLike) -> "File":
        return cls(open(path, "w+b"), str(path))

    @classmethod
    async def open(cls, path: PathLike) -> "File":
        return cls(open(path, "r+b"), str(path))

    async def read_at(self, buf_len: int, offset: int) -> bytes:
        self._fh.seek(offset)
        return self._fh.read(buf_len)

    async def write_all_at(self, data: bytes, offset: int) -> None:
        self._fh.seek(offset)
        self._fh.write(data)

    async def set_len(self, size: int) -> None:
        self._fh.truncate(size)

    async def sync_all(self) -> None:
        self._fh.flush()
        os.fsync(self._fh.fileno())

    async def metadata(self) -> Metadata:
        return Metadata(os.fstat(self._fh.fileno()).st_size)

    def close(self) -> None:
        self._fh.close()


async def read(path: PathLike) -> bytes:
    with open(path, "rb") as fh:
        return fh.read()


async def metadata(path: PathLike) -> Metadata:
    return Metadata(os.stat(path).st_size)
