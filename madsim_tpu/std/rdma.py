"""RDMA transport backends — feature-gated like the reference's.

The reference offers optional kernel-bypass transports behind cargo
features: UCX RDMA (madsim/src/std/net/ucx.rs, feature ``ucx``, C27) and
eRPC/ibverbs (std/net/erpc.rs, feature ``erpc``, C28), both exposing the
same tag-matching Endpoint API as the TCP backend. This module is the
same seam: ``UcxEndpoint``/``ErpcEndpoint`` select a native transport
when its library is present and fail with a clear error when not —
this environment has no RDMA NICs or UCX/ibverbs userspace, so the
gate is how the surface exists without the hardware.
"""

from __future__ import annotations

import ctypes.util

__all__ = ["UcxEndpoint", "ErpcEndpoint", "ucx_available", "erpc_available"]


def ucx_available() -> bool:
    return ctypes.util.find_library("ucp") is not None


def erpc_available() -> bool:
    return ctypes.util.find_library("ibverbs") is not None


class _Gated:
    _FEATURE = ""
    _LIB = ""
    _AVAILABLE = staticmethod(lambda: False)

    @classmethod
    async def bind(cls, addr):
        if not cls._AVAILABLE():
            raise RuntimeError(
                f"the {cls._FEATURE} transport needs {cls._LIB} installed "
                f"(the reference gates this behind the `{cls._FEATURE}` "
                f"cargo feature); use madsim_tpu.std.net.Endpoint (TCP) "
                f"on hosts without RDMA"
            )
        raise NotImplementedError(
            f"{cls._FEATURE} transport binding not implemented in this build"
        )


class UcxEndpoint(_Gated):
    """Tag-matching endpoint over UCX RDMA (C27)."""

    _FEATURE = "ucx"
    _LIB = "libucp"
    _AVAILABLE = staticmethod(ucx_available)


class ErpcEndpoint(_Gated):
    """Tag-matching endpoint over eRPC/ibverbs (C28)."""

    _FEATURE = "erpc"
    _LIB = "libibverbs"
    _AVAILABLE = staticmethod(erpc_available)
