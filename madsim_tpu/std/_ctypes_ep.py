"""Shared ctypes scaffolding for the native transport endpoints.

All three native transports (epoll ``msep_``, shared-memory ``shmep_``,
io_uring ``urep_``) export the identical C ABI shape — bind / send /
blocking recv / msg accessors / two-phase shutdown+free — and their
Python wrappers were line-for-line copies. This module is that wrapper
once: :func:`make_transport` binds the symbols for a prefix and returns
the loader plus an endpoint class, so a fix to the close/teardown
contract or the recv-executor pattern lands in every transport at once.
"""

from __future__ import annotations

import asyncio
import ctypes
import os
import pickle
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE = os.path.join(_REPO, "native")

__all__ = ["make_transport", "split_addr"]


def split_addr(addr) -> tuple[str, int]:
    if isinstance(addr, tuple):
        return addr[0], int(addr[1])
    host, port = str(addr).rsplit(":", 1)
    return host, int(port)


def make_transport(prefix: str, src_name: str, lib_name: str, label: str):
    """Return ``(build, load, EndpointClass)`` for one native transport.

    ``prefix`` is the C symbol prefix (``msep_``/``shmep_``/``urep_``),
    ``src_name``/``lib_name`` the files under ``native/``, ``label`` the
    human name used in error messages and thread names.
    """
    lib_path = os.path.join(_NATIVE, "lib", lib_name)
    src_path = os.path.join(_NATIVE, src_name)
    state = {"lib": None}
    lock = threading.Lock()

    def build() -> str:
        if not os.path.exists(lib_path) or os.path.getmtime(
            lib_path
        ) < os.path.getmtime(src_path):
            subprocess.run(["make", "-C", _NATIVE], check=True, capture_output=True)
        return lib_path

    def load() -> ctypes.CDLL:
        with lock:
            if state["lib"] is None:
                lib = ctypes.CDLL(build())
                g = lambda name: getattr(lib, prefix + name)  # noqa: E731
                g("bind").restype = ctypes.c_void_p
                g("bind").argtypes = [
                    ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
                ]
                g("send").restype = ctypes.c_int
                g("send").argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                    ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
                ]
                g("recv").restype = ctypes.c_void_p
                g("recv").argtypes = [
                    ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64
                ]
                g("msg_len").restype = ctypes.c_uint64
                g("msg_len").argtypes = [ctypes.c_void_p]
                g("msg_data").restype = ctypes.POINTER(ctypes.c_uint8)
                g("msg_data").argtypes = [ctypes.c_void_p]
                g("msg_src_ip").restype = ctypes.c_char_p
                g("msg_src_ip").argtypes = [ctypes.c_void_p]
                g("msg_src_port").restype = ctypes.c_int
                g("msg_src_port").argtypes = [ctypes.c_void_p]
                g("msg_free").argtypes = [ctypes.c_void_p]
                g("shutdown").argtypes = [ctypes.c_void_p]
                g("free").argtypes = [ctypes.c_void_p]
                state["lib"] = lib
            return state["lib"]

    class Endpoint:
        """Tag-matching endpoint on a native transport, asyncio-friendly.

        Blocking native receives run on a thread-pool executor so the
        asyncio surface stays non-blocking; payloads are pickled here
        (the transports carry opaque bytes)."""

        def __init__(self, handle: int, port: int, host: str):
            self._h = handle
            self._host = host
            self._port = port
            self._pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix=f"{prefix}recv"
            )
            self._closed = False

        @classmethod
        async def bind(cls, addr) -> "Endpoint":
            host, port = split_addr(addr)
            lib = load()
            out_port = ctypes.c_int(0)
            h = getattr(lib, prefix + "bind")(
                host.encode(), port, ctypes.byref(out_port)
            )
            if not h:
                raise OSError(f"{label} endpoint bind failed for {host}:{port}")
            return cls(h, out_port.value, host)

        @property
        def local_addr(self) -> tuple[str, int]:
            return (self._host, self._port)

        async def send_to(self, dst, tag: int, payload: Any) -> None:
            if self._closed:
                raise ConnectionError("endpoint is closed")
            if tag >= (1 << 64) - 1 or tag < 0:
                raise ValueError("tag 2**64-1 is reserved for the handshake")
            ip, port = split_addr(dst)
            raw = pickle.dumps(payload)
            rc = getattr(load(), prefix + "send")(
                self._h, ip.encode(), port, tag, raw, len(raw)
            )
            if rc != 0:
                raise ConnectionError(f"{label} send to {ip}:{port} failed")

        async def recv_from(self, tag: int, timeout: Optional[float] = None):
            if self._closed:
                raise ConnectionError("endpoint is closed")
            loop = asyncio.get_event_loop()
            lib = load()
            timeout_ms = -1 if timeout is None else max(int(timeout * 1000), 0)
            recv = getattr(lib, prefix + "recv")

            def blocking():
                return recv(self._h, tag, timeout_ms)

            m = await loop.run_in_executor(self._pool, blocking)
            if not m:
                if self._closed:
                    raise ConnectionError("endpoint closed during receive")
                raise asyncio.TimeoutError(f"recv tag {tag} timed out")
            try:
                n = getattr(lib, prefix + "msg_len")(m)
                data = ctypes.string_at(getattr(lib, prefix + "msg_data")(m), n)
                src = (
                    getattr(lib, prefix + "msg_src_ip")(m).decode(),
                    getattr(lib, prefix + "msg_src_port")(m),
                )
            finally:
                getattr(lib, prefix + "msg_free")(m)
            return pickle.loads(data), src

        def close(self) -> None:
            if not self._closed:
                self._closed = True
                lib = load()
                # two-phase: wake every blocked receiver, drain the
                # pool, then free the native object (freeing earlier
                # would be a use-after-free under a blocked recv)
                getattr(lib, prefix + "shutdown")(self._h)
                self._pool.shutdown(wait=True)
                getattr(lib, prefix + "free")(self._h)

    Endpoint.__name__ = label.title().replace("_", "") + "Endpoint"
    return build, load, Endpoint
