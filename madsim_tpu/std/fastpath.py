"""Shared-memory fast-path endpoint (native/shm_transport.cpp wrapper).

The reference offers optional kernel-bypass transports behind cargo
features — UCX RDMA (madsim/src/std/net/ucx.rs:23-30, C27) and
eRPC/ibverbs (std/net/erpc.rs:24-30, C28) — exposing the same
tag-matching Endpoint API as the TCP backend. This environment has no
RDMA NIC, so that role is filled honestly for the case those transports
accelerate most: ``ShmEndpoint`` moves messages between same-host
endpoints through a POSIX shared-memory ring with no socket syscalls on
the data path, behind the exact surface of
:class:`madsim_tpu.std.native.NativeEndpoint` (bind/send_to/recv_from/
close). ``pick_endpoint`` is the feature-selection seam: shm for
loopback peers, epoll TCP otherwise — the analog of the reference's
``ucx``/``erpc`` feature switch (std/net/mod.rs:33-48).

Measured on loopback (examples/rpc_bench.py): the shm path beats the
epoll transport on both empty-RPC latency and 1 MiB payload throughput.
"""

from __future__ import annotations

import asyncio
import ctypes
import os
import pickle
import subprocess
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE = os.path.join(_REPO, "native")
_LIB = os.path.join(_NATIVE, "lib", "libshmtransport.so")

__all__ = ["ShmEndpoint", "available", "build", "pick_endpoint"]


def build() -> str:
    src = os.path.join(_NATIVE, "shm_transport.cpp")
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(src):
        subprocess.run(["make", "-C", _NATIVE], check=True, capture_output=True)
    return _LIB


def available() -> bool:
    try:
        build()
        return True
    except Exception:
        return False


_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build())
        lib.shmep_bind.restype = ctypes.c_void_p
        lib.shmep_bind.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
        ]
        lib.shmep_send.restype = ctypes.c_int
        lib.shmep_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.shmep_recv.restype = ctypes.c_void_p
        lib.shmep_recv.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64]
        lib.shmep_msg_len.restype = ctypes.c_uint64
        lib.shmep_msg_len.argtypes = [ctypes.c_void_p]
        lib.shmep_msg_data.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.shmep_msg_data.argtypes = [ctypes.c_void_p]
        lib.shmep_msg_src_ip.restype = ctypes.c_char_p
        lib.shmep_msg_src_ip.argtypes = [ctypes.c_void_p]
        lib.shmep_msg_src_port.restype = ctypes.c_int
        lib.shmep_msg_src_port.argtypes = [ctypes.c_void_p]
        lib.shmep_msg_free.argtypes = [ctypes.c_void_p]
        lib.shmep_shutdown.argtypes = [ctypes.c_void_p]
        lib.shmep_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def _split(addr) -> tuple[str, int]:
    if isinstance(addr, tuple):
        return addr[0], int(addr[1])
    host, port = str(addr).rsplit(":", 1)
    return host, int(port)


class ShmEndpoint:
    """Tag-matching endpoint over the shared-memory ring, asyncio-friendly."""

    def __init__(self, handle: int, port: int, host: str):
        self._h = handle
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="shmep-recv"
        )
        self._closed = False

    @classmethod
    async def bind(cls, addr) -> "ShmEndpoint":
        host, port = _split(addr)
        lib = _load()
        out_port = ctypes.c_int(0)
        h = lib.shmep_bind(host.encode(), port, ctypes.byref(out_port))
        if not h:
            raise OSError(f"shm endpoint bind failed for {host}:{port}")
        return cls(h, out_port.value, host)

    @property
    def local_addr(self) -> tuple[str, int]:
        return (self._host, self._port)

    async def send_to(self, dst, tag: int, payload: Any) -> None:
        if self._closed:
            raise ConnectionError("endpoint is closed")
        if tag >= (1 << 64) - 1 or tag < 0:
            raise ValueError("tag must fit in 64 bits (top value reserved)")
        ip, port = _split(dst)
        raw = pickle.dumps(payload)
        rc = _load().shmep_send(self._h, ip.encode(), port, tag, raw, len(raw))
        if rc != 0:
            raise ConnectionError(f"shm send to {ip}:{port} failed")

    async def recv_from(self, tag: int, timeout: Optional[float] = None):
        if self._closed:
            raise ConnectionError("endpoint is closed")
        loop = asyncio.get_event_loop()
        lib = _load()
        timeout_ms = -1 if timeout is None else max(int(timeout * 1000), 0)

        def blocking():
            return lib.shmep_recv(self._h, tag, timeout_ms)

        m = await loop.run_in_executor(self._pool, blocking)
        if not m:
            if self._closed:
                raise ConnectionError("endpoint closed during receive")
            raise asyncio.TimeoutError(f"recv tag {tag} timed out")
        try:
            n = lib.shmep_msg_len(m)
            data = ctypes.string_at(lib.shmep_msg_data(m), n)
            src = (lib.shmep_msg_src_ip(m).decode(), lib.shmep_msg_src_port(m))
        finally:
            lib.shmep_msg_free(m)
        return pickle.loads(data), src

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            lib = _load()
            lib.shmep_shutdown(self._h)
            self._pool.shutdown(wait=True)
            lib.shmep_free(self._h)


_LOCAL_IPS = ("127.0.0.1", "localhost", "0.0.0.0", "::1")


async def pick_endpoint(
    addr,
    *,
    prefer_shm: Optional[bool] = None,
    prefer_uring: Optional[bool] = None,
):
    """Bind the fastest transport for ``addr`` — the feature-selection
    seam of the reference's std/net/mod.rs:33-48, now with both C28
    alternative slots filled:

      1. shm ring for loopback/same-host peers (the UCX-style bypass);
      2. io_uring proactor TCP when the kernel grants a ring (the
         eRPC-style alternative; cross-host capable, same wire format);
      3. epoll TCP otherwise.

    ``prefer_shm=False`` with ``prefer_uring=None`` probes io_uring;
    set ``prefer_uring=False`` to force epoll."""
    host, _ = _split(addr)
    want_shm = prefer_shm if prefer_shm is not None else host in _LOCAL_IPS
    if want_shm and available():
        return await ShmEndpoint.bind(addr)
    from . import uring

    want_uring = prefer_uring if prefer_uring is not None else True
    if want_uring and uring.available():
        return await uring.UringEndpoint.bind(addr)
    from .native import NativeEndpoint

    return await NativeEndpoint.bind(addr)
