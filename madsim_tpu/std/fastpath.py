"""Shared-memory fast-path endpoint (native/shm_transport.cpp wrapper).

The reference offers optional kernel-bypass transports behind cargo
features — UCX RDMA (madsim/src/std/net/ucx.rs:23-30, C27) and
eRPC/ibverbs (std/net/erpc.rs:24-30, C28) — exposing the same
tag-matching Endpoint API as the TCP backend. This environment has no
RDMA NIC, so that role is filled honestly for the case those transports
accelerate most: ``ShmEndpoint`` moves messages between same-host
endpoints through a POSIX shared-memory ring with no socket syscalls on
the data path, behind the exact surface of
:class:`madsim_tpu.std.native.NativeEndpoint` (bind/send_to/recv_from/
close). ``pick_endpoint`` is the feature-selection seam: shm for
loopback peers, epoll TCP otherwise — the analog of the reference's
``ucx``/``erpc`` feature switch (std/net/mod.rs:33-48).

Measured on loopback (examples/rpc_bench.py): the shm path beats the
epoll transport on both empty-RPC latency and 1 MiB payload throughput.
"""

from __future__ import annotations

from typing import Optional

from ._ctypes_ep import make_transport, split_addr

__all__ = ["ShmEndpoint", "available", "build", "pick_endpoint"]

# wrapper body shared with the epoll and io_uring transports
# (std/_ctypes_ep.py — identical C ABI shape)
build, _load, ShmEndpoint = make_transport(
    "shmep_", "shm_transport.cpp", "libshmtransport.so", "shm"
)
ShmEndpoint.__name__ = "ShmEndpoint"


def available() -> bool:
    try:
        build()
        return True
    except Exception:
        return False


_split = split_addr


_LOCAL_IPS = ("127.0.0.1", "localhost", "0.0.0.0", "::1")


async def pick_endpoint(
    addr,
    *,
    prefer_shm: Optional[bool] = None,
    prefer_uring: Optional[bool] = None,
):
    """Bind the fastest transport for ``addr`` — the feature-selection
    seam of the reference's std/net/mod.rs:33-48, now with both C28
    alternative slots filled:

      1. shm ring for loopback/same-host peers (the UCX-style bypass);
      2. io_uring proactor TCP when the kernel grants a ring (the
         eRPC-style alternative; cross-host capable, same wire format);
      3. epoll TCP otherwise.

    ``prefer_shm=False`` with ``prefer_uring=None`` probes io_uring;
    set ``prefer_uring=False`` to force epoll."""
    host, _ = _split(addr)
    want_shm = prefer_shm if prefer_shm is not None else host in _LOCAL_IPS
    if want_shm and available():
        return await ShmEndpoint.bind(addr)
    from . import uring

    want_uring = prefer_uring if prefer_uring is not None else True
    if want_uring and uring.available():
        return await uring.UringEndpoint.bind(addr)
    from .native import NativeEndpoint

    return await NativeEndpoint.bind(addr)
