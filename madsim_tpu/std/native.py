"""ctypes wrapper for the native C++ epoll transport (native/transport.cpp).

``NativeEndpoint`` exposes the same tag-matching surface as the asyncio
backend (std/net.py) on the C++ epoll transport — the native
production-path component mirroring the reference's native Endpoint over
real TCP (C26). Both speak the same wire format, so native and asyncio
endpoints interoperate on the same network (tested in
tests/test_native_transport.py).

The wrapper body lives in std/_ctypes_ep.py, shared with the shm and
io_uring transports (identical C ABI shape).
"""

from __future__ import annotations

from ._ctypes_ep import make_transport

__all__ = ["NativeEndpoint", "available", "build"]

build, _load, NativeEndpoint = make_transport(
    "msep_", "transport.cpp", "libmstransport.so", "native"
)
NativeEndpoint.__name__ = "NativeEndpoint"


def available() -> bool:
    try:
        build()
        return True
    except Exception:
        return False
