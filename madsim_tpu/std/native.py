"""ctypes wrapper for the native C++ transport (native/transport.cpp).

``NativeEndpoint`` exposes the same tag-matching surface as the asyncio
backend (std/net.py) on the C++ epoll transport — the native
production-path component mirroring the reference's native Endpoint over
real TCP (C26). Both speak the same wire format, so native and asyncio
endpoints interoperate on the same network (tested in
tests/test_native_transport.py).

Blocking native receives run on a thread-pool executor so the asyncio
surface stays non-blocking; payloads are pickled at this layer (the
transport carries opaque bytes).
"""

from __future__ import annotations

import asyncio
import ctypes
import os
import pickle
import subprocess
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE = os.path.join(_REPO, "native")
_LIB = os.path.join(_NATIVE, "lib", "libmstransport.so")

__all__ = ["NativeEndpoint", "available", "build"]


def build() -> str:
    src = os.path.join(_NATIVE, "transport.cpp")
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(src):
        subprocess.run(["make", "-C", _NATIVE], check=True, capture_output=True)
    return _LIB


def available() -> bool:
    try:
        build()
        return True
    except Exception:
        return False


_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build())
        lib.msep_bind.restype = ctypes.c_void_p
        lib.msep_bind.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
        ]
        lib.msep_send.restype = ctypes.c_int
        lib.msep_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.msep_recv.restype = ctypes.c_void_p
        lib.msep_recv.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64]
        lib.msep_msg_len.restype = ctypes.c_uint64
        lib.msep_msg_len.argtypes = [ctypes.c_void_p]
        lib.msep_msg_data.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.msep_msg_data.argtypes = [ctypes.c_void_p]
        lib.msep_msg_src_ip.restype = ctypes.c_char_p
        lib.msep_msg_src_ip.argtypes = [ctypes.c_void_p]
        lib.msep_msg_src_port.restype = ctypes.c_int
        lib.msep_msg_src_port.argtypes = [ctypes.c_void_p]
        lib.msep_msg_free.argtypes = [ctypes.c_void_p]
        lib.msep_shutdown.argtypes = [ctypes.c_void_p]
        lib.msep_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class NativeEndpoint:
    """Tag-matching endpoint on the C++ transport, asyncio-friendly."""

    def __init__(self, handle: int, port: int, host: str):
        self._h = handle
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="msep-recv"
        )
        self._closed = False

    @classmethod
    async def bind(cls, addr) -> "NativeEndpoint":
        if isinstance(addr, tuple):
            host, port = addr[0], int(addr[1])
        else:
            host, port = str(addr).rsplit(":", 1)
            port = int(port)
        lib = _load()
        out_port = ctypes.c_int(0)
        h = lib.msep_bind(host.encode(), port, ctypes.byref(out_port))
        if not h:
            raise OSError(f"native endpoint bind failed for {host}:{port}")
        return cls(h, out_port.value, host)

    @property
    def local_addr(self) -> tuple[str, int]:
        return (self._host, self._port)

    async def send_to(self, dst, tag: int, payload: Any) -> None:
        if self._closed:
            raise ConnectionError("endpoint is closed")
        if tag >= (1 << 64) - 1 or tag < 0:
            raise ValueError("tag 2**64-1 is reserved for the handshake")
        if isinstance(dst, tuple):
            ip, port = dst[0], int(dst[1])
        else:
            ip, port = str(dst).rsplit(":", 1)
            port = int(port)
        raw = pickle.dumps(payload)
        rc = _load().msep_send(self._h, ip.encode(), port, tag, raw, len(raw))
        if rc != 0:
            raise ConnectionError(f"native send to {ip}:{port} failed")

    async def recv_from(self, tag: int, timeout: Optional[float] = None):
        if self._closed:
            raise ConnectionError("endpoint is closed")
        loop = asyncio.get_event_loop()
        lib = _load()
        timeout_ms = -1 if timeout is None else max(int(timeout * 1000), 0)

        def blocking():
            return lib.msep_recv(self._h, tag, timeout_ms)

        m = await loop.run_in_executor(self._pool, blocking)
        if not m:
            if self._closed:
                raise ConnectionError("endpoint closed during receive")
            raise asyncio.TimeoutError(f"recv tag {tag} timed out")
        try:
            n = lib.msep_msg_len(m)
            data = ctypes.string_at(lib.msep_msg_data(m), n)
            src = (lib.msep_msg_src_ip(m).decode(), lib.msep_msg_src_port(m))
        finally:
            lib.msep_msg_free(m)
        return pickle.loads(data), src

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            lib = _load()
            # two-phase: wake every blocked receiver, drain the pool,
            # then free the native object (freeing earlier would be a
            # use-after-free under a blocked recv)
            lib.msep_shutdown(self._h)
            self._pool.shutdown(wait=True)
            lib.msep_free(self._h)
