"""Real-world backends — the production path.

The reference is a drop-in library: build normally and every API runs on
real I/O; build with ``--cfg madsim`` and the same code runs simulated
(reference madsim/src/lib.rs:14-23). This package is our real side
(SURVEY.md §1 L5, C26/C29): the same Endpoint / RPC / fs / time API
surfaces backed by asyncio TCP, the real filesystem and the real clock,
so an application written against the simulator deploys unchanged:

    if os.environ.get("MADSIM"):
        from madsim_tpu import net, fs
    else:
        from madsim_tpu.std import net, fs

Transport details mirror C26 (std/net/tcp.rs:22-135): lazy per-peer TCP
connections with an address-exchange handshake and length-delimited
frames; payloads are pickled (the analog of the reference's bincode
serialization in std/net/rpc.rs).
"""

from . import fs, net, time  # noqa: F401
