"""ctypes wrapper for the io_uring transport (native/uring_transport.cpp).

``UringEndpoint`` is the second alternative fast-path transport behind
``pick_endpoint`` — the C28 slot: the reference ships two alternative
kernel-adjacent transports behind one feature seam (UCX,
madsim/src/std/net/ucx.rs:23-30; eRPC, std/net/erpc.rs:24-30). Here the
alternatives are the shared-memory ring (same-host) and this io_uring
proactor endpoint (cross-host capable, same wire format as the epoll
and asyncio backends, so all four interoperate).

The wrapper body lives in std/_ctypes_ep.py, shared with the epoll and
shm transports (identical C ABI shape).
"""

from __future__ import annotations

from ._ctypes_ep import make_transport

__all__ = ["UringEndpoint", "available", "build"]

build, _load, UringEndpoint = make_transport(
    "urep_", "uring_transport.cpp", "liburingtransport.so", "io_uring"
)
UringEndpoint.__name__ = "UringEndpoint"


def available() -> bool:
    """True when the lib builds AND the kernel grants an io_uring."""
    try:
        return bool(_load().urep_available())
    except Exception:
        return False
