"""ctypes wrapper for the io_uring transport (native/uring_transport.cpp).

``UringEndpoint`` is the second alternative fast-path transport behind
``pick_endpoint`` — the C28 slot: the reference ships two alternative
kernel-adjacent transports behind one feature seam (UCX,
madsim/src/std/net/ucx.rs:23-30; eRPC, std/net/erpc.rs:24-30). Here the
alternatives are the shared-memory ring (same-host) and this io_uring
proactor endpoint (cross-host capable, same wire format as the epoll
and asyncio backends, so all four interoperate).

Surface and threading model mirror std/native.py: blocking native
receives run on a thread-pool executor; payloads are pickled here.
"""

from __future__ import annotations

import asyncio
import ctypes
import os
import pickle
import subprocess
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE = os.path.join(_REPO, "native")
_LIB = os.path.join(_NATIVE, "lib", "liburingtransport.so")

__all__ = ["UringEndpoint", "available", "build"]


def build() -> str:
    src = os.path.join(_NATIVE, "uring_transport.cpp")
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(src):
        subprocess.run(["make", "-C", _NATIVE], check=True, capture_output=True)
    return _LIB


_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build())
        lib.urep_bind.restype = ctypes.c_void_p
        lib.urep_bind.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
        ]
        lib.urep_send.restype = ctypes.c_int
        lib.urep_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.urep_recv.restype = ctypes.c_void_p
        lib.urep_recv.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64]
        lib.urep_msg_len.restype = ctypes.c_uint64
        lib.urep_msg_len.argtypes = [ctypes.c_void_p]
        lib.urep_msg_data.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.urep_msg_data.argtypes = [ctypes.c_void_p]
        lib.urep_msg_src_ip.restype = ctypes.c_char_p
        lib.urep_msg_src_ip.argtypes = [ctypes.c_void_p]
        lib.urep_msg_src_port.restype = ctypes.c_int
        lib.urep_msg_src_port.argtypes = [ctypes.c_void_p]
        lib.urep_msg_free.argtypes = [ctypes.c_void_p]
        lib.urep_shutdown.argtypes = [ctypes.c_void_p]
        lib.urep_free.argtypes = [ctypes.c_void_p]
        lib.urep_available.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    """True when the lib builds AND the kernel grants an io_uring."""
    try:
        return bool(_load().urep_available())
    except Exception:
        return False


def _split(addr) -> tuple[str, int]:
    if isinstance(addr, tuple):
        return addr[0], int(addr[1])
    host, port = str(addr).rsplit(":", 1)
    return host, int(port)


class UringEndpoint:
    """Tag-matching endpoint on the io_uring proactor, asyncio-friendly."""

    def __init__(self, handle: int, port: int, host: str):
        self._h = handle
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="urep-recv"
        )
        self._closed = False

    @classmethod
    async def bind(cls, addr) -> "UringEndpoint":
        host, port = _split(addr)
        lib = _load()
        out_port = ctypes.c_int(0)
        h = lib.urep_bind(host.encode(), port, ctypes.byref(out_port))
        if not h:
            raise OSError(f"io_uring endpoint bind failed for {host}:{port}")
        return cls(h, out_port.value, host)

    @property
    def local_addr(self) -> tuple[str, int]:
        return (self._host, self._port)

    async def send_to(self, dst, tag: int, payload: Any) -> None:
        if self._closed:
            raise ConnectionError("endpoint is closed")
        if tag >= (1 << 64) - 1 or tag < 0:
            raise ValueError("tag must fit in 64 bits (top value reserved)")
        ip, port = _split(dst)
        raw = pickle.dumps(payload)
        rc = _load().urep_send(self._h, ip.encode(), port, tag, raw, len(raw))
        if rc != 0:
            raise ConnectionError(f"io_uring send to {ip}:{port} failed")

    async def recv_from(self, tag: int, timeout: Optional[float] = None):
        if self._closed:
            raise ConnectionError("endpoint is closed")
        loop = asyncio.get_event_loop()
        lib = _load()
        timeout_ms = -1 if timeout is None else max(int(timeout * 1000), 0)

        def blocking():
            return lib.urep_recv(self._h, tag, timeout_ms)

        m = await loop.run_in_executor(self._pool, blocking)
        if not m:
            if self._closed:
                raise ConnectionError("endpoint closed during receive")
            raise asyncio.TimeoutError(f"recv tag {tag} timed out")
        try:
            n = lib.urep_msg_len(m)
            data = ctypes.string_at(lib.urep_msg_data(m), n)
            src = (lib.urep_msg_src_ip(m).decode(), lib.urep_msg_src_port(m))
        finally:
            lib.urep_msg_free(m)
        return pickle.loads(data), src

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            lib = _load()
            lib.urep_shutdown(self._h)
            self._pool.shutdown(wait=True)
            lib.urep_free(self._h)
