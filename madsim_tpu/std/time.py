"""Real-clock backend with the simulated time API surface.

Parity with reference madsim/src/std/time.rs (C29): re-exports of the
real runtime's time operations under the sim API names.
"""

from __future__ import annotations

import asyncio
import time as _time

__all__ = ["sleep", "sleep_until", "timeout", "now", "now_ns", "Elapsed"]


class Elapsed(Exception):
    pass


async def sleep(seconds: float) -> None:
    await asyncio.sleep(seconds)


async def sleep_until(deadline_s: float) -> None:
    await asyncio.sleep(max(0.0, deadline_s - _time.monotonic()))  # lint: allow(wall-clock)


async def timeout(seconds: float, awaitable):
    try:
        return await asyncio.wait_for(awaitable, seconds)
    except asyncio.TimeoutError:
        raise Elapsed from None


def now() -> float:
    return _time.monotonic()  # lint: allow(wall-clock)


def now_ns() -> int:
    return _time.monotonic_ns()  # lint: allow(wall-clock)
