"""Real-network Endpoint: tag-matching messaging over asyncio TCP.

Parity with reference madsim/src/std/net/tcp.rs (C26):
  * ``Endpoint`` bound on a real TCP listener (tcp.rs:22-66)
  * lazy per-peer connections: the first send dials the peer and opens
    with an address-exchange handshake so the receiver can map the
    inbound connection to the sender's canonical (listening) address for
    replies (tcp.rs:70-135)
  * length-delimited frames (the reference's LengthDelimitedCodec):
    8-byte big-endian payload length | 8-byte big-endian tag | payload
    (pickled); the handshake uses tag 2^64-1 with an ASCII "ip:port"
    payload. The native C++ transport (native/transport.cpp) speaks the
    identical format, so asyncio and native endpoints interoperate
  * the same tag-matching mailbox semantics as the simulated Endpoint
    (sim/net/endpoint.rs:288-353), so application code moves between
    the two unchanged
  * typed RPC mirroring std/net/rpc.rs: pickled requests (their bincode
    analog), random response tags, handler loops

The API is intentionally identical to madsim_tpu.net.Endpoint's tag
surface: bind / send_to / recv_from / call / add_rpc_handler.
"""

from __future__ import annotations

import asyncio
import pickle
import random
import struct
from collections import deque
from typing import Any, Awaitable, Callable, Optional

from ..net.rpc import rpc_id

__all__ = ["Endpoint", "StdPipeSender", "StdPipeReceiver"]

_HEAD = struct.Struct(">QQ")  # payload length, tag
_HELLO_TAG = (1 << 64) - 1
_CONN_TAG = (1 << 64) - 2  # connection setup ("syn") messages

# asyncio streams default to a 64 KiB buffer limit; readexactly() of a
# larger frame then ping-pongs transport pause/resume every 64 KiB,
# which halved throughput at the 1 MiB bench size. 16 MiB keeps the
# reader ahead of the largest bench frame with room to spare.
_STREAM_LIMIT = 16 * 1024 * 1024

Addr = tuple[str, int]


def _parse(addr) -> Addr:
    if isinstance(addr, tuple):
        return (addr[0], int(addr[1]))
    host, port = str(addr).rsplit(":", 1)
    return (host, int(port))


class _Mailbox:
    """Tag-matching mailbox on asyncio futures (mirror of the sim's)."""

    def __init__(self) -> None:
        self.msgs: dict[int, deque] = {}
        self.waiters: dict[int, deque] = {}

    def deliver(self, tag: int, payload: Any, src: Addr) -> None:
        q = self.waiters.get(tag)
        while q:
            w = q.popleft()
            if not q:
                del self.waiters[tag]
            if not w.done():
                w.set_result((payload, src))
                return
        self.msgs.setdefault(tag, deque()).append((payload, src))

    def recv(self, tag: int) -> asyncio.Future:
        fut = asyncio.get_event_loop().create_future()
        q = self.msgs.get(tag)
        if q:
            payload, src = q.popleft()
            if not q:
                del self.msgs[tag]
            fut.set_result((payload, src))
        else:
            self.waiters.setdefault(tag, deque()).append(fut)
        return fut

    def drop_tag(self, tag: int) -> None:
        self.waiters.pop(tag, None)
        self.msgs.pop(tag, None)


class Endpoint:
    """``ep = await Endpoint.bind("0.0.0.0:5000")`` on the real network."""

    def __init__(self) -> None:
        self._server: Optional[asyncio.base_events.Server] = None
        self._addr: Addr = ("0.0.0.0", 0)
        self._mailbox = _Mailbox()
        self._peers: dict[Addr, asyncio.StreamWriter] = {}
        self._peer_locks: dict[Addr, asyncio.Lock] = {}
        self._reader_tasks: set = set()
        self._closed = False

    # ---- construction ---------------------------------------------------
    @classmethod
    async def bind(cls, addr) -> "Endpoint":
        host, port = _parse(addr)
        ep = cls()
        ep._server = await asyncio.start_server(
            ep._on_accept, host, port, limit=_STREAM_LIMIT
        )
        sock = ep._server.sockets[0]
        ep._addr = sock.getsockname()[:2]
        return ep

    @property
    def local_addr(self) -> Addr:
        return self._addr

    async def close(self) -> None:
        self._closed = True
        if self._server is not None:
            self._server.close()
        # cancel readers and close writers FIRST: py3.12 wait_closed()
        # blocks until every connection handler is done
        for t in list(self._reader_tasks):
            t.cancel()
        for w in list(self._peers.values()):
            w.close()
        self._peers.clear()
        if self._reader_tasks:
            await asyncio.gather(*self._reader_tasks, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()

    # ---- framing --------------------------------------------------------
    @staticmethod
    def _frame(tag: int, raw: bytes) -> bytes:
        return _HEAD.pack(len(raw), tag) + raw

    @staticmethod
    async def _read_frame(reader: asyncio.StreamReader) -> tuple[int, bytes]:
        head = await reader.readexactly(_HEAD.size)
        n, tag = _HEAD.unpack(head)
        raw = await reader.readexactly(n)
        return tag, raw

    # ---- connections ----------------------------------------------------
    async def _on_accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # register ourselves so close() can cancel pre-handshake
        # connections too (py3.12 wait_closed blocks on open handlers)
        me = asyncio.current_task()
        if me is not None:
            self._reader_tasks.add(me)
            me.add_done_callback(self._reader_tasks.discard)
        # inbound handshake: the peer announces its canonical listen addr
        # (the address-exchange of tcp.rs:70-135)
        try:
            tag, raw = await self._read_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            writer.close()
            return
        if tag != _HELLO_TAG:
            writer.close()
            return
        host, _, port = raw.decode().rpartition(":")
        peer_addr = (host, int(port))
        self._peers.setdefault(peer_addr, writer)
        task = asyncio.get_event_loop().create_task(
            self._read_loop(reader, writer, peer_addr)
        )
        self._reader_tasks.add(task)
        task.add_done_callback(self._reader_tasks.discard)

    async def _read_loop(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, peer: Addr
    ) -> None:
        try:
            while True:
                tag, raw = await self._read_frame(reader)
                if tag == _HELLO_TAG:
                    continue
                self._mailbox.deliver(tag, pickle.loads(raw), peer)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            writer.close()
            if self._peers.get(peer) is writer:
                del self._peers[peer]

    async def _writer_for(self, dst: Addr) -> asyncio.StreamWriter:
        lock = self._peer_locks.setdefault(dst, asyncio.Lock())
        async with lock:
            w = self._peers.get(dst)
            if w is not None and not w.is_closing():
                return w
            reader, writer = await asyncio.open_connection(
                dst[0], dst[1], limit=_STREAM_LIMIT
            )
            # announce a routable canonical address: a wildcard bind
            # (0.0.0.0) is meaningless to the peer, so substitute the
            # outgoing socket's local IP with our listening port
            host, port = self._addr
            if host in ("0.0.0.0", "::"):
                host = writer.get_extra_info("sockname")[0]
            writer.write(self._frame(_HELLO_TAG, f"{host}:{port}".encode()))
            await writer.drain()
            self._peers[dst] = writer
            task = asyncio.get_event_loop().create_task(
                self._read_loop(reader, writer, dst)
            )
            self._reader_tasks.add(task)
            task.add_done_callback(self._reader_tasks.discard)
            return writer

    # ---- connections (sim Endpoint connect1/accept1 parity) --------------
    async def connect1(self, dst) -> tuple["StdPipeSender", "StdPipeReceiver"]:
        """Open a reliable ordered duplex "connection" to a peer endpoint
        over the real network — the std mirror of the sim Endpoint's
        ``connect1`` (sim/net/endpoint.rs:176-209), so service clients
        written against the sim surface run on real TCP unchanged.

        The connection is a pair of direction tags multiplexed over this
        endpoint's TCP link; items ride as ("d", obj) with an ("eof",)
        sentinel for half-close. Unreachable peers fail fast (the TCP
        dial happens here)."""
        dst_a = _parse(dst)
        c2s = random.getrandbits(61) | (1 << 62)  # top bit clear: no clash
        s2c = c2s | (1 << 61)                     # with RPC response tags
        host, port = self._addr
        try:
            await self._send_tagged(dst_a, _CONN_TAG, ("syn", c2s, s2c, (host, port)))
        except OSError as e:
            raise ConnectionRefusedError(f"connect to {dst_a} failed: {e}") from e
        return (
            StdPipeSender(self, dst_a, c2s),
            StdPipeReceiver(self, s2c),
        )

    async def accept1(self) -> tuple["StdPipeSender", "StdPipeReceiver", Addr]:
        """Accept one connection (sim ``accept1`` mirror): returns
        (sender, receiver, peer_addr)."""
        (kind, c2s, s2c, reply_addr), src = await self._mailbox.recv(_CONN_TAG)
        assert kind == "syn"
        peer = (src[0], reply_addr[1]) if reply_addr[0] in ("0.0.0.0", "::") else tuple(reply_addr)
        return StdPipeSender(self, peer, s2c), StdPipeReceiver(self, c2s), peer

    # ---- tag-matching datagram surface ----------------------------------
    async def send_to(self, dst, tag: int, payload: Any) -> None:
        if tag >= _CONN_TAG or tag < 0:
            raise ValueError("the top two tag values are reserved")
        await self._send_tagged(_parse(dst), tag, payload)

    async def _send_tagged(self, dst: Addr, tag: int, payload: Any) -> None:
        writer = await self._writer_for(dst)
        raw = pickle.dumps(payload)
        # two writes, no head+raw concatenation: the asyncio transport
        # chains buffers, and skipping the join saves a full copy of
        # every large payload
        writer.write(_HEAD.pack(len(raw), tag))
        writer.write(raw)
        await writer.drain()

    async def recv_from(self, tag: int) -> tuple[Any, Addr]:
        return await self._mailbox.recv(tag)

    # ---- typed RPC (std/net/rpc.rs parity) -------------------------------
    async def call(self, dst, req: Any, timeout: Optional[float] = None) -> Any:
        resp, _ = await self.call_with_data(dst, req, b"", timeout=timeout)
        return resp

    async def call_with_data(
        self, dst, req: Any, data: bytes, timeout: Optional[float] = None
    ) -> tuple[Any, bytes]:
        resp_tag = random.getrandbits(63) | (1 << 63)
        while resp_tag == _HELLO_TAG:  # 2^64-1 is reserved for the handshake
            resp_tag = random.getrandbits(63) | (1 << 63)
        await self.send_to(dst, rpc_id(type(req)), (req, data, resp_tag))
        try:
            if timeout is not None:
                payload, _src = await asyncio.wait_for(
                    self._mailbox.recv(resp_tag), timeout
                )
            else:
                payload, _src = await self._mailbox.recv(resp_tag)
        except BaseException:
            self._mailbox.drop_tag(resp_tag)
            raise
        resp, resp_data = payload
        if isinstance(resp, BaseException):
            raise resp
        return resp, resp_data

    def add_rpc_handler(
        self, req_type: type, handler: Callable[[Any], Awaitable[Any]]
    ) -> None:
        async def with_data(req: Any, _data: bytes) -> tuple[Any, bytes]:
            return await handler(req), b""

        self.add_rpc_handler_with_data(req_type, with_data)

    def add_rpc_handler_with_data(
        self,
        req_type: type,
        handler: Callable[[Any, bytes], Awaitable[tuple[Any, bytes]]],
    ) -> None:
        tag = rpc_id(req_type)
        loop = asyncio.get_event_loop()

        async def serve_loop():
            while True:
                (req, data, resp_tag), src = await self._mailbox.recv(tag)

                async def handle(req=req, data=data, resp_tag=resp_tag, src=src):
                    try:
                        resp, resp_data = await handler(req, data)
                    except Exception as exc:  # noqa: BLE001 - travels back
                        resp, resp_data = exc, b""
                    await self.send_to(src, resp_tag, (resp, resp_data))

                # hold a strong ref: the loop only weakly references
                # tasks and a mid-flight handler could be GC'd
                t = loop.create_task(handle())
                self._reader_tasks.add(t)
                t.add_done_callback(self._reader_tasks.discard)

        task = loop.create_task(serve_loop())
        self._reader_tasks.add(task)
        task.add_done_callback(self._reader_tasks.discard)


class StdPipeSender:
    """Sending half of a std connection — duck-types the sim
    ``PipeSender`` (send / shutdown / close / is_closed) so code written
    against sim connections runs on the real network."""

    __slots__ = ("_ep", "_dst", "_tag", "_closed")

    def __init__(self, ep: Endpoint, dst: Addr, tag: int):
        self._ep = ep
        self._dst = dst
        self._tag = tag
        self._closed = False

    async def send(self, payload: Any) -> None:
        if self._closed:
            raise ConnectionResetError("connection closed")
        await self._ep._send_tagged(self._dst, self._tag, ("d", payload))

    def is_closed(self) -> bool:
        return self._closed

    def _send_eof(self) -> None:
        if self._closed:
            return
        self._closed = True
        loop = asyncio.get_event_loop()
        t = loop.create_task(self._ep._send_tagged(self._dst, self._tag, ("eof",)))
        self._ep._reader_tasks.add(t)
        t.add_done_callback(self._ep._reader_tasks.discard)

    def shutdown(self) -> None:
        """Half-close: the peer reads EOF after in-flight items."""
        self._send_eof()

    def close(self) -> None:
        """Close the write direction (the receiver half is closed by its
        own ``close``; unlike the sim there is no shared group object)."""
        self._send_eof()


class StdPipeReceiver:
    """Receiving half of a std connection; ``recv`` returns None on EOF."""

    __slots__ = ("_ep", "_tag", "_eof")

    def __init__(self, ep: Endpoint, tag: int):
        self._ep = ep
        self._tag = tag
        self._eof = False

    async def recv(self) -> Any | None:
        if self._eof:
            return None
        item, _src = await self._ep._mailbox.recv(self._tag)
        if item[0] == "eof":
            self._eof = True
            self._ep._mailbox.drop_tag(self._tag)
            return None
        return item[1]

    def close(self) -> None:
        self._eof = True
        self._ep._mailbox.drop_tag(self._tag)
