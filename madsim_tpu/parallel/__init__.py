"""Seed-axis sharding over TPU device meshes.

The reference scales by running `MADSIM_TEST_NUM` seeds across
`MADSIM_TEST_JOBS` OS threads, one runtime per thread (reference
madsim/src/sim/runtime/builder.rs:110-148). The TPU-native scaling axis
is the same logical thing mapped to hardware: the seed batch is sharded
over a `jax.sharding.Mesh`, every chip advances its shard of seeds in
lockstep, and XLA inserts zero collectives in the hot loop because the
work is embarrassingly parallel along the seed axis — ICI/DCN are only
touched when results are gathered.

A 2D ('host', 'chip') mesh mirrors the DCN x ICI hierarchy: the seed
axis is sharded over both, so placement composes with multi-host
deployments the way data parallelism does in the scaling playbook.
"""

from __future__ import annotations

import inspect

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map graduated from jax.experimental after 0.4.x; the
# replication-check kwarg was later renamed (check_rep -> check_vma),
# NOT at the graduation boundary — so pick the kwarg by the resolved
# function's own signature, not by which spelling exists. The pinned
# jax (0.4.37) still resolves the pre-graduation fallback, so BOTH
# halves are live code paths: tests/test_parallel.py regression-tests
# the selection against both signatures instead of collapsing it.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pre-graduation JAX (e.g. 0.4.37)
    from jax.experimental.shard_map import shard_map as _shard_map


def _nocheck_kwargs(fn) -> dict:
    """The replication-check-off kwarg for this jax's ``shard_map``.

    Keyed on the resolved function's own signature (``check_vma`` on
    current jax, ``check_rep`` before the rename); a wrapped/builtin
    signature we cannot introspect assumes the current spelling.
    """
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return {"check_vma": False}
    return (
        {"check_vma": False} if "check_vma" in params
        else {"check_rep": False}
    )


_SM_NOCHECK = _nocheck_kwargs(_shard_map)


def shard_map_nocheck(fn, mesh, in_specs, out_specs):
    """``shard_map`` with the replication checker off — the repo's one
    spelling of the pattern (handler branches legitimately mix
    mesh-constant emits with shard-varying values, which the varying-
    axes checker rejects; correctness is asserted value-wise by the
    sharded == unsharded tests instead). Returns the unjitted mapped
    function; callers jit it themselves."""
    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **_SM_NOCHECK,
    )


__all__ = [
    "make_mesh",
    "merge_coverage",
    "merge_latency",
    "merge_metrics",
    "merge_verdicts",
    "seed_sharding",
    "shard_map_nocheck",
    "shard_state",
    "shard_over_seeds",
    "shard_run_compacted",
]


def make_mesh(devices=None, hosts: int | None = None) -> Mesh:
    """Build a ('host', 'chip') mesh over the given (default: all) devices.

    ``hosts`` defaults to the actual process/host count when running
    multi-host, else 1; the remaining factor becomes the chip axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if hosts is None:
        hosts = getattr(jax, "process_count", lambda: 1)()
        if n % hosts != 0:
            hosts = 1
    grid = np.asarray(devices).reshape(hosts, n // hosts)
    return Mesh(grid, axis_names=("host", "chip"))


def seed_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that splits the leading (seed) axis across every mesh axis."""
    return NamedSharding(mesh, P(mesh.axis_names))


def shard_state(state, mesh: Mesh):
    """Place a batched SimState so its seed axis is split across the mesh.

    Every leaf of the state pytree has seeds leading, so one sharding
    applies uniformly.
    """
    sh = seed_sharding(mesh)
    return jax.device_put(state, sh)


def shard_over_seeds(fn, mesh: Mesh):
    """Compile ``fn(state) -> state`` with the seed axis sharded over ``mesh``.

    GSPMD partitions the whole scan along the seed axis; each device runs
    its shard of independent simulations with no cross-device
    communication inside the loop.
    """
    sh = seed_sharding(mesh)
    # a single sharding is a valid pytree prefix: it broadcasts to every
    # leaf of the SimState, all of which lead with the seed axis
    return jax.jit(fn, in_shardings=sh, out_shardings=sh)


def merge_coverage(bitmaps, mesh: Mesh | None = None) -> np.ndarray:
    """OR-fold per-seed coverage bitmaps (S, CW) into one (CW,) map.

    With a ``mesh``, each device OR-folds its local seed shard
    (``shard_map``, no cross-device traffic — XLA's collective reducers
    don't implement bitwise OR, so the final fold of the D per-device
    rows happens on the host, D*CW words of transfer). The sharded
    coverage merge of a multi-chip exploration sweep
    (madsim_tpu.explore): a 65k-seed generation's bitmaps reduce on the
    mesh and only device-count rows reach the host. Without a mesh, the
    same reduction runs on the default device. ``S`` must divide over
    the mesh's device count.
    """
    import jax.numpy as jnp
    from jax import lax

    bm = jnp.asarray(bitmaps, jnp.uint32)
    if bm.ndim != 2:
        raise ValueError(f"bitmaps must be (S, CW), got shape {bm.shape}")

    def fold(b):
        return lax.reduce(b, jnp.uint32(0), lax.bitwise_or, (0,))

    if mesh is None:
        return np.asarray(jax.jit(fold)(bm))
    n_dev = mesh.devices.size
    if bm.shape[0] % n_dev:
        raise ValueError(
            f"{bm.shape[0]} bitmap rows do not split over {n_dev} devices"
        )
    spec = P(mesh.axis_names)
    local = lambda b: fold(b)[None, :]  # noqa: E731 — (1, CW) per device
    per_dev = jax.jit(
        _shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec,
                   **_SM_NOCHECK)
    )(bm)
    return np.bitwise_or.reduce(np.asarray(per_dev, np.uint32), axis=0)


def merge_metrics(met, mesh: Mesh | None = None) -> np.ndarray:
    """Sum-fold per-seed fleet-metric columns (S, M) into (M,) totals.

    The metrics analog of :func:`merge_coverage`: with a ``mesh``, each
    device sums its local seed shard (``shard_map``, zero cross-device
    traffic) and only device-count rows reach the host — a 65k-seed
    sweep's fleet totals cost D*M words of transfer. int64 accumulation
    so 32-bit per-seed counters can't overflow the fleet sum. The
    MET_HALT_CODE slot is summed like any other (meaningless as a
    total); use ``obs.fleet_reduce`` when the halt-code distribution or
    histograms are wanted.
    """
    import jax.numpy as jnp

    mm = jnp.asarray(met)
    if mm.ndim != 2:
        raise ValueError(f"met must be (S, M), got shape {mm.shape}")

    def fold(m):
        return jnp.sum(m.astype(jnp.int64), axis=0)

    if mesh is None:
        return np.asarray(jax.jit(fold)(mm))
    n_dev = mesh.devices.size
    if mm.shape[0] % n_dev:
        raise ValueError(
            f"{mm.shape[0]} metric rows do not split over {n_dev} devices"
        )
    spec = P(mesh.axis_names)
    local = lambda m: fold(m)[None, :]  # noqa: E731 — (1, M) per device
    per_dev = jax.jit(
        _shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec,
                   **_SM_NOCHECK)
    )(mm)
    return np.asarray(per_dev, np.int64).sum(axis=0)


def merge_latency(lat_hist, mesh: Mesh | None = None) -> np.ndarray:
    """Sum-fold per-seed latency sketches (S, P, B) into (P, B) totals.

    The tail analog of :func:`merge_metrics`: with a ``mesh``, each
    device sums its local seed shard (``shard_map``, zero cross-device
    traffic) and only device-count sketch pages reach the host — the
    ladder histogram is *exactly mergeable* (integer addition), so the
    sharded fold equals the sketch of the concatenated batch bit for
    bit, which is what lets pod-scale campaigns keep fleet tail
    analysis device-resident. int64 accumulation so 32-bit per-seed
    counts cannot overflow the fleet sum.
    """
    import jax.numpy as jnp

    hh = jnp.asarray(lat_hist)
    if hh.ndim != 3:
        raise ValueError(f"lat_hist must be (S, P, B), got shape {hh.shape}")

    def fold(h):
        return jnp.sum(h.astype(jnp.int64), axis=0)

    if mesh is None:
        return np.asarray(jax.jit(fold)(hh))
    n_dev = mesh.devices.size
    if hh.shape[0] % n_dev:
        raise ValueError(
            f"{hh.shape[0]} sketch rows do not split over {n_dev} devices"
        )
    spec = P(mesh.axis_names)
    local = lambda h: fold(h)[None]  # noqa: E731 — (1, P, B) per device
    per_dev = jax.jit(
        _shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec,
                   **_SM_NOCHECK)
    )(hh)
    return np.asarray(per_dev, np.int64).sum(axis=0)


def merge_verdicts(ok, mesh: Mesh | None = None) -> np.ndarray:
    """Pack per-seed verdicts (S,) bool into (S/32,) uint32 words.

    The verdict analog of :func:`merge_metrics`: with a ``mesh``, each
    device packs its LOCAL seed shard's verdicts
    (``check.device.pack_verdicts`` under ``shard_map``, zero
    cross-device traffic — seed shards are contiguous, so the word
    arrays concatenate in seed order) and only S/32 words reach the
    host — a 65k-seed sweep's history verdicts cost 2 KiB of transfer.
    Seeds must split over the devices in multiples of 32 (word
    alignment); unpack host-side with
    ``check.device.unpack_verdicts``.
    """
    import jax.numpy as jnp

    from ..check.device import pack_verdicts

    okb = jnp.asarray(ok, jnp.bool_)
    if okb.ndim != 1:
        raise ValueError(f"ok must be (S,), got shape {okb.shape}")
    if mesh is None:
        return np.asarray(jax.jit(pack_verdicts)(okb))
    n_dev = mesh.devices.size
    local = okb.shape[0] // n_dev if n_dev else 0
    if n_dev == 0 or okb.shape[0] % n_dev or local % 32:
        raise ValueError(
            f"{okb.shape[0]} verdicts do not split over {n_dev} devices "
            f"in word-aligned (multiple-of-32) shards"
        )
    spec = P(mesh.axis_names)
    per_dev = jax.jit(
        _shard_map(pack_verdicts, mesh=mesh, in_specs=spec, out_specs=spec,
                   **_SM_NOCHECK)
    )(okb)
    return np.asarray(per_dev, np.uint32)


def shard_run_compacted(
    wl,
    cfg,
    max_steps: int,
    mesh: Mesh,
    layout: str | None = None,
    time32: bool | None = None,
    shrink: int = 4,
    min_size: int = 2048,
    fields: tuple | None = None,
    latency=None,
    hist_screen=None,
):
    """Multi-chip form of :func:`engine.make_run_compacted`.

    ``shard_map`` runs the whole phase program *per device*: each chip
    compacts its local seed shard independently (its while_loops trip
    on local live counts), so there is zero cross-device traffic in the
    hot loop — the reference's one-thread-per-seed "finished seeds stop
    consuming CPU" economy, at mesh scale. Local phase boundaries fall
    at different steps than a global run's would, but rows are
    independent, so per-seed results are bit-identical to the unsharded
    runner (tests/test_parallel.py asserts it).

    Returns ``run(state) -> SimpleNamespace`` of per-original-seed
    numpy arrays, like the single-device runner. ``state`` should be
    placed with :func:`shard_state` (an unsharded state works too — jit
    reshards it to the declared input sharding).

    ``hist_screen`` runs the device history detectors + prefix-
    compaction at bank time PER DEVICE (the ``make_run_compacted``
    contract, inside ``shard_map``): each chip screens and folds its
    own banked rows with zero cross-device traffic, and the assembled
    host result carries the same ``hist_ok``/``hist_fold`` columns —
    bit-identical to the unsharded screened runner.
    """
    from ..engine import compact as _compact

    kw = {} if fields is None else {"fields": fields}
    base = _compact.make_run_compacted(
        wl, cfg, max_steps, layout, time32, shrink=shrink,
        min_size=min_size, latency=latency, hist_screen=hist_screen, **kw,
    )
    n_dev = mesh.devices.size
    spec = P(mesh.axis_names)

    def local(state):
        # global row offset of this device's shard: axis_index over the
        # full axis tuple is the major-order linearized device id, the
        # same order seed_sharding splits the seed axis in — works for
        # any mesh rank
        dev = jax.lax.axis_index(mesh.axis_names)
        local_rows = state.seed.shape[0]
        return base.phases(state, idx_offset=dev * local_rows)

    # check_vma=False: handler branches legitimately mix mesh-constant
    # emits (static rows from EmitBuilder) with shard-varying values;
    # the varying-axes checker would reject those lax.switch branches.
    # Correctness is asserted value-wise instead (sharded == unsharded,
    # tests/test_parallel.py)
    sharded = jax.jit(
        _shard_map(
            local, mesh=mesh, in_specs=spec, out_specs=spec, **_SM_NOCHECK
        )
    )

    def compute(state):
        if state.seed.shape[0] % n_dev:
            raise ValueError(
                f"{state.seed.shape[0]} seeds do not split over "
                f"{n_dev} devices"
            )
        return sharded(state)

    def run(state):
        return base.assemble(jax.block_until_ready(compute(state)))

    run.compute = compute
    run.assemble = base.assemble
    return run
