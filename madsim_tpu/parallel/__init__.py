"""Seed-axis sharding over TPU device meshes.

The reference scales by running `MADSIM_TEST_NUM` seeds across
`MADSIM_TEST_JOBS` OS threads, one runtime per thread (reference
madsim/src/sim/runtime/builder.rs:110-148). The TPU-native scaling axis
is the same logical thing mapped to hardware: the seed batch is sharded
over a `jax.sharding.Mesh`, every chip advances its shard of seeds in
lockstep, and XLA inserts zero collectives in the hot loop because the
work is embarrassingly parallel along the seed axis — ICI/DCN are only
touched when results are gathered.

A 2D ('host', 'chip') mesh mirrors the DCN x ICI hierarchy: the seed
axis is sharded over both, so placement composes with multi-host
deployments the way data parallelism does in the scaling playbook.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "seed_sharding", "shard_state", "shard_over_seeds"]


def make_mesh(devices=None, hosts: int | None = None) -> Mesh:
    """Build a ('host', 'chip') mesh over the given (default: all) devices.

    ``hosts`` defaults to the actual process/host count when running
    multi-host, else 1; the remaining factor becomes the chip axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if hosts is None:
        hosts = getattr(jax, "process_count", lambda: 1)()
        if n % hosts != 0:
            hosts = 1
    grid = np.asarray(devices).reshape(hosts, n // hosts)
    return Mesh(grid, axis_names=("host", "chip"))


def seed_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that splits the leading (seed) axis across every mesh axis."""
    return NamedSharding(mesh, P(mesh.axis_names))


def shard_state(state, mesh: Mesh):
    """Place a batched SimState so its seed axis is split across the mesh.

    Every leaf of the state pytree has seeds leading, so one sharding
    applies uniformly.
    """
    sh = seed_sharding(mesh)
    return jax.device_put(state, sh)


def shard_over_seeds(fn, mesh: Mesh):
    """Compile ``fn(state) -> state`` with the seed axis sharded over ``mesh``.

    GSPMD partitions the whole scan along the seed axis; each device runs
    its shard of independent simulations with no cross-device
    communication inside the loop.
    """
    sh = seed_sharding(mesh)
    # a single sharding is a valid pytree prefix: it broadcasts to every
    # leaf of the SimState, all of which lead with the seed axis
    return jax.jit(fn, in_shardings=sh, out_shardings=sh)
