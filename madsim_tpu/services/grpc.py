"""gRPC-style typed services over the simulated network.

Parity with the reference's madsim-tonic (madsim-tonic/src/):
  * ``Server``/``Router`` builder that accepts connections and routes on
    the request path "/package.Service/Method"
    (transport/server.rs:24-260)
  * ``Channel`` obtained from ``Endpoint.connect`` with a handshake that
    fails fast on unreachable addresses (transport/channel.rs:50-64)
  * the four call shapes: unary, client-streaming, server-streaming,
    bidirectional (client.rs:29-124)
  * ``Streaming`` response iterator (codec.rs:13-48)
  * ``Status``/``Code`` errors; a killed server surfaces as
    ``UNAVAILABLE`` at the client, the semantics the reference's
    server_crash test asserts (tonic-example/src/server.rs:371-405)

Messages travel as plain Python objects over Endpoint connections — the
analog of the reference's ``BoxMessage = Box<dyn Any>`` zero-copy payloads
(sim.rs:27-29): no serialization inside the simulation.

Instead of protoc codegen (madsim-tonic-build), services are plain Python
classes: public async methods become RPC methods; routing keys are
"/ClassName/method". The :func:`service_client` factory plays the role of
the generated client stub.

Cross-refs are to /root/reference files; behavior matched, code new.
"""

from __future__ import annotations

import enum
import inspect
from typing import Any, Callable, Optional

from ..net.addr import AddrLike, SocketAddr, parse_addr
from ..runtime.future import SimFuture
from ..sync import ChannelClosed
from ._dual import bind_endpoint, in_sim, spawn

__all__ = [
    "Code",
    "Status",
    "Request",
    "Response",
    "Streaming",
    "Server",
    "Router",
    "Channel",
    "connect",
    "service_client",
]


class Code(enum.IntEnum):
    """gRPC status codes (the subset the simulator produces)."""

    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    NOT_FOUND = 5
    ALREADY_EXISTS = 6
    PERMISSION_DENIED = 7
    RESOURCE_EXHAUSTED = 8
    FAILED_PRECONDITION = 9
    ABORTED = 10
    OUT_OF_RANGE = 11
    UNIMPLEMENTED = 12
    INTERNAL = 13
    UNAVAILABLE = 14
    DATA_LOSS = 15
    UNAUTHENTICATED = 16


class Status(Exception):
    """RPC error status (the reuse of real tonic::Status, sim.rs:2-4)."""

    def __init__(self, code: Code, message: str = ""):
        super().__init__(f"{code.name}: {message}")
        self.code = code
        self.message = message

    # constructors mirroring tonic::Status::*
    @classmethod
    def unavailable(cls, msg: str = "") -> "Status":
        return cls(Code.UNAVAILABLE, msg)

    @classmethod
    def not_found(cls, msg: str = "") -> "Status":
        return cls(Code.NOT_FOUND, msg)

    @classmethod
    def unimplemented(cls, msg: str = "") -> "Status":
        return cls(Code.UNIMPLEMENTED, msg)

    @classmethod
    def internal(cls, msg: str = "") -> "Status":
        return cls(Code.INTERNAL, msg)

    @classmethod
    def deadline_exceeded(cls, msg: str = "") -> "Status":
        return cls(Code.DEADLINE_EXCEEDED, msg)

    @classmethod
    def cancelled(cls, msg: str = "") -> "Status":
        return cls(Code.CANCELLED, msg)


class Request:
    """Request wrapper carrying the message and the caller's address
    (the remote_addr extension of sim.rs:35-42)."""

    __slots__ = ("message", "remote_addr", "metadata")

    def __init__(self, message: Any, remote_addr: Optional[SocketAddr] = None,
                 metadata: Optional[dict] = None):
        self.message = message
        self.remote_addr = remote_addr
        self.metadata = metadata or {}

    def into_inner(self) -> Any:
        return self.message


class Response:
    __slots__ = ("message", "metadata")

    def __init__(self, message: Any, metadata: Optional[dict] = None):
        self.message = message
        self.metadata = metadata or {}

    def into_inner(self) -> Any:
        return self.message


# wire markers (one connection per call, like Grpc::unary/streaming,
# client.rs:29-124)
_MSG = "msg"  # ("msg", payload)
_END = "end"  # ("end",)
_ERR = "err"  # ("err", Status)


class Streaming:
    """Async iterator over a stream of response (or request) messages
    (codec.rs:13-48). Ends on the end marker; raises Status on error;
    a dropped/reset peer surfaces UNAVAILABLE."""

    def __init__(self, rx, own_connection: bool = True):
        self._rx = rx
        self._done = False
        # server-side request streams must not close the connection when
        # the request stream ends — the reply still travels back over it
        self._own = own_connection

    def __aiter__(self) -> "Streaming":
        return self

    async def __anext__(self) -> Any:
        if self._done:
            raise StopAsyncIteration
        try:
            item = await self._rx.recv()
        except (ChannelClosed, EOFError, ConnectionError):
            self._finish()
            raise Status.unavailable("connection reset by peer") from None
        if item is None:
            self._finish()
            raise Status.unavailable("connection reset by peer")
        kind = item[0]
        if kind == _MSG:
            return item[1]
        if kind == _END:
            self._finish()
            raise StopAsyncIteration
        self._finish()
        raise item[1]

    def _finish(self) -> None:
        """Stream over: release the per-call connection (both directions)
        so calls don't accumulate pipes/pump tasks."""
        self._done = True
        if self._own:
            self._rx.close()

    async def message(self) -> Optional[Any]:
        """tonic-style: next message or None at end of stream."""
        try:
            return await self.__anext__()
        except StopAsyncIteration:
            return None


def _route_key(service_name: str, method: str) -> str:
    return f"/{service_name}/{method}"


def _classify(func: Callable, owner: Optional[type] = None) -> str:
    """unary | client_stream | server_stream | bidi.

    Explicit ``__rpc_shape__`` markers win (set by the .proto codegen,
    grpc_codegen.py — checked through the owner's MRO so user overrides
    of generated servicer methods keep the declared shape); otherwise
    classify by signature: an async-generator handler streams responses,
    and a handler whose single argument is annotated/named as a stream
    consumes a request stream."""
    marked = getattr(func, "__rpc_shape__", None)
    if marked is not None:
        return marked
    if owner is not None:
        name = getattr(func, "__name__", None)
        for klass in getattr(owner, "__mro__", ()):
            base = klass.__dict__.get(name)
            if base is not None and getattr(base, "__rpc_shape__", None):
                return base.__rpc_shape__
    wants_stream = False
    params = [
        p
        for p in inspect.signature(func).parameters.values()
        if p.name not in ("self",)
    ]
    if params:
        p0 = params[0]
        ann = str(p0.annotation).lower()
        wants_stream = "streaming" in ann or p0.name in ("stream", "requests")
    produces_stream = inspect.isasyncgenfunction(func)
    if produces_stream:
        return "bidi" if wants_stream else "server_stream"
    return "client_stream" if wants_stream else "unary"


class Router:
    """Accumulated services + the accept loop
    (transport/server.rs:156-260)."""

    local_addr = None  # set once serving (bind port 0, read it here)

    def __init__(self) -> None:
        self._services: dict[str, Any] = {}

    def add_service(self, svc: Any, name: Optional[str] = None) -> "Router":
        svc_name = name or getattr(svc, "SERVICE_NAME", type(svc).__name__)
        self._services[svc_name] = svc
        return self

    async def serve(self, addr: AddrLike) -> None:
        await self.serve_with_shutdown(addr, None)

    async def serve_with_shutdown(
        self, addr: AddrLike, signal: Optional[SimFuture]
    ) -> None:
        """Bind and accept until ``signal`` resolves (server.rs:202-260).
        Each accepted connection carries exactly one call."""
        ep = await bind_endpoint(addr)
        # bind port 0 and read the real port from here (test de-flaking)
        self.local_addr = ep.local_addr
        loop = spawn(self._accept_loop(ep), name="grpc-accept-loop")
        if signal is None:
            await loop
            return
        if in_sim():
            from ..runtime.future import select

            idx, _ = await select(loop._handle._fut, signal)
            if idx == 1:
                loop.cancel()
        else:
            import asyncio as _aio

            sig = _aio.ensure_future(signal)
            done, _pending = await _aio.wait(
                [loop, sig], return_when=_aio.FIRST_COMPLETED
            )
            if sig in done:
                loop.cancel()

    async def _accept_loop(self, ep) -> None:
        while True:
            tx, rx, peer = await ep.accept1()
            spawn(self._serve_conn(tx, rx, peer), name="grpc-conn")

    async def _serve_conn(self, tx, rx, peer) -> None:
        try:
            first = await rx.recv()
        except (ChannelClosed, EOFError, ConnectionError):
            return
        if first is None or first[0] != "call":
            return
        _, path, payload = first
        try:
            _, svc_name, method_name = path.split("/")
            svc = self._services[svc_name]
            func = getattr(svc, method_name)
            if method_name.startswith("_") or not callable(func):
                raise KeyError(method_name)
            shape = _classify(func, owner=type(svc))
        except (ValueError, KeyError, AttributeError, TypeError):
            try:
                await tx.send((_ERR, Status.unimplemented(f"unknown path {path}")))
            except (ChannelClosed, ConnectionError):
                pass
            finally:
                tx.shutdown()
            return

        try:
            if shape == "unary":
                resp = await func(Request(payload, peer))
                await tx.send((_MSG, _unwrap(resp)))
                await tx.send((_END,))
            elif shape == "client_stream":
                resp = await func(Streaming(rx, own_connection=False))
                await tx.send((_MSG, _unwrap(resp)))
                await tx.send((_END,))
            elif shape == "server_stream":
                async for item in func(Request(payload, peer)):
                    await tx.send((_MSG, _unwrap(item)))
                await tx.send((_END,))
            else:  # bidi
                async for item in func(Streaming(rx, own_connection=False)):
                    await tx.send((_MSG, _unwrap(item)))
                await tx.send((_END,))
        except Status as status:
            try:
                await tx.send((_ERR, status))
            except (ChannelClosed, ConnectionError):
                pass
        except (ChannelClosed, EOFError, ConnectionError):
            # peer went away mid-call (client crash/drop): nothing to do —
            # the reference's client_crash test relies on the server
            # surviving this (tonic-example/src/server.rs:283-331)
            pass
        finally:
            # one call per connection: half-close so the queued reply
            # still drains through the pump, then the client's close of
            # its receiving end releases the whole group
            tx.shutdown()


def _unwrap(resp: Any) -> Any:
    return resp.message if isinstance(resp, Response) else resp


class Server:
    """Server builder (transport/server.rs:24-152). The reference accepts
    ~15 HTTP/2 tuning knobs and ignores them all in simulation; kwargs
    are accepted and ignored here for the same drop-in reason."""

    def __init__(self, **_ignored: Any) -> None:
        self._router = Router()

    @staticmethod
    def builder(**kwargs: Any) -> "Server":
        return Server(**kwargs)

    def add_service(self, svc: Any, name: Optional[str] = None) -> Router:
        return self._router.add_service(svc, name)


class Channel:
    """A connected-on-demand client channel (transport/channel.rs:12-64).

    Connecting performs one handshake connection so unreachable
    addresses fail fast with UNAVAILABLE, then each call opens its own
    connection (client.rs:29-53 does the same per-call connect1)."""

    def __init__(self, ep, dst: SocketAddr):
        self._ep = ep
        self._dst = dst

    @classmethod
    async def connect(cls, dst: AddrLike) -> "Channel":
        ep = await bind_endpoint("0.0.0.0:0")
        dst_a = parse_addr(dst)
        try:
            tx, _rx = await ep.connect1(dst_a)
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(f"failed to connect to {dst_a}: {e}") from e
        tx.close()
        return cls(ep, dst_a)

    async def close(self) -> None:
        """Release the channel's endpoint (sockets/reader tasks on the
        std backend; a port-table entry in simulation)."""
        res = self._ep.close()
        if res is not None and hasattr(res, "__await__"):
            await res

    async def _open(self):
        try:
            return await self._ep.connect1(self._dst)
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(str(e)) from e

    # ---- the four call shapes (client.rs:29-124) ------------------------
    async def unary(self, path: str, msg: Any, timeout: Optional[float] = None) -> Any:
        tx, rx = await self._open()
        try:
            await tx.send(("call", path, msg))
        except (ChannelClosed, ConnectionError) as e:
            raise Status.unavailable(str(e)) from e
        stream = Streaming(rx)
        if timeout is not None:
            from ..runtime.time_ import Elapsed
            from ..runtime.time_ import timeout as timeout_

            try:
                return await timeout_(timeout, stream.__anext__())
            except Elapsed:
                # release the abandoned per-call connection, or retry
                # loops under partition leak pipes+pump tasks per attempt
                stream._finish()
                raise Status.deadline_exceeded(path) from None
        return await stream.__anext__()

    async def client_streaming(self, path: str) -> tuple["_SendHalf", "_UnaryReply"]:
        tx, rx = await self._open()
        await tx.send(("call", path, None))
        return _SendHalf(tx), _UnaryReply(Streaming(rx))

    async def server_streaming(self, path: str, msg: Any) -> Streaming:
        tx, rx = await self._open()
        await tx.send(("call", path, msg))
        return Streaming(rx)

    async def bidi(self, path: str) -> tuple["_SendHalf", Streaming]:
        tx, rx = await self._open()
        await tx.send(("call", path, None))
        return _SendHalf(tx), Streaming(rx)


class _SendHalf:
    """Client-side request stream (send_request_stream, client.rs:126-146)."""

    def __init__(self, tx):
        self._tx = tx

    async def send(self, msg: Any) -> None:
        try:
            await self._tx.send((_MSG, msg))
        except (ChannelClosed, ConnectionError) as e:
            raise Status.unavailable(str(e)) from e

    async def finish(self) -> None:
        try:
            await self._tx.send((_END,))
        except (ChannelClosed, ConnectionError):
            pass

    def drop(self) -> None:
        """Abandon the stream without finishing (the client-drops-stream
        scenario, tonic-example/src/server.rs:333-369)."""
        self._tx.close()


class _UnaryReply:
    """Awaitable single reply to a client-streaming call."""

    def __init__(self, stream: Streaming):
        self._stream = stream

    def __await__(self):
        return self._stream.__anext__().__await__()


async def connect(dst: AddrLike) -> Channel:
    """Shorthand: ``channel = await grpc.connect("10.0.0.1:50051")``."""
    return await Channel.connect(dst)


def service_client(service: type | str, channel: Channel):
    """Generated-client analog (madsim-tonic-build/src/client.rs): returns
    an object with one async method per public async method of
    ``service``, routing to "/ServiceName/method".

    unary:           await client.say_hello(msg)
    server-stream:   stream = await client.lots_of_replies(msg)
    client-stream:   tx, reply = await client.record(); await tx.send(..)
    bidi:            tx, stream = await client.chat()
    """
    if isinstance(service, str):
        raise TypeError("pass the service class so call shapes are known")
    svc_name = getattr(service, "SERVICE_NAME", service.__name__)

    class _Client:
        def __init__(self) -> None:
            self.channel = channel

    for name, func in inspect.getmembers(service, inspect.isfunction):
        if name.startswith("_"):
            continue
        # owner=service: overrides of codegen servicer methods keep the
        # declared shape on the client side too (matching the Router)
        shape = _classify(func, owner=service)
        path = _route_key(svc_name, name)

        def make(shape: str, path: str):
            if shape == "unary":

                async def call(self, msg: Any = None, timeout: Optional[float] = None):
                    return await self.channel.unary(path, msg, timeout=timeout)

            elif shape == "server_stream":

                async def call(self, msg: Any = None):
                    return await self.channel.server_streaming(path, msg)

            elif shape == "client_stream":

                async def call(self):
                    return await self.channel.client_streaming(path)

            else:

                async def call(self):
                    return await self.channel.bidi(path)

            return call

        setattr(_Client, name, make(shape, path))

    _Client.__name__ = f"{svc_name}Client"
    return _Client()
