"""gRPC code generation from .proto service definitions.

Parity with the reference's madsim-tonic-build (C23): the reference
forks tonic's protoc codegen to emit simulator client/server stubs from
.proto files (madsim-tonic-build/src/prost.rs:326-330, server.rs:11-128,
client.rs:10+). The analog here reads the ``service`` blocks out of a
.proto file and generates, at runtime:

  * ``<Name>Servicer`` — a base class whose methods raise UNIMPLEMENTED
    until overridden (the async_trait service trait, server.rs:144-163),
    carrying ``SERVICE_NAME = "package.Name"`` and per-method call-shape
    markers;
  * ``<Name>Client`` — a channel-bound client factory with one method
    per rpc, honoring ``stream`` on either side (client.rs generate).

Messages are not compiled: inside the simulation payloads travel as
plain Python objects (the BoxMessage = Box<dyn Any> design, sim.rs:
27-29), so message blocks in the .proto are intentionally ignored —
hand the methods dicts or your own classes.

    ns = compile_proto("proto/helloworld.proto")
    class MyGreeter(ns.GreeterServicer):
        async def say_hello(self, request): ...
    client = ns.GreeterClient(channel)
"""

from __future__ import annotations

import re
import types
from typing import Optional

from .grpc import Channel, Status

__all__ = ["compile_proto", "compile_proto_source"]

_PACKAGE_RE = re.compile(r"^\s*package\s+([\w.]+)\s*;", re.M)
_SERVICE_RE = re.compile(r"service\s+(\w+)\s*\{", re.M)
_RPC_RE = re.compile(
    r"rpc\s+(\w+)\s*\(\s*(stream\s+)?([\w.]+)\s*\)\s*"
    r"returns\s*\(\s*(stream\s+)?([\w.]+)\s*\)",
    re.M,
)
_COMMENT_RE = re.compile(r"//[^\n]*|/\*.*?\*/", re.S)


def _snake(name: str) -> str:
    """SayHello -> say_hello (tonic generates snake_case methods)."""
    out = re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name)
    return out.lower()


def _block(src: str, open_brace: int) -> str:
    """The text of a balanced {...} block starting at ``open_brace``."""
    depth = 0
    for i in range(open_brace, len(src)):
        if src[i] == "{":
            depth += 1
        elif src[i] == "}":
            depth -= 1
            if depth == 0:
                return src[open_brace + 1 : i]
    raise ValueError("unbalanced braces in .proto service block")


def _shape(client_stream: bool, server_stream: bool) -> str:
    if client_stream and server_stream:
        return "bidi"
    if client_stream:
        return "client_stream"
    if server_stream:
        return "server_stream"
    return "unary"


def compile_proto_source(src: str, package: Optional[str] = None) -> types.SimpleNamespace:
    """Generate Servicer/Client classes from .proto text."""
    src = _COMMENT_RE.sub("", src)
    if package is None:
        m = _PACKAGE_RE.search(src)
        package = m.group(1) if m else ""
    ns = types.SimpleNamespace()
    for m in _SERVICE_RE.finditer(src):
        svc_name = m.group(1)
        body = _block(src, m.end() - 1)
        methods = [
            (
                _snake(rm.group(1)),
                rm.group(1),
                _shape(bool(rm.group(2)), bool(rm.group(4))),
            )
            for rm in _RPC_RE.finditer(body)
        ]
        if not methods:
            continue
        full_name = f"{package}.{svc_name}" if package else svc_name
        setattr(ns, f"{svc_name}Servicer", _make_servicer(full_name, methods))
        setattr(
            ns,
            f"{svc_name}Client",
            _make_client(full_name, svc_name, methods),
        )
    return ns


def compile_proto(path: str) -> types.SimpleNamespace:
    """Generate Servicer/Client classes from a .proto file."""
    with open(path) as fh:
        return compile_proto_source(fh.read())


def _make_servicer(full_name: str, methods) -> type:
    """Base class: every rpc raises UNIMPLEMENTED until overridden
    (the generated async_trait default, server.rs:144-163)."""
    attrs = {"SERVICE_NAME": full_name}
    for py_name, proto_name, shape in methods:
        if shape in ("server_stream", "bidi"):
            # async generators so the router classifies the shape right
            # even for the unimplemented default
            async def default(self, request, _p=proto_name):  # type: ignore[misc]
                raise Status.unimplemented(_p)
                yield  # pragma: no cover - makes this an async generator

        else:

            async def default(self, request, _p=proto_name):  # type: ignore[misc]
                raise Status.unimplemented(_p)

        default.__name__ = py_name
        default.__rpc_shape__ = shape  # type: ignore[attr-defined]
        attrs[py_name] = default
    cls = type(full_name.rsplit(".", 1)[-1] + "Servicer", (), attrs)
    return cls


def _make_client(full_name: str, svc_name: str, methods) -> type:
    attrs = {}
    for py_name, proto_name, shape in methods:
        path = f"/{full_name}/{py_name}"
        if shape == "unary":

            def call(self, msg=None, timeout=None, _path=path):
                return self.channel.unary(_path, msg, timeout=timeout)

        elif shape == "server_stream":

            def call(self, msg=None, _path=path):
                return self.channel.server_streaming(_path, msg)

        elif shape == "client_stream":

            def call(self, _path=path):
                return self.channel.client_streaming(_path)

        else:

            def call(self, _path=path):
                return self.channel.bidi(_path)

        call.__name__ = py_name
        attrs[py_name] = call

    def __init__(self, channel: Channel):
        self.channel = channel

    attrs["__init__"] = __init__
    return type(f"{svc_name}Client", (), attrs)
