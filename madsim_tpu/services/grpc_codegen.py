"""gRPC code generation from .proto service definitions.

Parity with the reference's madsim-tonic-build (C23): the reference
forks tonic's protoc codegen to emit simulator client/server stubs from
.proto files (madsim-tonic-build/src/prost.rs:326-330, server.rs:11-128,
client.rs:10+). The analog here reads the ``service`` blocks out of a
.proto file and generates, at runtime:

  * ``<Name>Servicer`` — a base class whose methods raise UNIMPLEMENTED
    until overridden (the async_trait service trait, server.rs:144-163),
    carrying ``SERVICE_NAME = "package.Name"`` and per-method call-shape
    markers;
  * ``<Name>Client`` — a channel-bound client factory with one method
    per rpc, honoring ``stream`` on either side (client.rs generate).

Message and enum blocks are compiled too (the reference emits full prost
message types next to the sim stubs, prost.rs:326-330): each ``message``
becomes a dataclass whose fields carry the .proto types, numbers and
labels in ``__proto_fields__``, with proto3 zero-value defaults
(repeated -> list, map<k,v> -> dict, message fields -> None, enums ->
their zero variant). Inside the simulation instances travel by
reference (the BoxMessage = Box<dyn Any> design, sim.rs:27-29); on the
std backend they pickle like any payload — the same generated class is
the interface type on both sides of the cfg switch. Dicts remain
accepted everywhere for hand-rolled services.

    ns = compile_proto("proto/helloworld.proto")
    req = ns.HelloRequest(name="world")
    class MyGreeter(ns.GreeterServicer):
        async def say_hello(self, request): ...
    client = ns.GreeterClient(channel)
"""

from __future__ import annotations

import dataclasses
import keyword
import re
import types
from typing import Optional

from .grpc import Channel, Status

__all__ = ["compile_proto", "compile_proto_source"]

_PACKAGE_RE = re.compile(r"^\s*package\s+([\w.]+)\s*;", re.M)
_SERVICE_RE = re.compile(r"service\s+(\w+)\s*\{", re.M)
_MESSAGE_RE = re.compile(r"\bmessage\s+(\w+)\s*\{")
_ENUM_RE = re.compile(r"\benum\s+(\w+)\s*\{")
_RPC_RE = re.compile(
    r"rpc\s+(\w+)\s*\(\s*(stream\s+)?([\w.]+)\s*\)\s*"
    r"returns\s*\(\s*(stream\s+)?([\w.]+)\s*\)",
    re.M,
)
_FIELD_RE = re.compile(
    r"(repeated\s+|optional\s+|required\s+)?"
    r"(map\s*<\s*[\w.]+\s*,\s*[\w.]+\s*>|[\w.]+)\s+(\w+)\s*=\s*(\d+)\s*;"
)
_ENUM_VALUE_RE = re.compile(r"(\w+)\s*=\s*(-?\d+)\s*;")
_ONEOF_RE = re.compile(r"\boneof\s+\w+\s*\{")
_COMMENT_RE = re.compile(r"//[^\n]*|/\*.*?\*/", re.S)

# proto3 scalar zero values (prost's Default impls)
_SCALAR_DEFAULTS = {
    "double": 0.0, "float": 0.0,
    "int32": 0, "int64": 0, "uint32": 0, "uint64": 0,
    "sint32": 0, "sint64": 0, "fixed32": 0, "fixed64": 0,
    "sfixed32": 0, "sfixed64": 0,
    "bool": False, "string": "", "bytes": b"",
}


def _snake(name: str) -> str:
    """SayHello -> say_hello (tonic generates snake_case methods)."""
    out = re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name)
    return out.lower()


def _block(src: str, open_brace: int) -> str:
    """The text of a balanced {...} block starting at ``open_brace``."""
    depth = 0
    for i in range(open_brace, len(src)):
        if src[i] == "{":
            depth += 1
        elif src[i] == "}":
            depth -= 1
            if depth == 0:
                return src[open_brace + 1 : i]
    raise ValueError("unbalanced braces in .proto service block")


def _shape(client_stream: bool, server_stream: bool) -> str:
    if client_stream and server_stream:
        return "bidi"
    if client_stream:
        return "client_stream"
    if server_stream:
        return "server_stream"
    return "unary"


def compile_proto_source(src: str, package: Optional[str] = None) -> types.SimpleNamespace:
    """Generate message dataclasses, enums and Servicer/Client classes
    from .proto text."""
    src = _COMMENT_RE.sub("", src)
    if package is None:
        m = _PACKAGE_RE.search(src)
        package = m.group(1) if m else ""
    ns = types.SimpleNamespace()
    for name, cls in _compile_types(src, package):
        setattr(ns, name, cls)
    for m in _SERVICE_RE.finditer(src):
        svc_name = m.group(1)
        body = _block(src, m.end() - 1)
        methods = [
            (
                _snake(rm.group(1)),
                rm.group(1),
                _shape(bool(rm.group(2)), bool(rm.group(4))),
            )
            for rm in _RPC_RE.finditer(body)
        ]
        if not methods:
            continue
        full_name = f"{package}.{svc_name}" if package else svc_name
        setattr(ns, f"{svc_name}Servicer", _make_servicer(full_name, methods))
        setattr(
            ns,
            f"{svc_name}Client",
            _make_client(full_name, svc_name, methods),
        )
    return ns


def compile_proto(path: str) -> types.SimpleNamespace:
    """Generate Servicer/Client classes from a .proto file."""
    with open(path) as fh:
        return compile_proto_source(fh.read())


# ---------------------------------------------------------------------------
# message / enum compilation
# ---------------------------------------------------------------------------

# full proto name -> generated class. Both ends of a std connection
# compile the same .proto at import time, so pickled messages restore
# through this registry (instances of runtime-generated classes can't
# pickle by module path).
_MESSAGE_REGISTRY: dict[str, type] = {}


def _restore_message(full_name: str, values: dict):
    cls = _MESSAGE_REGISTRY.get(full_name)
    if cls is None:
        raise RuntimeError(
            f"cannot unpickle proto message {full_name!r}: compile the "
            f".proto in this process first (compile_proto)"
        )
    return cls(**values)


def _collect_type_blocks(text: str, prefix: str):
    """Yield ('message'|'enum', dotted_name, body) for every (possibly
    nested) message/enum block, and return the text with those blocks
    removed (so a parent's field scan never sees nested fields)."""
    found = []

    def walk(chunk: str, pfx: str) -> str:
        while True:
            mm = _MESSAGE_RE.search(chunk)
            em = _ENUM_RE.search(chunk)
            m = min(
                (x for x in (mm, em) if x is not None),
                key=lambda x: x.start(),
                default=None,
            )
            if m is None:
                return chunk
            body = _block(chunk, m.end() - 1)
            name = (pfx + "." if pfx else "") + m.group(1)
            end = m.end() - 1 + len(body) + 2  # past the closing brace
            if m.re is _MESSAGE_RE:
                inner = walk(body, name)
                found.append(("message", name, inner))
            else:
                found.append(("enum", name, body))
            chunk = chunk[: m.start()] + chunk[end:]

    rest = walk(text, prefix)
    return found, rest


def _make_enum(name: str, body: str) -> type:
    values = {m.group(1): int(m.group(2)) for m in _ENUM_VALUE_RE.finditer(body)}
    attrs = dict(values)
    attrs["__proto_values__"] = values
    return type(name.rsplit(".", 1)[-1], (), attrs)


def _field_default(type_str: str, label: str, enums: dict):
    if label == "repeated":
        return dataclasses.field(default_factory=list)
    if type_str.startswith("map"):
        return dataclasses.field(default_factory=dict)
    if type_str in _SCALAR_DEFAULTS:
        return _SCALAR_DEFAULTS[type_str]
    short = type_str.rsplit(".", 1)[-1]
    if short in enums:
        vals = enums[short].__proto_values__
        return min(vals.values()) if vals else 0
    return None  # message-typed (or optional): absent until set


def _make_message(full_name: str, body: str, enums: dict, package: str = "") -> type:
    # oneof members are plain fields of the parent in the dataclass view
    while True:
        m = _ONEOF_RE.search(body)
        if m is None:
            break
        inner = _block(body, m.end() - 1)
        end = m.end() - 1 + len(inner) + 2
        body = body[: m.start()] + inner + body[end:]
    fields = []
    proto_fields = []
    for fm in _FIELD_RE.finditer(body):
        label = (fm.group(1) or "").strip()
        type_str = re.sub(r"\s+", "", fm.group(2))
        fname, number = fm.group(3), int(fm.group(4))
        # Python keywords can't be dataclass fields; suffix them the way
        # generated code conventionally does (prost escapes as r#from).
        # __proto_fields__ keeps the original wire name.
        py_name = fname + "_" if keyword.iskeyword(fname) else fname
        proto_fields.append((fname, number, label or "singular", type_str))
        fields.append((py_name, object, _field_default(type_str, label, enums)))
    # class name: the in-package path with dots flattened, so nested
    # messages (shop.Order.Address -> Order_Address) match their
    # namespace attribute and stay distinguishable across parents
    rel = full_name
    if package and full_name.startswith(package + "."):
        rel = full_name[len(package) + 1:]
    short = rel.replace(".", "_")
    cls = dataclasses.make_dataclass(
        short,
        fields,
        namespace={
            "__proto_fields__": tuple(proto_fields),
            "__proto_name__": full_name,
            # shallow field map: nested messages pickle through their
            # own __reduce__ (asdict would flatten them into dicts)
            "__reduce__": lambda self: (
                _restore_message,
                (
                    self.__proto_name__,
                    {
                        f.name: getattr(self, f.name)
                        for f in dataclasses.fields(self)
                    },
                ),
            ),
        },
    )
    _MESSAGE_REGISTRY[full_name] = cls
    return cls


def _compile_types(src: str, package: str):
    """Yield (attr_name, class) for every message/enum in the file."""
    blocks, _rest = _collect_type_blocks(src, "")
    enums: dict[str, type] = {}
    out = []
    for kind, name, body in blocks:
        if kind == "enum":
            cls = _make_enum(name, body)
            enums[name.rsplit(".", 1)[-1]] = cls
            out.append((name.replace(".", "_"), cls))
    for kind, name, body in blocks:
        if kind == "message":
            full = f"{package}.{name}" if package else name
            cls = _make_message(full, body, enums, package)
            out.append((name.replace(".", "_"), cls))
    return out


def _make_servicer(full_name: str, methods) -> type:
    """Base class: every rpc raises UNIMPLEMENTED until overridden
    (the generated async_trait default, server.rs:144-163)."""
    attrs = {"SERVICE_NAME": full_name}
    for py_name, proto_name, shape in methods:
        if shape in ("server_stream", "bidi"):
            # async generators so the router classifies the shape right
            # even for the unimplemented default
            async def default(self, request, _p=proto_name):  # type: ignore[misc]
                raise Status.unimplemented(_p)
                yield  # pragma: no cover - makes this an async generator

        else:

            async def default(self, request, _p=proto_name):  # type: ignore[misc]
                raise Status.unimplemented(_p)

        default.__name__ = py_name
        default.__rpc_shape__ = shape  # type: ignore[attr-defined]
        attrs[py_name] = default
    cls = type(full_name.rsplit(".", 1)[-1] + "Servicer", (), attrs)
    return cls


def _make_client(full_name: str, svc_name: str, methods) -> type:
    attrs = {}
    for py_name, proto_name, shape in methods:
        path = f"/{full_name}/{py_name}"
        if shape == "unary":

            def call(self, msg=None, timeout=None, _path=path):
                return self.channel.unary(_path, msg, timeout=timeout)

        elif shape == "server_stream":

            def call(self, msg=None, _path=path):
                return self.channel.server_streaming(_path, msg)

        elif shape == "client_stream":

            def call(self, _path=path):
                return self.channel.client_streaming(_path)

        else:

            def call(self, _path=path):
                return self.channel.bidi(_path)

        call.__name__ = py_name
        attrs[py_name] = call

    def __init__(self, channel: Channel):
        self.channel = channel

    attrs["__init__"] = __init__
    return type(f"{svc_name}Client", (), attrs)
