"""Kafka-style producer/consumer/admin over an in-process SimBroker.

Parity with the reference's madsim-rdkafka (madsim-rdkafka/src/sim/):
  * ``SimBroker`` served on a simulated node; request surface: produce /
    fetch / metadata / watermarks / offsets-for-times / create-topics
    (sim_broker.rs:14-76)
  * topics are lists of partition logs; **produce assigns partitions
    round-robin and ignores the record's requested partition** — a
    deliberate quirk of the reference broker preserved for parity
    (broker.rs:81-111)
  * fetch honors max_bytes and the high watermark (broker.rs:114-156)
  * ``BaseProducer`` buffers up to ``queue.buffering.max.messages``
    records (default 10) then errors QueueFull; ``flush`` drains
    (producer.rs:173-224); transactions buffer until commit
    (producer.rs:237+)
  * ``BaseConsumer`` assign/subscribe with ``auto.offset.reset``, cached
    fetch via poll (consumer.rs:49-207); ``StreamConsumer`` wraps it in
    an async stream (consumer.rs:209-240)
  * ``AdminClient.create_topics`` (admin.rs:80)
  * ``ClientConfig`` string map -> typed client construction
    (config.rs:30-69)
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from ..net.addr import AddrLike, parse_addr
from ._dual import bind_endpoint, make_notify, now_ns, sleep
from ._transport import RequestClient, serve_requests

__all__ = [
    "KafkaError",
    "SimBroker",
    "ClientConfig",
    "BaseRecord",
    "FutureRecord",
    "Message",
    "BaseProducer",
    "FutureProducer",
    "BaseConsumer",
    "StreamConsumer",
    "AdminClient",
    "NewTopic",
    "TopicPartitionList",
    "Offset",
]

_DEFAULT_QUEUE_MAX = 10  # producer.rs:173-190


class KafkaError(Exception):
    def __init__(self, kind: str, message: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message

    def __reduce__(self):
        # preserve (kind, message) across pickling — the std backend
        # ships exceptions over real sockets and group-protocol clients
        # dispatch on .kind (RebalanceInProgress etc.)
        return (KafkaError, (self.kind, self.message))


class BaseRecord:
    """A record to produce. ``partition`` is carried but the broker
    round-robins regardless (broker.rs:81-111)."""

    def __init__(self, topic: str, partition: Optional[int] = None,
                 key: Optional[bytes] = None, payload: Optional[bytes] = None):
        self.topic = topic
        self.partition = partition
        self.key = key
        self.payload = payload

    @classmethod
    def to(cls, topic: str) -> "BaseRecord":
        return cls(topic)

    def set_partition(self, p: int) -> "BaseRecord":
        self.partition = p
        return self

    def set_key(self, k) -> "BaseRecord":
        self.key = k if isinstance(k, bytes) else str(k).encode()
        return self

    def set_payload(self, p) -> "BaseRecord":
        self.payload = p if isinstance(p, bytes) else str(p).encode()
        return self


FutureRecord = BaseRecord


class Message:
    """A consumed record (message.rs)."""

    __slots__ = ("topic", "partition", "offset", "key", "payload", "timestamp")

    def __init__(self, topic, partition, offset, key, payload, timestamp):
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.key = key
        self.payload = payload
        self.timestamp = timestamp

    def __repr__(self):
        return f"Message({self.topic}[{self.partition}]@{self.offset})"


class Offset:
    BEGINNING = "beginning"
    END = "end"

    def __init__(self, kind: str, offset: int = 0):
        self.kind = kind
        self.offset = offset

    @classmethod
    def at(cls, offset: int) -> "Offset":
        return cls("offset", offset)


class TopicPartitionList:
    def __init__(self) -> None:
        self.items: list[tuple[str, int, Optional[Offset]]] = []

    def add_partition(self, topic: str, partition: int) -> None:
        self.items.append((topic, partition, None))

    def add_partition_offset(self, topic: str, partition: int, offset: Offset) -> None:
        self.items.append((topic, partition, offset))


class NewTopic:
    def __init__(self, name: str, num_partitions: int = 1):
        self.name = name
        self.num_partitions = num_partitions


# ---------------------------------------------------------------------------
# broker
# ---------------------------------------------------------------------------


class _Group:
    """Consumer-group coordinator state (a capability the reference's sim
    lacks: madsim-rdkafka/src/sim/consumer.rs:110-122 is assign-only)."""

    __slots__ = ("generation", "members", "subs", "assignments", "committed",
                 "member_seq")

    def __init__(self) -> None:
        self.generation = 0
        self.members: dict[str, tuple[int, int]] = {}  # id -> (last_hb_ms, session_ms)
        self.subs: dict[str, tuple] = {}  # id -> subscribed topics
        self.assignments: dict[str, list] = {}  # id -> [(topic, partition)]
        self.committed: dict[tuple[str, int], int] = {}
        self.member_seq = 0


class SimBroker:
    """In-process single broker served over the simulated network:

        await kafka.SimBroker().serve("0.0.0.0:9092")
    """

    local_addr = None  # set once serving (bind port 0, read it here)

    def __init__(self) -> None:
        # topic -> list of partition logs; each log is a list of Message
        self.topics: dict[str, list[list[Message]]] = {}
        self._rr: dict[str, int] = {}  # round-robin cursor per topic
        self._data_notify = make_notify()
        self._groups: dict[str, _Group] = {}

    async def serve(self, addr: AddrLike) -> None:
        await serve_requests(
            addr, self._dispatch, KafkaError, name="kafka-request",
            on_bound=lambda a: setattr(self, "local_addr", a),
        )

    async def _dispatch(self, op: str, kw: dict) -> Any:
        if op == "create_topics":
            created = []
            for name, parts in kw["topics"]:
                if name in self.topics:
                    raise KafkaError("TopicAlreadyExists", name)
                self.topics[name] = [[] for _ in range(parts)]
                self._rr[name] = 0
                created.append(name)
            # groups already subscribed to a just-created topic pick up
            # its partitions via a rebalance (the metadata-refresh path
            # of real brokers); without this an early subscriber would
            # starve forever
            for g in self._groups.values():
                if any(
                    t in sub for t in created for sub in g.subs.values()
                ):
                    self._rebalance(g)
            return created
        if op == "produce":
            return self._produce(kw["records"])
        if op == "fetch":
            return self._fetch(kw["topic"], kw["partition"], kw["offset"],
                               kw["max_bytes"])
        if op == "metadata":
            topic = kw.get("topic")
            if topic is not None:
                if topic not in self.topics:
                    raise KafkaError("UnknownTopic", topic)
                return {topic: len(self.topics[topic])}
            return {t: len(ps) for t, ps in self.topics.items()}
        if op == "watermarks":
            log = self._log(kw["topic"], kw["partition"])
            return (0, len(log))
        if op == "offsets_for_times":
            # first offset with timestamp >= target (broker.rs:182-199)
            out = []
            for topic, partition, ts_ms in kw["items"]:
                log = self._log(topic, partition)
                off = next(
                    (m.offset for m in log if m.timestamp >= ts_ms), len(log)
                )
                out.append((topic, partition, off))
            return out
        if op == "join_group":
            return self._join_group(
                kw["group"], kw.get("member_id"), kw["topics"], kw["session_ms"]
            )
        if op == "sync_group":
            g = self._group(kw["group"])
            self._expire(g)
            mid = kw["member_id"]
            if mid not in g.members:
                raise KafkaError("UnknownMemberId", mid)
            if kw["generation"] != g.generation:
                raise KafkaError("RebalanceInProgress", kw["group"])
            return g.assignments.get(mid, [])
        if op == "heartbeat":
            g = self._group(kw["group"])
            mid = kw["member_id"]
            self._expire(g)
            if mid not in g.members:
                raise KafkaError("UnknownMemberId", mid)
            if kw["generation"] != g.generation:
                raise KafkaError("RebalanceInProgress", kw["group"])
            _hb, session = g.members[mid]
            g.members[mid] = (now_ns() // 1_000_000, session)
            return True
        if op == "leave_group":
            g = self._group(kw["group"])
            if kw["member_id"] in g.members:
                del g.members[kw["member_id"]]
                g.subs.pop(kw["member_id"], None)
                self._rebalance(g)
            return True
        if op == "commit_offsets":
            # fenced: a zombie (expired or stale-generation) member must
            # not overwrite the new owner's offsets
            g = self._group(kw["group"])
            self._expire(g)
            mid = kw["member_id"]
            if mid not in g.members:
                raise KafkaError("UnknownMemberId", mid)
            if kw["generation"] != g.generation:
                raise KafkaError("IllegalGeneration", kw["group"])
            for topic, partition, off in kw["items"]:
                g.committed[(topic, partition)] = off
            return True
        if op == "fetch_offsets":
            g = self._group(kw["group"])
            return [
                (t, p, g.committed.get((t, p), -1)) for t, p in kw["items"]
            ]
        raise KafkaError("InvalidOp", op)

    # ---- consumer-group coordination ----------------------------------
    def _group(self, group_id: str) -> _Group:
        if group_id not in self._groups:
            self._groups[group_id] = _Group()
        return self._groups[group_id]

    def _expire(self, g: _Group) -> None:
        """Drop members whose session timed out; triggers a rebalance.
        Lazy (checked on every group op) — deterministic under the
        simulated clock."""
        now_ms = now_ns() // 1_000_000
        dead = [
            mid for mid, (hb, session) in g.members.items()
            if now_ms - hb > session
        ]
        for mid in dead:
            del g.members[mid]
            g.subs.pop(mid, None)
        if dead:
            self._rebalance(g)

    def _rebalance(self, g: _Group) -> None:
        """Round-robin each topic's partitions over the members
        subscribed to THAT topic; bumps the generation so stale members
        get RebalanceInProgress on their next heartbeat/sync."""
        g.generation += 1
        g.assignments = {m: [] for m in g.members}
        for topic in sorted({t for sub in g.subs.values() for t in sub}):
            if topic not in self.topics:
                continue
            members_t = sorted(m for m, sub in g.subs.items() if topic in sub)
            if not members_t:
                continue
            for p in range(len(self.topics[topic])):
                g.assignments[members_t[p % len(members_t)]].append((topic, p))

    def _join_group(self, group_id, member_id, topics, session_ms):
        g = self._group(group_id)
        self._expire(g)
        known = member_id in g.members if member_id else False
        rejoin_same = known and g.subs.get(member_id) == tuple(topics)
        if not member_id:
            g.member_seq += 1
            member_id = f"member-{g.member_seq}"
        g.members[member_id] = (now_ns() // 1_000_000, session_ms)
        g.subs[member_id] = tuple(topics)
        # only a membership/subscription CHANGE bumps the generation —
        # a known member re-entering the handshake (its reaction to a
        # rebalance) must converge on the current generation, otherwise
        # every rejoin would invalidate every other member forever
        if not rejoin_same:
            self._rebalance(g)
        return (member_id, g.generation)

    def _log(self, topic: str, partition: int) -> list[Message]:
        if topic not in self.topics:
            raise KafkaError("UnknownTopic", topic)
        parts = self.topics[topic]
        if not 0 <= partition < len(parts):
            raise KafkaError("UnknownPartition", f"{topic}[{partition}]")
        return parts[partition]

    def _produce(self, records: list) -> list:
        acks = []
        for rec in records:
            topic, _req_partition, key, payload, ts_ms = rec
            if topic not in self.topics:
                raise KafkaError("UnknownTopic", topic)
            parts = self.topics[topic]
            # round-robin placement, requested partition ignored
            # (broker.rs:81-111)
            p = self._rr[topic] % len(parts)
            self._rr[topic] += 1
            log = parts[p]
            msg = Message(topic, p, len(log), key, payload, ts_ms)
            log.append(msg)
            acks.append((topic, p, msg.offset))
        if acks:
            self._data_notify.notify_waiters()
        return acks

    def _fetch(self, topic: str, partition: int, offset: int, max_bytes: int):
        log = self._log(topic, partition)
        out = []
        size = 0
        for m in log[max(offset, 0):]:
            sz = len(m.payload or b"") + len(m.key or b"")
            if out and size + sz > max_bytes:
                break
            out.append((m.topic, m.partition, m.offset, m.key, m.payload,
                        m.timestamp))
            size += sz
        return {"messages": out, "high_watermark": len(log)}


# ---------------------------------------------------------------------------
# clients
# ---------------------------------------------------------------------------


class _Raw(RequestClient):
    def __init__(self, ep, dst):
        super().__init__(
            ep, dst, lambda m: KafkaError("BrokerTransportFailure", m)
        )


class ClientConfig:
    """String-keyed config map -> typed clients (config.rs:30-69)."""

    def __init__(self) -> None:
        self._map: dict[str, str] = {}

    def set(self, key: str, value) -> "ClientConfig":
        self._map[key] = str(value)
        return self

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._map.get(key, default)

    async def create(self, cls: type) -> Any:
        """``await config.create(BaseProducer)``"""
        servers = self._map.get("bootstrap.servers")
        if not servers:
            raise KafkaError("ClientConfig", "bootstrap.servers not set")
        dst = parse_addr(servers.split(",")[0])
        ep = await bind_endpoint("0.0.0.0:0")
        return cls(_Raw(ep, dst), self)


class BaseProducer:
    """Buffering producer (producer.rs:173-224)."""

    async def close(self) -> None:
        await self._raw.close()

    def __init__(self, raw: _Raw, config: ClientConfig):
        self._raw = raw
        self._config = config
        self._queue_max = int(
            config.get("queue.buffering.max.messages", str(_DEFAULT_QUEUE_MAX))
        )
        self._buffer: list = []
        self._in_txn = False
        self._txn_buffer: list = []

    def send(self, record: BaseRecord) -> None:
        """Buffer one record; raises QueueFull past the limit."""
        buf = self._txn_buffer if self._in_txn else self._buffer
        if len(buf) >= self._queue_max and not self._in_txn:
            raise KafkaError("QueueFull", f"more than {self._queue_max} queued")
        buf.append(
            (record.topic, record.partition, record.key, record.payload,
             now_ns() // 1_000_000)
        )

    async def flush(self) -> list:
        """Produce everything buffered (flush_internal, producer.rs:214-224).
        On transport failure the records stay buffered so a retrying
        caller does not silently lose them."""
        if not self._buffer:
            return []
        records, self._buffer = self._buffer, []
        try:
            return await self._raw.call("produce", records=records)
        except KafkaError:
            self._buffer = records + self._buffer
            raise

    # ---- transactions: buffer-until-commit (producer.rs:237+) ----------
    async def init_transactions(self) -> None:
        self._txn_buffer = []

    def begin_transaction(self) -> None:
        if self._in_txn:
            raise KafkaError("InvalidTxnState", "transaction already begun")
        self._in_txn = True

    async def commit_transaction(self) -> list:
        if not self._in_txn:
            raise KafkaError("InvalidTxnState", "no transaction begun")
        self._in_txn = False
        records, self._txn_buffer = self._txn_buffer, []
        if not records:
            return []
        try:
            return await self._raw.call("produce", records=records)
        except KafkaError:
            # commit failed in transit: keep the records so the caller
            # can retry the commit
            self._in_txn = True
            self._txn_buffer = records
            raise

    def abort_transaction(self) -> None:
        if not self._in_txn:
            raise KafkaError("InvalidTxnState", "no transaction begun")
        self._in_txn = False
        self._txn_buffer = []


class FutureProducer:
    """Awaitable per-record producer: returns (partition, offset)."""

    async def close(self) -> None:
        await self._raw.close()

    def __init__(self, raw: _Raw, config: ClientConfig):
        self._raw = raw

    async def send(self, record: BaseRecord, timeout: Optional[float] = None):
        acks = await self._raw.call(
            "produce",
            records=[(record.topic, record.partition, record.key, record.payload,
                      now_ns() // 1_000_000)],
        )
        _topic, partition, offset = acks[0]
        return partition, offset


class BaseConsumer:
    """Pull consumer with assign/subscribe + cached fetch
    (consumer.rs:49-207), plus ``group.id`` consumer groups."""

    async def close(self) -> None:
        if self._group and self._member_id:
            try:
                await self._raw.call(
                    "leave_group", group=self._group, member_id=self._member_id
                )
            except KafkaError:
                pass  # broker gone: the session timeout reaps us
        await self._raw.close()

    def __init__(self, raw: _Raw, config: ClientConfig):
        self._raw = raw
        self._config = config
        self._reset = config.get("auto.offset.reset", "latest")
        self._max_bytes = int(config.get("fetch.message.max.bytes", "1048576"))
        # (topic, partition) -> next offset
        self._positions: dict[tuple[str, int], int] = {}
        self._cache: list[Message] = []
        # consumer-group state ("group.id" set => subscribe coordinates
        # through the broker's group protocol; beats the assign-only
        # reference sim, consumer.rs:110-122)
        self._group = config.get("group.id")
        self._session_ms = int(config.get("session.timeout.ms", "10000"))
        self._hb_interval_ms = int(config.get("heartbeat.interval.ms", "3000"))
        self._auto_commit = (
            config.get("enable.auto.commit", "true").lower() == "true"
        )
        self._commit_interval_ms = int(
            config.get("auto.commit.interval.ms", "5000")
        )
        self._member_id: Optional[str] = None
        self._generation = 0
        self._sub_topics: tuple = ()
        self._last_hb_ms = 0
        self._last_commit_ms = 0
        # (topic, partition) -> next offset the APP has consumed through
        # poll(); commits use this, not the fetch position, so messages
        # cached but never delivered are re-read after a crash
        # (at-least-once, the librdkafka stored-offset behavior)
        self._processed: dict[tuple[str, int], int] = {}

    async def subscribe(self, topics: Iterable[str]) -> None:
        """Without ``group.id``: consume every partition (the reference
        sim's behavior). With ``group.id``: join the consumer group and
        consume only the partitions the coordinator assigns."""
        topics = tuple(topics)
        if self._group:
            self._sub_topics = topics
            await self._join_group()
            return
        for topic in topics:
            meta = await self._raw.call("metadata", topic=topic)
            for p in range(meta[topic]):
                await self._position_for(topic, p)

    # ---- group membership ---------------------------------------------
    async def _join_group(self) -> None:
        while True:
            self._member_id, self._generation = await self._raw.call(
                "join_group", group=self._group, member_id=self._member_id,
                topics=list(self._sub_topics), session_ms=self._session_ms,
            )
            try:
                assignment = await self._raw.call(
                    "sync_group", group=self._group, member_id=self._member_id,
                    generation=self._generation,
                )
                break
            except KafkaError as e:
                # another member joined/left between our join and sync:
                # re-enter the handshake at the new generation
                if e.kind not in ("RebalanceInProgress", "UnknownMemberId"):
                    raise
                await sleep(0.05)
        self._cache.clear()
        self._positions.clear()
        committed = await self._raw.call(
            "fetch_offsets", group=self._group,
            items=[(t, p) for t, p in assignment],
        )
        for topic, partition, off in committed:
            if off >= 0:
                self._positions[(topic, partition)] = off
            else:
                await self._position_for(topic, partition)
        self._processed = dict(self._positions)
        self._last_hb_ms = now_ns() // 1_000_000

    def assignment(self) -> list:
        """The partitions this consumer currently owns."""
        return sorted(self._positions)

    async def commit(self) -> None:
        """Commit processed positions to the group coordinator. Fenced
        by (member_id, generation): a commit from a member the broker
        has expired or rebalanced past raises UnknownMemberId /
        IllegalGeneration instead of clobbering the new owner."""
        if not self._group:
            raise KafkaError("InvalidConfig", "commit requires group.id")
        await self._raw.call(
            "commit_offsets", group=self._group,
            member_id=self._member_id, generation=self._generation,
            items=[(t, p, off) for (t, p), off in self._processed.items()],
        )
        self._last_commit_ms = now_ns() // 1_000_000

    async def _group_tick(self) -> None:
        """Heartbeat + auto-commit pacing, driven by poll() the way
        librdkafka drives its coordinator from the poll loop. A
        RebalanceInProgress / UnknownMemberId answer re-joins, which
        picks up the post-rebalance assignment."""
        now_ms = now_ns() // 1_000_000
        stale = ("RebalanceInProgress", "UnknownMemberId", "IllegalGeneration")
        if self._auto_commit and (
            now_ms - self._last_commit_ms >= self._commit_interval_ms
        ):
            try:
                await self.commit()
            except KafkaError as e:
                # fenced: we are a zombie — rejoin rather than clobber
                # the new owner's offsets (uncommitted progress is
                # re-delivered: at-least-once)
                if e.kind not in stale:
                    raise
                await self._join_group()
                return
        if now_ms - self._last_hb_ms >= self._hb_interval_ms:
            try:
                await self._raw.call(
                    "heartbeat", group=self._group,
                    member_id=self._member_id, generation=self._generation,
                )
                self._last_hb_ms = now_ms
            except KafkaError as e:
                if e.kind in stale:
                    await self._join_group()
                else:
                    raise

    async def assign(self, tpl: TopicPartitionList) -> None:
        for topic, partition, offset in tpl.items:
            if offset is None:
                await self._position_for(topic, partition)
            elif offset.kind == "beginning":
                self._positions[(topic, partition)] = 0
            elif offset.kind == "end":
                lo, hi = await self._raw.call(
                    "watermarks", topic=topic, partition=partition
                )
                self._positions[(topic, partition)] = hi
            else:
                self._positions[(topic, partition)] = offset.offset

    async def _position_for(self, topic: str, partition: int) -> None:
        if self._reset == "earliest":
            self._positions[(topic, partition)] = 0
        else:
            _lo, hi = await self._raw.call(
                "watermarks", topic=topic, partition=partition
            )
            self._positions[(topic, partition)] = hi

    async def poll(self) -> Optional[Message]:
        """Next message from cache, fetching when empty
        (poll_internal, consumer.rs:179-207); None when nothing new."""
        if self._group and self._member_id:
            await self._group_tick()
        if self._cache:
            m = self._cache.pop(0)
            self._processed[(m.topic, m.partition)] = m.offset + 1
            return m
        for (topic, partition), offset in sorted(self._positions.items()):
            r = await self._raw.call(
                "fetch", topic=topic, partition=partition, offset=offset,
                max_bytes=self._max_bytes,
            )
            msgs = [Message(*m) for m in r["messages"]]
            if msgs:
                self._positions[(topic, partition)] = msgs[-1].offset + 1
                self._cache.extend(msgs)
                m = self._cache.pop(0)
                self._processed[(m.topic, m.partition)] = m.offset + 1
                return m
        return None

    async def offsets_for_times(self, items) -> list:
        return await self._raw.call("offsets_for_times", items=list(items))

    async def fetch_watermarks(self, topic: str, partition: int):
        return await self._raw.call("watermarks", topic=topic, partition=partition)


class StreamConsumer(BaseConsumer):
    """Async-stream consumer: ``async for`` / awaited recv with a poll
    loop (consumer.rs:209-240)."""

    async def recv(self) -> Message:
        while True:
            msg = await self.poll()
            if msg is not None:
                return msg
            await sleep(0.05)

    def __aiter__(self):
        return self

    async def __anext__(self) -> Message:
        return await self.recv()


class AdminClient:
    async def close(self) -> None:
        await self._raw.close()

    def __init__(self, raw: _Raw, config: ClientConfig):
        self._raw = raw

    async def create_topics(self, topics: Iterable[NewTopic]) -> list:
        return await self._raw.call(
            "create_topics", topics=[(t.name, t.num_partitions) for t in topics]
        )
