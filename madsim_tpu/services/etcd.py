"""etcd v3 simulator: KV / Txn / Lease / Election over the simulated net.

Parity with the reference's madsim-etcd-client (madsim-etcd-client/src/):
  * ``SimServer`` builder serving an in-process single-node etcd state
    machine on a simulated address (server.rs:8-70)
  * the 14-op request surface: put/get(range)/delete/txn, lease
    grant/revoke/keep-alive/ttl/leases, campaign/proclaim/leader/resign
    (server.rs:73-127, service.rs:136-442)
  * revision bookkeeping: global revision bumps on every mutation;
    per-key create_revision / mod_revision / version (service.rs:127-134)
  * leases tick down once per simulated second and expiry deletes
    attached keys (service.rs:20-26, 353-370)
  * election campaign parks waiters in FIFO order and wakes the next
    on resign/expiry (poll_campaign, service.rs:372-409); ``observe``
    streams leader changes — implemented here although the reference
    server answers it Unimplemented (server.rs:60)
  * fault injection: with probability ``timeout_rate`` a request stalls
    5-15 simulated seconds and fails UNAVAILABLE (service.rs:113-124)

Client classes mirror the etcd-client API shape (KvClient, LeaseClient,
ElectionClient); every op is one connection round-trip like the
reference's kv.rs:25-100. Values are bytes; keys are bytes.

Dual-mode (the reference's cfg-switch contract, lib.rs:1-8): inside a
simulation the server and clients ride the simulated network; outside,
the same classes run over real localhost TCP via madsim_tpu.std.net.
"""

from __future__ import annotations

from typing import Any, Optional

from ..net.addr import AddrLike, parse_addr
from ._dual import bind_endpoint, make_notify, rng, sleep, spawn
from ._transport import RequestClient, ResponseStream, StreamReply, serve_requests

__all__ = [
    "EtcdError",
    "SimServer",
    "Client",
    "KvClient",
    "LeaseClient",
    "ElectionClient",
    "KeyValue",
    "Compare",
    "Txn",
    "TxnOp",
    "PutOptions",
    "GetOptions",
    "DeleteOptions",
]


class EtcdError(Exception):
    """etcd-compatible error (error.rs:10-40)."""

    def __init__(self, kind: str, message: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message


def _to_bytes(x: "bytes | str") -> bytes:
    return x.encode() if isinstance(x, str) else bytes(x)


class KeyValue:
    """A stored key-value with etcd revision metadata."""

    __slots__ = ("key", "value", "create_revision", "mod_revision", "version", "lease")

    def __init__(self, key, value, create_revision, mod_revision, version, lease):
        self.key = key
        self.value = value
        self.create_revision = create_revision
        self.mod_revision = mod_revision
        self.version = version
        self.lease = lease

    def _copy(self) -> "KeyValue":
        return KeyValue(
            self.key, self.value, self.create_revision, self.mod_revision,
            self.version, self.lease,
        )

    def __repr__(self) -> str:
        return f"KeyValue({self.key!r}={self.value!r} @mod {self.mod_revision})"


# ---- options (kv.rs option structs) ---------------------------------------


class PutOptions:
    def __init__(self, lease: int = 0, prev_kv: bool = False):
        self.lease = lease
        self.prev_kv = prev_kv


class GetOptions:
    def __init__(
        self,
        prefix: bool = False,
        range_end: Optional[bytes] = None,
        limit: int = 0,
        count_only: bool = False,
        keys_only: bool = False,
    ):
        self.prefix = prefix
        self.range_end = range_end
        self.limit = limit
        self.count_only = count_only
        self.keys_only = keys_only


class DeleteOptions:
    def __init__(self, prefix: bool = False, range_end: Optional[bytes] = None,
                 prev_kv: bool = False):
        self.prefix = prefix
        self.range_end = range_end
        self.prev_kv = prev_kv


class Compare:
    """Txn guard (kv.rs:247-460). op in {'=', '!=', '>', '<'};
    target in {'value', 'version', 'create', 'mod', 'lease'}."""

    def __init__(self, key, target: str, op: str, operand):
        self.key = _to_bytes(key)
        self.target = target
        self.op = op
        self.operand = operand

    @classmethod
    def value(cls, key, op, v):
        return cls(key, "value", op, _to_bytes(v))

    @classmethod
    def version(cls, key, op, v):
        return cls(key, "version", op, int(v))

    @classmethod
    def create_revision(cls, key, op, v):
        return cls(key, "create", op, int(v))

    @classmethod
    def mod_revision(cls, key, op, v):
        return cls(key, "mod", op, int(v))


class TxnOp:
    def __init__(self, kind: str, *args: Any):
        self.kind = kind
        self.args = args

    @classmethod
    def put(cls, key, value, options: Optional[PutOptions] = None):
        return cls("put", _to_bytes(key), _to_bytes(value), options or PutOptions())

    @classmethod
    def get(cls, key, options: Optional[GetOptions] = None):
        return cls("get", _to_bytes(key), options or GetOptions())

    @classmethod
    def delete(cls, key, options: Optional[DeleteOptions] = None):
        return cls("delete", _to_bytes(key), options or DeleteOptions())


class Txn:
    """compare-and-do transaction (kv.rs Txn builder)."""

    def __init__(self) -> None:
        self._when: list[Compare] = []
        self._then: list[TxnOp] = []
        self._else: list[TxnOp] = []

    def when(self, compares) -> "Txn":
        self._when = list(compares)
        return self

    def and_then(self, ops) -> "Txn":
        self._then = list(ops)
        return self

    def or_else(self, ops) -> "Txn":
        self._else = list(ops)
        return self


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _ServiceInner:
    """The etcd state machine (service.rs:127-134)."""

    def __init__(self) -> None:
        self.revision = 0
        self.kv: dict[bytes, KeyValue] = {}
        # lease id -> [ttl, remaining_seconds, set(keys)]
        self.leases: dict[int, list] = {}
        # election name -> list of waiting campaigns (FIFO)
        self.waiters: dict[bytes, list] = {}

    # ---- kv ---------------------------------------------------------------
    def _range(self, key: bytes, opt: GetOptions) -> list[KeyValue]:
        if opt.prefix:
            out = [kv for k, kv in sorted(self.kv.items()) if k.startswith(key)]
        elif opt.range_end:
            out = [
                kv for k, kv in sorted(self.kv.items()) if key <= k < opt.range_end
            ]
        else:
            kv = self.kv.get(key)
            out = [kv] if kv is not None else []
        if opt.limit:
            out = out[: opt.limit]
        return out

    def put(self, key: bytes, value: bytes, opt: PutOptions):
        self.revision += 1
        prev = self.kv.get(key)
        if prev is not None:
            nkv = KeyValue(
                key, value, prev.create_revision, self.revision, prev.version + 1,
                opt.lease,
            )
        else:
            nkv = KeyValue(key, value, self.revision, self.revision, 1, opt.lease)
        if opt.lease:
            if opt.lease not in self.leases:
                self.revision -= 1
                raise EtcdError("LeaseError", f"lease {opt.lease} not found")
            self.leases[opt.lease][2].add(key)
        if prev is not None and prev.lease and prev.lease != opt.lease:
            lease = self.leases.get(prev.lease)
            if lease:
                lease[2].discard(key)
        self.kv[key] = nkv
        return {"header_revision": self.revision,
                "prev_kv": prev._copy() if (prev and opt.prev_kv) else None}

    def get(self, key: bytes, opt: GetOptions):
        kvs = self._range(key, opt)
        return {
            "header_revision": self.revision,
            "count": len(kvs),
            "kvs": [] if opt.count_only else [kv._copy() for kv in kvs],
        }

    def delete(self, key: bytes, opt: DeleteOptions):
        kvs = self._range(
            key, GetOptions(prefix=opt.prefix, range_end=opt.range_end)
        )
        if kvs:
            self.revision += 1
        deleted = []
        for kv in kvs:
            del self.kv[kv.key]
            if kv.lease and kv.lease in self.leases:
                self.leases[kv.lease][2].discard(kv.key)
            deleted.append(kv)
        return {
            "header_revision": self.revision,
            "deleted": len(deleted),
            "prev_kvs": deleted if opt.prev_kv else [],
        }

    # ---- txn (service.rs:250-284) ------------------------------------------
    def _check(self, c: Compare) -> bool:
        kv = self.kv.get(c.key)
        if c.target == "value":
            actual = kv.value if kv else None
            if actual is None:
                return False
        elif c.target == "version":
            actual = kv.version if kv else 0
        elif c.target == "create":
            actual = kv.create_revision if kv else 0
        elif c.target == "mod":
            actual = kv.mod_revision if kv else 0
        elif c.target == "lease":
            actual = kv.lease if kv else 0
        else:
            raise EtcdError("InvalidArgs", f"bad compare target {c.target}")
        if c.op == "=":
            return actual == c.operand
        if c.op == "!=":
            return actual != c.operand
        if c.op == ">":
            return actual > c.operand
        if c.op == "<":
            return actual < c.operand
        raise EtcdError("InvalidArgs", f"bad compare op {c.op}")

    def txn(self, t: Txn):
        succeeded = all(self._check(c) for c in t._when)
        ops = t._then if succeeded else t._else
        # validate before applying so a txn is all-or-nothing like real
        # etcd: the only op that can fail is a put with an unknown lease
        for op in ops:
            if op.kind == "put" and op.args[2].lease and (
                op.args[2].lease not in self.leases
            ):
                raise EtcdError("LeaseError", f"lease {op.args[2].lease} not found")
        results = []
        for op in ops:
            if op.kind == "put":
                results.append(("put", self.put(op.args[0], op.args[1], op.args[2])))
            elif op.kind == "get":
                results.append(("get", self.get(op.args[0], op.args[1])))
            elif op.kind == "delete":
                results.append(("delete", self.delete(op.args[0], op.args[1])))
        return {
            "header_revision": self.revision,
            "succeeded": succeeded,
            "responses": results,
        }

    # ---- leases (service.rs:286-370) ----------------------------------------
    def lease_grant(self, ttl: int, lease_id: int, rng) -> dict:
        if lease_id == 0:
            lease_id = rng.randrange(1, 1 << 62)
        if lease_id in self.leases:
            raise EtcdError("LeaseError", f"lease {lease_id} already exists")
        self.leases[lease_id] = [ttl, ttl, set()]
        return {"id": lease_id, "ttl": ttl}

    def lease_revoke(self, lease_id: int) -> dict:
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            raise EtcdError("LeaseError", f"lease {lease_id} not found")
        woken = []
        for key in sorted(lease[2]):
            self.kv.pop(key, None)
            woken.append(key)
        if woken:
            self.revision += 1
        return {"header_revision": self.revision, "expired_keys": woken}

    def lease_keep_alive(self, lease_id: int) -> dict:
        lease = self.leases.get(lease_id)
        if lease is None:
            raise EtcdError("LeaseError", f"lease {lease_id} not found")
        lease[1] = lease[0]
        return {"id": lease_id, "ttl": lease[0]}

    def lease_ttl(self, lease_id: int) -> dict:
        lease = self.leases.get(lease_id)
        if lease is None:
            raise EtcdError("LeaseError", f"lease {lease_id} not found")
        return {"id": lease_id, "granted_ttl": lease[0], "ttl": lease[1],
                "keys": sorted(lease[2])}

    def lease_list(self) -> dict:
        return {"leases": sorted(self.leases)}

    def tick(self) -> list[bytes]:
        """One simulated second: age leases, expire, delete attached keys,
        return expired election leader keys so campaigns re-run
        (service.rs:353-370)."""
        expired = [lid for lid, lease in self.leases.items() if lease[1] <= 1]
        for lease in self.leases.values():
            lease[1] -= 1
        keys = []
        for lid in expired:
            keys += self.lease_revoke(lid)["expired_keys"]
        return keys

    # ---- election (service.rs:372-442) ---------------------------------------
    def leader_kv(self, name: bytes) -> Optional[KeyValue]:
        cands = [kv for k, kv in self.kv.items() if k.startswith(name + b"/")]
        if not cands:
            return None
        return min(cands, key=lambda kv: kv.create_revision)

    def try_campaign(self, name: bytes, value: bytes, lease_id: int):
        """Succeeds iff nobody currently owns the election."""
        if self.leader_kv(name) is not None:
            return None
        key = name + b"/" + hex(lease_id)[2:].encode()
        self.put(key, value, PutOptions(lease=lease_id))
        kv = self.kv[key]
        return {"name": name, "key": key, "rev": kv.create_revision,
                "lease": lease_id}


class SimServer:
    """etcd server builder (server.rs:8-24):

        await etcd.SimServer(timeout_rate=0.1).serve("0.0.0.0:2379")
    """

    local_addr = None  # set once serving (bind port 0, read it here)

    def __init__(self, timeout_rate: float = 0.0):
        self.timeout_rate = timeout_rate
        self._inner = _ServiceInner()
        self._election_notify = make_notify()

    def with_timeout_rate(self, rate: float) -> "SimServer":
        self.timeout_rate = rate
        return self

    async def serve(self, addr: AddrLike) -> None:
        spawn(self._lease_ticker(), name="etcd-lease-ticker")
        await serve_requests(
            addr, self._handle, EtcdError, name="etcd-request",
            on_bound=lambda a: setattr(self, "local_addr", a),
        )

    async def _lease_ticker(self) -> None:
        # 1 s lease tick task (service.rs:20-26)
        while True:
            await sleep(1.0)
            expired = self._inner.tick()
            if expired:
                self._election_notify.notify_waiters()

    async def _handle(self, op: str, kwargs: dict) -> Any:
        # fault injection (service.rs:113-124): stall then Unavailable
        if self.timeout_rate > 0 and rng().random_bool(self.timeout_rate):
            await sleep(rng().randrange(5, 15))
            raise EtcdError("GRpcStatus", "Unavailable")
        return await self._dispatch(op, kwargs)

    async def _dispatch(self, op: str, kw: dict) -> Any:
        inner = self._inner
        if op == "put":
            return inner.put(kw["key"], kw["value"], kw["options"])
        if op == "get":
            return inner.get(kw["key"], kw["options"])
        if op == "delete":
            r = inner.delete(kw["key"], kw["options"])
            if r["deleted"]:
                # a deleted key may have been an election leader key:
                # wake blocked campaigns so they can re-check
                self._election_notify.notify_waiters()
            return r
        if op == "txn":
            r = inner.txn(kw["txn"])
            if any(
                kind == "delete" and res["deleted"]
                for kind, res in r["responses"]
            ):
                self._election_notify.notify_waiters()
            return r
        if op == "lease_grant":
            return inner.lease_grant(kw["ttl"], kw["id"], rng())
        if op == "lease_revoke":
            r = inner.lease_revoke(kw["id"])
            self._election_notify.notify_waiters()
            return r
        if op == "lease_keep_alive":
            return inner.lease_keep_alive(kw["id"])
        if op == "lease_ttl":
            return inner.lease_ttl(kw["id"])
        if op == "lease_list":
            return inner.lease_list()
        if op == "campaign":
            # FIFO wait until the election is free (poll_campaign,
            # service.rs:372-409)
            name, value, lease = kw["name"], kw["value"], kw["lease"]
            while True:
                win = inner.try_campaign(name, value, lease)
                if win is not None:
                    # a new leader exists: observers must hear about it
                    self._election_notify.notify_waiters()
                    return win
                if lease and lease not in inner.leases:
                    raise EtcdError("LeaseError", f"lease {lease} expired")
                await self._election_notify.notified()
        if op == "proclaim":
            key, value = kw["key"], kw["value"]
            kv = inner.kv.get(key)
            if kv is None:
                raise EtcdError("ElectError", "session expired / not leader")
            inner.put(key, value, PutOptions(lease=kv.lease))
            self._election_notify.notify_waiters()
            return {"header_revision": inner.revision}
        if op == "leader":
            kv = inner.leader_kv(kw["name"])
            if kv is None:
                raise EtcdError("ElectError", "no leader")
            return {"kv": kv._copy()}
        if op == "resign":
            key = kw["key"]
            if inner.kv.pop(key, None) is not None:
                inner.revision += 1
                self._election_notify.notify_waiters()
            return {"header_revision": inner.revision}
        if op == "observe":
            # leader-change stream — the reference server left this
            # unimplemented (madsim-etcd-client/src/server.rs:60); real
            # etcd semantics: report the current leader, then every
            # change, with rapid flaps allowed to coalesce
            return StreamReply(self._observe(kw["name"]))
        raise EtcdError("InvalidArgs", f"unknown op {op}")

    async def _observe(self, name: bytes):
        last = None
        while True:
            kv = self._inner.leader_kv(name)
            if kv is not None and (kv.key, kv.mod_revision) != last:
                last = (kv.key, kv.mod_revision)
                yield {"kv": kv._copy()}
                # re-check before parking: a change that landed while the
                # yielded item was in flight must not wait for the next wake
                continue
            await self._election_notify.notified()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _Raw(RequestClient):
    """One-connection-per-request client core (kv.rs:25-100 pattern)."""

    def __init__(self, ep, dst):
        super().__init__(
            ep, dst, lambda m: EtcdError("GRpcStatus", f"Unavailable: {m}")
        )


class Client:
    """``await etcd.Client.connect(["10.0.0.1:2379"])`` (sim.rs:33-45:
    takes the first endpoint)."""

    def __init__(self, raw: _Raw):
        self._raw = raw

    @classmethod
    async def connect(cls, endpoints, options: Any = None) -> "Client":
        if isinstance(endpoints, (str, tuple)):
            endpoints = [endpoints]
        dst = parse_addr(endpoints[0])
        ep = await bind_endpoint("0.0.0.0:0")
        return cls(_Raw(ep, dst))

    async def close(self) -> None:
        await self._raw.close()

    def kv_client(self) -> "KvClient":
        return KvClient(self._raw)

    def lease_client(self) -> "LeaseClient":
        return LeaseClient(self._raw)

    def election_client(self) -> "ElectionClient":
        return ElectionClient(self._raw)

    # convenience passthroughs like etcd-client's Client
    async def put(self, key, value, options: Optional[PutOptions] = None):
        return await self.kv_client().put(key, value, options)

    async def get(self, key, options: Optional[GetOptions] = None):
        return await self.kv_client().get(key, options)

    async def delete(self, key, options: Optional[DeleteOptions] = None):
        return await self.kv_client().delete(key, options)

    async def txn(self, txn: Txn):
        return await self.kv_client().txn(txn)


class KvClient:
    def __init__(self, raw: _Raw):
        self._raw = raw

    async def put(self, key, value, options: Optional[PutOptions] = None):
        return await self._raw.call(
            "put", key=_to_bytes(key), value=_to_bytes(value),
            options=options or PutOptions(),
        )

    async def get(self, key, options: Optional[GetOptions] = None):
        return await self._raw.call(
            "get", key=_to_bytes(key), options=options or GetOptions()
        )

    async def delete(self, key, options: Optional[DeleteOptions] = None):
        return await self._raw.call(
            "delete", key=_to_bytes(key), options=options or DeleteOptions()
        )

    async def txn(self, txn: Txn):
        return await self._raw.call("txn", txn=txn)


class LeaseKeeper:
    """Periodic keep-alive helper (lease.rs:170)."""

    def __init__(self, raw: _Raw, lease_id: int):
        self._raw = raw
        self.id = lease_id

    async def keep_alive(self) -> dict:
        return await self._raw.call("lease_keep_alive", id=self.id)


class LeaseClient:
    def __init__(self, raw: _Raw):
        self._raw = raw

    async def grant(self, ttl: int, lease_id: int = 0) -> dict:
        return await self._raw.call("lease_grant", ttl=int(ttl), id=int(lease_id))

    async def revoke(self, lease_id: int) -> dict:
        return await self._raw.call("lease_revoke", id=int(lease_id))

    async def keep_alive(self, lease_id: int) -> LeaseKeeper:
        keeper = LeaseKeeper(self._raw, lease_id)
        await keeper.keep_alive()
        return keeper

    async def time_to_live(self, lease_id: int) -> dict:
        return await self._raw.call("lease_ttl", id=int(lease_id))

    async def leases(self) -> dict:
        return await self._raw.call("lease_list")


class ElectionClient:
    def __init__(self, raw: _Raw):
        self._raw = raw

    async def campaign(self, name, value, lease: int) -> dict:
        """Blocks until this candidate wins ``name`` (FIFO order)."""
        return await self._raw.call(
            "campaign", name=_to_bytes(name), value=_to_bytes(value), lease=int(lease)
        )

    async def proclaim(self, key, value) -> dict:
        return await self._raw.call(
            "proclaim", key=_to_bytes(key), value=_to_bytes(value)
        )

    async def leader(self, name) -> dict:
        return await self._raw.call("leader", name=_to_bytes(name))

    async def resign(self, key) -> dict:
        return await self._raw.call("resign", key=_to_bytes(key))

    async def observe(self, name) -> ResponseStream:
        """Stream of leader changes for ``name``: the current leader
        first, then every handover (campaign win, proclaim, resign,
        lease expiry). Beats the reference — its server answers this
        with Unimplemented (madsim-etcd-client/src/server.rs:60).
        Iterate with ``async for`` or ``await stream.message()``;
        ``stream.close()`` cancels."""
        return await self._raw.call_stream("observe", name=_to_bytes(name))
