"""Dual-mode primitives: one service codebase, sim and real execution.

Every reference ecosystem crate is a drop-in that works in *both*
builds — `#[cfg(madsim)]` swaps the implementation at compile time
(madsim-etcd-client/src/lib.rs:1-8; madsim-rdkafka vendors the whole
real-rdkafka surface for the std build). Python has no cfg flags, so
the switch is a runtime check: when a deterministic simulation context
is active these helpers bind the sim network/time/rng, otherwise plain
asyncio and the std TCP endpoint. Service code (etcd, gRPC, kafka) uses
only this seam, making each simulator a true drop-in: the same client
and server classes run over localhost TCP unchanged.
"""

from __future__ import annotations

import asyncio as _real_asyncio
import random as _random_mod
from collections import deque
from typing import Any, Coroutine

from ..runtime import context

__all__ = [
    "bind_endpoint",
    "in_sim",
    "make_notify",
    "now_ns",
    "rng",
    "sleep",
    "spawn",
]


def in_sim() -> bool:
    return context.in_simulation()


def spawn(coro: Coroutine, name: str = ""):
    """Sim: deterministic task on the current node; std: asyncio task.
    Both returned handles support ``cancel()`` and ``await``."""
    from ..compat.asyncio import create_task

    return create_task(coro, name=name or None)


async def sleep(delay: float) -> None:
    from ..compat.asyncio import sleep as dual_sleep

    await dual_sleep(delay)


def now_ns() -> int:
    """Sim: virtual clock; std: the real clock."""
    if in_sim():
        from ..runtime.time_ import now_ns as sim_now_ns

        return sim_now_ns()
    import time as _time

    # the real-mode branch of the dual seam: outside a simulation the
    # real clock IS the contract
    return _time.time_ns()  # lint: allow(wall-clock)


class _StdRng(_random_mod.Random):
    def random_bool(self, p: float) -> bool:
        return self.random() < p


_std_rng = _StdRng()


def rng():
    """Sim: the seeded GlobalRng view (deterministic); std: a process
    RNG with the same surface."""
    if in_sim():
        from ..runtime.rand import thread_rng

        return thread_rng()
    return _std_rng


class _StdNotify:
    """asyncio mirror of :class:`madsim_tpu.sync.Notify`."""

    def __init__(self) -> None:
        self._notified = False
        self._waiters: deque = deque()

    async def notified(self) -> None:
        if self._notified:
            self._notified = False
            return
        fut = _real_asyncio.get_event_loop().create_future()
        self._waiters.append(fut)
        await fut

    def notify_one(self) -> None:
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)
                return
        self._notified = True

    def notify_waiters(self) -> None:
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)


def make_notify():
    if in_sim():
        from ..sync import Notify

        return Notify()
    return _StdNotify()


async def bind_endpoint(addr) -> Any:
    """The transport seam: the simulated Endpoint inside a simulation,
    the real-TCP Endpoint (std/net.py) outside. Both expose the same
    bind/send_to/recv_from/connect1/accept1 surface, which is exactly
    the reference's cfg-switch contract."""
    if in_sim():
        from ..net.endpoint import Endpoint

        return await Endpoint.bind(addr)
    from ..std.net import Endpoint as StdEndpoint

    return await StdEndpoint.bind(addr)
