"""Shared one-connection-per-request transport for service simulators.

The reference's etcd and kafka shims both use the same pattern — each
client op opens a connection, sends one request, reads one reply
(madsim-etcd-client/src/kv.rs:25-100, madsim-rdkafka's sim clients) and
the server answers each accepted connection once. This module is that
pattern factored out so connection hygiene (half-close on the server so
the reply drains; full close on the client after reading) lives in one
place for every service built on it.
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Type

from ..net.addr import AddrLike
from ._dual import bind_endpoint, spawn

__all__ = ["RequestClient", "ResponseStream", "StreamReply", "serve_requests"]


class StreamReply:
    """Wrap an async generator to stream a response item-per-message.

    A handler returning ``StreamReply(gen)`` keeps its connection open;
    each yielded item travels as one message until the generator ends or
    the client hangs up (the server-streaming shape of observe/watch
    style ops — the reference's tonic server-streaming analog).
    """

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen


class ResponseStream:
    """Client half of a streamed reply: ``async for`` or ``message()``."""

    def __init__(self, tx, rx, transport_error):
        self._tx = tx
        self._rx = rx
        self._err = transport_error
        self._done = False

    def __aiter__(self):
        return self

    async def __anext__(self):
        item = await self.message()
        if item is None:
            raise StopAsyncIteration
        return item

    async def message(self) -> Any | None:
        """Next item, or None when the stream ends (etcd-client shape)."""
        if self._done:
            return None
        reply = await self._rx.recv()
        if reply is None:
            self.close()
            return None
        status, payload = reply
        if status == "item":
            return payload
        self.close()
        if status == "err":
            raise payload
        return None  # "end"

    def close(self) -> None:
        """Cancel the stream; the server notices (send failure in sim,
        eof watcher on the std backend) and unwinds its generator."""
        self._done = True
        self._tx.close()
        self._rx.close()


class RequestClient:
    """Client core: ``await call(op, **kwargs)`` = one round-trip.

    ``transport_error(str) -> Exception`` wraps connection failures in
    the service's own error type.
    """

    def __init__(self, ep, dst, transport_error: Callable[[str], Exception]):
        self._ep = ep
        self._dst = dst
        self._err = transport_error

    async def close(self) -> None:
        """Release the underlying endpoint (the std backend holds real
        sockets and reader tasks; the sim endpoint a port-table entry)."""
        res = self._ep.close()
        if res is not None and hasattr(res, "__await__"):
            await res

    async def call(self, op: str, **kwargs: Any) -> Any:
        try:
            tx, rx = await self._ep.connect1(self._dst)
        except (ConnectionError, OSError) as e:
            raise self._err(str(e)) from e
        try:
            await tx.send((op, kwargs))
            reply = await rx.recv()
        except (ConnectionError, OSError) as e:
            raise self._err(str(e)) from e
        finally:
            # one request per connection: release pipes + pump tasks
            # (and the receive tag, on the std backend)
            tx.close()
            rx.close()
        if reply is None:
            raise self._err("connection reset")
        status, payload = reply
        if status == "err":
            raise payload
        return payload

    async def call_stream(self, op: str, **kwargs: Any) -> ResponseStream:
        """Open a server-streaming op; the connection stays up for the
        stream's lifetime (close the returned stream to cancel)."""
        try:
            tx, rx = await self._ep.connect1(self._dst)
            await tx.send((op, kwargs))
            first = await rx.recv()
        except (ConnectionError, OSError) as e:
            raise self._err(str(e)) from e
        if first is None:
            tx.close()
            rx.close()
            raise self._err("connection reset")
        status, payload = first
        if status == "err":
            tx.close()
            rx.close()
            raise payload
        if status != "ok-stream":
            tx.close()
            rx.close()
            raise self._err(f"expected a stream, got {status!r}")
        return ResponseStream(tx, rx, self._err)


async def serve_requests(
    addr: AddrLike,
    handler: Callable[[str, dict], Awaitable[Any]],
    error_type: Type[Exception],
    name: str = "service-request",
    on_bound: Callable[[Any], None] | None = None,
) -> None:
    """Server accept loop: each connection carries one (op, kwargs)
    request; the handler's return value (or raised ``error_type``) is
    the reply. Replies are half-closed so they drain through the pump
    before the peer sees EOF. Dual-mode: binds the sim Endpoint inside
    a simulation, the std TCP Endpoint outside.

    ``on_bound`` receives the bound local address — bind port 0 and read
    the real port from it (the flake-free pattern for test servers)."""
    ep = await bind_endpoint(addr)
    if on_bound is not None:
        on_bound(ep.local_addr)
    while True:
        tx, rx, _peer = await ep.accept1()
        spawn(_serve_one(tx, rx, handler, error_type), name=name)


async def _stream_items(tx, rx, gen, error_type) -> None:
    # cancellation watcher: the client closing its end surfaces as EOF
    # on our receive half (both backends), stopping the stream at its
    # next item instead of streaming to a closed peer forever
    cancelled = False

    async def watch():
        nonlocal cancelled
        while await rx.recv() is not None:
            pass
        cancelled = True

    watcher = spawn(watch(), name="stream-cancel-watch")
    try:
        async for item in gen:
            if cancelled:
                return
            await tx.send(("item", item))
        await tx.send(("end", None))
    finally:
        watcher.cancel()
        try:
            await gen.aclose()
        except RuntimeError:
            # task teardown delivered GeneratorExit while the generator
            # was suspended under this very frame; it is already unwinding
            pass


async def _serve_one(tx, rx, handler, error_type) -> None:
    try:
        req = await rx.recv()
        if req is None:
            return
        op, kwargs = req
        try:
            result = await handler(op, kwargs)
            if isinstance(result, StreamReply):
                await tx.send(("ok-stream", None))
                await _stream_items(tx, rx, result.gen, error_type)
            else:
                await tx.send(("ok", result))
        except error_type as e:
            try:
                await tx.send(("err", e))
            except ConnectionError:
                pass
        except ConnectionError:
            pass  # client hung up mid-stream: normal cancellation
    finally:
        tx.shutdown()
