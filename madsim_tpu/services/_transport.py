"""Shared one-connection-per-request transport for service simulators.

The reference's etcd and kafka shims both use the same pattern — each
client op opens a connection, sends one request, reads one reply
(madsim-etcd-client/src/kv.rs:25-100, madsim-rdkafka's sim clients) and
the server answers each accepted connection once. This module is that
pattern factored out so connection hygiene (half-close on the server so
the reply drains; full close on the client after reading) lives in one
place for every service built on it.
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Type

from ..net.addr import AddrLike
from ..net.endpoint import Endpoint
from ..runtime.task import spawn

__all__ = ["RequestClient", "serve_requests"]


class RequestClient:
    """Client core: ``await call(op, **kwargs)`` = one round-trip.

    ``transport_error(str) -> Exception`` wraps connection failures in
    the service's own error type.
    """

    def __init__(self, ep: Endpoint, dst, transport_error: Callable[[str], Exception]):
        self._ep = ep
        self._dst = dst
        self._err = transport_error

    async def call(self, op: str, **kwargs: Any) -> Any:
        try:
            tx, rx = await self._ep.connect1(self._dst)
        except (ConnectionError, OSError) as e:
            raise self._err(str(e)) from e
        try:
            await tx.send((op, kwargs))
            reply = await rx.recv()
        except (ConnectionError, OSError) as e:
            raise self._err(str(e)) from e
        finally:
            # one request per connection: release pipes + pump tasks
            tx.close()
        if reply is None:
            raise self._err("connection reset")
        status, payload = reply
        if status == "err":
            raise payload
        return payload


async def serve_requests(
    addr: AddrLike,
    handler: Callable[[str, dict], Awaitable[Any]],
    error_type: Type[Exception],
    name: str = "service-request",
) -> None:
    """Server accept loop: each connection carries one (op, kwargs)
    request; the handler's return value (or raised ``error_type``) is
    the reply. Replies are half-closed so they drain through the pump
    before the peer sees EOF."""
    ep = await Endpoint.bind(addr)
    while True:
        tx, rx, _peer = await ep.accept1()
        spawn(_serve_one(tx, rx, handler, error_type), name=name)


async def _serve_one(tx, rx, handler, error_type) -> None:
    try:
        req = await rx.recv()
        if req is None:
            return
        op, kwargs = req
        try:
            result = await handler(op, kwargs)
            await tx.send(("ok", result))
        except error_type as e:
            try:
                await tx.send(("err", e))
            except ConnectionError:
                pass
        except ConnectionError:
            pass
    finally:
        tx.shutdown()
