"""Service-level simulators — the ecosystem shims of the reference.

  * :mod:`grpc`  — gRPC-style typed services over simulated connections
                   (parity: madsim-tonic, reference madsim-tonic/src/)
  * :mod:`etcd`  — etcd v3 KV/Txn/Lease/Election state machine
                   (parity: madsim-etcd-client, src/service.rs)
  * :mod:`kafka` — Kafka-style producer/consumer/admin over a SimBroker
                   (parity: madsim-rdkafka, src/sim/)

Each runs as ordinary user tasks inside the single-seed runtime, built on
``madsim_tpu.net.Endpoint`` exactly as the reference shims are built on
its Endpoint (SURVEY.md §1 L3).
"""

from . import grpc  # noqa: F401
from . import etcd  # noqa: F401
from . import kafka  # noqa: F401
