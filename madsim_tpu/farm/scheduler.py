"""Multi-tenant campaign scheduling over one device set.

A campaign driver owns the mesh while it runs; a farm serves MANY
hunts — different workloads, spaces and configs — on the same chips.
:func:`run_farm` time-slices N :class:`Tenant` campaigns in
generation-sized quanta, and the whole trick is that both halves of a
tenant switch were already built and certified:

* **preemption is the checkpoint path**: a tenant's slice ends by
  snapshotting its ``CampaignState`` (``persist.CampaignState
  .from_report``) and resumes later through ``resume=`` — the SAME
  splice the save/resume tests pin as bit-identical, because every
  draw is keyed by absolute generation index. A scheduled tenant's
  final corpus/coverage/violations equal its standalone run's,
  whatever the interleaving (test-pinned).
* **switching is compile-free**: the explore generation-program cache
  (``_GEN_CACHE``) keys programs by campaign shape, so each tenant's
  uniform/breed pair is built once and every later slice reuses it —
  retraces == 1 per program key across the whole session,
  profiler-certified (``obs.prof``). Size the cache to the tenant set
  with ``MADSIM_GEN_CACHE_MAX``; eviction counts surface in
  ``flight_summary``.

Slices are awarded round-robin by default (reproducible), or by a
:class:`~.energy.FarmEnergy` power schedule (budget shifts toward
tenants still finding new coverage bits / violations — the
tenant-level AFLFast analogy). All tenants can share one
``obs.FlightRecorder``: the scheduler tags each slice's records with
the tenant name (``FlightRecorder.tagged``), and
``tools/campaign_top.py`` renders the tagged stream as a per-tenant
farm dashboard.
"""

from __future__ import annotations

import dataclasses

from ..explore.device import run_device
from ..explore.persist import CampaignState
from .pipeline import run_pipelined

__all__ = ["FarmReport", "Tenant", "run_farm"]


@dataclasses.dataclass
class Tenant:
    """One farm tenant: a (workload, space, config) campaign plus its
    driver arguments.

    ``generations`` is the tenant's own budget (None = unbounded —
    legal only under a farm-wide ``total_generations``). ``kwargs``
    are passed to the campaign driver verbatim (``invariant``,
    ``batch``, ``root_seed``, ``max_steps``, ``cov_words``, ... —
    everything ``explore.run_device`` takes except ``generations``,
    ``resume`` and ``telemetry``, which the scheduler owns).
    """

    name: str
    wl: object
    cfg: object
    space: object
    generations: int | None = None
    kwargs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FarmReport:
    """Outcome of one scheduled farm session."""

    reports: dict  # tenant name -> final ExploreReport
    schedule: list  # [(slice index, tenant name, generations run)]
    preemptions: dict  # tenant name -> times resumed after preemption
    slices: int

    def banner(self) -> str:
        lines = [
            f"farm: {len(self.reports)} tenants over {self.slices} slices"
        ]
        for name, rep in self.reports.items():
            lines.append(
                f"  {name:<20} {rep.generations:>4} gens | "
                f"{rep.coverage_bits:>5} cov bits | corpus "
                f"{len(rep.corpus):>5} | violations "
                f"{len(rep.violations):>4} | preempted "
                f"{self.preemptions.get(name, 0)}x"
            )
        return "\n".join(lines)


def _tagged_sink(telemetry, name: str):
    if telemetry is None:
        return None
    tagged = getattr(telemetry, "tagged", None)
    if tagged is not None:
        return tagged(name)
    return lambda rec, _s=telemetry, _n=name: _s({**rec, "tenant": _n})


def run_farm(
    tenants,
    *,
    quantum: int = 1,
    total_generations: int | None = None,
    pipeline: bool = False,
    energy=None,
    telemetry=None,
    log=None,
) -> FarmReport:
    """Time-slice ``tenants`` over one device set.

    Each slice runs ONE tenant for up to ``quantum`` generations
    through ``explore.run_device`` (or the pipelined driver with
    ``pipeline=True``), then preempts it via the in-memory
    checkpoint/resume splice. Slices are awarded round-robin in tenant
    declaration order, or by ``energy`` (a :class:`~.energy.FarmEnergy`)
    — a deterministic weighted draw favoring tenants whose last slice
    found new coverage or violations.

    ``total_generations`` caps the farm-wide generation budget (the
    equal-budget knob adaptive-vs-uniform comparisons hold fixed);
    per-tenant ``Tenant.generations`` caps still apply. The session
    ends when every tenant hits its cap or the farm budget runs out.

    A scheduled tenant's outcome is bit-identical to running it
    standalone for the same generation count — the module-docstring
    invariants; the per-tenant ``ExploreReport`` in the returned
    :class:`FarmReport` is the final resumed report (its ``wall_*``
    timers cover the last slice, its corpus/coverage the whole
    campaign).
    """
    tenants = list(tenants)
    if not tenants:
        raise ValueError("run_farm needs at least one tenant")
    names = [t.name for t in tenants]
    if len(set(names)) != len(names):
        raise ValueError(f"tenant names must be unique, got {names}")
    if quantum < 1:
        raise ValueError("need quantum >= 1")
    for t in tenants:
        if t.generations is None and total_generations is None:
            raise ValueError(
                f"tenant {t.name!r} has no generation budget and the farm "
                f"has no total_generations — one bound is required"
            )
        for owned in ("generations", "resume", "telemetry"):
            if owned in t.kwargs:
                raise ValueError(
                    f"tenant {t.name!r} kwargs carry {owned!r}: the "
                    f"scheduler owns it (Tenant docstring)"
                )
    runner = run_pipelined if pipeline else run_device

    states: dict = {t.name: None for t in tenants}
    reports: dict = {}
    done = {t.name: 0 for t in tenants}
    slices_of = {t.name: 0 for t in tenants}
    gains: dict = {}  # name -> (new cov bits, new violations) last slice
    last_cov = {t.name: 0 for t in tenants}
    last_viol = {t.name: 0 for t in tenants}
    schedule: list = []
    total_done = 0
    slice_idx = 0
    cursor = 0  # round-robin position over the declaration order

    def _remaining(t: Tenant) -> int:
        if t.generations is None:
            return total_generations - total_done
        return t.generations - done[t.name]

    while True:
        if total_generations is not None and total_done >= total_generations:
            break
        live = [t for t in tenants if _remaining(t) > 0]
        if not live:
            break
        if energy is not None and energy.active:
            by_name = {t.name: t for t in live}
            t = by_name[energy.pick(slice_idx, [t.name for t in live], gains)]
        else:
            while tenants[cursor % len(tenants)] not in live:
                cursor += 1
            t = tenants[cursor % len(tenants)]
            cursor += 1
        gens = min(quantum, _remaining(t))
        if total_generations is not None:
            gens = min(gens, total_generations - total_done)
        rep = runner(
            t.wl, t.cfg, t.space, generations=gens,
            resume=states[t.name],
            telemetry=_tagged_sink(telemetry, t.name),
            **({"log": log} if log is not None and "log" not in t.kwargs
               else {}),
            **t.kwargs,
        )
        # preemption IS the checkpoint path: snapshot, resume next slice
        states[t.name] = CampaignState.from_report(rep)
        reports[t.name] = rep
        gains[t.name] = (
            rep.coverage_bits - last_cov[t.name],
            len(rep.violations) - last_viol[t.name],
        )
        last_cov[t.name] = rep.coverage_bits
        last_viol[t.name] = len(rep.violations)
        done[t.name] += gens
        total_done += gens
        slices_of[t.name] += 1
        schedule.append((slice_idx, t.name, gens))
        if log is not None:
            log(
                f"farm slice {slice_idx}: {t.name} +{gens} gens "
                f"(done {done[t.name]}, +{gains[t.name][0]} cov bits, "
                f"+{gains[t.name][1]} violations)"
            )
        slice_idx += 1

    return FarmReport(
        reports=reports,
        schedule=schedule,
        preemptions={n: max(s - 1, 0) for n, s in slices_of.items()},
        slices=slice_idx,
    )
