"""madsim_tpu.farm — the always-on fuzzing farm.

One exploration campaign is a blocking Python loop over generations,
and one (workload, space) pair owns the whole device set until it
finishes. The farm turns that single loop into a service-shaped
subsystem, three cooperating layers over the explore drivers:

* **pipelined generations** (:func:`run_pipelined`, farm/pipeline.py) —
  double-buffer ``explore.run_device``: generation g+1's dispatch is
  enqueued before generation g's admission summary, checkpointing and
  flight telemetry are processed on the host, with the strict
  ``jax.block_until_ready`` only at the consume point. The new
  ``queue_wall_s`` / ``idle_wall_s`` split measures the overlap;
  corpus, coverage and violations stay bit-identical to the blocking
  driver (draw keys are addressed by absolute generation index — this
  is a scheduling change, not a semantics change).
* **a campaign scheduler** (:func:`run_farm`, farm/scheduler.py) — N
  :class:`Tenant` (workload, space, config) triples time-sliced over
  one mesh in generation-sized quanta. Preemption is exactly the
  checkpoint/resume path (``CampaignState`` / ``resolve_resume`` —
  already bit-identical across splice points), every tenant's
  generation programs stay resident in the explore ``_GEN_CACHE``
  (retraces == 1 across the whole session, profiler-certified), and
  telemetry streams are tenant-tagged so ``tools/campaign_top.py``
  renders the whole farm.
* **adaptive energy assignment** (:class:`EnergySchedule` /
  :class:`FarmEnergy`, farm/energy.py) — AFLFast-style power schedules
  at two levels: across corpus entries (energy decays with
  times-picked, boosts rare-path coverage and violations) and across
  tenants (budget shifts toward tenants still finding new coverage /
  violations). The uniform schedule is the reproducible default, and
  every energy draw is threefry-keyed under the registered ``farm``
  purpose lane — disjoint from the explore lane, so energy on/off
  never shifts a mutation draw.

Evidence artifact: ``tools/farm_soak.py`` (FARM_r11.txt).
"""

from .energy import EnergySchedule, FarmEnergy  # noqa: F401
from .pipeline import run_pipelined  # noqa: F401
from .scheduler import FarmReport, Tenant, run_farm  # noqa: F401

__all__ = [
    "EnergySchedule",
    "FarmEnergy",
    "FarmReport",
    "Tenant",
    "run_farm",
    "run_pipelined",
]
