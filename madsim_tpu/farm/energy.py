"""AFLFast-style power schedules for the farm, at two levels.

AFL's insight (refined by AFLFast, Boehme et al. CCS'16): not every
corpus entry deserves the same mutation budget. Entries exercising
rare paths and entries that have not been fuzzed much deserve MORE
energy; entries picked over and over deserve exponentially less — the
schedule moves budget from the well-mined center of the corpus to its
frontier. The farm applies the same economics twice:

* **across corpus entries** (:class:`EnergySchedule`, plugged into
  ``explore.run(energy=...)``): a parent's weight starts from its
  admission score (``new_bits``, the bits it set first), gains bonuses
  for violating and for touching rare coverage bits (bits set by at
  most ``rare_k`` entries), and decays polynomially with the number of
  times it has already been picked. Seed inheritance becomes
  per-parent (violating parents hold their engine seed more often —
  the fault alignment is the find).
* **across tenants** (:class:`FarmEnergy`, plugged into
  ``farm.run_farm(energy=...)``): each scheduler slice is awarded by
  weighted draw where a tenant's weight is its last slice's new
  coverage bits plus a violation bonus — budget drains away from
  plateaued tenants toward those still finding things.

Determinism is non-negotiable: every draw at both levels comes from
counter-based threefry under the registered ``farm`` purpose lane
(``engine.rng.PURPOSE_FARM`` — per-child parent picks at ``x1 =
base``, tenant awards at ``x1 = base + 1``), disjoint by the lane
registry from the explore mutation stream. Turning energy on changes
WHICH parents breed, never the draws a given (parent, child key)
mutation consumes; turning it off (``mode="uniform"``, or simply not
passing it) is bit-identical to the historical uniform schedule — the
reproducible default, test-pinned.

All weights are integer arithmetic (no float accumulation), so a
schedule replays exactly across platforms.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..engine.rng import PURPOSE_FARM, np_threefry2x32
from ..explore.mutate import HostStream, inherit_threshold

__all__ = ["EnergySchedule", "FarmEnergy"]


@dataclasses.dataclass(frozen=True)
class EnergySchedule:
    """Corpus-entry power schedule for ``explore.run(energy=...)``.

    ``mode="fast"`` (the AFLFast shape) is the only adaptive mode;
    ``mode="uniform"`` is inert — the driver runs its historical
    frontier-first ``select_top``/``inherit_seed_p`` pick,
    bit-identically (the non-interference certificate in
    tools/lint_soak.py pins this).

    The parent pool is the driver's OWN frontier (violating first,
    newest first — recency won the kvchaos equal-budget measurement,
    and diluting energy across the whole mined corpus measurably loses
    to it), ``top`` entries deep (None = the driver's ``select_top``).
    An entry's integer weight each generation:

        base = 1 + min(new_bits, bits_cap)
               + viol_bonus  (if the entry violates)
               + rare_bonus  (if it touches a bit set by <= rare_k
                              pool entries)
        weight = max(base * 64 // (1 + times_picked) ** decay, 1)

    ``bits_cap`` bounds the admission-score term: an outlier entry
    that lit up 30 new bits should not soak up the whole generation's
    energy (parent DIVERSITY is itself budget — concentrated picks
    breed duplicate traces the dedup then discards).

    ``inherit_seed_p`` / ``inherit_viol_p`` are the per-parent seed
    inheritance probabilities; None inherits the campaign's
    ``inherit_seed_p`` (violating parents floor at 0.9 — holding the
    engine seed through the mutation is how a fault alignment is
    tuned rather than re-rolled).
    """

    mode: str = "fast"
    viol_bonus: int = 8
    rare_bonus: int = 4
    rare_k: int = 2
    decay: int = 2
    bits_cap: int = 32
    top: int | None = None
    inherit_seed_p: float | None = None
    inherit_viol_p: float | None = None

    @property
    def active(self) -> bool:
        return self.mode != "uniform"

    def state(self) -> "_EnergyState":
        """Fresh per-campaign mutable state (times-picked counters)."""
        if self.mode not in ("uniform", "fast"):
            raise ValueError(
                f"unknown energy mode {self.mode!r} (uniform|fast)"
            )
        return _EnergyState(self)


class _EnergyState:
    """One campaign's energy bookkeeping: the times-picked counters and
    the per-generation weight table. Owned by the driver loop; never
    serialized (a resumed campaign restarts its pick counters — the
    corpus scores it weights from ARE checkpointed)."""

    def __init__(self, sched: EnergySchedule):
        self.sched = sched
        self.picks: dict = {}  # corpus id -> times picked as parent

    def pool(self, corpus, select_top: int = 32):
        """The generation's parent pool and cumulative weights.

        Recomputed once per generation (picks made within a generation
        take effect the next one — batch-order independence keeps the
        weight table one vectorized pass)."""
        sched = self.sched
        # the driver's frontier order, at the schedule's own depth
        pool = sorted(
            corpus, key=lambda e: (not e.violating, -e.id)
        )[: max(sched.top if sched.top is not None else select_top, 1)]
        covs = np.stack([np.asarray(e.cov, np.uint32) for e in pool])
        bits = np.unpackbits(covs.view(np.uint8), axis=1).astype(bool)
        counts = bits.sum(axis=0)
        rare_cols = (counts > 0) & (counts <= sched.rare_k)
        rare = (bits & rare_cols[None, :]).any(axis=1)
        weights = np.empty(len(pool), np.int64)
        for i, e in enumerate(pool):
            base = 1 + min(int(e.new_bits), sched.bits_cap)
            if e.violating:
                base += sched.viol_bonus
            if bool(rare[i]):
                base += sched.rare_bonus
            picked = self.picks.get(e.id, 0)
            weights[i] = max((base * 64) // (1 + picked) ** sched.decay, 1)
        return pool, np.cumsum(weights)

    def choose(self, k0: int, k1: int, pool, cum) -> int:
        """Weighted parent pick for one child slot — ONE threefry draw
        on the farm lane (the child's own key, ``x1 = PURPOSE_FARM``),
        leaving the explore-lane mutation stream untouched."""
        fs = HostStream(k0, k1, PURPOSE_FARM)
        r = fs.bits() % int(cum[-1])
        i = int(np.searchsorted(cum, r, side="right"))
        e = pool[i]
        self.picks[e.id] = self.picks.get(e.id, 0) + 1
        return e.id

    def inherit_threshold(self, entry, default_p: float) -> int:
        seed_p = (self.sched.inherit_seed_p
                  if self.sched.inherit_seed_p is not None else default_p)
        if entry.violating:
            p = (self.sched.inherit_viol_p
                 if self.sched.inherit_viol_p is not None
                 else max(seed_p, 0.9))
        else:
            p = seed_p
        return inherit_threshold(p)


@dataclasses.dataclass(frozen=True)
class FarmEnergy:
    """Tenant-level power schedule for ``farm.run_farm(energy=...)``.

    Each scheduler slice is awarded to a live tenant by one weighted
    threefry draw (``x1 = PURPOSE_FARM + 1``, x0 = the slice index —
    coordinate-addressed, so the award sequence is a pure function of
    ``root_seed`` and the gain history). A tenant's weight:

        floor + last-slice new coverage bits
              + viol_weight * last-slice new violations

    Tenants that have never run weigh ``bootstrap`` (optimism: every
    tenant gets sampled before the gains can judge it).
    ``mode="uniform"`` is round-robin — the reproducible default
    ``run_farm`` uses when no energy is passed.
    """

    mode: str = "adaptive"
    root_seed: int = 0
    viol_weight: int = 16
    floor: int = 1
    bootstrap: int = 32

    @property
    def active(self) -> bool:
        return self.mode != "uniform"

    def pick(self, slice_idx: int, names, gains: dict) -> str:
        """The tenant awarded slice ``slice_idx``. ``names`` are the
        live tenants in declaration order; ``gains`` maps a tenant to
        its last slice's ``(new_cov_bits, new_violations)``."""
        weights = []
        for n in names:
            g = gains.get(n)
            if g is None:
                w = max(int(self.bootstrap), 1)
            else:
                w = max(
                    int(self.floor)
                    + int(g[0]) + int(self.viol_weight) * int(g[1]),
                    1,
                )
            weights.append(w)
        total = sum(weights)
        root = int(self.root_seed)
        a, _ = np_threefry2x32(
            np.uint32(root & 0xFFFFFFFF),
            np.uint32((root >> 32) & 0xFFFFFFFF),
            np.uint32(slice_idx & 0xFFFFFFFF),
            np.uint32(PURPOSE_FARM + 1),
        )
        r = int(a) % total
        acc = 0
        for n, w in zip(names, weights):
            acc += w
            if r < acc:
                return n
        return names[-1]
