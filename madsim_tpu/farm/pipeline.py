"""Pipelined device campaigns: dispatch ahead, consume behind.

``explore.run_device`` is a strictly alternating loop — dispatch one
generation, block on its admission summary, do host work (telemetry,
checkpoint serialization), dispatch the next. jax dispatch is
asynchronous, so every millisecond of that host work is a millisecond
the device sits idle for no reason: the next generation's program and
inputs are already known (the carry is a device future, the generation
index and root key are host scalars).

:func:`run_pipelined` is the SAME campaign on an overlapped schedule —
a depth-``depth`` (default 2) double buffer:

    enqueue g, g+1                      # call_async, no barrier
    loop: block_until_ready(summary g)  # the ONE consume-point sync
          consume g (summary fetch, telemetry, checkpoint) while the
            device executes g+1
          enqueue g+2

Bit-identity with the blocking driver is the hard invariant, not a
best effort: both drivers run the identical cached generation programs
(``explore.device._CampaignSession``) with draw keys addressed by
absolute generation index, so the corpus, coverage map, violations and
every checkpoint are bit-for-bit equal — the schedule moves WHEN the
host observes a generation, never WHAT the generation computes.

The one speculative choice is the uniform-vs-breed program for a
generation whose predecessors have not been consumed yet: the corpus
count is monotone non-decreasing, so the pipeline optimistically
predicts *breed* whenever admissions are in flight. A misprediction
(possible only at the empty->non-empty corpus boundary, i.e. when a
whole generation admitted nothing) is detected at the consume point
and repaired by re-dispatching from the pre-generation carry — the
generation programs are pure functions of ``(carry, g, root key)``, so
the discarded speculative execution costs wall clock, never
correctness (``respeculations`` in the campaign_end record counts
them).

The wall split makes the overlap measurable: ``queue_wall_s`` is host
time spent enqueueing dispatches, ``idle_wall_s`` is host time blocked
at the consume point waiting for the device. Host-side work that the
blocking driver serialized after the dispatch now lands inside the
device's execution window — ``tools/farm_soak.py`` banks the A/B
(FARM_r11.txt).
"""

from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp

from ..explore.device import _CampaignSession
from ..explore.driver import ExploreReport

__all__ = ["run_pipelined"]


def run_pipelined(
    wl,
    cfg,
    space,
    *,
    invariant=None,
    depth: int = 2,
    generations: int = 8,
    batch: int = 256,
    root_seed: int = 0,
    max_steps: int = 1000,
    cov_words: int = 32,
    layout: str | None = None,
    require_halt: bool = False,
    seed_corpus=(),
    select_top: int = 32,
    max_corpus: int = 4096,
    max_ops: int = 3,
    inherit_seed_p: float = 0.75,
    log=None,
    cov_hitcount: bool = False,
    telemetry=None,
    resume=None,
    checkpoint_path: str | None = None,
    latency=None,
    metrics: bool = False,
    mesh=None,
    viol_cap: int | None = None,
    pool_index: bool | None = None,
    history_check=None,
    causal: bool = False,
) -> ExploreReport:
    """``explore.run_device`` on a depth-``depth`` pipelined schedule.

    Same contract, same arguments (plus ``depth``), bit-identical
    outcomes — corpus, coverage map, violations, checkpoints and replay
    keys all match the blocking driver (module docstring). ``depth=1``
    degenerates to the blocking schedule and exists for A/B sanity.

    Telemetry differences, by design: ``generation`` records carry the
    measured ``queue_wall_s``/``idle_wall_s`` split (the blocking
    drivers emit zeros), ``dispatch_wall_s`` is their sum, and the
    ``campaign_end`` record adds ``respeculations`` (discarded
    speculative dispatches — nonzero only when a generation admitted
    nothing while the pipeline was breeding ahead). ``host_syncs`` is
    still exactly 1 per generation, at the consume point.
    """
    if depth < 1:
        raise ValueError("need pipeline depth >= 1")
    sess = _CampaignSession(
        wl, cfg, space, invariant=invariant, generations=generations,
        batch=batch, root_seed=root_seed, max_steps=max_steps,
        cov_words=cov_words, layout=layout, require_halt=require_halt,
        seed_corpus=seed_corpus, select_top=select_top,
        max_corpus=max_corpus, max_ops=max_ops,
        inherit_seed_p=inherit_seed_p, log=log, cov_hitcount=cov_hitcount,
        telemetry=telemetry, resume=resume,
        checkpoint_path=checkpoint_path, latency=latency, metrics=metrics,
        mesh=mesh, viol_cap=viol_cap, pool_index=pool_index,
        history_check=history_check, causal=causal,
    )
    sess.log_label = "pipelined"
    sess.start("device-pipelined", pipeline_depth=depth)

    wall_queue = 0.0
    wall_idle = 0.0
    wall_sync = 0.0
    wall_compile = 0.0
    host_syncs = 0
    respeculations = 0
    g_end = sess.g_start + generations
    g_next = sess.g_start
    pending: list = []  # in-flight generations, oldest first

    def _dispatch(g: int, breed: bool) -> dict:
        """Enqueue generation ``g``'s program (no completion barrier)
        and advance the speculative carry chain."""
        nonlocal wall_queue, wall_compile
        t0 = _time.monotonic()  # lint: allow(wall-clock)
        runner = sess.runner(breed)
        carry_before = sess.carry
        carry_after, summary, extras = runner.call_async(
            carry_before, jnp.uint32(g), sess.rk0, sess.rk1
        )
        build = runner.last_build_s
        t1 = _time.monotonic()  # lint: allow(wall-clock)
        sess.carry = carry_after
        queue_s = (t1 - t0) - build
        wall_queue += queue_s
        wall_compile += build
        return dict(
            g=g, breed=breed, carry_before=carry_before, carry=carry_after,
            summary=summary, extras=extras, queue_s=queue_s, build_s=build,
        )

    while g_next < g_end or pending:
        while g_next < g_end and len(pending) < depth:
            # optimistic mode prediction: the corpus count is monotone
            # non-decreasing, so a known-nonempty corpus means breed
            # for certain; with unconsumed admissions in flight,
            # speculate breed (a generation that admits NOTHING is the
            # only way this is wrong)
            breed = g_next > 0 and (sess.count > 0 or len(pending) > 0)
            pending.append(_dispatch(g_next, breed))
            g_next += 1
        item = pending.pop(0)
        g = item["g"]
        # all generations < g are consumed, so sess.count is exactly
        # the count the blocking driver would see before dispatching g
        actual_breed = g > 0 and sess.count > 0
        if actual_breed != item["breed"]:
            # mispredicted speculation: the programs are pure functions
            # of (carry, g, root key), so discard the speculative chain
            # and recompute from the pre-g carry — wall clock lost,
            # bit-identity kept
            respeculations += 1 + len(pending)
            pending.clear()
            g_next = g + 1
            sess.carry = item["carry_before"]
            item = _dispatch(g, actual_breed)
        t0 = _time.monotonic()  # lint: allow(wall-clock)
        jax.block_until_ready(item["summary"])  # THE consume-point sync
        t1 = _time.monotonic()  # lint: allow(wall-clock)
        s = jax.device_get(item["summary"])
        host_syncs += 1
        fleet = sess.fleet(item["extras"])
        t2 = _time.monotonic()  # lint: allow(wall-clock)
        idle = t1 - t0
        sync = t2 - t1
        wall_idle += idle
        wall_sync += sync
        # consume against generation g's OWN carry: sess.carry has
        # already speculated ahead, and the per-generation checkpoint
        # must snapshot the campaign as of g (it also overlaps the
        # device executing g+1 — the whole point of the schedule)
        sess.consume(g, s, fleet, {
            "dispatch_wall_s": round(item["queue_s"] + idle, 3),
            "compile_wall_s": round(item["build_s"], 3),
            "sync_wall_s": round(sync, 3),
            "queue_wall_s": round(item["queue_s"], 3),
            "idle_wall_s": round(idle, 3),
        }, carry=item["carry"])

    wall_dispatch = wall_queue + wall_idle
    sess.emit({
        "event": "campaign_end", "generations": g_end,
        "generations_run": generations,
        "sims": sess.sims,
        "cov_bits": sess.curve[-1] if sess.curve else 0,
        "corpus_size": sess.count, "violations": sess.vcount_host,
        "wall_dispatch_s": round(wall_dispatch, 3),
        "wall_sync_s": round(wall_sync, 3),
        "wall_compile_s": round(wall_compile, 3),
        "wall_queue_s": round(wall_queue, 3),
        "wall_idle_s": round(wall_idle, 3),
        "host_syncs": host_syncs,
        "respeculations": respeculations,
    })
    return sess.report(
        wall_dispatch=wall_dispatch, wall_sync=wall_sync,
        wall_compile=wall_compile, host_syncs=host_syncs,
        wall_queue=wall_queue, wall_idle=wall_idle,
    )
