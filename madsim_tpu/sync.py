"""Deterministic async synchronization primitives.

The reference reuses real tokio's ``sync`` module unchanged because those
primitives are already deterministic *given deterministic scheduling*
(madsim-tokio/src/lib.rs:39-52 — the key insight called out in SURVEY.md
§2 C21). Python has no tokio to borrow, so this module provides the same
API surface natively: oneshot / mpsc / watch / broadcast channels, Mutex,
RwLock, Semaphore, Notify, Barrier. All wakeups go through SimFutures
polled by the seeded executor, so lock handoff order is randomized per
seed and reproducible from it.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Generic, Optional, TypeVar

from .runtime.future import SimFuture

T = TypeVar("T")

__all__ = [
    "oneshot",
    "channel",
    "unbounded_channel",
    "watch",
    "broadcast",
    "Mutex",
    "RwLock",
    "Semaphore",
    "Notify",
    "Barrier",
    "ChannelClosed",
]


class ChannelClosed(Exception):
    """All senders (or the receiver) of a channel are gone."""


# ---- oneshot -------------------------------------------------------------


class OneshotSender(Generic[T]):
    __slots__ = ("_fut",)

    def __init__(self, fut: SimFuture):
        self._fut = fut

    def send(self, value: T) -> None:
        if self._fut.done():
            raise ChannelClosed("oneshot receiver already resolved")
        self._fut.set_result(("ok", value))

    def is_closed(self) -> bool:
        return self._fut.done()


class OneshotReceiver(Generic[T]):
    __slots__ = ("_fut",)

    def __init__(self, fut: SimFuture):
        self._fut = fut

    def __await__(self):
        return self.recv().__await__()

    async def recv(self) -> T:
        kind, value = await self._fut
        if kind == "closed":
            raise ChannelClosed("oneshot sender dropped")
        return value

    def close(self) -> None:
        if not self._fut.done():
            self._fut.set_result(("closed", None))


def oneshot() -> tuple[OneshotSender, OneshotReceiver]:
    fut = SimFuture(name="oneshot")
    return OneshotSender(fut), OneshotReceiver(fut)


# ---- mpsc ----------------------------------------------------------------


class _ChannelCore:
    __slots__ = ("capacity", "queue", "recv_waiters", "send_waiters", "closed")

    def __init__(self, capacity: Optional[int]):
        self.capacity = capacity
        self.queue: deque = deque()
        self.recv_waiters: deque[SimFuture] = deque()
        self.send_waiters: deque[SimFuture] = deque()
        self.closed = False

    def _wake_one(self, waiters: deque) -> bool:
        while waiters:
            w = waiters.popleft()
            if not w.done():
                w.set_result(None)
                return True
        return False

    def push(self, item: Any) -> None:
        # hand directly to a waiting receiver when possible
        while self.recv_waiters:
            w = self.recv_waiters.popleft()
            if not w.done():
                w.set_result(("ok", item))
                return
        self.queue.append(item)

    def close(self) -> None:
        self.closed = True
        while self.recv_waiters:
            w = self.recv_waiters.popleft()
            if not w.done():
                w.set_result(("closed", None))
        while self.send_waiters:
            w = self.send_waiters.popleft()
            if not w.done():
                w.set_result(None)


class Sender(Generic[T]):
    __slots__ = ("_core",)

    def __init__(self, core: _ChannelCore):
        self._core = core

    async def send(self, value: T) -> None:
        core = self._core
        if core.closed:
            raise ChannelClosed("channel closed")
        if core.capacity is not None:
            while len(core.queue) >= core.capacity and not core.closed:
                fut = SimFuture(name="chan.send")
                core.send_waiters.append(fut)
                await fut
            if core.closed:
                raise ChannelClosed("channel closed")
        core.push(value)

    def try_send(self, value: T) -> bool:
        core = self._core
        if core.closed:
            raise ChannelClosed("channel closed")
        if core.capacity is not None and len(core.queue) >= core.capacity:
            return False
        core.push(value)
        return True

    def close(self) -> None:
        self._core.close()


class Receiver(Generic[T]):
    __slots__ = ("_core",)

    def __init__(self, core: _ChannelCore):
        self._core = core

    async def recv(self) -> Optional[T]:
        """Next value, or None once the channel is closed and drained."""
        core = self._core
        if core.queue:
            item = core.queue.popleft()
            core._wake_one(core.send_waiters)
            return item
        if core.closed:
            return None
        fut = SimFuture(name="chan.recv")
        core.recv_waiters.append(fut)
        kind, value = await fut
        if kind == "closed":
            return None
        return value

    def try_recv(self) -> Optional[T]:
        core = self._core
        if core.queue:
            item = core.queue.popleft()
            core._wake_one(core.send_waiters)
            return item
        return None

    def close(self) -> None:
        self._core.close()

    def __aiter__(self):
        return self

    async def __anext__(self) -> T:
        v = await self.recv()
        if v is None:
            raise StopAsyncIteration
        return v


def channel(capacity: int) -> tuple[Sender, Receiver]:
    """Bounded mpsc channel (tokio::sync::mpsc::channel analog)."""
    if capacity < 1:
        raise ValueError("capacity must be >= 1")
    core = _ChannelCore(capacity)
    return Sender(core), Receiver(core)


def unbounded_channel() -> tuple[Sender, Receiver]:
    core = _ChannelCore(None)
    return Sender(core), Receiver(core)


# ---- watch ---------------------------------------------------------------


class WatchSender(Generic[T]):
    __slots__ = ("_state",)

    def __init__(self, state: dict):
        self._state = state

    def send(self, value: T) -> None:
        st = self._state
        st["value"] = value
        st["version"] += 1
        waiters, st["waiters"] = st["waiters"], []
        for w in waiters:
            if not w.done():
                w.set_result(None)


class WatchReceiver(Generic[T]):
    __slots__ = ("_state", "_seen")

    def __init__(self, state: dict):
        self._state = state
        self._seen = state["version"]

    def borrow(self) -> T:
        return self._state["value"]

    async def changed(self) -> None:
        if self._state["version"] > self._seen:
            self._seen = self._state["version"]
            return
        fut = SimFuture(name="watch")
        self._state["waiters"].append(fut)
        await fut
        self._seen = self._state["version"]

    def clone(self) -> "WatchReceiver[T]":
        return WatchReceiver(self._state)


def watch(initial: T) -> tuple[WatchSender, WatchReceiver]:
    state = {"value": initial, "version": 0, "waiters": []}
    return WatchSender(state), WatchReceiver(state)


# ---- broadcast -----------------------------------------------------------


class BroadcastSender(Generic[T]):
    __slots__ = ("_subs",)

    def __init__(self) -> None:
        self._subs: list[_ChannelCore] = []

    def subscribe(self) -> Receiver:
        core = _ChannelCore(None)
        self._subs.append(core)
        return Receiver(core)

    def send(self, value: T) -> int:
        n = 0
        for core in self._subs:
            if not core.closed:
                core.push(value)
                n += 1
        return n

    def close(self) -> None:
        for core in self._subs:
            core.close()


def broadcast() -> BroadcastSender:
    return BroadcastSender()


# ---- locks ---------------------------------------------------------------


class Mutex(Generic[T]):
    """Async mutex; ``async with`` yields the protected value."""

    def __init__(self, value: T = None):
        self._value = value
        self._locked = False
        self._waiters: deque[SimFuture] = deque()

    async def acquire(self) -> T:
        while self._locked:
            fut = SimFuture(name="mutex")
            self._waiters.append(fut)
            await fut
        self._locked = True
        return self._value

    def release(self) -> None:
        if not self._locked:
            raise RuntimeError("release of unlocked Mutex")
        self._locked = False
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)
                break

    def set(self, value: T) -> None:
        self._value = value

    async def __aenter__(self) -> T:
        return await self.acquire()

    async def __aexit__(self, *exc) -> None:
        self.release()


class RwLock(Generic[T]):
    """Write-preferring RwLock (tokio semantics): once a writer is waiting,
    new readers queue behind it, so steady read traffic cannot starve
    writers."""

    def __init__(self, value: T = None):
        self._value = value
        self._readers = 0
        self._writer = False
        self._pending_writers = 0
        self._waiters: deque[SimFuture] = deque()

    def _wake_all(self) -> None:
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)

    async def read(self) -> "_ReadGuard[T]":
        while self._writer or self._pending_writers > 0:
            fut = SimFuture(name="rwlock.r")
            self._waiters.append(fut)
            await fut
        self._readers += 1
        return _ReadGuard(self)

    async def write(self) -> "_WriteGuard[T]":
        self._pending_writers += 1
        try:
            while self._writer or self._readers > 0:
                fut = SimFuture(name="rwlock.w")
                self._waiters.append(fut)
                await fut
        finally:
            self._pending_writers -= 1
        self._writer = True
        return _WriteGuard(self)


class _ReadGuard(Generic[T]):
    def __init__(self, lock: RwLock):
        self._lock = lock

    @property
    def value(self) -> T:
        return self._lock._value

    async def __aenter__(self) -> T:
        return self._lock._value

    async def __aexit__(self, *exc) -> None:
        self.release()

    def release(self) -> None:
        self._lock._readers -= 1
        if self._lock._readers == 0:
            self._lock._wake_all()


class _WriteGuard(Generic[T]):
    def __init__(self, lock: RwLock):
        self._lock = lock

    @property
    def value(self) -> T:
        return self._lock._value

    @value.setter
    def value(self, v: T) -> None:
        self._lock._value = v

    async def __aenter__(self) -> "_WriteGuard[T]":
        return self

    async def __aexit__(self, *exc) -> None:
        self.release()

    def release(self) -> None:
        self._lock._writer = False
        self._lock._wake_all()


class Semaphore:
    def __init__(self, permits: int):
        self._permits = permits
        self._waiters: deque[SimFuture] = deque()

    async def acquire(self, n: int = 1) -> None:
        while self._permits < n:
            fut = SimFuture(name="sem")
            self._waiters.append(fut)
            await fut
        self._permits -= n

    def release(self, n: int = 1) -> None:
        self._permits += n
        # Wake every waiter: waiters re-check their own permit demand, so a
        # single wakeup could strand a small waiter behind a large one.
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)

    def available_permits(self) -> int:
        return self._permits

    async def __aenter__(self) -> None:
        await self.acquire()

    async def __aexit__(self, *exc) -> None:
        self.release()


class Notify:
    def __init__(self) -> None:
        self._notified = False
        self._waiters: deque[SimFuture] = deque()

    async def notified(self) -> None:
        if self._notified:
            self._notified = False
            return
        fut = SimFuture(name="notify")
        self._waiters.append(fut)
        await fut

    def notify_one(self) -> None:
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)
                return
        self._notified = True

    def notify_waiters(self) -> None:
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)


class Barrier:
    def __init__(self, n: int):
        self._n = n
        self._count = 0
        self._waiters: list[SimFuture] = []

    async def wait(self) -> bool:
        """Returns True for the leader (last arriver)."""
        self._count += 1
        if self._count == self._n:
            self._count = 0
            waiters, self._waiters = self._waiters, []
            for w in waiters:
                if not w.done():
                    w.set_result(False)
            return True
        fut = SimFuture(name="barrier")
        self._waiters.append(fut)
        return await fut or False
