"""Simulated per-node filesystem with power-failure semantics.

Parity with reference madsim/src/sim/fs.rs:
  * ``FsSim`` keeps an in-memory ``{path: INode}`` map per node
    (fs.rs:24-41); node reset = power failure.
  * ``File`` supports ``read_at`` / ``write_all_at`` / ``set_len`` /
    ``sync_all`` / ``metadata`` (fs.rs:148-229); free functions ``read``
    and ``metadata`` (fs.rs:232-248).
  * Power failure drops *unsynced* writes: each inode tracks its last
    ``sync_all`` snapshot and reset rolls back to it. (The reference
    leaves this as a TODO — fs.rs:51, fs.rs:204 — and currently keeps all
    data; we implement the intended semantics, which is strictly more
    useful for crash-consistency testing.)
"""

from __future__ import annotations

from typing import Optional

from .runtime import context
from .runtime.plugin import Simulator, node as current_node
from .runtime.runtime import DEFAULT_SIMULATORS

__all__ = ["FsSim", "File", "Metadata", "read", "write", "metadata"]


class Metadata:
    __slots__ = ("len",)

    def __init__(self, length: int):
        self.len = length

    def __repr__(self) -> str:
        return f"Metadata(len={self.len})"


class _INode:
    __slots__ = ("data", "synced")

    def __init__(self) -> None:
        self.data = bytearray()
        self.synced = b""

    def sync(self) -> None:
        self.synced = bytes(self.data)

    def power_fail(self) -> None:
        self.data = bytearray(self.synced)


class FsSim(Simulator):
    """Filesystem device simulator (fs.rs:24-66)."""

    def __init__(self, rng, time, config, handle):
        super().__init__(rng, time, config, handle)
        self._nodes: dict[int, dict[str, _INode]] = {}

    def create_node(self, node_id: int) -> None:
        self._nodes.setdefault(node_id, {})

    def reset_node(self, node_id: int) -> None:
        """Power failure: every file rolls back to its last synced state
        (the intended semantics of fs.rs:51)."""
        for inode in self._nodes.get(node_id, {}).values():
            inode.power_fail()

    # ---- introspection (fs.rs:56-66) ------------------------------------
    def get_file_size(self, node_id: int, path: str) -> Optional[int]:
        inode = self._nodes.get(node_id, {}).get(str(path))
        return len(inode.data) if inode is not None else None

    def _dir(self, node_id: int) -> dict[str, _INode]:
        return self._nodes.setdefault(node_id, {})

    @staticmethod
    def current() -> "FsSim":
        return context.current_handle().simulator(FsSim)


class File:
    """An open file on the current node (fs.rs:148-229)."""

    def __init__(self, inode: _INode, path: str):
        self._inode = inode
        self.path = path

    @classmethod
    async def create(cls, path: str) -> "File":
        fs = FsSim.current()
        d = fs._dir(current_node())
        inode = _INode()
        d[str(path)] = inode
        return cls(inode, str(path))

    @classmethod
    async def open(cls, path: str) -> "File":
        fs = FsSim.current()
        d = fs._dir(current_node())
        inode = d.get(str(path))
        if inode is None:
            raise FileNotFoundError(path)
        return cls(inode, str(path))

    @classmethod
    async def open_or_create(cls, path: str) -> "File":
        fs = FsSim.current()
        d = fs._dir(current_node())
        inode = d.setdefault(str(path), _INode())
        return cls(inode, str(path))

    async def read_at(self, n: int, offset: int) -> bytes:
        data = self._inode.data
        return bytes(data[offset : offset + n])

    async def write_all_at(self, data: bytes, offset: int) -> None:
        buf = self._inode.data
        end = offset + len(data)
        if len(buf) < end:
            buf.extend(b"\x00" * (end - len(buf)))
        buf[offset:end] = data

    async def set_len(self, n: int) -> None:
        buf = self._inode.data
        if n < len(buf):
            del buf[n:]
        else:
            buf.extend(b"\x00" * (n - len(buf)))

    async def sync_all(self) -> None:
        """Persist: survives power failure from here (fs.rs:219)."""
        self._inode.sync()

    async def metadata(self) -> Metadata:
        return Metadata(len(self._inode.data))


async def read(path: str) -> bytes:
    """Whole-file read on the current node (fs.rs:232-239)."""
    f = await File.open(path)
    return await f.read_at(len(f._inode.data), 0)


async def write(path: str, data: bytes) -> None:
    f = await File.open_or_create(path)
    await f.set_len(0)
    await f.write_all_at(data, 0)


async def metadata(path: str) -> Metadata:
    f = await File.open(path)
    return await f.metadata()


if FsSim not in DEFAULT_SIMULATORS:
    # Registered before NetSim to match the reference's order
    # (runtime/mod.rs:62-63).
    DEFAULT_SIMULATORS.insert(0, FsSim)
