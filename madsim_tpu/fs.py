"""Simulated per-node filesystem with power-failure semantics.

Parity with reference madsim/src/sim/fs.rs:
  * ``FsSim`` keeps an in-memory ``{path: INode}`` map per node
    (fs.rs:24-41); node reset = power failure.
  * ``File`` supports ``read_at`` / ``write_all_at`` / ``set_len`` /
    ``sync_all`` / ``metadata`` (fs.rs:148-229); free functions ``read``
    and ``metadata`` (fs.rs:232-248).
  * Power failure drops *unsynced* writes: each inode tracks its last
    ``sync_all`` snapshot and reset rolls back to it. (The reference
    leaves this as a TODO — fs.rs:51, fs.rs:204 — and currently keeps all
    data; we implement the intended semantics, which is strictly more
    useful for crash-consistency testing.)

Injectable disk faults (the asyncio twin of the batched engine's
``Workload.durable_sync`` discipline — ``chaos.Nemesis`` drives the same
``DiskFault`` plan windows through these hooks):

  * ``set_torn(node)`` — a power failure additionally re-applies a
    random *prefix* of the node's last unsynced write on top of the
    synced snapshot (the FoundationDB torn-write fault; the prefix
    length draws from the runtime's deterministic RNG).
  * ``set_sync_loss(node)`` — the node's disk lies: ``sync_all``
    silently commits nothing, so a later power failure still rolls the
    file back (the firmware-lies-about-fsync fault).
  * ``set_fail_writes(node)`` — writes raise ``OSError(EIO)``, the
    injectable write-error path.
"""

from __future__ import annotations

from typing import Optional

from .runtime import context
from .runtime.plugin import Simulator, node as current_node
from .runtime.runtime import DEFAULT_SIMULATORS

__all__ = ["FsSim", "File", "Metadata", "read", "write", "metadata"]


class Metadata:
    __slots__ = ("len",)

    def __init__(self, length: int):
        self.len = length

    def __repr__(self) -> str:
        return f"Metadata(len={self.len})"


class _INode:
    __slots__ = ("data", "synced", "last_write")

    def __init__(self) -> None:
        self.data = bytearray()
        self.synced = b""
        # (offset, payload) of the newest unsynced write — the write a
        # torn power failure tears; None once synced (or truncated:
        # set_len is a metadata op, not a tearable data write)
        self.last_write: Optional[tuple] = None

    def write(self, offset: int, data: bytes) -> None:
        buf = self.data
        end = offset + len(data)
        if len(buf) < end:
            buf.extend(b"\x00" * (end - len(buf)))
        buf[offset:end] = data
        self.last_write = (offset, bytes(data))

    def sync(self) -> None:
        self.synced = bytes(self.data)
        self.last_write = None

    def power_fail(self, torn: bool = False, rng=None) -> None:
        """Roll back to the synced snapshot; under ``torn`` a drawn
        prefix of the last unsynced write survives on top of it. The
        post-failure contents ARE the on-disk state — the snapshot is
        refreshed to them, so a second power failure cannot un-persist
        a torn fragment that physically reached the platter (the
        engine's rule: the torn prefix commits into ``SimState.disk``
        at the kill)."""
        last = self.last_write
        self.data = bytearray(self.synced)
        if torn and last is not None and rng is not None:
            offset, payload = last
            frag = payload[: rng.randrange(0, len(payload) + 1)]
            if frag:
                end = offset + len(frag)
                if len(self.data) < end:
                    self.data.extend(b"\x00" * (end - len(self.data)))
                self.data[offset:end] = frag
        self.synced = bytes(self.data)
        self.last_write = None


class FsSim(Simulator):
    """Filesystem device simulator (fs.rs:24-66)."""

    def __init__(self, rng, time, config, handle):
        super().__init__(rng, time, config, handle)
        self._nodes: dict[int, dict[str, _INode]] = {}
        self._torn: set[int] = set()
        self._sync_loss: set[int] = set()
        self._fail_writes: set[int] = set()

    def create_node(self, node_id: int) -> None:
        self._nodes.setdefault(node_id, {})

    def reset_node(self, node_id: int) -> None:
        """Power failure: every file rolls back to its last synced state
        (the intended semantics of fs.rs:51); an armed torn-write mode
        (``set_torn``) keeps a drawn prefix of each file's last unsynced
        write — the same fault the engine's KIND_TORN_ON injects."""
        torn = node_id in self._torn
        for inode in self._nodes.get(node_id, {}).values():
            inode.power_fail(torn=torn, rng=self.rng)

    # ---- injectable disk faults (chaos.DiskFault's asyncio twin) --------
    def set_torn(self, node_id: int, on: bool = True) -> None:
        """Arm/disarm torn-write mode: power failures tear the last
        unsynced write instead of dropping it cleanly."""
        (self._torn.add if on else self._torn.discard)(node_id)

    def set_sync_loss(self, node_id: int, on: bool = True) -> None:
        """Make/stop the node's disk lying: ``sync_all`` commits nothing
        while set, so power failures keep rolling back past it."""
        (self._sync_loss.add if on else self._sync_loss.discard)(node_id)

    def set_fail_writes(self, node_id: int, on: bool = True) -> None:
        """Inject write errors: ``write_all_at`` raises ``OSError(EIO)``."""
        (self._fail_writes.add if on else self._fail_writes.discard)(node_id)

    # ---- introspection (fs.rs:56-66) ------------------------------------
    def get_file_size(self, node_id: int, path: str) -> Optional[int]:
        inode = self._nodes.get(node_id, {}).get(str(path))
        return len(inode.data) if inode is not None else None

    def _dir(self, node_id: int) -> dict[str, _INode]:
        return self._nodes.setdefault(node_id, {})

    @staticmethod
    def current() -> "FsSim":
        return context.current_handle().simulator(FsSim)


class File:
    """An open file on the current node (fs.rs:148-229)."""

    def __init__(self, fs: FsSim, node: int, inode: _INode, path: str):
        self._fs = fs
        self._node = node
        self._inode = inode
        self.path = path

    @classmethod
    async def create(cls, path: str) -> "File":
        fs = FsSim.current()
        node = current_node()
        d = fs._dir(node)
        inode = _INode()
        d[str(path)] = inode
        return cls(fs, node, inode, str(path))

    @classmethod
    async def open(cls, path: str) -> "File":
        fs = FsSim.current()
        node = current_node()
        d = fs._dir(node)
        inode = d.get(str(path))
        if inode is None:
            raise FileNotFoundError(path)
        return cls(fs, node, inode, str(path))

    @classmethod
    async def open_or_create(cls, path: str) -> "File":
        fs = FsSim.current()
        node = current_node()
        d = fs._dir(node)
        inode = d.setdefault(str(path), _INode())
        return cls(fs, node, inode, str(path))

    async def read_at(self, n: int, offset: int) -> bytes:
        data = self._inode.data
        return bytes(data[offset : offset + n])

    async def write_all_at(self, data: bytes, offset: int) -> None:
        if self._node in self._fs._fail_writes:
            raise OSError(5, "simulated disk write error", self.path)
        self._inode.write(offset, bytes(data))

    async def set_len(self, n: int) -> None:
        if self._node in self._fs._fail_writes:
            raise OSError(5, "simulated disk write error", self.path)
        buf = self._inode.data
        if n < len(buf):
            del buf[n:]
        else:
            buf.extend(b"\x00" * (n - len(buf)))
        # truncation/extension is a metadata op: it is not the write a
        # torn power failure re-applies
        self._inode.last_write = None

    async def sync_all(self) -> None:
        """Persist: survives power failure from here (fs.rs:219) —
        unless the node's disk is inside an injected sync-loss window,
        in which case the call silently commits nothing (the lie is
        indistinguishable from a working fsync, exactly like the
        engine's KIND_SYNC_LOSS)."""
        if self._node in self._fs._sync_loss:
            return
        self._inode.sync()

    async def metadata(self) -> Metadata:
        return Metadata(len(self._inode.data))


async def read(path: str) -> bytes:
    """Whole-file read on the current node (fs.rs:232-239)."""
    f = await File.open(path)
    return await f.read_at(len(f._inode.data), 0)


async def write(path: str, data: bytes) -> None:
    f = await File.open_or_create(path)
    await f.set_len(0)
    await f.write_all_at(data, 0)


async def metadata(path: str) -> Metadata:
    f = await File.open(path)
    return await f.metadata()


if FsSim not in DEFAULT_SIMULATORS:
    # Registered before NetSim to match the reference's order
    # (runtime/mod.rs:62-63).
    DEFAULT_SIMULATORS.insert(0, FsSim)
