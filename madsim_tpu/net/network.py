"""Message-level network graph: the core fault model.

Parity with reference madsim/src/sim/net/network.rs:
  * nodes with at most one IP; sockets keyed ``((ip, port), protocol)``
    with 0.0.0.0 wildcard matching (network.rs:24-70, 311-313).
  * per-message faults consulted on every send: clogged-node and
    clogged-link sets, packet loss rate, uniform random latency
    (network.rs:75-95 Config, 169-210 clog API, 268-276 test_link).
  * ephemeral-port allocation when binding port 0 (network.rs:213-258).
  * ``reset_node`` clears the node's sockets — a killed machine loses all
    bindings (network.rs:148-154).
  * ``Stat`` message counter (network.rs:106-111).

The latency/loss draws all flow through the simulation's GlobalRng, so a
partition schedule replays exactly from the seed. The batched TPU engine
(madsim_tpu/engine/netmodel.py) implements this same model as vectorized
arrays over a seed axis.
"""

from __future__ import annotations

from typing import Optional, Protocol

from ..runtime.config import NetConfig
from ..runtime.rand import GlobalRng
from .addr import SocketAddr

__all__ = ["Network", "Socket", "Stat", "Protocols"]

NANOS_PER_SEC = 1_000_000_000


class Protocols:
    UDP = "udp"
    TCP = "tcp"
    EP = "ep"  # Endpoint tagged datagrams


class Socket(Protocol):
    """Delivery target registered in the network (network.rs:57-70)."""

    def deliver(self, src: SocketAddr, dst: SocketAddr, msg: object) -> None: ...


class Stat:
    """Built-in metrics (network.rs:106-111)."""

    __slots__ = ("msg_count",)

    def __init__(self) -> None:
        self.msg_count = 0

    def __repr__(self) -> str:
        return f"Stat(msg_count={self.msg_count})"


class _NetNode:
    __slots__ = ("id", "ip", "sockets")

    def __init__(self, node_id: int, ip: Optional[str]):
        self.id = node_id
        self.ip = ip
        # (addr, proto) -> Socket
        self.sockets: dict[tuple[SocketAddr, str], Socket] = {}


class Network:
    def __init__(self, rng: GlobalRng, config: NetConfig):
        self.rng = rng
        self.config = config
        self.stat = Stat()
        self._nodes: dict[int, _NetNode] = {}
        self._ip_to_node: dict[str, int] = {}
        self._clogged_nodes: set[int] = set()
        self._clogged_links: set[tuple[int, int]] = set()  # (src, dst) one-way
        self._clogged_in: set[int] = set()   # deliveries TO node blocked
        self._clogged_out: set[int] = set()  # sends FROM node blocked
        # gray failures (madsim_tpu.chaos): per-link latency multipliers;
        # absent = x1. The dict mirrors the batched engine's (N,N) `slow`
        # matrix with OVERWRITE semantics — a node-wide set/unset writes
        # every link touching the node, exactly like the engine's
        # node-wide select (so the same plan yields the same multiplier
        # state in both execution modes, including the case where a
        # node-wide unslow wipes an earlier link-specific multiplier).
        self._slow_links: dict[tuple[int, int], int] = {}  # (src, dst) one-way

    # ---- node lifecycle -------------------------------------------------
    def insert_node(self, node_id: int, ip: Optional[str]) -> None:
        if ip is not None and ip in self._ip_to_node:
            raise ValueError(f"IP {ip} already assigned to node {self._ip_to_node[ip]}")
        self._nodes[node_id] = _NetNode(node_id, ip)
        if ip is not None:
            self._ip_to_node[ip] = node_id

    def reset_node(self, node_id: int) -> None:
        """Clear sockets; the machine rebooted (network.rs:148-154)."""
        node = self._nodes.get(node_id)
        if node is not None:
            node.sockets.clear()

    def set_ip(self, node_id: int, ip: str) -> None:
        node = self._nodes[node_id]
        if node.ip is not None:
            self._ip_to_node.pop(node.ip, None)
        if ip in self._ip_to_node and self._ip_to_node[ip] != node_id:
            raise ValueError(f"IP {ip} already assigned")
        node.ip = ip
        self._ip_to_node[ip] = node_id

    def ip_of(self, node_id: int) -> Optional[str]:
        node = self._nodes.get(node_id)
        return node.ip if node else None

    # ---- fault injection (network.rs:169-210) ---------------------------
    def clog_node(self, node_id: int) -> None:
        self._clogged_nodes.add(node_id)

    def unclog_node(self, node_id: int) -> None:
        self._clogged_nodes.discard(node_id)

    def clog_node_in(self, node_id: int) -> None:
        """Directional clog: messages TO the node blocked (mod.rs:183)."""
        self._clogged_in.add(node_id)

    def unclog_node_in(self, node_id: int) -> None:
        self._clogged_in.discard(node_id)

    def clog_node_out(self, node_id: int) -> None:
        """Directional clog: messages FROM the node blocked (mod.rs:188)."""
        self._clogged_out.add(node_id)

    def unclog_node_out(self, node_id: int) -> None:
        self._clogged_out.discard(node_id)

    def clog_link(self, src: int, dst: int) -> None:
        """Block messages src -> dst (one direction)."""
        self._clogged_links.add((src, dst))

    def unclog_link(self, src: int, dst: int) -> None:
        self._clogged_links.discard((src, dst))

    def set_slow_link(self, src: int, dst: int, mult: int) -> None:
        """Gray failure: multiply src -> dst latency by ``mult`` (one
        direction; mult <= 1 restores normal speed)."""
        if mult > 1:
            self._slow_links[(src, dst)] = int(mult)
        else:
            self._slow_links.pop((src, dst), None)

    def set_slow_node(self, node_id: int, mult: int) -> None:
        """Set every link in or out of the node to ``mult`` (engine
        node-wide overwrite semantics; mult <= 1 restores them all,
        including any link-specific multiplier set earlier)."""
        for other in self._nodes:
            self.set_slow_link(node_id, other, mult)
            self.set_slow_link(other, node_id, mult)

    def slow_mult(self, src: int, dst: int) -> int:
        """Effective latency multiplier for one message."""
        return self._slow_links.get((src, dst), 1)

    def is_clogged(self, src: int, dst: int) -> bool:
        return (
            src in self._clogged_nodes
            or dst in self._clogged_nodes
            or src in self._clogged_out
            or dst in self._clogged_in
            or (src, dst) in self._clogged_links
        )

    # ---- binding (network.rs:213-261) -----------------------------------
    def bind(
        self, node_id: int, addr: SocketAddr, proto: str, socket: Socket
    ) -> SocketAddr:
        node = self._nodes[node_id]
        ip, port = addr
        if port == 0:
            # ephemeral-port allocation: random scan of 0x8000..0xffff
            for _ in range(64):
                cand = self.rng.randrange(0x8000, 0x10000)
                if ((ip, cand), proto) not in node.sockets:
                    port = cand
                    break
            else:
                raise OSError("address space exhausted: no free ephemeral port")
        key = ((ip, port), proto)
        if key in node.sockets:
            raise OSError(f"address already in use: {ip}:{port}/{proto}")
        node.sockets[key] = socket
        return (ip, port)

    def close(self, node_id: int, addr: SocketAddr, proto: str) -> None:
        node = self._nodes.get(node_id)
        if node is not None:
            node.sockets.pop((addr, proto), None)

    # ---- resolution + send (network.rs:268-320) -------------------------
    def resolve_dest_node(self, dst_ip: str, src_node: int) -> Optional[int]:
        """IP -> node id; loopback resolves to the sender's own node
        (localhost isolation, endpoint.rs tests)."""
        if dst_ip in ("127.0.0.1", "localhost"):
            return src_node
        return self._ip_to_node.get(dst_ip)

    def test_link(self, src: int, dst: int) -> Optional[int]:
        """Consult clog + loss + latency for one message. Returns latency
        in ns, or None if the message is dropped (network.rs:268-276).

        Draw order is fixed (loss first, then latency) — part of the
        deterministic trace contract shared with the batched engine."""
        if self.is_clogged(src, dst):
            return None
        cfg = self.config
        if cfg.packet_loss_rate > 0 and self.rng.random_bool(cfg.packet_loss_rate):
            return None
        lo = round(cfg.send_latency[0] * NANOS_PER_SEC)
        hi = round(cfg.send_latency[1] * NANOS_PER_SEC)
        latency = self.rng.randrange(lo, max(hi, lo + 1))
        # gray failure: the drawn latency scales AFTER the draw, so
        # enabling/disabling a slow link never shifts the RNG stream
        # (determinism: the same draws happen either way)
        return latency * self.slow_mult(src, dst)

    def lookup_socket(self, node_id: int, addr: SocketAddr, proto: str) -> Optional[Socket]:
        """Exact-match then 0.0.0.0-wildcard socket lookup on a node
        (network.rs:311-313). Shared by datagram routing and connection
        setup so binding semantics cannot diverge."""
        node = self._nodes.get(node_id)
        if node is None:
            return None
        sock = node.sockets.get((addr, proto))
        if sock is None:
            sock = node.sockets.get((("0.0.0.0", addr[1]), proto))
        return sock

    def try_send(
        self, src_node: int, dst: SocketAddr, proto: str
    ) -> Optional[tuple[Socket, int, int]]:
        """Route one message: returns (socket, dst_node, latency_ns) or
        None if unroutable/clogged/lost (network.rs:303-320)."""
        dst_node = self.resolve_dest_node(dst[0], src_node)
        if dst_node is None or dst_node not in self._nodes:
            return None
        latency = self.test_link(src_node, dst_node)
        if latency is None:
            return None
        sock = self.lookup_socket(dst_node, dst, proto)
        if sock is None:
            return None
        self.stat.msg_count += 1
        return (sock, dst_node, latency)
