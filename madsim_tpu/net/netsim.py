"""NetSim — the network device simulator plugin.

Parity with reference madsim/src/sim/net/mod.rs:
  * ``Simulator`` plugin owning the :class:`Network` graph; per-node state
    created on node creation and wiped on reset (mod.rs:93-117).
  * user-facing chaos API: clog/unclog node and link, stats
    (mod.rs:126-216).
  * datagram send path: random 0-5 us processing delay, send hooks (the
    RPC-drop chaos hook, mod.rs:223-262), route through the network fault
    model, then a latency timer that delivers into the destination socket
    (mod.rs:265-302).
  * reliable ordered "connections": per-direction pipes drained by a pump
    task on the sending node that re-checks link clog state per message
    with 1 ms -> 10 s exponential backoff (mod.rs:329-365), so a partition
    stalls the stream and recovery resumes it in order.
"""

from __future__ import annotations

from collections import deque
from typing import Callable

from ..runtime import context
from ..runtime.future import SimFuture
from ..runtime.plugin import Simulator
from ..runtime.time_ import NANOS_PER_SEC
from .addr import SocketAddr
from .network import Network, Stat

__all__ = ["NetSim", "Pipe", "PipeSender", "PipeReceiver"]

_MAX_PROCESSING_DELAY_NS = 5_000  # 0-5 us (mod.rs:265-270)
_BACKOFF_MIN_NS = 1_000_000  # 1 ms
_BACKOFF_MAX_NS = 10 * NANOS_PER_SEC  # 10 s


class Pipe:
    """One direction of a reliable ordered connection."""

    __slots__ = ("src_node", "dst_node", "queue", "waiters", "closed", "on_close", "group")

    def __init__(self, src_node: int, dst_node: int):
        self.src_node = src_node
        self.dst_node = dst_node
        self.queue: deque = deque()
        self.waiters: deque[SimFuture] = deque()
        self.closed = False
        self.on_close = None  # set by NetSim.register_pipe for dereg
        self.group: tuple = ()  # all pipes of the same connection

    def push(self, item: object) -> None:
        while self.waiters:
            w = self.waiters.popleft()
            if not w.done():
                w.set_result(item)
                return
        self.queue.append(item)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        while self.waiters:
            w = self.waiters.popleft()
            if not w.done():
                w.set_result(None)
        if self.on_close is not None:
            self.on_close(self)
            self.on_close = None

    def pop(self) -> SimFuture:
        fut = SimFuture(name="pipe.pop")
        if self.queue:
            fut.set_result(self.queue.popleft())
        elif self.closed:
            fut.set_result(None)
        else:
            self.waiters.append(fut)
        return fut


class PipeSender:
    """Sending half of a connection (mod.rs:329-340 Sender)."""

    __slots__ = ("_out",)

    def __init__(self, out: Pipe):
        self._out = out

    async def send(self, payload: object) -> None:
        if self._out.closed:
            raise ConnectionResetError("connection closed by peer or node reset")
        self._out.push(payload)

    def is_closed(self) -> bool:
        return self._out.closed

    def shutdown(self) -> None:
        """Close this direction only (half-close): the peer sees EOF after
        in-flight data drains; the reverse direction keeps working."""
        self._out.close()

    def close(self) -> None:
        """Close the whole connection: both directions end, the peer's
        reads EOF, its sends fail, and the pump tasks exit so all pipe
        resources are released."""
        for p in self._out.group or (self._out,):
            p.close()


class PipeReceiver:
    """Receiving half of a connection; ``recv`` returns None on EOF."""

    __slots__ = ("_in",)

    def __init__(self, inp: Pipe):
        self._in = inp

    async def recv(self) -> object | None:
        return await self._in.pop()

    def close(self) -> None:
        """Close the whole connection (see PipeSender.close)."""
        for p in self._in.group or (self._in,):
            p.close()


class NetSim(Simulator):
    """The network simulator plugin (mod.rs:77-117)."""

    def __init__(self, rng, time, config, handle):
        super().__init__(rng, time, config, handle)
        self.network = Network(rng, config.net)
        self._send_hooks: dict[int, Callable] = {}
        self._next_hook_id = 0
        # typed RPC hooks, one per node like the reference's HashMap
        # (mod.rs:82-83): req keyed by SENDING node, consulted at send;
        # rsp keyed by DESTINATION node, consulted at delivery
        self._hooks_req: dict[int, Callable[[object], bool]] = {}
        self._hooks_rsp: dict[int, Callable[[object], bool]] = {}
        # pipes registered per node id — closed when the node resets,
        # deregistered when they close (no growth across connection churn)
        self._pipes_by_node: dict[int, set[Pipe]] = {}
        # unix-domain socket namespace: (node_id, path) -> bound socket.
        # Node-local IPC (paths never cross machines), wiped on reset.
        self.unix_binds: dict[tuple[int, str], object] = {}
        # chaos: datagram duplication flag (set_duplicate)
        self._duplicate = False

    # ---- Simulator lifecycle -------------------------------------------
    def create_node(self, node_id: int) -> None:
        info = self.handle.executor.nodes.get(node_id)
        self.network.insert_node(node_id, info.ip if info else None)

    def reset_node(self, node_id: int) -> None:
        self.network.reset_node(node_id)
        for pipe in list(self._pipes_by_node.get(node_id, ())):
            pipe.close()
        self._pipes_by_node.pop(node_id, None)
        for key in [k for k in self.unix_binds if k[0] == node_id]:
            sock = self.unix_binds.pop(key)
            on_reset = getattr(sock, "_on_node_reset", None)
            if on_reset is not None:
                on_reset()

    # ---- stats / chaos (mod.rs:126-216) --------------------------------
    @property
    def stat(self) -> Stat:
        return self.network.stat

    @staticmethod
    def _nid(node) -> int:
        return node if isinstance(node, int) else node.id

    def clog_node(self, node) -> None:
        self.network.clog_node(self._nid(node))

    def unclog_node(self, node) -> None:
        self.network.unclog_node(self._nid(node))

    def clog_link(self, a, b) -> None:
        """Block both directions between a and b (a partition edge)."""
        a, b = self._nid(a), self._nid(b)
        self.network.clog_link(a, b)
        self.network.clog_link(b, a)

    def unclog_link(self, a, b) -> None:
        a, b = self._nid(a), self._nid(b)
        self.network.unclog_link(a, b)
        self.network.unclog_link(b, a)

    def clog_link_one_way(self, src, dst) -> None:
        self.network.clog_link(self._nid(src), self._nid(dst))

    def unclog_link_one_way(self, src, dst) -> None:
        self.network.unclog_link(self._nid(src), self._nid(dst))

    # ---- gray failures + duplication (madsim_tpu.chaos) ----------------
    def slow_link(self, a, b, mult: int) -> None:
        """Gray failure: multiply a<->b latency by ``mult`` (both
        directions, like clog_link; mult <= 1 restores). The asyncio
        hook behind the engine's KIND_SLOW_LINK."""
        a, b = self._nid(a), self._nid(b)
        self.network.set_slow_link(a, b, mult)
        self.network.set_slow_link(b, a, mult)

    def unslow_link(self, a, b) -> None:
        self.slow_link(a, b, 1)

    def slow_node(self, node, mult: int) -> None:
        """Slow every link in or out of the node (mult <= 1 restores)."""
        self.network.set_slow_node(self._nid(node), mult)

    def set_duplicate(self, on: bool) -> None:
        """Message duplication (KIND_DUP_ON analog): while set, every
        datagram delivery also schedules a second copy with its own
        independent loss/latency draw."""
        self._duplicate = bool(on)

    def update_config(self, f: Callable) -> None:
        """Mutate the live network config (mod.rs:131-136) — e.g.
        ``netsim.update_config(lambda c: setattr(c, "packet_loss_rate",
        0.2))``; the fault model reads it per send, so changes apply to
        every subsequent message."""
        f(self.network.config)

    def clog_node_in(self, node) -> None:
        """Block messages TO the node; its own sends still flow
        (mod.rs:183-186)."""
        self.network.clog_node_in(self._nid(node))

    def unclog_node_in(self, node) -> None:
        self.network.unclog_node_in(self._nid(node))

    def clog_node_out(self, node) -> None:
        """Block messages FROM the node; deliveries to it still flow
        (mod.rs:188-192)."""
        self.network.clog_node_out(self._nid(node))

    def unclog_node_out(self, node) -> None:
        self.network.unclog_node_out(self._nid(node))

    # naming-parity aliases (mod.rs:152-213): connect/disconnect are the
    # reference's names for unclog/clog of a node, connect2/disconnect2
    # for a link (both directions)
    def connect(self, node) -> None:
        self.unclog_node(node)

    def disconnect(self, node) -> None:
        self.clog_node(node)

    def connect2(self, a, b) -> None:
        self.unclog_link(a, b)

    def disconnect2(self, a, b) -> None:
        self.clog_link(a, b)

    def _install_typed_hook(
        self, hooks: dict, node, typ: type, f, is_rsp: bool, kind: str
    ) -> None:
        """Shared body of hook_rpc_req/hook_rpc_rsp: one hook per node
        (insert overwrites, None removes — the reference's HashMap
        insert, mod.rs:228/251). RPC frames are discriminated by the
        bit-63 response-tag invariant rpc.py guarantees (rpc.py:48):
        requests are ("dgram", req_tag, (obj, data, resp_tag&bit63)),
        responses are ("dgram", resp_tag&bit63, (obj, data)) — plain
        same-shape datagrams never match."""
        nid = self._nid(node)
        if f is None:
            hooks.pop(nid, None)
            return

        def hook(msg: object) -> bool:
            if not (isinstance(msg, tuple) and len(msg) == 3 and msg[0] == "dgram"):
                return True
            tag, payload = msg[1], msg[2]
            if is_rsp:
                is_frame = (
                    isinstance(tag, int) and tag >> 63
                    and isinstance(payload, tuple) and len(payload) == 2
                )
            else:
                is_frame = (
                    isinstance(payload, tuple) and len(payload) == 3
                    and isinstance(payload[2], int) and payload[2] >> 63
                )
            if is_frame and isinstance(payload[0], typ):
                try:
                    return bool(f(payload[0]))
                except Exception as exc:
                    # attribute a raising hook clearly (a rsp hook runs
                    # inside the delivery timer, outside any task)
                    raise RuntimeError(f"{kind} hook raised: {exc!r}") from exc
            return True

        hooks[nid] = hook

    def hook_rpc_req(self, node, req_type: type, f: Callable) -> None:
        """Install THE request hook for ``node`` (one per node, insert
        overwrites — mod.rs:223-240): RPC requests of ``req_type`` SENT
        BY ``node`` are dropped when ``f(req)`` returns False. Pass
        ``f=None`` to remove."""
        self._install_typed_hook(
            self._hooks_req, node, req_type, f, is_rsp=False,
            kind="hook_rpc_req",
        )

    def hook_rpc_rsp(self, node, rsp_type: type, f: Callable) -> None:
        """Install THE response hook for ``node`` (mod.rs:242-264): RPC
        responses of ``rsp_type`` about to be DELIVERED TO ``node`` are
        dropped when ``f(rsp)`` returns False. Pass ``f=None`` to
        remove."""
        self._install_typed_hook(
            self._hooks_rsp, node, rsp_type, f, is_rsp=True,
            kind="hook_rpc_rsp",
        )

    def add_send_hook(self, hook: Callable[[int, SocketAddr, object], bool]) -> int:
        """Register a chaos hook consulted before every datagram send;
        return False from the hook to drop the message (the analog of the
        RPC req/rsp drop hooks, mod.rs:223-262). Returns a hook id."""
        hook_id = self._next_hook_id
        self._next_hook_id += 1
        self._send_hooks[hook_id] = hook
        return hook_id

    def remove_send_hook(self, hook_id: int) -> None:
        self._send_hooks.pop(hook_id, None)

    # ---- send path (mod.rs:265-302) ------------------------------------
    def rand_delay(self) -> SimFuture:
        """Random 0-5 us processing delay before each network op."""
        delay = self.rng.randrange(0, _MAX_PROCESSING_DELAY_NS)
        fut = SimFuture(name="rand_delay")
        self.time.add_timer_at(self.time.now_ns() + delay, fut.set_result)
        return fut

    async def send(
        self,
        src_node: int,
        src_addr: SocketAddr,
        dst: SocketAddr,
        proto: str,
        msg: object,
    ) -> None:
        """Datagram send: processing delay -> hooks -> fault model ->
        latency timer -> ``Socket.deliver`` (mod.rs:273-302). Loss, clog
        and missing destination all drop silently, like UDP."""
        await self.rand_delay()
        req_hook = self._hooks_req.get(src_node)
        if req_hook is not None and not req_hook(msg):
            return
        for hook in list(self._send_hooks.values()):
            if not hook(src_node, dst, msg):
                return
        deliveries = []
        res = self.network.try_send(src_node, dst, proto)
        if res is not None:
            deliveries.append(res)
        if self._duplicate:
            # duplication chaos: a second copy routed independently —
            # its own loss coin and latency draw, like a real duplicate
            # in flight (the engine's dup shadow rows)
            res2 = self.network.try_send(src_node, dst, proto)
            if res2 is not None:
                deliveries.append(res2)
        for sock, dst_node, latency in deliveries:
            # rsp hook captured at send, consulted at delivery time like
            # the reference's timer closure (mod.rs:291-297)
            rsp_hook = self._hooks_rsp.get(dst_node)

            def deliver(sock=sock, rsp_hook=rsp_hook) -> None:
                if rsp_hook is not None and not rsp_hook(msg):
                    return
                # visible source address: loopback stays loopback
                sock.deliver(src_addr, dst, msg)

            self.time.add_timer_at(self.time.now_ns() + latency, deliver)

    # ---- reliable connection machinery (mod.rs:306-365) ----------------
    def register_pipe(self, pipe: Pipe) -> None:
        self._pipes_by_node.setdefault(pipe.src_node, set()).add(pipe)
        self._pipes_by_node.setdefault(pipe.dst_node, set()).add(pipe)
        pipe.on_close = self._unregister_pipe

    def _unregister_pipe(self, pipe: Pipe) -> None:
        self._pipes_by_node.get(pipe.src_node, set()).discard(pipe)
        self._pipes_by_node.get(pipe.dst_node, set()).discard(pipe)

    async def wait_unclogged(self, src: int, dst: int) -> None:
        """Exponential backoff while the link is clogged
        (1 ms -> 10 s, mod.rs:341-355)."""
        backoff = _BACKOFF_MIN_NS
        while self.network.is_clogged(src, dst):
            fut = SimFuture(name="backoff")
            self.time.add_timer_at(self.time.now_ns() + backoff, fut.set_result)
            await fut
            backoff = min(backoff * 2, _BACKOFF_MAX_NS)

    async def deliver_reliable(self, src: int, dst: int, deliver: Callable[[], None]) -> None:
        """Reliable in-order delivery: wait out clogs, then apply one-way
        latency (connections never drop packets; TCP-like semantics)."""
        await self.wait_unclogged(src, dst)
        lo = round(self.config.net.send_latency[0] * NANOS_PER_SEC)
        hi = round(self.config.net.send_latency[1] * NANOS_PER_SEC)
        # gray failure scales the drawn latency (post-draw, so the RNG
        # stream is identical with or without the slow link)
        latency = self.rng.randrange(lo, max(hi, lo + 1)) * self.network.slow_mult(
            src, dst
        )
        fut = SimFuture(name="conn_latency")
        self.time.add_timer_at(self.time.now_ns() + latency, fut.set_result)
        await fut
        deliver()

    def spawn_pump(self, out_pipe: Pipe, in_pipe: Pipe) -> None:
        """Pump task moving messages out_pipe -> in_pipe, spawned on the
        sending node so it dies with the node (mod.rs:329-365)."""

        async def pump():
            while True:
                item = await out_pipe.pop()
                if item is None:  # closed and drained
                    in_pipe.close()
                    return
                await self.deliver_reliable(
                    out_pipe.src_node, out_pipe.dst_node, lambda it=item: in_pipe.push(it)
                )

        executor = self.handle.executor
        node_info = executor.nodes[out_pipe.src_node]
        executor.spawn_on(node_info, pump(), name=f"pump:{out_pipe.src_node}->{out_pipe.dst_node}")

    @staticmethod
    def current() -> "NetSim":
        """The current runtime's NetSim instance."""
        return context.current_handle().simulator(NetSim)
