"""Deterministic address parsing/resolution.

Parity with reference madsim/src/sim/net/addr.rs: a synchronous,
deterministic resolver — no real DNS. ``"localhost"`` maps to 127.0.0.1
(addr.rs:1-80); accepted forms are ``"ip:port"`` strings, ``(ip, port)``
tuples, and already-parsed :class:`SocketAddr`.
"""

from __future__ import annotations

from typing import Iterable, Tuple, Union

__all__ = ["SocketAddr", "parse_addr", "lookup_host", "AddrLike"]

SocketAddr = Tuple[str, int]
AddrLike = Union[str, SocketAddr]

_ALIASES = {"localhost": "127.0.0.1", "": "0.0.0.0", "*": "0.0.0.0"}


def _canon_ip(ip: str) -> str:
    return _ALIASES.get(ip, ip)


def parse_addr(addr: AddrLike) -> SocketAddr:
    """Parse an address into a canonical ``(ip, port)`` tuple."""
    if isinstance(addr, tuple):
        ip, port = addr
        return (_canon_ip(str(ip)), int(port))
    if isinstance(addr, str):
        if ":" not in addr:
            raise ValueError(f"invalid socket address {addr!r}: expected 'ip:port'")
        host, _, port_s = addr.rpartition(":")
        return (_canon_ip(host), int(port_s))
    raise TypeError(f"cannot parse address from {type(addr).__name__}")


def _is_ip_literal(s: str) -> bool:
    return bool(s) and not any(c.isalpha() for c in s)


async def lookup_host(host: AddrLike) -> Iterable[SocketAddr]:
    """Deterministic hostname resolution (addr.rs:32): never touches
    real DNS. IP literals (plus the localhost aliases) canonicalize;
    inside a simulation, a non-IP name resolves to the simulated node
    with that name (the node registry IS the zone file — beyond the
    reference's alias-only resolver), so services connect by name:
    ``asyncio.open_connection("kv-server", 7000)``. An unknown name
    raises OSError like a real resolver."""
    ip, port = parse_addr(host)
    if _is_ip_literal(ip):
        return [(ip, port)]
    from ..runtime import context

    h = context.try_current_handle()
    if h is not None:
        for info in h.executor.nodes.values():
            if info.name == ip and info.ip:
                return [(info.ip, port)]
    raise OSError(f"name resolution failed for {ip!r}")
