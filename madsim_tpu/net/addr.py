"""Deterministic address parsing/resolution.

Parity with reference madsim/src/sim/net/addr.rs: a synchronous,
deterministic resolver — no real DNS. ``"localhost"`` maps to 127.0.0.1
(addr.rs:1-80); accepted forms are ``"ip:port"`` strings, ``(ip, port)``
tuples, and already-parsed :class:`SocketAddr`.
"""

from __future__ import annotations

from typing import Iterable, Tuple, Union

__all__ = ["SocketAddr", "parse_addr", "lookup_host", "AddrLike"]

SocketAddr = Tuple[str, int]
AddrLike = Union[str, SocketAddr]

_ALIASES = {"localhost": "127.0.0.1", "": "0.0.0.0", "*": "0.0.0.0"}


def _canon_ip(ip: str) -> str:
    return _ALIASES.get(ip, ip)


def parse_addr(addr: AddrLike) -> SocketAddr:
    """Parse an address into a canonical ``(ip, port)`` tuple."""
    if isinstance(addr, tuple):
        ip, port = addr
        return (_canon_ip(str(ip)), int(port))
    if isinstance(addr, str):
        if ":" not in addr:
            raise ValueError(f"invalid socket address {addr!r}: expected 'ip:port'")
        host, _, port_s = addr.rpartition(":")
        return (_canon_ip(host), int(port_s))
    raise TypeError(f"cannot parse address from {type(addr).__name__}")


async def lookup_host(host: AddrLike) -> Iterable[SocketAddr]:
    """Deterministic hostname resolution (addr.rs:32): returns the single
    canonical address; never touches real DNS."""
    return [parse_addr(host)]
