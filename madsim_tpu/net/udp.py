"""UDP socket simulator — a thin veneer over Endpoint tag 0.

Parity with reference madsim/src/sim/net/udp.rs:9-73: bind / connect /
send_to / recv_from with datagram loss/latency/partition semantics
inherited from the network fault model.
"""

from __future__ import annotations

from typing import Optional

from .addr import AddrLike, SocketAddr, parse_addr
from .endpoint import Endpoint
from .network import Protocols

__all__ = ["UdpSocket"]

_UDP_TAG = 0


class UdpSocket:
    def __init__(self, ep: Endpoint):
        self._ep = ep
        self._peer: Optional[SocketAddr] = None

    @classmethod
    async def bind(cls, addr: AddrLike) -> "UdpSocket":
        # Own protocol namespace: coexists with TCP/Endpoint on a port.
        return cls(await Endpoint.bind(addr, _proto=Protocols.UDP))

    @property
    def local_addr(self) -> SocketAddr:
        return self._ep.local_addr

    async def send_to(self, data: bytes, addr: AddrLike) -> int:
        await self._ep.send_to(addr, _UDP_TAG, bytes(data))
        return len(data)

    async def recv_from(self) -> tuple[bytes, SocketAddr]:
        payload, src = await self._ep.recv_from(_UDP_TAG)
        return payload, src

    async def connect(self, addr: AddrLike) -> None:
        self._peer = parse_addr(addr)

    async def send(self, data: bytes) -> int:
        if self._peer is None:
            raise OSError("UdpSocket.send before connect")
        return await self.send_to(data, self._peer)

    async def recv(self) -> bytes:
        if self._peer is None:
            raise OSError("UdpSocket.recv before connect")
        while True:
            payload, src = await self.recv_from()
            if src == self._peer:
                return payload

    @property
    def peer_addr(self) -> Optional[SocketAddr]:
        return self._peer

    def close(self) -> None:
        """Release the port binding (sockets are per-node resources)."""
        self._ep.close()
