"""Unix-domain-socket API placeholders.

Parity with reference madsim/src/sim/net/unix/ (C15): the reference
ships hidden-doc stubs whose methods are ``todo!()`` — the API surface
exists so code referencing it compiles, but using it in simulation
panics. Same contract here: constructing or using these raises
NotImplementedError.
"""

from __future__ import annotations

__all__ = ["UnixDatagram", "UnixListener", "UnixStream"]


class _Todo:
    _WHAT = "unix sockets"

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            f"{self._WHAT} are not simulated yet (reference parity: "
            f"sim/net/unix/ is todo!() stubs)"
        )

    @classmethod
    async def bind(cls, *a, **kw):
        raise NotImplementedError(f"{cls._WHAT} are not simulated yet")

    @classmethod
    async def connect(cls, *a, **kw):
        raise NotImplementedError(f"{cls._WHAT} are not simulated yet")


class UnixDatagram(_Todo):
    _WHAT = "unix datagram sockets"


class UnixListener(_Todo):
    _WHAT = "unix listeners"


class UnixStream(_Todo):
    _WHAT = "unix streams"
