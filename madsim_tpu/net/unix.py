"""Unix-domain socket simulator: path-addressed node-local IPC.

The reference ships only hidden-doc ``todo!()`` stubs here
(madsim/src/sim/net/unix/stream.rs:16-45, datagram.rs:6 — C15); this
implementation goes beyond parity. Semantics chosen to match real unix
sockets mapped onto the simulation model:

  * paths are **node-local**: a bind on node A is invisible to node B,
    exactly as filesystem paths don't cross machines.
  * transfers are local IPC — no latency/loss/clog draws (network chaos
    does not touch same-machine sockets) — but every socket dies with
    its node: kill/restart closes streams (peer reads EOF) and unbinds
    paths, riding the same pipe-reset machinery as TCP connections.
  * streams support half-close and EOF like the TCP sim; datagrams are
    unreliable-in-principle but never dropped (loopback).

Streams reuse the connection :class:`~madsim_tpu.net.netsim.Pipe`
machinery; the byte-stream façade mirrors ``TcpStream``.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from ..runtime.future import SimFuture
from ..runtime.plugin import node as current_node
from .netsim import NetSim, Pipe, PipeReceiver, PipeSender
from .tcp import TcpStream

__all__ = ["UnixDatagram", "UnixListener", "UnixStream"]


def _key(path: str) -> tuple[int, str]:
    if not path:
        raise ValueError("unix socket path must be non-empty")
    return (current_node(), str(path))


class UnixStream(TcpStream):
    """Byte stream over a unix path (stream.rs API shape).

    Inherits the buffered read/write/flush/half-close behavior from the
    TCP sim; only addressing and connection setup differ.
    """

    def __init__(self, tx: PipeSender, rx: PipeReceiver, local_path: str, peer_path: str):
        super().__init__(tx, rx, local_path, peer_path)  # type: ignore[arg-type]

    @classmethod
    async def connect(cls, path: str) -> "UnixStream":
        """Connect to a listener bound at ``path`` on the *current* node."""
        net = NetSim.current()
        key = _key(path)
        await net.rand_delay()
        listener = net.unix_binds.get(key)
        if not isinstance(listener, UnixListener):
            raise ConnectionRefusedError(f"no unix listener at {path!r}")
        node = key[0]
        # one pipe per direction; local IPC pushes directly (no pump, no
        # latency draw) but registration ties lifetime to the node
        a2b, b2a = Pipe(node, node), Pipe(node, node)
        group = (a2b, b2a)
        for p in group:
            p.group = group
            net.register_pipe(p)
        stream = cls(PipeSender(a2b), PipeReceiver(b2a), "", path)
        listener._deliver(a2b, b2a)
        return stream

    @property
    def local_path(self) -> str:
        return self._local  # type: ignore[return-value]

    @property
    def peer_path(self) -> str:
        return self._peer  # type: ignore[return-value]


class UnixListener:
    def __init__(self, net: NetSim, key: tuple[int, str]):
        self._net = net
        self._key = key
        self._backlog: deque[tuple[Pipe, Pipe]] = deque()
        self._waiters: deque[SimFuture] = deque()
        self._closed = False

    @classmethod
    async def bind(cls, path: str) -> "UnixListener":
        net = NetSim.current()
        key = _key(path)
        if key in net.unix_binds:
            raise OSError(f"address already in use: unix path {path!r}")
        listener = cls(net, key)
        net.unix_binds[key] = listener
        return listener

    @property
    def local_path(self) -> str:
        return self._key[1]

    def _deliver(self, a2b: Pipe, b2a: Pipe) -> None:
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result((a2b, b2a))
                return
        self._backlog.append((a2b, b2a))

    async def accept(self) -> tuple[UnixStream, str]:
        if self._closed:
            raise OSError("listener is closed")
        if self._backlog:
            a2b, b2a = self._backlog.popleft()
        else:
            fut = SimFuture(name="unix.accept")
            self._waiters.append(fut)
            res = await fut
            if res is None:
                raise ConnectionResetError("listener closed while accepting")
            a2b, b2a = res
        stream = UnixStream(PipeSender(b2a), PipeReceiver(a2b), self._key[1], "")
        return stream, ""

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._net.unix_binds.pop(self._key, None)
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)
        for a2b, b2a in self._backlog:
            a2b.close()
            b2a.close()
        self._backlog.clear()

    def _on_node_reset(self) -> None:
        """Node kill/restart: pending accepts fail, backlog closes.
        (Established streams close via the pipe registry.)"""
        self._closed = True
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)
        self._backlog.clear()


class UnixDatagram:
    """Datagram socket over unix paths (datagram.rs API shape)."""

    def __init__(self, net: NetSim, key: Optional[tuple[int, str]]):
        self._net = net
        self._key = key  # None = anonymous (unbound) socket
        self._queue: deque[tuple[bytes, str]] = deque()
        self._waiters: deque[SimFuture] = deque()
        self._peer: Optional[str] = None
        self._closed = False

    @classmethod
    async def bind(cls, path: str) -> "UnixDatagram":
        net = NetSim.current()
        key = _key(path)
        if key in net.unix_binds:
            raise OSError(f"address already in use: unix path {path!r}")
        sock = cls(net, key)
        net.unix_binds[key] = sock
        return sock

    @classmethod
    async def unbound(cls) -> "UnixDatagram":
        """An anonymous socket: can send, cannot be addressed."""
        return cls(NetSim.current(), None)

    @property
    def local_path(self) -> str:
        return self._key[1] if self._key else ""

    async def connect(self, path: str) -> None:
        """Set the default destination for :meth:`send`."""
        self._peer = str(path)

    async def send_to(self, data: bytes, path: str) -> int:
        if self._closed:
            raise OSError("socket is closed")
        net = self._net
        key = _key(path)
        await net.rand_delay()
        dst = net.unix_binds.get(key)
        if not isinstance(dst, UnixDatagram):
            raise ConnectionRefusedError(f"no unix datagram socket at {path!r}")
        dst._deliver(bytes(data), self.local_path)
        return len(data)

    async def send(self, data: bytes) -> int:
        if self._peer is None:
            raise OSError("socket is not connected")
        return await self.send_to(data, self._peer)

    def _deliver(self, data: bytes, src: str) -> None:
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result((data, src))
                return
        self._queue.append((data, src))

    async def recv_from(self) -> tuple[bytes, str]:
        if self._queue:
            return self._queue.popleft()
        if self._closed:
            raise OSError("socket is closed")
        fut = SimFuture(name="unix.recv")
        self._waiters.append(fut)
        res = await fut
        if res is None:
            raise ConnectionResetError("socket closed while receiving")
        return res

    async def recv(self) -> bytes:
        data, _src = await self.recv_from()
        return data

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._key is not None:
            self._net.unix_binds.pop(self._key, None)
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)

    def _on_node_reset(self) -> None:
        self.close()
