"""TCP stream simulator: byte streams over NetSim connections.

Parity with reference madsim/src/sim/net/tcp/:
  * ``TcpListener.bind`` / ``accept`` hand out fully-formed streams
    (listener.rs:35-95).
  * ``TcpStream`` buffers writes locally and transmits on ``flush``
    (stream.rs:146-163 — ``poll_write`` buffers, ``poll_flush`` sends);
    reads buffer incoming chunks and serve partial reads
    (stream.rs:118-142).
  * a peer node reset closes the stream: reads return EOF (b"") and
    writes raise — the partition/reset semantics tested by the reference
    (tcp/mod.rs:98-208).

Streams ride the same reliable in-order connection pipes as Endpoint
``connect1``/``accept1``, so clog/unclog stalls and resumes byte streams
exactly like the reference's TCP sim.
"""

from __future__ import annotations

from typing import Optional

from .addr import AddrLike, SocketAddr, parse_addr
from .endpoint import Endpoint, PipeReceiver, PipeSender
from .network import Protocols

__all__ = ["TcpListener", "TcpStream"]


class TcpStream:
    def __init__(
        self,
        tx: PipeSender,
        rx: PipeReceiver,
        local_addr: SocketAddr,
        peer_addr: SocketAddr,
        owned_ep: Optional[Endpoint] = None,
    ):
        self._tx = tx
        self._rx = rx
        self._local = local_addr
        self._peer = peer_addr
        self._wbuf = bytearray()
        self._rbuf = bytearray()
        self._eof = False
        # the ephemeral endpoint backing an outbound connection — unbound
        # on close so connection churn doesn't exhaust the port space
        self._owned_ep = owned_ep

    # ---- construction ---------------------------------------------------
    @classmethod
    async def connect(cls, addr: AddrLike) -> "TcpStream":
        """Connect from the current node (stream.rs:71-91)."""
        ep = await Endpoint.bind(("0.0.0.0", 0), _proto=Protocols.TCP)
        try:
            tx, rx = await ep.connect1(addr)
        except BaseException:
            ep.close()
            raise
        return cls(tx, rx, ep.local_addr, parse_addr(addr), owned_ep=ep)

    @property
    def local_addr(self) -> SocketAddr:
        return self._local

    @property
    def peer_addr(self) -> SocketAddr:
        return self._peer

    # ---- write side (stream.rs:146-163) ---------------------------------
    async def write(self, data: bytes) -> int:
        """Buffer bytes locally; nothing is transmitted until flush."""
        self._wbuf.extend(data)
        return len(data)

    async def flush(self) -> None:
        if not self._wbuf:
            return
        chunk = bytes(self._wbuf)
        self._wbuf.clear()
        await self._tx.send(chunk)

    async def write_all(self, data: bytes) -> None:
        await self.write(data)
        await self.flush()

    # ---- read side (stream.rs:118-142) ----------------------------------
    async def read(self, n: int) -> bytes:
        """Up to ``n`` bytes; b"" on EOF (peer closed or node reset)."""
        if n <= 0:
            return b""
        while not self._rbuf:
            if self._eof:
                return b""
            chunk = await self._rx.recv()
            if chunk is None:
                self._eof = True
                return b""
            self._rbuf.extend(chunk)
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    async def read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = await self.read(n - len(out))
            if not chunk:
                raise ConnectionResetError(
                    f"connection closed with {n - len(out)} bytes still expected"
                )
            out.extend(chunk)
        return bytes(out)

    def set_nodelay(self, _nodelay: bool = True) -> None:
        """Accepted and ignored, like the reference's simulated socket
        (stream.rs:94-98) — the sim has no Nagle buffering to disable."""

    def shutdown(self) -> None:
        """Close the write half; the peer sees EOF after in-flight data.
        The read half keeps working (TCP half-close)."""
        self._tx.shutdown()

    def close(self) -> None:
        """Close the whole stream, releasing both directions' resources.
        Reset-like: a peer blocked in ``read`` wakes with EOF immediately,
        even if sent bytes are still in flight (the node-reset semantics
        of tcp/mod.rs:98-208)."""
        self._tx.close()
        if self._owned_ep is not None:
            self._owned_ep.close()
            self._owned_ep = None

    def close_graceful(self) -> None:
        """FIN-like close: the write half shuts down, so the peer sees
        EOF only AFTER all in-flight bytes deliver (real-TCP close
        ordering — the asyncio transport layer needs this; plain
        ``close`` is a reset). Our own future reads return EOF; the
        reverse-direction pipes close when the peer closes its end."""
        self._tx.shutdown()
        self._eof = True
        if self._owned_ep is not None:
            self._owned_ep.close()
            self._owned_ep = None


class TcpListener:
    def __init__(self, ep: Endpoint):
        self._ep = ep

    @classmethod
    async def bind(cls, addr: AddrLike) -> "TcpListener":
        # TCP ports live in their own namespace (network.rs keys sockets
        # by (addr, protocol)), so a UDP socket and TCP listener coexist
        # on the same port number.
        return cls(await Endpoint.bind(addr, _proto=Protocols.TCP))

    @property
    def local_addr(self) -> SocketAddr:
        return self._ep.local_addr

    async def accept(self) -> tuple[TcpStream, SocketAddr]:
        tx, rx, peer = await self._ep.accept1()
        return TcpStream(tx, rx, self._ep.local_addr, peer), peer
