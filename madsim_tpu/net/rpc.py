"""Typed request/response RPC over Endpoint tags.

Parity with reference madsim/src/sim/net/rpc.rs:
  * each request type has a stable 64-bit tag derived from its qualified
    name (the analog of ``#[derive(Request)]``'s
    ``ID = hash_str(module_path + name)``, madsim-macros/src/request.rs:
    60-66) — no registration or serialization needed in simulation.
  * ``call`` sends ``(req, data, resp_tag, ...)`` on the request tag with a
    *random* u64 response tag, then awaits that tag (rpc.rs:96-131).
  * ``add_rpc_handler`` spawns a service loop on the current node:
    receive -> spawn handler task -> reply (rpc.rs:134-166).
"""

from __future__ import annotations

import hashlib
from typing import Any, Awaitable, Callable, Optional

from ..runtime import context, task as task_mod
from ..runtime.time_ import timeout as time_timeout
from .addr import AddrLike

__all__ = ["rpc_id", "call", "call_with_data", "add_rpc_handler", "add_rpc_handler_with_data"]


def rpc_id(req_type: type) -> int:
    """Stable request tag from the type's qualified name (request.rs:60-66).

    Override by setting a class attribute ``__rpc_id__``."""
    explicit = req_type.__dict__.get("__rpc_id__")
    if explicit is not None:
        return int(explicit)
    name = f"{req_type.__module__}.{req_type.__qualname__}"
    # masked to 63 bits: the bit-63 tag space is reserved for response
    # frames (see call_with_data / Endpoint.send_to)
    return int.from_bytes(
        hashlib.sha256(name.encode()).digest()[:8], "big"
    ) & ((1 << 63) - 1)


async def call(ep, dst: AddrLike, req: Any, timeout: Optional[float] = None) -> Any:
    resp, _ = await call_with_data(ep, dst, req, b"", timeout=timeout)
    return resp


async def call_with_data(
    ep, dst: AddrLike, req: Any, data: bytes, timeout: Optional[float] = None
) -> tuple[Any, bytes]:
    """Send a typed request plus a data payload; await the typed response
    (rpc.rs:114-131). A response tag is drawn at random per call."""
    rng = context.current_handle().rng
    resp_tag = rng.getrandbits(63) | (1 << 63)  # avoid user tag collisions
    req_tag = rpc_id(type(req))
    await ep.send_to(dst, req_tag, (req, data, resp_tag))

    async def wait_resp():
        payload, _src = await ep.recv_from(resp_tag)
        return payload

    if timeout is not None:
        try:
            result = await time_timeout(timeout, wait_resp())
        except BaseException:
            # The per-call response tag is never reused; drop its waiter so
            # failed calls don't grow the mailbox.
            ep._mailbox.drop_tag(resp_tag)
            raise
    else:
        result = await wait_resp()
    resp, resp_data = result
    if isinstance(resp, BaseException):
        raise resp
    return resp, resp_data


def add_rpc_handler(ep, req_type: type, handler: Callable[[Any], Awaitable[Any]]) -> None:
    """Serve ``req_type`` requests on this endpoint: each request spawns a
    handler task whose return value is sent back (rpc.rs:134-150).
    Exceptions raised by the handler travel back and re-raise at the
    caller."""

    async def with_data(req: Any, _data: bytes) -> tuple[Any, bytes]:
        return await handler(req), b""

    add_rpc_handler_with_data(ep, req_type, with_data)


def add_rpc_handler_with_data(
    ep, req_type: type, handler: Callable[[Any, bytes], Awaitable[tuple[Any, bytes]]]
) -> None:
    """Data-carrying variant (rpc.rs:152-166)."""
    tag = rpc_id(req_type)

    async def serve_loop():
        while True:
            (req, data, resp_tag), src = await ep.recv_from(tag)

            async def handle(req=req, data=data, resp_tag=resp_tag, src=src):
                try:
                    resp, resp_data = await handler(req, data)
                except Exception as exc:  # noqa: BLE001 - travels to caller
                    resp, resp_data = exc, b""
                await ep.send_to(src, resp_tag, (resp, resp_data), _reserved=True)

            task_mod.spawn(handle(), name=f"rpc:{req_type.__name__}")

    task_mod.spawn(serve_loop(), name=f"rpc-serve:{req_type.__name__}")
