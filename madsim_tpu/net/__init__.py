"""Simulated network stack (reference: madsim/src/sim/net/)."""

from ..runtime.runtime import DEFAULT_SIMULATORS
from .addr import SocketAddr, lookup_host, parse_addr
from .endpoint import Endpoint, PipeReceiver, PipeSender
from .netsim import NetSim
from .network import Network, Stat
from .rpc import add_rpc_handler, add_rpc_handler_with_data, call, call_with_data, rpc_id
# NOTE: the @rpc decorator is deliberately NOT re-exported here — it
# would shadow the `net.rpc` submodule. Import it from the service
# module: `from madsim_tpu.net.service import rpc, service`.
from .service import service
from .tcp import TcpListener, TcpStream
from .udp import UdpSocket
from .unix import UnixDatagram, UnixListener, UnixStream

if NetSim not in DEFAULT_SIMULATORS:
    DEFAULT_SIMULATORS.append(NetSim)

__all__ = [
    "Endpoint",
    "NetSim",
    "TcpListener",
    "TcpStream",
    "UdpSocket",
    "UnixDatagram",
    "UnixListener",
    "UnixStream",
    "Network",
    "PipeReceiver",
    "PipeSender",
    "SocketAddr",
    "Stat",
    "add_rpc_handler",
    "add_rpc_handler_with_data",
    "call",
    "call_with_data",
    "lookup_host",
    "parse_addr",
    "rpc_id",
]
