"""Service decorator: declarative RPC services over Endpoint.

Parity with the reference's ``#[madsim::service]`` + ``#[rpc]`` codegen
(madsim-macros/src/service.rs:61-110): decorate a class with
:func:`service` and its ``@rpc`` methods become typed RPC handlers; the
generated ``serve(addr)`` / ``serve_on(ep)`` methods register every
handler on an Endpoint, exactly like the generated ``serve`` functions.

    @service
    class KvStore:
        @rpc
        async def get(self, req: GetReq) -> bytes: ...

    node.spawn(KvStore().serve("0.0.0.0:7000"))

The request type is taken from the handler's single-argument annotation
(the analog of the reference's typed fn signature).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

from .endpoint import Endpoint

__all__ = ["service", "rpc"]


def rpc(fn: Callable) -> Callable:
    """Mark a method as an RPC handler (the ``#[rpc]`` attribute)."""
    fn.__rpc_method__ = True  # type: ignore[attr-defined]
    return fn


def _request_type(fn: Callable) -> type:
    # eval_str resolves PEP-563 string annotations (modules using
    # `from __future__ import annotations`) to the actual classes
    try:
        sig = inspect.signature(fn, eval_str=True)
    except NameError as e:
        raise TypeError(
            f"@rpc method {fn.__name__}: request annotation could not be "
            f"resolved ({e}); define the request type at module scope"
        ) from e
    params = [p for name, p in sig.parameters.items() if name != "self"]
    if not params or params[0].annotation is inspect.Parameter.empty:
        raise TypeError(
            f"@rpc method {fn.__name__} must annotate its request parameter "
            f"with the request type (e.g. `async def get(self, req: GetReq)`)"
        )
    req_type = params[0].annotation
    if not isinstance(req_type, type):
        raise TypeError(
            f"@rpc method {fn.__name__}: request annotation {req_type!r} is "
            f"not a class"
        )
    return req_type


def service(cls: type) -> type:
    """Class decorator generating ``serve``/``serve_on``
    (service.rs:61-110)."""
    handlers: list[tuple[type, str]] = []
    for name, fn in inspect.getmembers(cls, inspect.isfunction):
        if getattr(fn, "__rpc_method__", False):
            handlers.append((_request_type(fn), name))
    if not handlers:
        raise TypeError(f"@service class {cls.__name__} has no @rpc methods")

    async def serve_on(self, ep: Endpoint) -> None:
        """Register every @rpc handler on an existing endpoint."""
        for req_type, name in handlers:
            bound = getattr(self, name)
            ep.add_rpc_handler(req_type, bound)

    async def serve(self, addr: Any) -> Endpoint:
        """Bind an endpoint on ``addr`` and serve all @rpc methods."""
        ep = await Endpoint.bind(addr)
        await serve_on(self, ep)
        return ep

    cls.serve = serve  # type: ignore[attr-defined]
    cls.serve_on = serve_on  # type: ignore[attr-defined]
    cls.__rpc_handlers__ = tuple(handlers)  # type: ignore[attr-defined]
    return cls
