"""Endpoint — tag-matching messaging, the core transport primitive.

Parity with reference madsim/src/sim/net/endpoint.rs:
  * UDP-like *tagged datagrams* whose payload is any Python object,
    zero-copy within the process (the analog of ``Payload = Box<dyn Any>``
    — no serialization in simulation, endpoint.rs:13-172).
  * a ``Mailbox`` that matches incoming messages to pending receivers by
    tag, or queues them (endpoint.rs:288-353).
  * reliable ordered "connections" via ``connect1``/``accept1`` returning
    sender/receiver halves (endpoint.rs:176-264), pumped with clog-aware
    backoff by NetSim; a node reset closes the connection and the peer
    observes EOF.

Everything above this layer (RPC, the gRPC-like service shim, etcd- and
kafka-style simulators) is built on Endpoint, exactly as in the reference.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Optional

from ..runtime.future import SimFuture
from ..runtime.plugin import node as current_node
from .addr import AddrLike, SocketAddr, parse_addr
from .netsim import NetSim, Pipe, PipeReceiver, PipeSender
from .network import Protocols

__all__ = ["Endpoint", "PipeSender", "PipeReceiver"]


class _Mailbox:
    """Tag-matching mailbox (endpoint.rs:288-353)."""

    __slots__ = ("msgs", "waiters")

    def __init__(self) -> None:
        self.msgs: dict[int, deque[tuple[Any, SocketAddr]]] = {}
        self.waiters: dict[int, deque[SimFuture]] = {}

    def deliver(self, tag: int, payload: Any, src: SocketAddr) -> None:
        q = self.waiters.get(tag)
        while q:
            w = q.popleft()
            if not q:
                del self.waiters[tag]
            if not w.done():
                w.set_result((payload, src))
                return
        self.msgs.setdefault(tag, deque()).append((payload, src))

    def recv(self, tag: int) -> SimFuture:
        fut = SimFuture(name=f"recv:{tag}")
        q = self.msgs.get(tag)
        if q:
            payload, src = q.popleft()
            if not q:
                del self.msgs[tag]
            fut.set_result((payload, src))
        else:
            self.waiters.setdefault(tag, deque()).append(fut)
        return fut

    def drop_tag(self, tag: int) -> None:
        """Forget a tag's waiters and queued messages — used to clean up
        per-call response tags after an RPC timeout so the mailbox does
        not grow with every failed call."""
        self.waiters.pop(tag, None)
        self.msgs.pop(tag, None)


class _EndpointSocket:
    """Network-registered delivery target (endpoint.rs:301-341)."""

    __slots__ = ("endpoint",)

    def __init__(self, endpoint: "Endpoint"):
        self.endpoint = endpoint

    def deliver(self, src: SocketAddr, dst: SocketAddr, msg: object) -> None:
        kind = msg[0]
        if kind == "dgram":
            _, tag, payload = msg
            self.endpoint._mailbox.deliver(tag, payload, src)
        elif kind == "conn":
            _, conn = msg
            self.endpoint._deliver_conn(conn)


class _Conn:
    """Shared connection record exchanged at setup (zero-copy)."""

    __slots__ = ("out_ab", "in_ab", "out_ba", "in_ba", "client_addr")

    def __init__(self, out_ab: Pipe, in_ab: Pipe, out_ba: Pipe, in_ba: Pipe, client_addr: SocketAddr):
        self.out_ab = out_ab
        self.in_ab = in_ab
        self.out_ba = out_ba
        self.in_ba = in_ba
        self.client_addr = client_addr


class Endpoint:
    """Bind with ``await Endpoint.bind("0.0.0.0:5000")`` on a node task."""

    def __init__(self, netsim: NetSim, node_id: int, addr: SocketAddr, proto: str = Protocols.EP):
        self._net = netsim
        self._node = node_id
        self._addr = addr
        self._proto = proto
        self._mailbox = _Mailbox()
        self._accept_backlog: deque[_Conn] = deque()
        self._accept_waiters: deque[SimFuture] = deque()
        self._peer: Optional[SocketAddr] = None

    # ---- construction ---------------------------------------------------
    @classmethod
    async def bind(cls, addr: AddrLike, *, _proto: str = Protocols.EP) -> "Endpoint":
        """Bind on the current node (endpoint.rs:23-37). Port 0 allocates
        an ephemeral port. Ports are namespaced per protocol (the network
        keys sockets by ``(addr, protocol)``, network.rs:24-70), so the
        TCP/UDP sims bind their own namespaces and coexist with Endpoint
        on the same port number."""
        netsim = NetSim.current()
        node_id = current_node()
        req = parse_addr(addr)
        ep = cls(netsim, node_id, req, _proto)
        bound = netsim.network.bind(node_id, req, _proto, _EndpointSocket(ep))
        ep._addr = bound
        return ep

    @classmethod
    async def connect(cls, dst: AddrLike) -> "Endpoint":
        """Bind an ephemeral endpoint whose default peer is ``dst``
        (endpoint.rs:39-45); ``send``/``recv`` then omit the address."""
        ep = await cls.bind("0.0.0.0:0")
        ep._peer = parse_addr(dst)
        return ep

    @property
    def local_addr(self) -> SocketAddr:
        return self._addr

    @property
    def peer_addr(self) -> SocketAddr:
        """The connected peer (endpoint.rs:52-58); raises if the
        endpoint was bound rather than connected."""
        if self._peer is None:
            raise OSError("endpoint is not connected")
        return self._peer

    async def send(self, tag: int, payload: Any) -> None:
        """Send to the connected peer (endpoint.rs:96-101)."""
        await self.send_to(self.peer_addr, tag, payload)

    async def recv(self, tag: int) -> Any:
        """Receive a matching datagram from the connected peer
        (endpoint.rs:103-113): errors on an unconnected endpoint, and
        like the reference, a matching datagram from any OTHER source is
        an error — misdelivery surfaces instead of masquerading as the
        peer."""
        peer = self.peer_addr
        payload, src = await self.recv_from(tag)
        if src != peer:
            raise OSError(
                f"received tag {tag} from {src}, not the connected peer {peer}"
            )
        return payload

    def close(self) -> None:
        """Unbind from the network, releasing the socket-table entry
        (Network::close, network.rs:261). Ephemeral per-connection
        endpoints (e.g. TcpStream.connect) must call this or the node's
        port space leaks one entry per connect."""
        self._net.network.close(self._node, self._addr, self._proto)

    def _visible_src(self, dst_ip: str) -> SocketAddr:
        """Source address as seen by the receiver: loopback for local
        destinations, the node IP otherwise. A node without an assigned IP
        cannot address remote peers — fail loudly instead of silently
        misrouting replies."""
        ip, port = self._addr
        if dst_ip in ("127.0.0.1", "localhost"):
            return ("127.0.0.1", port)
        node_ip = self._net.network.ip_of(self._node)
        if node_ip is None:
            raise OSError(
                f"node {self._node} has no IP address; give it one with "
                f"create_node().ip(...) before sending to remote peers"
            )
        return (node_ip, port)

    # ---- tagged datagrams (endpoint.rs:68-147) --------------------------
    async def send_to(
        self, dst: AddrLike, tag: int, payload: Any, *, _reserved: bool = False
    ) -> None:
        """Send one tagged datagram; silently dropped on loss/partition
        like the reference's UDP-style sends.

        Tags with bit 63 set are reserved for RPC response frames
        (rpc.py draws response tags in that space; the typed RPC hooks
        discriminate frames by it) — user sends may not use them."""
        if not _reserved and isinstance(tag, int) and tag >> 63:
            raise ValueError(
                "tags >= 2**63 are reserved for RPC response frames"
            )
        dst_a = parse_addr(dst)
        await self._net.send(
            self._node,
            self._visible_src(dst_a[0]),
            dst_a,
            self._proto,
            ("dgram", tag, payload),
        )

    async def recv_from(self, tag: int) -> tuple[Any, SocketAddr]:
        """Receive the next datagram matching ``tag``
        (endpoint.rs:86-111, 343-352)."""
        payload, src = await self._mailbox.recv(tag)
        await self._net.rand_delay()
        return payload, src

    def try_recv_from(self, tag: int) -> Optional[tuple[Any, SocketAddr]]:
        q = self._mailbox.msgs.get(tag)
        if q:
            payload, src = q.popleft()
            if not q:
                del self._mailbox.msgs[tag]
            return payload, src
        return None

    # ---- connections (endpoint.rs:176-264) ------------------------------
    async def connect1(self, dst: AddrLike) -> tuple[PipeSender, PipeReceiver]:
        """Open a reliable ordered connection to a bound peer endpoint.

        Raises ConnectionRefusedError when no endpoint is bound at ``dst``.
        Blocks (with clog backoff) until the setup message reaches the
        peer's backlog — TCP-handshake-like semantics."""
        net = self._net
        await net.rand_delay()
        dst_a = parse_addr(dst)
        dst_node = net.network.resolve_dest_node(dst_a[0], self._node)
        if dst_node is None:
            raise ConnectionRefusedError(f"no route to {dst_a[0]}:{dst_a[1]}")
        sock = net.network.lookup_socket(dst_node, dst_a, self._proto)
        if sock is None or not isinstance(sock, _EndpointSocket):
            raise ConnectionRefusedError(f"connection refused: {dst_a[0]}:{dst_a[1]}")

        a, b = self._node, dst_node
        out_ab, in_ab = Pipe(a, b), Pipe(a, b)
        out_ba, in_ba = Pipe(b, a), Pipe(b, a)
        group = (out_ab, in_ab, out_ba, in_ba)
        conn = _Conn(out_ab, in_ab, out_ba, in_ba, self._visible_src(dst_a[0]))
        for p in group:
            p.group = group
            net.register_pipe(p)
        net.spawn_pump(out_ab, in_ab)
        # Handshake: the setup message travels reliably (no loss draw, but
        # clog blocks it) and lands in the peer's accept backlog.
        await net.deliver_reliable(a, b, lambda: sock.deliver(conn.client_addr, dst_a, ("conn", conn)))
        return PipeSender(out_ab), PipeReceiver(in_ba)

    def _deliver_conn(self, conn: _Conn) -> None:
        while self._accept_waiters:
            w = self._accept_waiters.popleft()
            if not w.done():
                w.set_result(conn)
                return
        self._accept_backlog.append(conn)

    async def accept1(self) -> tuple[PipeSender, PipeReceiver, SocketAddr]:
        """Accept one connection (endpoint.rs:198-209): returns
        (sender, receiver, peer_addr)."""
        if self._accept_backlog:
            conn = self._accept_backlog.popleft()
        else:
            fut = SimFuture(name="accept")
            self._accept_waiters.append(fut)
            conn = await fut
        # pump for our -> client direction runs on our node
        self._net.spawn_pump(conn.out_ba, conn.in_ba)
        return PipeSender(conn.out_ba), PipeReceiver(conn.in_ab), conn.client_addr

    # ---- typed RPC sugar (C12; implemented in net/rpc.py) ---------------
    async def call(self, dst: AddrLike, req: Any, timeout: Optional[float] = None) -> Any:
        # import the submodule explicitly: the package re-exports the @rpc
        # decorator under the same name, shadowing `from . import rpc`
        from .rpc import call as rpc_call

        return await rpc_call(self, dst, req, timeout=timeout)

    async def call_with_data(
        self, dst: AddrLike, req: Any, data: bytes, timeout: Optional[float] = None
    ) -> tuple[Any, bytes]:
        from .rpc import call_with_data as rpc_call_with_data

        return await rpc_call_with_data(self, dst, req, data, timeout=timeout)

    def add_rpc_handler(self, req_type: type, handler) -> None:
        from .rpc import add_rpc_handler as rpc_add

        rpc_add(self, req_type, handler)

    def add_rpc_handler_with_data(self, req_type: type, handler) -> None:
        from .rpc import add_rpc_handler_with_data as rpc_add_wd

        rpc_add_wd(self, req_type, handler)
