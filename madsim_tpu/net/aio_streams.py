"""Raw asyncio streams over the simulated network.

The transport half of the loop interposition (runtime/aio.py): stdlib
``asyncio.open_connection`` / ``asyncio.start_server`` call
``loop.create_connection`` / ``loop.create_server`` on the running
loop — inside a simulation that is the :class:`SimEventLoop`, which
delegates here. A :class:`SimTransport` adapts the byte-stream TCP
simulator (net/tcp.py — NetSim latency/loss/clog/partition semantics,
reference sim/net/tcp/) to asyncio's Transport/Protocol contract, so
the stdlib's OWN ``StreamReader``/``StreamWriter``/
``StreamReaderProtocol`` machinery runs unmodified against the
simulated network: an asyncio echo server written purely with
``asyncio.start_server`` accepts simulated connections, sees simulated
latency, and dies with its simulated node.

This is the analog of the reference simulating tokio's TcpStream under
the same API (sim/net/tcp/stream.rs): user network code unchanged,
bytes riding the deterministic network.
"""

from __future__ import annotations

import asyncio as _aio
from typing import Callable, Optional

from ..runtime.task import spawn
from .addr import lookup_host, parse_addr
from .tcp import TcpListener, TcpStream
from .udp import UdpSocket

__all__ = [
    "SimTransport",
    "SimDatagramTransport",
    "SimServer",
    "create_connection",
    "create_server",
    "create_datagram_endpoint",
]

_READ_CHUNK = 64 * 1024


class SimTransport:
    """asyncio.Transport over a simulated TcpStream.

    Writes are synchronous per the Transport contract: bytes land in an
    ordered queue drained by a writer pump task (one flush per queued
    chunk, preserving order); reads run in a reader pump that feeds
    ``protocol.data_received`` and honors ``pause_reading``.
    """

    def __init__(self, loop, stream: TcpStream, protocol, on_lost=None):
        self._loop = loop
        self._stream = stream
        self._protocol = protocol
        self._on_lost = on_lost  # server book-keeping (connection churn)
        self._closing = False
        self._closed = False
        self._eof_sent = False
        self._write_q: list[Optional[bytes]] = []  # None = shutdown marker
        self._write_wake = _aio.Event()
        self._read_paused = _aio.Event()
        self._read_paused.set()  # set = reading allowed
        self._pumps = []

    # -- wiring ------------------------------------------------------------
    def _start(self) -> None:
        self._protocol.connection_made(self)
        self._pumps.append(spawn(self._read_pump(), name="tcp-read-pump"))
        self._pumps.append(spawn(self._write_pump(), name="tcp-write-pump"))

    async def _read_pump(self) -> None:
        try:
            while not self._closed:
                await self._read_paused.wait()
                data = await self._stream.read(_READ_CHUNK)
                if not data:
                    # EOF: peer half-closed (or reset). eof_received()
                    # returning true means KEEP the transport open for
                    # writes (TCP half-close — StreamReaderProtocol does
                    # this), so request/EOF/response exchanges work;
                    # falsy = tear down, as real transports do
                    keep = False
                    try:
                        keep = bool(self._protocol.eof_received())
                    finally:
                        if not keep:
                            self._drop(None)
                    return
                self._protocol.data_received(data)
        except ConnectionError as exc:
            self._drop(exc)

    async def _write_pump(self) -> None:
        try:
            while True:
                while not self._write_q:
                    if self._closing:
                        # graceful close: every queued write has been
                        # flushed — FIN after data, never a reset
                        self._drop(None, graceful=True)
                        return
                    self._write_wake.clear()
                    await self._write_wake.wait()
                item = self._write_q.pop(0)
                if item is None:
                    self._stream.shutdown()  # half-close: EOF after data
                    continue
                await self._stream.write_all(item)
        except ConnectionError as exc:
            self._drop(exc)

    def _drop(self, exc: Optional[BaseException], graceful: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        if graceful:
            self._stream.close_graceful()
        else:
            self._stream.close()
        try:
            self._protocol.connection_lost(exc)
        finally:
            if self._on_lost is not None:
                self._on_lost(self)
            for p in self._pumps:
                if not p.done():
                    p.abort()

    # -- asyncio.Transport surface ----------------------------------------
    def get_extra_info(self, name: str, default=None):
        # SocketAddr is already the ``(ip, port)`` tuple (net/addr.py)
        if name == "peername":
            return self._stream.peer_addr
        if name == "sockname":
            return self._stream.local_addr
        return default

    def write(self, data: bytes) -> None:
        if self._eof_sent:
            # loud like real transports — a silent drop here would let a
            # buggy test pass in sim and fail in production
            raise RuntimeError("Cannot call write() after write_eof()")
        if self._closing or self._closed:
            return  # real transports warn-and-drop after close
        if data:
            self._write_q.append(bytes(data))
            self._write_wake.set()

    def writelines(self, chunks) -> None:
        self.write(b"".join(chunks))

    def can_write_eof(self) -> bool:
        return True

    def write_eof(self) -> None:
        if self._eof_sent or self._closed:
            return
        self._eof_sent = True
        self._write_q.append(None)
        self._write_wake.set()

    def is_closing(self) -> bool:
        return self._closing or self._closed

    def close(self) -> None:
        """Graceful: pending writes flush, then the connection drops."""
        if self._closing or self._closed:
            return
        self._closing = True
        self._write_wake.set()

    def abort(self) -> None:
        self._drop(None)

    # flow control (StreamReader buffer limits call these)
    def pause_reading(self) -> None:
        self._read_paused.clear()

    def resume_reading(self) -> None:
        self._read_paused.set()

    def is_reading(self) -> bool:
        return self._read_paused.is_set()

    # write flow control introspection (StreamWriter.drain consults the
    # protocol, which only pauses if WE call pause_writing — we never
    # do: the simulated send buffer is unbounded like the reference's)
    def get_write_buffer_size(self) -> int:
        return sum(len(c) for c in self._write_q if c)

    def get_write_buffer_limits(self) -> tuple:
        return (0, 0)

    def set_write_buffer_limits(self, high=None, low=None) -> None:
        pass


class SimDatagramTransport:
    """asyncio.DatagramTransport over the simulated UdpSocket: backs raw
    ``loop.create_datagram_endpoint`` — stdlib DatagramProtocol code
    (``datagram_received``/``error_received``) runs against NetSim's
    datagram loss/latency/partition model (udp.rs:9-73 parity)."""

    def __init__(self, loop, sock: UdpSocket, protocol, remote):
        self._loop = loop
        self._sock = sock
        self._protocol = protocol
        self._remote = remote  # (ip, port) filter for connected sockets
        self._closing = False
        self._closed = False
        self._send_q: list[tuple[bytes, tuple]] = []
        self._send_wake = _aio.Event()
        self._pumps = []

    def _start(self) -> None:
        self._protocol.connection_made(self)
        self._pumps.append(spawn(self._recv_pump(), name="udp-recv-pump"))
        self._pumps.append(spawn(self._send_pump(), name="udp-send-pump"))

    async def _recv_pump(self) -> None:
        # stop on _closing too: asyncio removes the reader the moment
        # close() is called, even while queued sends still flush
        while not (self._closing or self._closed):
            data, src = await self._sock.recv_from()
            if self._closing or self._closed:
                return
            if self._remote is not None and src != self._remote:
                continue  # connected-socket filter (udp.py recv parity)
            self._protocol.datagram_received(data, src)

    async def _send_pump(self) -> None:
        while True:
            while not self._send_q:
                if self._closing:
                    self._teardown(None)
                    return
                self._send_wake.clear()
                await self._send_wake.wait()
            data, addr = self._send_q.pop(0)
            try:
                await self._sock.send_to(data, addr)
            except (OSError, ValueError, TypeError) as exc:
                # datagram semantics: per-packet error, transport lives
                self._protocol.error_received(exc)

    def _teardown(self, exc) -> None:
        if self._closed:
            return
        self._closed = True
        self._sock.close()
        try:
            self._protocol.connection_lost(exc)
        finally:
            for p in self._pumps:
                if not p.done():
                    p.abort()

    # -- asyncio.DatagramTransport surface --------------------------------
    def get_extra_info(self, name: str, default=None):
        if name == "sockname":
            return self._sock.local_addr
        if name == "peername":
            return self._remote
        return default

    def sendto(self, data: bytes, addr=None) -> None:
        if self._closing or self._closed:
            return
        if addr is None:
            if self._remote is None:
                raise ValueError("no address given and socket not connected")
            addr = self._remote
        else:
            # validate at the CALL SITE (a malformed addr surfacing later
            # in the send pump would fail the whole sim far from the bug)
            addr = parse_addr(addr)
            if self._remote is not None and addr != tuple(self._remote):
                raise ValueError(
                    f"invalid address: must be {self._remote} "
                    f"(connected socket)"
                )
        self._send_q.append((bytes(data), addr))
        self._send_wake.set()

    def is_closing(self) -> bool:
        return self._closing or self._closed

    def close(self) -> None:
        if self._closing or self._closed:
            return
        self._closing = True
        self._send_wake.set()  # queued datagrams flush, then teardown

    def abort(self) -> None:
        self._teardown(None)


class SimServer:
    """asyncio.Server stand-in returned by ``start_server`` in a sim."""

    def __init__(self, loop, listener: TcpListener, protocol_factory):
        self._loop = loop
        self._listener = listener
        self._factory = protocol_factory
        self._accept_task = None
        self._closed_fut = loop.create_future()
        self._serving_fut = None
        # dict-as-ordered-set: a plain set would iterate in address
        # order, making close_clients()/abort_clients() close
        # connections in a NONDETERMINISTIC order — the exact class of
        # hidden nondeterminism this simulator exists to forbid
        self._transports: dict[SimTransport, None] = {}

    @property
    def sockets(self) -> list:
        return []  # no real sockets in a simulation

    def is_serving(self) -> bool:
        return self._accept_task is not None and not self._accept_task.done()

    def _start(self) -> None:
        self._accept_task = spawn(self._accept_loop(), name="tcp-accept-loop")

    async def _accept_loop(self) -> None:
        while True:
            stream, _peer = await self._listener.accept()
            protocol = self._factory()
            # the connection-lost hook prunes the transport so churn
            # does not accumulate dead entries for the server's lifetime
            tr = SimTransport(
                self._loop, stream, protocol,
                on_lost=lambda t: self._transports.pop(t, None),
            )
            self._transports[tr] = None
            tr._start()

    async def start_serving(self) -> None:
        if not self.is_serving():
            self._start()

    async def serve_forever(self) -> None:
        if self._serving_fut is not None:
            raise RuntimeError("server is already being awaited on")
        await self.start_serving()
        self._serving_fut = self._loop.create_future()
        try:
            # pends until close() cancels it (asyncio.Server semantics:
            # close cancels the serve-forever future; CancelledError
            # propagates to the caller after cleanup)
            await self._serving_fut
        except _aio.CancelledError:
            try:
                self.close()
                await self.wait_closed()
            finally:
                raise

    def close(self) -> None:
        if self._accept_task is not None and not self._accept_task.done():
            self._accept_task.abort()
        self._listener._ep.close()
        if self._serving_fut is not None and not self._serving_fut.done():
            self._serving_fut.cancel()
        if not self._closed_fut.done():
            self._closed_fut.set_result(None)

    def close_clients(self) -> None:
        for tr in list(self._transports):
            tr.close()

    def abort_clients(self) -> None:
        for tr in list(self._transports):
            tr.abort()

    async def wait_closed(self) -> None:
        await self._closed_fut

    async def __aenter__(self) -> "SimServer":
        return self

    async def __aexit__(self, *exc) -> None:
        self.close()
        await self.wait_closed()


async def create_connection(
    loop, protocol_factory: Callable, host: str, port: int, **kwargs
):
    """``loop.create_connection`` for the sim loop: resolve (node names
    resolve deterministically, net/addr.py), connect the simulated TCP,
    adapt via SimTransport, return ``(transport, protocol)``."""
    addr = next(iter(await lookup_host((host, port))))
    stream = await TcpStream.connect(addr)
    protocol = protocol_factory()
    tr = SimTransport(loop, stream, protocol)
    tr._start()
    return tr, protocol


async def create_server(
    loop, protocol_factory: Callable, host=None, port=None, *,
    start_serving: bool = True, **kwargs
):
    """``loop.create_server`` for the sim loop."""
    listener = await TcpListener.bind((host or "0.0.0.0", port or 0))
    server = SimServer(loop, listener, protocol_factory)
    if start_serving:
        server._start()
    return server


async def create_datagram_endpoint(
    loop, protocol_factory: Callable, local_addr=None, remote_addr=None,
    **kwargs
):
    """``loop.create_datagram_endpoint`` for the sim loop."""
    sock = await UdpSocket.bind(local_addr or ("0.0.0.0", 0))
    try:
        if remote_addr is not None:
            await sock.connect(next(iter(await lookup_host(remote_addr))))
    except BaseException:
        # the bind succeeded: release the port or a retry on the same
        # local_addr fails with address-already-in-use for the rest of
        # the sim
        sock.close()
        raise
    protocol = protocol_factory()
    tr = SimDatagramTransport(
        loop, sock, protocol, sock.peer_addr
    )
    tr._start()
    return tr, protocol
