// io_uring tag-matching message transport — the second alternative
// fast-path endpoint (completing the C28 slot).
//
// The reference ships two alternative transports behind the same
// feature seam as its TCP endpoint: UCX RDMA (madsim/src/std/net/
// ucx.rs:23-30) and eRPC/ibverbs (std/net/erpc.rs:24-30). This file is
// the second alternative here: the same wire format and C ABI shape as
// the epoll transport (native/transport.cpp), but the event loop is a
// proactor over a raw io_uring — completions instead of readiness, so
// the receive path costs one io_uring_enter per batch instead of
// epoll_wait + recv per wakeup, and backpressured writes ride WRITE
// SQEs instead of EPOLLOUT re-arming.
//
// Wire format (identical to transport.cpp and madsim_tpu/std/net.py, so
// uring, epoll and asyncio endpoints all interoperate):
//     8B big-endian payload length | 8B big-endian tag | payload bytes
// Handshake frame: tag 2^64-1, payload "ip:port".
//
// The environment has no liburing; the ~100-line shim below drives the
// raw kernel interface (io_uring_setup / mmap'd SQ+CQ rings /
// io_uring_enter) directly with acquire/release atomics.
//
// C ABI only (ctypes binding; no pybind11 in this environment).

#include <arpa/inet.h>
#include <linux/io_uring.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// minimal raw io_uring shim (no liburing in this image)
// ---------------------------------------------------------------------------

int sys_io_uring_setup(unsigned entries, io_uring_params* p) {
  return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}

int sys_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                       unsigned flags) {
  return static_cast<int>(syscall(__NR_io_uring_enter, fd, to_submit,
                                  min_complete, flags, nullptr, 0));
}

struct Uring {
  int ring_fd = -1;
  unsigned sq_entries = 0;
  // SQ ring (mmap'd)
  uint8_t* sq_ptr = nullptr;
  size_t sq_len = 0;
  unsigned* sq_head = nullptr;  // kernel-consumed index
  unsigned* sq_tail = nullptr;  // producer index (ours)
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  io_uring_sqe* sqes = nullptr;
  size_t sqes_len = 0;
  // CQ ring
  uint8_t* cq_ptr = nullptr;
  size_t cq_len = 0;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;
  unsigned pending = 0;  // SQEs staged since the last enter

  bool setup(unsigned entries) {
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    ring_fd = sys_io_uring_setup(entries, &p);
    if (ring_fd < 0) return false;
    sq_entries = p.sq_entries;
    sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    sq_ptr = static_cast<uint8_t*>(
        mmap(nullptr, sq_len, PROT_READ | PROT_WRITE, MAP_SHARED | MAP_POPULATE,
             ring_fd, IORING_OFF_SQ_RING));
    if (sq_ptr == MAP_FAILED) return false;
    sq_head = reinterpret_cast<unsigned*>(sq_ptr + p.sq_off.head);
    sq_tail = reinterpret_cast<unsigned*>(sq_ptr + p.sq_off.tail);
    sq_mask = reinterpret_cast<unsigned*>(sq_ptr + p.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned*>(sq_ptr + p.sq_off.array);
    sqes_len = p.sq_entries * sizeof(io_uring_sqe);
    sqes = static_cast<io_uring_sqe*>(
        mmap(nullptr, sqes_len, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQES));
    if (sqes == MAP_FAILED) return false;
    cq_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    cq_ptr = static_cast<uint8_t*>(
        mmap(nullptr, cq_len, PROT_READ | PROT_WRITE, MAP_SHARED | MAP_POPULATE,
             ring_fd, IORING_OFF_CQ_RING));
    if (cq_ptr == MAP_FAILED) return false;
    cq_head = reinterpret_cast<unsigned*>(cq_ptr + p.cq_off.head);
    cq_tail = reinterpret_cast<unsigned*>(cq_ptr + p.cq_off.tail);
    cq_mask = reinterpret_cast<unsigned*>(cq_ptr + p.cq_off.ring_mask);
    cqes = reinterpret_cast<io_uring_cqe*>(cq_ptr + p.cq_off.cqes);
    return true;
  }

  void teardown() {
    if (sq_ptr && sq_ptr != MAP_FAILED) munmap(sq_ptr, sq_len);
    if (sqes && sqes != reinterpret_cast<io_uring_sqe*>(MAP_FAILED))
      munmap(sqes, sqes_len);
    if (cq_ptr && cq_ptr != MAP_FAILED) munmap(cq_ptr, cq_len);
    if (ring_fd >= 0) ::close(ring_fd);
    ring_fd = -1;
  }

  // Next free SQE, or null when the staged batch fills the ring (the
  // caller flushes with enter() and retries).
  io_uring_sqe* get_sqe() {
    unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    unsigned tail = *sq_tail;
    if (tail - head >= sq_entries) return nullptr;
    io_uring_sqe* sqe = &sqes[tail & *sq_mask];
    std::memset(sqe, 0, sizeof(*sqe));
    sq_array[tail & *sq_mask] = tail & *sq_mask;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    pending++;
    return sqe;
  }

  int enter(unsigned wait_nr) {
    int rc = sys_io_uring_enter(ring_fd, pending, wait_nr,
                                wait_nr ? IORING_ENTER_GETEVENTS : 0);
    if (rc >= 0) {
      // rc = SQEs the kernel consumed; on error (e.g. EBUSY under CQ
      // pressure) everything stays staged and the next enter retries
      pending -= (static_cast<unsigned>(rc) < pending
                      ? static_cast<unsigned>(rc)
                      : pending);
    }
    return rc;
  }

  bool peek_cqe(io_uring_cqe** out) {
    unsigned head = *cq_head;
    if (head == __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE)) return false;
    *out = &cqes[head & *cq_mask];
    return true;
  }

  void seen() { __atomic_store_n(cq_head, *cq_head + 1, __ATOMIC_RELEASE); }
};

// ---------------------------------------------------------------------------
// transport (same semantics as transport.cpp's Endpoint)
// ---------------------------------------------------------------------------

constexpr uint64_t kHelloTag = ~0ull;
constexpr uint64_t kMaxFrame = 1ull << 30;
constexpr size_t kMaxWbuf = (1ull << 30) + (1ull << 20);
// 256 KiB: four bench-size frames per completion — the proactor's
// throughput edge comes from fewer completion round-trips per byte
constexpr size_t kRecvChunk = 1 << 18;

// user_data: op tag in the top byte, fd in the low 32 bits
constexpr uint64_t kOpAccept = 1;
constexpr uint64_t kOpRecv = 2;
constexpr uint64_t kOpWrite = 3;
constexpr uint64_t kOpWake = 4;

uint64_t make_ud(uint64_t op, int fd) {
  return (op << 56) | static_cast<uint32_t>(fd);
}

uint64_t load_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

void store_be64(uint8_t* p, uint64_t v) {
  for (int i = 7; i >= 0; i--) {
    p[i] = v & 0xff;
    v >>= 8;
  }
}

void append_frame(std::vector<uint8_t>& out, uint64_t tag, const uint8_t* data,
                  uint64_t len) {
  uint8_t head[16];
  store_be64(head, len);
  store_be64(head + 8, tag);
  out.insert(out.end(), head, head + 16);
  if (len) out.insert(out.end(), data, data + len);
}

struct Msg {
  std::vector<uint8_t> data;
  std::string src_ip;
  int src_port;
};

struct Conn {
  int fd = -1;
  std::string peer_key;
  std::vector<uint8_t> rbuf;       // parsed-frame accumulator
  std::vector<uint8_t> chunk;      // in-flight RECV target (stable)
  bool recv_inflight = false;
  std::vector<uint8_t> wbuf;       // append-only staging (do_send)
  std::vector<uint8_t> inflight;   // stable buffer owned by a WRITE SQE
  size_t inflight_off = 0;
  bool write_inflight = false;
};

struct Endpoint {
  int listen_fd = -1;
  int wake_fd = -1;
  int port = 0;
  std::string bind_ip;
  Uring ring;
  std::thread loop;
  std::mutex mu;
  std::condition_variable cv;
  bool closed = false;
  std::map<int, Conn> conns;
  std::map<std::string, int> peers;
  std::map<uint64_t, std::deque<Msg>> mailbox;
  std::vector<int> new_conns;   // fds the loop must start RECVing
  std::vector<int> kick_write;  // fds with fresh wbuf data
  bool accept_inflight = false;

  ~Endpoint() { close_all(); }

  void kick() {
    uint64_t one = 1;
    (void)!write(wake_fd, &one, 8);
  }

  void close_all() {
    {
      std::lock_guard<std::mutex> g(mu);
      if (closed) return;
      closed = true;
    }
    kick();
    if (loop.joinable()) loop.join();
    std::lock_guard<std::mutex> g(mu);
    for (auto& [fd, c] : conns) ::close(fd);
    conns.clear();
    peers.clear();
    if (listen_fd >= 0) ::close(listen_fd);
    if (wake_fd >= 0) ::close(wake_fd);
    ring.teardown();
    listen_fd = wake_fd = -1;
    cv.notify_all();
  }

  bool start(const char* ip, int want_port) {
    bind_ip = ip;
    listen_fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) return false;
    if (bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      return false;
    if (listen(listen_fd, 128) != 0) return false;
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);
    wake_fd = eventfd(0, EFD_CLOEXEC);
    if (wake_fd < 0 || !ring.setup(256)) return false;
    loop = std::thread([this] { run_loop(); });
    return true;
  }

  // ---- SQE submission helpers (loop thread only) ----------------------
  io_uring_sqe* sqe_or_flush() {
    io_uring_sqe* s = ring.get_sqe();
    if (s == nullptr) {
      ring.enter(0);
      s = ring.get_sqe();
    }
    return s;
  }

  void submit_accept() {
    io_uring_sqe* s = sqe_or_flush();
    if (!s) return;
    s->opcode = IORING_OP_ACCEPT;
    s->fd = listen_fd;
    s->user_data = make_ud(kOpAccept, listen_fd);
    accept_inflight = true;
  }

  uint64_t wake_buf = 0;
  bool wake_inflight = false;
  void submit_wake_read() {
    if (wake_inflight) return;
    io_uring_sqe* s = sqe_or_flush();
    if (!s) return;
    s->opcode = IORING_OP_READ;
    s->fd = wake_fd;
    s->addr = reinterpret_cast<uint64_t>(&wake_buf);
    s->len = 8;
    s->user_data = make_ud(kOpWake, wake_fd);
    wake_inflight = true;
  }

  void submit_recv_locked(Conn& c) {
    if (c.recv_inflight) return;
    io_uring_sqe* s = sqe_or_flush();
    if (!s) return;
    if (c.chunk.size() != kRecvChunk) c.chunk.resize(kRecvChunk);
    s->opcode = IORING_OP_RECV;
    s->fd = c.fd;
    s->addr = reinterpret_cast<uint64_t>(c.chunk.data());
    s->len = kRecvChunk;
    s->user_data = make_ud(kOpRecv, c.fd);
    c.recv_inflight = true;
  }

  void submit_write_locked(Conn& c) {
    if (c.write_inflight) return;
    if (c.inflight_off >= c.inflight.size()) {
      if (c.wbuf.empty()) return;
      // swap-in a stable buffer: do_send keeps appending to wbuf while
      // this one rides the SQE (a vector the kernel reads must never
      // reallocate under it)
      c.inflight.clear();
      c.inflight.swap(c.wbuf);
      c.inflight_off = 0;
    }
    io_uring_sqe* s = sqe_or_flush();
    if (!s) return;
    s->opcode = IORING_OP_SEND;
    s->fd = c.fd;
    s->addr = reinterpret_cast<uint64_t>(c.inflight.data() + c.inflight_off);
    s->len = static_cast<unsigned>(c.inflight.size() - c.inflight_off);
    s->user_data = make_ud(kOpWrite, c.fd);
    c.write_inflight = true;
  }

  void drop_conn_locked(int fd) {
    auto it = conns.find(fd);
    if (it != conns.end()) {
      if (!it->second.peer_key.empty()) {
        auto pit = peers.find(it->second.peer_key);
        if (pit != peers.end() && pit->second == fd) peers.erase(pit);
      }
      conns.erase(it);
    }
    ::close(fd);
  }

  void parse_frames_locked(Conn& c) {
    for (;;) {
      if (c.rbuf.size() < 16) return;
      uint64_t len = load_be64(c.rbuf.data());
      uint64_t tag = load_be64(c.rbuf.data() + 8);
      if (len > kMaxFrame) {
        drop_conn_locked(c.fd);
        return;
      }
      if (c.rbuf.size() < 16 + len) return;
      if (tag == kHelloTag) {
        std::string key(c.rbuf.begin() + 16, c.rbuf.begin() + 16 + len);
        c.peer_key = key;
        peers.emplace(key, c.fd);
      } else {
        Msg m;
        m.data.assign(c.rbuf.begin() + 16, c.rbuf.begin() + 16 + len);
        auto colon = c.peer_key.rfind(':');
        if (colon != std::string::npos) {
          m.src_ip = c.peer_key.substr(0, colon);
          m.src_port = atoi(c.peer_key.c_str() + colon + 1);
        } else {
          m.src_ip = "?";
          m.src_port = 0;
        }
        mailbox[tag].push_back(std::move(m));
        cv.notify_all();
      }
      c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + 16 + len);
    }
  }

  void run_loop() {
    {
      std::lock_guard<std::mutex> g(mu);
      submit_accept();
      submit_wake_read();
    }
    for (;;) {
      int rc = ring.enter(1);
      if (rc < 0 && errno != EINTR) return;
      std::unique_lock<std::mutex> g(mu);
      io_uring_cqe* cqe;
      while (ring.peek_cqe(&cqe)) {
        uint64_t op = cqe->user_data >> 56;
        int fd = static_cast<int>(cqe->user_data & 0xffffffffu);
        int res = cqe->res;
        ring.seen();
        if (op == kOpWake) {
          wake_inflight = false;
          if (closed) return;
          submit_wake_read();
          // kicked: new outbound conns to watch / fresh bytes to write
          for (int nfd : new_conns) {
            auto it = conns.find(nfd);
            if (it != conns.end()) submit_recv_locked(it->second);
          }
          new_conns.clear();
          for (int wfd : kick_write) {
            auto it = conns.find(wfd);
            if (it != conns.end()) submit_write_locked(it->second);
          }
          kick_write.clear();
        } else if (op == kOpAccept) {
          accept_inflight = false;
          if (res >= 0) {
            conns[res] = Conn{};
            conns[res].fd = res;
            submit_recv_locked(conns[res]);
          }
          submit_accept();
        } else if (op == kOpRecv) {
          auto it = conns.find(fd);
          if (it == conns.end()) continue;
          Conn& c = it->second;
          c.recv_inflight = false;
          if (res <= 0) {
            drop_conn_locked(fd);
            continue;
          }
          c.rbuf.insert(c.rbuf.end(), c.chunk.data(), c.chunk.data() + res);
          parse_frames_locked(c);
          // the conn may have been dropped by a bad frame
          auto it2 = conns.find(fd);
          if (it2 != conns.end()) submit_recv_locked(it2->second);
        } else if (op == kOpWrite) {
          auto it = conns.find(fd);
          if (it == conns.end()) continue;
          Conn& c = it->second;
          c.write_inflight = false;
          if (res < 0) {
            drop_conn_locked(fd);
            continue;
          }
          c.inflight_off += static_cast<size_t>(res);
          submit_write_locked(c);  // rest of inflight, or swap in wbuf
        }
      }
      if (closed) return;
      // self-heal sweep: a submit_* that found the SQ full (or an
      // enter() that failed) dropped its SQE silently; nothing else
      // retries, so re-arm anything missing each wakeup
      if (!accept_inflight) submit_accept();
      if (!wake_inflight) submit_wake_read();
      for (auto& [cfd, c] : conns) {
        submit_recv_locked(c);
        submit_write_locked(c);
      }
    }
  }

  int connect_peer_locked(const std::string& ip, int pport,
                          const std::string& key) {
    int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(pport));
    if (inet_pton(AF_INET, ip.c_str(), &addr.sin_addr) != 1 ||
        ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(fd);
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::string my_ip = bind_ip;
    if (my_ip == "0.0.0.0") {
      sockaddr_in local{};
      socklen_t llen = sizeof(local);
      getsockname(fd, reinterpret_cast<sockaddr*>(&local), &llen);
      char buf[INET_ADDRSTRLEN];
      inet_ntop(AF_INET, &local.sin_addr, buf, sizeof(buf));
      my_ip = buf;
    }
    std::string hello = my_ip + ":" + std::to_string(port);
    Conn c;
    c.fd = fd;
    c.peer_key = key;
    append_frame(c.wbuf, kHelloTag,
                 reinterpret_cast<const uint8_t*>(hello.data()), hello.size());
    conns[fd] = std::move(c);
    peers[key] = fd;
    new_conns.push_back(fd);
    kick_write.push_back(fd);
    kick();
    return fd;
  }

  int do_send(const char* ip, int pport, uint64_t tag, const uint8_t* data,
              uint64_t len) {
    std::lock_guard<std::mutex> g(mu);
    if (closed) return -1;
    std::string key = std::string(ip) + ":" + std::to_string(pport);
    auto it = peers.find(key);
    int fd = (it != peers.end()) ? it->second
                                 : connect_peer_locked(ip, pport, key);
    if (fd < 0) return -1;
    auto cit = conns.find(fd);
    if (cit == conns.end()) return -1;
    Conn& c = cit->second;
    size_t queued = c.wbuf.size() + (c.inflight.size() - c.inflight_off);
    if (queued + len + 16 > kMaxWbuf) return -1;  // backpressure
    append_frame(c.wbuf, tag, data, len);
    if (!c.write_inflight && c.inflight_off >= c.inflight.size()) {
      // fast path: no WRITE SQE owns this fd, so the caller may drain
      // directly with a non-blocking send — skipping the eventfd-kick +
      // loop-thread hop that would otherwise tax every message's
      // latency. Ordering is safe: mu serializes against the loop
      // thread, which only writes when write_inflight is set.
      size_t off = 0;
      while (off < c.wbuf.size()) {
        ssize_t w = ::send(fd, c.wbuf.data() + off, c.wbuf.size() - off,
                           MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w <= 0) break;
        off += static_cast<size_t>(w);
      }
      if (off >= c.wbuf.size()) {
        c.wbuf.clear();
        return 0;
      }
      c.wbuf.erase(c.wbuf.begin(), c.wbuf.begin() + static_cast<ptrdiff_t>(off));
    }
    if (!c.write_inflight) {
      kick_write.push_back(fd);
      kick();
    }
    return 0;
  }

  Msg* take(uint64_t tag, int64_t timeout_ms) {
    std::unique_lock<std::mutex> g(mu);
    auto ready = [&] {
      auto it = mailbox.find(tag);
      return closed || (it != mailbox.end() && !it->second.empty());
    };
    if (timeout_ms < 0) {
      cv.wait(g, ready);
    } else if (!cv.wait_for(g, std::chrono::milliseconds(timeout_ms), ready)) {
      return nullptr;
    }
    auto it = mailbox.find(tag);
    if (it == mailbox.end() || it->second.empty()) return nullptr;
    Msg* m = new Msg(std::move(it->second.front()));
    it->second.pop_front();
    if (it->second.empty()) mailbox.erase(it);
    return m;
  }
};

}  // namespace

extern "C" {

void* urep_bind(const char* ip, int port, int* out_port) {
  auto* ep = new Endpoint();
  if (!ep->start(ip, port)) {
    delete ep;
    return nullptr;
  }
  if (out_port) *out_port = ep->port;
  return ep;
}

int urep_send(void* h, const char* ip, int port, uint64_t tag,
              const uint8_t* data, uint64_t len) {
  return static_cast<Endpoint*>(h)->do_send(ip, port, tag, data, len);
}

void* urep_recv(void* h, uint64_t tag, int64_t timeout_ms) {
  return static_cast<Endpoint*>(h)->take(tag, timeout_ms);
}

uint64_t urep_msg_len(void* m) { return static_cast<Msg*>(m)->data.size(); }
const uint8_t* urep_msg_data(void* m) {
  return static_cast<Msg*>(m)->data.data();
}
const char* urep_msg_src_ip(void* m) {
  return static_cast<Msg*>(m)->src_ip.c_str();
}
int urep_msg_src_port(void* m) { return static_cast<Msg*>(m)->src_port; }
void urep_msg_free(void* m) { delete static_cast<Msg*>(m); }

// Two-phase teardown, same contract as the epoll transport: shutdown()
// wakes blocked receivers and joins the loop; free() only after the
// caller drained its receiver threads.
void urep_shutdown(void* h) { static_cast<Endpoint*>(h)->close_all(); }
void urep_free(void* h) { delete static_cast<Endpoint*>(h); }
void urep_close(void* h) {
  urep_shutdown(h);
  urep_free(h);
}

// 1 when the kernel accepts an io_uring (the wrapper probes before
// advertising this transport).
int urep_available(void) {
  io_uring_params p;
  std::memset(&p, 0, sizeof(p));
  int fd = sys_io_uring_setup(2, &p);
  if (fd < 0) return 0;
  ::close(fd);
  return 1;
}

}  // extern "C"
