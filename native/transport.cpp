// Native tag-matching message transport — the production-path endpoint.
//
// The reference's real-world side is a native tag-matching Endpoint over
// TCP: lazy per-peer connections opened on first send, an
// address-exchange handshake so inbound connections map to the peer's
// canonical listening address, length-delimited frames, and a
// tag-matching mailbox (reference madsim/src/std/net/tcp.rs:22-135,
// C26). This is that component in C++: a background epoll thread per
// endpoint reads frames into the mailbox; sends enqueue onto a
// per-connection write buffer flushed with non-blocking writes (by the
// caller when the socket has room, else by the epoll thread on
// EPOLLOUT) — a send can never block while holding the endpoint lock,
// so two in-process endpoints with full socket buffers cannot deadlock
// each other's reader threads.
//
// Wire format (shared with the asyncio backend in madsim_tpu/std/net.py
// so C++ and Python endpoints interoperate):
//     8B big-endian payload length | 8B big-endian tag | payload bytes
// The handshake frame uses tag HELLO = 2^64-1 with payload "ip:port"
// (the sender's canonical listen address). Payload bytes are opaque to
// the transport; the Python wrapper pickles/unpickles objects.
//
// C ABI only (ctypes binding; no pybind11 in this environment).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kHelloTag = ~0ull;
constexpr uint64_t kMaxFrame = 1ull << 30;  // 1 GiB sanity cap
// backpressure bound: one max-size frame may always be queued; beyond
// that do_send reports failure instead of buffering without limit
constexpr size_t kMaxWbuf = (1ull << 30) + (1ull << 20);

uint64_t load_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

void store_be64(uint8_t* p, uint64_t v) {
  for (int i = 7; i >= 0; i--) {
    p[i] = v & 0xff;
    v >>= 8;
  }
}

struct Msg {
  std::vector<uint8_t> data;
  std::string src_ip;
  int src_port;
};

struct Conn {
  int fd;
  std::string peer_key;  // canonical "ip:port" after hello, else ""
  std::vector<uint8_t> rbuf;
  std::vector<uint8_t> wbuf;  // pending outbound bytes (framed)
  size_t woff = 0;            // consumed prefix of wbuf
  bool want_write = false;    // EPOLLOUT armed
};

void append_frame(std::vector<uint8_t>& out, uint64_t tag, const uint8_t* data,
                  uint64_t len) {
  uint8_t head[16];
  store_be64(head, len);
  store_be64(head + 8, tag);
  out.insert(out.end(), head, head + 16);
  if (len) out.insert(out.end(), data, data + len);
}

struct Endpoint {
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;  // eventfd to stop the loop
  int port = 0;
  std::string bind_ip;
  std::thread loop;
  std::mutex mu;
  std::condition_variable cv;
  bool closed = false;
  std::map<int, Conn> conns;                      // fd -> conn (reader side)
  std::map<std::string, int> peers;               // "ip:port" -> fd (send side)
  std::map<uint64_t, std::deque<Msg>> mailbox;    // tag matching

  ~Endpoint() { close_all(); }

  void close_all() {
    {
      std::lock_guard<std::mutex> g(mu);
      if (closed) return;
      closed = true;
    }
    if (wake_fd >= 0) {
      uint64_t one = 1;
      (void)!write(wake_fd, &one, 8);
    }
    if (loop.joinable()) loop.join();
    std::lock_guard<std::mutex> g(mu);
    for (auto& [fd, c] : conns) ::close(fd);
    conns.clear();
    peers.clear();
    if (listen_fd >= 0) ::close(listen_fd);
    if (epoll_fd >= 0) ::close(epoll_fd);
    if (wake_fd >= 0) ::close(wake_fd);
    listen_fd = epoll_fd = wake_fd = -1;
    cv.notify_all();
  }

  bool start(const char* ip, int want_port) {
    bind_ip = ip;
    listen_fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) return false;
    if (bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      return false;
    if (listen(listen_fd, 128) != 0) return false;
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);

    epoll_fd = epoll_create1(EPOLL_CLOEXEC);
    wake_fd = eventfd(0, EFD_CLOEXEC);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = listen_fd;
    epoll_ctl(epoll_fd, EPOLL_CTL_ADD, listen_fd, &ev);
    ev.data.fd = wake_fd;
    epoll_ctl(epoll_fd, EPOLL_CTL_ADD, wake_fd, &ev);
    loop = std::thread([this] { run_loop(); });
    return true;
  }

  void watch(int fd) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(epoll_fd, EPOLL_CTL_ADD, fd, &ev);
  }

  void arm_write_locked(Conn& c, bool want) {
    if (c.want_write == want) return;
    c.want_write = want;
    epoll_event ev{};
    ev.events = EPOLLIN | (want ? static_cast<uint32_t>(EPOLLOUT) : 0u);
    ev.data.fd = c.fd;
    epoll_ctl(epoll_fd, EPOLL_CTL_MOD, c.fd, &ev);
  }

  // Non-blocking drain of c.wbuf. Returns false on a fatal socket
  // error (caller drops the conn). Never blocks: a full socket buffer
  // just leaves the tail queued with EPOLLOUT armed.
  bool flush_locked(Conn& c) {
    while (c.woff < c.wbuf.size()) {
      ssize_t w = ::send(c.fd, c.wbuf.data() + c.woff, c.wbuf.size() - c.woff,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w > 0) {
        c.woff += static_cast<size_t>(w);
        continue;
      }
      if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        // reclaim the consumed prefix even while backpressured, or a
        // connection that never fully drains retains every byte it
        // ever sent
        if (c.woff > (1u << 20)) {
          c.wbuf.erase(c.wbuf.begin(),
                       c.wbuf.begin() + static_cast<ptrdiff_t>(c.woff));
          c.woff = 0;
        }
        arm_write_locked(c, true);
        return true;
      }
      return false;
    }
    c.wbuf.clear();
    c.woff = 0;
    arm_write_locked(c, false);
    return true;
  }

  void drop_conn_locked(int fd) {
    epoll_ctl(epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
    auto it = conns.find(fd);
    if (it != conns.end()) {
      if (!it->second.peer_key.empty()) {
        auto pit = peers.find(it->second.peer_key);
        if (pit != peers.end() && pit->second == fd) peers.erase(pit);
      }
      conns.erase(it);
    }
    ::close(fd);
  }

  void run_loop() {
    epoll_event events[64];
    std::vector<uint8_t> tmp(1 << 16);
    for (;;) {
      int n = epoll_wait(epoll_fd, events, 64, 200);
      {
        std::lock_guard<std::mutex> g(mu);
        if (closed) return;
      }
      for (int i = 0; i < n; i++) {
        int fd = events[i].data.fd;
        if (fd == wake_fd) return;
        if (fd == listen_fd) {
          int cfd = accept4(listen_fd, nullptr, nullptr, SOCK_CLOEXEC);
          if (cfd >= 0) {
            std::lock_guard<std::mutex> g(mu);
            conns[cfd] = Conn{cfd, "", {}, {}, 0, false};
            watch(cfd);
          }
          continue;
        }
        if (events[i].events & EPOLLOUT) {
          std::lock_guard<std::mutex> g(mu);
          auto it = conns.find(fd);
          if (it != conns.end() && !flush_locked(it->second)) {
            drop_conn_locked(fd);
            continue;
          }
        }
        if (!(events[i].events & (EPOLLIN | EPOLLERR | EPOLLHUP))) continue;
        ssize_t r = ::recv(fd, tmp.data(), tmp.size(), MSG_DONTWAIT);
        if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) continue;
        std::lock_guard<std::mutex> g(mu);
        auto it = conns.find(fd);
        if (it == conns.end()) continue;
        if (r <= 0) {
          drop_conn_locked(fd);
          continue;
        }
        Conn& c = it->second;
        c.rbuf.insert(c.rbuf.end(), tmp.data(), tmp.data() + r);
        // drain complete frames
        for (;;) {
          if (c.rbuf.size() < 16) break;
          uint64_t len = load_be64(c.rbuf.data());
          uint64_t tag = load_be64(c.rbuf.data() + 8);
          if (len > kMaxFrame) {
            drop_conn_locked(fd);
            break;
          }
          if (c.rbuf.size() < 16 + len) break;
          if (tag == kHelloTag) {
            std::string key(c.rbuf.begin() + 16, c.rbuf.begin() + 16 + len);
            c.peer_key = key;
            peers.emplace(key, fd);  // prefer the first connection
          } else {
            Msg m;
            m.data.assign(c.rbuf.begin() + 16, c.rbuf.begin() + 16 + len);
            auto colon = c.peer_key.rfind(':');
            if (colon != std::string::npos) {
              m.src_ip = c.peer_key.substr(0, colon);
              m.src_port = atoi(c.peer_key.c_str() + colon + 1);
            } else {
              m.src_ip = "?";
              m.src_port = 0;
            }
            mailbox[tag].push_back(std::move(m));
            cv.notify_all();
          }
          c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + 16 + len);
        }
      }
    }
  }

  int connect_peer_locked(const std::string& ip, int pport,
                          const std::string& key) {
    int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(pport));
    if (inet_pton(AF_INET, ip.c_str(), &addr.sin_addr) != 1 ||
        ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(fd);
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // announce our canonical listen address; for a wildcard bind use
    // the outgoing socket's local IP (routable, unlike 0.0.0.0)
    std::string my_ip = bind_ip;
    if (my_ip == "0.0.0.0") {
      sockaddr_in local{};
      socklen_t llen = sizeof(local);
      getsockname(fd, reinterpret_cast<sockaddr*>(&local), &llen);
      char buf[INET_ADDRSTRLEN];
      inet_ntop(AF_INET, &local.sin_addr, buf, sizeof(buf));
      my_ip = buf;
    }
    std::string hello = my_ip + ":" + std::to_string(port);
    Conn c{fd, key, {}, {}, 0, false};
    append_frame(c.wbuf, kHelloTag,
                 reinterpret_cast<const uint8_t*>(hello.data()), hello.size());
    conns[fd] = std::move(c);
    peers[key] = fd;
    watch(fd);
    if (!flush_locked(conns[fd])) {
      // same rule as do_send's failure path: only the epoll thread may
      // close() a watched fd (it may already hold an event for it);
      // shutdown makes its recv return 0 so it closes safely itself
      ::shutdown(fd, SHUT_RDWR);
      peers.erase(key);
      return -1;
    }
    return fd;
  }

  int do_send(const char* ip, int pport, uint64_t tag, const uint8_t* data,
              uint64_t len) {
    // Lookup + connect + enqueue hold mu (the epoll thread closes fds
    // under the same lock, so a send can never target a
    // closed-and-reused descriptor, and concurrent sends to one peer
    // cannot interleave frames) — but the socket write itself is
    // non-blocking: a full socket buffer leaves the tail queued for the
    // epoll thread's EPOLLOUT flush instead of stalling reads, so two
    // in-process endpoints saturating each other cannot deadlock.
    std::string key = std::string(ip) + ":" + std::to_string(pport);
    std::lock_guard<std::mutex> g(mu);
    if (closed) return -1;
    auto it = peers.find(key);
    int fd = (it != peers.end()) ? it->second
                                 : connect_peer_locked(ip, pport, key);
    if (fd < 0) return -1;
    auto cit = conns.find(fd);
    if (cit == conns.end()) return -1;
    Conn& c = cit->second;
    if (c.wbuf.size() - c.woff + len + 16 > kMaxWbuf) return -1;  // backpressure
    append_frame(c.wbuf, tag, data, len);
    if (!flush_locked(c)) {
      // only the epoll thread close()s connection fds (it may be about
      // to recv() on this fd; closing here could let the fd number be
      // reused mid-recv). shutdown() makes its recv return 0 so it
      // performs the close safely on its own thread.
      ::shutdown(fd, SHUT_RDWR);
      auto pit = peers.find(key);
      if (pit != peers.end() && pit->second == fd) peers.erase(pit);
      return -1;
    }
    return 0;
  }

  Msg* take(uint64_t tag, int64_t timeout_ms) {
    std::unique_lock<std::mutex> g(mu);
    auto ready = [&] {
      auto it = mailbox.find(tag);
      return closed || (it != mailbox.end() && !it->second.empty());
    };
    if (timeout_ms < 0) {
      cv.wait(g, ready);
    } else if (!cv.wait_for(g, std::chrono::milliseconds(timeout_ms), ready)) {
      return nullptr;
    }
    auto it = mailbox.find(tag);
    if (it == mailbox.end() || it->second.empty()) return nullptr;
    Msg* m = new Msg(std::move(it->second.front()));
    it->second.pop_front();
    if (it->second.empty()) mailbox.erase(it);
    return m;
  }
};

}  // namespace

extern "C" {

void* msep_bind(const char* ip, int port, int* out_port) {
  auto* ep = new Endpoint();
  if (!ep->start(ip, port)) {
    delete ep;
    return nullptr;
  }
  if (out_port) *out_port = ep->port;
  return ep;
}

int msep_send(void* h, const char* ip, int port, uint64_t tag,
              const uint8_t* data, uint64_t len) {
  return static_cast<Endpoint*>(h)->do_send(ip, port, tag, data, len);
}

// Blocking receive: returns an opaque Msg* or null on timeout.
void* msep_recv(void* h, uint64_t tag, int64_t timeout_ms) {
  return static_cast<Endpoint*>(h)->take(tag, timeout_ms);
}

uint64_t msep_msg_len(void* m) { return static_cast<Msg*>(m)->data.size(); }
const uint8_t* msep_msg_data(void* m) {
  return static_cast<Msg*>(m)->data.data();
}
const char* msep_msg_src_ip(void* m) {
  return static_cast<Msg*>(m)->src_ip.c_str();
}
int msep_msg_src_port(void* m) { return static_cast<Msg*>(m)->src_port; }
void msep_msg_free(void* m) { delete static_cast<Msg*>(m); }

// Two-phase teardown: shutdown() wakes every blocked msep_recv (they
// observe closed and return null) and joins the epoll thread; free()
// deletes only after the caller has drained its receiver threads —
// deleting with a receiver still inside take() would destroy a mutex in
// use (UB).
void msep_shutdown(void* h) { static_cast<Endpoint*>(h)->close_all(); }

void msep_free(void* h) { delete static_cast<Endpoint*>(h); }

void msep_close(void* h) {  // convenience for single-threaded callers
  msep_shutdown(h);
  msep_free(h);
}

}  // extern "C"
