// Shared-memory fast-path transport — the kernel-bypass-class endpoint.
//
// The reference's std side offers optional high-performance transports
// behind the same tag-matching Endpoint API: UCX RDMA
// (madsim/src/std/net/ucx.rs:23-30) and eRPC/ibverbs
// (madsim/src/std/net/erpc.rs:24-30), selected by cargo feature. No
// RDMA NIC exists in this environment, so this component fills that
// role honestly for the case those transports accelerate most —
// same-host messaging: a POSIX shared-memory MPSC ring per endpoint.
// Data transfer is two memcpys through /dev/shm with no socket
// syscalls; blocking uses a process-shared robust mutex + condvars
// (futexes — kernel entered only on contention/empty), which is the
// same "bypass the network stack" idea as the reference's RDMA paths.
//
// Addressing matches the TCP transports ("ip:port"), so the Python
// Endpoint seam (madsim_tpu/std/) can pick epoll-TCP or shm per peer
// exactly like the reference's cargo features pick ucx/erpc.
//
// C ABI only (ctypes binding; no pybind11 in this environment).

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4d545055;  // "MTPU"
constexpr uint64_t kDataCap = 8u << 20;  // ring data area per endpoint
constexpr uint64_t kMaxFrame = kDataCap / 2;

// Frame: u64 total_len (of what follows) | u64 tag | u32 src_ip_len |
// u32 src_port | src_ip bytes | payload bytes, all written mod-capacity.
struct ShmRing {
  uint32_t magic;
  uint32_t owner_pid;
  uint64_t capacity;
  pthread_mutex_t mu;
  pthread_cond_t nonempty;
  pthread_cond_t nonfull;
  uint64_t head;  // read cursor (monotonic; offset = head % capacity)
  uint64_t tail;  // write cursor
  uint32_t closed;
  uint8_t data[];
};

size_t ring_bytes() { return sizeof(ShmRing) + kDataCap; }

std::string seg_name(const std::string& ip, int port) {
  std::string n = "/mstpu_" + ip + "_" + std::to_string(port);
  for (char& c : n)
    if (c == '.' || c == ':') c = '-';
  return n;
}

// mod-capacity copy helpers (at most two memcpys each)
void ring_write(ShmRing* r, uint64_t pos, const void* src, uint64_t n) {
  uint64_t off = pos % r->capacity;
  uint64_t first = std::min(n, r->capacity - off);
  memcpy(r->data + off, src, first);
  if (n > first) memcpy(r->data, static_cast<const uint8_t*>(src) + first, n - first);
}

void ring_read(ShmRing* r, uint64_t pos, void* dst, uint64_t n) {
  uint64_t off = pos % r->capacity;
  uint64_t first = std::min(n, r->capacity - off);
  memcpy(dst, r->data + off, first);
  if (n > first) memcpy(static_cast<uint8_t*>(dst) + first, r->data, n - first);
}

// Robust process-shared lock: if a peer died holding the mutex, adopt
// and mark it consistent (the ring may hold a torn frame; the owner
// detects that via cursor sanity checks and resets).
int lock_robust(ShmRing* r) {
  int rc = pthread_mutex_lock(&r->mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&r->mu);
    rc = 0;
  }
  return rc;
}

bool init_ring(ShmRing* r) {
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  if (pthread_mutex_init(&r->mu, &ma) != 0) return false;
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  if (pthread_cond_init(&r->nonempty, &ca) != 0) return false;
  if (pthread_cond_init(&r->nonfull, &ca) != 0) return false;
  r->capacity = kDataCap;
  r->head = r->tail = 0;
  r->closed = 0;
  r->owner_pid = static_cast<uint32_t>(getpid());
  std::atomic_thread_fence(std::memory_order_seq_cst);
  r->magic = kMagic;
  return true;
}

struct Msg {
  std::vector<uint8_t> data;
  std::string src_ip;
  int src_port;
};

struct PeerSeg {
  int fd = -1;
  ShmRing* ring = nullptr;

  PeerSeg() = default;
  PeerSeg(PeerSeg&& o) noexcept : fd(o.fd), ring(o.ring) {
    o.fd = -1;
    o.ring = nullptr;
  }
  PeerSeg& operator=(PeerSeg&& o) noexcept {
    if (this != &o) {
      this->~PeerSeg();
      fd = o.fd;
      ring = o.ring;
      o.fd = -1;
      o.ring = nullptr;
    }
    return *this;
  }
  PeerSeg(const PeerSeg&) = delete;
  PeerSeg& operator=(const PeerSeg&) = delete;

  ~PeerSeg() {
    if (ring) munmap(ring, ring_bytes());
    if (fd >= 0) ::close(fd);
    ring = nullptr;
    fd = -1;
  }
};

struct ShmEndpoint {
  std::string ip;
  int port = 0;
  std::string name;
  int fd = -1;
  ShmRing* ring = nullptr;
  std::thread drain;
  std::mutex mu;  // local mailbox lock
  std::condition_variable cv;
  bool closed = false;
  std::map<uint64_t, std::deque<Msg>> mailbox;
  std::map<std::string, PeerSeg> peers;  // "ip:port" -> mapped segment
  std::mutex peers_mu;

  ~ShmEndpoint() { close_all(); }

  bool create(const char* want_ip, int want_port, int* out_port) {
    ip = want_ip;
    std::mt19937_64 rng(static_cast<uint64_t>(getpid()) * 2654435761u ^
                        static_cast<uint64_t>(time(nullptr)));
    for (int attempt = 0; attempt < 64; attempt++) {
      int p = want_port != 0
                  ? want_port
                  : 20000 + static_cast<int>(rng() % 40000);  // ephemeral
      std::string n = seg_name(ip, p);
      int f = shm_open(n.c_str(), O_RDWR | O_CREAT | O_EXCL, 0600);
      if (f < 0) {
        if (errno == EEXIST) {
          // stale segment from a dead process? adopt its name
          int ef = shm_open(n.c_str(), O_RDWR, 0600);
          if (ef >= 0) {
            void* m = mmap(nullptr, ring_bytes(), PROT_READ | PROT_WRITE,
                           MAP_SHARED, ef, 0);
            bool stale = false;
            if (m != MAP_FAILED) {
              auto* r = static_cast<ShmRing*>(m);
              stale = r->magic == kMagic && r->owner_pid != 0 &&
                      kill(static_cast<pid_t>(r->owner_pid), 0) != 0 &&
                      errno == ESRCH;
              munmap(m, ring_bytes());
            }
            ::close(ef);
            if (stale) {
              shm_unlink(n.c_str());
              attempt--;  // retry the same port against the fresh name
              continue;
            }
          }
          if (want_port != 0) return false;  // fixed port taken
          continue;                          // pick another ephemeral
        }
        return false;
      }
      if (ftruncate(f, static_cast<off_t>(ring_bytes())) != 0) {
        ::close(f);
        shm_unlink(n.c_str());
        return false;
      }
      void* m =
          mmap(nullptr, ring_bytes(), PROT_READ | PROT_WRITE, MAP_SHARED, f, 0);
      if (m == MAP_FAILED) {
        ::close(f);
        shm_unlink(n.c_str());
        return false;
      }
      fd = f;
      ring = static_cast<ShmRing*>(m);
      if (!init_ring(ring)) return false;
      port = p;
      name = n;
      if (out_port) *out_port = p;
      drain = std::thread([this] { drain_loop(); });
      return true;
    }
    return false;
  }

  // Move every complete frame from the shared ring into the local
  // tag-matching mailbox. Runs on a dedicated thread so shared-ring
  // occupancy stays near zero and senders almost never block.
  void drain_loop() {
    // no spin phases anywhere: this container runs on a single CPU,
    // where busy-waiting starves the very thread being waited on
    // (measured: a spin phase here turned an 11.7 us RTT into 760 us)
    for (;;) {
      if (lock_robust(ring) != 0) return;
      while (ring->head == ring->tail && !ring->closed) {
        timespec ts;
        clock_gettime(CLOCK_MONOTONIC, &ts);
        ts.tv_nsec += 200 * 1000 * 1000;  // 200 ms tick to notice close
        if (ts.tv_nsec >= 1000000000) {
          ts.tv_sec += 1;
          ts.tv_nsec -= 1000000000;
        }
        int rc = pthread_cond_timedwait(&ring->nonempty, &ring->mu, &ts);
        if (rc == EOWNERDEAD) pthread_mutex_consistent(&ring->mu);
        {
          std::lock_guard<std::mutex> g(mu);
          if (closed) {
            pthread_mutex_unlock(&ring->mu);
            return;
          }
        }
      }
      if (ring->closed) {
        pthread_mutex_unlock(&ring->mu);
        return;
      }
      std::vector<std::pair<uint64_t, Msg>> batch;
      while (ring->head != ring->tail) {
        uint64_t len = 0;
        ring_read(ring, ring->head, &len, 8);
        if (len < 16 || len > kMaxFrame ||
            len + 8 > ring->tail - ring->head) {
          // torn frame (a writer died mid-write): drop everything
          ring->head = ring->tail;
          break;
        }
        std::vector<uint8_t> frame(len);
        ring_read(ring, ring->head + 8, frame.data(), len);
        ring->head += 8 + len;
        uint64_t tag;
        uint32_t ip_len, src_port;
        memcpy(&tag, frame.data(), 8);
        memcpy(&ip_len, frame.data() + 8, 4);
        memcpy(&src_port, frame.data() + 12, 4);
        if (16 + ip_len > len) continue;  // malformed
        Msg m;
        m.src_ip.assign(reinterpret_cast<char*>(frame.data() + 16), ip_len);
        m.src_port = static_cast<int>(src_port);
        m.data.assign(frame.begin() + 16 + ip_len, frame.end());
        batch.emplace_back(tag, std::move(m));
      }
      pthread_cond_broadcast(&ring->nonfull);
      pthread_mutex_unlock(&ring->mu);
      if (!batch.empty()) {
        std::lock_guard<std::mutex> g(mu);
        for (auto& [tag, m] : batch) mailbox[tag].push_back(std::move(m));
        cv.notify_all();
      }
    }
  }

  int do_send(const char* dst_ip, int dst_port, uint64_t tag,
              const uint8_t* data, uint64_t len) {
    if (len + 16 > kMaxFrame) return -1;
    std::string key = std::string(dst_ip) + ":" + std::to_string(dst_port);
    PeerSeg* seg;
    {
      std::lock_guard<std::mutex> g(peers_mu);
      auto it = peers.find(key);
      if (it == peers.end()) {
        PeerSeg s;
        std::string n = seg_name(dst_ip, dst_port);
        s.fd = shm_open(n.c_str(), O_RDWR, 0600);
        if (s.fd < 0) return -1;
        void* m = mmap(nullptr, ring_bytes(), PROT_READ | PROT_WRITE,
                       MAP_SHARED, s.fd, 0);
        if (m == MAP_FAILED) return -1;
        s.ring = static_cast<ShmRing*>(m);
        if (s.ring->magic != kMagic) return -1;
        it = peers.emplace(key, std::move(s)).first;
        // moved-from PeerSeg must not close the now-owned fd/map
      }
      seg = &it->second;
    }
    ShmRing* r = seg->ring;
    // frame body: tag | ip_len | src_port | ip | payload
    uint32_t ip_len = static_cast<uint32_t>(ip.size());
    uint64_t body = 16 + ip_len + len;
    if (lock_robust(r) != 0) return -1;
    while (r->capacity - (r->tail - r->head) < 8 + body && !r->closed) {
      timespec ts;
      clock_gettime(CLOCK_MONOTONIC, &ts);
      ts.tv_sec += 5;  // bounded wait: a dead receiver can't wedge us
      int rc = pthread_cond_timedwait(&r->nonfull, &r->mu, &ts);
      if (rc == EOWNERDEAD) pthread_mutex_consistent(&r->mu);
      if (rc == ETIMEDOUT &&
          r->capacity - (r->tail - r->head) < 8 + body) {
        pthread_mutex_unlock(&r->mu);
        return -1;
      }
    }
    if (r->closed) {
      pthread_mutex_unlock(&r->mu);
      return -1;
    }
    uint64_t pos = r->tail;
    ring_write(r, pos, &body, 8);
    ring_write(r, pos + 8, &tag, 8);
    uint32_t src_port_u = static_cast<uint32_t>(port);
    ring_write(r, pos + 16, &ip_len, 4);
    ring_write(r, pos + 20, &src_port_u, 4);
    ring_write(r, pos + 24, ip.data(), ip_len);
    if (len) ring_write(r, pos + 24 + ip_len, data, len);
    std::atomic_thread_fence(std::memory_order_release);
    r->tail = pos + 8 + body;
    pthread_cond_signal(&r->nonempty);
    pthread_mutex_unlock(&r->mu);
    return 0;
  }

  Msg* take(uint64_t tag, int64_t timeout_ms) {
    std::unique_lock<std::mutex> g(mu);
    auto ready = [&] {
      auto it = mailbox.find(tag);
      return closed || (it != mailbox.end() && !it->second.empty());
    };
    if (timeout_ms < 0) {
      cv.wait(g, ready);
    } else if (!cv.wait_for(g, std::chrono::milliseconds(timeout_ms), ready)) {
      return nullptr;
    }
    auto it = mailbox.find(tag);
    if (it == mailbox.end() || it->second.empty()) return nullptr;
    Msg* m = new Msg(std::move(it->second.front()));
    it->second.pop_front();
    if (it->second.empty()) mailbox.erase(it);
    return m;
  }

  void close_all() {
    {
      std::lock_guard<std::mutex> g(mu);
      if (closed) return;
      closed = true;
      cv.notify_all();
    }
    if (ring) {
      if (lock_robust(ring) == 0) {
        ring->closed = 1;
        pthread_cond_broadcast(&ring->nonempty);
        pthread_cond_broadcast(&ring->nonfull);
        pthread_mutex_unlock(&ring->mu);
      }
    }
    if (drain.joinable()) drain.join();
    {
      std::lock_guard<std::mutex> g(peers_mu);
      peers.clear();
    }
    if (ring) munmap(ring, ring_bytes());
    if (fd >= 0) ::close(fd);
    ring = nullptr;
    fd = -1;
    if (!name.empty()) shm_unlink(name.c_str());
  }
};

}  // namespace

extern "C" {

void* shmep_bind(const char* ip, int port, int* out_port) {
  auto* ep = new ShmEndpoint();
  if (!ep->create(ip, port, out_port)) {
    delete ep;
    return nullptr;
  }
  return ep;
}

int shmep_send(void* h, const char* ip, int port, uint64_t tag,
               const uint8_t* data, uint64_t len) {
  return static_cast<ShmEndpoint*>(h)->do_send(ip, port, tag, data, len);
}

void* shmep_recv(void* h, uint64_t tag, int64_t timeout_ms) {
  return static_cast<ShmEndpoint*>(h)->take(tag, timeout_ms);
}

uint64_t shmep_msg_len(void* m) { return static_cast<Msg*>(m)->data.size(); }

const uint8_t* shmep_msg_data(void* m) {
  return static_cast<Msg*>(m)->data.data();
}

const char* shmep_msg_src_ip(void* m) {
  return static_cast<Msg*>(m)->src_ip.c_str();
}

int shmep_msg_src_port(void* m) { return static_cast<Msg*>(m)->src_port; }

void shmep_msg_free(void* m) { delete static_cast<Msg*>(m); }

void shmep_shutdown(void* h) { static_cast<ShmEndpoint*>(h)->close_all(); }

void shmep_free(void* h) { delete static_cast<ShmEndpoint*>(h); }

}  // extern "C"
