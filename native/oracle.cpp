// Single-seed C++ oracle for the batched JAX engine.
//
// The reference's determinism checker replays a run and compares the RNG
// op stream (reference madsim/src/sim/runtime/mod.rs:165-190,
// rand.rs:64-110). The batched engine's analog is stronger: this file is
// an *independent reimplementation* of the engine's integer semantics
// (engine/core.py) and its counter-based RNG (engine/rng.py), plus the
// benchmark workloads (models/*.py), in plain C++ — no JAX, no arrays.
// For any (workload, seed, config) the oracle's rolling trace hash must
// equal the engine's bit-for-bit; tests/test_oracle.py enforces it.
// A divergence means one side misimplements the spec.
//
// Built as a shared library (native/Makefile) and loaded via ctypes
// (engine/oracle.py) — the environment has no pybind11, and a C ABI is
// all this needs.
//
// Everything here is integer arithmetic: uint32 threefry, int64
// nanosecond clocks, uint64 trace hashes. Keep textually close to the
// Python spec; cite the mirrored definition in comments.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ---- threefry2x32 (engine/rng.py threefry2x32) --------------------------
constexpr uint32_t kParity = 0x1BD11BDA;
constexpr int kRot[8] = {13, 15, 26, 6, 17, 29, 16, 24};

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

void threefry2x32(uint32_t k0, uint32_t k1, uint32_t x0, uint32_t x1,
                  uint32_t* o0, uint32_t* o1) {
  uint32_t ks[3] = {k0, k1, static_cast<uint32_t>(k0 ^ k1 ^ kParity)};
  x0 += ks[0];
  x1 += ks[1];
  for (int chunk = 0; chunk < 5; chunk++) {
    const int* rots = (chunk % 2 == 0) ? kRot : kRot + 4;
    for (int j = 0; j < 4; j++) {
      x0 += x1;
      x1 = rotl32(x1, rots[j]);
      x1 ^= x0;
    }
    x0 += ks[(chunk + 1) % 3];
    x1 += ks[(chunk + 2) % 3] + static_cast<uint32_t>(chunk + 1);
  }
  *o0 = x0;
  *o1 = x1;
}

// ---- draw discipline (engine/rng.py Draw) -------------------------------
constexpr uint32_t kPurposePollCost = 0;
constexpr uint32_t kPurposeClogJitter = 1;
constexpr uint32_t kPurposeLatency = 8;
constexpr uint32_t kPurposeLoss = 64;
constexpr uint32_t kPurposeUser = 128;

struct Draw {
  uint32_t k0, k1, step;
  uint32_t bits(uint32_t purpose) const {
    uint32_t a, b;
    threefry2x32(k0, k1, step, purpose, &a, &b);
    return a;
  }
  // both lanes of one block (engine Draw.bits2): the per-emit latency
  // (lane 0) and loss (lane 1) draws share the PURPOSE_LATENCY+slot
  // counter
  void bits2(uint32_t purpose, uint32_t* a, uint32_t* b) const {
    threefry2x32(k0, k1, step, purpose, a, b);
  }
  // uniform int64 in [lo, hi): modulo reduction, same bias as the spec
  int64_t uniform_int(int64_t lo, int64_t hi, uint32_t purpose) const {
    uint32_t span = static_cast<uint32_t>(hi - lo);
    if (span == 0) span = 1;
    return lo + static_cast<int64_t>(bits(purpose) % span);
  }
  // two uniform int64s from ONE block (engine Draw.uniform_int2):
  // lane 0 -> [lo_a, hi_a), lane 1 -> [lo_b, hi_b)
  void uniform_int2(int64_t lo_a, int64_t hi_a, int64_t lo_b, int64_t hi_b,
                    uint32_t purpose, int64_t* out_a, int64_t* out_b) const {
    uint32_t a, b;
    bits2(purpose, &a, &b);
    uint32_t span_a = static_cast<uint32_t>(hi_a - lo_a);
    if (span_a == 0) span_a = 1;
    uint32_t span_b = static_cast<uint32_t>(hi_b - lo_b);
    if (span_b == 0) span_b = 1;
    *out_a = lo_a + static_cast<int64_t>(a % span_a);
    *out_b = lo_b + static_cast<int64_t>(b % span_b);
  }
  uint32_t user(uint32_t purpose) const { return bits(kPurposeUser + purpose); }
  int64_t user_int(int64_t lo, int64_t hi, uint32_t purpose) const {
    return uniform_int(lo, hi, kPurposeUser + purpose);
  }
};

// ---- event kinds (engine/core.py) ---------------------------------------
constexpr int32_t KIND_KILL = 0;
constexpr int32_t KIND_RESTART = 1;
constexpr int32_t KIND_CLOG = 2;
constexpr int32_t KIND_UNCLOG = 3;
constexpr int32_t KIND_CLOG_NODE = 4;
constexpr int32_t KIND_UNCLOG_NODE = 5;
constexpr int32_t KIND_HALT = 6;
constexpr int32_t KIND_NOP = 7;
constexpr int32_t KIND_PAUSE = 8;
constexpr int32_t KIND_RESUME = 9;
constexpr int32_t FIRST_USER_KIND = 10;

constexpr int64_t kInf = int64_t{1} << 62;
constexpr uint64_t kTracePrime = 0x100000001B3ull;
constexpr uint64_t kTraceMix = 0x9E3779B97F4A7C15ull;

struct Config {  // EngineConfig
  int64_t pool_size;
  int64_t lat_min_ns, lat_max_ns;
  uint64_t loss_u32;  // in [0, 2^32]; 2^32 = always drop (loss_p=1.0)
  int64_t proc_min_ns, proc_max_ns;
  int64_t clog_backoff_min_ns, clog_backoff_max_ns;
  int64_t time_limit_ns;  // 0 = unlimited
};

// payload arena width cap (Workload.payload_words; engine events carry
// W int32 words — engine/core.py ev_pay)
constexpr int32_t kMaxPay = 4;

struct Event {
  int64_t time;
  bool valid;
  int32_t kind, node, src, epoch, retry;
  int32_t args[4];
  int32_t pay[kMaxPay] = {0, 0, 0, 0};
};

// one emit row (Emits)
struct Emit {
  bool valid = false;
  bool send = false;
  int32_t kind = 0, dst = 0;
  int64_t delay = 0;
  int32_t args[4] = {0, 0, 0, 0};
  int32_t pay[kMaxPay] = {0, 0, 0, 0};
};

// ---- optional per-dispatch event log (engine/replay.py) -----------------
// Caller-owned buffers; when set, oracle_run records every DISPATCHED
// event — exactly the tuples trace_fold consumes, so a timeline built
// from the log re-folds to the certified trace hash. The count keeps
// growing past the capacity so the caller can detect truncation.
int64_t* g_log_time = nullptr;
int32_t* g_log_kind = nullptr;
int32_t* g_log_node = nullptr;
int32_t* g_log_src = nullptr;
int32_t* g_log_args = nullptr;  // (cap, 4) row-major
int32_t* g_log_pay = nullptr;   // (cap, kMaxPay) row-major
int64_t g_log_cap = 0;
int64_t g_log_count = 0;

struct Effects {
  std::vector<Emit> emits;
  int32_t kill = -1, restart = -1;
  int32_t clog_a = -1, clog_b = -1, clog_set = -1;
  int32_t pause_node = -1, pause_set = -1;
  bool halt = false;
};

struct Ctx {
  int64_t now;
  int32_t node;
  const int32_t* state;  // (U,)
  const int32_t* args;   // (4,)
  int32_t src;
  Draw draw;
  const int32_t* pay = nullptr;  // (W,) the event's payload words
};

// Workload interface: mirrors engine Workload. new_state is written by
// the handler; the engine applies it only when the event dispatches.
struct Workload {
  int32_t n_nodes, state_width, n_handlers, max_emits;
  // handler(h, ctx, new_state_out, effects_out)
  void (*handler)(int32_t h, const Ctx&, int32_t*, Effects*);
  int32_t payload_words = 0;  // engine Workload.payload_words
};

// ---- the step loop (engine/core.py make_step) ---------------------------
struct Sim {
  Config cfg;
  Workload wl;
  uint64_t seed;
  int64_t now = 0;
  uint32_t step = 0;
  bool halted = false;
  int64_t halt_time = 0;
  uint64_t trace = 0;
  int32_t overflow = 0;
  int64_t msg_count = 0;
  std::vector<Event> ev;
  std::vector<uint8_t> alive;
  std::vector<uint8_t> paused;
  std::vector<int32_t> epoch;
  std::vector<int32_t> node_state;  // (N,U)
  std::vector<int32_t> init_state;  // (N,U) Workload.initial_state() rows
  std::vector<uint8_t> durable;     // (U) restart-surviving columns
  std::vector<uint8_t> clog;        // (N,N)

  void init() {
    ev.assign(cfg.pool_size, Event{0, false, KIND_NOP, 0, -1, 0, 0, {0, 0, 0, 0}});
    for (int32_t n = 0; n < wl.n_nodes; n++) {
      ev[n] = Event{0, true, FIRST_USER_KIND, n, -1, 0, 0, {0, 0, 0, 0}};
    }
    alive.assign(wl.n_nodes, 1);
    paused.assign(wl.n_nodes, 0);
    epoch.assign(wl.n_nodes, 0);
    if (init_state.empty())
      init_state.assign(static_cast<size_t>(wl.n_nodes) * wl.state_width, 0);
    node_state = init_state;
    clog.assign(static_cast<size_t>(wl.n_nodes) * wl.n_nodes, 0);
  }

  void trace_fold(int64_t t, int32_t kind, int32_t node, const int32_t* args,
                  const int32_t* pay) {
    uint64_t h = static_cast<uint64_t>(t) * kTraceMix;
    h ^= static_cast<uint64_t>(static_cast<uint32_t>(kind)) << 32;
    h ^= static_cast<uint64_t>(static_cast<uint32_t>(node)) << 40;
    uint64_t a0 = static_cast<uint32_t>(args[0]);
    uint64_t a1 = static_cast<uint32_t>(args[1]);
    uint64_t a2 = static_cast<uint32_t>(args[2]);
    uint64_t a3 = static_cast<uint32_t>(args[3]);
    h ^= a0 ^ (a1 << 8) ^ (a2 << 16) ^ (a3 << 24);
    if (wl.payload_words > 0) {
      // payload words participate in the trace (engine _trace_fold):
      // h ^= sum_w pay[w] * (MIX ^ w), wrapping uint64
      uint64_t acc = 0;
      for (int32_t wi = 0; wi < wl.payload_words; wi++) {
        acc += static_cast<uint64_t>(static_cast<uint32_t>(pay[wi])) *
               (kTraceMix ^ static_cast<uint64_t>(wi));
      }
      h ^= acc;
    }
    trace = trace * kTracePrime + h;
  }

  void do_step() {
    const int64_t time_limit = cfg.time_limit_ns ? cfg.time_limit_ns : kInf;
    // pop earliest (first-min, matching jnp.argmin)
    int64_t best = kInf;
    int64_t i = 0;
    for (int64_t j = 0; j < cfg.pool_size; j++) {
      int64_t t = ev[j].valid ? ev[j].time : kInf;
      if (t < best) {
        best = t;
        i = j;
      }
    }
    bool has_event = ev[i].valid;
    int64_t ev_t = ev[i].time > now ? ev[i].time : now;
    bool over_limit = ev_t > time_limit;
    bool active = has_event && !halted && !over_limit;

    int32_t kind = ev[i].kind, dst = ev[i].node, src = ev[i].src;
    int32_t args[4];
    std::memcpy(args, ev[i].args, sizeof(args));
    int32_t pay[kMaxPay];  // copied now: the slot may be reused below
    std::memcpy(pay, ev[i].pay, sizeof(pay));
    bool is_engine = kind < FIRST_USER_KIND;
    bool is_msg = src >= 0;
    bool live = alive[dst] && epoch[dst] == ev[i].epoch;
    bool clogged =
        is_msg && clog[static_cast<size_t>(src < 0 ? 0 : src) * wl.n_nodes + dst];
    // paused node: user events are stashed and retried (engine `held`)
    bool held = !is_engine && paused[dst];
    bool blocked = clogged || held;
    bool dispatch = active && !blocked && (is_engine || live);

    if (active) now = ev_t;
    Draw draw{static_cast<uint32_t>(seed & 0xFFFFFFFFull),
              static_cast<uint32_t>(seed >> 32), step};
    // poll cost paired with clog jitter in ONE block (engine
    // Draw.uniform_int2 at PURPOSE_POLL_COST: lane 0 = cost, lane 1 =
    // jitter)
    int64_t cost, clog_jit;
    draw.uniform_int2(cfg.proc_min_ns, cfg.proc_max_ns, 0, 1000,
                      kPurposePollCost, &cost, &clog_jit);
    int64_t now_after = dispatch ? now + cost : now;

    // consume / clog-reschedule (engine: resched branch)
    int32_t retries = ev[i].retry;
    int64_t shift = retries < 34 ? retries : 34;
    int64_t backoff = cfg.clog_backoff_min_ns << shift;
    if (backoff > cfg.clog_backoff_max_ns) backoff = cfg.clog_backoff_max_ns;
    backoff += clog_jit;
    bool resched = active && blocked && (is_engine || live);
    ev[i].valid = resched;
    if (resched) {
      ev[i].time = now + backoff;
      ev[i].retry = retries + 1;
    }

    // dispatch through the branch table
    Effects eff;
    std::vector<int32_t> new_state(wl.state_width);
    const int32_t* row = &node_state[static_cast<size_t>(dst) * wl.state_width];
    std::memcpy(new_state.data(), row, wl.state_width * sizeof(int32_t));
    Ctx ctx{now, dst, row, args, src, draw, pay};
    int32_t safe_kind = kind < 0 ? 0 : kind;
    int32_t max_kind = FIRST_USER_KIND + wl.n_handlers - 1;
    if (safe_kind > max_kind) safe_kind = max_kind;
    if (safe_kind >= FIRST_USER_KIND) {
      wl.handler(safe_kind - FIRST_USER_KIND, ctx, new_state.data(), &eff);
    } else {
      switch (safe_kind) {
        case KIND_KILL: eff.kill = args[0]; break;
        case KIND_RESTART: {
          eff.restart = args[0];
          Emit e;  // reborn node re-runs on_init (engine _b_restart)
          e.valid = true;
          e.kind = FIRST_USER_KIND;
          e.dst = args[0];
          eff.emits.push_back(e);
          break;
        }
        case KIND_CLOG: eff.clog_a = args[0]; eff.clog_b = args[1]; eff.clog_set = 1; break;
        case KIND_UNCLOG: eff.clog_a = args[0]; eff.clog_b = args[1]; eff.clog_set = 0; break;
        case KIND_CLOG_NODE: eff.clog_a = args[0]; eff.clog_b = -1; eff.clog_set = 1; break;
        case KIND_UNCLOG_NODE: eff.clog_a = args[0]; eff.clog_b = -1; eff.clog_set = 0; break;
        case KIND_HALT: eff.halt = true; break;
        case KIND_PAUSE: eff.pause_node = args[0]; eff.pause_set = 1; break;
        case KIND_RESUME: eff.pause_node = args[0]; eff.pause_set = 0; break;
        default: break;  // NOP
      }
    }

    // apply node state
    if (dispatch) {
      std::memcpy(&node_state[static_cast<size_t>(dst) * wl.state_width],
                  new_state.data(), wl.state_width * sizeof(int32_t));
    }

    // chaos effects
    int32_t kill_id = dispatch ? eff.kill : -1;
    int32_t restart_id = dispatch ? eff.restart : -1;
    if (kill_id >= 0 && kill_id < wl.n_nodes) {
      alive[kill_id] = 0;
      epoch[kill_id] += 1;
    }
    if (restart_id >= 0 && restart_id < wl.n_nodes) {
      alive[restart_id] = 1;
      epoch[restart_id] += 1;
      // the reborn node restarts from the workload's initial rows, not
      // zeros (engine: node_state reset to init_rows on restart) —
      // EXCEPT durable columns, which survive the crash (the FsSim
      // power-fail analog, Workload.durable_cols)
      for (int32_t u = 0; u < wl.state_width; u++) {
        if (u < static_cast<int32_t>(durable.size()) && durable[u]) continue;
        node_state[static_cast<size_t>(restart_id) * wl.state_width + u] =
            init_state[static_cast<size_t>(restart_id) * wl.state_width + u];
      }
    }
    int32_t pause_id = dispatch ? eff.pause_node : -1;
    if (pause_id >= 0 && pause_id < wl.n_nodes)
      paused[pause_id] = eff.pause_set == 1;
    // kill/restart clears paused (fresh incarnation runs)
    if (kill_id >= 0 && kill_id < wl.n_nodes) paused[kill_id] = 0;
    if (restart_id >= 0 && restart_id < wl.n_nodes) paused[restart_id] = 0;
    int32_t clog_set = dispatch ? eff.clog_set : -1;
    if (clog_set >= 0) {
      for (int32_t a = 0; a < wl.n_nodes; a++) {
        for (int32_t b = 0; b < wl.n_nodes; b++) {
          bool pair_sel = (a == eff.clog_a && b == eff.clog_b) ||
                          (a == eff.clog_b && b == eff.clog_a);
          bool node_sel = eff.clog_b < 0 && (a == eff.clog_a || b == eff.clog_a);
          if (pair_sel || node_sel)
            clog[static_cast<size_t>(a) * wl.n_nodes + b] = clog_set == 1;
        }
      }
    }
    bool was_halted = halted;
    halted = halted || (dispatch && eff.halt) || (has_event && over_limit);
    if (halted && !was_halted)
      halt_time = now < time_limit ? now : time_limit;

    // translate emits (static slot index -> latency/loss purposes)
    int32_t n_sends = 0;
    std::vector<Emit>& em = eff.emits;
    int free_cursor = 0;  // index into the free-slot sequence
    // free slots in pool order (flatnonzero)
    std::vector<int64_t> free;
    for (int64_t j = 0; j < cfg.pool_size && static_cast<int32_t>(free.size()) < wl.max_emits; j++)
      if (!ev[j].valid) free.push_back(j);
    for (size_t slot = 0; slot < em.size(); slot++) {
      const Emit& e = em[slot];
      uint32_t lat_bits, loss_bits;
      draw.bits2(kPurposeLatency + static_cast<uint32_t>(slot), &lat_bits,
                 &loss_bits);
      uint32_t span = static_cast<uint32_t>(cfg.lat_max_ns - cfg.lat_min_ns);
      if (span == 0) span = 1;
      int64_t latency = cfg.lat_min_ns + static_cast<int64_t>(lat_bits % span);
      bool lost = e.send && static_cast<uint64_t>(loss_bits) < cfg.loss_u32;
      bool e_valid = dispatch && e.valid && !lost;
      if (e.send && e_valid && !(e.dst >= 0 && e.dst < wl.n_nodes && alive[e.dst]))
        e_valid = false;
      if (dispatch && e.valid && e.send) n_sends++;
      if (!e_valid) continue;
      if (free_cursor >= static_cast<int>(free.size())) {
        overflow += 1;  // pool full: dropped (engine `dropped`)
        continue;
      }
      int64_t j = free[free_cursor++];
      Event& ne = ev[j];
      ne.valid = true;
      ne.time = now_after + (e.send ? latency : e.delay);
      ne.kind = e.kind;
      ne.node = e.dst;
      ne.src = e.send ? dst : -1;
      ne.epoch = e.kind < FIRST_USER_KIND ? 0
                 : (e.dst >= 0 && e.dst < wl.n_nodes ? epoch[e.dst] : 0);
      ne.retry = 0;
      std::memcpy(ne.args, e.args, sizeof(ne.args));
      std::memcpy(ne.pay, e.pay, sizeof(ne.pay));
    }
    msg_count += n_sends;
    if (dispatch) {
      trace_fold(now, kind, dst, args, pay);
      if (g_log_cap > 0) {
        if (g_log_count < g_log_cap) {
          g_log_time[g_log_count] = now;
          g_log_kind[g_log_count] = kind;
          g_log_node[g_log_count] = dst;
          g_log_src[g_log_count] = src;
          std::memcpy(g_log_args + g_log_count * 4, args, sizeof(args));
          std::memcpy(g_log_pay + g_log_count * kMaxPay, pay, sizeof(pay));
        }
        g_log_count++;
      }
    }
    now = now_after;
    step += 1;
  }
};

// ---- workloads (mirrors of models/*.py) ---------------------------------

inline Emit mk_send(int32_t dst, int32_t kind, int32_t a0 = 0, int32_t a1 = 0,
                    bool when = true) {
  Emit e;
  e.valid = when;
  e.send = true;
  e.kind = kind;
  e.dst = dst;
  e.args[0] = a0;
  e.args[1] = a1;
  return e;
}

inline Emit mk_after(int64_t delay, int32_t kind, int32_t dst, int32_t a0 = 0,
                     bool when = true) {
  Emit e;
  e.valid = when;
  e.send = false;
  e.kind = kind;
  e.dst = dst;
  e.delay = delay;
  e.args[0] = a0;
  return e;
}

// pingpong (models/pingpong.py): rounds=compiled-in via globals below
struct PingPongParams {
  int32_t rounds, n_clients;
};
PingPongParams g_pp{10, 2};

void pingpong_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t SERVER = 0;
  const int32_t K_PING = FIRST_USER_KIND + 1, K_PONG = FIRST_USER_KIND + 2,
                K_DONE = FIRST_USER_KIND + 3;
  switch (h) {
    case 0: {  // on_init
      bool is_client = ctx.node != SERVER;
      eff->emits.push_back(mk_send(SERVER, K_PING, 0, ctx.node, is_client));
      break;
    }
    case 1: {  // on_ping at server
      ns[1] = ctx.state[1] + 1;
      eff->emits.push_back(mk_send(ctx.args[1], K_PONG, ctx.args[0]));
      break;
    }
    case 2: {  // on_pong at client
      int32_t seq = ctx.args[0] + 1;
      ns[0] = seq;
      bool done = seq >= g_pp.rounds;
      eff->emits.push_back(mk_send(SERVER, K_PING, seq, ctx.node, !done));
      eff->emits.push_back(mk_send(SERVER, K_DONE, 0, 0, done));
      break;
    }
    case 3: {  // on_done at server
      int32_t fin = ctx.state[0] + 1;
      ns[0] = fin;
      eff->emits.push_back(
          mk_after(0, KIND_HALT, 0, 0, fin >= g_pp.n_clients));
      break;
    }
  }
}

// microbench (models/microbench.py)
struct MicrobenchParams {
  int32_t rounds;
  int64_t delay_min, delay_max;
};
MicrobenchParams g_mb{1000, 1000, 1000000};

void microbench_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t K_TICK = FIRST_USER_KIND + 1;
  switch (h) {
    case 0: {
      int64_t d = ctx.draw.user_int(g_mb.delay_min, g_mb.delay_max, 0);
      eff->emits.push_back(mk_after(d, K_TICK, ctx.node));
      break;
    }
    case 1: {
      int32_t count = ctx.state[0] + 1;
      int32_t bits = static_cast<int32_t>(ctx.draw.user(1));
      ns[0] = count;
      ns[1] = ctx.state[1] ^ bits;
      bool done = count >= g_mb.rounds;
      int64_t d = ctx.draw.user_int(g_mb.delay_min, g_mb.delay_max, 0);
      eff->emits.push_back(mk_after(d, K_TICK, ctx.node, 0, !done));
      eff->emits.push_back(mk_after(0, KIND_HALT, 0, 0, done));
      break;
    }
  }
}

// raft election (models/raft.py)
struct RaftParams {
  int32_t n_nodes;
  int64_t timeout_min, timeout_max;
};
RaftParams g_raft{5, 150000000, 300000000};

void raft_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t ROLE = 0, TERM = 1, VOTED = 2, VOTES = 3, TSEQ = 4;
  const int32_t FOLLOWER = 0, CANDIDATE = 1, LEADER = 2;
  const int32_t K_TIMEOUT = FIRST_USER_KIND + 1, K_REQVOTE = FIRST_USER_KIND + 2,
                K_GRANT = FIRST_USER_KIND + 3, K_HEARTBEAT = FIRST_USER_KIND + 4;
  const int32_t majority = g_raft.n_nodes / 2 + 1;
  const int32_t N = g_raft.n_nodes;
  auto arm = [&](int32_t new_seq, bool when) {
    int64_t d = ctx.draw.user_int(g_raft.timeout_min, g_raft.timeout_max, 0);
    eff->emits.push_back(mk_after(d, K_TIMEOUT, ctx.node, new_seq, when));
  };
  switch (h) {
    case 0: {  // on_init
      arm(1, true);
      ns[TSEQ] = 1;
      break;
    }
    case 1: {  // on_timeout
      const int32_t* st = ctx.state;
      bool fire = ctx.args[0] == st[TSEQ] && st[ROLE] != LEADER;
      int32_t term = st[TERM] + 1;
      if (fire) {
        ns[ROLE] = CANDIDATE;
        ns[TERM] = term;
        ns[VOTED] = term;
        ns[VOTES] = 1;
        ns[TSEQ] = st[TSEQ] + 1;
      }
      for (int32_t p = 0; p < N; p++)
        eff->emits.push_back(
            mk_send(p, K_REQVOTE, term, ctx.node, fire && p != ctx.node));
      arm(st[TSEQ] + 1, fire);
      break;
    }
    case 2: {  // on_reqvote
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0], cand = ctx.args[1];
      int32_t st1[8];
      std::memcpy(st1, st, sizeof(int32_t) * 5);
      bool newer = term > st[TERM];
      if (newer) {
        st1[TERM] = term;
        st1[ROLE] = FOLLOWER;
        st1[VOTES] = 0;
      }
      bool grant = term == st1[TERM] && st1[VOTED] < term;
      std::memcpy(ns, st1, sizeof(int32_t) * 5);
      if (grant) {
        ns[VOTED] = term;
        ns[TSEQ] = st1[TSEQ] + 1;
      }
      eff->emits.push_back(mk_send(cand, K_GRANT, term, 0, grant));
      {
        int64_t d = ctx.draw.user_int(g_raft.timeout_min, g_raft.timeout_max, 0);
        eff->emits.push_back(
            mk_after(d, K_TIMEOUT, ctx.node, st1[TSEQ] + 1, grant));
      }
      break;
    }
    case 3: {  // on_grant
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0];
      bool counts = st[ROLE] == CANDIDATE && term == st[TERM];
      int32_t votes = counts ? st[VOTES] + 1 : st[VOTES];
      bool wins = counts && votes >= majority;
      ns[VOTES] = votes;
      if (wins) ns[ROLE] = LEADER;
      for (int32_t p = 0; p < N; p++)
        eff->emits.push_back(
            mk_send(p, K_HEARTBEAT, term, 0, wins && p != ctx.node));
      eff->emits.push_back(mk_after(0, KIND_HALT, 0, 0, wins));
      break;
    }
    case 4: {  // on_heartbeat
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0];
      bool accept = term >= st[TERM];
      if (accept) {
        ns[TERM] = term;
        ns[ROLE] = FOLLOWER;
        ns[TSEQ] = st[TSEQ] + 1;
      }
      arm(st[TSEQ] + 1, accept);
      break;
    }
  }
}

// broadcast (models/broadcast.py): origin 0 broadcasts `rounds` sequenced
// messages to n_nodes-1 peers with acks + retransmit, under a random link
// partition the origin schedules at init.
struct BroadcastParams {
  int32_t rounds, n_nodes;
  int64_t retx_ns;
  int32_t partition;
};
BroadcastParams g_bc{5, 5, 50000000, 1};

void broadcast_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t ORIGIN = 0;
  const int32_t K_MSG = FIRST_USER_KIND + 1, K_ACK = FIRST_USER_KIND + 2,
                K_RETX = FIRST_USER_KIND + 3;
  const int32_t P_CHAOS_LINK = 1, P_CHAOS_AT = 2, P_CHAOS_LEN = 3;
  const int32_t N = g_bc.n_nodes;
  const int32_t n_peers = N - 1;
  const int32_t full_mask = (1 << n_peers) - 1;
  // slot order must match the Python EmitBuilder exactly: invalid emits
  // still consume a slot index (latency/loss purposes are per-slot)
  auto bcast = [&](int32_t seq, bool when) {
    for (int32_t p = 1; p < N; p++)
      eff->emits.push_back(mk_send(p, K_MSG, seq, 0, when));
  };
  switch (h) {
    case 0: {  // on_init
      bool is_origin = ctx.node == ORIGIN;
      bcast(1, is_origin);
      eff->emits.push_back(mk_after(g_bc.retx_ns, K_RETX, ORIGIN, 1, is_origin));
      if (g_bc.partition) {
        int64_t a = ctx.draw.user_int(1, N, P_CHAOS_LINK);
        int64_t b_raw = ctx.draw.user_int(1, N - 1, P_CHAOS_LINK + 16);
        int64_t b = b_raw >= a ? b_raw + 1 : b_raw;
        int64_t at = ctx.draw.user_int(0, 100000000, P_CHAOS_AT);
        int64_t length = ctx.draw.user_int(50000000, 400000000, P_CHAOS_LEN);
        Emit e1 = mk_after(at, KIND_CLOG, 0, static_cast<int32_t>(a), is_origin);
        e1.args[1] = static_cast<int32_t>(b);
        eff->emits.push_back(e1);
        Emit e2 = mk_after(at + length, KIND_UNCLOG, 0,
                           static_cast<int32_t>(a), is_origin);
        e2.args[1] = static_cast<int32_t>(b);
        eff->emits.push_back(e2);
      }
      if (is_origin) ns[0] = 1;
      break;
    }
    case 1: {  // on_msg at receiver
      int32_t seq = ctx.args[0];
      ns[0] = ctx.state[0] > seq ? ctx.state[0] : seq;
      ns[1] = ctx.state[1] + 1;
      // always ack (idempotent) so lost acks are re-covered by retx
      eff->emits.push_back(mk_send(ORIGIN, K_ACK, seq, ctx.node));
      break;
    }
    case 2: {  // on_ack at origin
      int32_t seq = ctx.args[0], peer = ctx.args[1];
      int32_t cur = ctx.state[0];
      int32_t mask = ctx.state[1];
      int32_t bit = int32_t{1} << (peer - 1);
      if (seq == cur) mask |= bit;
      bool complete = mask == full_mask;
      bool last_round = cur >= g_bc.rounds;
      int32_t nxt = (complete && !last_round) ? cur + 1 : cur;
      int32_t new_mask = (complete && !last_round) ? 0 : mask;
      bcast(nxt, complete && !last_round);
      eff->emits.push_back(
          mk_after(g_bc.retx_ns, K_RETX, ORIGIN, nxt, complete && !last_round));
      eff->emits.push_back(
          mk_after(0, KIND_HALT, 0, 0, complete && last_round));
      ns[0] = nxt;
      ns[1] = new_mask;
      break;
    }
    case 3: {  // on_retx at origin
      int32_t seq = ctx.args[0];
      int32_t cur = ctx.state[0];
      int32_t mask = ctx.state[1];
      bool pending = seq == cur && mask != full_mask;
      for (int32_t idx = 0; idx < n_peers; idx++) {
        bool unacked = ((mask >> idx) & 1) == 0;
        eff->emits.push_back(
            mk_send(idx + 1, K_MSG, cur, 0, pending && unacked));
      }
      eff->emits.push_back(mk_after(g_bc.retx_ns, K_RETX, ORIGIN, cur, pending));
      break;
    }
  }
}

// kvchaos (models/kvchaos.py): primary-backup KV store under a scheduled
// replica kill/restart; payload mode carries two client-drawn value words
// through WRITE/REPL messages (state_width 6, payload_words 2).
struct KvParams {
  int32_t writes, n_replicas;
  int64_t retx_ns, client_retx_ns;
  int32_t chaos, payload;
};
KvParams g_kv{20, 4, 40000000, 100000000, 1, 0};

void kvchaos_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t PRIMARY = 0;
  const int32_t K_WRITE = FIRST_USER_KIND + 1, K_REPL = FIRST_USER_KIND + 2,
                K_ACK = FIRST_USER_KIND + 3, K_COMMIT = FIRST_USER_KIND + 4,
                K_RETX = FIRST_USER_KIND + 5, K_CRETX = FIRST_USER_KIND + 6,
                K_FIN = FIRST_USER_KIND + 7, K_JOIN = FIRST_USER_KIND + 8,
                K_JRETX = FIRST_USER_KIND + 9;
  const int32_t P_KILL_AT = 0, P_KILL_WHO = 1, P_REVIVE = 2;
  const int32_t P_VAL0 = 8, P_VAL1 = 9;
  const int32_t R = g_kv.n_replicas;
  const int32_t client = R + 1;
  const int32_t majority = R / 2 + 1;
  const int32_t full_mask = (1 << R) - 1;
  const bool payload = g_kv.payload != 0;
  auto client_value = [&](int32_t* v0, int32_t* v1) {
    *v0 = static_cast<int32_t>(ctx.draw.user(P_VAL0));
    *v1 = static_cast<int32_t>(ctx.draw.user(P_VAL1));
  };
  auto send_pay = [&](Emit e, int32_t p0, int32_t p1) {
    if (payload) {
      e.pay[0] = p0;
      e.pay[1] = p1;
    }
    eff->emits.push_back(e);
  };
  // slots 0..R-1: REPL sends gated per-replica on the ack mask
  auto replicate = [&](int32_t seq, bool when, int32_t mask, int32_t p0,
                       int32_t p1) {
    for (int32_t idx = 0; idx < R; idx++)
      send_pay(mk_send(idx + 1, K_REPL, seq, 0,
                       when && (((mask >> idx) & 1) == 0)),
               p0, p1);
  };
  auto maybe_halt = [&](int32_t committed, int32_t mask, int32_t fin) {
    eff->emits.push_back(mk_after(
        0, KIND_HALT, 0, 0,
        committed >= g_kv.writes && mask == full_mask && fin > 0));
  };
  switch (h) {
    case 0: {  // on_init
      bool is_client = ctx.node == client;
      bool is_replica = ctx.node >= 1 && ctx.node <= R;
      int32_t v0 = 0, v1 = 0;
      if (payload) client_value(&v0, &v1);
      send_pay(mk_send(PRIMARY, K_WRITE, 1, 0, is_client), v0, v1);
      eff->emits.push_back(
          mk_after(g_kv.client_retx_ns, K_CRETX, client, 0, is_client));
      eff->emits.push_back(
          mk_send(PRIMARY, K_JOIN, ctx.node, 0, is_replica));
      eff->emits.push_back(
          mk_after(g_kv.retx_ns, K_JRETX, ctx.node, 0, is_replica));
      if (g_kv.chaos) {
        int64_t who = ctx.draw.user_int(1, 1 + R, P_KILL_WHO);
        int64_t at = ctx.draw.user_int(20000000, 300000000, P_KILL_AT);
        int64_t revive = ctx.draw.user_int(100000000, 600000000, P_REVIVE);
        eff->emits.push_back(mk_after(
            at, KIND_KILL, 0, static_cast<int32_t>(who), is_client));
        eff->emits.push_back(mk_after(
            at + revive, KIND_RESTART, 0, static_cast<int32_t>(who), is_client));
      }
      break;
    }
    case 1: {  // on_write at primary
      int32_t seq = ctx.args[0];
      const int32_t* st = ctx.state;
      bool fresh = seq > st[0] && seq > st[1];
      if (fresh) {
        ns[1] = seq;
        ns[2] = 0;
        if (payload) {
          // the first WRITE to arrive for a seq fixes its value
          ns[4] = ctx.pay[0];
          ns[5] = ctx.pay[1];
        }
      }
      int32_t p0 = payload ? ns[4] : 0, p1 = payload ? ns[5] : 0;
      replicate(seq, fresh, 0, p0, p1);
      eff->emits.push_back(
          mk_after(g_kv.retx_ns, K_RETX, PRIMARY, seq, fresh));
      break;
    }
    case 2: {  // on_repl at replica
      int32_t seq = ctx.args[0];
      const int32_t* st = ctx.state;
      bool fresh = seq > st[0];
      ns[0] = st[0] > seq ? st[0] : seq;
      ns[1] = st[1] + 1;
      if (payload && fresh) {
        ns[2] = ctx.pay[0];
        ns[3] = ctx.pay[1];
      }
      eff->emits.push_back(mk_send(PRIMARY, K_ACK, seq, ctx.node));
      break;
    }
    case 3: {  // on_ack at primary
      int32_t seq = ctx.args[0], who = ctx.args[1];
      const int32_t* st = ctx.state;
      int32_t bit = int32_t{1} << (who - 1);
      bool current = seq == st[1];
      int32_t mask = current ? (st[2] | bit) : st[2];
      int32_t acks = 0;
      for (int32_t idx = 0; idx < R; idx++) acks += (mask >> idx) & 1;
      bool committed_now = current && seq > st[0] && acks >= majority;
      int32_t committed = committed_now ? seq : st[0];
      ns[0] = committed;
      ns[2] = mask;
      eff->emits.push_back(mk_send(client, K_COMMIT, committed, 0,
                                   current && committed >= seq));
      maybe_halt(committed, mask, st[3]);
      break;
    }
    case 4: {  // on_commit at client
      int32_t seq = ctx.args[0];
      const int32_t* st = ctx.state;
      bool fresh = seq > st[0];
      if (fresh) ns[0] = seq;
      bool done = seq >= g_kv.writes;
      int32_t v0 = 0, v1 = 0;
      if (payload) client_value(&v0, &v1);
      send_pay(mk_send(PRIMARY, K_WRITE, seq + 1, 0, fresh && !done), v0, v1);
      eff->emits.push_back(mk_send(PRIMARY, K_FIN, 0, 0, fresh && done));
      break;
    }
    case 5: {  // on_retx at primary
      int32_t seq = ctx.args[0];
      const int32_t* st = ctx.state;
      bool current = seq == st[1];
      bool pending_repl = current && st[2] != full_mask;
      bool pending_commit = current && st[0] >= seq;
      replicate(seq, pending_repl, st[2], payload ? st[4] : 0,
                payload ? st[5] : 0);
      eff->emits.push_back(
          mk_send(client, K_COMMIT, st[0], 0, pending_commit));
      eff->emits.push_back(mk_after(g_kv.retx_ns, K_RETX, PRIMARY, seq,
                                    pending_repl || pending_commit));
      break;
    }
    case 6: {  // on_cretx at client
      const int32_t* st = ctx.state;
      bool waiting = st[0] < g_kv.writes;
      int32_t v0 = 0, v1 = 0;
      if (payload) client_value(&v0, &v1);
      send_pay(mk_send(PRIMARY, K_WRITE, st[0] + 1, 0, waiting), v0, v1);
      eff->emits.push_back(mk_send(PRIMARY, K_FIN, 0, 0, !waiting));
      eff->emits.push_back(
          mk_after(g_kv.client_retx_ns, K_CRETX, client, 0, true));
      break;
    }
    case 7: {  // on_fin at primary
      const int32_t* st = ctx.state;
      ns[3] = 1;
      maybe_halt(st[0], st[2], 1);
      break;
    }
    case 8: {  // on_join at primary
      int32_t who = ctx.args[0];
      const int32_t* st = ctx.state;
      int32_t bit = int32_t{1} << (who - 1);
      ns[2] = st[2] & ~bit;
      // the retx timer may have died while the mask was full: re-arm
      eff->emits.push_back(
          mk_after(g_kv.retx_ns, K_RETX, PRIMARY, st[1], st[1] > 0));
      break;
    }
    case 9: {  // on_jretx at replica
      const int32_t* st = ctx.state;
      bool behind = st[0] == 0;
      eff->emits.push_back(mk_send(PRIMARY, K_JOIN, ctx.node, 0, behind));
      eff->emits.push_back(
          mk_after(g_kv.retx_ns, K_JRETX, ctx.node, 0, behind));
      break;
    }
  }
}

// twophase (models/twophase.py): coordinator-driven 2PC over n_parts
// participants with stored votes, phase-aware retransmits, and a
// scheduled participant kill/restart.
struct TwoPhaseParams {
  int32_t txns, n_parts, no_pct;
  int64_t retx_ns;
  int32_t chaos;
  int64_t revive_min_ns, revive_max_ns;
};
TwoPhaseParams g_tp{5, 4, 10, 40000000, 1, 80000000, 400000000};

void twophase_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t COORD = 0;
  const int32_t K_PREPARE = FIRST_USER_KIND + 1, K_VOTE = FIRST_USER_KIND + 2,
                K_DECISION = FIRST_USER_KIND + 3, K_ACK = FIRST_USER_KIND + 4,
                K_RETX = FIRST_USER_KIND + 5, K_HELLO = FIRST_USER_KIND + 6,
                K_HRETX = FIRST_USER_KIND + 7, K_RESYNC = FIRST_USER_KIND + 8;
  const int32_t P_VOTE = 0, P_KILL_AT = 1, P_KILL_WHO = 2, P_REVIVE = 3;
  const int32_t P = g_tp.n_parts;
  const int32_t full_mask = (1 << P) - 1;
  // slot ordering mirrors the Python EmitBuilder exactly (invalid rows
  // still consume slot indices)
  auto bcast_prepare = [&](int32_t txn, bool when, int32_t skip_mask) {
    for (int32_t i = 0; i < P; i++)
      eff->emits.push_back(mk_send(i + 1, K_PREPARE, txn, 0,
                                   when && (((skip_mask >> i) & 1) == 0)));
  };
  auto bcast_decision = [&](int32_t txn, int32_t commit, bool when,
                            int32_t skip_mask) {
    for (int32_t i = 0; i < P; i++) {
      Emit e = mk_send(i + 1, K_DECISION, txn, commit,
                       when && (((skip_mask >> i) & 1) == 0));
      eff->emits.push_back(e);
    }
  };
  switch (h) {
    case 0: {  // on_init
      bool is_coord = ctx.node == COORD;
      bool is_part = !is_coord;
      bcast_prepare(1, is_coord, 0);
      eff->emits.push_back(mk_after(g_tp.retx_ns, K_RETX, COORD, 1, is_coord));
      eff->emits.push_back(mk_send(COORD, K_HELLO, ctx.node, 0, is_part));
      eff->emits.push_back(
          mk_after(g_tp.retx_ns, K_HRETX, ctx.node, 0, is_part));
      if (g_tp.chaos) {
        int64_t who = ctx.draw.user_int(1, 1 + P, P_KILL_WHO);
        int64_t at = ctx.draw.user_int(20000000, 250000000, P_KILL_AT);
        int64_t revive =
            ctx.draw.user_int(g_tp.revive_min_ns, g_tp.revive_max_ns, P_REVIVE);
        eff->emits.push_back(
            mk_after(at, KIND_KILL, 0, static_cast<int32_t>(who), is_coord));
        eff->emits.push_back(mk_after(at + revive, KIND_RESTART, 0,
                                      static_cast<int32_t>(who), is_coord));
        // loss-free local resync at the revive time (engine on_init)
        eff->emits.push_back(mk_after(at + revive, K_RESYNC, COORD,
                                      static_cast<int32_t>(who), is_coord));
      }
      if (is_coord) ns[0] = 1;
      break;
    }
    case 1: {  // on_prepare at participant
      int32_t txn = ctx.args[0];
      const int32_t* st = ctx.state;
      bool fresh = txn > st[0];
      int64_t roll = ctx.draw.user_int(0, 100, P_VOTE);
      int32_t new_vote = roll >= g_tp.no_pct ? 1 : 0;
      int32_t vote = fresh ? new_vote : st[1];
      ns[0] = st[0] > txn ? st[0] : txn;
      ns[1] = vote;
      Emit e = mk_send(COORD, K_VOTE, txn, ctx.node, true);
      e.args[2] = vote;
      eff->emits.push_back(e);
      break;
    }
    case 2: {  // on_vote at coordinator
      int32_t txn = ctx.args[0], who = ctx.args[1], yes = ctx.args[2];
      const int32_t* st = ctx.state;
      bool relevant = txn == st[0] && st[1] == 0;
      int32_t bit = int32_t{1} << (who - 1);
      int32_t votes = relevant ? (st[2] | bit) : st[2];
      bool abort_now = relevant && yes == 0;
      bool commit_now = relevant && yes != 0 && votes == full_mask;
      bool decide = abort_now || commit_now;
      int32_t phase = decide ? (abort_now ? 2 : 1) : st[1];
      ns[1] = phase;
      ns[2] = votes;
      ns[3] = decide ? 0 : st[3];
      // no retx arm: the per-transaction chain from prepare time covers
      // both phases (engine on_vote mirrors)
      bcast_decision(txn, phase == 1 ? 1 : 0, decide, 0);
      break;
    }
    case 3: {  // on_decision at participant
      int32_t txn = ctx.args[0], commit = ctx.args[1];
      const int32_t* st = ctx.state;
      bool fresh = txn > st[2];
      ns[2] = st[2] > txn ? st[2] : txn;
      ns[3] = st[3] + (fresh ? 1 : 0);
      ns[4] = fresh ? commit : st[4];  // stored decision VALUE (agreement)
      eff->emits.push_back(mk_send(COORD, K_ACK, txn, ctx.node, true));
      break;
    }
    case 4: {  // on_ack at coordinator
      int32_t txn = ctx.args[0], who = ctx.args[1];
      const int32_t* st = ctx.state;
      bool relevant = txn == st[0] && st[1] >= 1;
      int32_t bit = int32_t{1} << (who - 1);
      int32_t acks = relevant ? (st[3] | bit) : st[3];
      bool complete = relevant && acks == full_mask;
      bool committed = st[1] == 1;
      bool last = st[0] >= g_tp.txns;
      bool advance = complete && !last;
      int32_t nxt = advance ? st[0] + 1 : st[0];
      ns[0] = nxt;
      ns[1] = advance ? 0 : st[1];
      ns[2] = advance ? 0 : st[2];
      ns[3] = acks;
      ns[4] = st[4] + ((complete && committed) ? 1 : 0);
      ns[5] = st[5] + ((complete && !committed) ? 1 : 0);
      bcast_prepare(nxt, advance, 0);
      eff->emits.push_back(
          mk_after(g_tp.retx_ns, K_RETX, COORD, nxt, advance));
      eff->emits.push_back(mk_after(0, KIND_HALT, 0, 0, complete && last));
      break;
    }
    case 5: {  // on_retx at coordinator
      int32_t txn = ctx.args[0];
      const int32_t* st = ctx.state;
      bool current = txn == st[0];
      bool preparing = current && st[1] == 0;
      bool deciding = current && st[1] >= 1;
      for (int32_t i = 0; i < P; i++)
        eff->emits.push_back(
            mk_send(i + 1, K_PREPARE, txn, 0,
                    preparing && (((st[2] >> i) & 1) == 0)));
      for (int32_t i = 0; i < P; i++)
        eff->emits.push_back(
            mk_send(i + 1, K_DECISION, txn, st[1] == 1 ? 1 : 0,
                    deciding && (((st[3] >> i) & 1) == 0)));
      eff->emits.push_back(
          mk_after(g_tp.retx_ns, K_RETX, COORD, txn, current));
      break;
    }
    case 6:    // on_hello at coordinator
    case 8: {  // on_resync at coordinator (same bit-clear, loss-free)
      int32_t who = ctx.args[0];
      const int32_t* st = ctx.state;
      int32_t bit = int32_t{1} << (who - 1);
      bool preparing = st[1] == 0;
      ns[2] = preparing ? (st[2] & ~bit) : st[2];
      ns[3] = !preparing ? (st[3] & ~bit) : st[3];
      break;
    }
    case 7: {  // on_hretx at participant
      const int32_t* st = ctx.state;
      bool unseen = st[0] == 0 && st[2] == 0;
      eff->emits.push_back(mk_send(COORD, K_HELLO, ctx.node, 0, unseen));
      eff->emits.push_back(
          mk_after(g_tp.retx_ns, K_HRETX, ctx.node, 0, unseen));
      break;
    }
  }
}

// raftlog (models/raftlog.py): raft log replication + leader crash.
// Emit-row ORDER must mirror the python EmitBuilder exactly (slot index
// keys the per-slot latency/loss draws); draw purposes are coordinates,
// so draw CALL order is free.
struct RaftLogParams {
  int32_t n_nodes, n_writes;
  int64_t timeout_min, timeout_max, propose_ns, retx_ns;
  int32_t chaos;
};
RaftLogParams g_rl{5, 4, 150000000, 300000000, 20000000, 60000000, 1};

void raftlog_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t ROLE = 0, TERM = 1, VOTED = 2, VOTES = 3, TSEQ = 4,
                LOGLEN = 5, COMMIT = 6, ACKS = 7, LOG0 = 8;
  const int32_t FOLLOWER = 0, CANDIDATE = 1, LEADER = 2;
  const int32_t K_TIMEOUT = FIRST_USER_KIND + 1,
                K_REQVOTE = FIRST_USER_KIND + 2,
                K_GRANT = FIRST_USER_KIND + 3,
                K_APPEND = FIRST_USER_KIND + 4,
                K_ACKAPP = FIRST_USER_KIND + 5,
                K_PROPOSE = FIRST_USER_KIND + 6,
                K_RETX = FIRST_USER_KIND + 7;
  const int32_t P_TIMEOUT = 0, P_VALUE = 1, P_KILL_AT = 2, P_KILL_WHO = 3,
                P_REVIVE = 4;
  const int32_t N = g_rl.n_nodes, W = g_rl.n_writes;
  const int32_t majority = N / 2 + 1;
  // value = low 8 bits, term = the remaining 23 (unbounded terms; a
  // 0xFF mask would wrap term 256 to 0 and corrupt the vote rule)
  auto entry_term = [](int32_t e) { return e >> 8; };
  auto lastterm = [&](const int32_t* st) {
    int32_t acc = 0;
    for (int32_t j = 0; j < W; j++)
      if (st[LOGLEN] == j + 1) acc = entry_term(st[LOG0 + j]);
    return acc;
  };
  auto arm = [&](int32_t new_seq, bool when) {
    int64_t d = ctx.draw.user_int(g_rl.timeout_min, g_rl.timeout_max, P_TIMEOUT);
    eff->emits.push_back(mk_after(d, K_TIMEOUT, ctx.node, new_seq, when));
  };
  auto send_appends = [&](const int32_t* st, int32_t term, bool when) {
    int32_t idx = st[LOGLEN] - 1;
    for (int32_t p = 0; p < N; p++) {
      Emit e = mk_send(p, K_APPEND, term, idx, when && p != ctx.node);
      e.args[2] = st[COMMIT];
      e.args[3] = ctx.node;
      for (int32_t j = 0; j < W; j++) e.pay[j] = st[LOG0 + j];
      eff->emits.push_back(e);
    }
  };
  switch (h) {
    case 0: {  // on_init
      arm(1, true);
      if (g_rl.chaos) {
        bool first = ctx.node == 0 && ctx.now == 0;
        int32_t who =
            static_cast<int32_t>(ctx.draw.user_int(0, N, P_KILL_WHO));
        int64_t at = ctx.draw.user_int(200000000, 500000000, P_KILL_AT);
        int64_t revive = ctx.draw.user_int(100000000, 600000000, P_REVIVE);
        eff->emits.push_back(mk_after(at, KIND_KILL, 0, who, first));
        eff->emits.push_back(mk_after(at + revive, KIND_RESTART, 0, who, first));
      }
      ns[TSEQ] = 1;
      break;
    }
    case 1: {  // on_timeout
      const int32_t* st = ctx.state;
      bool fire = ctx.args[0] == st[TSEQ] && st[ROLE] != LEADER;
      int32_t term = st[TERM] + 1;
      if (fire) {
        ns[ROLE] = CANDIDATE;
        ns[TERM] = term;
        ns[VOTED] = term;
        ns[VOTES] = 1;
        ns[TSEQ] = st[TSEQ] + 1;
      }
      for (int32_t p = 0; p < N; p++) {
        Emit e = mk_send(p, K_REQVOTE, term, ctx.node, fire && p != ctx.node);
        e.args[2] = st[LOGLEN];
        e.args[3] = lastterm(st);
        eff->emits.push_back(e);
      }
      arm(st[TSEQ] + 1, fire);
      break;
    }
    case 2: {  // on_reqvote
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0], cand = ctx.args[1];
      int32_t c_len = ctx.args[2], c_lt = ctx.args[3];
      std::vector<int32_t> st1(st, st + LOG0 + W);
      bool newer = term > st[TERM];
      if (newer) {
        st1[TERM] = term;
        st1[ROLE] = FOLLOWER;
        st1[VOTES] = 0;
      }
      int32_t my_lt = lastterm(st1.data());
      bool up_to_date =
          c_lt > my_lt || (c_lt == my_lt && c_len >= st1[LOGLEN]);
      bool grant = term == st1[TERM] && st1[VOTED] < term && up_to_date;
      std::memcpy(ns, st1.data(), sizeof(int32_t) * (LOG0 + W));
      if (grant) {
        ns[VOTED] = term;
        ns[TSEQ] = st1[TSEQ] + 1;
      }
      eff->emits.push_back(mk_send(cand, K_GRANT, term, 0, grant));
      arm(st1[TSEQ] + 1, grant);
      break;
    }
    case 3: {  // on_grant
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0];
      bool counts = st[ROLE] == CANDIDATE && term == st[TERM];
      int32_t votes = counts ? st[VOTES] + 1 : st[VOTES];
      bool wins = counts && votes >= majority;
      ns[VOTES] = votes;
      if (wins) {
        ns[ROLE] = LEADER;
        // win-time re-stamp of the uncommitted suffix
        for (int32_t j = 0; j < W; j++)
          if (j >= ns[COMMIT] && j < ns[LOGLEN])
            ns[LOG0 + j] = (ns[LOG0 + j] & 0xFF) | (term << 8);
        ns[ACKS] = ns[LOGLEN] > ns[COMMIT] ? (1 << ctx.node) : 0;
      }
      send_appends(ns, term, wins);
      eff->emits.push_back(
          mk_after(g_rl.propose_ns, K_PROPOSE, ctx.node, term, wins));
      eff->emits.push_back(
          mk_after(g_rl.retx_ns, K_RETX, ctx.node, term, wins));
      break;
    }
    case 4: {  // on_append
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0], idx = ctx.args[1], l_commit = ctx.args[2];
      int32_t leader = ctx.args[3];
      bool ok = term >= st[TERM];
      bool newer_term = term > st[TERM];
      if (ok) {
        ns[TERM] = term;
        ns[ROLE] = FOLLOWER;
        ns[TSEQ] = st[TSEQ] + 1;
      }
      bool adopt = ok && idx >= 0 && (newer_term || idx + 1 >= st[LOGLEN]);
      if (adopt) {
        for (int32_t j = 0; j < W; j++)
          if (j <= idx) ns[LOG0 + j] = ctx.pay[j];
        ns[LOGLEN] = idx + 1;
      }
      if (ok && l_commit > ns[COMMIT]) ns[COMMIT] = l_commit;
      {
        Emit e = mk_send(leader, K_ACKAPP, term, idx, adopt);
        e.args[2] = ctx.node;
        eff->emits.push_back(e);
      }
      arm(st[TSEQ] + 1, ok);
      break;
    }
    case 5: {  // on_ackapp
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0], idx = ctx.args[1], frm = ctx.args[2];
      bool counts = st[ROLE] == LEADER && term == st[TERM] &&
                    idx == st[LOGLEN] - 1 && st[COMMIT] < st[LOGLEN];
      int32_t acks = counts ? (st[ACKS] | (1 << frm)) : st[ACKS];
      int32_t n_acks = 0;
      for (int32_t p = 0; p < N; p++) n_acks += (acks >> p) & 1;
      bool commit_now = counts && n_acks >= majority;
      ns[ACKS] = acks;
      if (commit_now) ns[COMMIT] = idx + 1;
      send_appends(ns, term, commit_now);
      eff->emits.push_back(
          mk_after(0, KIND_HALT, 0, 0, commit_now && ns[COMMIT] == W));
      break;
    }
    case 6: {  // on_propose
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0];
      bool alive_leader = st[ROLE] == LEADER && term == st[TERM];
      bool can = alive_leader && st[COMMIT] == st[LOGLEN] && st[LOGLEN] < W;
      int32_t value = static_cast<int32_t>(ctx.draw.user(P_VALUE) & 0xFF);
      int32_t entry = value | (st[TERM] << 8);
      if (can) {
        for (int32_t j = 0; j < W; j++)
          if (st[LOGLEN] == j) ns[LOG0 + j] = entry;
        ns[LOGLEN] = st[LOGLEN] + 1;
        ns[ACKS] = 1 << ctx.node;
      }
      send_appends(ns, term, can);
      eff->emits.push_back(
          mk_after(g_rl.propose_ns, K_PROPOSE, ctx.node, term, alive_leader));
      break;
    }
    case 7: {  // on_retx
      const int32_t* st = ctx.state;
      int32_t term = ctx.args[0];
      bool alive_leader = st[ROLE] == LEADER && term == st[TERM];
      bool send = alive_leader && st[LOGLEN] > 0;
      send_appends(st, term, send);
      eff->emits.push_back(
          mk_after(g_rl.retx_ns, K_RETX, ctx.node, term, alive_leader));
      break;
    }
  }
}

// paxos (models/paxos.py): single-decree synod — A acceptors (nodes
// 0..A-1, never killed: stable storage), P proposers (A..A+P-1) with
// unique ballots round*P+pidx+1, NACK fast-forward, proposer-crash
// chaos. Emit-row ORDER mirrors the Python EmitBuilder exactly.
struct PaxosParams {
  int32_t n_acceptors, n_proposers;
  int64_t start_min_ns, start_max_ns, timeout_min_ns, timeout_max_ns;
  int32_t chaos;
  int64_t kill_min_ns, kill_max_ns, revive_min_ns, revive_max_ns;
  // kill an acceptor (1..A-1) instead of a proposer; pairs with the
  // durable acceptor columns (Workload.durable_cols = promised/bal/val)
  int32_t durable_acceptors;
};
PaxosParams g_px{5, 3, 5000000, 30000000, 60000000, 120000000,
                 1, 30000000, 150000000, 80000000, 300000000, 0};

void paxos_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t K_PROPOSE = FIRST_USER_KIND + 1,
                K_PREPARE = FIRST_USER_KIND + 2,
                K_PROMISE = FIRST_USER_KIND + 3,
                K_ACCEPT = FIRST_USER_KIND + 4,
                K_ACCEPTED = FIRST_USER_KIND + 5,
                K_DECIDED = FIRST_USER_KIND + 6,
                K_NACK = FIRST_USER_KIND + 7;
  const int32_t P_START = 0, P_TIMEOUT = 1, P_KILL_AT = 2, P_KILL_WHO = 3,
                P_REVIVE = 4;
  const int32_t A = g_px.n_acceptors, P = g_px.n_proposers;
  const int32_t majority = A / 2 + 1;
  // proposer state columns (acceptors use 0..2 as promised/bal/val)
  const int32_t S_PHASE = 0, S_BAL = 1, S_VAL = 2, S_PCNT = 3, S_BESTB = 4,
                S_BESTV = 5, S_ACNT = 6, S_DEC = 7, S_ROUND = 8, S_TSEQ = 9;
  const int32_t* st = ctx.state;
  bool is_prop = ctx.node >= A;
  switch (h) {
    case 0: {  // on_init
      int64_t d = ctx.draw.user_int(g_px.start_min_ns, g_px.start_max_ns,
                                    P_START);
      eff->emits.push_back(mk_after(d, K_PROPOSE, ctx.node, 1, is_prop));
      if (g_px.chaos) {
        bool first = ctx.node == 0 && ctx.now == 0;
        int64_t who = g_px.durable_acceptors
                          ? 1 + ctx.draw.user_int(0, A - 1, P_KILL_WHO)
                          : A + ctx.draw.user_int(0, P, P_KILL_WHO);
        int64_t at =
            ctx.draw.user_int(g_px.kill_min_ns, g_px.kill_max_ns, P_KILL_AT);
        int64_t revive = ctx.draw.user_int(g_px.revive_min_ns,
                                           g_px.revive_max_ns, P_REVIVE);
        eff->emits.push_back(
            mk_after(at, KIND_KILL, 0, static_cast<int32_t>(who), first));
        eff->emits.push_back(mk_after(at + revive, KIND_RESTART, 0,
                                      static_cast<int32_t>(who), first));
      }
      if (is_prop) ns[S_TSEQ] = 1;
      break;
    }
    case 1: {  // on_propose (timer at proposer)
      bool live = ctx.args[0] == st[S_TSEQ] && is_prop;
      bool fire = live && st[S_DEC] == 0;
      // decided proposers keep re-delivering DECIDED to the halt
      // witness (engine on_propose `redeliver`)
      bool redeliver = live && st[S_DEC] != 0;
      int32_t pidx = ctx.node - A;
      int32_t ballot = st[S_ROUND] * P + pidx + 1;
      if (fire) {
        ns[S_PHASE] = 1;  // PREPARING
        ns[S_BAL] = ballot;
        ns[S_PCNT] = 0;
        ns[S_BESTB] = 0;
        ns[S_BESTV] = 0;
        ns[S_ACNT] = 0;
        ns[S_ROUND] = st[S_ROUND] + 1;
        ns[S_TSEQ] = st[S_TSEQ] + 1;
      } else if (redeliver) {
        ns[S_TSEQ] = st[S_TSEQ] + 1;
      }
      eff->emits.push_back(mk_send(0, K_DECIDED, st[S_DEC], 0, redeliver));
      for (int32_t acc = 0; acc < A; acc++)
        eff->emits.push_back(mk_send(acc, K_PREPARE, ballot, 0, fire));
      int64_t d = ctx.draw.user_int(g_px.timeout_min_ns, g_px.timeout_max_ns,
                                    P_TIMEOUT);
      eff->emits.push_back(
          mk_after(d, K_PROPOSE, ctx.node, st[S_TSEQ] + 1, fire || redeliver));
      break;
    }
    case 2: {  // on_prepare (at acceptor)
      int32_t b = ctx.args[0];
      bool grant = b > st[0];
      if (grant) ns[0] = b;
      Emit e = mk_send(ctx.src, K_PROMISE, b, st[1], grant);
      e.args[2] = st[2];
      eff->emits.push_back(e);
      eff->emits.push_back(mk_send(ctx.src, K_NACK, st[0], 0, !grant));
      break;
    }
    case 3: {  // on_promise (at proposer)
      int32_t b = ctx.args[0], abal = ctx.args[1], aval = ctx.args[2];
      bool relevant = st[S_PHASE] == 1 && b == st[S_BAL];
      int32_t pcnt = relevant ? st[S_PCNT] + 1 : st[S_PCNT];
      bool better = relevant && abal > st[S_BESTB];
      int32_t bestb = better ? abal : st[S_BESTB];
      int32_t bestv = better ? aval : st[S_BESTV];
      bool won = relevant && pcnt >= majority;
      int32_t own = ctx.node - A + 1;
      int32_t value = bestb > 0 ? bestv : own;
      ns[S_PCNT] = pcnt;
      ns[S_BESTB] = bestb;
      ns[S_BESTV] = bestv;
      if (won) {
        ns[S_PHASE] = 2;  // ACCEPTING
        ns[S_VAL] = value;
        ns[S_ACNT] = 0;
      }
      for (int32_t acc = 0; acc < A; acc++)
        eff->emits.push_back(mk_send(acc, K_ACCEPT, b, value, won));
      break;
    }
    case 4: {  // on_accept (at acceptor)
      int32_t b = ctx.args[0], v = ctx.args[1];
      bool ok = b >= st[0];
      if (ok) {
        ns[0] = b;
        ns[1] = b;
        ns[2] = v;
      }
      eff->emits.push_back(mk_send(ctx.src, K_ACCEPTED, b, 0, ok));
      eff->emits.push_back(mk_send(ctx.src, K_NACK, st[0], 0, !ok));
      break;
    }
    case 5: {  // on_accepted (at proposer)
      int32_t b = ctx.args[0];
      bool relevant = st[S_PHASE] == 2 && b == st[S_BAL];
      int32_t acnt = relevant ? st[S_ACNT] + 1 : st[S_ACNT];
      bool chosen = relevant && acnt >= majority;
      ns[S_ACNT] = acnt;
      if (chosen) {
        ns[S_PHASE] = 3;  // DONE
        ns[S_DEC] = st[S_VAL];
      }
      for (int32_t prop = A; prop < A + P; prop++)
        eff->emits.push_back(mk_send(prop, K_DECIDED, st[S_VAL], 0,
                                     chosen && prop != ctx.node));
      eff->emits.push_back(mk_send(0, K_DECIDED, st[S_VAL], 0, chosen));
      break;
    }
    case 6: {  // on_decided
      int32_t v = ctx.args[0];
      if (is_prop) {
        ns[S_DEC] = st[S_DEC] == 0 ? v : st[S_DEC];
        ns[S_PHASE] = 3;
      }
      eff->emits.push_back(mk_after(0, KIND_HALT, 0, 0, ctx.node == 0));
      break;
    }
    case 7: {  // on_nack (at proposer)
      int32_t b = ctx.args[0];
      bool act = is_prop && b > st[S_BAL] && st[S_DEC] == 0;
      if (act) {
        int32_t ffwd = b / P + 1;
        ns[S_PHASE] = 0;  // IDLE
        ns[S_ROUND] = st[S_ROUND] > ffwd ? st[S_ROUND] : ffwd;
      }
      break;
    }
  }
}

// snapshot (models/snapshot.py): Lai-Yang distributed snapshot over a
// money-transfer workload — consistent cut under message reordering,
// conservation invariant sum(rec_bal)+sum(chan_in) == n*balance.
// Emit-row ORDER mirrors the Python EmitBuilder exactly (incl. the
// statically-present self slot in the paint loop, when=false).
struct SnapshotParams {
  int32_t n_nodes, n_sends, balance, amount_max;
  int64_t send_min_ns, send_max_ns, snap_min_ns, snap_max_ns;
};
SnapshotParams g_sn{5, 6, 1000, 100, 5000000, 25000000, 20000000, 80000000};

void snapshot_handler(int32_t h, const Ctx& ctx, int32_t* ns, Effects* eff) {
  const int32_t K_SEND = FIRST_USER_KIND + 1,
                K_TRANSFER = FIRST_USER_KIND + 2,
                K_SNAP = FIRST_USER_KIND + 3,
                K_RECVD = FIRST_USER_KIND + 4;
  const int32_t P_SEND = 0, P_DST = 1, P_AMT = 2, P_SNAP = 3;
  const int32_t S_COLOR = 0, S_BAL = 1, S_RECBAL = 2, S_CHANIN = 3,
                S_SENT = 4, S_RCNT = 5;
  const int32_t N = g_sn.n_nodes;
  const int32_t total_msgs = N * g_sn.n_sends + N * (N - 1);
  const int32_t* st = ctx.state;
  auto paints = [&](bool when) {
    for (int32_t p = 0; p < N; p++)
      eff->emits.push_back(
          mk_send(p, K_TRANSFER, 0, 1, when && p != ctx.node));
  };
  switch (h) {
    case 0: {  // on_init
      int64_t d =
          ctx.draw.user_int(g_sn.send_min_ns, g_sn.send_max_ns, P_SEND);
      eff->emits.push_back(mk_after(d, K_SEND, ctx.node));
      int64_t sd =
          ctx.draw.user_int(g_sn.snap_min_ns, g_sn.snap_max_ns, P_SNAP);
      eff->emits.push_back(mk_after(sd, K_SNAP, ctx.node, 0, ctx.node == 0));
      ns[S_BAL] = g_sn.balance;
      break;
    }
    case 1: {  // on_send (transfer timer)
      bool fire = st[S_SENT] < g_sn.n_sends;
      int64_t r = ctx.draw.user_int(0, N - 1, P_DST);
      int32_t dst =
          (ctx.node + 1 + static_cast<int32_t>(r)) % N;  // never self
      int32_t amt = static_cast<int32_t>(
          ctx.draw.user_int(1, g_sn.amount_max + 1, P_AMT));
      if (fire) {
        ns[S_BAL] = st[S_BAL] - amt;
        ns[S_SENT] = st[S_SENT] + 1;
      }
      eff->emits.push_back(mk_send(dst, K_TRANSFER, amt, st[S_COLOR], fire));
      int64_t d =
          ctx.draw.user_int(g_sn.send_min_ns, g_sn.send_max_ns, P_SEND);
      eff->emits.push_back(mk_after(d, K_SEND, ctx.node, 0,
                                    fire && st[S_SENT] + 1 < g_sn.n_sends));
      break;
    }
    case 2: {  // on_transfer; args = (amount, sender_color)
      int32_t amt = ctx.args[0];
      bool msg_red = ctx.args[1] == 1;
      bool was_white = st[S_COLOR] == 0;
      bool turn = was_white && msg_red;
      if (turn) {
        ns[S_COLOR] = 1;
        ns[S_RECBAL] = st[S_BAL];  // record BEFORE applying
      }
      if (!was_white && !msg_red) ns[S_CHANIN] = st[S_CHANIN] + amt;
      ns[S_BAL] = st[S_BAL] + amt;
      paints(turn);
      eff->emits.push_back(mk_send(0, K_RECVD));
      break;
    }
    case 3: {  // on_snap (initiator)
      bool turn = st[S_COLOR] == 0;
      if (turn) {
        ns[S_COLOR] = 1;
        ns[S_RECBAL] = st[S_BAL];
      }
      paints(turn);
      break;
    }
    case 4: {  // on_recvd (witness count at node 0)
      int32_t cnt = st[S_RCNT] + 1;
      ns[S_RCNT] = cnt;
      eff->emits.push_back(mk_after(0, KIND_HALT, 0, 0, cnt == total_msgs));
      break;
    }
  }
}

Workload make_workload(int32_t id) {
  switch (id) {
    case 0:  // pingpong
      return Workload{1 + g_pp.n_clients, 4, 4, 2, pingpong_handler};
    case 1:  // microbench
      return Workload{1, 4, 2, 2, microbench_handler};
    case 2:  // raft
      return Workload{g_raft.n_nodes, 6, 5, g_raft.n_nodes + 1, raft_handler};
    case 3: {  // broadcast: max_emits = max(n_peers + 3, 6)
      int32_t k = g_bc.n_nodes - 1 + 3;
      if (k < 6) k = 6;
      return Workload{g_bc.n_nodes, 4, 4, k, broadcast_handler};
    }
    case 4: {  // kvchaos: max_emits = max(n_replicas + 2, 6)
      int32_t k = g_kv.n_replicas + 2;
      if (k < 6) k = 6;
      return Workload{g_kv.n_replicas + 2, g_kv.payload ? 6 : 4, 10, k,
                      kvchaos_handler, g_kv.payload ? 2 : 0};
    }
    case 5: {  // twophase: max_emits = max(2P+1, P+6, 6)
      int32_t k = 2 * g_tp.n_parts + 1;
      if (k < g_tp.n_parts + 6) k = g_tp.n_parts + 6;
      if (k < 6) k = 6;
      return Workload{1 + g_tp.n_parts, 6, 9, k, twophase_handler};
    }
    case 6:  // raftlog: max_emits = N + 2 (grant: N appends + 2 timers)
      return Workload{g_rl.n_nodes, 8 + g_rl.n_writes, 8, g_rl.n_nodes + 2,
                      raftlog_handler, g_rl.n_writes};
    case 7: {  // paxos: max_emits = max(A+2, P+1, 3)
      int32_t k = g_px.n_acceptors + 2;
      if (k < g_px.n_proposers + 1) k = g_px.n_proposers + 1;
      if (k < 3) k = 3;
      return Workload{g_px.n_acceptors + g_px.n_proposers, 10, 8, k,
                      paxos_handler};
    }
    case 8: {  // snapshot: max_emits = n_nodes + 1 (paint slots + notice)
      int32_t k = g_sn.n_nodes + 1;
      if (k < 2) k = 2;
      return Workload{g_sn.n_nodes, 6, 5, k, snapshot_handler};
    }
    default:
      return Workload{0, 0, 0, 0, nullptr};
  }
}

}  // namespace

extern "C" {

// Set workload parameters (mirrors the model factory arguments).
void oracle_set_pingpong(int32_t rounds, int32_t n_clients) {
  g_pp = {rounds, n_clients};
}
void oracle_set_microbench(int32_t rounds, int64_t dmin, int64_t dmax) {
  g_mb = {rounds, dmin, dmax};
}
void oracle_set_raft(int32_t n_nodes, int64_t tmin, int64_t tmax) {
  g_raft = {n_nodes, tmin, tmax};
}
void oracle_set_broadcast(int32_t rounds, int32_t n_nodes, int64_t retx_ns,
                          int32_t partition) {
  g_bc = {rounds, n_nodes, retx_ns, partition};
}
void oracle_set_twophase(int32_t txns, int32_t n_parts, int32_t no_pct,
                         int64_t retx_ns, int32_t chaos,
                         int64_t revive_min_ns, int64_t revive_max_ns) {
  g_tp = {txns, n_parts, no_pct, retx_ns, chaos, revive_min_ns, revive_max_ns};
}
void oracle_set_kvchaos(int32_t writes, int32_t n_replicas, int64_t retx_ns,
                        int64_t client_retx_ns, int32_t chaos,
                        int32_t payload) {
  g_kv = {writes, n_replicas, retx_ns, client_retx_ns, chaos, payload};
}
int32_t oracle_set_raftlog(int32_t n_nodes, int32_t n_writes, int64_t tmin,
                           int64_t tmax, int64_t propose_ns, int64_t retx_ns,
                           int32_t chaos) {
  if (n_writes > kMaxPay) return 1;  // payload arena cap
  g_rl = {n_nodes, n_writes, tmin, tmax, propose_ns, retx_ns, chaos};
  return 0;
}
void oracle_set_snapshot(int32_t n_nodes, int32_t n_sends, int32_t balance,
                         int32_t amount_max, int64_t send_min_ns,
                         int64_t send_max_ns, int64_t snap_min_ns,
                         int64_t snap_max_ns) {
  g_sn = {n_nodes, n_sends, balance, amount_max,
          send_min_ns, send_max_ns, snap_min_ns, snap_max_ns};
}
void oracle_set_paxos(int32_t n_acceptors, int32_t n_proposers,
                      int64_t start_min_ns, int64_t start_max_ns,
                      int64_t timeout_min_ns, int64_t timeout_max_ns,
                      int32_t chaos, int64_t kill_min_ns, int64_t kill_max_ns,
                      int64_t revive_min_ns, int64_t revive_max_ns,
                      int32_t durable_acceptors) {
  g_px = {n_acceptors,    n_proposers,    start_min_ns, start_max_ns,
          timeout_min_ns, timeout_max_ns, chaos,        kill_min_ns,
          kill_max_ns,    revive_min_ns,  revive_max_ns, durable_acceptors};
}

// Initial node-state rows (Workload.initial_state()), flattened (N*U).
// Passed per run by the Python bridge so nonzero init_state workloads
// stay bit-identical (init AND restart both restore these rows).
std::vector<int32_t> g_init_state;

// Durable (restart-surviving) state columns, as indices; cleared or
// replaced per run by the Python bridge (Workload.durable_cols).
std::vector<int32_t> g_durable_cols;
void oracle_set_durable_cols(const int32_t* cols, int64_t n) {
  g_durable_cols.clear();
  if (cols != nullptr && n > 0) g_durable_cols.assign(cols, cols + n);
}

void oracle_set_init_state(const int32_t* rows, int64_t n) {
  if (rows == nullptr || n <= 0) {
    g_init_state.clear();
  } else {
    g_init_state.assign(rows, rows + n);
  }
}

// Run one seed for n_steps; returns 0 on success. Outputs mirror the
// SimState fields the trace compare checks.
int32_t oracle_run(int32_t workload_id, uint64_t seed, int64_t n_steps,
                   int64_t pool_size, int64_t lat_min_ns, int64_t lat_max_ns,
                   uint64_t loss_u32, int64_t proc_min_ns, int64_t proc_max_ns,
                   int64_t clog_backoff_min_ns, int64_t clog_backoff_max_ns,
                   int64_t time_limit_ns, int64_t* out_now, uint64_t* out_trace,
                   int64_t* out_msg_count, int32_t* out_halted,
                   int64_t* out_halt_time, int32_t* out_overflow,
                   int32_t* out_node_state /* N*U, may be null */) {
  Workload wl = make_workload(workload_id);
  if (wl.n_nodes == 0) return 1;
  g_log_count = 0;  // each run logs from the start of its buffers
  Sim sim;
  sim.cfg = Config{pool_size, lat_min_ns, lat_max_ns, loss_u32,
                   proc_min_ns, proc_max_ns, clog_backoff_min_ns,
                   clog_backoff_max_ns, time_limit_ns};
  sim.wl = wl;
  sim.seed = seed;
  if (static_cast<int64_t>(g_init_state.size()) ==
      static_cast<int64_t>(wl.n_nodes) * wl.state_width) {
    sim.init_state = g_init_state;
  }
  sim.durable.assign(wl.state_width, 0);
  for (int32_t c : g_durable_cols)
    if (c >= 0 && c < wl.state_width) sim.durable[c] = 1;
  sim.init();
  for (int64_t s = 0; s < n_steps; s++) sim.do_step();
  *out_now = sim.now;
  *out_trace = sim.trace;
  *out_msg_count = sim.msg_count;
  *out_halted = sim.halted ? 1 : 0;
  *out_halt_time = sim.halt_time;
  *out_overflow = sim.overflow;
  if (out_node_state) {
    std::memcpy(out_node_state, sim.node_state.data(),
                sim.node_state.size() * sizeof(int32_t));
  }
  return 0;
}

// Direct threefry access for RNG unit tests.
void oracle_threefry2x32(uint32_t k0, uint32_t k1, uint32_t x0, uint32_t x1,
                         uint32_t* o0, uint32_t* o1) {
  threefry2x32(k0, k1, x0, x1, o0, o1);
}

// Attach caller-owned per-dispatch log buffers (engine/replay.py).
// args is (cap, 4) row-major; pay is (cap, 4 = kMaxPay) row-major.
// Pass cap=0 (and nulls) to detach. The next oracle_run fills from 0.
// NOT thread-safe: the g_log_* globals are unguarded, so the
// attach -> oracle_run -> detach window must be serialized by the
// caller against EVERY other oracle_run in the process (the Python
// bridge's reentrant ORACLE_LOCK guards every run_oracle, and
// replay.py holds the same lock across this window).
void oracle_set_log(int64_t* t, int32_t* kind, int32_t* node, int32_t* src,
                    int32_t* args, int32_t* pay, int64_t cap) {
  g_log_time = t;
  g_log_kind = kind;
  g_log_node = node;
  g_log_src = src;
  g_log_args = args;
  g_log_pay = pay;
  g_log_cap = cap;
  g_log_count = 0;
}

// Dispatched-event count of the last run (may exceed the attached
// capacity — that means the log was truncated).
int64_t oracle_log_count() { return g_log_count; }

}  // extern "C"
