"""Multi-device throughput curve for the sharded compacted runner.

Measures raft sim-s/s at device counts {1, 2, 4, 8} on the virtual
CPU mesh (`--xla_force_host_platform_device_count=8`), the analog of
the reference's jobs-axis scaling (`MADSIM_TEST_JOBS`, reference
madsim/src/sim/runtime/builder.rs:110-148 — seeds split over threads,
embarrassingly parallel, trivially linear).

What this can and cannot show per host:

* On a host with >= 8 cores the curve is the real thing: each virtual
  device gets a core and total sim-s/s should rise ~linearly.
* On a 1-core host (this container: nproc == 1) the 8 virtual devices
  timeshare one core, so total throughput CANNOT rise; the meaningful
  measurements are (a) per-seed results stay bit-identical to the
  unsharded runner at every device count, (b) total wall stays ~flat
  as the device count rises — i.e. GSPMD sharding + per-device
  compaction add no overhead — and (c) per-device banked-row counts
  show every shard compacting locally. Flat-wall-at-fixed-work on a
  timeshared core is exactly the evidence that on D real chips (each
  shard getting its own silicon) throughput multiplies by D: the
  per-device program is identical, only the executor changes.

The artifact records cores/devices so a reader can tell which regime
a row was measured in.

Usage: python examples/multidev_curve.py [out.json]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import os
import sys
import time

if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

from madsim_tpu.engine import EngineConfig, make_init  # noqa: E402
from madsim_tpu.models import BENCH_SPECS  # noqa: E402
from madsim_tpu.parallel import make_mesh, shard_run_compacted, shard_state  # noqa: E402

N_SEEDS = 65536
REPEATS = 3
DEVICE_COUNTS = [1, 2, 4, 8]


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "MULTIDEV.json"
    mk, cfg_kw, _, max_steps = BENCH_SPECS["raft"]
    wl, cfg = mk(), EngineConfig(**cfg_kw)
    init = make_init(wl, cfg)
    seeds = np.arange(N_SEEDS, dtype=np.uint64)

    rows = []
    baseline_now = None
    for d in DEVICE_COUNTS:
        mesh = make_mesh(jax.devices()[:d])
        # min_size is per-shard: keep the same FINAL per-device phase
        # floor so the compaction economics match across device counts
        run = shard_run_compacted(
            wl, cfg, max_steps, mesh, min_size=max(2048 // d, 256),
            fields=("now", "overflow", "halted"),
        )
        state = shard_state(init(seeds), mesh)
        jax.block_until_ready(run.compute(state))  # compile
        walls = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()  # lint: allow(wall-clock)
            banked = jax.block_until_ready(run.compute(state))
            walls.append(time.perf_counter() - t0)  # lint: allow(wall-clock)
        out = run.assemble(banked)
        wall = float(np.median(walls))
        sim_s = float(np.asarray(out.now, dtype=np.float64).sum() / 1e9)
        if baseline_now is None:
            baseline_now = np.asarray(out.now).copy()
        rec = {
            "devices": d,
            "n_seeds": N_SEEDS,
            "wall_s_median": round(wall, 3),
            "walls_s": [round(w, 3) for w in walls],
            "sim_s_per_s_total": round(sim_s / wall, 1),
            "overflow": int(np.asarray(out.overflow).sum()),
            "all_halted": bool(np.all(np.asarray(out.halted))),
            "identical_to_1dev": bool(
                np.array_equal(np.asarray(out.now), baseline_now)
            ),
        }
        rows.append(rec)
        print(json.dumps(rec), flush=True)

    doc = {
        "workload": "raft",
        "platform": jax.devices()[0].platform,
        "host_cores": os.cpu_count(),
        "note": (
            "host_cores < devices means virtual devices timeshare cores: "
            "the scaling signal is flat wall at fixed work (zero sharding "
            "overhead), not rising total throughput"
        ),
        "rows": rows,
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# wrote {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
