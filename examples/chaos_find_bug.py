"""The DST loop end-to-end on an UNMODIFIED asyncio app: seed search
finds a timing-dependent bug, the banner reproduces it.

The client below has a real bug: it retries a request after a
connection reset, but only ONCE — if the server's crash window swallows
both attempts, the request is silently lost. Whether that happens
depends entirely on the seeded timing of the kill/restart against the
client's schedule: measured over seeds 1-100, 17 trigger the bug and
83 pass. Exactly the class of bug
deterministic simulation testing exists for (the reference's pitch,
madsim README):

    python examples/chaos_find_bug.py          # sweep 40 seeds, find one
    MADSIM_TEST_SEED=<reported> python examples/chaos_find_bug.py --one
                                               # replay just that seed

The app code is plain stdlib asyncio (open_connection/start_server,
Queue, sleep) — no simulator imports; only the harness at the bottom
touches madsim_tpu. Sweeping N seeds takes seconds of wall time because
all the "seconds" in the app are virtual.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import asyncio
import os
import random
import sys

import madsim_tpu as ms

N_REQS = 6


# ----------------------------------------------------------------------
# The application under test: plain asyncio, one real bug.
# ----------------------------------------------------------------------
async def kv_server():
    store = {}

    async def on_client(reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                key, _, val = line.decode().strip().partition("=")
                store[key] = val
                writer.write(f"ok:{key}\n".encode())
                await writer.drain()
        except ConnectionError:
            pass

    server = await asyncio.start_server(on_client, "10.0.0.1", 7100)
    async with server:
        await server.serve_forever()


async def flaky_client(results: list):
    """Writes N_REQS keys; on a reset it reconnects and retries the
    in-flight request — but only once (THE BUG: a second failure of the
    same request is silently dropped)."""

    async def connect():
        return await asyncio.open_connection("10.0.0.1", 7100)

    reader, writer = await connect()
    for i in range(N_REQS):
        payload = f"k{i}=v{i}\n".encode()
        for attempt in (1, 2):
            try:
                writer.write(payload)
                await writer.drain()
                ack = await asyncio.wait_for(reader.readline(), timeout=1.0)
                if ack:
                    results.append(i)
                    break
                raise ConnectionResetError  # EOF mid-request
            except (ConnectionError, RuntimeError, asyncio.TimeoutError):
                if attempt == 2:
                    break  # BUG: request i silently lost
                await asyncio.sleep(0.3)  # BUG: assumes 300 ms is enough
                try:
                    writer.close()
                except Exception:
                    pass
                try:
                    reader, writer = await connect()
                except ConnectionError:
                    break  # BUG: gives up instead of backing off more
        await asyncio.sleep(0.05)


# ----------------------------------------------------------------------
# The harness: chaos + invariant. Only this part knows the simulator.
# ----------------------------------------------------------------------
@ms.test
async def main():
    h = ms.Handle.current()
    srv = (
        h.create_node().name("kv").ip("10.0.0.1").init(kv_server).build()
    )
    cli = h.create_node().name("client").ip("10.0.0.2").build()

    results: list = []
    done = cli.spawn(flaky_client(results))

    # chaos: one kill/restart at a seeded moment while requests flow
    await ms.sleep(random.random() * 0.8)
    h.kill(srv)
    await ms.sleep(0.1 + random.random() * 0.5)
    h.restart(srv)

    await done
    acked = sorted(results)
    assert acked == list(range(N_REQS)), (
        f"LOST REQUESTS: acked only {acked} of {list(range(N_REQS))}"
    )


if __name__ == "__main__":
    if "--one" in sys.argv:
        main()
        print("this seed passes")
    else:
        os.environ.setdefault("MADSIM_TEST_NUM", "40")
        try:
            main()
        except BaseException:
            print(
                "\nbug found — replay with the banner seed above:\n"
                "  MADSIM_TEST_SEED=<seed> python examples/chaos_find_bug.py --one",
                file=sys.stderr,
            )
            raise
        print(f"all {os.environ['MADSIM_TEST_NUM']} seeds passed (unexpected "
              f"for this buggy client — raise MADSIM_TEST_NUM)")
