"""CPU-vs-TPU bit-identical trace check on real silicon.

SURVEY.md §4's build implication (d): the TPU-native analog of the
reference's determinism checker is a cross-backend trace compare —
the same seeds run on the CPU backend (scatter layout) and the
accelerator (dense layout) must produce identical uint64 trace hashes,
clocks, and final node state. This script runs it for every benchmark
workload and writes the committed artifact (CROSS_BACKEND.json).

Zero divergence is the BASELINE.json "trace-divergence rate" metric.

Usage: python examples/cross_backend_check.py [n_seeds] [out.json]
(run it WITHOUT JAX_PLATFORMS so the accelerator is visible; the CPU
half runs in a subprocess pinned to the cpu backend)
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import os
import subprocess
import sys

FIELDS = ("trace", "now", "halted", "halt_time", "msg_count", "overflow")


def run_half(platform: str, n_seeds: int) -> dict:
    """Run every config on one backend in a subprocess; return arrays."""
    env = dict(os.environ)
    env["CROSS_CHILD"] = "1"
    env["CROSS_SEEDS"] = str(n_seeds)
    env["CROSS_PLATFORM"] = platform
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"{platform} half failed: {proc.stderr[-800:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def child() -> None:
    import jax

    if os.environ.get("CROSS_PLATFORM") == "cpu":
        # the env var alone is not enough: this image's sitecustomize
        # pins JAX_PLATFORMS to the TPU plugin at interpreter startup
        # (see tests/conftest.py); the config update wins
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from madsim_tpu.engine import EngineConfig, make_init, make_run
    from madsim_tpu.engine.compact import make_run_compacted
    from madsim_tpu.models import (
        BENCH_SPECS, make_paxos, make_snapshot, make_twophase,
    )

    n_seeds = int(os.environ["CROSS_SEEDS"])
    seeds = np.arange(n_seeds, dtype=np.uint64)
    out = {"platform": jax.devices()[0].platform, "configs": {}}
    # the SAME configurations the benchmark reports (shared table), so
    # a freshly generated artifact certifies exactly what bench.py
    # measures (regenerate after any BENCH_SPECS change — the committed
    # JSON records the spec table at its generation time); step caps
    # trimmed where the workload halts far earlier
    # (raftlog's 4000 in BENCH_SPECS is a run_while chaos-tail cap; its
    # seeds halt well under 400 lockstep steps — tests/test_engine.py)
    step_cap = {"raft": 400, "broadcast": 400, "kvchaos": 700, "raftlog": 400}
    # the 7th and 8th workload families (not bench configs, but the
    # artifact certifies every oracle-covered family): two-phase commit
    # and single-decree paxos, at the oracle-suite configurations
    # (tests/test_oracle.py)
    specs = dict(BENCH_SPECS)
    specs["twophase"] = (
        lambda: make_twophase(txns=4),
        dict(pool_size=64, loss_p=0.03),
        None,
        500,
    )
    specs["paxos"] = (
        make_paxos,
        dict(pool_size=64, loss_p=0.02),
        None,
        400,
    )
    specs["snapshot"] = (
        make_snapshot,
        dict(pool_size=96),
        None,
        400,
    )
    for name, (factory, cfg_kwargs, _seeds, spec_steps) in specs.items():
        wl, cfg = factory(), EngineConfig(**cfg_kwargs)
        steps = step_cap.get(name, spec_steps)
        st0 = make_init(wl, cfg)(seeds)  # one init serves both runners
        run = jax.jit(make_run(wl, cfg, steps))
        res = jax.block_until_ready(run(st0))
        rec = {
            f: np.asarray(getattr(res, f)).astype(np.uint64).tolist()
            if f == "trace"
            else np.asarray(getattr(res, f)).astype(np.int64).tolist()
            for f in FIELDS
        }
        # the compacted runner is the path bench.py actually times:
        # certify it cross-backend too (per-seed values are asserted
        # bit-identical to lockstep by tests/test_compact.py; here the
        # same banked fields must also agree across backends)
        crun = make_run_compacted(
            wl, cfg, steps, min_size=max(n_seeds // 4, 16), fields=FIELDS
        )
        cres = crun(st0)
        for f in FIELDS:
            rec["compact_" + f] = (
                np.asarray(getattr(cres, f)).astype(np.uint64).tolist()
                if f == "trace"
                else np.asarray(getattr(cres, f)).astype(np.int64).tolist()
            )
        out["configs"][name] = rec
    print(json.dumps(out))


def main() -> None:
    if os.environ.get("CROSS_CHILD"):
        child()
        return
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    out_path = sys.argv[2] if len(sys.argv) > 2 else "CROSS_BACKEND.json"
    acc = run_half("default", n_seeds)
    cpu = run_half("cpu", n_seeds)
    if acc["platform"] == "cpu" or cpu["platform"] != "cpu":
        # comparing a backend against itself proves nothing — refuse to
        # write a vacuous artifact
        raise SystemExit(
            f"not a cross-backend run: accel={acc['platform']} "
            f"cpu={cpu['platform']} (is the accelerator visible?)"
        )
    # the artifact of record must certify every oracle-covered family —
    # refuse to bank an under-covering run (round-3's committed JSON
    # silently covered 5 of 8)
    expected = {
        "raft", "microbench", "pingpong", "broadcast", "kvchaos",
        "raftlog", "twophase", "paxos", "snapshot",
    }
    missing = expected - set(acc["configs"])
    if missing:
        raise SystemExit(f"cross-backend run missing families: {sorted(missing)}")
    report = {
        "accel_platform": acc["platform"],
        "cpu_platform": cpu["platform"],
        "n_seeds": n_seeds,
        "configs": {},
        "divergences": 0,
    }
    for name in acc["configs"]:
        diverged = []
        # every emitted field: the lockstep set plus its compact_* twins
        for f in acc["configs"][name]:
            a, c = acc["configs"][name][f], cpu["configs"][name][f]
            n_bad = sum(1 for x, y in zip(a, c) if x != y)
            if n_bad:
                diverged.append((f, n_bad))
        report["configs"][name] = {
            "identical": not diverged,
            "diverged_fields": diverged,
        }
        report["divergences"] += sum(n for _f, n in diverged)
        status = "IDENTICAL" if not diverged else f"DIVERGED {diverged}"
        print(f"{name}: {status}", file=sys.stderr)
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1)
    print(json.dumps({"divergence_rate": report["divergences"],
                      "accel": acc["platform"], "n_seeds": n_seeds}))
    if report["divergences"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
