"""Chaos-schedule search demo: dig a durability hazard out of 4,096
seeded schedules in one batched run (BASELINE.md config 5).

The invariant deliberately over-promises — "every replica has applied at
least `writes` replication messages by halt" — and the search reports
exactly the schedules whose kill/restart chaos makes it false, each with
a repro recipe. Any reported seed re-run alone (or inside any other
batch) produces the identical trace; that determinism is what turns a
fleet-scale sweep into a debuggable bug report.

Usage:  python examples/chaos_search.py [n_seeds]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import os

import jax

if os.environ.get("MADSIM_DEMO_PLATFORM", "cpu") == "cpu":
    # demos default to CPU: the image's accelerator tunnel can wedge
    # such that ANY axon backend init hangs forever (not fails), and
    # env vars cannot pin the platform here (sitecustomize sets it via
    # jax config at interpreter start). Set MADSIM_DEMO_PLATFORM=default
    # to run on the accelerator when the tunnel is known-good.
    jax.config.update("jax_platforms", "cpu")

from madsim_tpu.engine import EngineConfig, search_seeds
from madsim_tpu.models import make_kvchaos


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    writes, n_replicas = 5, 4
    wl = make_kvchaos(writes=writes, n_replicas=n_replicas)
    cfg = EngineConfig(pool_size=48, loss_p=0.02)

    def every_replica_fully_applied(view):
        # replica rows are 1..n_replicas; column 1 counts applied REPL
        # messages. RAM-only replicas lose the counter when chaos kills
        # them.
        replicas = view["node_state"][:, 1 : 1 + n_replicas, 1]
        return (replicas >= writes).all(axis=1)

    t0 = time.perf_counter()  # lint: allow(wall-clock)
    # compact=True: the seed-compaction path (identical verdicts and
    # traces; the invariant only reads node_state, well within the
    # banked view)
    report = search_seeds(
        wl, cfg, every_replica_fully_applied,
        n_seeds=n_seeds, max_steps=900, compact=True,
    )
    wall = time.perf_counter() - t0  # lint: allow(wall-clock)
    print(report.banner(limit=5))
    print(
        f"searched {n_seeds} schedules in {wall:.2f}s "
        f"({n_seeds / wall:,.0f} schedules/s), {report.steps} engine steps"
    )

    if report.failing_seeds.size:
        bad = int(report.failing_seeds[0])
        solo = search_seeds(
            wl, cfg, every_replica_fully_applied,
            n_seeds=1, max_steps=900, seed_base=bad,
        )
        assert solo.failing_seeds.tolist() == [bad]
        print(f"seed {bad} reproduced in isolation (identical trace)")

        # the debug loop's last mile: replay the failing schedule into a
        # readable timeline (engine/replay.py — the C++ oracle logs the
        # exact tuples the trace hash folds, so this story IS the trace)
        from madsim_tpu.engine import format_timeline, refold, replay

        events, res = replay(
            wl, cfg, bad, 900, writes=writes, n_replicas=n_replicas
        )
        assert refold(events, wl) == res.trace
        tail = events[-12:]
        print(f"\nlast {len(tail)} of {len(events)} events of seed {bad}:")
        print(format_timeline(tail, res, wl))


if __name__ == "__main__":
    main()
