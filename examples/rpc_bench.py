"""RPC benchmark harness — the criterion bench analog (C31).

The reference defines (but never records) two workloads on its *std*
runtime (madsim/benches/rpc.rs:11-55): empty-RPC latency and RPC
throughput with 16 B - 1 MiB payloads over real TCP loopback. Same
workloads here on the std backend:

    python examples/rpc_bench.py
"""

import asyncio
import sys
import time

sys.path.insert(0, ".")

from madsim_tpu.std import net as std_net


class Empty:
    pass


class Payload:
    def __init__(self, n):
        self.n = n


async def main():
    server = await std_net.Endpoint.bind("127.0.0.1:0")
    client = await std_net.Endpoint.bind("127.0.0.1:0")

    async def empty(req):
        return None

    async def payload(req, data):
        return len(data), data

    server.add_rpc_handler(Empty, empty)
    server.add_rpc_handler_with_data(Payload, payload)
    addr = server.local_addr

    # empty-RPC latency (rpc.rs:11-26)
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        await client.call(addr, Empty())
    dt = time.perf_counter() - t0
    print(f"empty rpc: {dt / n * 1e6:.1f} us/op  ({n / dt:.0f} op/s)")

    # payload throughput 16 B - 1 MiB (rpc.rs:28-55)
    for size in (16, 256, 4096, 65536, 1 << 20):
        data = b"\x00" * size
        reps = max(4, min(500, (64 << 20) // max(size, 1) // 8))
        t0 = time.perf_counter()
        for _ in range(reps):
            got_n, _ = await client.call_with_data(addr, Payload(size), data)
            assert got_n == size
        dt = time.perf_counter() - t0
        mb = size * reps * 2 / 1e6  # both directions
        print(
            f"payload {size:>8}B: {dt / reps * 1e6:>8.1f} us/op  "
            f"{mb / dt:>8.1f} MB/s"
        )

    await server.close()
    await client.close()


if __name__ == "__main__":
    asyncio.run(main())
