"""RPC benchmark harness — the criterion bench analog (C31).

The reference defines (but never records) two workloads on its *std*
runtime (madsim/benches/rpc.rs:11-55): empty-RPC latency and RPC
throughput with 16 B - 1 MiB payloads over real TCP loopback. Same
workloads here on the std backend, then a transport-level comparison of
the native endpoints — C++ epoll TCP (C26) vs the shared-memory fast
path (the UCX/eRPC role, C27/C28):

    python examples/rpc_bench.py
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import asyncio
import ctypes
import sys
import time


from madsim_tpu.std import net as std_net


class Empty:
    pass


class Payload:
    def __init__(self, n):
        self.n = n


async def main():
    server = await std_net.Endpoint.bind("127.0.0.1:0")
    client = await std_net.Endpoint.bind("127.0.0.1:0")

    async def empty(req):
        return None

    async def payload(req, data):
        return len(data), data

    server.add_rpc_handler(Empty, empty)
    server.add_rpc_handler_with_data(Payload, payload)
    addr = server.local_addr

    # empty-RPC latency (rpc.rs:11-26)
    n = 2000
    t0 = time.perf_counter()  # lint: allow(wall-clock)
    for _ in range(n):
        await client.call(addr, Empty())
    dt = time.perf_counter() - t0  # lint: allow(wall-clock)
    print(f"empty rpc: {dt / n * 1e6:.1f} us/op  ({n / dt:.0f} op/s)")

    # payload throughput 16 B - 1 MiB (rpc.rs:28-55)
    for size in (16, 256, 4096, 65536, 1 << 20):
        data = b"\x00" * size
        reps = max(4, min(500, (64 << 20) // max(size, 1) // 8))
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        for _ in range(reps):
            got_n, _ = await client.call_with_data(addr, Payload(size), data)
            assert got_n == size
        dt = time.perf_counter() - t0  # lint: allow(wall-clock)
        mb = size * reps * 2 / 1e6  # both directions
        print(
            f"payload {size:>8}B: {dt / reps * 1e6:>8.1f} us/op  "
            f"{mb / dt:>8.1f} MB/s"
        )

    await server.close()
    await client.close()


def _raw(mod, prefix):
    lib = mod._load()
    return (
        getattr(lib, prefix + "bind"),
        getattr(lib, prefix + "send"),
        getattr(lib, prefix + "recv"),
        getattr(lib, prefix + "msg_free"),
        getattr(lib, prefix + "shutdown"),
        getattr(lib, prefix + "free"),
    )


def native_transport_bench():
    """Head-to-head: epoll TCP vs io_uring TCP vs shm ring, C ABI level."""
    try:
        from madsim_tpu.std import fastpath
        from madsim_tpu.std import native as native_mod
        from madsim_tpu.std import uring as uring_mod
    except Exception as e:  # toolchain missing
        print(f"(native transports unavailable: {e})")
        return
    if not (native_mod.available() and fastpath.available()):
        print("(native toolchain unavailable; skipping transport bench)")
        return
    rows = [
        ("epoll-tcp", native_mod, "msep_"),
        ("shm-ring ", fastpath, "shmep_"),
    ]
    if uring_mod.available():
        rows.insert(1, ("uring-tcp", uring_mod, "urep_"))
    for label, mod, prefix in rows:
        bind, send, recv, free, shutdown, dealloc = _raw(mod, prefix)
        pa, pb = ctypes.c_int(0), ctypes.c_int(0)
        a = bind(b"127.0.0.1", 0, ctypes.byref(pa))
        b = bind(b"127.0.0.1", 0, ctypes.byref(pb))
        try:
            send(a, b"127.0.0.1", pb.value, 1, b"x", 1)
            free(recv(b, 1, 5000))
            n = 2000
            t0 = time.perf_counter()  # lint: allow(wall-clock)
            for _ in range(n):
                send(a, b"127.0.0.1", pb.value, 1, b"x", 1)
                free(recv(b, 1, 5000))
                send(b, b"127.0.0.1", pa.value, 2, b"y", 1)
                free(recv(a, 2, 5000))
            rtt = (time.perf_counter() - t0) / n  # lint: allow(wall-clock)
            blob = b"z" * 65536
            reps = 2000
            t0 = time.perf_counter()  # lint: allow(wall-clock)
            sent = received = 0
            while received < reps:
                while sent < reps and sent - received < 32:
                    send(a, b"127.0.0.1", pb.value, 3, blob, len(blob))
                    sent += 1
                free(recv(b, 3, 10000))
                received += 1
            dt = time.perf_counter() - t0  # lint: allow(wall-clock)
            print(
                f"{label}: rtt {rtt * 1e6:>6.1f} us   "
                f"64KiB one-way {len(blob) * reps / dt / 1e9:>5.2f} GB/s"
            )
        finally:
            shutdown(a)
            shutdown(b)
            dealloc(a)
            dealloc(b)


if __name__ == "__main__":
    asyncio.run(main())
    native_transport_bench()
