"""Head-to-head: VMEM-resident pallas runner vs the XLA-scheduled loop.

Times `engine.vmem.make_run_vmem` against `make_run` (same lockstep
step count, same seeds) on the current backend and prints one JSON
line per configuration plus a verdict line. Run on TPU via
tools/tpu_chain.sh (last step); on CPU the kernel interprets, so the
numbers only validate plumbing, not performance.

Usage: python examples/vmem_probe.py [n_seeds] [n_steps] [block_seeds]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path)

import dataclasses
import json
import sys
import time

import numpy as np

import jax

from madsim_tpu.engine import EngineConfig, SimState, make_init, make_run
from madsim_tpu.engine.vmem import make_run_vmem
from madsim_tpu.models import BENCH_SPECS

N_SEEDS = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
N_STEPS = int(sys.argv[2]) if len(sys.argv) > 2 else 64
BLOCK = int(sys.argv[3]) if len(sys.argv) > 3 else 2048
REPEATS = 3


def timed(tag, fn, state):
    jax.block_until_ready(fn(state))  # compile
    walls = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        out = jax.block_until_ready(fn(state))
        walls.append(time.perf_counter() - t0)  # lint: allow(wall-clock)
    wall = float(np.median(walls))
    rec = {
        "variant": tag,
        "wall_s_median": round(wall, 4),
        "walls_s": [round(w, 4) for w in walls],
        "ns_per_seed_step": round(wall / N_SEEDS / N_STEPS * 1e9, 3),
    }
    print(json.dumps(rec), flush=True)
    return out, wall


def main():
    mk, cfg_kw, _, _ = BENCH_SPECS["raft"]
    wl, cfg = mk(), EngineConfig(**cfg_kw)
    platform = jax.devices()[0].platform
    print(json.dumps({
        "platform": platform, "n_seeds": N_SEEDS, "n_steps": N_STEPS,
        "block_seeds": BLOCK,
    }), flush=True)
    st = make_init(wl, cfg)(np.arange(N_SEEDS, dtype=np.uint64))

    plain_out, plain_wall = timed("xla_loop", jax.jit(make_run(wl, cfg, N_STEPS)), st)
    vmem_out, vmem_wall = timed(
        "vmem_kernel",
        jax.jit(make_run_vmem(wl, cfg, N_STEPS, block_seeds=BLOCK)),
        st,
    )

    identical = all(
        np.array_equal(
            np.asarray(getattr(plain_out, f.name)),
            np.asarray(getattr(vmem_out, f.name)),
        )
        for f in dataclasses.fields(SimState)
    )
    print(json.dumps({
        "verdict": {
            "identical": identical,
            "speedup_vmem_over_xla": round(plain_wall / vmem_wall, 3),
            "platform": platform,
        }
    }), flush=True)


if __name__ == "__main__":
    main()
