"""A replicated raft KV store on the single-seed runtime — the
MadRaft-style application the reference ecosystem is built to test.

The reference's flagship downstream use is exactly this shape: a real
consensus implementation driven through simulated chaos (madsim's
README points at MadRaft; the in-tree analog is the tonic-example crash
tests, tonic-example/src/server.rs:283-405). This example implements
raft itself — randomized elections, log replication, fsync-durable
persistent state, a KV state machine — against the PUBLIC single-seed
API only:

- RPC via the ``@service``/``@rpc`` macro over ``Endpoint``
  (net/service.py; the #[madsim::service] analog),
- randomized election timeouts from the interposed stdlib ``random``
  (deterministic per seed, runtime/intercept.py),
- persistent (currentTerm, votedFor, log[]) written through the
  simulated fs with ``sync_all`` — node kills roll unsynced writes
  back (fs.py power-fail semantics, the reference's fs.rs:51 intent),
  so raft's crash-recovery argument rests on real fsync points,
- chaos from the supervisor: ``Handle.kill``/``restart`` replay the
  node's init task, which reloads state from disk (task.rs:279-291
  restart semantics).

Run it:  MADSIM_TEST_SEED=1 python examples/raft_kv.py
The safety/liveness invariants are asserted by tests/test_raft_example.py.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import pickle
import random

import madsim_tpu as ms
from madsim_tpu import check, fs
from madsim_tpu.net import Endpoint
from madsim_tpu.net.service import rpc, service
from madsim_tpu.runtime import Elapsed

__all__ = [
    "RaftPeer", "ClusterMonitor", "spawn_cluster", "spawn_server",
    "client_put", "client_get", "client_add_server",
    "client_remove_server", "N_PEERS", "peer_addr",
]

N_PEERS = 5
PORT = 9100
ELECTION_TIMEOUT = (0.15, 0.30)   # s, randomized per wait (raft §5.2)
HEARTBEAT = 0.05                  # s
STATE_FILE = "raft_state"


def peer_ip(i: int) -> str:
    return f"10.0.1.{i + 1}"


def peer_addr(i: int) -> str:
    return f"{peer_ip(i)}:{PORT}"


# ---------------------------------------------------------------- messages
class RequestVote:
    def __init__(self, term, candidate, last_log_idx, last_log_term):
        self.term = term
        self.candidate = candidate
        self.last_log_idx = last_log_idx
        self.last_log_term = last_log_term


class VoteReply:
    def __init__(self, term, granted):
        self.term = term
        self.granted = granted


class AppendEntries:
    def __init__(self, term, leader, prev_idx, prev_term, entries, commit):
        self.term = term
        self.leader = leader
        self.prev_idx = prev_idx
        self.prev_term = prev_term
        self.entries = entries      # list[(term, cmd)]; cmd = (op, key, val)
        self.commit = commit


class AppendReply:
    def __init__(self, term, ok, match_idx):
        self.term = term
        self.ok = ok
        self.match_idx = match_idx


class ClientPut:
    def __init__(self, key, val):
        self.key = key
        self.val = val


class ClientGet:
    def __init__(self, key):
        self.key = key


class AddServer:
    """Single-server membership change (Ongaro thesis §4.1): add sid to
    the cluster config. One change at a time."""

    def __init__(self, sid):
        self.sid = sid


class RemoveServer:
    def __init__(self, sid):
        self.sid = sid


class Redirect:
    """Not the leader; carries a hint (the reference pattern: clients
    probe the cluster, tonic-example drives a fixed address)."""

    def __init__(self, hint):
        self.hint = hint


class ClusterMonitor:
    """Test instrumentation shared across nodes (the analog of the
    reference tests' static atomics, tonic-example/src/server.rs:283)."""

    def __init__(self):
        self.leaders_by_term: dict[int, set[int]] = {}
        self.peers: dict[int, "RaftPeer"] = {}

    def note_leader(self, term: int, who: int) -> None:
        self.leaders_by_term.setdefault(term, set()).add(who)


# ---------------------------------------------------------------- the peer
@service
class RaftPeer:
    """One raft peer. All state transitions run on the node's
    single-threaded executor; awaits are the only interleave points, so
    handler bodies between awaits are atomic."""

    def __init__(self, me: int, monitor: ClusterMonitor):
        self.me = me
        self.monitor = monitor
        # persistent (raft fig. 2): reloaded by load() on restart
        self.term = 0
        self.voted_for = None
        self.log = []               # [(term, cmd)]; 1-based indexing helpers
        # volatile
        self.role = "follower"
        self.commit = 0
        self.applied = 0
        self.kv = {}
        self.leader_hint = None
        self.heard_from_leader = False
        self.last_leader_ns = -(10 ** 18)   # leader-stickiness guard clock
        self.cfg_idx = 0            # index of the latest config entry (0=none)
        self.apply_waiters = {}     # log idx -> (term, SimFuture)
        monitor.peers[me] = self

    # ---- persistence (fsync-durable; kills roll back unsynced writes)
    async def save(self) -> None:
        f = await fs.File.open_or_create(STATE_FILE)
        blob = pickle.dumps((self.term, self.voted_for, self.log))
        await f.set_len(0)
        await f.write_all_at(blob, 0)
        await f.sync_all()

    async def load(self) -> None:
        try:
            blob = await fs.read(STATE_FILE)
        except FileNotFoundError:
            return
        if blob:
            self.term, self.voted_for, self.log = pickle.loads(blob)
            self.cfg_idx = self._scan_cfg()

    # ---- log helpers (1-based: index 0 is the empty sentinel)
    def last_idx(self) -> int:
        return len(self.log)

    def term_at(self, idx: int) -> int:
        return self.log[idx - 1][0] if 1 <= idx <= len(self.log) else 0

    def up_to_date(self, m: RequestVote) -> bool:
        mine = (self.term_at(self.last_idx()), self.last_idx())
        return (m.last_log_term, m.last_log_idx) >= mine

    # ---- membership (single-server changes, Ongaro thesis §4.1-4.2).
    # A server uses the LATEST config entry in its log, committed or
    # not; configs are ordinary log entries ("config", members). The
    # latest config index is cached (cfg_idx) so the hot paths
    # (heartbeat, campaign) stay O(1) instead of rescanning the log.
    def _scan_cfg(self) -> int:
        for i in range(self.last_idx(), 0, -1):
            if self.log[i - 1][1][0] == "config":
                return i
        return 0

    def _log_append(self, entry) -> None:
        self.log.append(entry)
        if entry[1][0] == "config":
            self.cfg_idx = len(self.log)

    def _log_truncate(self, from_idx: int) -> None:
        """Delete entries from 1-based ``from_idx`` onward."""
        del self.log[from_idx - 1:]
        if self.cfg_idx >= from_idx:
            self.cfg_idx = self._scan_cfg()

    def config_at(self, idx: int) -> frozenset:
        if self.cfg_idx and self.cfg_idx <= idx:
            return frozenset(self.log[self.cfg_idx - 1][1][1])
        # rare: asking below a config entry still in flight above idx
        for i in range(min(idx, self.last_idx()), 0, -1):
            cmd = self.log[i - 1][1]
            if cmd[0] == "config":
                return frozenset(cmd[1])
        return frozenset(range(N_PEERS))

    def current_config(self) -> frozenset:
        if self.cfg_idx:
            return frozenset(self.log[self.cfg_idx - 1][1][1])
        return frozenset(range(N_PEERS))

    def config_pending(self) -> bool:
        """An uncommitted config entry forbids another change."""
        return any(
            self.log[i - 1][1][0] == "config"
            for i in range(self.commit + 1, self.last_idx() + 1)
        )

    def become_follower(self, term: int) -> None:
        # one vote per term: votedFor only resets when the term advances
        # (a same-term step-down — candidate hearing the term's leader —
        # must keep its vote, raft fig. 2)
        if term != self.term:
            self.voted_for = None
        self.term = term
        self.role = "follower"

    # ---- RPC handlers
    @rpc
    async def request_vote(self, m: RequestVote):
        # Leader stickiness (thesis §4.2.3): while we believe a current
        # leader exists — we ARE it, or we heard one within the minimum
        # election timeout — DISREGARD RequestVote entirely, no term
        # update. This is what makes removed servers non-disruptive:
        # their rising terms cannot depose a working leader (a
        # partitioned stale leader still steps down via the higher term
        # on AppendEntries replies once it reaches a member).
        if self.role == "leader" \
                or ms.now_ns() - self.last_leader_ns < int(ELECTION_TIMEOUT[0] * 1e9):
            return VoteReply(self.term, False)
        if m.term > self.term:
            self.become_follower(m.term)
            await self.save()
        granted = (
            m.term == self.term
            and self.voted_for in (None, m.candidate)
            and self.up_to_date(m)
        )
        if granted:
            self.voted_for = m.candidate
            self.heard_from_leader = True   # reset election timer on grant
            await self.save()
        return VoteReply(self.term, granted)

    @rpc
    async def append_entries(self, m: AppendEntries):
        if m.term < self.term:
            return AppendReply(self.term, False, 0)
        if m.term > self.term or self.role != "follower":
            self.become_follower(m.term)
            await self.save()
        self.heard_from_leader = True
        self.last_leader_ns = ms.now_ns()
        self.leader_hint = m.leader
        if m.prev_idx > self.last_idx() or self.term_at(m.prev_idx) != m.prev_term:
            return AppendReply(self.term, False, 0)
        # truncate conflicts, append the rest (raft fig. 2 AppendEntries 3-4)
        changed = False
        for k, ent in enumerate(m.entries):
            idx = m.prev_idx + 1 + k
            if idx <= self.last_idx():
                if self.term_at(idx) != ent[0]:
                    self._log_truncate(idx)
                    self._log_append(ent)
                    changed = True
            else:
                self._log_append(ent)
                changed = True
        if changed:
            await self.save()
        match = m.prev_idx + len(m.entries)
        if m.commit > self.commit:
            self.commit = min(m.commit, self.last_idx())
            self.apply_committed()
        return AppendReply(self.term, True, match)

    @rpc
    async def client_put(self, m: ClientPut):
        if self.role != "leader":
            return Redirect(self.leader_hint)
        self._log_append((self.term, ("put", m.key, m.val)))
        idx = self.last_idx()
        await self.save()
        fut = ms.SimFuture(name=f"apply-{idx}")
        # key the waiter by (index, term): if this entry is truncated by
        # a new leader and a DIFFERENT entry commits at idx, the waiter
        # must NOT ack — it resolves to a Redirect so the client retries
        self.apply_waiters[idx] = (self.term, fut)
        return await fut            # resolves when committed+applied

    @rpc
    async def client_get(self, m: ClientGet):
        # leader-local read after a committed no-op would be the
        # linearizable form; committed-state read is what the tests
        # assert against (they only read after quiescence)
        if self.role != "leader":
            return Redirect(self.leader_hint)
        return self.kv.get(m.key)

    @rpc
    async def add_server(self, m: AddServer):
        return await self._reconfig(lambda c: c | {m.sid})

    @rpc
    async def remove_server(self, m: RemoveServer):
        return await self._reconfig(lambda c: c - {m.sid})

    async def _reconfig(self, f):
        """Append a single-server config change; reply once committed
        (thesis §4.1: one uncommitted change at a time)."""
        if self.role != "leader":
            return Redirect(self.leader_hint)
        if self.config_pending():
            return Redirect(self.me)    # change in flight; client retries
        new = frozenset(f(self.current_config()))
        if not new or new == self.current_config():
            return "ok"                 # no-op change
        self._log_append((self.term, ("config", tuple(sorted(new)))))
        idx = self.last_idx()
        await self.save()
        fut = ms.SimFuture(name=f"cfg-{idx}")
        self.apply_waiters[idx] = (self.term, fut)
        return await fut

    # ---- apply
    def apply_committed(self) -> None:
        while self.applied < self.commit:
            self.applied += 1
            t, cmd = self.log[self.applied - 1]
            if cmd[0] == "put":
                _, key, val = cmd
                self.kv[key] = val
                result = val
            else:                       # ("config", members): no kv effect
                result = "ok"
            entry = self.apply_waiters.pop(self.applied, None)
            if entry is not None:
                waited_term, w = entry
                if not w.done():
                    if waited_term == t:
                        w.set_result(result)
                    else:
                        # the entry the client appended was replaced —
                        # its write did NOT commit; make the client retry
                        w.set_result(Redirect(self.leader_hint))

    # ---- roles
    async def run(self) -> None:
        """The node's init task: restart re-enters here and load()
        restores the synced persistent state (crash recovery)."""
        await self.load()
        ep = await self.serve(f"0.0.0.0:{PORT}")
        while True:
            if self.role == "leader":
                await self.lead(ep)
            else:
                await self.follow(ep)

    async def follow(self, ep: Endpoint) -> None:
        self.heard_from_leader = False
        await ms.sleep(random.uniform(*ELECTION_TIMEOUT))
        if self.heard_from_leader:
            return
        if self.me not in self.current_config():
            return      # a non-member never campaigns (thesis §4.2.2)
        await self.campaign(ep)

    async def campaign(self, ep: Endpoint) -> None:
        self.role = "candidate"
        self.term += 1
        self.voted_for = self.me
        await self.save()
        term = self.term
        members = self.current_config()
        req = RequestVote(term, self.me, self.last_idx(),
                          self.term_at(self.last_idx()))
        votes = 1       # self (campaign is members-only)

        async def ask(i):
            try:
                return await ep.call(peer_addr(i), req, timeout=0.1)
            except Elapsed:
                return None

        pending = [ms.spawn(ask(i)) for i in sorted(members) if i != self.me]
        for h in pending:
            r = await h
            if r is None or self.term != term or self.role != "candidate":
                continue
            if r.term > self.term:
                self.become_follower(r.term)
                await self.save()
                return
            if r.granted:
                votes += 1
        if self.role == "candidate" and self.term == term \
                and votes * 2 > len(members):
            self.role = "leader"
            self.leader_hint = self.me
            self.monitor.note_leader(term, self.me)
            self.next_idx = {}
            self.match_idx = {}
            # current-term no-op (raft §8 / thesis §3.6.1): lets the
            # leader commit prior-term entries — without it, an
            # uncommitted config entry inherited from a dead leader
            # would wedge reconfiguration until an unrelated client put
            self._log_append((self.term, ("noop",)))
            await self.save()

    async def lead(self, ep: Endpoint) -> None:
        term = self.term
        members = self.current_config()

        async def replicate(i):
            prev = self.next_idx.setdefault(i, self.last_idx() + 1) - 1
            entries = self.log[prev:]
            req = AppendEntries(term, self.me, prev, self.term_at(prev),
                                entries, self.commit)
            try:
                r = await ep.call(peer_addr(i), req, timeout=0.1)
            except Elapsed:
                return
            if self.term != term or self.role != "leader":
                return
            if r.term > self.term:
                self.become_follower(r.term)
                await self.save()
                return
            if r.ok:
                self.match_idx[i] = max(self.match_idx.get(i, 0), r.match_idx)
                self.next_idx[i] = self.match_idx[i] + 1
            else:
                self.next_idx[i] = max(1, self.next_idx[i] - 1)

        for i in sorted(members):
            if i != self.me:
                ms.spawn(replicate(i))
        # leader commit rule: majority of the CURRENT config matches AND
        # the entry is from the current term
        for n in range(self.last_idx(), self.commit, -1):
            if self.term_at(n) != self.term:
                break
            count = (1 if self.me in members else 0) + sum(
                1 for i in members
                if i != self.me and self.match_idx.get(i, 0) >= n
            )
            if count * 2 > len(members):
                self.commit = n
                self.apply_committed()
                break
        # a leader removed by a now-COMMITTED config steps down
        # (thesis §4.2.2)
        if self.me not in self.config_at(self.commit):
            self.role = "follower"
            return
        await ms.sleep(HEARTBEAT)


# ---------------------------------------------------------------- harness
def spawn_server(h, monitor: ClusterMonitor, i: int):
    """One raft server node (also used to bring up NEW servers joining
    via AddServer)."""
    async def init():
        await RaftPeer(i, monitor).run()

    return (
        h.create_node().name(f"raft-{i}").ip(peer_ip(i))
        .init(init).build()
    )


def spawn_cluster(h, monitor: ClusterMonitor):
    """Create the 5 initial peer nodes; returns their NodeHandles
    (kill/restart them through the supervisor, tonic-example
    server_crash pattern)."""
    return [spawn_server(h, monitor, i) for i in range(N_PEERS)]


async def _client_call(ep: Endpoint, req, retries: int = 60, servers=None):
    """Probe for the leader with redirects + retries (clients outlive
    elections, leader crashes and reconfigurations)."""
    servers = list(servers) if servers is not None else list(range(N_PEERS))
    hint = None
    for _ in range(retries):
        order = [hint] if hint is not None else []
        order += [i for i in servers if i != hint]
        for i in order:
            try:
                r = await ep.call(peer_addr(i), req, timeout=0.25)
            except Elapsed:
                continue
            if isinstance(r, Redirect):
                hint = r.hint
                continue
            return r
        await ms.sleep(0.1)
    raise TimeoutError(f"no leader answered {type(req).__name__}")


async def client_put(ep: Endpoint, key, val, servers=None):
    return await _client_call(ep, ClientPut(key, val), servers=servers)


async def client_get(ep: Endpoint, key, servers=None):
    return await _client_call(ep, ClientGet(key), servers=servers)


async def client_add_server(ep: Endpoint, sid, servers=None):
    return await _client_call(ep, AddServer(sid), servers=servers)


async def client_remove_server(ep: Endpoint, sid, servers=None):
    return await _client_call(ep, RemoveServer(sid), servers=servers)


@ms.main
async def main():
    h = ms.Handle.current()
    monitor = ClusterMonitor()
    nodes = spawn_cluster(h, monitor)
    client = h.create_node().name("client").ip("10.0.9.9").build()

    # the same operation-history checker that validates the batched
    # engine's recorded histories (madsim_tpu.check) validates this
    # asyncio-level app: record every client op, Wing–Gong check at end
    rec = check.Recorder()
    key_ids = {"a": 0, "b": 1, "c": 2}

    async def put(ep, key, val):
        tok = rec.invoke(client=0, op=check.OP_WRITE,
                         key=key_ids[key], arg=val)
        r = await client_put(ep, key, val)
        rec.respond(tok, ok=True, value=val)
        return r

    async def get(ep, key):
        tok = rec.invoke(client=0, op=check.OP_READ, key=key_ids[key])
        v = await client_get(ep, key)
        rec.respond(tok, ok=True, value=0 if v is None else v)
        return v

    async def run():
        ep = await Endpoint.bind("0.0.0.0:0")
        await put(ep, "a", 1)
        await put(ep, "b", 2)
        print(f"t={ms.now_ns()/1e9:.3f}s  put a=1 b=2 committed")
        # crash the current leader, cluster must recover and keep data
        lead_term = max(monitor.leaders_by_term)
        (who,) = monitor.leaders_by_term[lead_term]
        h.kill(nodes[who])
        print(f"t={ms.now_ns()/1e9:.3f}s  killed leader raft-{who}")
        await put(ep, "c", 3)
        assert await get(ep, "a") == 1
        assert await get(ep, "c") == 3
        h.restart(nodes[who])
        print(f"t={ms.now_ns()/1e9:.3f}s  new leader serving; a=1 c=3 intact")
        for term in sorted(monitor.leaders_by_term):
            assert len(monitor.leaders_by_term[term]) <= 1, "election safety"
        print("election safety held:",
              {t: sorted(w) for t, w in monitor.leaders_by_term.items()})
        lin = rec.check_kv()
        assert lin.ok, f"client history not linearizable: {lin.reason}"
        print(f"client history linearizable: {lin.n_ops} ops "
              f"(madsim_tpu.check.Recorder)")

    await client.spawn(run())


if __name__ == "__main__":
    main()
