"""Ablation profile of one batched engine step (the SCALING.md evidence).

Times the full jitted raft step against stripped variants that isolate
the step's cost centers (pop/argmin, threefry draws, the lax.switch
dispatch, the emit scatters) at a given seed count, so the engine
optimization work attacks measured hot spots instead of guesses.

Usage:  python examples/profile_step.py [n_seeds] [platform]
Prints one JSON object per measurement plus a summary line.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax

if len(sys.argv) > 2 and sys.argv[2] == "cpu":
    # env vars cannot pin the platform here: the image's sitecustomize
    # registers the axon plugin and sets the platform via jax config at
    # interpreter start, so only a config update wins (and with a
    # wedged tunnel, any axon init would hang forever)
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax import lax

from madsim_tpu.engine import EngineConfig, make_init, make_step
from madsim_tpu.engine.core import _INF_NS, _meta_kind, _meta_node
from madsim_tpu.engine.rng import PURPOSE_LATENCY, PURPOSE_POLL_COST, Draw
from madsim_tpu.models import make_raft

N_SEEDS = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
N_STEPS = 100
REPEATS = 3


def timed(name, fn, state):
    """Median wall time of REPEATS runs of jitted fn (scanned N_STEPS)."""
    jfn = jax.jit(fn)
    jax.block_until_ready(jfn(state))  # compile
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        jax.block_until_ready(jfn(state))
        times.append(time.perf_counter() - t0)
    wall = sorted(times)[len(times) // 2]
    us_per_step = wall / N_STEPS * 1e6
    rec = {
        "variant": name,
        "wall_s": round(wall, 4),
        "us_per_step": round(us_per_step, 2),
        "ns_per_seed_step": round(us_per_step * 1e3 / N_SEEDS, 3),
    }
    print(json.dumps(rec), flush=True)
    return rec


def scan_n(body):
    def run(st):
        def f(s, _):
            return body(s), None

        out, _ = lax.scan(f, st, None, length=N_STEPS)
        return out

    return run


def main():
    wl = make_raft()
    cfg = EngineConfig(pool_size=48, loss_p=0.02)
    k = wl.max_emits
    init = make_init(wl, cfg)
    state = init(np.arange(N_SEEDS, dtype=np.uint64))
    state = jax.block_until_ready(state)
    platform = jax.devices()[0].platform
    print(json.dumps({"platform": platform, "n_seeds": N_SEEDS, "pool": cfg.pool_size,
                      "max_emits": k, "n_steps": N_STEPS}), flush=True)

    results = {}

    # 1. the real thing
    step = jax.vmap(make_step(wl, cfg))
    results["full_step"] = timed("full_step", scan_n(step), state)

    # 2. pop only: argmin over the masked int64 pool
    def pop_only(st):
        tmask = jnp.where(st.ev_valid, st.ev_time, _INF_NS)
        i = jnp.argmin(tmask, axis=1)
        rows = jnp.arange(st.ev_time.shape[0])
        now = jnp.maximum(st.now, st.ev_time[rows, i])
        return st.__class__(**{**st.__dict__, "now": now})

    results["pop_argmin"] = timed("pop_argmin", scan_n(pop_only), state)

    # 3. RNG draws: poll cost + K paired latency/loss blocks (bits2)
    def draws_only(st):
        def one(seed, stp):
            draw = Draw(seed, stp)
            cost = draw.uniform_int(cfg.proc_min_ns, cfg.proc_max_ns, PURPOSE_POLL_COST)
            slot_ix = jnp.arange(k, dtype=jnp.uint32)
            lat, loss = jax.vmap(
                lambda s: draw.bits2(jnp.uint32(PURPOSE_LATENCY) + s)
            )(slot_ix)
            return cost + lat.astype(jnp.int64).sum() + loss.astype(jnp.int64).sum()

        extra = jax.vmap(one)(st.seed, st.step)
        return st.__class__(**{**st.__dict__, "now": st.now + extra,
                               "step": st.step + jnp.uint32(1)})

    results["rng_draws"] = timed("rng_draws", scan_n(draws_only), state)

    # 4. gathers: the per-seed dynamic reads the dispatch needs
    def gathers_only(st):
        rows = jnp.arange(st.ev_time.shape[0])
        tmask = jnp.where(st.ev_valid, st.ev_time, _INF_NS)
        i = jnp.argmin(tmask, axis=1)
        meta = st.ev_meta[rows, i]
        kind = _meta_kind(meta)
        dst = _meta_node(meta)
        dst_c = jnp.clip(dst, 0, st.node_state.shape[1] - 1)
        args = st.ev_args[rows, i]
        nstate = st.node_state[rows, dst_c]
        alive = st.alive[rows, dst_c]
        acc = (kind + dst + args.sum(-1) + nstate.sum(-1) + alive).astype(jnp.int64)
        return st.__class__(**{**st.__dict__, "now": st.now + acc})

    results["pop_gathers"] = timed("pop_gathers", scan_n(gathers_only), state)

    # 5. scatters: the emit-insertion writes (K slots into the E pool)
    def scatters_only(st):
        def one(ev_valid, ev_time, ev_meta, ev_args, stp):
            free = jnp.flatnonzero(~ev_valid, size=k, fill_value=ev_valid.shape[0])
            e_valid = jnp.ones((k,), jnp.bool_)
            slot = free
            return (
                ev_valid.at[slot].set(e_valid, mode="drop"),
                ev_time.at[slot].set(
                    jnp.full((k,), 7, ev_time.dtype), mode="drop"
                ),
                ev_meta.at[slot].set(jnp.full((k,), 1, jnp.uint32), mode="drop"),
                ev_args.at[slot].set(
                    jnp.zeros((k, ev_args.shape[-1]), jnp.int32), mode="drop"
                ),
            )

        ev_valid, ev_time, ev_meta, ev_args = jax.vmap(one)(
            st.ev_valid, st.ev_time, st.ev_meta, st.ev_args, st.step
        )
        return st.__class__(**{**st.__dict__, "ev_valid": ev_valid,
                               "ev_time": ev_time, "ev_meta": ev_meta,
                               "ev_args": ev_args})

    results["emit_scatters"] = timed("emit_scatters", scan_n(scatters_only), state)

    # (switch cost is measured by subtraction: full - pop - rng - gathers
    # - place; the branch table is internal to make_step)

    # 6. dense placement math alone (the scatter replacement)
    def place_only(st):
        def one(ev_valid, ev_time, stp):
            e_valid = jnp.ones((k,), jnp.bool_)
            e_time = jnp.full((k,), 7, ev_time.dtype)
            free_rank = jnp.cumsum(~ev_valid) - 1
            pos = jnp.cumsum(e_valid.astype(jnp.int32)) - 1
            match = (
                (~ev_valid)[:, None]
                & e_valid[None, :]
                & (free_rank[:, None] == pos[None, :])
            )
            match_any = jnp.any(match, axis=1)
            picked = jnp.sum(
                jnp.where(match, e_time[None, :], 0), axis=1
            ).astype(e_time.dtype)
            return ev_valid | match_any, jnp.where(match_any, picked, ev_time)

        ev_valid, ev_time = jax.vmap(one)(st.ev_valid, st.ev_time, st.step)
        return st.__class__(**{**st.__dict__, "ev_valid": ev_valid, "ev_time": ev_time})

    results["dense_place_2fields"] = timed(
        "dense_place_2fields", scan_n(place_only), state
    )

    full = results["full_step"]["us_per_step"]
    parts = {n: results[n]["us_per_step"] for n in results if n != "full_step"}
    print(json.dumps({
        "summary": {
            "platform": platform,
            "n_seeds": N_SEEDS,
            "full_us_per_step": full,
            "parts_us_per_step": parts,
            "unattributed_us_per_step": round(full - sum(parts.values()), 2),
        }
    }), flush=True)


if __name__ == "__main__":
    main()
