"""Ablation profile of one batched engine step (the SCALING.md evidence).

Times the full jitted raft step against stripped variants that isolate
the step's cost centers (pop/argmin, threefry draws, the lax.switch
dispatch, the emit scatters) at a given seed count, so the engine
optimization work attacks measured hot spots instead of guesses.

Usage:  python examples/profile_step.py [n_seeds] [platform]
Prints one JSON object per measurement plus a summary line.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import sys
import time

import numpy as np

import jax

if len(sys.argv) > 2 and sys.argv[2] == "cpu":
    # env vars cannot pin the platform here: the image's sitecustomize
    # registers the axon plugin and sets the platform via jax config at
    # interpreter start, so only a config update wins (and with a
    # wedged tunnel, any axon init would hang forever)
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax import lax

from madsim_tpu.engine import EngineConfig, make_init, make_step
from madsim_tpu.engine.core import _INF_NS, _meta_kind, _meta_node
from madsim_tpu.engine.rng import PURPOSE_LATENCY, PURPOSE_POLL_COST, Draw
from madsim_tpu.models import BENCH_SPECS

N_SEEDS = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
N_STEPS = 100  # calibration scan length; timed runs auto-size upward
REPEATS = 3
# every timed run is sized to at least this wall so remote-tunnel
# dispatch jitter (multi-100 ms) can't dominate a cell (SCALING.md §4)
TARGET_WALL_S = 5.0


def timed(name, body, state):
    """Median wall of REPEATS sized runs; each run is ONE dispatch of a
    scan long enough to hit TARGET_WALL_S (per-variant calibration —
    cheap variants get proportionally longer scans)."""
    cal = jax.jit(scan_n(body, N_STEPS))
    jax.block_until_ready(cal(state))  # compile
    t0 = time.perf_counter()  # lint: allow(wall-clock)
    jax.block_until_ready(cal(state))
    cal_wall = time.perf_counter() - t0  # lint: allow(wall-clock)

    steps = N_STEPS
    while cal_wall * (steps / N_STEPS) < TARGET_WALL_S and steps < 2_000_000:
        steps *= 2
    jfn = cal if steps == N_STEPS else jax.jit(scan_n(body, steps))
    # the warm-up of each sized program re-calibrates: a contaminated
    # first calibration (host contention, cache effects) otherwise
    # leaves the cell sub-second and jitter-dominated again. Each
    # re-jitted program is compiled (untimed) before its timed probe —
    # otherwise the compile wall would satisfy the target spuriously.
    for _ in range(6):
        jax.block_until_ready(jfn(state))  # compile / cache hit, untimed
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(jfn(state))
        warm = time.perf_counter() - t0  # lint: allow(wall-clock)
        if warm >= TARGET_WALL_S * 0.6 or steps >= 2_000_000:
            break
        per_step = warm / steps
        new_steps = steps
        while per_step * new_steps < TARGET_WALL_S and new_steps < 2_000_000:
            new_steps *= 2
        steps = new_steps
        jfn = jax.jit(scan_n(body, steps))
    jax.block_until_ready(jfn(state))  # compile, untimed (loop may exit
    # by exhaustion with a freshly re-jitted, never-executed program)
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(jfn(state))
        times.append(time.perf_counter() - t0)  # lint: allow(wall-clock)
    wall = sorted(times)[len(times) // 2]
    us_per_step = wall / steps * 1e6
    rec = {
        "variant": name,
        "scan_steps": steps,
        "wall_s": round(wall, 4),
        "spread_pct": round(100 * (max(times) - min(times)) / wall, 1),
        "us_per_step": round(us_per_step, 2),
        "ns_per_seed_step": round(us_per_step * 1e3 / N_SEEDS, 3),
    }
    print(json.dumps(rec), flush=True)
    return rec


def scan_n(body, length):
    def run(st):
        def f(s, _):
            return body(s), None

        out, _ = lax.scan(f, st, None, length=length)
        return out

    return run


def main():
    # the exact raft bench config (models.BENCH_SPECS), so the ablation
    # describes the same program bench.py times
    mk, cfg_kw, _, _ = BENCH_SPECS["raft"]
    wl = mk()
    cfg = EngineConfig(**cfg_kw)
    k = wl.max_emits
    init = make_init(wl, cfg)
    state = init(np.arange(N_SEEDS, dtype=np.uint64))
    state = jax.block_until_ready(state)
    platform = jax.devices()[0].platform
    print(json.dumps({"platform": platform, "n_seeds": N_SEEDS, "pool": cfg.pool_size,
                      "max_emits": k, "n_steps": N_STEPS}), flush=True)

    results = {}

    # 1. the real thing
    step = jax.vmap(make_step(wl, cfg))
    results["full_step"] = timed("full_step", step, state)

    # 2. pop only: argmin over the masked pool. The (now & 1) term makes
    # the input loop-VARIANT — without it the whole argmin is constant
    # across scan iterations and XLA hoists it, timing an empty loop.
    def pop_only(st):
        wob = (st.now & 1).astype(st.ev_time.dtype)[:, None]
        tmask = jnp.where(st.ev_valid, st.ev_time + wob, _INF_NS)
        i = jnp.argmin(tmask, axis=1)
        rows = jnp.arange(st.ev_time.shape[0])
        now = st.now + jnp.maximum(jnp.int64(1), st.ev_time[rows, i].astype(jnp.int64))
        return st.__class__(**{**st.__dict__, "now": now})

    results["pop_argmin"] = timed("pop_argmin", pop_only, state)

    # 3. RNG draws: poll cost + K paired latency/loss blocks (bits2)
    def draws_only(st):
        def one(seed, stp):
            draw = Draw(seed, stp)
            cost = draw.uniform_int(cfg.proc_min_ns, cfg.proc_max_ns, PURPOSE_POLL_COST)
            slot_ix = jnp.arange(k, dtype=jnp.uint32)
            lat, loss = jax.vmap(
                lambda s: draw.bits2(jnp.uint32(PURPOSE_LATENCY) + s)
            )(slot_ix)
            return cost + lat.astype(jnp.int64).sum() + loss.astype(jnp.int64).sum()

        extra = jax.vmap(one)(st.seed, st.step)
        return st.__class__(**{**st.__dict__, "now": st.now + extra,
                               "step": st.step + jnp.uint32(1)})

    results["rng_draws"] = timed("rng_draws", draws_only, state)

    # 4. gathers: the per-seed dynamic reads the dispatch needs (same
    # loop-variance wobble as pop_only — see the hoisting note there)
    def gathers_only(st):
        rows = jnp.arange(st.ev_time.shape[0])
        wob = (st.now & 1).astype(st.ev_time.dtype)[:, None]
        tmask = jnp.where(st.ev_valid, st.ev_time + wob, _INF_NS)
        i = jnp.argmin(tmask, axis=1)
        meta = st.ev_meta[rows, i]
        kind = _meta_kind(meta)
        dst = _meta_node(meta)
        dst_c = jnp.clip(dst, 0, st.node_state.shape[1] - 1)
        args = st.ev_args[rows, i]
        nstate = st.node_state[rows, dst_c]
        alive = st.alive[rows, dst_c]
        acc = (kind + dst + args.sum(-1) + nstate.sum(-1) + alive).astype(jnp.int64)
        return st.__class__(**{**st.__dict__, "now": st.now + acc})

    results["pop_gathers"] = timed("pop_gathers", gathers_only, state)

    # 5. scatters: the emit-insertion writes (K slots into the E pool)
    def scatters_only(st):
        def one(ev_valid, ev_time, ev_meta, ev_args, stp):
            free = jnp.flatnonzero(~ev_valid, size=k, fill_value=ev_valid.shape[0])
            e_valid = jnp.ones((k,), jnp.bool_)
            slot = free
            return (
                ev_valid.at[slot].set(e_valid, mode="drop"),
                ev_time.at[slot].set(
                    jnp.full((k,), 7, ev_time.dtype), mode="drop"
                ),
                ev_meta.at[slot].set(jnp.full((k,), 1, jnp.uint32), mode="drop"),
                ev_args.at[slot].set(
                    jnp.zeros((k, ev_args.shape[-1]), jnp.int32), mode="drop"
                ),
            )

        ev_valid, ev_time, ev_meta, ev_args = jax.vmap(one)(
            st.ev_valid, st.ev_time, st.ev_meta, st.ev_args, st.step
        )
        return st.__class__(**{**st.__dict__, "ev_valid": ev_valid,
                               "ev_time": ev_time, "ev_meta": ev_meta,
                               "ev_args": ev_args})

    results["emit_scatters"] = timed("emit_scatters", scatters_only, state)

    # (switch cost is measured by subtraction: full - pop - rng - gathers
    # - place; the branch table is internal to make_step)

    # 6. dense placement math alone (the scatter replacement)
    def place_only(st):
        def one(ev_valid, ev_time, stp):
            e_valid = jnp.ones((k,), jnp.bool_)
            e_time = jnp.full((k,), 7, ev_time.dtype)
            free_rank = jnp.cumsum(~ev_valid) - 1
            pos = jnp.cumsum(e_valid.astype(jnp.int32)) - 1
            match = (
                (~ev_valid)[:, None]
                & e_valid[None, :]
                & (free_rank[:, None] == pos[None, :])
            )
            match_any = jnp.any(match, axis=1)
            picked = jnp.sum(
                jnp.where(match, e_time[None, :], 0), axis=1
            ).astype(e_time.dtype)
            return ev_valid | match_any, jnp.where(match_any, picked, ev_time)

        ev_valid, ev_time = jax.vmap(one)(st.ev_valid, st.ev_time, st.step)
        return st.__class__(**{**st.__dict__, "ev_valid": ev_valid, "ev_time": ev_time})

    results["dense_place_2fields"] = timed(
        "dense_place_2fields", place_only, state
    )

    full = results["full_step"]["us_per_step"]
    parts = {n: results[n]["us_per_step"] for n in results if n != "full_step"}
    # the step uses ONE placement lowering (scatter on cpu, dense
    # elsewhere — make_step layout auto); both variants are measured
    # for comparison, but the decomposition must subtract only the
    # active one or the unattributed residue double-counts placement
    inactive = "dense_place_2fields" if platform == "cpu" else "emit_scatters"
    active_parts = {n: v for n, v in parts.items() if n != inactive}
    print(json.dumps({
        "summary": {
            "platform": platform,
            "n_seeds": N_SEEDS,
            "full_us_per_step": full,
            "parts_us_per_step": parts,
            "active_layout": "scatter" if platform == "cpu" else "dense",
            "unattributed_us_per_step": round(
                full - sum(active_parts.values()), 2
            ),
        }
    }), flush=True)


if __name__ == "__main__":
    main()
