"""Typed-RPC service example — the madsim/examples/rpc.rs analog (C31).

A KV store declared with the @service/@rpc decorators (the
``#[madsim::service]`` macro analog), served on a simulated node and
driven by a client with packet loss configured.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys


import madsim_tpu as ms
from madsim_tpu.net import Endpoint
from madsim_tpu.net.service import rpc, service


class Get:
    def __init__(self, key):
        self.key = key


class Put:
    def __init__(self, key, value):
        self.key = key
        self.value = value


@service
class KvStore:
    def __init__(self):
        self.data = {}

    @rpc
    async def get(self, req: Get):
        return self.data.get(req.key)

    @rpc
    async def put(self, req: Put):
        old = self.data.get(req.key)
        self.data[req.key] = req.value
        return old


@ms.main
async def main():
    h = ms.Handle.current()

    async def server():
        await KvStore().serve("0.0.0.0:7000")

    h.create_node().name("kv-server").ip("10.0.0.1").init(server).build()
    client = h.create_node().name("client").ip("10.0.0.2").build()

    async def run():
        await ms.sleep(0.1)
        ep = await Endpoint.bind("0.0.0.0:0")
        assert await ep.call("10.0.0.1:7000", Put("k", "v1")) is None
        assert await ep.call("10.0.0.1:7000", Get("k")) == "v1"
        assert await ep.call("10.0.0.1:7000", Put("k", "v2")) == "v1"
        print("kv roundtrips ok at", f"t={ms.now_ns() / 1e9:.3f}s")

    await client.spawn(run())


if __name__ == "__main__":
    main()
