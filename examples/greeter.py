"""End-to-end gRPC-style app — the tonic-example analog (C30).

The reference's tonic-example runs the same Greeter service as real
binaries and as seeded simulation tests (tonic-example/src/server.rs).
This example does both:

    python examples/greeter.py sim     # seeded simulation with chaos
    MADSIM_TEST_SEED=7 python examples/greeter.py sim   # pick the seed

The simulated run drives all four RPC shapes through a 3-node cluster,
kills the server mid-session, restarts it, and shows the client
recovering — the server_crash/client_crash scenarios of the reference's
test suite as a demo.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys


import madsim_tpu as ms
from madsim_tpu.services import grpc


class Greeter:
    SERVICE_NAME = "helloworld.Greeter"

    async def say_hello(self, request):
        return {"message": f"Hello {request.message['name']}!"}

    async def lots_of_replies(self, request):
        for i in range(3):
            await ms.sleep(0.05)
            yield {"message": f"reply #{i} for {request.message['name']}"}

    async def record_hellos(self, stream):
        names = [msg["name"] async for msg in stream]
        return {"message": f"Hello {', '.join(names)}!"}

    async def chat(self, stream):
        async for msg in stream:
            yield {"message": f"ack:{msg['name']}"}


@ms.main
async def sim_main():
    h = ms.Handle.current()

    async def serve():
        await grpc.Server.builder().add_service(Greeter()).serve("0.0.0.0:50051")

    server = h.create_node().name("server").ip("10.0.0.1").init(serve).build()
    client_node = h.create_node().name("client").ip("10.0.0.2").build()

    async def client():
        await ms.sleep(0.1)
        ch = await grpc.connect("10.0.0.1:50051")
        c = grpc.service_client(Greeter, ch)

        r = await c.say_hello({"name": "world"})
        print("unary          :", r["message"])

        stream = await c.lots_of_replies({"name": "world"})
        async for msg in stream:
            print("server-stream  :", msg["message"])

        tx, reply = await c.record_hellos()
        for n in ("alice", "bob"):
            await tx.send({"name": n})
        await tx.finish()
        print("client-stream  :", (await reply)["message"])

        tx, stream = await c.chat()
        await tx.send({"name": "ping"})
        print("bidi           :", (await stream.message())["message"])
        await tx.finish()

        # chaos: kill the server and watch the client observe UNAVAILABLE,
        # then restart and recover (server_crash, server.rs:371-405)
        h.kill(server)
        try:
            await c.say_hello({"name": "ghost"})
        except grpc.Status as s:
            print("after kill     :", s.code.name)
        h.restart(server)
        await ms.sleep(0.2)
        r = await c.say_hello({"name": "phoenix"})
        print("after restart  :", r["message"])

    await client_node.spawn(client())
    print(f"seed {h.seed} complete at t={ms.now_ns() / 1e9:.3f}s simulated")


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "sim"
    if mode == "sim":
        sim_main()
    else:
        print("usage: greeter.py sim")
        sys.exit(1)
