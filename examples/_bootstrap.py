"""Put the repo root on sys.path for directly-run example scripts.

``python examples/foo.py`` puts ``examples/`` (the script dir) on the
path, not the repo root, so ``import madsim_tpu`` fails unless the repo
is installed or PYTHONPATH is set. Every example imports this module
first; it resolves because the script dir IS on the path.
"""

import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)

# Persistent XLA compile cache for every example (read by jax at
# import time). The TPU tunnel historically wedges DURING long
# compiles (rounds 3 and 5 both lost their window to a fresh
# broadcast/microbench compile); caching means a post-recovery retry
# replays earlier compiles in seconds instead of re-exposing the
# tunnel to the same multi-10 s compile that wedged it.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_bench_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")
