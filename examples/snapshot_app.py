"""Lai-Yang distributed snapshot on the single-seed runtime — the same
algorithm the batched engine family certifies (models/snapshot.py),
here as an application a user would actually write: @service RPC over
the simulated network, stdlib random for timers, virtual time.

Five "bank branch" nodes make random transfers to random peers. At a
drawn time the initiator goes red and records its balance; every
transfer carries its sender's color:

* first RED message at a white node -> record balance BEFORE applying
  (the node turns red and broadcasts a zero-amount red "paint" so
  color reaches branches nobody happens to pay),
* WHITE message at a red node -> applied AND recorded as channel
  state (it crossed the cut),
* the initiator counts delivery notices; when every transfer and
  paint has landed, the snapshot is complete.

The invariant — exact conservation over the cut: recorded balances +
recorded channel state == total money minted, despite transfers being
in flight across the cut and the simulated network reordering
deliveries. Run it:

    MADSIM_TEST_SEED=1 python examples/snapshot_app.py

Asserted across seeds by tests/test_snapshot_app.py; the engine family
proves the same invariant over 65,536 schedules per run
(SEARCH_r05.txt) with a bit-identical C++ oracle.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import random

import madsim_tpu as ms
from madsim_tpu.net.service import rpc, service

__all__ = ["Branch", "run_snapshot", "N_NODES", "BALANCE"]

N_NODES = 5
BALANCE = 1000
N_SENDS = 6
PORT = 9200


def addr(i: int) -> str:
    return f"10.0.2.{i + 1}:{PORT}"


class Transfer:
    def __init__(self, amount, color):
        self.amount = amount
        self.color = color          # sender's color at send time


class Recvd:
    """Delivery notice counted by the initiator for termination."""


@service
class Branch:
    def __init__(self, me: int, registry: dict):
        self.me = me
        self.balance = BALANCE
        self.color = 0              # 0 white, 1 red
        self.recorded = None        # balance at the cut
        self.chan_in = 0            # white amounts received while red
        self.recvd_count = 0        # initiator only
        self.done = ms.SimFuture(name=f"snapshot-done-{me}")
        registry[me] = self
        self._ep = None

    # ---- Lai-Yang receive rules
    @rpc
    async def transfer(self, m: Transfer):
        if self.color == 0 and m.color == 1:
            await self._go_red()    # record BEFORE applying
        elif self.color == 1 and m.color == 0:
            self.chan_in += m.amount    # crossed the cut
        self.balance += m.amount
        await self._ep.call(addr(0), Recvd())

    @rpc
    async def recvd(self, _m: Recvd):
        self.recvd_count += 1
        total = N_NODES * N_SENDS + N_NODES * (N_NODES - 1)
        if self.recvd_count == total and not self.done.done():
            self.done.set_result(True)

    async def _go_red(self):
        self.recorded = self.balance
        self.color = 1
        for p in range(N_NODES):    # paint: zero-amount red transfers
            if p != self.me:
                ms.spawn(self._ep.call(addr(p), Transfer(0, 1)))

    # ---- the workload
    async def run(self, snap_delay: float | None):
        self._ep = await self.serve(f"0.0.0.0:{PORT}")
        if snap_delay is not None:
            async def trigger():
                await ms.sleep(snap_delay)
                if self.color == 0:
                    await self._go_red()
            ms.spawn(trigger())
        for _ in range(N_SENDS):
            await ms.sleep(random.uniform(0.005, 0.025))
            dst = (self.me + 1 + random.randrange(N_NODES - 1)) % N_NODES
            amount = random.randint(1, 100)
            self.balance -= amount
            ms.spawn(self._ep.call(addr(dst), Transfer(amount, self.color)))


def run_snapshot(seed: int) -> dict:
    registry: dict[int, Branch] = {}

    async def main():
        h = ms.Handle.current()
        snap_delay = None
        for i in range(N_NODES):
            def make_init(i=i):
                async def init():
                    d = random.uniform(0.02, 0.08) if i == 0 else None
                    await Branch(i, registry).run(d)
                return init
            h.create_node().name(f"branch-{i}").ip(f"10.0.2.{i + 1}") \
                .init(make_init()).build()
        await ms.sleep(0.05)
        await ms.timeout(30.0, registry[0].done)

    ms.Runtime(seed=seed).block_on(main())
    return {
        "recorded": {i: b.recorded for i, b in registry.items()},
        "chan_in": {i: b.chan_in for i, b in registry.items()},
        "balances": {i: b.balance for i, b in registry.items()},
        "colors": {i: b.color for i, b in registry.items()},
    }


if __name__ == "__main__":
    import os

    seed = int(os.environ.get("MADSIM_TEST_SEED", "1"))
    out = run_snapshot(seed)
    total = sum(out["recorded"].values()) + sum(out["chan_in"].values())
    print("recorded:", out["recorded"])
    print("channel :", out["chan_in"])
    print(f"cut total = {total} == minted {N_NODES * BALANCE}")
    assert total == N_NODES * BALANCE
    assert sum(out["balances"].values()) == N_NODES * BALANCE
    print("consistent cut: conservation holds")
