"""Batch-size scaling sweep: sim-s/s across seeds x the five configs.

Produces the SCALING.md evidence: for each benchmark config, run the
bench measurement at seed counts 1k/4k/16k/65k (and 256k for raft) and
record simulated-seconds/sec plus wall per step. Best-of-3 per cell
(the remote-TPU dispatch path has multi-100ms jitter).

Usage: python examples/scaling_sweep.py [out.json]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax

from madsim_tpu.engine import EngineConfig, make_init, make_run_while
from madsim_tpu.models import (
    make_broadcast,
    make_kvchaos,
    make_microbench,
    make_pingpong,
    make_raft,
)

SEED_COUNTS = [1024, 4096, 16384, 65536]

CONFIGS = {
    "raft": (lambda: make_raft(), dict(pool_size=48, loss_p=0.02), 600),
    "microbench": (lambda: make_microbench(), dict(pool_size=32), 1100),
    "broadcast": (lambda: make_broadcast(), dict(pool_size=48, loss_p=0.05), 500),
    "kvchaos": (lambda: make_kvchaos(), dict(pool_size=48, loss_p=0.02), 900),
    "pingpong": (lambda: make_pingpong(), dict(pool_size=32), 300),
}


def measure(name, mk, cfg_kw, max_steps, n_seeds):
    wl = mk()
    cfg = EngineConfig(**cfg_kw)
    init = make_init(wl, cfg)
    run = jax.jit(make_run_while(wl, cfg, max_steps), donate_argnums=0)
    jax.block_until_ready(run(init(np.arange(n_seeds, dtype=np.uint64))))
    best_wall, best = float("inf"), None
    for _ in range(3):
        state = init(np.arange(n_seeds, 2 * n_seeds, dtype=np.uint64))
        t0 = time.perf_counter()
        out = jax.block_until_ready(run(state))
        wall = time.perf_counter() - t0
        if wall < best_wall:
            best_wall, best = wall, out
    sim_s = float(np.asarray(best.now, dtype=np.float64).sum() / 1e9)
    rec = {
        "config": name,
        "n_seeds": n_seeds,
        "wall_s": round(best_wall, 4),
        "sim_s_per_s": round(sim_s / best_wall, 1),
        "overflow": int(np.asarray(best.overflow).sum()),
        "all_halted": bool(np.all(np.asarray(best.halted))),
        "steps": int(np.asarray(best.step).max()),
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SCALING_SWEEP.json"
    platform = jax.devices()[0].platform
    rows = []
    for name, (mk, cfg_kw, max_steps) in CONFIGS.items():
        counts = SEED_COUNTS + ([262144] if name == "raft" else [])
        for s in counts:
            rows.append(measure(name, mk, cfg_kw, max_steps, s))
    doc = {"platform": platform, "rows": rows}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# wrote {out_path} ({platform})", file=sys.stderr)


if __name__ == "__main__":
    main()
