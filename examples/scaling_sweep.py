"""Batch-size scaling sweep: sim-s/s across seeds x the six configs.

Produces the SCALING.md evidence: for each of the six benchmark
configs (the five BASELINE ones + raftlog), measure
simulated-seconds/sec at seed counts 1k/4k/16k/65k (256k extra for
raft; a single-seed cell extra for pingpong, BASELINE config 1).

Methodology (engine/measure.py): every cell is timed as >= 5 s-long
jitted dispatches — a ``fori_loop`` of independent seed-batches inside
ONE dispatch — so the remote-tunnel dispatch jitter (multi-100 ms per
dispatch) is amortized below the noise floor instead of dominating
sub-second runs. Cells report the median over 3 dispatches with
min/max spread; the artifact also records a null-kernel dispatch
profile quantifying the transport overhead the sizing defeats. A cell
is quotable only if ``overflow == 0`` and ``all_halted`` — check
before quoting.

Usage: python examples/scaling_sweep.py [out.json] [--quick] [cpu]
                                        [--resume rows.jsonl]
  --quick: 2 s dispatches, 2 measures (for smoke runs)
  cpu: pin the CPU backend (jax.config — env vars can't, sitecustomize
       wins; required for fallback sweeps while the tunnel is wedged)
  --resume: reuse same-platform rows already banked in rows.jsonl and
       measure only the missing cells (the tunnel historically survives
       ~5-15 min — one window cannot fit all ~27 cells, so the chain
       appends each window's rows to one file and resumes)
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import sys
import time

import jax

if "cpu" in sys.argv[1:]:
    # env vars cannot pin the platform here: the image's sitecustomize
    # registers the axon plugin at interpreter start, and with a wedged
    # tunnel any axon init hangs forever — only a config update wins
    # (same seam as profile_step.py / the bench children)
    jax.config.update("jax_platforms", "cpu")

from madsim_tpu.engine import EngineConfig
from madsim_tpu.engine.measure import measure_throughput, null_dispatch_stats
from madsim_tpu.models import BENCH_SPECS

SEED_COUNTS = [1024, 4096, 16384, 65536]


def load_resume_rows(path: str, platform: str, quick: bool) -> dict:
    """Rows already banked by a previous window, keyed (config, seeds).
    Only rows measured on the SAME platform at the SAME quality setting
    are reused — a CPU-fallback row must never masquerade as a TPU
    cell, and a --quick smoke row must never satisfy a full-quality
    sweep (rows lacking either field are from the pre-resume format
    and are not reusable)."""
    import os

    done = {}
    if not os.path.exists(path):
        return done
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if (
                isinstance(rec, dict)
                and rec.get("platform") == platform
                and rec.get("quick") == quick
                and "config" in rec
                and "n_seeds" in rec
            ):
                done[(rec["config"], int(rec["n_seeds"]))] = rec
    return done


def main():
    argv = sys.argv[1:]
    quick = "--quick" in argv
    resume_path = None
    if "--resume" in argv:
        i = argv.index("--resume")
        operand = argv[i + 1] if i + 1 < len(argv) else None
        if operand is None or operand.startswith("--") or operand == "cpu":
            raise SystemExit("--resume requires a rows.jsonl path operand")
        resume_path = operand
        argv = argv[:i] + argv[i + 2:]
    args = [a for a in argv if not a.startswith("--") and a != "cpu"]
    out_path = args[0] if args else "SCALING_SWEEP.json"
    target_wall = 2.0 if quick else 5.0
    n_measure = 2 if quick else 3

    platform = jax.devices()[0].platform
    done = load_resume_rows(resume_path, platform, quick) if resume_path else {}
    null = null_dispatch_stats()
    print(f"# platform={platform} resumed_rows={len(done)} "
          f"null_dispatch={json.dumps(null)}", file=sys.stderr)

    rows = []
    for name, (mk, cfg_kw, _spec_seeds, max_steps) in BENCH_SPECS.items():
        counts = SEED_COUNTS + ([262144] if name == "raft" else [])
        if name == "pingpong":
            counts = [1] + counts  # BASELINE config 1 is single-seed
        for s in counts:
            if (name, s) in done:
                rows.append(done[(name, s)])
                continue
            t0 = time.monotonic()  # lint: allow(wall-clock)
            rec = measure_throughput(
                mk(), EngineConfig(**cfg_kw), max_steps, s,
                target_wall_s=target_wall, n_measure=n_measure,
                seed_mod=524288 if name == "raft" else 131072,
                min_size=min(2048, max(s // 4, 1)),
            )
            rec = {
                "config": name, "platform": platform, "quick": quick, **rec,
                "cell_wall_s": round(time.monotonic() - t0, 1),  # lint: allow(wall-clock)
            }
            rows.append(rec)
            print(json.dumps(rec), flush=True)

    doc = {"platform": platform, "null_dispatch": null, "rows": rows}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# wrote {out_path} ({platform})", file=sys.stderr)


if __name__ == "__main__":
    main()
