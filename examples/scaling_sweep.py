"""Batch-size scaling sweep: sim-s/s across seeds x the six configs.

Produces the SCALING.md evidence: for each of the six benchmark
configs (the five BASELINE ones + raftlog), measure
simulated-seconds/sec at seed counts 1k/4k/16k/65k (256k extra for
raft; a single-seed cell extra for pingpong, BASELINE config 1).

Methodology (engine/measure.py): every cell is timed as >= 5 s-long
jitted dispatches — a ``fori_loop`` of independent seed-batches inside
ONE dispatch — so the remote-tunnel dispatch jitter (multi-100 ms per
dispatch) is amortized below the noise floor instead of dominating
sub-second runs. Cells report the median over 3 dispatches with
min/max spread; the artifact also records a null-kernel dispatch
profile quantifying the transport overhead the sizing defeats. A cell
is quotable only if ``overflow == 0`` and ``all_halted`` — check
before quoting.

Usage: python examples/scaling_sweep.py [out.json] [--quick] [cpu]
  --quick: 2 s dispatches, 2 measures (for smoke runs)
  cpu: pin the CPU backend (jax.config — env vars can't, sitecustomize
       wins; required for fallback sweeps while the tunnel is wedged)
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import sys
import time

import jax

if "cpu" in sys.argv[1:]:
    # env vars cannot pin the platform here: the image's sitecustomize
    # registers the axon plugin at interpreter start, and with a wedged
    # tunnel any axon init hangs forever — only a config update wins
    # (same seam as profile_step.py / the bench children)
    jax.config.update("jax_platforms", "cpu")

from madsim_tpu.engine import EngineConfig
from madsim_tpu.engine.measure import measure_throughput, null_dispatch_stats
from madsim_tpu.models import BENCH_SPECS

SEED_COUNTS = [1024, 4096, 16384, 65536]


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--") and a != "cpu"]
    quick = "--quick" in sys.argv
    out_path = args[0] if args else "SCALING_SWEEP.json"
    target_wall = 2.0 if quick else 5.0
    n_measure = 2 if quick else 3

    platform = jax.devices()[0].platform
    null = null_dispatch_stats()
    print(f"# platform={platform} null_dispatch={json.dumps(null)}", file=sys.stderr)

    rows = []
    for name, (mk, cfg_kw, _spec_seeds, max_steps) in BENCH_SPECS.items():
        counts = SEED_COUNTS + ([262144] if name == "raft" else [])
        if name == "pingpong":
            counts = [1] + counts  # BASELINE config 1 is single-seed
        for s in counts:
            t0 = time.monotonic()
            rec = measure_throughput(
                mk(), EngineConfig(**cfg_kw), max_steps, s,
                target_wall_s=target_wall, n_measure=n_measure,
                seed_mod=524288 if name == "raft" else 131072,
                min_size=min(2048, max(s // 4, 1)),
            )
            rec = {"config": name, **rec, "cell_wall_s": round(time.monotonic() - t0, 1)}
            rows.append(rec)
            print(json.dumps(rec), flush=True)

    doc = {"platform": platform, "null_dispatch": null, "rows": rows}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# wrote {out_path} ({platform})", file=sys.stderr)


if __name__ == "__main__":
    main()
