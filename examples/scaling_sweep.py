"""Batch-size scaling sweep: sim-s/s across seeds x the six configs.

Produces the SCALING.md evidence: for each of the six benchmark
configs (the five BASELINE ones + raftlog), run the bench measurement
at seed counts 1k/4k/16k/65k (256k extra for raft; a single-seed cell
extra for pingpong, BASELINE config 1) and record
simulated-seconds/sec plus wall per step. Uses the same compacted
runner and compute/assemble timing seam as bench.py; it differs from
the headline artifact in repeat policy (best-of-3 every cell, vs
bench.py's best-of-5 on accelerators / single run on CPU) and in
reporting cells with a nonzero overflow count instead of refusing
them — check the `overflow` field before quoting a cell.

Usage: python examples/scaling_sweep.py [out.json]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax

from madsim_tpu.engine import EngineConfig, make_init, make_run_compacted
from madsim_tpu.models import BENCH_SPECS

SEED_COUNTS = [1024, 4096, 16384, 65536]


def measure(name, mk, cfg_kw, max_steps, n_seeds):
    wl = mk()
    cfg = EngineConfig(**cfg_kw)
    init = make_init(wl, cfg)
    run = make_run_compacted(
        wl, cfg, max_steps, min_size=2048,
        fields=("now", "overflow", "halted", "step"),
    )
    jax.block_until_ready(run.compute(init(np.arange(n_seeds, dtype=np.uint64))))
    best_wall, best = float("inf"), None
    for _ in range(3):
        state = init(np.arange(n_seeds, 2 * n_seeds, dtype=np.uint64))
        t0 = time.perf_counter()
        banked = jax.block_until_ready(run.compute(state))
        wall = time.perf_counter() - t0
        if wall < best_wall:
            best_wall, best = wall, banked
    out = run.assemble(best)
    sim_s = float(np.asarray(out.now, dtype=np.float64).sum() / 1e9)
    rec = {
        "config": name,
        "n_seeds": n_seeds,
        "wall_s": round(best_wall, 4),
        "sim_s_per_s": round(sim_s / best_wall, 1),
        "overflow": int(np.asarray(out.overflow).sum()),
        "all_halted": bool(np.all(np.asarray(out.halted))),
        "steps": int(np.asarray(out.step).max()),
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SCALING_SWEEP.json"
    platform = jax.devices()[0].platform
    rows = []
    for name, (mk, cfg_kw, _spec_seeds, max_steps) in BENCH_SPECS.items():
        counts = SEED_COUNTS + ([262144] if name == "raft" else [])
        if name == "pingpong":
            counts = [1] + counts  # BASELINE config 1 is single-seed
        for s in counts:
            rows.append(measure(name, mk, cfg_kw, max_steps, s))
    doc = {"platform": platform, "rows": rows}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# wrote {out_path} ({platform})", file=sys.stderr)


if __name__ == "__main__":
    main()
