"""An UNMODIFIED asyncio application under deterministic chaos.

The point of this demo: the worker/queue pipeline below is written
against the plain stdlib — ``import asyncio``, ``asyncio.Queue``,
``asyncio.TaskGroup``, ``asyncio.timeout`` — with no simulator imports
inside the application code at all. Run under the simulator it executes
on virtual time with seeded scheduling (the loop interposition of
``runtime/aio.py``, the analog of the reference's build-time tokio swap
— madsim-tokio/src/lib.rs): same seed, bit-identical run; the whole
"10 seconds" of simulated pipeline finishes in milliseconds of wall
time.

    python examples/raw_asyncio_app.py            # seed 1
    MADSIM_TEST_SEED=7 python examples/raw_asyncio_app.py
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import asyncio
import random
import time

import madsim_tpu as ms


# ----------------------------------------------------------------------
# The application: plain asyncio, no simulator imports.
# ----------------------------------------------------------------------
async def pipeline(n_jobs: int, n_workers: int) -> dict:
    jobs: asyncio.Queue = asyncio.Queue(maxsize=4)
    done: list = []

    async def producer():
        for i in range(n_jobs):
            await asyncio.sleep(random.uniform(0.01, 0.05))
            await jobs.put(i)
        for _ in range(n_workers):
            await jobs.put(None)  # poison pills

    async def worker(w: int):
        while True:
            job = await jobs.get()
            if job is None:
                return
            # flaky downstream call with a timeout + one retry
            for attempt in (1, 2):
                try:
                    async with asyncio.timeout(0.2):
                        await asyncio.sleep(random.uniform(0.05, 0.4))
                    done.append((job, w, attempt))
                    break
                except TimeoutError:
                    if attempt == 2:
                        done.append((job, w, "gave-up"))

    async with asyncio.TaskGroup() as tg:
        tg.create_task(producer())
        for w in range(n_workers):
            tg.create_task(worker(w))

    return {
        "completed": sorted(j for j, _, a in done if a != "gave-up"),
        "gave_up": sorted(j for j, _, a in done if a == "gave-up"),
    }


# ----------------------------------------------------------------------
# The harness: only THIS part knows about the simulator.
# ----------------------------------------------------------------------
@ms.test
async def main():
    wall0 = time.monotonic()  # interposed: virtual seconds  # lint: allow(wall-clock)
    out = await pipeline(n_jobs=12, n_workers=3)
    print(f"virtual elapsed: {time.monotonic() - wall0:.3f}s (simulated)")  # lint: allow(wall-clock)
    print(f"completed={out['completed']}")
    print(f"gave_up  ={out['gave_up']}")
    assert sorted(out["completed"] + out["gave_up"]) == list(range(12))


if __name__ == "__main__":
    main()
