"""Put the repo root on sys.path for directly-run example scripts.

``python examples/foo.py`` puts ``examples/`` (the script dir) on the
path, not the repo root, so ``import madsim_tpu`` fails unless the repo
is installed or PYTHONPATH is set. Every example imports this module
first; it resolves because the script dir IS on the path.
"""

import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)
