"""Large-scale operation-history checker soak: workload certificates.

The search soak (tools/search_soak.py) certifies *final-state*
invariants; this soak certifies *histories* (madsim_tpu.check) — the
FoundationDB-style workload verification. Three certificates:

1. kvchaos-record, unmutated: N seeds through the vectorized detectors
   (stale_reads + read_your_writes, one numpy pass over the batch) AND
   the exact Wing–Gong linearizability checker per seed. Must be 0
   violations — a clean negative-result artifact.
2. raft-record: election-safety over every recorded win. Must be 0.
3. kvchaos-bug, the seeded lost-write mutant (primary forgets its
   commit point on replica rejoin; the protocol re-commits, so every
   final state looks healthy): the history checkers MUST flag seeds,
   the existing final-state durability invariant MUST pass all of them
   — proving the subsystem detects a bug class final-state checks
   cannot.
4. raftlog-record: election safety (one winner per term) AND log
   agreement (no index committed with two different entries) over
   every recorded leader decision. Must be 0.
5. paxos-record: agreement over every decide event (chooser majorities
   and first adoptions alike). Must be 0.

Usage: python tools/check_soak.py [n_seeds] > CHECK_HIST_r06.txt
Exit 0 iff all five certificates hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu.check import (  # noqa: E402
    check_kv,
    election_safety,
    read_your_writes,
    stale_reads,
)
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import (  # noqa: E402
    make_kvchaos,
    make_paxos,
    make_raft,
    make_raftlog,
)
from madsim_tpu.models.raft import OP_ELECT  # noqa: E402
from madsim_tpu.models.raftlog import OP_COMMIT  # noqa: E402
from madsim_tpu.models.raftlog import OP_ELECT as RL_OP_ELECT  # noqa: E402
from madsim_tpu.models.paxos import OP_DECIDE  # noqa: E402

W = 10  # kvchaos writes (the search-soak shape): 4W history records/seed


def kv_history_invariant(box):
    def inv(h):
        box["h"] = h
        box["ok"] = stale_reads(h) & read_your_writes(h)
        return box["ok"]

    return inv


# the existing final-state invariant — the control the mutant
# certificate is measured against; single copy, pinned to writes=10
# (hence W above must stay 10)
from search_soak import kvchaos_durability  # noqa: E402


def lin_sweep(h, n_cap=None) -> list:
    """Exact Wing–Gong pass over per-seed histories; returns the
    violating seed indices. ~tens of ops per seed -> microseconds
    each."""
    n = h.n_seeds if n_cap is None else min(h.n_seeds, n_cap)
    drop = np.asarray(h.drop)
    bad = []
    for s in range(n):
        if drop[s] > 0:
            continue  # already counted/quarantined as an overflow
        if not check_kv(h.ops(s)).ok:
            bad.append(s)
    return bad


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    cfg = EngineConfig(pool_size=192, loss_p=0.05)
    t_all = time.monotonic()  # lint: allow(wall-clock)
    failures = []
    print(f"# operation-history checker soak: {n_seeds} schedules/cert, "
          f"platform={jax.devices()[0].platform}")

    # ---- certificate 1: unmutated kvchaos, history clean ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    rep = search_seeds(
        make_kvchaos(writes=W, record=True), cfg, None,
        n_seeds=n_seeds, max_steps=3000,
        history_invariant=kv_history_invariant(box),
    )
    h = box["h"]
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    nl = len(lin_sweep(h))
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    t_lin = time.monotonic() - t0  # lint: allow(wall-clock)
    print(f"kvchaos-record: {n_seeds} schedules, {nv} vectorized "
          f"violations, {nl} linearizability violations, {no} overflows, "
          f"{nh} unhalted ({t_lin:.1f}s incl. {n_seeds} Wing-Gong checks)")
    if nv or nl or no or nh:
        failures.append("kvchaos-record")

    # ---- certificate 2: raft election safety over recorded wins ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}

    def elect_inv(h):
        box["ok"] = election_safety(h, elect_op=OP_ELECT)
        return box["ok"]

    rep = search_seeds(
        make_raft(record=True), EngineConfig(pool_size=48, loss_p=0.02),
        None, n_seeds=n_seeds, max_steps=600,
        history_invariant=elect_inv,
    )
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    print(f"raft-record: {n_seeds} schedules, {nv} election-safety "
          f"violations, {no} overflows, {nh} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no or nh:
        failures.append("raft-record")

    # ---- certificate 4: raftlog election safety + log agreement ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}

    def raftlog_inv(h):
        box["ok"] = election_safety(h, elect_op=RL_OP_ELECT) & election_safety(
            h, elect_op=OP_COMMIT
        )
        return box["ok"]

    rep = search_seeds(
        make_raftlog(record=True),
        EngineConfig(pool_size=64, loss_p=0.02,
                     clog_backoff_max_ns=2_000_000_000),
        None, n_seeds=n_seeds, max_steps=4000,
        history_invariant=raftlog_inv,
    )
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    print(f"raftlog-record: {n_seeds} schedules, {nv} election/log-"
          f"agreement violations, {no} overflows, {nh} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no or nh:
        failures.append("raftlog-record")

    # ---- certificate 5: paxos agreement over decide events ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}

    def paxos_inv(h):
        box["ok"] = election_safety(h, elect_op=OP_DECIDE)
        return box["ok"]

    rep = search_seeds(
        make_paxos(record=True), EngineConfig(pool_size=64, loss_p=0.05),
        None, n_seeds=n_seeds, max_steps=2500,
        history_invariant=paxos_inv,
    )
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    print(f"paxos-record: {n_seeds} schedules, {nv} agreement "
          f"violations, {no} overflows, {nh} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no or nh:
        failures.append("paxos-record")

    # ---- certificate 3: the lost-write mutant ----
    # flagged by the history checkers, passed by the final-state
    # invariant: the bug class the old subsystem provably cannot see
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    fbox = {}

    def durability_probe(view):
        # capture the final-state verdict without folding it into
        # rep_h.ok — judged separately below, so one simulation serves
        # both sides of the certificate
        fbox["ok"] = np.asarray(kvchaos_durability(view), bool)
        return np.ones_like(fbox["ok"])

    rep_h = search_seeds(
        make_kvchaos(writes=W, record=True, bug=True), cfg,
        durability_probe, n_seeds=n_seeds, max_steps=3000,
        history_invariant=kv_history_invariant(box),
    )
    h = box["h"]
    # count from the captured verdicts, not rep_h.failing_seeds, so an
    # unhalted seed (ok folds in require_halt) can't masquerade as a
    # history catch or a final-state catch — unhalted is its own line
    trusted = ~rep_h.overflowed
    caught = ~box["ok"] & trusted
    n_hist = int(caught.sum())
    lin_bad = set(lin_sweep(h))
    # "confirmed" means CONFIRMED: every seed the vectorized detectors
    # flag must also fail the exact checker (the floor detectors are a
    # sound under-approximation of linearizability) — a divergence is a
    # checker regression, and the certificate must not certify it
    unconfirmed = sorted(set(np.flatnonzero(caught).tolist()) - lin_bad)
    n_lin = len(lin_bad)
    # unhalted seeds are excluded here too: durability is trivially
    # false on an unfinished run (client_done mid-run), which is not a
    # final-state catch — the history checks above are prefix-closed,
    # so `caught` needs no such mask
    n_final = int((~fbox["ok"] & trusted & np.asarray(rep_h.halted)).sum())
    nh3 = int((~np.asarray(rep_h.halted)).sum())
    print(f"kvchaos-bug mutant: {n_seeds} schedules, {n_hist} caught by "
          f"history check ({n_lin} confirmed by Wing-Gong), {n_final} "
          f"caught by final-state invariant, {nh3} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if n_hist:
        print(f"  first flagged seeds: {rep_h.seeds[caught][:5].tolist()}")
    if n_hist == 0:
        failures.append("mutant-not-caught")
    if unconfirmed:
        print(f"  UNCONFIRMED by Wing-Gong: seed indices "
              f"{unconfirmed[:5]} (+{max(0, len(unconfirmed) - 5)} more)")
        failures.append("vectorized-unconfirmed-by-wing-gong")
    if nh3 != 0:
        failures.append("mutant-unhalted")
    if n_final != 0:
        # the mutant is supposed to be INVISIBLE to final states; if the
        # final-state invariant sees it, the certificate proves nothing
        failures.append("mutant-visible-to-final-state")

    verdict = "PASS" if not failures else f"FAIL ({', '.join(failures)})"
    print(f"# verdict: {verdict} — history checkers catch the lost-write "
          f"bug class; final-state invariants do not")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
