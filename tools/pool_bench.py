"""Readiness-partitioned pool A/B: same-box interleaved flat-vs-indexed
bench at client-army pool sizes (the ISSUE-13 acceptance evidence).

For each army config (raftlog / kvchaos, ``army=True``, history +
latency taps on) at pool sizes >= 2048 this tool:

1. runs the SAME pre-seeded batch through the flat lowering
   (``pool_index=False`` — exactly the pre-ISSUE-13 program) and the
   indexed one (``pool_index=True``) and asserts every SimState field
   except the derived tile summaries is bit-identical — traces,
   event pools, histories, latency sketches, overflow counts. Final-
   state equality implies identical verdicts for ANY invariant, so
   "violations identical" is covered by construction, not sampled;
2. times both sides INTERLEAVED (A/B/A/B, best-of per round) on one
   box, reporting seed-steps/s and the speedup — the same-box
   methodology BENCH_AB_r06 established (absolute cells on this
   container are throttle-depressed; compare A/B, not cross-round);
3. pins the small-pool guard: pools <= 512 resolve ``pool_index`` off
   by default, so the default program there is byte-identical to the
   previous engine — a 0% regression by construction, asserted from
   the resolution rule itself.

Usage:
    python tools/pool_bench.py            > BENCH_AB_r07.txt   # full
    python tools/pool_bench.py --smoke                         # make check

Exit 0 iff every identity holds (and, in full mode, every measured
speedup clears the 2x acceptance floor).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

import _bootstrap  # noqa: F401  (sys.path for tools/)

import numpy as np

import jax
from jax import lax

from madsim_tpu.chaos import CrashStorm, FaultPlan, GrayFailure
from madsim_tpu.engine import (
    POOL_INDEX_STATE_FIELDS,
    EngineConfig,
    LatencySpec,
    make_init,
)
from madsim_tpu.engine.core import _resolve_pool_index, make_step
from madsim_tpu.models import make_kvchaos, make_raftlog
from madsim_tpu.models import kvchaos as kv_mod
from madsim_tpu.models import raftlog as rl_mod

ACCEPT_SPEEDUP = 2.0  # the ISSUE-13 acceptance floor (full mode only)


def _army_setup(name: str, pool: int):
    """(workload, config, plan, latency) for one army config at one pool."""
    n_ops = max(pool // 2 - 64, 64)
    chaos = (
        CrashStorm(targets=tuple(range(5)), n=1, t_min_ns=50_000_000,
                   t_max_ns=200_000_000, down_min_ns=20_000_000,
                   down_max_ns=80_000_000),
        GrayFailure(targets=tuple(range(5)), n_links=1, mult_min=4,
                    mult_max=8, t_min_ns=30_000_000, t_max_ns=150_000_000,
                    dur_min_ns=50_000_000, dur_max_ns=150_000_000),
    )
    if name == "raftlog":
        wl = make_raftlog(record=True, army=True)
        army = rl_mod.client_army(n_ops=n_ops, t_min_ns=5_000_000,
                                  t_max_ns=3_000_000_000)
    elif name == "kvchaos":
        wl = make_kvchaos(record=True, army=True,
                          hist_capacity=80 + 4 * n_ops)
        army = kv_mod.client_army(n_ops=n_ops, t_min_ns=5_000_000,
                                  t_max_ns=3_000_000_000)
    else:
        raise SystemExit(f"unknown army config {name!r}")
    plan = FaultPlan((army,) + chaos)
    cfg = EngineConfig(pool_size=pool, loss_p=0.02,
                       clog_backoff_max_ns=2_000_000_000)
    return wl, cfg, plan, LatencySpec(ops=n_ops, phases=3)


def _build(wl, cfg, plan, lat, n_steps, pool_index):
    step = jax.vmap(make_step(
        wl, cfg, layout="scatter", latency=lat, pool_index=pool_index,
    ))

    def run(st):
        final, _ = lax.scan(
            lambda s, _: (step(s), None), st, None, length=n_steps
        )
        return final

    init = make_init(wl, cfg, plan_slots=plan.slots, latency=lat,
                     pool_index=pool_index)
    return jax.jit(run), init


def _state_fields(st):
    return {
        f.name: np.asarray(getattr(st, f.name))
        for f in dataclasses.fields(st)
        if f.name not in POOL_INDEX_STATE_FIELDS
    }


def ab_config(name: str, pool: int, n_seeds: int, n_steps: int,
              rounds: int) -> tuple[bool, float]:
    wl, cfg, plan, lat = _army_setup(name, pool)
    seeds = np.arange(n_seeds, dtype=np.uint64)
    rows = plan.compile_batch(seeds, wl=wl)
    run_a, init_a = _build(wl, cfg, plan, lat, n_steps, pool_index=False)
    run_b, init_b = _build(wl, cfg, plan, lat, n_steps, pool_index=True)
    st_a, st_b = init_a(seeds, rows), init_b(seeds, rows)

    # ---- identity (and compile, outside the timed windows) ----
    out_a = jax.block_until_ready(run_a(st_a))
    out_b = jax.block_until_ready(run_b(st_b))
    fa, fb = _state_fields(out_a), _state_fields(out_b)
    diverged = [
        k for k in fa
        if fa[k].shape != fb[k].shape or not np.array_equal(fa[k], fb[k])
    ]
    lat_ops = int(np.asarray(out_a.lat_count).sum())
    hist_drops = int(np.asarray(out_a.hist_drop).sum())
    pool_drops = int(np.asarray(out_a.overflow).sum())
    print(f"  identity: {'OK' if not diverged else f'DIVERGED {diverged}'} "
          f"over {len(fa)} fields (traces, pools, histories, sketches); "
          f"{lat_ops} army ops completed, hist drops {hist_drops}, "
          f"pool drops {pool_drops}")

    # ---- interleaved A/B ----
    walls_a, walls_b = [], []
    for _ in range(rounds):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(run_a(st_a))
        walls_a.append(time.perf_counter() - t0)  # lint: allow(wall-clock)
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(run_b(st_b))
        walls_b.append(time.perf_counter() - t0)  # lint: allow(wall-clock)
    steps = n_seeds * n_steps
    rate_a = steps / min(walls_a)
    rate_b = steps / min(walls_b)
    speedup = rate_b / rate_a
    print(f"  throughput: flat {rate_a:,.0f} seed-steps/s | indexed "
          f"{rate_b:,.0f} seed-steps/s | speedup {speedup:.2f}x "
          f"(interleaved best-of-{rounds}, "
          f"{1e9 * min(walls_a) / steps:.0f} -> "
          f"{1e9 * min(walls_b) / steps:.0f} ns/seed-step)")
    return not diverged, speedup


def main() -> None:
    smoke = "--smoke" in sys.argv[1:]
    plat = jax.devices()[0].platform
    mode = "smoke" if smoke else "full"
    print(f"# pool-bench ({mode}): readiness-partitioned pool A/B, "
          f"platform={plat}")

    # this is a GATE over the shipped defaults: neutralize any
    # deployment env overrides so an exported knob on the CI box
    # cannot flip what is being certified (the knobs themselves are
    # test-pinned in tests/test_pool_index.py)
    for var in ("MADSIM_POOL_INDEX_MIN_POOL", "MADSIM_RANK_PLACE_MAX_POOL"):
        os.environ.pop(var, None)

    # small-pool guard: <= 512 resolves the index OFF by default, so
    # the default program is the pre-ISSUE-13 one — 0% regression by
    # construction (a real check, not an assert: gates must survive -O)
    if _resolve_pool_index(EngineConfig(pool_size=512), None):
        print("# FAIL: pool_index auto-resolved ON at pool_size=512 — "
              "the small-pool no-regression guarantee is broken")
        sys.exit(1)
    print("# small-pool guard: pool_size<=512 defaults to the flat "
          "lowering (identical program, 0% regression by construction)")

    if smoke:
        cells = [("raftlog", 2048, 48, 200, 1)]
    else:
        cells = [
            ("raftlog", 2048, 192, 250, 3),
            ("raftlog", 8192, 96, 250, 3),
            ("kvchaos", 2048, 192, 250, 3),
            ("kvchaos", 8192, 96, 250, 3),
        ]

    ok = True
    for name, pool, n_seeds, n_steps, rounds in cells:
        print(f"== {name} army=True pool_size={pool} n_seeds={n_seeds} "
              f"n_steps={n_steps} ==")
        ident, speedup = ab_config(name, pool, n_seeds, n_steps, rounds)
        ok &= ident
        if not smoke and speedup < ACCEPT_SPEEDUP:
            print(f"  FAIL: speedup {speedup:.2f}x below the "
                  f"{ACCEPT_SPEEDUP}x acceptance floor")
            ok = False
    print(f"# pool-bench: {'PASS' if ok else 'FAIL'}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
