"""Extended oracle soak: bit-identical evidence at many times the suite's
seed counts.

The default gate compares 8-16 seeds per family against the C++ oracle
(tests/test_oracle.py). This soak widens that to N seeds per family —
every field of every seed (trace hash, clock, msg count, halt, final
node state) — across all 8 protocol families plus the durable
variants, and prints one verdict line per config. Run it when idle CPU
is cheap; commit the transcript as the round's soak artifact.

Usage: python tools/oracle_soak.py [n_seeds] > ORACLE_SOAK_rNN.txt
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu.engine import EngineConfig, make_init, make_run  # noqa: E402
from madsim_tpu.engine.oracle import run_oracle  # noqa: E402
from madsim_tpu.models import (  # noqa: E402
    make_broadcast,
    make_kvchaos,
    make_microbench,
    make_paxos,
    make_snapshot,
    make_pingpong,
    make_raft,
    make_raftlog,
    make_twophase,
)

# (name, workload factory, engine config, steps, oracle kwargs) — the
# oracle-suite configurations (tests/test_oracle.py), soaked wider
CONFIGS = [
    ("pingpong", lambda: make_pingpong(rounds=5),
     dict(pool_size=64), 200, dict(rounds=5)),
    ("microbench", lambda: make_microbench(rounds=200),
     dict(pool_size=16), 220, dict(rounds=200)),
    ("raft", make_raft, dict(pool_size=128, loss_p=0.05), 400, {}),
    ("broadcast", lambda: make_broadcast(rounds=3),
     dict(pool_size=128, loss_p=0.05), 400, dict(rounds=3)),
    ("kvchaos", lambda: make_kvchaos(writes=5),
     dict(pool_size=128, loss_p=0.02), 500, dict(writes=5)),
    ("kvchaos-payload", lambda: make_kvchaos(writes=5, payload=True),
     dict(pool_size=128, loss_p=0.02), 500, dict(writes=5)),
    ("twophase", lambda: make_twophase(txns=4),
     dict(pool_size=64, loss_p=0.03), 500, dict(txns=4)),
    ("raftlog", make_raftlog,
     dict(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000),
     3000, {}),
    ("raftlog-durable", lambda: make_raftlog(durable=True),
     dict(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000),
     3000, {}),
    ("paxos", make_paxos, dict(pool_size=64, loss_p=0.02), 400, {}),
    ("snapshot", make_snapshot, dict(pool_size=96), 400, {}),
    ("paxos-durable", lambda: make_paxos(durable_acceptors=True),
     dict(pool_size=64, loss_p=0.02), 400,
     dict(durable_acceptors=True)),
]

def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    total_bad = 0
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# oracle soak: {n_seeds} seeds x {len(CONFIGS)} configs, "
          f"platform={jax.devices()[0].platform}")
    for name, factory, cfg_kw, steps, okw in CONFIGS:
        wl, cfg = factory(), EngineConfig(**cfg_kw)
        seeds = np.arange(n_seeds, dtype=np.uint64)
        t0 = time.monotonic()  # lint: allow(wall-clock)
        out = jax.block_until_ready(
            jax.jit(make_run(wl, cfg, steps))(make_init(wl, cfg)(seeds))
        )
        bad = 0
        for i, seed in enumerate(seeds):
            o = run_oracle(wl, cfg, int(seed), steps, **okw)
            ok = (
                int(out.trace[i]) == o.trace
                and int(out.now[i]) == o.now
                and int(out.msg_count[i]) == o.msg_count
                and bool(out.halted[i]) == o.halted
                and int(out.halt_time[i]) == o.halt_time
                and int(out.overflow[i]) == o.overflow
                and np.array_equal(np.asarray(out.node_state[i]), o.node_state)
            )
            if not ok:
                bad += 1
                print(f"  DIVERGED {name} seed={seed}")
        total_bad += bad
        verdict = "IDENTICAL" if bad == 0 else f"{bad} DIVERGED"
        print(f"{name}: {n_seeds} seeds {verdict} "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    print(f"# total divergences: {total_bad} "
          f"({time.monotonic() - t_all:.0f}s wall)")  # lint: allow(wall-clock)
    sys.exit(1 if total_bad else 0)


if __name__ == "__main__":
    main()
