"""Tail-latency soak: open-loop client army, fleet sketches, the
gray-failure p99 blowup, and the guided SLO hunt. The LATENCY evidence
artifact.

Five certificates:

1. **Latency-off identity** — the latency tap + army markers change NO
   trace, across dense/scatter layouts and the compacted runner (the
   derived-state-only rule at soak scale).
2. **Sketch exactness** — the merged fleet sketch equals the histogram
   of the concatenated exact per-op latencies, device quantiles sit
   within one bucket of exact numpy quantiles, and the sharded merge
   (halves summed) equals the whole.
3. **Clean-run tail baseline vs gray-failure blowup** — kvchaos under
   army load alone, then the same load with a GrayFailure window over
   the client<->primary path: the faulted p99 must exceed the clean
   p99 by >= 2x (the tail signal the whole layer exists to see).
4. **SLO hunt: guided finds what uniform misses** — over one
   gray-failure space, the SLO bound is calibrated AT the worst
   provable window-p99 bucket that uniform sampling reaches at the
   full budget, so a breach requires pushing the tail at least two
   ladder buckets (~40%) past uniform's extreme. Uniform finds zero
   by construction (asserted); the latency-coverage-guided campaign
   must find one anyway at equal budget — search reaching tails
   sampling cannot.
5. **Find -> shrink -> replay -> explain** — the hunt's first breach is
   ddmin-shrunk (army slots and fault slots alike), the shrunk literal
   replays to the identical violation + trace, and ``obs.explain``
   narrates the tail percentiles of the breaching seed.

Usage: python tools/latency_soak.py [n_seeds] > LATENCY_r12.txt
Exit 0 iff every certificate holds.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import dataclasses
import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import check, explore, obs  # noqa: E402
from madsim_tpu.chaos import FaultPlan, GrayFailure, shrink_plan  # noqa: E402
from madsim_tpu.engine import (  # noqa: E402
    EngineConfig,
    LatencySpec,
    lat_bucket,
    search_seeds,
)
from madsim_tpu.engine.core import N_LAT_BUCKETS  # noqa: E402
from madsim_tpu.models import kvchaos as KV  # noqa: E402
from madsim_tpu.parallel import merge_latency  # noqa: E402

N_OPS = 64
MAX_STEPS = 4000
# two ~268 ms measurement windows over the arrival span: wide enough
# that cert 4's uniform blips (<= 80 ms) can never dominate a window
SPEC = LatencySpec(ops=N_OPS, phases=2, phase_ns=1 << 28)

# each army op is a 3-round session (client -> primary -> client x3):
# the multi-round shape real client calls have, and what makes a
# windowed tail breach require SUSTAINED slowness instead of one blip
WL = KV.make_kvchaos(
    writes=20, n_replicas=2, chaos=False, army=True, army_probes=3
)
ARMY = KV.client_army(
    n_ops=N_OPS, t_min_ns=5_000_000, t_max_ns=500_000_000, n_replicas=2
)
CFG = EngineConfig(pool_size=160, time_limit_ns=700_000_000)
# the client<->primary probe path: node 3 is the client, 0 the primary
GRAY = GrayFailure(
    targets=(0, 3), n_links=1, mult_min=8, mult_max=16,
    t_min_ns=20_000_000, t_max_ns=250_000_000,
    dur_min_ns=250_000_000, dur_max_ns=450_000_000,
)

_ONES = lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool)  # noqa: E731


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# latency soak: platform={jax.devices()[0].platform}, "
          f"n_seeds={n_seeds}")
    army_plan = FaultPlan((ARMY,), name="army-clean")
    gray_plan = FaultPlan((ARMY, GRAY), name="army-gray")

    # ---- certificate 1: latency-off identity ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 1: latency-off identity (layouts + compact) ==")
    s_id = min(n_seeds, 256)
    kw = dict(n_seeds=s_id, max_steps=MAX_STEPS, plan=gray_plan,
              require_halt=False)
    base = search_seeds(WL, CFG, _ONES, layout="scatter", **kw)
    rows = [
        ("scatter+latency", search_seeds(
            WL, CFG, _ONES, layout="scatter", latency=SPEC, **kw)),
        ("dense+latency", search_seeds(
            WL, CFG, _ONES, layout="dense", latency=SPEC, **kw)),
        ("compact+latency", search_seeds(
            WL, CFG, _ONES, compact=True, latency=SPEC, **kw)),
    ]
    ok1 = True
    for name, rep in rows:
        same = np.array_equal(base.traces, rep.traces)
        print(f"  {name}: traces {'identical' if same else 'DIVERGED'}")
        ok1 &= same
    same_sketch = np.array_equal(rows[0][1].lat_hist, rows[1][1].lat_hist)
    same_sketch &= np.array_equal(rows[0][1].lat_hist, rows[2][1].lat_hist)
    print(f"  sketches identical across lowerings: {same_sketch}")
    ok1 &= same_sketch
    if not ok1:
        failures.append("identity")
    print(f"cert1 {'PASS' if ok1 else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 2: sketch exactness at scale ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 2: sketch exactness vs exact per-op latencies ==")
    import jax as _jax

    from madsim_tpu.engine import make_init, make_run_while

    # the acceptance scale: >= 4096 seeds. The sketch side goes through
    # obs.fleet_latency — the device-resident reduction that never
    # transfers a per-seed latency column; the GROUND TRUTH side
    # re-runs the same seeds with the per-op clocks pulled to host
    # (that transfer is the test's oracle, not the product path).
    s_ex = max(n_seeds, 4096) if n_seeds >= 2048 else min(n_seeds, 512)
    fl = obs.fleet_latency(
        WL, CFG, SPEC, n_seeds=s_ex, max_steps=MAX_STEPS, plan=gray_plan,
    )
    seeds = np.arange(s_ex, dtype=np.uint64)
    init = make_init(WL, CFG, plan_slots=gray_plan.slots, latency=SPEC)
    run = _jax.jit(make_run_while(WL, CFG, MAX_STEPS, latency=SPEC))
    out = _jax.block_until_ready(
        run(init(seeds, gray_plan.compile_batch(seeds, wl=WL)))
    )
    inv = np.asarray(out.lat_inv)
    resp = np.asarray(out.lat_resp)
    done = (inv >= 0) & (resp >= 0)
    lats = (resp - inv)[done]
    hist = np.asarray(out.lat_hist)
    merged = fl.hist.sum(axis=0)
    exact_hist = np.bincount(lat_bucket(lats), minlength=N_LAT_BUCKETS)
    ok_merge = np.array_equal(merged, exact_hist)
    halves = merge_latency(hist[: s_ex // 2]) + merge_latency(hist[s_ex // 2:])
    ok_shard = np.array_equal(merge_latency(hist), halves)
    ok_paths = np.array_equal(fl.hist, merge_latency(hist))
    print(f"  {int(done.sum())} completed ops over {s_ex} seeds; "
          f"fleet sketch (device-resident) == exact bucketing: {ok_merge}; "
          f"sharded merge == whole: {ok_shard}; "
          f"fleet_latency == merge of state columns: {ok_paths}")
    ok2 = ok_merge and ok_shard and ok_paths
    for q in (0.5, 0.9, 0.99, 0.999):
        sk = int(obs.hist_quantile_bucket(merged, q))
        ex = int(lat_bucket(float(np.quantile(lats, q))))
        hit = abs(sk - ex) <= 1
        print(f"  p{q*100:g}: sketch bucket {sk}, exact bucket {ex} "
              f"({'within one bucket' if hit else 'OFF'})")
        ok2 &= hit
    if not ok2:
        failures.append("exactness")
    print(f"cert2 {'PASS' if ok2 else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 3: clean baseline vs gray-failure blowup ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 3: clean tail baseline vs GrayFailure blowup ==")
    fl_clean = obs.fleet_latency(
        WL, CFG, SPEC, n_seeds=n_seeds, max_steps=MAX_STEPS, plan=army_plan,
    )
    fl_gray = obs.fleet_latency(
        WL, CFG, SPEC, n_seeds=n_seeds, max_steps=MAX_STEPS, plan=gray_plan,
    )
    print("  -- clean run --")
    print("  " + fl_clean.format().replace("\n", "\n  "))
    print("  -- gray failure over the probe path --")
    print("  " + fl_gray.format().replace("\n", "\n  "))
    p99c, p99g = fl_clean.quantile(0.99), fl_gray.quantile(0.99)
    ratio = p99g / max(p99c, 1)
    print(f"  p99 clean={p99c / 1e6:.2f}ms gray={p99g / 1e6:.2f}ms "
          f"blowup={ratio:.2f}x")
    ok3 = p99c > 0 and ratio >= 2.0
    if not ok3:
        failures.append("blowup")
    print(f"cert3 {'PASS' if ok3 else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 4: guided SLO hunt vs uniform at equal budget ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 4: SLO hunt — guided vs uniform at equal budget ==")
    from madsim_tpu.engine import lat_bucket_hi

    # the hunt space: BLIPS only — slow windows of 50-80 ms, far
    # shorter than a 3-round session under slowness. A blip bounds the
    # sum of slowed rounds structurally (later rounds launch after the
    # heal), so no uniform draw can slow a session end to end; the
    # mutation surface CAN — retiming the unslow event stretches the
    # window across a whole measurement phase (the legal template
    # range), which is the schedule shape the hunt must discover
    hunt_gray = GrayFailure(
        targets=(0, 1, 2, 3), n_links=1, mult_min=4, mult_max=12,
        t_min_ns=20_000_000, t_max_ns=600_000_000,
        dur_min_ns=50_000_000, dur_max_ns=80_000_000,
    )
    space = FaultPlan((ARMY, hunt_gray), name="slo-hunt")
    gens, batch = 8, max(n_seeds // 8, 32)
    budget = gens * batch
    min_ops = 8
    uni = search_seeds(
        WL, CFG, _ONES, plan=space, n_seeds=budget,
        max_steps=MAX_STEPS, require_halt=False, latency=SPEC,
    )
    # calibrate: the worst provable window-p99 bucket uniform reached
    total = uni.lat_hist.sum(axis=-1)  # (S, P)
    qb = obs.hist_quantile_bucket(uni.lat_hist, 0.99)
    qb = np.where(total >= min_ops, qb, -1)
    worst_uni = int(qb.max())
    bound = int(lat_bucket_hi(worst_uni))
    slo = check.slo_bounded(bound, q=0.99, min_ops=min_ops)
    uni_found = int(check.slo_breaches(
        uni.lat_hist, bound, q=0.99, min_ops=min_ops
    ).sum())
    print(f"  uniform worst window-p99 bucket over {budget} sims: "
          f"{worst_uni} (<= {bound / 1e6:.2f}ms)")
    print(f"  SLO: p99 <= {bound / 1e6:.2f}ms per "
          f"{SPEC.phase_ns / 1e6:.0f}ms window, min {min_ops} ops — a "
          f"breach must land >= 2 ladder buckets (~40%) past uniform's "
          f"extreme")
    rep = explore.run(
        WL, CFG, space, invariant=slo, generations=gens, batch=batch,
        root_seed=7, max_steps=MAX_STEPS, cov_words=64, latency=SPEC,
        log=lambda s: print(f"  {s}"),
    )
    print(f"  uniform: {uni_found} breach(es) in {budget} sims "
          f"(0 by construction); guided: {len(rep.violations)} in "
          f"{rep.sims} sims")
    ok4 = uni_found == 0 and len(rep.violations) > 0
    if not ok4:
        failures.append("hunt")
    print(f"cert4 {'PASS' if ok4 else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 5: shrink -> replay -> explain ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 5: ddmin-shrink the breach, replay, explain ==")
    ok5 = True
    if not rep.violations:
        print("  (no breach to shrink — cert 4 already failed)")
        ok5 = False
    else:
        entry = rep.violations[0]
        res = shrink_plan(
            WL, CFG, entry.seed, entry.plan, invariant=slo,
            max_steps=MAX_STEPS, latency=SPEC,
        )
        print("  " + res.banner().replace("\n", "\n  "))
        replay = explore.replay_entry(
            WL, CFG, dataclasses.replace(entry, plan=res.plan),
            invariant=slo, max_steps=MAX_STEPS, latency=SPEC,
        )
        exact = int(replay.traces[0]) == res.trace
        still = bool(~replay.ok[0])
        print(f"  replay: trace {'identical' if exact else 'DIVERGED'}, "
              f"breach {'reproduced' if still else 'LOST'}")
        ok5 = exact and still
        text = obs.explain(
            WL, CFG, entry.seed, plan=res.plan, invariant=slo,
            max_steps=MAX_STEPS, timeline_cap=4096, latency=SPEC,
        )
        has_lat = "--- latency:" in text and "p99<=" in text
        has_verdict = "VIOLATED" in text
        print("  explain excerpt:")
        for line in text.splitlines():
            if line.startswith("---") or "window [" in line or \
                    "slowest" in line:
                print(f"    {line}")
        print(f"  explain narrates percentiles: {has_lat}, "
              f"verdict line: {has_verdict}")
        ok5 = ok5 and has_lat and has_verdict
    if not ok5:
        failures.append("shrink-replay-explain")
    print(f"cert5 {'PASS' if ok5 else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    if failures:
        print(f"LATENCY SOAK FAIL: {failures}")
        sys.exit(1)
    print("LATENCY SOAK PASS")


if __name__ == "__main__":
    main()
