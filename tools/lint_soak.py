"""Static-determinism soak: the full jaxpr non-interference matrix plus
the repo-wide nondeterminism-leak lint. The LINT evidence artifact.

Four certificates:

1. **Non-interference matrix** — the six recorded models (raft,
   kvchaos, paxos, raftlog, leasekv, shardkv; each with history
   recording on and off, kvchaos additionally with the client-army
   latency markers, raftlog additionally with the disk discipline on,
   the service models with their own army rows) x every observability
   build axis (base / metrics / timeline / coverage / hit-count /
   latency / all) x every lowering tuple (scatter/int64, dense, time32
   where eligible, and the readiness-indexed pool rows — ISSUE 13:
   the tile-summary columns sit on the CORE side, so the proof
   obligation over the indexed program is that no obs column reaches
   them or anything else core), traced via the single-seed step AND
   the vmapped ``make_run`` scan path, plus the sharded-campaign row
   (every model under the campaign tap set, proved through the
   ``shard_map`` call boundary — the program shape
   ``explore.run_device`` dispatches), plus the flight-recorder
   boundary row (the same campaign program traced with an
   ``obs.prof.ProgramProfiler`` active: no host-callback primitive,
   taint unchanged — the flight taps are provably host-side): every
   derived column provably isolated from every core column and the
   trace fold.

   **1c (dynamic):** the tile summaries' own derived-only certificate
   — a taint proof cannot state "value-identical", so the pool-index
   row is paired with a runtime bit-identity check: the indexed and
   flat lowerings produce identical traces/pools/histories on a
   chaos-bearing batch, and the carried summaries equal a
   from-scratch ``engine.build_pool_index`` rebuild.

   **1d (dynamic):** the farm non-interference row (ISSUE 16) — the
   energy machinery draws on its own registered threefry lane, so
   passing ``energy=None`` / ``EnergySchedule(mode="uniform")`` to
   ``explore.run`` must be bit-identical to not passing the argument
   at all: energy off is provably inert, the reproducible default.

   **1e (dynamic):** the device-detector on/off certificate (ISSUE
   18) — arming a fused ``check.device`` history screen
   (``search_seeds(device_check=...)``) is verdict-only: the
   simulation columns (traces, halt set, histories) are bit-identical
   to the unarmed host-judged sweep, and the screen's verdict equals
   the authoritative numpy detector on the unarmed arm's histories.
2. **Planted-leak positive control** — the ``met -> step`` mutant (one
   value-identical op reading a metrics counter into the RNG cursor)
   is caught, with the offending equation chain and the column names.
3. **Repo-wide lint** — the default surface (madsim_tpu/, examples/,
   tools/, bench.py) is finding-free; every intentional real-mode site
   is enumerated by a live ``# lint: allow(rule)`` pragma (the checked
   allowlist — a stale pragma is itself a finding).
4. **Rule fixtures** — every linter rule fires on a canonical negative
   fixture (the linter's own positive control).
5. **Interval-prover smoke** — the absint overflow + lane proofs on
   raft/record across the full lowering sweep, both planted mutants
   (time32 sentinel decay, lane collision) caught with cited chains.
   The FULL absint matrix is its own artifact (tools/absint_soak.py,
   `make absint-soak`).

Usage: python tools/lint_soak.py > LINT_r11.txt
Exit 0 iff every certificate holds.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import jax

from madsim_tpu.lint import (  # noqa: E402
    check_matrix,
    check_noninterference,
    lint_repo,
    lint_source,
    plant_met_leak,
)
from madsim_tpu.lint.noninterference import (  # noqa: E402
    BUILD_AXES,
    CAMPAIGN_AXES,
    CHECK_AXES,
    FLIGHT_AXES,
    LAYOUT_AXES,
)
from madsim_tpu.engine import EngineConfig  # noqa: E402
from madsim_tpu.models import make_raft  # noqa: E402


def main() -> None:
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# lint soak: platform={jax.devices()[0].platform}")

    # ---- certificate 1: the full non-interference matrix ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 1: jaxpr non-interference, model x build-flag matrix ==")
    reports = check_matrix(layouts=LAYOUT_AXES, log=lambda s: print(f"  {s}"))
    bad = [r for r in reports if not r.ok]
    n_eqns = sum(r.n_eqns for r in reports)
    print(f"  step-entry matrix: {len(reports)} proofs, "
          f"{n_eqns} equations walked, {len(bad)} leak(s)")
    # the scan path: one run-entry proof per model at the widest flags
    run_reports = check_matrix(
        axes={"all": BUILD_AXES["all"]}, entry="run",
        log=lambda s: print(f"  {s}"),
    )
    bad += [r for r in run_reports if not r.ok]
    # the pod-scale row: every model under the campaign tap set, the
    # batched run proved THROUGH the shard_map boundary — the program
    # shape explore.run_device dispatches every generation
    sharded_reports = check_matrix(
        axes=CAMPAIGN_AXES, entry="sharded_run",
        log=lambda s: print(f"  {s}"),
    )
    bad += [r for r in sharded_reports if not r.ok]
    # the flight-recorder boundary row: the campaign tap set traced
    # with an obs.prof.ProgramProfiler ACTIVE through the shard_map
    # boundary — the profiler/heartbeat/memory taps are host-side by
    # design, and this proves the traced program stays callback-free
    # and taint-isolated with them armed
    flight_reports = check_matrix(
        axes=FLIGHT_AXES, entry="sharded_run",
        log=lambda s: print(f"  {s}"),
    )
    bad += [r for r in flight_reports if not r.ok]
    # the device-verification row (ISSUE 14): every model with the
    # check.device detector kernels traced WITH the sim through the
    # shard_map boundary — the explore.run_device history-hunt program
    # shape. Proof obligations: the detectors touch only the derived
    # history columns and the new check_ok verdict output (taint set
    # unchanged), and the program stays host-callback-free
    check_reports = check_matrix(
        axes=CHECK_AXES, entry="sharded_run",
        log=lambda s: print(f"  {s}"),
    )
    bad += [r for r in check_reports if not r.ok]
    if bad:
        failures.append("noninterference")
        for r in bad:
            print(r.summary())
    print(f"cert1 {'PASS' if not bad else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 1c: pool-index derived-only, the dynamic half ----
    # (the static rows above prove obs isolation over the indexed
    # program; bit-identity of the indexed lowering itself is a VALUE
    # property no taint walk can witness — certified here at runtime)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 1c: readiness-index on/off bit-identity (dynamic) ==")
    import dataclasses as _dc

    import numpy as _np

    from madsim_tpu.engine import (
        POOL_INDEX_STATE_FIELDS,
        build_pool_index,
        make_init,
        make_run,
        pool_tile,
    )

    _wl = make_raft(record=True)
    _cfg = EngineConfig(
        pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
    )
    _seeds = _np.arange(64, dtype=_np.uint64)
    _a = jax.block_until_ready(jax.jit(make_run(
        _wl, _cfg, 300, layout="scatter", pool_index=False
    ))(make_init(_wl, _cfg, pool_index=False)(_seeds)))
    _b = jax.block_until_ready(jax.jit(make_run(
        _wl, _cfg, 300, layout="scatter", pool_index=True
    ))(make_init(_wl, _cfg, pool_index=True)(_seeds)))
    _div = [
        f.name for f in _dc.fields(_a)
        if f.name not in POOL_INDEX_STATE_FIELDS
        and not _np.array_equal(
            _np.asarray(getattr(_a, f.name)), _np.asarray(getattr(_b, f.name))
        )
    ]
    _tm, _tc = build_pool_index(
        _b.ev_time, _b.ev_valid, pool_tile(_cfg.pool_size)
    )
    _mask = _np.asarray(_tc) > 0
    _sum_ok = _np.array_equal(
        _np.asarray(_tc), _np.asarray(_b.tile_cnt)
    ) and _np.array_equal(
        _np.asarray(_tm)[_mask], _np.asarray(_b.tile_min)[_mask]
    )
    if _div or not _sum_ok:
        failures.append("pool-index-identity")
        print(f"  DIVERGED fields={_div} summaries_ok={_sum_ok}")
    else:
        print(f"  indexed == flat over {len(_dc.fields(_a)) - 2} fields; "
              f"carried summaries == from-scratch rebuild")
    print(f"cert1c {'PASS' if not (_div or not _sum_ok) else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 1d: farm energy off is provably inert ----
    # (ISSUE 16: energy draws live on their own registered threefry
    # lane; off/uniform must replay the historical schedule exactly)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 1d: farm energy-off bit-identity (dynamic) ==")
    from madsim_tpu import explore as _explore
    from madsim_tpu.chaos import FaultPlan as _FaultPlan
    from madsim_tpu.chaos import PauseStorm as _PauseStorm
    from madsim_tpu.farm import EnergySchedule as _ES

    _eplan = _FaultPlan((
        _PauseStorm(targets=(0, 1, 2, 3, 4), n=1, t_min_ns=20_000_000,
                    t_max_ns=300_000_000, down_min_ns=50_000_000,
                    down_max_ns=200_000_000),
    ), name="lint-energy")
    _ekw = dict(generations=3, batch=16, root_seed=11, max_steps=200,
                cov_words=8, invariant=lambda v: (v["trace"] & 7) != 0)
    _ewl = make_raft()

    def _efp(rep):
        return (
            [(e.id, e.seed, e.trace, e.new_bits) for e in rep.corpus],
            rep.cov_map.tolist(),
            [(e.seed, e.trace) for e in rep.violations],
            rep.curve, rep.viol_curve,
        )

    _base = _efp(_explore.run(_ewl, _cfg, _eplan, **_ekw))
    _off = _efp(_explore.run(_ewl, _cfg, _eplan, energy=None, **_ekw))
    _uni = _efp(_explore.run(
        _ewl, _cfg, _eplan, energy=_ES(mode="uniform"), **_ekw
    ))
    _energy_ok = _base == _off == _uni
    if not _energy_ok:
        failures.append("farm-energy-identity")
        print("  DIVERGED: energy off/uniform changed the campaign")
    else:
        print(f"  absent == None == uniform over {len(_base[0])} corpus "
              f"entries, {len(_base[2])} violations")
    print(f"cert1d {'PASS' if _energy_ok else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 1e: device detector on/off bit-identity ----
    # (ISSUE 18: the fused history screens are verdict-only — arming
    # one must not perturb a single simulation bit, and its verdict
    # must equal the authoritative numpy detector on the unarmed arm)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 1e: device-detector on/off bit-identity (dynamic) ==")
    from madsim_tpu.check import device as _dcheck
    from madsim_tpu.check import lease_safety as _lease_safety
    from madsim_tpu.engine import search_seeds as _search_seeds
    from madsim_tpu.models import make_leasekv as _make_leasekv
    from madsim_tpu.models.leasekv import OP_EXPIRE as _OPE
    from madsim_tpu.models.leasekv import OP_PUT as _OPP

    _det_ok = True
    _lcfg = EngineConfig(
        pool_size=48, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
    )
    _lscreens = (_dcheck.lease_safety(_OPP, _OPE),)
    for _tag, _lkw in (
        ("clean", dict(record=True)),
        ("mutant", dict(record=True, bug=True, ttl_ms=50)),
    ):
        _lwl = _make_leasekv(**_lkw)
        _lbox = {}

        def _lhinv(h, _b=_lbox):
            _b["h"] = h
            return _np.ones(len(h.count), bool)

        _skw = dict(n_seeds=128, max_steps=2500, require_halt=False)
        _roff = _search_seeds(
            _lwl, _lcfg, None, history_invariant=_lhinv, **_skw
        )
        _ron = _search_seeds(
            _lwl, _lcfg, None, device_check=_lscreens, **_skw
        )
        _h = _lbox["h"]
        _host_mask = _lease_safety(_h, _OPP, _OPE)
        _sim_same = _np.array_equal(_roff.traces, _ron.traces) and \
            _np.array_equal(_roff.halted, _ron.halted) and \
            _np.array_equal(_roff.overflowed, _ron.overflowed)
        _verdict_same = _np.array_equal(_ron.screen_ok, _host_mask)
        # the escalation payload: exactly the flagged seeds' histories,
        # bit-identical to the unarmed arm's rows
        _fl = _ron.flagged_idx
        _fh = _ron.flagged_history
        _payload_same = _np.array_equal(
            _fl, _np.nonzero(~_host_mask & ~_roff.overflowed)[0]
        ) and _np.array_equal(_fh.count, _h.count[_fl]) and \
            _np.array_equal(_fh.word, _h.word[_fl])
        _n_fl = len(_fl)
        if not (_sim_same and _verdict_same and _payload_same):
            _det_ok = False
            print(f"  {_tag}: DIVERGED sim={_sim_same} "
                  f"verdict={_verdict_same} payload={_payload_same}")
        else:
            print(f"  {_tag}: armed == unarmed over 128 seeds "
                  f"({_n_fl} flagged, payloads bit-identical)")
    if not _det_ok:
        failures.append("detector-identity")
    print(f"cert1e {'PASS' if _det_ok else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 2: the planted met->step leak is caught ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 2: planted derived->core leak (positive control) ==")
    rep = check_noninterference(
        make_raft(record=True),
        EngineConfig(
            pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
        ),
        metrics=True,
        mutate=plant_met_leak,
    )
    caught = (
        not rep.ok
        and "step" in rep.leaks
        and "met" in rep.leaks["step"]["labels"]
        and bool(rep.leaks["step"]["chain"])
    )
    print(rep.summary())
    if not caught:
        failures.append("mutant")
    print(f"cert2 {'PASS' if caught else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 3: repo-wide lint is clean ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 3: repo-wide nondeterminism-leak lint ==")
    res = lint_repo()
    for f in res.findings:
        print(f"  FINDING {f}")
    by_rule: dict = {}
    for f in res.allowed:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    print(f"  {res.n_files} files, {len(res.findings)} finding(s), "
          f"{len(res.allowed)} allowlisted site(s) by rule: "
          + ", ".join(f"{k}={v}" for k, v in sorted(by_rule.items())))
    for f in res.allowed:
        print(f"  allow {f.path}:{f.line} [{f.rule}]")
    if not res.ok:
        failures.append("repo-lint")
    print(f"cert3 {'PASS' if res.ok else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 4: every rule fires on its negative fixture ----
    print("== cert 4: rule fixtures (linter positive controls) ==")
    fixtures = {
        "wall-clock": "import time\ns = int(time.time_ns())\n",
        "ambient-entropy": "import os\nx = os.urandom(8)\n",
        "uuid-entropy": "import uuid\nu = uuid.uuid4()\n",
        "np-random": "import numpy as np\nx = np.random.rand()\n",
        "unordered-iter": "for x in set([1, 2]):\n    pass\n",
        "id-hash-branch": "if id(object()) % 2:\n    pass\n",
        "host-callback": (
            "from jax.experimental import io_callback\n"
            "io_callback(print, None, 1)\n"
        ),
        "fixed-key": "import jax\nk = jax.random.PRNGKey(0)\n",
        "unused-allow": "x = 1  # lint: allow(np-random)\n",
    }
    rules_ok = True
    for rule, src in fixtures.items():
        hit = rule in [
            f.rule for f in lint_source(src, "fx.py", sim_code=True).findings
        ]
        print(f"  {rule}: {'fires' if hit else 'MISSED'}")
        rules_ok &= hit
    if not rules_ok:
        failures.append("rule-fixtures")
    print(f"cert4 {'PASS' if rules_ok else 'FAIL'}")

    # ---- certificate 5: interval-prover smoke (absint) ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 5: absint overflow + lane smoke (full matrix: "
          "make absint-soak) ==")
    from madsim_tpu.lint import (
        ABSINT_AXES,
        absint_matrix,
        absint_model_matrix,
        run_mutant_controls,
    )

    amodels = [m for m in absint_model_matrix() if m[0] == "raft/record"]
    areps = absint_matrix(
        amodels, {"all": ABSINT_AXES["all"]}, layouts=LAYOUT_AXES,
        log=lambda s: print(f"  {s}"),
    )
    abad = [r for r in areps if not r.ok]
    controls = run_mutant_controls()
    mut_ok = all(caught for _n, _r, caught in controls)
    for name, _rep, caught in controls:
        print(f"  {name} mutant caught: {caught}")
    if abad or not mut_ok:
        failures.append("absint")
        for r in abad:
            print(r.summary())
    print(f"cert5 {'PASS' if not abad and mut_ok else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    if failures:
        print(f"# FAILURES: {failures}")
        sys.exit(1)
    print("# all certificates PASS")


if __name__ == "__main__":
    main()
