"""Explore soak: coverage-guided search vs uniform chaos, plus the
targeted diskless-raftlog hunt. The EXPLORE evidence artifact.

Four certificates:

1. **Guided beats uniform at equal budget** — on the kvchaos
   ``bug=True`` lost-write mutant, the same simulation budget is spent
   twice: once as a uniform nemesis sweep (``search_seeds(plan=...)``,
   the PR-2 shape) and once as a coverage-guided campaign
   (``explore.run``). The campaign must reach STRICTLY more coverage
   bits and at least 2x the distinct violation count. The per-
   generation coverage/violation curves are printed — the growth curve
   is the artifact's centerpiece.
2. **Campaign determinism** — the same root seed re-runs to an
   identical corpus, coverage map and violation set; a violating
   entry replays to its recorded trace hash and its stored plan
   ddmin-shrinks + replays exactly (the full explore -> chaos.shrink
   pipeline on one find).
3. **The diskless-raftlog hunt** — ROADMAP's open target: diskless
   raftlog (durable=False) can lose a committed value when BOTH
   fresh-log voters are wiped while the up-to-date holders are
   partitioned away (the reason raft's Figure 2 marks term/votedFor/
   log persistent); 8192 uniform nemesis schedules never triggered it.
   The hunt runs a targeted plan space (two-crash storm + flapping
   partition) under the guided loop; electoral double-votes (wiped
   votedFor) count as the same diskless-persistence bug class. If a
   committed-value loss or double-vote is found it is shrunk to a
   minimal replayable plan; otherwise the coverage evidence documents
   the negative result (exit stays 0 — the certificate is the
   INSTRUMENTED hunt, the find is the prize).
4. **Shrink integration** — the first hunt violation (if any) feeds
   ``chaos.shrink_plan`` and the shrunk plan replays to the identical
   violation + trace.

Usage: python tools/explore_soak.py [budget] > EXPLORE_r08.txt
Exit 0 iff certificates 1-2 (and 4, when a find exists) hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    FaultPlan,
    FlappingPartition,
    shrink_plan,
)
from madsim_tpu.check import (  # noqa: E402
    election_safety,
    read_your_writes,
    stale_reads,
)
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import make_kvchaos, make_raftlog  # noqa: E402
from madsim_tpu.models.raftlog import OP_COMMIT, OP_ELECT  # noqa: E402

W = 10  # kvchaos writes (the nemesis-soak shape)
KV_STEPS = 4000
CW = 64  # coverage words (2048 bits)

KV_PLAN = FaultPlan((
    CrashStorm(
        targets=(1, 2, 3, 4), n=2,
        t_min_ns=20_000_000, t_max_ns=400_000_000,
        down_min_ns=50_000_000, down_max_ns=250_000_000,
    ),
), name="kv-nemesis")

RL_NODES = (0, 1, 2, 3, 4)
HUNT_PLAN = FaultPlan((
    CrashStorm(
        targets=RL_NODES, n=2,
        t_min_ns=150_000_000, t_max_ns=500_000_000,
        down_min_ns=100_000_000, down_max_ns=400_000_000,
    ),
    FlappingPartition(
        targets=RL_NODES, n_cycles=2,
        t_min_ns=50_000_000, t_max_ns=400_000_000,
        dur_min_ns=100_000_000, dur_max_ns=300_000_000,
        up_min_ns=20_000_000, up_max_ns=200_000_000,
    ),
), name="raftlog-hunt")
HUNT_STEPS = 6000


def kv_hinv(box):
    def inv(h):
        box["ok"] = stale_reads(h) & read_your_writes(h)
        return box["ok"]

    return inv


def main() -> None:
    budget = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    gens = 8
    batch = max(budget // gens, 1)
    # equal budget is the certificate's whole point: both sides run
    # EXACTLY gens * batch sims, whatever was asked for
    budget = gens * batch
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# explore soak: budget {budget} sims/side, "
          f"platform={jax.devices()[0].platform}")
    print(f"# kv plan {KV_PLAN.hash()} | hunt plan {HUNT_PLAN.hash()} "
          f"({HUNT_PLAN.slots} slots)")

    # ---- certificate 1: guided vs uniform at equal budget ----
    wl_bug = make_kvchaos(writes=W, record=True, bug=True, chaos=False)
    kv_cfg = EngineConfig(pool_size=192, loss_p=0.05)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    rep_u = search_seeds(
        wl_bug, kv_cfg, None, n_seeds=budget, max_steps=KV_STEPS,
        history_invariant=kv_hinv(box), plan=KV_PLAN, cov_words=CW,
    )
    u_viol = int((~box["ok"] & ~rep_u.overflowed).sum())
    u_bits = explore.popcount(
        explore.merge(np.where(rep_u.overflowed[:, None], 0, rep_u.cov))
    )
    print(f"uniform sweep:    {u_viol} violations, {u_bits} coverage bits "
          f"/ {budget} sims ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    t0 = time.monotonic()  # lint: allow(wall-clock)
    rep_e = explore.run(
        wl_bug, kv_cfg, KV_PLAN, history_invariant=kv_hinv({}),
        generations=gens, batch=batch, root_seed=7, max_steps=KV_STEPS,
        cov_words=CW, max_ops=1, inherit_seed_p=0.9,
    )
    print(f"guided campaign:  {len(rep_e.violations)} violations, "
          f"{rep_e.coverage_bits} coverage bits / {rep_e.sims} sims "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    print(f"  coverage curve:  {rep_e.curve}")
    print(f"  violation curve: {rep_e.viol_curve}")
    ratio = len(rep_e.violations) / max(u_viol, 1)
    print(f"  guided/uniform: {ratio:.2f}x violations, "
          f"+{rep_e.coverage_bits - u_bits} coverage bits")
    if rep_e.coverage_bits <= u_bits:
        failures.append("guided-not-more-coverage")
    if len(rep_e.violations) < 2 * u_viol:
        failures.append("guided-below-2x-violations")

    # ---- certificate 2: campaign determinism + replay + shrink ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    d_kw = dict(
        history_invariant=kv_hinv({}), generations=3, batch=64,
        root_seed=7, max_steps=KV_STEPS, cov_words=CW, max_ops=1,
        inherit_seed_p=0.9,
    )
    da = explore.run(wl_bug, kv_cfg, KV_PLAN, **d_kw)
    db = explore.run(wl_bug, kv_cfg, KV_PLAN, **d_kw)
    fp = lambda r: (  # noqa: E731
        [(e.id, e.seed, e.plan.hash(), e.trace) for e in r.corpus],
        r.cov_map.tolist(), [(e.seed, e.trace) for e in r.violations],
    )
    same = fp(da) == fp(db)
    replay_ok = shrink_ok = True
    if da.violations:
        e = da.violations[0]
        box = {}
        r = explore.replay_entry(
            wl_bug, kv_cfg, e, history_invariant=kv_hinv(box),
            max_steps=KV_STEPS,
        )
        replay_ok = int(r.traces[0]) == e.trace and not bool(box["ok"][0])
        res = shrink_plan(
            wl_bug, kv_cfg, e.seed, e.plan,
            history_invariant=kv_hinv({}), max_steps=KV_STEPS,
        )
        rs = explore.replay_entry(
            wl_bug, kv_cfg,
            explore.CorpusEntry(
                id=-1, generation=-1, parent=-1, seed=e.seed,
                plan=res.plan, trace=res.trace, cov=e.cov, new_bits=0,
                violating=True,
            ),
            history_invariant=kv_hinv({}), max_steps=KV_STEPS,
        )
        shrink_ok = int(rs.traces[0]) == res.trace
        print(f"determinism: identical={same}; violation g{e.generation} "
              f"id{e.id} replay={replay_ok}; shrink "
              f"{res.original_events} -> {len(res.events)} events, "
              f"shrunk replay={shrink_ok} ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    else:
        print(f"determinism: identical={same}; no violation in the small "
              f"campaign (replay/shrink not exercised) "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if not same:
        failures.append("campaign-not-deterministic")
    if not replay_ok:
        failures.append("violation-replay-diverged")
    if not shrink_ok:
        failures.append("shrunk-replay-diverged")

    # ---- certificates 3+4: the diskless-raftlog hunt ----
    wl_rl = make_raftlog(record=True, chaos=False, durable=False)
    rl_cfg = EngineConfig(
        pool_size=128, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
    )
    rl_box = {}

    def rl_inv(h):
        commit_ok = election_safety(h, elect_op=OP_COMMIT)
        elect_ok = election_safety(h, elect_op=OP_ELECT)
        rl_box["commit"] = commit_ok
        rl_box["elect"] = elect_ok
        return commit_ok & elect_ok

    t0 = time.monotonic()  # lint: allow(wall-clock)
    hunt = explore.run(
        wl_rl, rl_cfg, HUNT_PLAN, history_invariant=rl_inv,
        generations=gens, batch=batch, root_seed=2024,
        max_steps=HUNT_STEPS, cov_words=CW, select_top=24, max_ops=2,
        inherit_seed_p=0.85, require_halt=False,
    )
    print(f"raftlog hunt: {len(hunt.violations)} violations, "
          f"{hunt.coverage_bits} coverage bits / {hunt.sims} sims "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    print(f"  coverage curve:  {hunt.curve}")
    print(f"  violation curve: {hunt.viol_curve}")
    if hunt.violations:
        e = hunt.violations[0]
        rl_box.clear()
        r = explore.replay_entry(
            wl_rl, rl_cfg, e, history_invariant=rl_inv,
            max_steps=HUNT_STEPS,
        )
        kind = ("committed-value-loss"
                if not bool(rl_box["commit"][0]) else "double-vote")
        hr_ok = int(r.traces[0]) == e.trace
        print(f"  FOUND [{kind}]: root={hunt.root_seed} g{e.generation} "
              f"id{e.id} seed={e.seed} plan={e.plan.hash()} "
              f"trace={e.trace:#x} replay={hr_ok}")
        t0 = time.monotonic()  # lint: allow(wall-clock)
        res = shrink_plan(
            wl_rl, rl_cfg, e.seed, e.plan, history_invariant=rl_inv,
            max_steps=HUNT_STEPS,
        )
        print(res.banner())
        rs = search_seeds(
            wl_rl, rl_cfg, None, seeds=np.asarray([e.seed], np.uint64),
            max_steps=HUNT_STEPS, history_invariant=rl_inv,
            plan=res.plan, require_halt=False,
        )
        hs_ok = int(rs.traces[0]) == res.trace and not bool(rs.ok[0])
        print(f"  shrink: {res.original_events} -> {len(res.events)} "
              f"events, shrunk replay identical violation + trace: "
              f"{hs_ok} ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        if not hr_ok:
            failures.append("hunt-replay-diverged")
        if not hs_ok:
            failures.append("hunt-shrunk-replay-diverged")
    else:
        print("  NEGATIVE: no diskless committed-write loss or double-vote "
              "within this budget; the coverage curve above documents the "
              "explored behavior space (raise the budget to hunt deeper)")

    verdict = "PASS" if not failures else f"FAIL ({', '.join(failures)})"
    print(f"# verdict: {verdict} — coverage-guided exploration beats "
          f"uniform chaos at equal budget and every find replays from "
          f"its (root seed, generation, id) key")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
