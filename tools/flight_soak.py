"""Flight-recorder soak: the campaign observability certificates.
The FLIGHT evidence artifact.

Four certificates:

1. **Retraces == 1 per cache key across a multi-campaign session**
   (the headline). A ``ProgramProfiler`` session runs THREE
   ``explore.run_device`` campaigns over the same (workload, config,
   space, batch) with three different root seeds — the repro-sweep
   shape of a real hunt session. Historically every campaign rebuilt
   its generation programs from fresh closures (one trace+lower+
   compile per campaign, ROADMAP item 1); the generation-program cache
   (``explore.device._GEN_CACHE``, keyed on workload/config/space/
   batch/build flags/invariant identity, root seed a runtime argument)
   must hold that to exactly ONE trace per program key, profiler-
   certified, with campaigns 2 and 3 reporting compile_wall_s == 0.
2. **Same-box interleaved cache A/B** — the same campaign run
   alternately with the cache active (steady state) and with the cache
   defeated per campaign (fresh workload + invariant identity — the
   pre-cache behavior). Rounds interleave so box noise hits both
   sides; the certificate is cached wall < uncached wall with the
   uncached side paying a fresh compile every campaign.
3. **Flight-recorder on/off bit-identity** — the same campaign with
   ``telemetry=None`` and with a full ``FlightRecorder`` (profiler +
   heartbeats + memory taps armed) must produce identical corpus,
   coverage map, violation set and curves on BOTH drivers; the flight
   JSONL must carry the complete wall-split schema
   (dispatch/compile/sync on the device driver, dispatch/compile/
   mutate/admit/host on the host driver), monotone heartbeats, and
   ``host_syncs: 1`` per device generation.
4. **Campaign Perfetto from a violation-bearing hunt** — a device
   campaign under a halt invariant (real finds) recorded through the
   flight recorder, exported with ``obs.campaign_perfetto``:
   generation spans == generations, coverage/violation counter tracks
   monotone, compile instants present. The trace JSON is written next
   to the artifact (open in ui.perfetto.dev).

Usage: python tools/flight_soak.py [batch] [gens] [trace_out]
           > FLIGHT_r08.txt
Defaults: batch 4096, gens 4, trace_out FLIGHT_campaign_trace.json.
Exit 0 iff all four certificates hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import os
import statistics
import sys
import tempfile
import time

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    FaultPlan,
    GrayFailure,
    PauseStorm,
)
from madsim_tpu.engine import EngineConfig  # noqa: E402
from madsim_tpu.explore import device as _device  # noqa: E402
from madsim_tpu.models import make_raft  # noqa: E402
from madsim_tpu.obs import (  # noqa: E402
    FlightRecorder,
    campaign_perfetto,
    write_campaign_perfetto,
)
from madsim_tpu.obs import prof  # noqa: E402

NODES = (0, 1, 2, 3, 4)
CFG = EngineConfig(pool_size=64, loss_p=0.02)
PLAN = FaultPlan((
    CrashStorm(targets=(1, 2, 3), n=2, t_min_ns=20_000_000,
               t_max_ns=400_000_000, down_min_ns=50_000_000,
               down_max_ns=250_000_000),
    PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
               t_max_ns=300_000_000, down_min_ns=50_000_000,
               down_max_ns=200_000_000),
    GrayFailure(targets=NODES, n_links=1),
), name="flight-soak")
MAX_STEPS = 64

DEVICE_WALL_KEYS = ("dispatch_wall_s", "compile_wall_s", "sync_wall_s")
HOST_WALL_KEYS = ("dispatch_wall_s", "compile_wall_s", "mutate_wall_s",
                  "admit_wall_s", "host_wall_s")


def _cov_inv(view):
    return view["halted"] | True


def _halt_inv(view):
    return view["halted"]


def _fingerprint(rep):
    return (
        [(e.id, e.generation, e.parent, e.seed, e.plan.hash(), e.trace,
          e.new_bits) for e in rep.corpus],
        rep.cov_map.tolist(),
        [(e.seed, e.trace) for e in rep.violations],
        rep.curve,
        rep.viol_curve,
    )


def main() -> None:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    gens = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    trace_out = sys.argv[3] if len(sys.argv) > 3 else (
        "FLIGHT_campaign_trace.json"
    )
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# flight soak: batch {batch}, {gens} generations/campaign, "
          f"platform={jax.devices()[0].platform}")
    print(f"# plan {PLAN.hash()} ({PLAN.slots} slots), raft, "
          f"max_steps {MAX_STEPS}")

    wl = make_raft()  # ONE workload object: cache identity, like search
    kw = dict(generations=gens, batch=batch, max_steps=MAX_STEPS,
              cov_words=32, invariant=_cov_inv)

    # ---- cert 1: retraces == 1 per key over a 3-campaign session ----
    print("== cert 1: generation-program retraces across 3 campaigns ==")
    _device._GEN_CACHE.clear()
    compile_walls = []
    with prof.profiled() as p:
        for i, root in enumerate((7, 8, 9)):
            t0 = time.monotonic()  # lint: allow(wall-clock)
            rep = explore.run_device(wl, CFG, PLAN, root_seed=root, **kw)
            w = time.monotonic() - t0  # lint: allow(wall-clock)
            compile_walls.append(rep.wall_compile_s)
            print(f"  campaign {i} (root {root}): {w:6.1f}s wall, "
                  f"compile {rep.wall_compile_s:6.2f}s, dispatch "
                  f"{rep.wall_dispatch_s:6.2f}s, {len(rep.corpus)} corpus")
        retr = p.retraces("explore.device")
        print("  profiler program table:")
        for line in p.report().splitlines():
            print(f"    {line}")
    ok1 = (
        retr
        and all(v == 1 for v in retr.values())
        and compile_walls[1] == 0.0
        and compile_walls[2] == 0.0
    )
    print(f"  retraces per key: "
          f"{ {k[0]: v for k, v in retr.items()} } "
          f"(was: one full rebuild per campaign)")
    if not ok1:
        failures.append("retraces")
    print(f"cert1 {'PASS' if ok1 else 'FAIL'}")

    # ---- cert 2: interleaved cache A/B ----
    print("== cert 2: same-box interleaved A/B, cache on vs defeated ==")
    walls = {"cached": [], "uncached": []}
    for r in range(3):
        t0 = time.monotonic()  # lint: allow(wall-clock)
        explore.run_device(wl, CFG, PLAN, root_seed=20 + r, **kw)
        walls["cached"].append(
            time.monotonic() - t0  # lint: allow(wall-clock)
        )
        # defeat the cache the way pre-cache code did implicitly:
        # fresh workload + fresh invariant identity = new cache key =
        # full trace+lower+compile for this campaign (the warm entry
        # for `wl` is untouched, so the next cached round stays warm)
        t0 = time.monotonic()  # lint: allow(wall-clock)
        explore.run_device(
            make_raft(), CFG, PLAN, root_seed=20 + r,
            **{**kw, "invariant": lambda v: v["halted"] | True},
        )
        walls["uncached"].append(
            time.monotonic() - t0  # lint: allow(wall-clock)
        )
        print(f"  round {r}: cached {walls['cached'][-1]:6.1f}s | "
              f"uncached {walls['uncached'][-1]:6.1f}s | ratio "
              f"{walls['uncached'][-1] / walls['cached'][-1]:.2f}x")
    med_c = statistics.median(walls["cached"])
    med_u = statistics.median(walls["uncached"])
    ratio = med_u / med_c
    print(f"  medians: cached {med_c:.1f}s vs uncached {med_u:.1f}s -> "
          f"cache saves {med_u - med_c:.1f}s/campaign ({ratio:.2f}x)")
    ok2 = ratio > 1.1
    if not ok2:
        failures.append("cache-ab")
    print(f"cert2 {'PASS' if ok2 else 'FAIL'}")

    # ---- cert 3: flight on/off bit-identity + schema ----
    print("== cert 3: flight-recorder on/off bit-identity (both drivers) ==")
    vkw = dict(generations=3, batch=min(batch, 4096), root_seed=7,
               max_steps=96, cov_words=32, invariant=_halt_inv)
    tmp = tempfile.mkdtemp(prefix="flight_soak_")
    ok3 = True
    for tag, runner in (("device", explore.run_device),
                        ("host", explore.run)):
        rep_off = runner(wl, CFG, PLAN, **vkw)
        path = os.path.join(tmp, f"{tag}.jsonl")
        with FlightRecorder(path, heartbeat_s=0.0) as fr:
            rep_on = runner(wl, CFG, PLAN, telemetry=fr, **vkw)
        identical = _fingerprint(rep_off) == _fingerprint(rep_on)
        recs = [json.loads(line) for line in open(path)]
        gen_recs = [x for x in recs if x["event"] == "generation"]
        want = DEVICE_WALL_KEYS if tag == "device" else HOST_WALL_KEYS
        schema = all(all(k in g for k in want) for g in gen_recs)
        syncs = (
            all(g["host_syncs"] == 1 for g in gen_recs)
            if tag == "device" else True
        )
        hbs = [x for x in recs if x["event"] == "heartbeat"]
        seqs = [x["seq"] for x in recs]
        monotone = (
            seqs == sorted(seqs)
            and [h["generations_done"] for h in hbs]
            == sorted(h["generations_done"] for h in hbs)
            and len(hbs) == len(gen_recs)
        )
        print(f"  {tag}: identical {identical}, wall-split schema "
              f"{schema}, host_syncs {syncs}, heartbeats "
              f"{len(hbs)} monotone {monotone}")
        ok3 = ok3 and identical and schema and syncs and monotone
    if not ok3:
        failures.append("flight-identity")
    print(f"cert3 {'PASS' if ok3 else 'FAIL'}")

    # ---- cert 4: campaign Perfetto from a violation-bearing hunt ----
    print("== cert 4: campaign Perfetto (violation-bearing hunt) ==")
    path = os.path.join(tmp, "hunt.jsonl")
    _device._GEN_CACHE.clear()  # a cold campaign: compile events real
    with FlightRecorder(path, heartbeat_s=0.0) as fr:
        rep = explore.run_device(wl, CFG, PLAN, telemetry=fr, **vkw)
    doc = write_campaign_perfetto(trace_out, path)
    spans = [e for e in doc["traceEvents"] if e.get("cat") == "generation"]
    compiles = [e for e in doc["traceEvents"] if e.get("cat") == "compile"]

    def counter_track(name):
        return [
            e["args"][name] for e in doc["traceEvents"]
            if e.get("ph") == "C" and e.get("name") == name
        ]

    cov = counter_track("cov_bits")
    vio = counter_track("violations")
    ok4 = (
        len(spans) == vkw["generations"]
        and len(rep.violations) > 0
        and cov == sorted(cov)
        and vio == sorted(vio)
        and len(compiles) >= 1
        and campaign_perfetto(path)["otherData"]["generations"]
        == vkw["generations"]
    )
    print(f"  {len(spans)} generation spans == {vkw['generations']} "
          f"generations, {len(rep.violations)} violations, cov track "
          f"{cov} monotone, violation track {vio} monotone, "
          f"{len(compiles)} compile instant(s)")
    print(f"  trace written to {trace_out} "
          f"({len(doc['traceEvents'])} events — open in ui.perfetto.dev)")
    if not ok4:
        failures.append("campaign-perfetto")
    print(f"cert4 {'PASS' if ok4 else 'FAIL'}")

    print(f"# total {time.monotonic() - t_all:.1f}s | "  # lint: allow(wall-clock)
          f"{'ALL PASS' if not failures else 'FAIL: ' + ','.join(failures)}")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
