"""Storage-fault soak: crash-recovery safety under disk chaos. The
STORE evidence artifact.

Four certificates over raftlog ``durable=True`` (the two-phase sync
discipline — engine ``Workload.durable_sync``):

1. **Disk-faults-off identity** — with no injected disk faults the
   sync-discipline trajectory is bit-identical across dense/scatter
   layouts and the compacted runner at soak scale, and bit-identical
   to the C++ oracle (which implements verbatim-durable semantics —
   equal by the sync-every-write equivalence) on a seed sample.
2. **Correct placement holds clean** — fsync-before-reply raftlog under
   crash storms + flapping partitions + torn-write windows shows ZERO
   committed-value losses, double votes and recovery-safety violations
   at >= 2048 seeds.
3. **The detector is live (positive control)** — the same correct model
   under SYNC_LOSS (lying fsync) windows: ``check.recovery_safety``
   must flag seeds (a lying disk breaks raft's assumptions by design;
   this certifies the injection and the detector, not the protocol).
4. **The missing-sync mutant is caught** — ``bug="nosync"`` (acks
   escape before durability) under the DiskFault-grown guided hunt
   (madsim_tpu.explore): committed-value-loss found, ddmin-shrunk to a
   minimal literal plan, and the shrunk (seed, config, plan) replays to
   the identical violation + trace; ``obs.explain`` narrates the repro.

Usage: python tools/store_soak.py [seeds] > STORE_r10.txt
Exit 0 iff all four certificates hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore, obs  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    DiskFault,
    FaultPlan,
    FlappingPartition,
    shrink_plan,
)
from madsim_tpu.check import election_safety, recovery_safety  # noqa: E402
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import make_raftlog  # noqa: E402
from madsim_tpu.models.raftlog import (  # noqa: E402
    OP_COMMIT,
    OP_ELECT,
    OP_RECOVER,
    OP_SYNCED,
)

NODES = (0, 1, 2, 3, 4)
STEPS = 6000
CW = 64

# crash storms + route flapping + torn-write windows: the full storage
# fault space a correctly-fsyncing raft must survive
STORE_PLAN = FaultPlan((
    CrashStorm(
        targets=NODES, n=2, t_min_ns=150_000_000, t_max_ns=500_000_000,
        down_min_ns=100_000_000, down_max_ns=400_000_000,
    ),
    FlappingPartition(
        targets=NODES, n_cycles=2, t_min_ns=50_000_000,
        t_max_ns=400_000_000, dur_min_ns=100_000_000,
        dur_max_ns=300_000_000, up_min_ns=20_000_000, up_max_ns=200_000_000,
    ),
    DiskFault(
        targets=NODES, n_torn=2, t_min_ns=50_000_000, t_max_ns=500_000_000,
    ),
), name="store-hunt")

# lying-fsync windows: the positive control for the recovery detector
LIE_PLAN = FaultPlan((
    CrashStorm(
        targets=NODES, n=2, t_min_ns=150_000_000, t_max_ns=500_000_000,
        down_min_ns=100_000_000, down_max_ns=400_000_000,
    ),
    DiskFault(
        targets=NODES, n_torn=0, n_sync_loss=3, t_min_ns=10_000_000,
        t_max_ns=400_000_000, dur_min_ns=200_000_000, dur_max_ns=600_000_000,
    ),
), name="lying-disk")

CFG = EngineConfig(
    pool_size=128, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
)


def store_inv(box):
    def inv(h):
        box["commit"] = election_safety(h, elect_op=OP_COMMIT)
        box["elect"] = election_safety(h, elect_op=OP_ELECT)
        box["recover"] = recovery_safety(
            h, sync_op=OP_SYNCED, recover_op=OP_RECOVER
        )
        return box["commit"] & box["elect"] & box["recover"]

    return inv


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# store soak: {n_seeds} seeds/cert, "
          f"platform={jax.devices()[0].platform}")
    print(f"# fault space {STORE_PLAN.hash()} ({STORE_PLAN.slots} slots) | "
          f"lying-disk {LIE_PLAN.hash()}")
    wl = make_raftlog(record=True, chaos=False, durable=True)
    wl_bug = make_raftlog(record=True, chaos=False, durable=True,
                          bug="nosync")

    # ---- certificate 1: disk-faults-off identity ----
    # no plan anywhere here: the discipline alone (sync flags, disk
    # image, the per-step torn draw) must not move a single bit
    t0 = time.monotonic()  # lint: allow(wall-clock)
    kw = dict(n_seeds=n_seeds, max_steps=STEPS, require_halt=False)
    off_a = search_seeds(wl, CFG, None, layout="scatter",
                         history_invariant=store_inv({}), **kw)
    off_b = search_seeds(wl, CFG, None, layout="dense",
                         history_invariant=store_inv({}), **kw)
    off_c = search_seeds(wl, CFG, None, compact=True,
                         history_invariant=store_inv({}), **kw)
    ident = (np.array_equal(off_a.traces, off_b.traces)
             and np.array_equal(off_a.traces, off_c.traces))
    # oracle sample: the sync discipline with fsync-everywhere placement
    # is trajectory-identical to the oracle's verbatim-durable semantics
    from madsim_tpu.engine.oracle import run_oracle

    wl_orc = make_raftlog(durable=True)  # oracle path: chaos=True, no record
    orc = search_seeds(
        wl_orc, CFG, lambda v: np.ones(64, bool), n_seeds=64,
        max_steps=STEPS, require_halt=False,
    )
    orc_ok = all(
        run_oracle(wl_orc, CFG, s, STEPS, n_writes=4).trace
        == int(orc.traces[s])
        for s in range(0, 64, 7)
    )
    print(f"identity: layouts+compact identical={ident}, oracle sample "
          f"identical={orc_ok} ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if not ident:
        failures.append("layout-identity")
    if not orc_ok:
        failures.append("oracle-identity")

    # ---- certificate 2: correct placement clean under disk chaos ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    rep = search_seeds(wl, CFG, None, history_invariant=store_inv(box),
                       plan=STORE_PLAN, metrics=True, **kw)
    viol = int(rep.failing_seeds.size)
    n_loss = int((~box["commit"] & ~rep.overflowed).sum())
    n_dv = int((~box["elect"] & ~rep.overflowed).sum())
    n_rec = int((~box["recover"] & ~rep.overflowed).sum())
    met = obs.fleet_reduce(rep.met)
    print(f"clean cert: {viol} violations / {n_seeds} seeds "
          f"(commit-loss {n_loss}, double-vote {n_dv}, recovery {n_rec}; "
          f"{int(rep.overflowed.sum())} overflowed) "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    print(f"  fleet: syncs {met.total('sync')}, lied {met.total('sync_lost')},"
          f" torn kills {met.total('torn')}, crashes {met.total('crash')}")
    if viol or int(rep.overflowed.sum()):
        failures.append("clean-cert")
    if met.total("torn") == 0:
        failures.append("no-torn-kills-injected")

    # ---- certificate 3: lying-disk positive control ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    rep_lie = search_seeds(
        wl, CFG, None,
        history_invariant=lambda h: recovery_safety(
            h, sync_op=OP_SYNCED, recover_op=OP_RECOVER
        ),
        plan=LIE_PLAN, **kw,
    )
    n_lie = int(rep_lie.failing_seeds.size)
    print(f"lying-disk control: {n_lie} recovery-safety violations / "
          f"{n_seeds} seeds ({time.monotonic() - t0:.1f}s) — the detector "  # lint: allow(wall-clock)
          f"SEES a lying fsync (expected nonzero; a lying disk is outside "
          f"raft's assumptions, this certifies injection+detector)")
    if n_lie == 0:
        failures.append("positive-control-dead")

    # ---- certificate 4: the missing-sync mutant hunt ----
    gens = 8
    batch = max(n_seeds // gens, 1)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    hunt = explore.run(
        wl_bug, CFG, STORE_PLAN, history_invariant=store_inv({}),
        generations=gens, batch=batch, root_seed=1031, max_steps=STEPS,
        cov_words=CW, select_top=24, max_ops=2, inherit_seed_p=0.85,
        require_halt=False,
    )
    print(f"mutant hunt: {len(hunt.violations)} violations, "
          f"{hunt.coverage_bits} coverage bits / {hunt.sims} sims "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    print(f"  coverage curve:  {hunt.curve}")
    print(f"  violation curve: {hunt.viol_curve}")
    if not hunt.violations:
        failures.append("mutant-not-caught")
    else:
        e = hunt.violations[0]
        box_r = {}
        r = explore.replay_entry(
            wl_bug, CFG, e, history_invariant=store_inv(box_r),
            max_steps=STEPS,
        )
        kind = ("committed-value-loss" if not bool(box_r["commit"][0])
                else ("double-vote" if not bool(box_r["elect"][0])
                      else "recovery-regression"))
        hr_ok = int(r.traces[0]) == e.trace
        print(f"  FOUND [{kind}]: root={hunt.root_seed} g{e.generation} "
              f"id{e.id} seed={e.seed} plan={e.plan.hash()} "
              f"trace={e.trace:#x} replay={hr_ok}")
        t0 = time.monotonic()  # lint: allow(wall-clock)
        res = shrink_plan(
            wl_bug, CFG, e.seed, e.plan, history_invariant=store_inv({}),
            max_steps=STEPS,
        )
        print(res.banner())
        rs = search_seeds(
            wl_bug, CFG, None, seeds=np.asarray([e.seed], np.uint64),
            max_steps=STEPS, history_invariant=store_inv({}),
            plan=res.plan, require_halt=False,
        )
        hs_ok = int(rs.traces[0]) == res.trace and not bool(rs.ok[0])
        print(f"  shrink: {res.original_events} -> {len(res.events)} "
              f"events, shrunk replay identical violation + trace: {hs_ok} "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        if not hr_ok:
            failures.append("hunt-replay-diverged")
        if not hs_ok:
            failures.append("shrunk-replay-diverged")
        # forensics: the shrunk repro narrated end to end (obs.explain
        # names the disk-fault events and the sync counters)
        story = obs.explain(
            wl_bug, CFG, e.seed, plan=res.plan,
            history_invariant=store_inv({}), max_steps=STEPS,
            max_events=24,
        )
        head = "\n".join(story.splitlines()[:18])
        tail = "\n".join(story.splitlines()[-8:])
        print("  --- explain excerpt (shrunk repro) ---")
        print(head)
        print("  ...")
        print(tail)

    verdict = "PASS" if not failures else f"FAIL ({', '.join(failures)})"
    print(f"# verdict: {verdict} — fsync-before-reply raftlog survives "
          f"torn-write disk chaos that the missing-sync mutant cannot")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
