#!/bin/bash
# Probe the TPU tunnel every PROBE_INTERVAL seconds; the moment it
# answers, run the full artifact chain (tools/tpu_chain.sh: bench ->
# cross-backend -> sweep -> ablation). The chain banks each artifact as
# it completes, so a mid-chain wedge keeps the earlier wins; if the
# headline bench itself degraded to CPU the watch resumes.
# Usage: tools/tpu_watch.sh [stamp] [probe_interval_s] [probe_timeout_s]
set -u
STAMP="${1:-r05}"
case "$STAMP" in
  *.jsonl|*/*) echo "usage: tpu_watch.sh [stamp] — got a path: $STAMP" >&2; exit 2 ;;
esac
INTERVAL="${2:-600}"
PROBE_TIMEOUT="${3:-60}"
cd "$(dirname "$0")/.."
while true; do
  echo "$(date -u +%H:%M:%S) probing tpu..." >&2
  PROBE_OUT=$(BENCH_CHILD=probe BENCH_PLATFORM=default timeout "$PROBE_TIMEOUT" \
     python bench.py 2>/dev/null)
  if echo "$PROBE_OUT" | grep -q '"ok": true' \
      && ! echo "$PROBE_OUT" | grep -q '"platform": "cpu"'; then
    echo "$(date -u +%H:%M:%S) TPU UP — running artifact chain" >&2
    if tools/tpu_chain.sh "$STAMP"; then
      echo "$(date -u +%H:%M:%S) chain complete (all artifacts banked)" >&2
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) chain incomplete; resuming watch (banked steps skip on retry)" >&2
  fi
  sleep "$INTERVAL"
done
