#!/bin/bash
# Probe the TPU tunnel every PROBE_INTERVAL seconds; the moment it
# answers, immediately capture the round's TPU bench artifact (the
# tunnel historically wedges again within ~15 min — see SCALING.md §0).
# Usage: tools/tpu_watch.sh OUT.jsonl [probe_interval_s] [probe_timeout_s]
set -u
OUT="${1:?usage: tpu_watch.sh OUT.jsonl [interval] [timeout]}"
INTERVAL="${2:-600}"
PROBE_TIMEOUT="${3:-60}"
cd "$(dirname "$0")/.."
while true; do
  echo "$(date -u +%H:%M:%S) probing tpu..." >&2
  if BENCH_CHILD=probe BENCH_PLATFORM=default timeout "$PROBE_TIMEOUT" \
     python bench.py 2>/dev/null | grep -q '"ok": true'; then
    echo "$(date -u +%H:%M:%S) TPU UP — running bench.py" >&2
    BENCH_BUDGET=2400 python bench.py > "$OUT.tmp" 2>> /tmp/bench_watch.err
    # keep the artifact only if the headline actually ran on the
    # accelerator — a mid-bench wedge degrades to a CPU fallback, and
    # spending the session's one TPU window on that would defeat the
    # watcher. On CPU output: save nothing, keep looping.
    if tail -1 "$OUT.tmp" | grep -vq '"platform": "cpu"'; then
      mv "$OUT.tmp" "$OUT"
      echo "$(date -u +%H:%M:%S) TPU bench done -> $OUT" >&2
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) bench degraded to CPU; resuming watch" >&2
    rm -f "$OUT.tmp"
  fi
  sleep "$INTERVAL"
done
