"""Nemesis soak: plan-randomized chaos with history checkers as oracle.

Four certificates, written as the NEMESIS evidence artifact:

1. **Amplification** — on the kvchaos ``bug=True`` lost-write mutant,
   a nemesis-driven sweep (declarative crash-restart storm, built-in
   chaos off) catches the bug on STRICTLY MORE seeds per N than the
   model's own hand-rolled schedule (one kill drawn in on_init). The
   nemesis layer is not just generic — it is *better* chaos.
2. **Clean negative** — the unmutated model under the same plan: 0
   violations, 0 unhalted (the plan breaks the bug, not the protocol).
3. **Shrinking** — the first failing (seed, plan) ddmin-shrinks to
   <= 4 fault events that still reproduce, and the shrunk (seed,
   config, plan) replays to the identical violation and trace hash.
4. **raft under nemesis** — crash-recovery raftlog (durable=True:
   persistent term/votedFor/log per the paper's Figure 2; built-in
   chaos off) under a crash storm + gray failure plan: election safety
   and log agreement hold on every seed. Two-crash storms are chaos
   the model's built-in schedule (one kill) never exercised — building
   this certificate exposed a commit-record artifact of the win-time
   re-stamp that looked exactly like lost data (see the OP_COMMIT note
   in models/raftlog.py).
5. **raft election under nemesis** — the election-only model under a
   PAUSE storm + gray failure: pauses hold a node's events without
   wiping its votedFor, so election safety must hold exactly. (Kill
   storms on this diskless model CAN legitimately double-vote — that
   hunt belongs to tools/explore_soak.py, not to a clean certificate.)
6. **paxos under nemesis** — single-decree paxos, built-in chaos off,
   proposer crash storm + cluster-wide gray failure: agreement over
   recorded OP_DECIDE events holds on every seed.
7. **twophase under nemesis** — 2PC (built-in chaos off, so the
   coordinator's loss-free RESYNC hook is absent) under a participant
   crash + message-duplication plan: ATOMICITY (OP_DECIDE agreement)
   holds on every seed. Liveness is NOT asserted — without the RESYNC
   hook a crash-after-ack can legitimately stall a run (the module
   docstring's documented race), so unhalted seeds are reported, not
   failed.

Usage: python tools/nemesis_soak.py [n_seeds] > NEMESIS_r08.txt
Exit 0 iff all certificates hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    Duplicate,
    FaultPlan,
    GrayFailure,
    PauseStorm,
    shrink_plan,
)
from madsim_tpu.check import (  # noqa: E402
    election_safety,
    read_your_writes,
    stale_reads,
)
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import (  # noqa: E402
    make_kvchaos,
    make_paxos,
    make_raft,
    make_raftlog,
    make_twophase,
)
from madsim_tpu.models.paxos import OP_DECIDE as PX_OP_DECIDE  # noqa: E402
from madsim_tpu.models.raft import OP_ELECT as R_OP_ELECT  # noqa: E402
from madsim_tpu.models.raftlog import OP_COMMIT  # noqa: E402
from madsim_tpu.models.raftlog import OP_ELECT as RL_OP_ELECT  # noqa: E402
from madsim_tpu.models.twophase import OP_DECIDE as TP_OP_DECIDE  # noqa: E402

W = 10  # kvchaos writes (the check-soak shape)
STEPS = 4000

KV_PLAN = FaultPlan((
    CrashStorm(
        targets=(1, 2, 3, 4), n=2,
        t_min_ns=20_000_000, t_max_ns=400_000_000,
        down_min_ns=50_000_000, down_max_ns=250_000_000,
    ),
), name="kv-nemesis")

RAFT_PLAN = FaultPlan((
    CrashStorm(
        targets=(0, 1, 2, 3, 4), n=2,
        t_min_ns=100_000_000, t_max_ns=600_000_000,
        down_min_ns=100_000_000, down_max_ns=500_000_000,
    ),
    GrayFailure(
        targets=(0, 1, 2, 3, 4), n_links=2,
        t_min_ns=50_000_000, t_max_ns=500_000_000,
        dur_min_ns=100_000_000, dur_max_ns=400_000_000,
        mult_min=4, mult_max=16,
    ),
), name="raft-nemesis")

# election-only raft is diskless by construction, so its clean
# certificate runs PAUSES (state survives) instead of kills
RAFT_EL_PLAN = FaultPlan((
    PauseStorm(
        targets=(0, 1, 2, 3, 4), n=2,
        t_min_ns=20_000_000, t_max_ns=400_000_000,
        down_min_ns=50_000_000, down_max_ns=300_000_000,
    ),
    GrayFailure(
        targets=(0, 1, 2, 3, 4), n_links=2,
        t_min_ns=20_000_000, t_max_ns=400_000_000,
        dur_min_ns=50_000_000, dur_max_ns=300_000_000,
        mult_min=4, mult_max=16,
    ),
), name="raft-election-nemesis")

# paxos: crash storms hit PROPOSERS only (nodes A..A+P-1 = 5..7 at the
# default shape) — diskless acceptors are allowed to lose promises, so
# killing them is not a clean-model certificate
PAXOS_PLAN = FaultPlan((
    CrashStorm(
        targets=(5, 6, 7), n=2,
        t_min_ns=30_000_000, t_max_ns=200_000_000,
        down_min_ns=80_000_000, down_max_ns=300_000_000,
    ),
    GrayFailure(
        targets=(0, 1, 2, 3, 4, 5, 6, 7), n_links=2,
        t_min_ns=10_000_000, t_max_ns=200_000_000,
        dur_min_ns=50_000_000, dur_max_ns=200_000_000,
        mult_min=4, mult_max=16,
    ),
), name="paxos-nemesis")

# twophase: participant crash + message duplication (idempotency check)
TP_PLAN = FaultPlan((
    CrashStorm(
        targets=(1, 2, 3, 4), n=1,
        t_min_ns=20_000_000, t_max_ns=250_000_000,
        down_min_ns=100_000_000, down_max_ns=400_000_000,
    ),
    Duplicate(
        t_min_ns=10_000_000, t_max_ns=300_000_000,
        dur_min_ns=50_000_000, dur_max_ns=300_000_000,
    ),
), name="twophase-nemesis")


def kv_hinv(box):
    def inv(h):
        box["ok"] = stale_reads(h) & read_your_writes(h)
        return box["ok"]

    return inv


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    cfg = EngineConfig(pool_size=192, loss_p=0.05)
    t_all = time.monotonic()  # lint: allow(wall-clock)
    failures = []
    print(f"# nemesis soak: {n_seeds} schedules/cert, "
          f"platform={jax.devices()[0].platform}")
    print(f"# kv plan {KV_PLAN.hash()}: {KV_PLAN.specs}")

    # ---- certificate 1: chaos amplification on the lost-write mutant ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    rep_b = search_seeds(
        make_kvchaos(writes=W, record=True, bug=True), cfg, None,
        n_seeds=n_seeds, max_steps=STEPS, history_invariant=kv_hinv(box),
    )
    n_builtin = int((~box["ok"] & ~rep_b.overflowed).sum())
    nh_b = int((~np.asarray(rep_b.halted)).sum())
    print(f"built-in schedule: {n_builtin} lost-write catches / {n_seeds}, "
          f"{int(rep_b.overflowed.sum())} overflows, {nh_b} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    wl_bug = make_kvchaos(writes=W, record=True, bug=True, chaos=False)
    rep_n = search_seeds(
        wl_bug, cfg, None, n_seeds=n_seeds, max_steps=STEPS,
        history_invariant=kv_hinv(box), plan=KV_PLAN,
    )
    nem_caught = ~box["ok"] & ~rep_n.overflowed
    n_nemesis = int(nem_caught.sum())
    nh_n = int((~np.asarray(rep_n.halted)).sum())
    print(f"nemesis plan:      {n_nemesis} lost-write catches / {n_seeds}, "
          f"{int(rep_n.overflowed.sum())} overflows, {nh_n} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    amp = n_nemesis / max(n_builtin, 1)
    print(f"amplification: {n_nemesis} vs {n_builtin} ({amp:.2f}x)")
    if n_nemesis <= n_builtin:
        failures.append("nemesis-not-amplifying")
    if nh_n:
        failures.append("nemesis-mutant-unhalted")

    # ---- certificate 2: the clean model under the same plan ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    rep_c = search_seeds(
        make_kvchaos(writes=W, record=True, chaos=False), cfg, None,
        n_seeds=n_seeds, max_steps=STEPS,
        history_invariant=kv_hinv(box), plan=KV_PLAN,
    )
    nv = int((~box["ok"] & ~rep_c.overflowed).sum())
    no = int(rep_c.overflowed.sum())
    nh = int((~np.asarray(rep_c.halted)).sum())
    print(f"clean model, same plan: {nv} violations, {no} overflows, "
          f"{nh} unhalted ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no or nh:
        failures.append("clean-model-flagged")

    # ---- certificate 3: shrink a failing plan + exact replay ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    if n_nemesis == 0:
        failures.append("nothing-to-shrink")
    else:
        # some seeds genuinely need the whole storm; shrink the first
        # few failures and report the smallest repro found
        results = [
            shrink_plan(
                wl_bug, cfg, int(s), KV_PLAN,
                history_invariant=kv_hinv({}), max_steps=STEPS,
            )
            for s in rep_n.seeds[nem_caught][:3]
        ]
        res = min(results, key=lambda r: len(r.events))
        bad = res.seed
        print(res.banner())
        box = {}
        rep_r = search_seeds(
            wl_bug, cfg, None, n_seeds=1, max_steps=STEPS, seed_base=bad,
            history_invariant=kv_hinv(box), plan=res.plan,
        )
        replay_ok = (
            rep_r.failing_seeds.tolist() == [bad]
            and int(rep_r.traces[0]) == res.trace
        )
        print(f"shrink: {res.original_events} -> {len(res.events)} events, "
              f"replay identical violation + trace: {replay_ok} "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        if len(res.events) > 4:
            failures.append("shrink-above-4-events")
        if not replay_ok:
            failures.append("shrunk-replay-diverged")

    # ---- certificate 4: raftlog under a nemesis plan ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}

    def raft_inv(h):
        box["ok"] = election_safety(h, elect_op=RL_OP_ELECT) & election_safety(
            h, elect_op=OP_COMMIT
        )
        return box["ok"]

    rep = search_seeds(
        make_raftlog(record=True, chaos=False, durable=True),
        EngineConfig(pool_size=96, loss_p=0.02,
                     clog_backoff_max_ns=2_000_000_000),
        None, n_seeds=n_seeds, max_steps=6000,
        history_invariant=raft_inv, plan=RAFT_PLAN,
    )
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    print(f"durable raftlog under nemesis ({RAFT_PLAN.hash()}): {nv} "
          f"election/log-agreement violations, {no} overflows, "
          f"{nh} unhalted ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no:
        failures.append("raftlog-nemesis")
    if nh:
        failures.append("raftlog-nemesis-unhalted")

    # ---- certificate 5: raft election under a pause-storm plan ----
    # pauses hold events without wiping votedFor (the state kills would
    # wipe), so at-most-one-winner-per-term must hold exactly
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}

    def relect_inv(h):
        box["ok"] = election_safety(h, elect_op=R_OP_ELECT)
        return box["ok"]

    rep = search_seeds(
        make_raft(record=True),
        EngineConfig(pool_size=64, loss_p=0.02),
        None, n_seeds=n_seeds, max_steps=2000,
        history_invariant=relect_inv, plan=RAFT_EL_PLAN,
    )
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    print(f"raft election under nemesis ({RAFT_EL_PLAN.hash()}): {nv} "
          f"election-safety violations, {no} overflows, {nh} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no or nh:
        failures.append("raft-election-nemesis")

    # ---- certificate 6: paxos agreement under a proposer crash storm ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}

    def paxos_inv(h):
        box["ok"] = election_safety(h, elect_op=PX_OP_DECIDE)
        return box["ok"]

    rep = search_seeds(
        make_paxos(record=True, chaos=False),
        EngineConfig(pool_size=96, loss_p=0.05),
        None, n_seeds=n_seeds, max_steps=4000,
        history_invariant=paxos_inv, plan=PAXOS_PLAN,
    )
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    print(f"paxos under nemesis ({PAXOS_PLAN.hash()}): {nv} agreement "
          f"violations, {no} overflows, {nh} unhalted "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no or nh:
        failures.append("paxos-nemesis")

    # ---- certificate 7: twophase atomicity under crash + duplication ----
    # liveness is deliberately NOT asserted (docstring: without the
    # built-in chaos hook the coordinator has no loss-free RESYNC, so a
    # crash-after-ack can stall); atomicity must hold regardless
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}

    def tp_inv(h):
        box["ok"] = election_safety(h, elect_op=TP_OP_DECIDE)
        return box["ok"]

    rep = search_seeds(
        make_twophase(record=True, chaos=False),
        EngineConfig(pool_size=96, loss_p=0.05),
        None, n_seeds=n_seeds, max_steps=4000,
        history_invariant=tp_inv, plan=TP_PLAN, require_halt=False,
    )
    nv = int((~box["ok"] & ~rep.overflowed).sum())
    no = int(rep.overflowed.sum())
    nh = int((~np.asarray(rep.halted)).sum())
    print(f"twophase under nemesis ({TP_PLAN.hash()}): {nv} atomicity "
          f"violations, {no} overflows, {nh} unhalted (liveness not "
          f"asserted) ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if nv or no:
        failures.append("twophase-nemesis")

    verdict = "PASS" if not failures else f"FAIL ({', '.join(failures)})"
    print(f"# verdict: {verdict} — declarative nemesis amplifies chaos, "
          f"keeps clean models clean, and shrinks failures to minimal "
          f"replayable plans")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
