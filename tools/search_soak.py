"""Large-scale chaos-schedule search soak: safety certificates.

The suite proves each protocol family's invariant over ~1k schedules;
this soak sweeps MANY more through `engine.search_seeds` (the batched
chaos search, compacted path) with fully vectorized invariants and
prints one certificate line per family: seeds searched, violations,
overflows, unhalted. A clean run is a negative-result artifact — "no
safety violation exists in the first N seeds" — exactly what the
reference's multi-seed harness produces one process per seed at a
time, here as a handful of XLA dispatches.

Usage: python tools/search_soak.py [n_seeds] > SEARCH_r05.txt
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import (  # noqa: E402
    make_kvchaos,
    make_paxos,
    make_raft,
    make_raftlog,
    make_snapshot,
    make_twophase,
)
from madsim_tpu.models.paxos import A_VAL, P_DEC  # noqa: E402
from madsim_tpu.models.raft import LEADER as R_LEADER  # noqa: E402
from madsim_tpu.models.raft import ROLE as R_ROLE  # noqa: E402
from madsim_tpu.models.raft import TERM as R_TERM  # noqa: E402
from madsim_tpu.models.raftlog import (  # noqa: E402
    COMMIT,
    LOG0,
    LOGLEN,
)

W = 4  # raftlog n_writes (the default the invariant is written for)


def raftlog_majority_prefix(view) -> np.ndarray:
    """Every committed entry present, in order, equal values, on a
    majority (the suite's TestRaftLog assertion, vectorized)."""
    ns = np.asarray(view["node_state"])  # (S, 5, U)
    committed = ns[:, :, COMMIT] == W  # (S, 5)
    has_committer = committed.any(axis=1)
    first = np.argmax(committed, axis=1)  # index of a committer
    vals = ns[:, :, LOG0:LOG0 + W] & 0xFF  # (S, 5, W)
    ref = vals[np.arange(ns.shape[0]), first]  # (S, W)
    long_enough = ns[:, :, LOGLEN] >= W
    match = long_enough & (vals == ref[:, None, :]).all(axis=2)
    return has_committer & (match.sum(axis=1) >= 3)


def raft_single_leader(view) -> np.ndarray:
    """At most one leader per term at halt (election safety)."""
    ns = np.asarray(view["node_state"])  # (S, 5, U)
    is_leader = ns[:, :, R_ROLE] == R_LEADER
    term = ns[:, :, R_TERM]
    ok = np.ones(ns.shape[0], dtype=bool)
    # leaders sharing a term within a seed would violate election safety
    for s in np.nonzero(is_leader.sum(axis=1) > 1)[0]:
        terms = term[s][is_leader[s]]
        ok[s] = len(np.unique(terms)) == len(terms)
    # the north-star workload halts when a leader exists
    return ok & is_leader.any(axis=1)


def paxos_agreement(view) -> np.ndarray:
    """Agreement + validity + acceptor-majority witness (the suite's
    paxos assertion, vectorized). 5 acceptors, 3 proposers."""
    a, p = 5, 3
    ns = np.asarray(view["node_state"])
    dec = ns[:, a:, P_DEC]  # (S, 3)
    acc = ns[:, :a, A_VAL]  # (S, 5)
    decided = dec != 0
    some = decided.any(axis=1)
    first = np.argmax(decided, axis=1)
    v = dec[np.arange(ns.shape[0]), first]
    agree = np.where(decided, dec == v[:, None], True).all(axis=1)
    valid = (v >= 1) & (v <= p)
    witness = (acc == v[:, None]).sum(axis=1) >= a // 2 + 1
    return some & agree & valid & witness


def snapshot_conservation(view) -> np.ndarray:
    """Exact consistent-cut conservation (the suite's snapshot
    assertion, vectorized): recorded balances + recorded channel state
    == minted total, all nodes red, live balances re-conserve. 5 nodes
    x 1000 units."""
    from madsim_tpu.models.snapshot import BAL, CHANIN, COLOR, RECBAL

    ns = np.asarray(view["node_state"])  # (S, 5, 6)
    total = 5 * 1000
    cut_ok = ns[:, :, RECBAL].sum(axis=1) + ns[:, :, CHANIN].sum(axis=1) == total
    live_ok = ns[:, :, BAL].sum(axis=1) == total
    all_red = (ns[:, :, COLOR] == 1).all(axis=1)
    return cut_ok & live_ok & all_red


def kvchaos_durability(view) -> np.ndarray:
    """Config-5 shape (the suite's TestKvchaos assertion, vectorized):
    client saw all 10 commits and the final committed write is durable
    on >= R-1 of the 4 RAM-only replicas at halt."""
    ns = np.asarray(view["node_state"])  # (S, 6, U)
    client_done = ns[:, 5, 0] == 10
    durable = (ns[:, 1:5, 0] >= 10).sum(axis=1)
    return client_done & (durable >= 3)


def twophase_atomicity(view) -> np.ndarray:
    """2PC (the suite's atomicity assertion, vectorized): all 5 txns
    decided, the final decision reached every participant, and every
    participant's stored final decision matches the coordinator's."""
    ns = np.asarray(view["node_state"])  # (S, 5, U)
    coord = ns[:, 0]
    decided = (coord[:, 4] + coord[:, 5]) == 5
    reached = (ns[:, 1:5, 2] == 5).all(axis=1)
    coord_committed = (coord[:, 1] == 1).astype(np.int32)
    agree = (ns[:, 1:5, 4] == coord_committed[:, None]).all(axis=1)
    return decided & reached & agree


SOAKS = [
    ("raft-election", make_raft,
     dict(pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000),
     600, raft_single_leader),
    ("raftlog", make_raftlog,
     dict(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000),
     4000, raftlog_majority_prefix),
    ("raftlog-durable", lambda: make_raftlog(durable=True),
     dict(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000),
     4000, raftlog_majority_prefix),
    ("paxos", make_paxos, dict(pool_size=64, loss_p=0.02), 2000,
     paxos_agreement),
    ("paxos-durable", lambda: make_paxos(durable_acceptors=True),
     dict(pool_size=64, loss_p=0.02), 2000, paxos_agreement),
    ("snapshot", make_snapshot, dict(pool_size=96), 400,
     snapshot_conservation),
    ("kvchaos", lambda: make_kvchaos(writes=10),
     dict(pool_size=160, loss_p=0.05), 8000, kvchaos_durability),
    ("twophase", lambda: make_twophase(txns=5),
     dict(pool_size=48, loss_p=0.03), 1400, twophase_atomicity),
]


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    t_all = time.monotonic()  # lint: allow(wall-clock)
    worst = 0
    print(f"# chaos-search soak: {n_seeds} schedules/family, "
          f"platform={jax.devices()[0].platform}")
    for name, factory, cfg_kw, steps, inv in SOAKS:
        t0 = time.monotonic()  # lint: allow(wall-clock)
        rep = search_seeds(
            factory(), EngineConfig(**cfg_kw), inv,
            n_seeds=n_seeds, max_steps=steps, compact=True,
        )
        nv = int(rep.failing_seeds.size)
        no = int(rep.overflowed.sum())
        nh = int((~np.asarray(rep.halted)).sum())
        # an overflowed or unhalted schedule was NOT fully verified — a
        # certificate must refuse, not silently count it as searched
        worst = max(worst, nv, no, nh)
        print(f"{name}: {n_seeds} schedules, {nv} violations, "
              f"{no} overflows, {nh} unhalted "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        if nv:
            print(f"  first failing seeds: {rep.failing_seeds[:5].tolist()}")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if worst else 0)


if __name__ == "__main__":
    main()
