"""Service-scale model soak: leasekv + shardkv certificates. The
SERVICES_MODELS evidence artifact.

The service-scale batched models (models/leasekv.py, models/shardkv.py)
are verified by the check-package detectors instead of a C++ oracle —
this soak is their end-to-end evidence chain. Six certificates:

1. **leasekv clean negatives.** The default shape AND the tight-TTL
   hunt shape (ttl 50 ms vs 40 ms keepalives: a single lost keepalive
   opens the expiry window) through ``check.lease_safety`` — 0
   violations, 0 history overflows, every seed halted. The device
   screen's verdicts equal the numpy detector bit-for-bit on the whole
   batch.
2. **shardkv clean negatives.** The default 14-node shape (4 groups x
   3 replicas, 8 shards, 4 migrations) through
   ``check.shard_coverage`` — same bars, same numpy == device
   identity.
3. **leasekv mutant hunt, device-resident.** The grant-after-expiry
   mutant (``bug=True``: a keepalive resurrects an expired lease with
   no grant record) hunted by ``explore.run_device`` with the
   ``lease_safety`` HistoryScreen traced into the cached generation
   program. The hunt MUST find violations; the host driver running
   ``screens_invariant`` over the same campaign is bit-identical
   (corpus, coverage map, violations).
4. **leasekv shrink + replay.** The first device find ddmin-shrinks
   (``chaos.shrink_plan``) and the shrunk (seed, plan) replays to the
   identical violation and trace hash.
5. **shardkv mutant hunt, device-resident.** The lost-shard mutant
   (``bug=True``: the source wipes its copy on handoff send instead of
   holding it to the release — a retried handoff then ships version-0
   state) hunted the same way, same bit-identity bar.
6. **shardkv shrink + replay.** Same bar as cert 4.

Usage: python tools/services_model_soak.py [n_seeds] > SERVICES_MODELS_r12.txt
       python tools/services_model_soak.py --smoke   (tiny sizes,
                                                      rides `make check`)
Exit 0 iff all six certificates hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore  # noqa: E402
from madsim_tpu.chaos import CrashStorm, FaultPlan, shrink_plan  # noqa: E402
from madsim_tpu.check import device as dc  # noqa: E402
from madsim_tpu.check import lease_safety, shard_coverage  # noqa: E402
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import make_leasekv, make_shardkv  # noqa: E402
from madsim_tpu.models.leasekv import OP_EXPIRE, OP_PUT  # noqa: E402
from madsim_tpu.models.shardkv import (  # noqa: E402
    OP_SHARD_OWN,
    OP_SHARD_WRITE,
)

LEASE_CFG = EngineConfig(pool_size=48, loss_p=0.02,
                         clog_backoff_max_ns=2_000_000_000)
SHARD_CFG = EngineConfig(pool_size=64, loss_p=0.02,
                         clog_backoff_max_ns=2_000_000_000)
LEASE_STEPS = 4000
SHARD_STEPS = 6000

LEASE_SCREENS = (dc.lease_safety(OP_PUT, OP_EXPIRE),)
SHARD_SCREENS = (dc.shard_coverage(OP_SHARD_OWN, OP_SHARD_WRITE),)

# hunt spaces: client/primary crash storms — the schedules both bug
# classes live in (a dead client's lease expires; a mid-migration
# primary kill exercises the handoff retry the wiped source answers)
LEASE_PLAN = FaultPlan(
    (CrashStorm(targets=(1, 2, 3), n=1, t_min_ns=20_000_000,
                t_max_ns=300_000_000, down_min_ns=100_000_000,
                down_max_ns=400_000_000),),
    name="lease-hunt",
)
SHARD_PLAN = FaultPlan(
    (CrashStorm(targets=(2, 5, 8, 11), n=1, t_min_ns=20_000_000,
                t_max_ns=300_000_000, down_min_ns=100_000_000,
                down_max_ns=400_000_000),),
    name="shard-hunt",
)


def _hinv(box, fn, *ops):
    def inv(h):
        box["h"] = h
        box["ok"] = fn(h, *ops)
        return box["ok"]

    return inv


def _clean_cert(tag, builds, cfg, steps, screens, fn, ops, n_seeds):
    """Clean-negative certificate: every build 0 violations / 0
    overflows / all halted, and numpy == device verdicts bit-for-bit."""
    ok = True
    for name, wl in builds:
        t0 = time.monotonic()  # lint: allow(wall-clock)
        box = {}
        rep = search_seeds(wl, cfg, None, n_seeds=n_seeds,
                           max_steps=steps,
                           history_invariant=_hinv(box, fn, *ops))
        h = box["h"]
        nv = int((~box["ok"] & ~rep.overflowed).sum())
        no = int(rep.overflowed.sum())
        nh = int((~np.asarray(rep.halted)).sum())
        dev = np.asarray(dc.screen_ok(screens, h.word, h.t, h.count,
                                      h.drop))
        ident = bool(np.array_equal(dev, np.asarray(box["ok"])))
        print(f"  {name}: {nv} violations, {no} overflows, {nh} "
              f"unhalted, numpy==device {ident} "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        ok &= nv == 0 and no == 0 and nh == 0 and ident
    return ok


def _hunt_cert(tag, wl, cfg, steps, plan, screens, fn, ops, batch, gens):
    """Device hunt certificate: run_device with the HistoryScreen finds
    the mutant, bit-identical to the host driver; returns the device
    report for the shrink certificate (None on failure)."""
    inv = dc.screens_invariant(screens)
    kw = dict(generations=gens, batch=batch, root_seed=7,
              max_steps=steps, cov_words=16)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    host = explore.run(wl, cfg, plan, invariant=None,
                       history_invariant=inv, **kw)
    dev = explore.run_device(wl, cfg, plan, invariant=None,
                             history_check=screens, **kw)
    identical = (
        [(e.id, e.seed, e.trace, e.violating, e.plan.hash())
         for e in host.corpus]
        == [(e.id, e.seed, e.trace, e.violating, e.plan.hash())
            for e in dev.corpus]
        and np.array_equal(host.cov_map, dev.cov_map)
        and [(e.seed, e.trace) for e in host.violations]
        == [(e.seed, e.trace) for e in dev.violations]
    )
    print(f"  {tag}: {len(dev.violations)} violations over "
          f"{dev.sims} sims, host==device campaign {identical} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if not dev.violations:
        print(f"  {tag}: HUNT FOUND NOTHING")
        return None
    return dev if identical else None


def _shrink_cert(tag, wl, cfg, steps, dev, fn, ops):
    """Shrink + replay certificate over the first device finds."""
    t0 = time.monotonic()  # lint: allow(wall-clock)
    results = [
        shrink_plan(wl, cfg, int(e.seed), e.plan,
                    history_invariant=_hinv({}, fn, *ops),
                    max_steps=steps)
        for e in dev.violations[:3]
    ]
    res = min(results, key=lambda r: len(r.events))
    print("  " + res.banner().replace("\n", "\n  "))
    box = {}
    rep = search_seeds(wl, cfg, None, n_seeds=1, max_steps=steps,
                       seed_base=res.seed,
                       history_invariant=_hinv(box, fn, *ops),
                       plan=res.plan)
    replay_ok = (rep.failing_seeds.tolist() == [res.seed]
                 and int(rep.traces[0]) == res.trace)
    print(f"  {tag}: shrink {res.original_events} -> {len(res.events)} "
          f"events, replay identical violation + trace: {replay_ok} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    return replay_ok


def main() -> None:
    smoke = "--smoke" in sys.argv
    argv = [a for a in sys.argv[1:] if a != "--smoke"]
    if smoke:
        n_seeds, batch, gens = 192, 96, 2
    else:
        n_seeds = int(argv[0]) if argv else 4096
        batch, gens = 256, 4
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# service-scale model soak{' (smoke)' if smoke else ''}: "
          f"{n_seeds} schedules/clean cert, hunt batch {batch} x "
          f"{gens} generations, platform={jax.devices()[0].platform}")

    # ---- certificate 1: leasekv clean negatives ----
    print("== cert 1: leasekv clean (default + tight-TTL hunt shape) ==")
    ok1 = _clean_cert(
        "leasekv",
        [("leasekv/default", make_leasekv(record=True)),
         ("leasekv/tight", make_leasekv(record=True, ttl_ms=50))],
        LEASE_CFG, LEASE_STEPS, LEASE_SCREENS, lease_safety,
        (OP_PUT, OP_EXPIRE), n_seeds,
    )
    if not ok1:
        failures.append("leasekv-clean")
    print(f"cert1 {'PASS' if ok1 else 'FAIL'}")

    # ---- certificate 2: shardkv clean negatives ----
    print("== cert 2: shardkv clean (14-node default) ==")
    ok2 = _clean_cert(
        "shardkv", [("shardkv/default", make_shardkv(record=True))],
        SHARD_CFG, SHARD_STEPS, SHARD_SCREENS, shard_coverage,
        (OP_SHARD_OWN, OP_SHARD_WRITE), n_seeds,
    )
    if not ok2:
        failures.append("shardkv-clean")
    print(f"cert2 {'PASS' if ok2 else 'FAIL'}")

    # ---- certificates 3+4: leasekv mutant hunt, shrink, replay ----
    print("== cert 3: leasekv grant-after-expiry hunt (device) ==")
    wl_lb = make_leasekv(record=True, bug=True, ttl_ms=50)
    dev_l = _hunt_cert("leasekv-bug", wl_lb, LEASE_CFG, LEASE_STEPS,
                       LEASE_PLAN, LEASE_SCREENS, lease_safety,
                       (OP_PUT, OP_EXPIRE), batch, gens)
    print(f"cert3 {'PASS' if dev_l else 'FAIL'}")
    if not dev_l:
        failures.append("leasekv-hunt")
        print("cert4 SKIP (no find to shrink)")
        failures.append("leasekv-shrink")
    else:
        print("== cert 4: leasekv shrink + replay ==")
        ok4 = _shrink_cert("leasekv-bug", wl_lb, LEASE_CFG, LEASE_STEPS,
                           dev_l, lease_safety, (OP_PUT, OP_EXPIRE))
        if not ok4:
            failures.append("leasekv-shrink")
        print(f"cert4 {'PASS' if ok4 else 'FAIL'}")

    # ---- certificates 5+6: shardkv mutant hunt, shrink, replay ----
    print("== cert 5: shardkv lost-shard hunt (device) ==")
    wl_sb = make_shardkv(record=True, bug=True)
    dev_s = _hunt_cert("shardkv-bug", wl_sb, SHARD_CFG, SHARD_STEPS,
                       SHARD_PLAN, SHARD_SCREENS, shard_coverage,
                       (OP_SHARD_OWN, OP_SHARD_WRITE), batch, gens)
    print(f"cert5 {'PASS' if dev_s else 'FAIL'}")
    if not dev_s:
        failures.append("shardkv-hunt")
        print("cert6 SKIP (no find to shrink)")
        failures.append("shardkv-shrink")
    else:
        print("== cert 6: shardkv shrink + replay ==")
        ok6 = _shrink_cert("shardkv-bug", wl_sb, SHARD_CFG, SHARD_STEPS,
                           dev_s, shard_coverage,
                           (OP_SHARD_OWN, OP_SHARD_WRITE))
        if not ok6:
            failures.append("shardkv-shrink")
        print(f"cert6 {'PASS' if ok6 else 'FAIL'}")

    print(f"# total {time.monotonic() - t_all:.1f}s | "  # lint: allow(wall-clock)
          f"{'ALL PASS' if not failures else 'FAIL: ' + ','.join(failures)}")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
