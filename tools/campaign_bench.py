"""Campaign driver A/B: device-resident vs host-driven generations.
The CAMPAIGN evidence artifact.

Four certificates:

1. **Same-box interleaved A/B** — the SAME guided campaign (workload,
   plan space, root seed, invariant, batch, generations) is run
   alternately by the host driver (``explore.run``: per-generation
   numpy/Python mutation + admission bookkeeping, per-seed state to
   the host every dispatch) and the device driver
   (``explore.run_device``: the whole generation ONE jitted program,
   one summary-sized host sync). Rounds interleave H,D,H,D,… so box
   noise hits both sides equally — on this class of box only the A/B
   ratio is meaningful, never the absolute numbers. Round 0 is the
   warm-up (it pays XLA compilation into the persistent cache) and is
   reported but not scored. The certificate: device ≥ 3x host
   generations/s at ≥65k seeds per generation, with **bit-identical
   campaign outcomes** (corpus ids, plans, traces, coverage map,
   violation set, curves) across every run of both drivers. The hunt
   is coverage-only (constant-true invariant): a 65k-child breeding
   generation floods a violation store under any horizon-biased
   predicate, and the A/B certificate is about DRIVER wall, not find
   rate — the violation path gets its own certificate (3) where finds
   are real.
2. **One host sync per generation** — checked from the device driver's
   telemetry records (every ``generation`` record carries
   ``host_syncs: 1`` and the dispatch/compile/sync wall split), not
   from this module's word; the artifact prints the host-sync wall
   fraction, and each round reports **warm and cold generations/s
   separately** per driver (generation 0 pays the program build; the
   old accounting billed that compile to dispatch and skewed every
   warm-vs-cold comparison).
3. **Violation-path identity + replay** — a smaller campaign (4096
   seeds/generation) under a halt-based invariant where finds exist:
   both drivers must produce the identical deduped (seed, trace)
   violation set, and a device-found violation must replay to its
   recorded trace through the ordinary host replay path.
4. **Guided still beats uniform at equal budget** — the lean form of
   tools/explore_soak.py cert 1 (kvchaos lost-write mutant): the
   guided campaign must reach strictly more coverage bits and ≥2.5x
   the deduped violation count of a uniform nemesis sweep spending the
   identical simulation budget. Guards the perf work against quietly
   regressing search QUALITY.

The A/B horizon is short (``MAX_STEPS`` = 64): on this CPU "device"
the simulation step is ~2 orders slower than real accelerator silicon,
so a long horizon buries the driver overhead both drivers share the
sim for — the short horizon keeps the sim share comparable to what a
TPU would give at production step counts. All raft seeds halt well
inside the uniform-generation horizon (uniform halt fraction is
printed as a sanity row).

Usage: python tools/campaign_bench.py [batch] [gens] [rounds] [gv_budget]
           > CAMPAIGN_r07.txt
Defaults: batch 65536, gens 5, rounds 3 (+1 warm-up), gv_budget 2048.
Exit 0 iff all four certificates hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import statistics
import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    FaultPlan,
    GrayFailure,
    PauseStorm,
)
from madsim_tpu.check import read_your_writes, stale_reads  # noqa: E402
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import make_kvchaos, make_raft  # noqa: E402

NODES = (0, 1, 2, 3, 4)
CFG = EngineConfig(pool_size=64, loss_p=0.02)
# the default hunt space: composed crash + pause + gray-failure chaos
# over the raft quorum — the explore package's stock mixed-fault shape
PLAN = FaultPlan((
    CrashStorm(targets=(1, 2, 3), n=2, t_min_ns=20_000_000,
               t_max_ns=400_000_000, down_min_ns=50_000_000,
               down_max_ns=250_000_000),
    PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
               t_max_ns=300_000_000, down_min_ns=50_000_000,
               down_max_ns=200_000_000),
    GrayFailure(targets=NODES, n_links=1),
), name="campaign-bench")
MAX_STEPS = 64
COV_WORDS = 32


def _cov_inv(view):
    # constant-true, same shape/dtype on both paths (ndarray | True is
    # elementwise on the host, a traced all-true vector on the device)
    return view["halted"] | True


def _halt_inv(view):
    return view["halted"]


def _fingerprint(rep):
    return (
        [(e.id, e.generation, e.parent, e.seed, e.plan.hash(), e.trace,
          e.new_bits) for e in rep.corpus],
        rep.cov_map.tolist(),
        [(e.seed, e.trace) for e in rep.violations],
        rep.curve,
        rep.viol_curve,
    )


def main() -> None:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    gens = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    rounds = int(sys.argv[3]) if len(sys.argv) > 3 else 3
    gv_budget = int(sys.argv[4]) if len(sys.argv) > 4 else 2048
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# campaign bench: batch {batch}, {gens} generations, "
          f"{rounds} timed rounds (+1 warm-up), "
          f"platform={jax.devices()[0].platform}")
    print(f"# plan {PLAN.hash()} ({PLAN.slots} slots), raft, "
          f"max_steps {MAX_STEPS}, cov_words {COV_WORDS}")

    # horizon sanity: the uniform generation must halt comfortably
    probe = search_seeds(
        make_raft(), CFG,
        lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool),
        n_seeds=4096, max_steps=MAX_STEPS, plan=PLAN,
    )
    print(f"# uniform halt fraction at {MAX_STEPS} steps: "
          f"{float(np.mean(probe.halted)):.3f}")

    kw = dict(generations=gens, batch=batch, root_seed=7,
              max_steps=MAX_STEPS, cov_words=COV_WORDS, invariant=_cov_inv)

    # ---- certificates 1+2: interleaved A/B ----
    print("== cert 1: interleaved A/B, host vs device driver ==")
    fps = []
    walls = {"host": [], "device": []}
    warm_cold = {"host": [], "device": []}
    sync_fracs = []
    telemetry_ok = True

    def _gen_walls(recs):
        # per-generation total wall from the telemetry split (compile
        # is a separate key since the flight-recorder round, so cold
        # and warm generations are comparable like-with-like)
        return [
            sum(x.get(k, 0.0) for k in ("dispatch_wall_s",
                                        "compile_wall_s",
                                        "sync_wall_s", "host_wall_s"))
            for x in recs if x["event"] == "generation"
        ]

    for r in range(rounds + 1):
        tag = "warmup " if r == 0 else f"round {r}"
        records_h = []
        t0 = time.monotonic()  # lint: allow(wall-clock)
        rep_h = explore.run(
            make_raft(), CFG, PLAN, telemetry=records_h.append, **kw
        )
        wh = time.monotonic() - t0  # lint: allow(wall-clock)
        records = []
        t0 = time.monotonic()  # lint: allow(wall-clock)
        rep_d = explore.run_device(
            make_raft(), CFG, PLAN, telemetry=records.append, **kw
        )
        wd = time.monotonic() - t0  # lint: allow(wall-clock)
        fps += [_fingerprint(rep_h), _fingerprint(rep_d)]
        gen_recs = [x for x in records if x["event"] == "generation"]
        if not (len(gen_recs) == gens
                and all(x["host_syncs"] == 1 for x in gen_recs)):
            telemetry_ok = False
        dsp, snc = rep_d.wall_dispatch_s, rep_d.wall_host_s
        frac = snc / max(dsp + snc, 1e-9)
        print(f"  {tag}: host {wh:7.1f}s ({gens / wh:.3f} gens/s, "
              f"{gens * batch / wh:7.0f} seeds/s) | "
              f"device {wd:6.1f}s ({gens / wd:.3f} gens/s, "
              f"{gens * batch / wd:7.0f} seeds/s) | "
              f"device host-sync {snc * 1e3:.0f}ms = {frac:.2%} of wall | "
              f"ratio {wh / wd:.2f}x")
        # warm vs cold generations/s: generation 0 pays the program
        # build (cold) unless the run cache was already warm; later
        # generations are pure execution. Reported per driver — the
        # skew the old compile-inside-dispatch accounting hid.
        for name, recs, rep in (("host", records_h, rep_h),
                                ("device", records, rep_d)):
            gw = _gen_walls(recs)
            # telemetry walls are rounded to ms: a sub-ms smoke
            # generation reads as 0.0 — skip the rate line, don't crash
            if len(gw) >= 2 and gw[0] > 0 and statistics.median(gw[1:]) > 0:
                cold = gw[0]
                warm = statistics.median(gw[1:])
                warm_cold[name].append((1 / cold, 1 / warm))
                print(f"    {name}: cold {1 / cold:6.3f} gens/s "
                      f"(gen 0, incl {rep.wall_compile_s:.2f}s compile) "
                      f"| warm {1 / warm:6.3f} gens/s")
        if r > 0:
            walls["host"].append(wh)
            walls["device"].append(wd)
            sync_fracs.append(frac)

    med_h = statistics.median(walls["host"])
    med_d = statistics.median(walls["device"])
    ratio = med_h / med_d
    identical = all(f == fps[0] for f in fps[1:])
    rep = fps[0]
    print(f"  medians: host {med_h:.1f}s vs device {med_d:.1f}s -> "
          f"device {ratio:.2f}x generations/s "
          f"(host-sync fraction {statistics.median(sync_fracs):.2%})")
    for name in ("host", "device"):
        if warm_cold[name]:
            mc = statistics.median(c for c, _ in warm_cold[name])
            mw = statistics.median(w for _, w in warm_cold[name])
            print(f"  {name} medians: cold {mc:.3f} gens/s | warm "
                  f"{mw:.3f} gens/s ({mw / max(mc, 1e-9):.2f}x)")
    print(f"  outcomes: corpus {len(rep[0])}, {len(rep[2])} violations, "
          f"curve {rep[3]} | identical across {len(fps)} runs: {identical}")
    if not identical:
        failures.append("outcomes-not-bit-identical")
    if ratio < 3.0:
        failures.append("device-below-3x")
    print(f"cert1 {'PASS' if identical and ratio >= 3.0 else 'FAIL'}")

    print("== cert 2: one host sync per generation (telemetry) ==")
    if not telemetry_ok:
        failures.append("telemetry-syncs")
    print(f"  every generation record: host_syncs=1 -> {telemetry_ok}")
    print(f"cert2 {'PASS' if telemetry_ok else 'FAIL'}")

    # ---- certificate 3: violation-path identity + replay ----
    print("== cert 3: violation identity + replay (4096 seeds/gen) ==")
    t0 = time.monotonic()  # lint: allow(wall-clock)
    vkw = dict(generations=3, batch=4096, root_seed=7, max_steps=96,
               cov_words=COV_WORDS, invariant=_halt_inv)
    rep_h = explore.run(make_raft(), CFG, PLAN, **vkw)
    rep_d = explore.run_device(make_raft(), CFG, PLAN, **vkw)
    v_same = _fingerprint(rep_h) == _fingerprint(rep_d)
    replay_ok = bool(rep_d.violations)
    if rep_d.violations:
        e = rep_d.violations[0]
        r = explore.replay_entry(
            make_raft(), CFG, e, invariant=_halt_inv, max_steps=96,
        )
        replay_ok = (int(r.traces[0]) == e.trace
                     and int(r.failing_seeds[0]) == e.seed)
    print(f"  violations host {len(rep_h.violations)} == device "
          f"{len(rep_d.violations)}, identical {v_same}, "
          f"replay {replay_ok} ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if not (v_same and replay_ok):
        failures.append("violation-identity")
    print(f"cert3 {'PASS' if v_same and replay_ok else 'FAIL'}")

    # ---- certificate 4: guided-vs-uniform quality guard ----
    print("== cert 4: guided vs uniform at equal budget "
          f"({gv_budget} sims/side) ==")
    t0 = time.monotonic()  # lint: allow(wall-clock)
    wl_bug = make_kvchaos(writes=10, record=True, bug=True, chaos=False)
    kv_cfg = EngineConfig(pool_size=192, loss_p=0.05)
    kv_plan = FaultPlan((
        CrashStorm(targets=(1, 2, 3, 4), n=2, t_min_ns=20_000_000,
                   t_max_ns=400_000_000, down_min_ns=50_000_000,
                   down_max_ns=250_000_000),
    ), name="kv-nemesis")
    kv_steps, cw = 4000, 64
    box = {}

    def hinv(h):
        box["ok"] = stale_reads(h) & read_your_writes(h)
        return box["ok"]

    rep_u = search_seeds(
        wl_bug, kv_cfg, None, n_seeds=gv_budget, max_steps=kv_steps,
        history_invariant=hinv, plan=kv_plan, cov_words=cw,
    )
    u_viol = int((~box["ok"] & ~rep_u.overflowed).sum())
    u_bits = explore.popcount(
        explore.merge(np.where(rep_u.overflowed[:, None], 0, rep_u.cov))
    )
    g = 8
    rep_e = explore.run(
        wl_bug, kv_cfg, kv_plan,
        history_invariant=lambda h: stale_reads(h) & read_your_writes(h),
        generations=g, batch=gv_budget // g, root_seed=7,
        max_steps=kv_steps, cov_words=cw, max_ops=1, inherit_seed_p=0.9,
    )
    gv = len(rep_e.violations) / max(u_viol, 1)
    print(f"  uniform: {u_viol} violations, {u_bits} bits | guided: "
          f"{len(rep_e.violations)} violations, {rep_e.coverage_bits} bits "
          f"-> {gv:.2f}x ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    gv_ok = rep_e.coverage_bits > u_bits and gv >= 2.5
    if not gv_ok:
        failures.append("guided-quality-regressed")
    print(f"cert4 {'PASS' if gv_ok else 'FAIL'}")

    print(f"# total {time.monotonic() - t_all:.1f}s | "  # lint: allow(wall-clock)
          f"{'ALL PASS' if not failures else 'FAIL: ' + ','.join(failures)}")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
