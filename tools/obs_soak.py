"""Observability soak: fleet metrics at sweep scale, the raftlog
violation as a Perfetto timeline, and the obs-off identity. The OBS
evidence artifact.

Five certificates:

1. **Obs-off identity** — metrics + timeline + hit-count taps enabled
   change NO trace and NO verdict, across dense/scatter layouts and the
   compacted runner (the derived-state-only rule, test-pinned here at
   soak scale).
2. **Fleet metrics at scale** — the kvchaos nemesis sweep's fleet
   shape (totals, halt-reason distribution, log2 histograms) reduced on
   device from N seeds; the metrics-only path never moves history or
   timeline columns to the host.
3. **Violation forensics** — the coverage-guided diskless-raftlog hunt
   (the PR-3 find) re-run small; its first violation is ddmin-shrunk,
   replayed with the timeline ring on, decoded, REFOLDED to the
   certified trace hash, rendered by ``obs.explain``, and exported as
   trace-event JSON (OBS_raftlog_trace.json — open it in
   ui.perfetto.dev). Valid JSON + dispatch-count == timeline-length are
   asserted.
4. **Hit-count delta** — the guided-vs-uniform measurement re-run with
   AFL-style hit-count bucketing on both sides at equal budget (the
   satellite's re-measurement; set-only numbers live in EXPLORE_r08).
5. **Campaign telemetry + persistence** — the hunt emits structured
   JSONL progress records and checkpoints its corpus; the checkpoint
   reloads to the identical corpus.

Usage: python tools/obs_soak.py [n_seeds] > OBS_r09.txt
Exit 0 iff every certificate holds (a hunt that finds nothing documents
the negative and skips cert 3's forensics, exit still 0).
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore, obs  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    FaultPlan,
    FlappingPartition,
    shrink_plan,
)
from madsim_tpu.check import (  # noqa: E402
    election_safety,
    read_your_writes,
    stale_reads,
)
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import make_kvchaos, make_raftlog  # noqa: E402
from madsim_tpu.models.raftlog import OP_COMMIT, OP_ELECT  # noqa: E402

W = 10
KV_STEPS = 4000
CW = 64
PERFETTO_OUT = "OBS_raftlog_trace.json"
TELEMETRY_OUT = "/tmp/obs_soak_telemetry.jsonl"
CAMPAIGN_OUT = "/tmp/obs_soak_campaign.json"

KV_PLAN = FaultPlan((
    CrashStorm(
        targets=(1, 2, 3, 4), n=2,
        t_min_ns=20_000_000, t_max_ns=400_000_000,
        down_min_ns=50_000_000, down_max_ns=250_000_000,
    ),
), name="kv-nemesis")

RL_NODES = (0, 1, 2, 3, 4)
HUNT_PLAN = FaultPlan((
    CrashStorm(
        targets=RL_NODES, n=2,
        t_min_ns=150_000_000, t_max_ns=500_000_000,
        down_min_ns=100_000_000, down_max_ns=400_000_000,
    ),
    FlappingPartition(
        targets=RL_NODES, n_cycles=2,
        t_min_ns=50_000_000, t_max_ns=400_000_000,
        dur_min_ns=100_000_000, dur_max_ns=300_000_000,
        up_min_ns=20_000_000, up_max_ns=200_000_000,
    ),
), name="raftlog-hunt")
HUNT_STEPS = 6000


def kv_hinv(box):
    def inv(h):
        box["ok"] = stale_reads(h) & read_your_writes(h)
        return box["ok"]

    return inv


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# obs soak: {n_seeds} seeds, platform="
          f"{jax.devices()[0].platform}")
    print(f"# kv plan {KV_PLAN.hash()} | hunt plan {HUNT_PLAN.hash()}")

    wl_bug = make_kvchaos(writes=W, record=True, bug=True, chaos=False)
    kv_cfg = EngineConfig(pool_size=192, loss_p=0.05)

    # ---- certificate 1: obs-off identity at soak scale ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    idn = min(n_seeds, 512)
    box_off, box_on = {}, {}
    base = search_seeds(
        wl_bug, kv_cfg, None, n_seeds=idn, max_steps=KV_STEPS,
        history_invariant=kv_hinv(box_off), plan=KV_PLAN,
    )
    variants = {
        "dense+obs": dict(layout="dense"),
        "scatter+obs": dict(layout="scatter"),
        "compact+obs": dict(compact=True),
    }
    ident_ok = True
    for name, kw in variants.items():
        r = search_seeds(
            wl_bug, kv_cfg, None, n_seeds=idn, max_steps=KV_STEPS,
            history_invariant=kv_hinv(box_on), plan=KV_PLAN,
            metrics=True, timeline_cap=256, cov_words=CW,
            cov_hitcount=True, **kw,
        )
        same = (
            np.array_equal(base.traces, r.traces)
            and np.array_equal(box_off["ok"], box_on["ok"])
        )
        ident_ok &= same
        print(f"identity [{name}]: traces+verdicts identical to obs-off "
              f"over {idn} seeds: {same}")
    if not ident_ok:
        failures.append("obs-on-changed-values")
    print(f"  ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 2: fleet metrics at scale, device-reduced ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    box = {}
    rep = search_seeds(
        wl_bug, kv_cfg, None, n_seeds=n_seeds, max_steps=KV_STEPS,
        history_invariant=kv_hinv(box), plan=KV_PLAN, metrics=True,
    )
    fm = obs.fleet_reduce(rep.met, overflow=rep.pool_overflowed)
    viol = int((~box["ok"] & ~rep.overflowed).sum())
    print(f"fleet sweep: {n_seeds} seeds, {viol} violations "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    print(fm.format(histograms=True))
    print("banner with halt breakdown:")
    print(rep.banner(limit=3))
    if not (fm.halt_codes.sum() == n_seeds and fm.total("sent") > 0):
        failures.append("fleet-metrics-degenerate")
    # the metrics-only path: device-side sweep, reduced shapes only
    fm2 = obs.fleet_metrics(
        wl_bug, kv_cfg, n_seeds=min(n_seeds, 2048), max_steps=KV_STEPS,
        plan=KV_PLAN,
    )
    print(f"metrics-only path (device-reduced, {fm2.n_seeds} seeds): "
          f"sent/seed {fm2.mean('sent'):.1f}, "
          f"delivered/seed {fm2.mean('delivered'):.1f}")

    # ---- certificate 3: raftlog violation forensics ----
    wl_rl = make_raftlog(record=True, chaos=False, durable=False)
    rl_cfg = EngineConfig(
        pool_size=128, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
    )
    rl_box = {}

    def rl_inv(h):
        rl_box["commit"] = election_safety(h, elect_op=OP_COMMIT)
        rl_box["elect"] = election_safety(h, elect_op=OP_ELECT)
        return rl_box["commit"] & rl_box["elect"]

    t0 = time.monotonic()  # lint: allow(wall-clock)
    sink = obs.JsonlSink(open(TELEMETRY_OUT, "w"))
    hunt = explore.run(
        wl_rl, rl_cfg, HUNT_PLAN, history_invariant=rl_inv,
        generations=2, batch=256, root_seed=2024,
        max_steps=HUNT_STEPS, cov_words=CW, select_top=24, max_ops=2,
        inherit_seed_p=0.85, require_halt=False,
        telemetry=sink, checkpoint_path=CAMPAIGN_OUT,
    )
    sink.close()
    print(f"raftlog hunt: {len(hunt.violations)} violations, "
          f"{hunt.coverage_bits} coverage bits / {hunt.sims} sims "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if hunt.violations:
        e = hunt.violations[0]
        t0 = time.monotonic()  # lint: allow(wall-clock)
        res = shrink_plan(
            wl_rl, rl_cfg, e.seed, e.plan, history_invariant=rl_inv,
            max_steps=HUNT_STEPS,
        )
        print(f"  shrink: {res.original_events} -> {len(res.events)} "
              f"events ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        # replay the SHRUNK plan with the flight recorder on
        r = explore.replay_entry(
            wl_rl, rl_cfg,
            explore.CorpusEntry(
                id=-1, generation=-1, parent=-1, seed=e.seed,
                plan=res.plan, trace=res.trace, cov=e.cov, new_bits=0,
                violating=True,
            ),
            history_invariant=rl_inv, max_steps=HUNT_STEPS,
            timeline_cap=4096, metrics=True,
        )
        events = obs.decode_timeline(r.timeline, wl_rl, 0)
        refold_ok = obs.refold_timeline(events, wl_rl) == int(r.traces[0])
        doc = obs.write_perfetto(
            PERFETTO_OUT, events, wl_rl, seed=e.seed
        )
        n_disp = sum(
            1 for x in doc["traceEvents"] if x.get("cat") == "dispatch"
        )
        json_ok = (
            json.loads(open(PERFETTO_OUT).read())["otherData"]["events"]
            == len(events)
        )
        count_ok = n_disp == len(events)
        print(f"  timeline: {len(events)} events, trace refold exact: "
              f"{refold_ok}; perfetto: {len(doc['traceEvents'])} rows "
              f"-> {PERFETTO_OUT}, valid JSON: {json_ok}, dispatch "
              f"count matches: {count_ok}")
        if not (refold_ok and json_ok and count_ok):
            failures.append("forensics-broken")
        kind = ("committed-value-loss"
                if not bool(rl_box["commit"][0]) else "double-vote")
        print(f"  explain [{kind}] (tail):")
        story = obs.explain(
            wl_rl, rl_cfg, seed=e.seed, plan=res.plan,
            history_invariant=rl_inv, max_steps=HUNT_STEPS,
            timeline_cap=4096, max_events=40,
        )
        for line in story.splitlines()[-28:]:
            print(f"    {line}")
    else:
        print("  NEGATIVE: no find at this budget; forensics certificate "
              "not exercised (raise the budget)")

    # telemetry + persistence evidence
    recs = [json.loads(ln) for ln in open(TELEMETRY_OUT)]
    gens = [x for x in recs if x["event"] == "generation"]
    st = explore.load_campaign(CAMPAIGN_OUT)
    persist_ok = (
        len(gens) == 2
        and st.generations_done == 2
        and [x.id for x in st.corpus] == [x.id for x in hunt.corpus]
        and np.array_equal(st.cov_map, hunt.cov_map)
    )
    print(f"telemetry: {len(recs)} JSONL records ({len(gens)} generation "
          f"rows, dispatch wall "
          f"{[g['dispatch_wall_s'] for g in gens]}s); campaign "
          f"checkpoint reloads identically: {persist_ok}")
    if not persist_ok:
        failures.append("telemetry-or-persistence-broken")

    # ---- certificate 4: hit-count guided-vs-uniform delta ----
    # the 8-generation shape of the EXPLORE_r08 measurement: guided
    # amplification compounds per generation (4 gens measured 1.89x,
    # below the 2x bar the set-only loop also only clears at 8)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    hc_gens, hc_batch = 8, 128
    hc_budget = hc_gens * hc_batch
    box = {}
    rep_u = search_seeds(
        wl_bug, kv_cfg, None, n_seeds=hc_budget, max_steps=KV_STEPS,
        history_invariant=kv_hinv(box), plan=KV_PLAN, cov_words=CW,
        cov_hitcount=True,
    )
    u_viol = int((~box["ok"] & ~rep_u.overflowed).sum())
    u_bits = explore.popcount(
        explore.merge(np.where(rep_u.overflowed[:, None], 0, rep_u.cov))
    )
    rep_g = explore.run(
        wl_bug, kv_cfg, KV_PLAN, history_invariant=kv_hinv({}),
        generations=hc_gens, batch=hc_batch, root_seed=7,
        max_steps=KV_STEPS, cov_words=CW, max_ops=1, inherit_seed_p=0.9,
        cov_hitcount=True,
    )
    ratio = len(rep_g.violations) / max(u_viol, 1)
    print(f"hit-count delta at {hc_budget} sims/side: uniform {u_viol} "
          f"violations / {u_bits} bits; guided "
          f"{len(rep_g.violations)} violations / "
          f"{rep_g.coverage_bits} bits = {ratio:.2f}x "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    print(f"  guided hit-count curve: {rep_g.curve}")
    if rep_g.coverage_bits <= u_bits:
        failures.append("hitcount-guided-not-more-coverage")
    if len(rep_g.violations) < 2 * u_viol:
        failures.append("hitcount-guided-below-2x")

    verdict = "PASS" if not failures else f"FAIL ({', '.join(failures)})"
    print(f"# verdict: {verdict} — the batched engine has a flight "
          f"recorder: device-reduced fleet metrics, per-seed timelines "
          f"that refold to the certified trace, and Perfetto-renderable "
          f"violation forensics, all bit-exactly free when off")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
