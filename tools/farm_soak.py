"""Fuzzing-farm soak: the pipelined-driver A/B, the multi-tenant
scheduler session, and the adaptive-energy hunt. The FARM evidence
artifact.

Four certificates:

1. **Pipelined >= 1.25x blocking, bit-identical** (the headline). The
   same device campaign — checkpointing every generation and streaming
   flight telemetry to JSONL, the host work a real hunt carries — run
   alternately by blocking ``explore.run_device`` and by
   ``farm.run_pipelined`` (depth 2), interleaved rounds so box noise
   hits both sides, in TWO regimes. **Organic**: the campaign's own
   host work; wall-clock overlap needs a second core (host JSON/numpy
   work time-slices against XLA's threads on one), so the organic
   floor applies only when ``os.cpu_count() > 1`` — on a 1-core box
   the ratio is printed as evidence, not gated. **Loaded**: the
   telemetry sink carries a per-generation drain latency of 0.6x the
   measured generation time (an emulated slow collector — blocking
   I/O wait, the "variable host-side work" the farm exists to absorb;
   the emulation is disclosed in the artifact). The pipelined driver
   must absorb the drain (floor 1.25x on EVERY box — I/O wait
   overlaps device execution even on one core), the blocking driver
   serializes it. The hard invariants hold across BOTH regimes:
   corpus / coverage map / violations / the final checkpoint FILE all
   bit-identical, ``host_syncs`` exactly 1 per generation on both
   sides (from telemetry), and the ``queue_wall_s``/``idle_wall_s``
   split in the records shows where the overlap landed.
2. **3-tenant farm session** — three differently-shaped campaigns
   (halt invariant / planted trace-bias invariant / wider coverage
   shape) time-sliced by ``farm.run_farm`` in one-generation quanta
   over one device set. Every tenant's final campaign equals its
   standalone run bit-for-bit (preemption IS the checkpoint/resume
   splice), and the whole session traces every generation program
   EXACTLY once (profiler-certified ``retraces == 1``; the
   ``_GEN_CACHE`` holds all tenant programs resident, evictions == 0
   at the default ``MADSIM_GEN_CACHE_MAX``).
3. **Adaptive energy >= uniform at equal budget** — on the kvchaos
   planted lost-write mutant at the needle shape (short horizons, low
   loss: violations are scarce enough that WHICH parents breed
   matters; at saturated shapes every frontier entry is equally
   fertile and the comparison is realization noise — measured, see
   SCALING.md round 11), the AFLFast-style ``EnergySchedule`` must
   find at least as many violations as the historical uniform
   schedule at the SAME total sim budget, aggregated over three root
   seeds so one lucky realization cannot decide either way. The
   violation totals per root are printed for the quality claim.
4. **Energy off is inert** — ``energy=None`` /
   ``EnergySchedule(mode="uniform")`` replay the no-argument campaign
   bit-identically on the mutant hunt shape (the farm lane draws never
   touch the explore mutation stream; the static row lives in
   tools/lint_soak.py cert 1d).

Usage: python tools/farm_soak.py [batch] [gens] [rounds] > FARM_r11.txt
       python tools/farm_soak.py --smoke     (tiny sizes, no floors —
                                              rides `make check`)
Defaults: batch 1024, gens 6, rounds 3 (generation walls of a few
hundred ms — the farm regime is many modest generations, and the A/B
needs enough of them per round for the pipeline split to show).
Exit 0 iff all four certificates hold.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import json
import os
import statistics
import sys
import tempfile
import time

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore, farm  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    FaultPlan,
    GrayFailure,
    PauseStorm,
)
from madsim_tpu.check import read_your_writes, stale_reads  # noqa: E402
from madsim_tpu.engine import EngineConfig  # noqa: E402
from madsim_tpu.explore import device as _device  # noqa: E402
from madsim_tpu.farm import EnergySchedule, Tenant  # noqa: E402
from madsim_tpu.models import make_kvchaos, make_raft  # noqa: E402
from madsim_tpu.obs import FlightRecorder  # noqa: E402
from madsim_tpu.obs import prof  # noqa: E402

NODES = (0, 1, 2, 3, 4)
CFG = EngineConfig(pool_size=64, loss_p=0.02)
PLAN = FaultPlan((
    CrashStorm(targets=(1, 2, 3), n=2, t_min_ns=20_000_000,
               t_max_ns=400_000_000, down_min_ns=50_000_000,
               down_max_ns=250_000_000),
    PauseStorm(targets=NODES, n=1, t_min_ns=20_000_000,
               t_max_ns=300_000_000, down_min_ns=50_000_000,
               down_max_ns=200_000_000),
    GrayFailure(targets=NODES, n_links=1),
), name="farm-soak")

# the kvchaos mutant hunt (the explore/nemesis-soak shape)
KV_PLAN = FaultPlan((
    CrashStorm(targets=(1, 2, 3, 4), n=2,
               t_min_ns=20_000_000, t_max_ns=400_000_000,
               down_min_ns=50_000_000, down_max_ns=250_000_000),
), name="kv-nemesis")
KV_CFG = EngineConfig(pool_size=192, loss_p=0.02)
KV_STEPS = 800
KV_CW = 64
KV_ROOTS = (7, 13, 29)


def _cov_inv(view):
    return view["halted"] | True


def _halt_inv(view):
    return view["halted"]


def _biased_inv(view):
    return (view["trace"] & 7) != 0


def _kv_hinv(h):
    return stale_reads(h) & read_your_writes(h)


class _SlowSink:
    """Emulated slow telemetry collector: each generation record costs
    ``delay`` seconds of drain latency before reaching the inner sink —
    blocking I/O wait, the variable host-side work of cert 1's loaded
    regime (disclosed emulation; the delay is printed)."""

    def __init__(self, inner, delay: float):
        self.inner, self.delay = inner, delay

    def __call__(self, rec):
        if rec.get("event") == "generation":
            time.sleep(self.delay)
        self.inner(rec)


def _fingerprint(rep):
    return (
        [(e.id, e.generation, e.parent, e.seed, e.plan.hash(), e.trace,
          e.new_bits, e.violating) for e in rep.corpus],
        rep.cov_map.tolist(),
        [(e.seed, e.trace) for e in rep.violations],
        rep.curve,
        rep.viol_curve,
    )


def main() -> None:
    smoke = "--smoke" in sys.argv
    argv = [a for a in sys.argv[1:] if a != "--smoke"]
    if smoke:
        batch, gens, rounds = 256, 3, 1
    else:
        batch = int(argv[0]) if len(argv) > 0 else 1024
        gens = int(argv[1]) if len(argv) > 1 else 6
        rounds = int(argv[2]) if len(argv) > 2 else 3
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# farm soak{' (smoke)' if smoke else ''}: batch {batch}, "
          f"{gens} generations, {rounds} rounds, "
          f"platform={jax.devices()[0].platform}")
    print(f"# plan {PLAN.hash()} ({PLAN.slots} slots), raft, "
          f"kv plan {KV_PLAN.hash()}")
    tmp = tempfile.mkdtemp(prefix="farm_soak_")

    wl = make_raft()  # ONE workload object: program-cache identity
    kw = dict(generations=gens, batch=batch, root_seed=7, max_steps=256,
              cov_words=32, invariant=_cov_inv)

    # ---- cert 1: pipelined vs blocking, interleaved A/B ----
    print("== cert 1: pipelined vs blocking device driver (A/B) ==")
    # warm the shared programs once (2 gens: uniform AND breed built)
    # so both sides time pure execution
    explore.run_device(wl, CFG, PLAN, **{**kw, "generations": 2})

    # the loaded regime's drain latency: 0.6x the measured generation
    # time, so the sink is heavy but still hideable at depth 2
    t0 = time.monotonic()  # lint: allow(wall-clock)
    explore.run_device(wl, CFG, PLAN, **kw)
    gen_wall = (time.monotonic() - t0) / gens  # lint: allow(wall-clock)
    drain = 0.6 * gen_wall
    cores = os.cpu_count() or 1
    print(f"  generation wall {gen_wall * 1000:.0f} ms | loaded-regime "
          f"drain {drain * 1000:.0f} ms/gen | {cores} core(s)")

    def _campaign(runner, tag, r, delay):
        ck = os.path.join(tmp, f"{tag}{r}.ckpt")
        jl = os.path.join(tmp, f"{tag}{r}.jsonl")
        t0 = time.monotonic()  # lint: allow(wall-clock)
        with FlightRecorder(jl, heartbeat_s=0.0, profile=False) as fr:
            sink = _SlowSink(fr, delay) if delay else fr
            rep = runner(wl, CFG, PLAN, telemetry=sink,
                         checkpoint_path=ck, **kw)
        wall = time.monotonic() - t0  # lint: allow(wall-clock)
        recs = [json.loads(line) for line in open(jl)]
        return rep, wall, ck, recs

    identical = syncs_ok = ckpt_ok = True
    ratios = {}
    for regime, delay in (("organic", 0.0), ("loaded", drain)):
        walls = {"blocking": [], "pipelined": []}
        queue = idle = 0.0
        for r in range(rounds):
            rb, wb, ckb, recb = _campaign(
                explore.run_device, f"blk-{regime}", r, delay)
            rp, wp, ckp, recp = _campaign(
                farm.run_pipelined, f"pipe-{regime}", r, delay)
            walls["blocking"].append(wb)
            walls["pipelined"].append(wp)
            identical &= _fingerprint(rb) == _fingerprint(rp)
            ckpt_ok &= open(ckb, "rb").read() == open(ckp, "rb").read()
            for recs in (recb, recp):
                g = [x for x in recs if x["event"] == "generation"]
                syncs_ok &= (len(g) == gens
                             and all(x["host_syncs"] == 1 for x in g))
            end = next(x for x in recp if x["event"] == "campaign_end")
            queue, idle = end["wall_queue_s"], end["wall_idle_s"]
            print(f"  {regime:7} round {r}: blocking {wb:6.2f}s | "
                  f"pipelined {wp:6.2f}s ({wb / wp:.2f}x) | "
                  f"queue {queue:.2f}s idle {idle:.2f}s "
                  f"respec {end['respeculations']}")
        med_b = statistics.median(walls["blocking"])
        med_p = statistics.median(walls["pipelined"])
        ratios[regime] = med_b / med_p
        print(f"  {regime:7} medians: blocking {gens / med_b:.2f} gens/s "
              f"vs pipelined {gens / med_p:.2f} gens/s -> "
              f"{ratios[regime]:.2f}x")
    # the organic floor needs a second core for the host work to
    # overlap at all (CPU host work time-slices against XLA on one);
    # the loaded floor is I/O wait and must overlap on EVERY box
    organic_ok = smoke or cores == 1 or ratios["organic"] >= 1.25
    loaded_ok = smoke or ratios["loaded"] >= 1.25
    if cores == 1 and not smoke:
        print("  [1-core box] organic wall-clock overlap physically "
              "unavailable (host compute shares the core with XLA); "
              "organic ratio reported as evidence, loaded floor gates")
    ok1 = (identical and syncs_ok and ckpt_ok
           and organic_ok and loaded_ok)
    print(f"  bit-identical {identical} | checkpoint files byte-equal "
          f"{ckpt_ok} | host_syncs 1/gen {syncs_ok} | floors "
          f"{'none — smoke' if smoke else 'organic 1.25x (multi-core), loaded 1.25x'}")
    if not ok1:
        failures.append("pipeline-ab")
    print(f"cert1 {'PASS' if ok1 else 'FAIL'}")

    # ---- cert 2: the 3-tenant farm session ----
    print("== cert 2: 3-tenant scheduled session (retraces == 1) ==")
    tb = max(batch // 4, 16)
    kws = {
        "halt": dict(invariant=_halt_inv, batch=tb, root_seed=11,
                     max_steps=256, cov_words=32),
        "biased": dict(invariant=_biased_inv, batch=tb + 16, root_seed=5,
                       max_steps=256, cov_words=32),
        "wide": dict(invariant=_halt_inv, batch=tb, root_seed=2,
                     max_steps=384, cov_words=64),
    }
    _device._GEN_CACHE.clear()
    ev0 = _device.gen_cache_stats()["evictions"]
    with prof.profiled() as p:
        refs = {
            n: explore.run_device(wl, CFG, PLAN, generations=gens, **k)
            for n, k in kws.items()
        }
        fl = os.path.join(tmp, "farm.jsonl")
        with FlightRecorder(fl, heartbeat_s=0.0, profile=False) as fr:
            t0 = time.monotonic()  # lint: allow(wall-clock)
            freport = farm.run_farm(
                [Tenant(n, wl, CFG, PLAN, generations=gens, kwargs=k)
                 for n, k in kws.items()],
                quantum=1, telemetry=fr,
            )
            fw = time.monotonic() - t0  # lint: allow(wall-clock)
        retr = p.retraces("explore.device")
    tenants_ok = all(
        _fingerprint(freport.reports[n]) == _fingerprint(refs[n])
        for n in kws
    )
    retr_ok = bool(retr) and all(v == 1 for v in retr.values())
    stats = _device.gen_cache_stats()
    evictions = stats["evictions"] - ev0
    recs = [json.loads(line) for line in open(fl)]
    gen_tags = [x["tenant"] for x in recs if x["event"] == "generation"]
    tags_ok = (len(gen_tags) == 3 * gens
               and set(gen_tags) == set(kws))
    print(f"  {freport.slices} slices in {fw:.1f}s, preemptions "
          f"{freport.preemptions}")
    print(f"  scheduled == standalone for all 3 tenants: {tenants_ok}")
    print(f"  retraces per program key: "
          f"{sorted(set(retr.values())) if retr else '{}'} (want [1]); "
          f"cache {stats['entries']}/{stats['max']} entries, "
          f"{evictions} evictions this session")
    print(f"  tenant-tagged generation records: {tags_ok} "
          f"({len(gen_tags)} records)")
    for line in freport.banner().splitlines():
        print(f"  {line}")
    ok2 = tenants_ok and retr_ok and evictions == 0 and tags_ok
    if not ok2:
        failures.append("farm-session")
    print(f"cert2 {'PASS' if ok2 else 'FAIL'}")

    # ---- cert 3: adaptive energy vs uniform at equal budget ----
    print("== cert 3: adaptive energy vs uniform on the kvchaos mutant ==")
    # the needle shape: short horizons + low loss make violations
    # scarce enough that parent choice matters (at saturated shapes the
    # comparison is realization noise — SCALING.md round 11); the
    # aggregate over KV_ROOTS keeps one lucky realization from
    # deciding either way
    if smoke:
        kv_gens, kv_batch, kv_roots = 3, 64, (7,)
    else:
        kv_gens, kv_batch, kv_roots = 8, 256, KV_ROOTS
    wl_bug = make_kvchaos(writes=10, record=True, bug=True, chaos=False)
    ekw = dict(generations=kv_gens, batch=kv_batch,
               max_steps=KV_STEPS, cov_words=KV_CW, max_ops=1,
               inherit_seed_p=0.9, history_invariant=_kv_hinv)
    tot_u = tot_a = 0
    sims_ok = True
    t0 = time.monotonic()  # lint: allow(wall-clock)
    for rs in kv_roots:
        rep_u = explore.run(wl_bug, KV_CFG, KV_PLAN, root_seed=rs, **ekw)
        rep_a = explore.run(wl_bug, KV_CFG, KV_PLAN, root_seed=rs,
                            energy=EnergySchedule(), **ekw)
        sims_ok &= rep_a.sims == rep_u.sims
        tot_u += len(rep_u.violations)
        tot_a += len(rep_a.violations)
        print(f"  root {rs:2}: uniform {len(rep_u.violations):5} | "
              f"adaptive {len(rep_a.violations):5} violations "
              f"(cov {rep_u.coverage_bits}/{rep_a.coverage_bits} bits, "
              f"{rep_u.sims} sims each)")
    wq = time.monotonic() - t0  # lint: allow(wall-clock)
    print(f"  aggregate over {len(kv_roots)} root(s): uniform {tot_u} | "
          f"adaptive {tot_a} violations ({wq:.1f}s)")
    # the quality floor holds at artifact scale; the smoke shape is too
    # small for a schedule heuristic to be judged on
    ok3 = sims_ok and (smoke or tot_a >= tot_u)
    if not ok3:
        failures.append("energy-quality")
    print(f"cert3 {'PASS' if ok3 else 'FAIL'} (equal budget"
          + ("" if smoke else ", adaptive >= uniform aggregate") + ")")

    # ---- cert 4: energy off is inert ----
    print("== cert 4: energy off / uniform-mode bit-identity ==")
    ikw = {**ekw, "generations": min(kv_gens, 3), "root_seed": 7}
    base = _fingerprint(explore.run(wl_bug, KV_CFG, KV_PLAN, **ikw))
    off = _fingerprint(explore.run(
        wl_bug, KV_CFG, KV_PLAN, energy=None, **ikw
    ))
    uni = _fingerprint(explore.run(
        wl_bug, KV_CFG, KV_PLAN, energy=EnergySchedule(mode="uniform"),
        **ikw
    ))
    ok4 = base == off == uni
    print(f"  absent == None == uniform: {ok4} "
          f"({len(base[0])} corpus entries, {len(base[2])} violations)")
    if not ok4:
        failures.append("energy-identity")
    print(f"cert4 {'PASS' if ok4 else 'FAIL'}")

    print(f"# total {time.monotonic() - t_all:.1f}s | "  # lint: allow(wall-clock)
          f"{'ALL PASS' if not failures else 'FAIL: ' + ','.join(failures)}")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
