"""Interval-prover soak: the full absint matrix over the recorded
models. The ABSINT evidence artifact.

Four certificates:

1. **Overflow + lane matrix** — the four recorded models (every
   lint_entries variant, with each model's declared certification
   horizon from ``absint_entries``) x the absint build axes (base /
   dup-shadow-lanes / all-taps) x every ``LAYOUT_AXES`` lowering tuple
   (scatter/int64, dense, time32 where eligible, the readiness-indexed
   pool rows), walked via the single-seed step AND the vmapped
   ``make_run`` scan path: every signed add/sub/mul on a time- or
   counter-tainted value provably fits its dtype within the declared
   horizon, and every live threefry lane resolves into the structured
   ``PURPOSE_LANES`` registry with all sites pairwise disjoint.

2. **Planted positive controls** — the re-created time32
   sentinel-decay mutant (the PR-13 bug class: the carried tile_min
   rebased without the empty-tile re-mask, wrapping once the
   accumulated advance exceeds int32) and the lane-collision mutant
   (a value-identical draw at the engine's first per-emit latency
   lane) are both caught, with cited equation chains / site pairs.

3. **Pragma hygiene** — the ``# lint: allow(absint-*)`` allowlist is
   exercised exactly: every pragma the matrix used is printed, and a
   pragma no traced program exercised is a failure (the
   ``unused-allow`` rule extended to the interval prover).

4. **Lane census** — the live purpose-lane map of the default
   programs (which registry lanes carry draws, at how many sites).

Usage: python tools/absint_soak.py > ABSINT_r10.txt
Exit 0 iff every certificate holds.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import jax

from madsim_tpu.lint import (
    ABSINT_AXES,
    absint_matrix,
    run_mutant_controls,
    stale_absint_pragmas,
)
from madsim_tpu.lint.noninterference import LAYOUT_AXES


def main() -> None:
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# absint soak: platform={jax.devices()[0].platform}")

    # ---- certificate 1: the full overflow + lane matrix ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 1: interval matrix, model x axis x lowering ==")
    reports = absint_matrix(
        layouts=LAYOUT_AXES, log=lambda s: print(f"  {s}")
    )
    run_reports = absint_matrix(
        axes={"all": ABSINT_AXES["all"]},
        layouts=(("scatter", False, None), ("scatter", True, None, True)),
        entry="run",
        log=lambda s: print(f"  {s}"),
    )
    reports += run_reports
    bad = [r for r in reports if not r.ok]
    n_eqns = sum(r.n_eqns for r in reports)
    n_ops = sum(r.checked_ops for r in reports)
    print(
        f"  {len(reports)} proofs ({len(run_reports)} run-entry), "
        f"{n_eqns} equations walked, {n_ops} tracked ops certified, "
        f"{len(bad)} failure(s)"
    )
    if bad:
        failures.append("matrix")
        for r in bad:
            print(r.summary())
    print(f"cert1 {'PASS' if not bad else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 2: the planted positive controls ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    print("== cert 2: planted mutants (positive controls) ==")
    controls = run_mutant_controls()
    for name, rep, caught in controls:
        print(f"  {name} (caught={caught}):")
        print("  " + rep.summary().replace("\n", "\n  "))
    if not all(caught for _n, _r, caught in controls):
        failures.append("mutants")
    ok2 = all(caught for _n, _r, caught in controls)
    print(f"cert2 {'PASS' if ok2 else 'FAIL'} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    # ---- certificate 3: pragma hygiene ----
    print("== cert 3: absint pragma allowlist exercised exactly ==")
    used = set()
    for r in reports:
        used.update(tuple(u) for u in r.used_pragmas)
    for u in sorted(used):
        print(f"  allow {u[0]}:{u[1]} [{u[2]}]")
    stale = stale_absint_pragmas(used)
    for s in stale:
        print(f"  STALE {s['file']}:{s['line']}: {s['message']}")
    if stale:
        failures.append("stale-pragmas")
    print(f"cert3 {'PASS' if not stale else 'FAIL'} "
          f"({len(used)} pragma(s) in use)")

    # ---- certificate 4: the live lane census ----
    print("== cert 4: live purpose-lane census ==")
    lanes: dict = {}
    sites = 0
    for r in reports:
        sites += len(r.lane_sites)
        for ln in r.lanes:
            lanes[ln] = lanes.get(ln, 0) + 1
    for ln, n in sorted(lanes.items()):
        print(f"  lane {ln}: live in {n} traced program(s)")
    print(f"  {sites} threefry site(s) across the matrix")
    ok4 = sites > 0 and "latency" in lanes and "poll_cost" in lanes
    if not ok4:
        failures.append("lane-census")
    print(f"cert4 {'PASS' if ok4 else 'FAIL'}")

    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    if failures:
        print(f"# FAILURES: {failures}")
        sys.exit(1)
    print("# all certificates PASS")


if __name__ == "__main__":
    main()
