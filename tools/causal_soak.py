"""Causal-provenance soak: the causal-off identity, device fold vs
host DAG, cone-vs-ring forensics on a real find, and exact-vs-heuristic
Perfetto arrows. The CAUSAL evidence artifact.

Four certificates:

1. **Causal-off identity at soak scale** — ``causal=True`` changes NO
   trace and NO verdict across dense/scatter layouts and the compacted
   runner (the derived-state-only rule, test-pinned in
   tests/test_causal.py, re-asserted here at soak scale); off-side
   reports carry zero-size provenance columns.
2. **Device fold == host DAG** — on sampled seeds the host-side
   happens-before reconstruction (``obs.rederive`` over the decoded
   ring) reproduces the device-folded Lamport clocks exactly, dispatch
   seqs strictly increase (gaps are unrecorded dead-drop dispatches),
   and ``fleet_reduce(met, lam=...)`` folds the fleet's causal
   depth/width shape on device.
3. **Cone-vs-ring forensics on a real find** — the coverage-guided
   diskless-raftlog hunt (16-write variant: traffic continues long
   past the first conflicting commit, so the violation's past is a
   small slice of the ring) finds election-safety violations; the
   banked repro anchors ``causal_slice`` at the conflicting COMMIT
   record and the backward cone must be <= 25% of the captured
   timeline (everything outside it is provably concurrent with the
   violation), and ``obs.explain(causal=True)`` narrates the same
   violation cone-first.
4. **Exact arrows beat the heuristic** — under a Duplicate +
   GrayFailure plan (retransmitted copies + slowed links: the shapes
   that fool last-dispatch-at-or-before attribution) the Perfetto flow
   arrows built from causal lineage differ from the ones rebuilt after
   stripping seq/parent/emit_ns — the heuristic demonstrably
   mis-attributes arrows the exact path gets right, and every exact
   arrow matches the parent column.

Usage: python tools/causal_soak.py [n_seeds] > CAUSAL_r13.txt
       python tools/causal_soak.py --smoke    (tiny sizes, no cone
                                               floor — rides `make
                                               check`)
Exit 0 iff every certificate holds (a hunt that finds nothing documents
the negative and skips cert 3's cone floor, exit still 0).
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import dataclasses
import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore, obs  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    CrashStorm,
    Duplicate,
    FaultPlan,
    FlappingPartition,
    GrayFailure,
)
from madsim_tpu.check import (  # noqa: E402
    election_safety,
    read_your_writes,
    stale_reads,
)
from madsim_tpu.engine import EngineConfig, search_seeds  # noqa: E402
from madsim_tpu.models import make_kvchaos, make_raftlog  # noqa: E402
from madsim_tpu.models.raftlog import OP_COMMIT, OP_ELECT  # noqa: E402
from madsim_tpu.obs.causal import causal_slice, rederive  # noqa: E402

W = 10
KV_STEPS = 4000
CW = 64
CONE_BAR = 0.25

KV_PLAN = FaultPlan((
    CrashStorm(
        targets=(1, 2, 3, 4), n=2,
        t_min_ns=20_000_000, t_max_ns=400_000_000,
        down_min_ns=50_000_000, down_max_ns=250_000_000,
    ),
), name="kv-nemesis")

RL_NODES = (0, 1, 2, 3, 4)
HUNT_PLAN = FaultPlan((
    CrashStorm(
        targets=RL_NODES, n=2,
        t_min_ns=150_000_000, t_max_ns=500_000_000,
        down_min_ns=100_000_000, down_max_ns=400_000_000,
    ),
    FlappingPartition(
        targets=RL_NODES, n_cycles=2,
        t_min_ns=50_000_000, t_max_ns=400_000_000,
        dur_min_ns=100_000_000, dur_max_ns=300_000_000,
        up_min_ns=20_000_000, up_max_ns=200_000_000,
    ),
), name="raftlog-cone-hunt")
HUNT_STEPS = 20000

# the arrow-confuser: duplicated copies of in-flight messages plus a
# slowed link reorder deliveries past later dispatches from the same
# source — exactly where last-dispatch-at-or-before guesses wrong
ARROW_PLAN = FaultPlan((
    Duplicate(t_min_ns=20_000_000, t_max_ns=600_000_000,
              dur_min_ns=100_000_000, dur_max_ns=500_000_000),
    GrayFailure(targets=(0, 1, 2, 3, 4), n_links=2,
                t_min_ns=20_000_000, t_max_ns=600_000_000,
                dur_min_ns=100_000_000, dur_max_ns=500_000_000,
                mult_min=8, mult_max=32),
), name="dup-slowlink")


def kv_hinv(box):
    def inv(h):
        box["ok"] = stale_reads(h) & read_your_writes(h)
        return box["ok"]

    return inv


def arrow_endpoints(doc):
    """Multiset of flow-arrow start anchors (pid, ts) in a perfetto doc."""
    out = {}
    for row in doc["traceEvents"]:
        if row.get("cat") == "flow" and row.get("ph") == "s":
            k = (row["pid"], row["ts"])
            out[k] = out.get(k, 0) + 1
    return out


def main() -> None:
    smoke = "--smoke" in sys.argv
    argv = [a for a in sys.argv[1:] if a != "--smoke"]
    n_seeds = int(argv[0]) if argv else 4096
    if smoke:
        n_seeds = 128
    hunt_batch = 64 if smoke else 256
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# causal soak{' (smoke)' if smoke else ''}: {n_seeds} seeds, "
          f"platform={jax.devices()[0].platform}")
    print(f"# kv plan {KV_PLAN.hash()} | hunt plan {HUNT_PLAN.hash()} | "
          f"arrow plan {ARROW_PLAN.hash()}")

    wl_bug = make_kvchaos(writes=W, record=True, bug=True, chaos=False)
    kv_cfg = EngineConfig(pool_size=192, loss_p=0.05)

    # ---- certificate 1: causal-off identity at soak scale ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    idn = min(n_seeds, 512)
    box_off, box_on = {}, {}
    base = search_seeds(
        wl_bug, kv_cfg, None, n_seeds=idn, max_steps=KV_STEPS,
        history_invariant=kv_hinv(box_off), plan=KV_PLAN,
    )
    variants = {
        "dense+causal": dict(layout="dense"),
        "scatter+causal": dict(layout="scatter"),
        "compact+causal": dict(compact=True),
    }
    ident_ok = True
    lam_on = None
    for name, kw in variants.items():
        r = search_seeds(
            wl_bug, kv_cfg, None, n_seeds=idn, max_steps=KV_STEPS,
            history_invariant=kv_hinv(box_on), plan=KV_PLAN,
            metrics=True, timeline_cap=128, causal=True, **kw,
        )
        same = (
            np.array_equal(base.traces, r.traces)
            and np.array_equal(box_off["ok"], box_on["ok"])
        )
        ident_ok &= same and r.lam is not None
        lam_on = r.lam
        print(f"identity [{name}]: traces+verdicts identical to "
              f"causal-off over {idn} seeds: {same}")
    off_cols_empty = base.lam is None
    print(f"off-side provenance columns absent: {off_cols_empty} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if not (ident_ok and off_cols_empty):
        failures.append("causal-on-changed-values")

    # ---- certificate 2: device fold == host DAG ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    fold_ok = True
    n_sample = 2 if smoke else 6
    for s in range(n_sample):
        view, _ = obs.telemetry._capture(
            wl_bug, kv_cfg, 1000 + s, KV_PLAN, KV_STEPS, 256, None,
            causal=True,
        )
        ev = obs.decode_timeline(view, wl_bug, 0)
        lams = rederive(ev)
        fold_ok &= lams == [e.lam for e in ev]
        # seqs strictly increase; gaps are dispatches the ring never
        # records (e.g. deliveries dead-dropped at a crashed node)
        seqs = [e.seq for e in ev]
        fold_ok &= all(a < b for a, b in zip(seqs, seqs[1:]))
    rep = search_seeds(
        wl_bug, kv_cfg, None, n_seeds=n_seeds, max_steps=KV_STEPS,
        history_invariant=kv_hinv({}), plan=KV_PLAN, metrics=True,
        causal=True,
    )
    fm = obs.fleet_reduce(rep.met, lam=rep.lam)
    print(f"device fold == host DAG on {n_sample} sampled seeds: "
          f"{fold_ok}; fleet causal shape over {n_seeds} seeds: "
          f"depth min {fm.depth_min} max {fm.depth_max}, mean "
          f"concurrency width {fm.width_mean:.2f} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if lam_on is not None:
        print(f"  (cert-1 on-side lam populated: max depth "
              f"{int(np.max(lam_on))})")
    if not fold_ok or fm.depth_max is None or fm.depth_max <= 0:
        failures.append("fold-vs-dag-mismatch")

    # ---- certificate 3: cone-vs-ring forensics on a real find ----
    wl_rl = make_raftlog(record=True, chaos=False, durable=False,
                         n_writes=16)
    rl_cfg = EngineConfig(
        pool_size=192, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
    )
    rl_box = {}

    def rl_inv(h):
        rl_box["commit"] = election_safety(h, elect_op=OP_COMMIT)
        rl_box["elect"] = election_safety(h, elect_op=OP_ELECT)
        return rl_box["commit"] & rl_box["elect"]

    t0 = time.monotonic()  # lint: allow(wall-clock)
    hunt = explore.run(
        wl_rl, rl_cfg, HUNT_PLAN, history_invariant=rl_inv,
        generations=2, batch=hunt_batch, root_seed=2024,
        max_steps=HUNT_STEPS, cov_words=CW, select_top=24, max_ops=2,
        inherit_seed_p=0.85, require_halt=False,
    )
    print(f"raftlog hunt: {len(hunt.violations)} violations / "
          f"{hunt.sims} sims "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if hunt.violations:
        best = None
        seen_seeds = set()
        for e in hunt.violations[:6]:
            if e.seed in seen_seeds:
                continue
            seen_seeds.add(e.seed)
            view, _ = obs.telemetry._capture(
                wl_rl, rl_cfg, e.seed, e.plan, HUNT_STEPS, 8192, None,
                causal=True,
            )
            ev = obs.decode_timeline(view, wl_rl, 0)
            n_hist = int(view["hist_count"][0])
            seen_vals, anchor_rec = {}, None
            for i in range(n_hist):
                w = tuple(int(x) for x in view["hist_word"][0][i])
                if w[0] != OP_COMMIT:
                    continue
                if w[1] in seen_vals and seen_vals[w[1]] != w[2]:
                    anchor_rec = (int(view["hist_t"][0][i]), w)
                    break
                seen_vals.setdefault(w[1], w[2])
            if anchor_rec is None:
                continue
            t, w = anchor_rec
            cone = causal_slice(ev, anchor=(t, w[3]))
            print(f"  seed {e.seed}: conflicting COMMIT key={w[1]} "
                  f"args {seen_vals[w[1]]} vs {w[2]} at t={t}ns; cone "
                  f"{len(cone.indices)}/{len(ev)} = "
                  f"{cone.fraction:.3f} of the ring (depth "
                  f"{cone.depth}, {len(cone.chaos_indices)} fault "
                  f"windows inside)")
            if best is None or cone.fraction < best[1].fraction:
                best = (e, cone, t, w)
        if best is None:
            print("  NEGATIVE: violations found but none witnessed by a "
                  "conflicting COMMIT pair in the captured history")
            if not smoke:
                failures.append("cone-no-conflicting-commit")
        else:
            e, cone, t, w = best
            bar_ok = smoke or cone.fraction <= CONE_BAR
            print(f"  banked repro: seed {e.seed}, cone fraction "
                  f"{cone.fraction:.3f} <= {CONE_BAR}: "
                  f"{cone.fraction <= CONE_BAR}")
            if not bar_ok:
                failures.append("cone-above-bar")
            kind = ("committed-value-loss"
                    if not bool(rl_box["commit"][0]) else "double-vote")
            print(f"  explain(causal=True) [{kind}] (tail):")
            story = obs.explain(
                wl_rl, rl_cfg, seed=e.seed, plan=e.plan,
                history_invariant=rl_inv, max_steps=HUNT_STEPS,
                timeline_cap=8192, max_events=40, causal=True,
            )
            if "causal cone:" not in story:
                failures.append("explain-causal-missing-cone")
            for line in story.splitlines()[-24:]:
                print(f"    {line}")
    else:
        print("  NEGATIVE: no find at this budget; cone certificate not "
              "exercised (raise the budget)")

    # ---- certificate 4: exact arrows beat the heuristic ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    n_arrow_seeds = 2 if smoke else 8
    diff_total = exact_checked = 0
    arrows_ok = True
    for s in range(n_arrow_seeds):
        view, _ = obs.telemetry._capture(
            wl_bug, kv_cfg, 77 + s, ARROW_PLAN, KV_STEPS, 512, None,
            causal=True,
        )
        ev = obs.decode_timeline(view, wl_bug, 0)
        doc_exact = obs.to_perfetto(ev, wl_bug, seed=77 + s)
        stripped = [
            dataclasses.replace(x, seq=-1, parent=-1, emit_ns=-1)
            for x in ev
        ]
        doc_heur = obs.to_perfetto(stripped, wl_bug, seed=77 + s)
        a_exact, a_heur = arrow_endpoints(doc_exact), arrow_endpoints(
            doc_heur)
        diff = sum(abs(a_exact.get(k, 0) - a_heur.get(k, 0))
                   for k in sorted(set(a_exact) | set(a_heur)))
        diff_total += diff
        # every exact arrow must match the parent column's emit site
        by_seq = {x.seq: x for x in ev}
        for x in ev:
            if x.src >= 0 and x.parent >= 0 and x.parent in by_seq:
                p = by_seq[x.parent]
                ts = (x.emit_ns if x.emit_ns >= 0 else p.time_ns) / 1e3
                arrows_ok &= (p.node, ts) in a_exact
                exact_checked += 1
    print(f"arrow diff under {ARROW_PLAN.name}: exact vs stripped "
          f"heuristic differ on {diff_total} arrow anchors over "
          f"{n_arrow_seeds} seeds; all {exact_checked} exact arrows "
          f"match the parent column: {arrows_ok} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if not arrows_ok:
        failures.append("exact-arrows-wrong")
    if diff_total == 0 and not smoke:
        failures.append("heuristic-never-differs")

    verdict = "PASS" if not failures else f"FAIL ({', '.join(failures)})"
    print(f"# verdict: {verdict} — every ring row carries exact lineage "
          f"(seq / parent / Lamport clock) folded on device for free "
          f"when off; a violation's backward cone replaces the whole "
          f"ring in forensics, and Perfetto arrows are provenance, not "
          f"guesses")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
