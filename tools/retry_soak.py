"""Client-retry soak: clean models under aggressive retries, the
gray-failure retry-amplification law, and the non-idempotent-apply hunt
that only the attempt-aware detector can win. The RETRY evidence
artifact.

Three certificates:

1. **Clean models are retry-proof** — kvchaos and shardkv (clean
   builds) under a ``chaos.RetryPolicy`` army plus a gray-failure slow
   link: thousands of re-sent attempts, ZERO violations from the full
   history-checker set (stale/RYW floors for kvchaos; exactly_once +
   shard_coverage for shardkv). A correctly deduplicating state machine
   does not care how aggressively the client re-sends.
2. **Retry amplification under gray failure** — the same offered load
   with and without the slow link: the slow link multiplies delivered
   re-sends >= 2x (the madsim-class motivation for modeling retries in
   the simulator rather than leaving them to user code — the policy is
   part of the failure surface, and the books prove it).
3. **The hunt only the new detector can win** — ``shardkv`` with the
   planted ``bug="noidem"`` (applies every delivered attempt; the
   deduplication guard removed) under the retried army: the coverage-
   guided hunt finds exactly-once violations, the final-state
   ``shard_coverage`` checker catches ZERO of the same seeds (the
   double-applied puts corrupt no shard bookkeeping), the first find is
   ddmin-shrunk under the campaign's own RetrySpec, and the shrunk
   literal replays to the identical violation and trace hash — twice.

Usage: python tools/retry_soak.py [n_seeds] > RETRY_r14.txt
       python tools/retry_soak.py --smoke    (tiny sizes — rides
                                              `make check`)
Exit 0 iff every certificate holds.
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore  # noqa: E402
from madsim_tpu.chaos import (  # noqa: E402
    FaultPlan,
    GrayFailure,
    RetryPolicy,
    shrink_plan,
)
from madsim_tpu.check import (  # noqa: E402
    exactly_once,
    read_your_writes,
    shard_coverage,
    stale_reads,
)
from madsim_tpu.engine import (  # noqa: E402
    MET_RETRY,
    MET_RETRY_GIVEUP,
    EngineConfig,
    LatencySpec,
    search_seeds,
)
from madsim_tpu.models import kvchaos as kv_mod  # noqa: E402
from madsim_tpu.models import shardkv as sk_mod  # noqa: E402
from madsim_tpu.models import make_kvchaos, make_shardkv  # noqa: E402

N_OPS = 16
KV_POLICY = RetryPolicy(timeout_ns=50_000_000, max_attempts=3,
                        backoff_base_ns=10_000_000, backoff_mult=2.0,
                        jitter=0.5)
SK_POLICY = RetryPolicy(timeout_ns=8_000_000, max_attempts=3,
                        backoff_base_ns=4_000_000, backoff_mult=2.0,
                        jitter=0.25)
KV_CFG = EngineConfig(pool_size=96, time_limit_ns=450_000_000,
                      clog_backoff_max_ns=2_000_000_000)
SK_CFG = EngineConfig(pool_size=96, time_limit_ns=600_000_000)
KV_STEPS = 3000
SK_STEPS = 3000
LAT = LatencySpec(ops=N_OPS, phases=3, phase_ns=1 << 27)
SK_LAT = LatencySpec(ops=N_OPS)


def kv_plans():
    army = kv_mod.client_army(n_ops=N_OPS, t_min_ns=5_000_000,
                              t_max_ns=280_000_000, n_replicas=2,
                              retry=KV_POLICY)
    gray = GrayFailure(targets=(0, 3), n_links=1, mult_min=6, mult_max=12)
    return (FaultPlan((army,), name="kv-retry-quiet"),
            FaultPlan((army, gray), name="kv-retry-gray"))


def sk_plan(name):
    return FaultPlan(
        (sk_mod.client_army(n_ops=N_OPS, t_min_ns=5_000_000,
                            t_max_ns=280_000_000, retry=SK_POLICY),
         GrayFailure(targets=(0, 1), n_links=1, mult_min=8, mult_max=16)),
        name=name,
    )


def main() -> None:
    smoke = "--smoke" in sys.argv
    argv = [a for a in sys.argv[1:] if a != "--smoke"]
    n_seeds = int(argv[0]) if argv else 2048
    if smoke:
        n_seeds = 64
    hunt_batch = 32 if smoke else 128
    generations = 2 if smoke else 3
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    quiet_plan, gray_plan = kv_plans()
    print(f"# retry soak{' (smoke)' if smoke else ''}: {n_seeds} seeds, "
          f"platform={jax.devices()[0].platform}")
    print(f"# kv policy {KV_POLICY.timeout_ns // 10**6}ms x"
          f"{KV_POLICY.max_attempts} | sk policy "
          f"{SK_POLICY.timeout_ns // 10**6}ms x{SK_POLICY.max_attempts} "
          f"| gray plan {gray_plan.hash()}")

    # ---- certificate 1: clean models are retry-proof ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    wl_kv = make_kvchaos(writes=12, n_replicas=2, chaos=False, army=True,
                         record=True)
    r_kv = search_seeds(
        wl_kv, KV_CFG, None, n_seeds=n_seeds, max_steps=KV_STEPS,
        plan=gray_plan, latency=LAT, metrics=True, require_halt=False,
        history_invariant=lambda h: stale_reads(h) & read_your_writes(h),
    )
    kv_retries = int(np.asarray(r_kv.met)[:, MET_RETRY].sum())
    print(f"kvchaos clean under retries: {len(r_kv.failing_seeds)} "
          f"violations / {n_seeds} seeds, {kv_retries} re-sent attempts, "
          f"{int(np.asarray(r_kv.met)[:, MET_RETRY_GIVEUP].sum())} "
          f"give-ups ({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)

    t0 = time.monotonic()  # lint: allow(wall-clock)
    wl_sk = make_shardkv(record=True, chaos=False, army=True)
    plan_sk = sk_plan("sk-retry-clean")

    def sk_inv(h):
        return (exactly_once(h, sk_mod.OP_ARMY_PUT)
                & shard_coverage(h, sk_mod.OP_SHARD_OWN,
                                 sk_mod.OP_SHARD_WRITE))

    r_sk = search_seeds(
        wl_sk, SK_CFG, None, n_seeds=n_seeds, max_steps=SK_STEPS,
        plan=plan_sk, latency=SK_LAT, metrics=True, require_halt=False,
        history_invariant=sk_inv,
    )
    sk_retries = int(np.asarray(r_sk.met)[:, MET_RETRY].sum())
    print(f"shardkv clean under retries: {len(r_sk.failing_seeds)} "
          f"violations / {n_seeds} seeds, {sk_retries} re-sent attempts "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if len(r_kv.failing_seeds) or len(r_sk.failing_seeds):
        failures.append("clean-model-violated-under-retries")
    if kv_retries == 0 or sk_retries == 0:
        failures.append("cert1-vacuous-no-retries")

    # ---- certificate 2: gray failure amplifies re-sends ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    amp_seeds = max(64, n_seeds // 4)
    ones = lambda v: np.ones(np.asarray(v["halted"]).shape[0], bool)  # noqa: E731
    base = search_seeds(
        wl_kv, KV_CFG, ones, n_seeds=amp_seeds, max_steps=KV_STEPS,
        plan=quiet_plan, latency=LAT, metrics=True, require_halt=False,
    )
    slow = search_seeds(
        wl_kv, KV_CFG, ones, n_seeds=amp_seeds, max_steps=KV_STEPS,
        plan=gray_plan, latency=LAT, metrics=True, require_halt=False,
    )
    rb = int(np.asarray(base.met)[:, MET_RETRY].sum())
    rs = int(np.asarray(slow.met)[:, MET_RETRY].sum())
    ratio = rs / rb if rb else float("inf")
    print(f"retry amplification over {amp_seeds} seeds: quiet {rb} "
          f"re-sends, gray-failure {rs} -> x{ratio:.2f} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if rs < 2 * rb or rs == 0:
        failures.append("gray-amplification-below-2x")

    # ---- certificate 3: the hunt only exactly_once can win ----
    t0 = time.monotonic()  # lint: allow(wall-clock)
    wl_bug = make_shardkv(record=True, chaos=False, army=True,
                          bug="noidem")
    hunt_plan = sk_plan("sk-noidem-hunt")
    rt = hunt_plan.retry_spec()

    def hinv(h):
        return exactly_once(h, sk_mod.OP_ARMY_PUT)

    hunt = explore.run(
        wl_bug, SK_CFG, hunt_plan, history_invariant=hinv,
        generations=generations, batch=hunt_batch, root_seed=14,
        max_steps=SK_STEPS, cov_words=32, select_top=16, max_ops=2,
        latency=SK_LAT,
    )
    print(f"noidem hunt: {len(hunt.violations)} exactly-once violations "
          f"/ {hunt.sims} sims "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    if not hunt.violations:
        failures.append("noidem-not-found")
    else:
        # the final-state checker must be blind on the SAME evidence
        t0 = time.monotonic()  # lint: allow(wall-clock)
        cov_catches = eo_catches = 0
        box = {}

        def both_inv(h):
            box["cov"] = shard_coverage(h, sk_mod.OP_SHARD_OWN,
                                        sk_mod.OP_SHARD_WRITE)
            return exactly_once(h, sk_mod.OP_ARMY_PUT)

        checked = hunt.violations[: 3 if smoke else 8]
        for e in checked:
            rep = search_seeds(
                wl_bug, SK_CFG, None,
                seeds=np.asarray([e.seed], np.uint64),
                max_steps=SK_STEPS, plan=e.plan, history_invariant=both_inv,
                latency=SK_LAT, require_halt=False, retry=rt,
            )
            eo_catches += int(not bool(np.asarray(rep.ok)[0]))
            cov_catches += int(not bool(box["cov"][0]))
        print(f"  detector exclusivity over {len(checked)} banked finds: "
              f"exactly_once catches {eo_catches}, final-state "
              f"shard_coverage catches {cov_catches} "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        if eo_catches != len(checked):
            failures.append("banked-find-not-reproducible")
        if cov_catches != 0:
            failures.append("final-state-checker-not-blind")

        # shrink the first find under the campaign's own RetrySpec,
        # then replay the shrunk literal twice: identical verdict+trace
        t0 = time.monotonic()  # lint: allow(wall-clock)
        e = hunt.violations[0]
        res = shrink_plan(wl_bug, SK_CFG, e.seed, e.plan,
                          history_invariant=hinv, max_steps=SK_STEPS,
                          latency=SK_LAT, retry=rt)
        print(f"  ddmin: {len(e.plan.events)} -> {len(res.events)} chaos "
              f"events in {res.rounds} rounds / {res.tested} probes "
              f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
        traces = []
        for _ in range(2):
            rep = search_seeds(
                wl_bug, SK_CFG, None,
                seeds=np.asarray([e.seed], np.uint64),
                max_steps=SK_STEPS, plan=res.plan, history_invariant=hinv,
                latency=SK_LAT, require_halt=False, retry=rt,
            )
            assert not bool(np.asarray(rep.ok)[0])
            traces.append(int(np.asarray(rep.traces)[0]))
        replay_ok = traces[0] == traces[1] == int(res.trace)
        print(f"  shrunk repro replays identically (trace "
              f"{res.trace:#x}): {replay_ok}")
        if not replay_ok:
            failures.append("shrunk-repro-diverges")

    verdict = "PASS" if not failures else f"FAIL ({', '.join(failures)})"
    print(f"# verdict: {verdict} — the retry policy is simulator state "
          f"(seed-pure timers, exact books), gray failure measurably "
          f"amplifies re-sends, and the attempt-aware exactly_once "
          f"detector catches the non-idempotent apply no final-state "
          f"invariant can see")
    print(f"# done in {time.monotonic() - t_all:.0f}s wall")  # lint: allow(wall-clock)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
