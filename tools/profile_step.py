"""Per-config step profile: phase wall breakdown + HLO cost analysis.

Replaces the hand-run PROFILE_CPU_r05 flow: for each requested bench
config this tool measures where a fused step's wall time actually goes
— by timing ABLATED step variants whose difference isolates one phase —
and attaches XLA's own HLO cost analysis (flop / byte counts) for the
compiled step, so a perf claim can be attributed to a phase instead of
guessed. Ablated variants change values (nop handlers, shrunk pools);
they exist only to difference wall times, never to verify anything —
trace identity is tools/step_goldens.py's job.

Rows (JSONL, one per config):

    {"config": ..., "n_seeds": ..., "n_steps": ...,
     "ns_per_seed_step": {"full": ..., "nop_handlers": ...,
                          "placement_scatter": ..., "pool_half": ...,
                          "emits_k1": ...},
     "attribution": {"handlers": ..., "pool+placement (half-pool "
                     "delta)": ..., "emit+rng lanes (k1 delta)": ...},
     "hlo": {"flops": ..., "bytes_accessed": ..., "transcendentals": ...}}

Usage:

    python tools/profile_step.py [config ...] > PROFILE_CPU_rNN.jsonl
    python tools/profile_step.py --pool-sweep   # ISSUE-13 pool-size axis
    make profile

The ``--pool-sweep`` axis measures the O(E)-vs-O(ready) claim behind
the readiness-partitioned pool (ISSUE 13): raftlog at pool sizes
512/2048/8192 with the client army on and off, timing the flat
lowering against the indexed one (both write lowerings) plus
nop-handler ablations, so "the flat pop/free-search scales with pool
width and the index removes it" is a measured attribution, not an
asserted one (evidence PROFILE_CPU_r07.jsonl).

Not part of tier-1 (pure measurement, no assertions).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import _bootstrap  # noqa: F401  (sys.path for tools/)

import numpy as np

import jax
from jax import lax

from madsim_tpu.engine import EngineConfig, LatencySpec, make_init
from madsim_tpu.engine.core import make_step
from madsim_tpu.models import BENCH_SPECS

DEFAULT_CONFIGS = ("raftlog", "kvchaos", "raft")
N_SEEDS = 4096
N_STEPS = 200

# the pool-size sweep axis (ISSUE 13): (pool_size, n_seeds) — seeds
# shrink as pools grow so the flat O(E) cells stay within budget
POOL_SWEEP = ((512, 512), (2048, 256), (8192, 128))
POOL_SWEEP_STEPS = 200


def _nop_handler(ctx):
    return ctx.state, ctx.emits().build()


def _time_variant(wl, cfg, n_seeds, n_steps, **mk) -> float:
    """Best-of-3 wall of a jitted n_steps scan, ns per seed-step."""
    step = jax.vmap(make_step(wl, cfg, **mk))

    def run(st):
        def body(s, _):
            return step(s), None

        final, _ = lax.scan(body, st, None, length=n_steps)
        return final

    r = jax.jit(run)
    st = make_init(wl, cfg)(np.arange(n_seeds, dtype=np.uint64))
    jax.block_until_ready(r(st))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(r(st))
        best = min(best, time.perf_counter() - t0)  # lint: allow(wall-clock)
    return best / (n_seeds * n_steps) * 1e9


def _hlo_cost(wl, cfg) -> dict:
    """XLA's cost analysis of ONE vmapped step (the scan body)."""
    step = jax.vmap(make_step(wl, cfg))
    st = make_init(wl, cfg)(np.arange(N_SEEDS, dtype=np.uint64))
    try:
        cost = jax.jit(step).lower(st).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return {
            "flops": cost.get("flops"),
            "bytes_accessed": cost.get("bytes accessed"),
            "transcendentals": cost.get("transcendentals"),
        }
    except Exception as exc:  # cost analysis is best-effort per backend
        return {"error": repr(exc)}


def profile_config(name: str, n_seeds: int = N_SEEDS, n_steps: int = N_STEPS) -> dict:
    factory, cfg_kwargs, _s, _n = BENCH_SPECS[name]
    wl, cfg = factory(), EngineConfig(**cfg_kwargs)
    wl_nop = dataclasses.replace(
        wl, handlers=tuple(_nop_handler for _ in wl.handlers),
        handler_names=None,
    )
    cfg_half = dataclasses.replace(
        cfg, pool_size=max(wl.n_nodes + 1, cfg.pool_size // 2)
    )
    wl_k1 = dataclasses.replace(
        wl_nop, max_emits=1, payload_words=0, handler_names=None
    )

    ns = {
        "full": _time_variant(wl, cfg, n_seeds, n_steps),
        "nop_handlers": _time_variant(wl_nop, cfg, n_seeds, n_steps),
        "placement_scatter": _time_variant(
            wl, cfg, n_seeds, n_steps, placement="scatter"
        ),
        "pool_half": _time_variant(wl_nop, cfg_half, n_seeds, n_steps),
        "emits_k1": _time_variant(wl_k1, cfg, n_seeds, n_steps),
    }
    row = {
        "config": name,
        "platform": jax.devices()[0].platform,
        "n_seeds": n_seeds,
        "n_steps": n_steps,
        "ns_per_seed_step": {k: round(v, 1) for k, v in ns.items()},
        "attribution": {
            "handlers": round(ns["full"] - ns["nop_handlers"], 1),
            "pool+placement (half-pool delta)": round(
                ns["nop_handlers"] - ns["pool_half"], 1
            ),
            "emit+rng lanes (k1 delta)": round(
                ns["nop_handlers"] - ns["emits_k1"], 1
            ),
        },
        "hlo": _hlo_cost(wl, cfg),
    }
    return row


def _time_pool_variant(wl, cfg, rows, slots, lat, n_seeds, n_steps, **mk) -> float:
    """Best-of-2 wall of a jitted plan-seeded scan, ns per seed-step."""
    step = jax.vmap(make_step(wl, cfg, layout="scatter", latency=lat, **mk))

    def run(st):
        final, _ = lax.scan(
            lambda s, _: (step(s), None), st, None, length=n_steps
        )
        return final

    r = jax.jit(run)
    init = make_init(wl, cfg, plan_slots=slots, latency=lat,
                     pool_index=mk.get("pool_index"))
    seeds = np.arange(n_seeds, dtype=np.uint64)
    st = init(seeds, rows) if rows is not None else init(seeds)
    jax.block_until_ready(r(st))  # compile
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(r(st))
        best = min(best, time.perf_counter() - t0)  # lint: allow(wall-clock)
    return best / (n_seeds * n_steps) * 1e9


def profile_pool_sweep() -> list:
    """The ISSUE-13 pool-size axis: raftlog x {512, 2048, 8192} x
    {army on, off}, flat vs indexed (both write lowerings) vs
    nop-handler ablations — pop/free-search wall attributed by
    differencing, exactly the profile methodology above."""
    from madsim_tpu.chaos import CrashStorm, FaultPlan
    from madsim_tpu.models import make_raftlog
    from madsim_tpu.models import raftlog as rl_mod

    out = []
    for pool, n_seeds in POOL_SWEEP:
        for army in (True, False):
            wl = make_raftlog(record=True, army=army)
            wl_nop = dataclasses.replace(
                wl, handlers=tuple(_nop_handler for _ in wl.handlers),
                handler_names=None,
            )
            cfg = EngineConfig(pool_size=pool, loss_p=0.02,
                               clog_backoff_max_ns=2_000_000_000)
            if army:
                n_ops = max(pool // 2 - 64, 64)
                plan = FaultPlan((
                    rl_mod.client_army(
                        n_ops=n_ops, t_min_ns=5_000_000,
                        t_max_ns=3_000_000_000,
                    ),
                    CrashStorm(targets=tuple(range(5)), n=1,
                               t_min_ns=50_000_000, t_max_ns=200_000_000,
                               down_min_ns=20_000_000,
                               down_max_ns=80_000_000),
                ))
                lat = LatencySpec(ops=n_ops, phases=3)
                slots = plan.slots
                rows = plan.compile_batch(
                    np.arange(n_seeds, dtype=np.uint64), wl=wl
                )
            else:
                n_ops, lat, slots, rows = 0, None, 0, None

            def t(w, **mk):
                return _time_pool_variant(
                    w, cfg, rows, slots, lat, n_seeds, POOL_SWEEP_STEPS,
                    **mk,
                )

            ns = {
                "flat": t(wl, pool_index=False),
                "indexed": t(wl, pool_index=True, placement="scatter"),
                "indexed_rank_chains": t(wl, pool_index=True,
                                         placement="rank"),
                "flat_nop": t(wl_nop, pool_index=False),
                "indexed_nop": t(wl_nop, pool_index=True,
                                 placement="scatter"),
            }
            out.append({
                "config": "raftlog/pool-sweep",
                "platform": jax.devices()[0].platform,
                "pool_size": pool,
                "army_ops": n_ops,
                "n_seeds": n_seeds,
                "n_steps": POOL_SWEEP_STEPS,
                "ns_per_seed_step": {k: round(v, 1) for k, v in ns.items()},
                "attribution": {
                    "handlers": round(ns["flat"] - ns["flat_nop"], 1),
                    "pop+placement (index delta)": round(
                        ns["flat"] - ns["indexed"], 1
                    ),
                    "pop argmin + free search (nop index delta)": round(
                        ns["flat_nop"] - ns["indexed_nop"], 1
                    ),
                },
                "speedup_indexed": round(ns["flat"] / ns["indexed"], 2),
            })
            print(json.dumps(out[-1]), flush=True)
    return out


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "--pool-sweep"]
    sweep = "--pool-sweep" in sys.argv[1:]
    names = args or ([] if sweep else list(DEFAULT_CONFIGS))
    for name in names:
        if name not in BENCH_SPECS:
            raise SystemExit(f"unknown config {name!r} (BENCH_SPECS)")
        row = profile_config(name)
        print(json.dumps(row), flush=True)
    if sweep:
        profile_pool_sweep()


if __name__ == "__main__":
    main()
