"""Per-config step profile: phase wall breakdown + HLO cost analysis.

Replaces the hand-run PROFILE_CPU_r05 flow: for each requested bench
config this tool measures where a fused step's wall time actually goes
— by timing ABLATED step variants whose difference isolates one phase —
and attaches XLA's own HLO cost analysis (flop / byte counts) for the
compiled step, so a perf claim can be attributed to a phase instead of
guessed. Ablated variants change values (nop handlers, shrunk pools);
they exist only to difference wall times, never to verify anything —
trace identity is tools/step_goldens.py's job.

Rows (JSONL, one per config):

    {"config": ..., "n_seeds": ..., "n_steps": ...,
     "ns_per_seed_step": {"full": ..., "nop_handlers": ...,
                          "placement_scatter": ..., "pool_half": ...,
                          "emits_k1": ...},
     "attribution": {"handlers": ..., "pool+placement (half-pool "
                     "delta)": ..., "emit+rng lanes (k1 delta)": ...},
     "hlo": {"flops": ..., "bytes_accessed": ..., "transcendentals": ...}}

Usage:

    python tools/profile_step.py [config ...] > PROFILE_CPU_rNN.jsonl
    make profile

Not part of tier-1 (pure measurement, no assertions).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import _bootstrap  # noqa: F401  (sys.path for tools/)

import numpy as np

import jax
from jax import lax

from madsim_tpu.engine import EngineConfig, make_init
from madsim_tpu.engine.core import make_step
from madsim_tpu.models import BENCH_SPECS

DEFAULT_CONFIGS = ("raftlog", "kvchaos", "raft")
N_SEEDS = 4096
N_STEPS = 200


def _nop_handler(ctx):
    return ctx.state, ctx.emits().build()


def _time_variant(wl, cfg, n_seeds, n_steps, **mk) -> float:
    """Best-of-3 wall of a jitted n_steps scan, ns per seed-step."""
    step = jax.vmap(make_step(wl, cfg, **mk))

    def run(st):
        def body(s, _):
            return step(s), None

        final, _ = lax.scan(body, st, None, length=n_steps)
        return final

    r = jax.jit(run)
    st = make_init(wl, cfg)(np.arange(n_seeds, dtype=np.uint64))
    jax.block_until_ready(r(st))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(r(st))
        best = min(best, time.perf_counter() - t0)  # lint: allow(wall-clock)
    return best / (n_seeds * n_steps) * 1e9


def _hlo_cost(wl, cfg) -> dict:
    """XLA's cost analysis of ONE vmapped step (the scan body)."""
    step = jax.vmap(make_step(wl, cfg))
    st = make_init(wl, cfg)(np.arange(N_SEEDS, dtype=np.uint64))
    try:
        cost = jax.jit(step).lower(st).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return {
            "flops": cost.get("flops"),
            "bytes_accessed": cost.get("bytes accessed"),
            "transcendentals": cost.get("transcendentals"),
        }
    except Exception as exc:  # cost analysis is best-effort per backend
        return {"error": repr(exc)}


def profile_config(name: str, n_seeds: int = N_SEEDS, n_steps: int = N_STEPS) -> dict:
    factory, cfg_kwargs, _s, _n = BENCH_SPECS[name]
    wl, cfg = factory(), EngineConfig(**cfg_kwargs)
    wl_nop = dataclasses.replace(
        wl, handlers=tuple(_nop_handler for _ in wl.handlers),
        handler_names=None,
    )
    cfg_half = dataclasses.replace(
        cfg, pool_size=max(wl.n_nodes + 1, cfg.pool_size // 2)
    )
    wl_k1 = dataclasses.replace(
        wl_nop, max_emits=1, payload_words=0, handler_names=None
    )

    ns = {
        "full": _time_variant(wl, cfg, n_seeds, n_steps),
        "nop_handlers": _time_variant(wl_nop, cfg, n_seeds, n_steps),
        "placement_scatter": _time_variant(
            wl, cfg, n_seeds, n_steps, placement="scatter"
        ),
        "pool_half": _time_variant(wl_nop, cfg_half, n_seeds, n_steps),
        "emits_k1": _time_variant(wl_k1, cfg, n_seeds, n_steps),
    }
    row = {
        "config": name,
        "platform": jax.devices()[0].platform,
        "n_seeds": n_seeds,
        "n_steps": n_steps,
        "ns_per_seed_step": {k: round(v, 1) for k, v in ns.items()},
        "attribution": {
            "handlers": round(ns["full"] - ns["nop_handlers"], 1),
            "pool+placement (half-pool delta)": round(
                ns["nop_handlers"] - ns["pool_half"], 1
            ),
            "emit+rng lanes (k1 delta)": round(
                ns["nop_handlers"] - ns["emits_k1"], 1
            ),
        },
        "hlo": _hlo_cost(wl, cfg),
    }
    return row


def main() -> None:
    names = sys.argv[1:] or list(DEFAULT_CONFIGS)
    for name in names:
        if name not in BENCH_SPECS:
            raise SystemExit(f"unknown config {name!r} (BENCH_SPECS)")
        row = profile_config(name)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
