"""Device-resident verification A/B: history-verified sweeps with the
detectors on device vs the host numpy path. The VERIFY evidence
artifact (ISSUE 14).

Four certificates:

1. **Verdict identity + fold accounting** — ``search_seeds(
   device_check=...)`` produces bit-identical per-seed verdicts to the
   numpy ``history_invariant`` path (``check.device.
   screens_invariant``) on the kvchaos record model, clean AND planted
   lost-write mutant, across the lockstep and the compacted
   (prefix-compacting) runner; and the fold is loud and lossless:
   original count == hist_count + hist_fold per seed, flagged seeds'
   columns verbatim equal to the unscreened runner's.
2. **Same-box interleaved A/B: the history-verified campaign.** The
   host driver was the ONLY path for ``history_invariant`` hunts
   (ROADMAP item 1); the device driver now runs them end-to-end with
   the detectors traced into the generation program. The SAME guided
   history hunt (kvchaos record, stale/lost-write + read-your-writes +
   monotonic-reads screens) runs alternately on both drivers,
   interleaved rounds, bit-identical campaign outcomes asserted. The
   certificate: device ≥ 3x host generations/s at ≥65k seeds per
   generation with history invariants on (warm-up round reported, not
   scored — the campaign_bench discipline: on this box only the A/B
   ratio means anything, never absolutes). The generation-program
   cache is profiler-certified across the rounds: retraces == 1 per
   (key, mode) including the new screen key component.
3. **Transfer bytes + verification wall** — verification's
   host-transfer payload at A/B scale, from the array shapes that
   actually cross: the numpy path moves the full history columns
   (word + t + count + drop); the device path moves ceil(S/32) verdict
   words plus the *flagged* seeds' full histories (the Wing–Gong
   escalation input). Certificate: ≥ 10x reduction on the mutant sweep
   (real flags — no free lunch from a clean batch). The wall split
   (sim-only / +numpy detectors / +device screen) prints alongside.
4. **Find path** — a smaller (4096 seeds/gen) device history hunt on
   the mutant finds the lost write, outcomes identical to the host
   driver, the find replays to its recorded trace + verdict through
   the host driver's replay path, and the flagged seed's escalated
   full history fails exact Wing–Gong KV linearizability (the PR-1
   cross-check: vectorized catches are exact-confirmed).

The A/B horizon is short (the campaign_bench argument: on this CPU
"device" the sim step is ~2 orders slower than accelerator silicon, so
a long horizon buries the driver+verification overhead both arms share
the sim for).

Usage: python tools/verify_bench.py [batch] [gens] [rounds] > VERIFY_r09.txt
       python tools/verify_bench.py --smoke   (lean `make check` gate:
           identity + fold + bytes accounting + a tiny A/B, no floors)
Defaults: batch 65536, gens 4, rounds 2 (+1 warm-up).
Exit 0 iff every certificate holds (throughput/bytes floors skipped
under --smoke).
"""

import _bootstrap  # noqa: F401  (repo root on sys.path)

import statistics
import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from madsim_tpu import explore  # noqa: E402
from madsim_tpu.chaos import CrashStorm, FaultPlan  # noqa: E402
from madsim_tpu.chaos.plan import stack_plan_rows  # noqa: E402
from madsim_tpu.check import device as dcheck  # noqa: E402
from madsim_tpu.check.linearize import check_kv  # noqa: E402
from madsim_tpu.engine import EngineConfig, make_init, search_seeds  # noqa: E402
from madsim_tpu.engine.compact import make_run_compacted  # noqa: E402
from madsim_tpu.models import make_kvchaos  # noqa: E402
from madsim_tpu.obs import prof  # noqa: E402

CFG = EngineConfig(pool_size=40, loss_p=0.02,
                   clog_backoff_max_ns=2_000_000_000)
SCREENS = (
    dcheck.stale_reads(),
    dcheck.read_your_writes(),
    dcheck.monotonic_reads(),
)
HOST_INV = dcheck.screens_invariant(SCREENS)
PLAN = FaultPlan(
    (CrashStorm(targets=(1, 2, 3, 4), n=2, t_min_ns=20_000_000,
                t_max_ns=400_000_000, down_min_ns=50_000_000,
                down_max_ns=250_000_000),),
    name="verify-bench",
)
WRITES = 5
MAX_STEPS = 96
COV_WORDS = 32


def _fingerprint(rep):
    return (
        [(e.id, e.generation, e.parent, e.seed, e.plan.hash(), e.trace,
          e.new_bits) for e in rep.corpus],
        rep.cov_map.tolist(),
        [(e.seed, e.trace) for e in rep.violations],
        rep.curve,
        rep.viol_curve,
    )


def main() -> None:
    smoke = "--smoke" in sys.argv[1:]
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    if smoke:
        batch = int(args[0]) if args else 2048
        gens, rounds = 2, 1
    else:
        batch = int(args[0]) if args else 65536
        gens = int(args[1]) if len(args) > 1 else 4
        rounds = int(args[2]) if len(args) > 2 else 2
    failures = []
    t_all = time.monotonic()  # lint: allow(wall-clock)
    print(f"# verify bench: batch {batch}, {gens} generations, "
          f"{rounds} timed rounds (+1 warm-up), smoke={smoke}, "
          f"platform={jax.devices()[0].platform}")
    print(f"# kvchaos writes={WRITES} record, plan {PLAN.hash()}, "
          f"max_steps {MAX_STEPS}, screens "
          f"{'+'.join(s.kind for s in SCREENS)}")

    wl_clean = make_kvchaos(writes=WRITES, record=True)
    wl_bug = make_kvchaos(writes=WRITES, record=True, bug=True)

    # ---- certificate 1: verdict identity + fold accounting ----
    print("== cert 1: device == numpy verdicts, lockstep + compact ==")
    id_seeds = min(batch, 8192)
    id_ok = True
    for wl, tag in ((wl_clean, "clean"), (wl_bug, "mutant")):
        kw = dict(n_seeds=id_seeds, max_steps=600, require_halt=False)
        host = search_seeds(wl, CFG, None, history_invariant=HOST_INV, **kw)
        dev = search_seeds(wl, CFG, None, device_check=SCREENS, **kw)
        cmp_ = search_seeds(wl, CFG, None, device_check=SCREENS,
                            compact=True, **kw)
        same = (np.array_equal(host.ok, dev.ok)
                and np.array_equal(host.ok, cmp_.ok))
        # the fold is loud and lossless: screened vs unscreened
        # compacted runs of the identical batch
        fseeds = np.arange(min(id_seeds, 2048), dtype=np.uint64)
        init = make_init(wl, CFG)
        plain = make_run_compacted(wl, CFG, 600)(init(fseeds))
        folded = make_run_compacted(wl, CFG, 600, hist_screen=SCREENS)(
            init(fseeds)
        )
        fold_ok = np.array_equal(
            folded.hist_count + folded.hist_fold, plain.hist_count
        )
        flagged_rows = ~folded.hist_ok
        fold_ok = fold_ok and np.array_equal(
            folded.hist_word[flagged_rows], plain.hist_word[flagged_rows]
        ) and np.array_equal(
            folded.hist_t[flagged_rows], plain.hist_t[flagged_rows]
        )
        frac = (
            folded.hist_fold.sum() / max(plain.hist_count.sum(), 1)
        )
        print(f"  {tag}: {id_seeds} seeds, verdicts identical={same}, "
              f"fold lossless={fold_ok} "
              f"({frac:.0%} of records folded before transfer), "
              f"{len(dev.failing_seeds)} violations, "
              f"{len(dev.flagged_idx)} flagged -> escalated")
        id_ok = id_ok and same and fold_ok
    if not id_ok:
        failures.append("verdict-identity")
    print(f"cert1 {'PASS' if id_ok else 'FAIL'}")

    # ---- certificate 3: bytes + verification wall at A/B scale ----
    print("== cert 3: verification wall + host-transfer bytes ==")
    kw = dict(n_seeds=batch, max_steps=600, require_halt=False)
    search_seeds(wl_bug, CFG, None, device_check=SCREENS, **kw)  # warm
    t0 = time.monotonic()  # lint: allow(wall-clock)
    search_seeds(wl_bug, CFG, lambda v: np.ones(batch, bool), **kw)
    w_sim = time.monotonic() - t0  # lint: allow(wall-clock)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    search_seeds(wl_bug, CFG, None, history_invariant=HOST_INV, **kw)
    w_host = time.monotonic() - t0  # lint: allow(wall-clock)
    t0 = time.monotonic()  # lint: allow(wall-clock)
    dev = search_seeds(wl_bug, CFG, None, device_check=SCREENS, **kw)
    w_dev = time.monotonic() - t0  # lint: allow(wall-clock)
    hcap = wl_bug.history.capacity
    host_bytes = batch * hcap * 5 * 4 + batch * hcap * 8 + 2 * batch * 4
    fl = len(dev.flagged_idx)
    # the device path still materializes the per-seed hist_count +
    # hist_drop counters host-side (the overflow quarantine reads
    # them), so they count against it — only the big word/t columns
    # are replaced by verdict words + flagged rows
    dev_bytes = (
        dev.verdict_words.nbytes + 2 * batch * 4
        + fl * (hcap * 5 * 4 + hcap * 8 + 2 * 4)
    )
    ratio_b = host_bytes / max(dev_bytes, 1)
    print(f"  wall: sim-only {w_sim:.2f}s | +numpy detectors "
          f"{w_host:.2f}s | +device screen {w_dev:.2f}s")
    print(f"  bytes/sweep: full columns {host_bytes / 1e6:.1f} MB vs "
          f"{dev.verdict_words.nbytes} B verdict words + {fl} flagged "
          f"histories = {dev_bytes / 1e6:.3f} MB -> "
          f"{ratio_b:.0f}x reduction")
    bytes_ok = smoke or ratio_b >= 10.0
    if not bytes_ok:
        failures.append("bytes-below-10x")
    print(f"cert3 {'PASS' if bytes_ok else 'FAIL'}")

    # ---- certificate 2: interleaved A/B, history-verified campaign ----
    print("== cert 2: interleaved A/B, host vs device history hunt ==")
    kw = dict(generations=gens, batch=batch, root_seed=7,
              max_steps=MAX_STEPS, cov_words=COV_WORDS)
    fps = []
    walls = {"host": [], "device": []}
    profiler = prof.ProgramProfiler()
    for r in range(rounds + 1):
        tag = "warmup " if r == 0 else f"round {r}"
        t0 = time.monotonic()  # lint: allow(wall-clock)
        rep_h = explore.run(
            wl_clean, CFG, PLAN, invariant=None,
            history_invariant=HOST_INV, **kw,
        )
        wh = time.monotonic() - t0  # lint: allow(wall-clock)
        with prof.profiled(profiler):
            t0 = time.monotonic()  # lint: allow(wall-clock)
            rep_d = explore.run_device(
                wl_clean, CFG, PLAN, invariant=None,
                history_check=SCREENS, **kw,
            )
            wd = time.monotonic() - t0  # lint: allow(wall-clock)
        fps += [_fingerprint(rep_h), _fingerprint(rep_d)]
        print(f"  {tag}: host {wh:7.1f}s ({gens / wh:.3f} gens/s) | "
              f"device {wd:6.1f}s ({gens / wd:.3f} gens/s) | "
              f"ratio {wh / wd:.2f}x")
        if r > 0:
            walls["host"].append(wh)
            walls["device"].append(wd)
    med_h = statistics.median(walls["host"])
    med_d = statistics.median(walls["device"])
    ratio = med_h / med_d
    identical = all(f == fps[0] for f in fps[1:])
    retr = profiler.retraces("explore.device")
    retrace_ok = bool(retr) and all(v == 1 for v in retr.values())
    print(f"  medians: host {med_h:.1f}s vs device {med_d:.1f}s -> "
          f"device {ratio:.2f}x generations/s with history screens on")
    print(f"  outcomes identical across {len(fps)} runs: {identical} | "
          f"_GEN_CACHE retraces == 1 per key over {rounds + 1} device "
          f"campaigns: {retrace_ok} {dict(retr)}")
    ab_ok = identical and retrace_ok and (smoke or ratio >= 3.0)
    if not identical:
        failures.append("outcomes-not-bit-identical")
    if not retrace_ok:
        failures.append("gen-cache-retraced")
    if not smoke and ratio < 3.0:
        failures.append("device-below-3x")
    print(f"cert2 {'PASS' if ab_ok else 'FAIL'}")

    # ---- certificate 4: the find path at 4096 seeds/gen ----
    print("== cert 4: device history hunt finds the lost write ==")
    t0 = time.monotonic()  # lint: allow(wall-clock)
    fkw = dict(generations=3, batch=min(batch, 4096), root_seed=7,
               max_steps=600, cov_words=COV_WORDS)
    rep_h = explore.run(wl_bug, CFG, PLAN, invariant=None,
                        history_invariant=HOST_INV, **fkw)
    rep_d = explore.run_device(wl_bug, CFG, PLAN, invariant=None,
                               history_check=SCREENS, **fkw)
    v_same = _fingerprint(rep_h) == _fingerprint(rep_d)
    found = bool(rep_d.violations)
    replay_ok = exact_ok = False
    if found:
        e = rep_d.violations[0]
        r = explore.replay_entry(wl_bug, CFG, e,
                                 history_invariant=HOST_INV,
                                 max_steps=600)
        replay_ok = (int(r.traces[0]) == e.trace and not bool(r.ok[0]))
        # escalation: rerun the entry under the device screen; the
        # flagged seed's FULL history must fail exact Wing-Gong KV
        # linearizability too
        dev_rep = search_seeds(
            wl_bug, CFG, None, seeds=np.asarray([e.seed], np.uint64),
            plan_rows=stack_plan_rows([e.plan]),
            dup_rows=e.plan.uses_dup(), device_check=SCREENS,
            max_steps=600, require_halt=False,
        )
        fh = dev_rep.flagged_history
        exact_ok = (
            fh is not None and len(fh) == 1 and not check_kv(fh.ops(0)).ok
        )
    print(f"  host {len(rep_h.violations)} == device "
          f"{len(rep_d.violations)} violations, identical {v_same}, "
          f"found {found}, host-driver replay {replay_ok}, "
          f"Wing-Gong escalation confirms {exact_ok} "
          f"({time.monotonic() - t0:.1f}s)")  # lint: allow(wall-clock)
    find_ok = v_same and found and replay_ok and exact_ok
    if not find_ok:
        failures.append("find-path")
    print(f"cert4 {'PASS' if find_ok else 'FAIL'}")

    dt = time.monotonic() - t_all  # lint: allow(wall-clock)
    print(f"# verify bench: {'PASS' if not failures else 'FAIL'} "
          f"({dt:.0f}s)"
          f"{' failures=' + ','.join(failures) if failures else ''}")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
