"""campaign_top — a terminal dashboard over a running campaign's JSONL.

``explore.run`` / ``run_device`` with ``telemetry=obs.FlightRecorder(
path)`` (or a bare ``obs.JsonlSink``) append one JSON record per
campaign event; this tool tails that file and renders a one-screen
summary that refreshes in place — the ``top`` for a multi-hour hunt:

* generation progress + generations/s + ETA (from heartbeats when the
  flight recorder stamped them, recomputed from the wall splits
  otherwise);
* coverage bits (with a sparkline of the whole curve), corpus size,
  violation count;
* the last generation's wall split (mutate / compile / dispatch /
  admit / sync) as percentages — compile shows up ONLY on cold
  programs, so a nonzero steady-state compile column is the re-trace
  bug this round's cache killed;
* device memory (live-buffer bytes from heartbeats) and profiled
  program totals from the ``flight_summary`` once the campaign ends.

With tenant-tagged records (a farm session writing N campaigns
through one ``FlightRecorder.tagged`` per tenant — see
``madsim_tpu/farm/``) or with several JSONL paths, the frame becomes
the farm dashboard: one summary row per (stream, tenant) — progress,
coverage, corpus, violations, last-slice wall split — plus the shared
generation-program cache accounting from the flight summary. A stream
with no tags renders exactly as before.

Usage: python tools/campaign_top.py CAMPAIGN.jsonl [MORE.jsonl ...]
                                    [--interval 2] [--once]

Reads only; works on live, finished, and crashed (torn last line)
logs alike. ``--once`` renders a single frame and exits (CI/smoke).
A multi-stream/multi-tenant tail runs until interrupted (a farm has
no single campaign_end to wait for).
"""

import argparse
import json
import sys
import time

_SPARK = "▁▂▃▄▅▆▇█"
_WALL_KEYS = ("mutate_wall_s", "compile_wall_s", "dispatch_wall_s",
              "admit_wall_s", "sync_wall_s")


def read_records(path: str) -> list:
    """Whole-file JSONL read tolerating a torn last line.

    Deliberately duplicates ``obs.flight._records`` (same torn-tail
    rule) rather than importing it: the dashboard must start in
    milliseconds and run on boxes without jax — importing madsim_tpu
    pulls the whole engine. Keep the two policies in step."""
    out = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    break
    except FileNotFoundError:
        pass
    return out


def sparkline(values, width: int = 40) -> str:
    if not values:
        return ""
    vals = values[-width:]
    lo, hi = min(vals), max(vals)
    span = max(hi - lo, 1)
    return "".join(
        _SPARK[min(int((v - lo) * (len(_SPARK) - 1) / span), len(_SPARK) - 1)]
        for v in vals
    )


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024:
            return f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}PB"


def render(records: list, path: str = "") -> str:
    """One dashboard frame from a campaign's records (pure function —
    the smoke test renders synthetic histories through here)."""
    start = next(
        (r for r in records if r.get("event") == "campaign_start"), {}
    )
    gens = [r for r in records if r.get("event") == "generation"]
    hbs = [r for r in records if r.get("event") == "heartbeat"]
    compiles = [r for r in records if r.get("event") == "compile"]
    end = next(
        (r for r in records if r.get("event") == "campaign_end"), None
    )
    summary = next(
        (r for r in records if r.get("event") == "flight_summary"), None
    )
    lines = [
        f"== campaign_top {path}".rstrip() + " ==",
        f"workload {start.get('workload', '?')} | driver "
        f"{start.get('driver', 'host')} | batch {start.get('batch', '?')} "
        f"| root_seed {start.get('root_seed', '?')} | space "
        f"{start.get('plan_hash', '?')}",
    ]
    target = int(start.get("generations", 0) or 0)
    done = len(gens)
    if target:
        frac = min(done / target, 1.0)
        bar = "#" * int(frac * 30)
        state = "DONE" if end else "running"
        lines.append(
            f"progress [{bar:<30}] {done}/{target} generations ({state})"
        )
    rate = eta = None
    if hbs:
        rate = hbs[-1].get("gens_per_s")
        eta = hbs[-1].get("eta_s")
    elif gens:
        wall = sum(
            sum(float(g.get(k, 0.0)) for k in _WALL_KEYS)
            + float(g.get("host_wall_s", 0.0))
            - float(g.get("mutate_wall_s", 0.0))
            - float(g.get("admit_wall_s", 0.0))
            for g in gens
        )
        rate = done / wall if wall > 0 else None
        eta = (target - done) / rate if rate and target > done else None
    if rate:
        lines.append(
            f"rate {rate:.3f} gens/s | sims {gens[-1].get('sims', '?') if gens else 0}"
            + (f" | ETA {eta:.0f}s" if eta else "")
        )
    if gens:
        curve = [g.get("cov_bits", 0) for g in gens]
        g = gens[-1]
        lines.append(
            f"coverage {curve[-1]} bits {sparkline(curve)} | corpus "
            f"{g.get('corpus_size', '?')} | violations "
            f"{g.get('violations', '?')}"
        )
        walls = [(k.replace("_wall_s", ""), float(g.get(k, 0.0)))
                 for k in _WALL_KEYS if g.get(k) is not None]
        total = sum(w for _, w in walls)
        if total > 0:
            split = " ".join(
                f"{name} {w / total:.0%}" for name, w in walls if w > 0
            )
            lines.append(f"last gen wall {total:.2f}s: {split}")
    if hbs and hbs[-1].get("live_buffer_bytes") is not None:
        hb = hbs[-1]
        lines.append(
            f"device memory {_fmt_bytes(hb['live_buffer_bytes'])} across "
            f"{hb.get('live_buffers', '?')} live buffers"
            + (f" | allocator {_fmt_bytes(hb['allocator_bytes_in_use'])}"
               if hb.get("allocator_bytes_in_use") is not None else "")
        )
    if compiles:
        cw = sum(
            float(c.get("trace_s", 0)) + float(c.get("lower_s", 0))
            + float(c.get("compile_s", 0))
            for c in compiles
        )
        lines.append(
            f"compiles {len(compiles)} ({cw:.1f}s total) | last: "
            f"{compiles[-1].get('program', '?')}"
        )
    if summary is not None and summary.get("programs"):
        lines.append("programs (flight summary):")
        for p in summary["programs"]:
            lines.append(
                f"  {p['name']:<28} traces {p['traces']} calls "
                f"{p['calls']} compile {p['compile_wall_s']:.2f}s "
                f"exec {p['execute_wall_s']:.2f}s"
            )
    if end is not None:
        lines.append(
            f"campaign ended: {end.get('violations', 0)} violations, "
            f"{end.get('cov_bits', 0)} coverage bits, "
            f"{end.get('sims', 0)} sims"
        )
    return "\n".join(lines)


def group_streams(paths) -> list:
    """Split telemetry paths into renderable (label, records) groups.

    Records carrying a ``"tenant"`` tag (a farm session sharing one
    recorder) split their stream into one group per tenant, in first-
    appearance order; untagged records in a tagged stream (the shared
    flight summary, untagged heartbeats) go to a ``farm`` group only
    if it would not be the sole group. Untagged single-campaign logs
    come back as one group — the single-stream dashboard."""
    groups: list = []
    for path in paths:
        records = read_records(path)
        by_tenant: dict = {}
        shared = []
        for r in records:
            t = r.get("tenant")
            if t is None:
                shared.append(r)
            else:
                by_tenant.setdefault(t, []).append(r)
        prefix = f"{path}:" if len(paths) > 1 else ""
        if not by_tenant:
            groups.append((f"{prefix}{path}" if not prefix else path,
                           records))
        else:
            for t, recs in by_tenant.items():
                groups.append((f"{prefix}{t}", recs))
            if any(r.get("event") == "flight_summary" for r in shared):
                groups.append((f"{prefix}(farm)", shared))
    return groups


def _tenant_row(label: str, records: list) -> str:
    gens = [r for r in records if r.get("event") == "generation"]
    ends = [r for r in records if r.get("event") == "campaign_end"]
    g = gens[-1] if gens else {}
    walls = [(k.replace("_wall_s", ""), float(g.get(k, 0.0)))
             for k in _WALL_KEYS if g.get(k)]
    total = sum(w for _, w in walls)
    split = " ".join(f"{n} {w / total:.0%}" for n, w in walls if w > 0) \
        if total > 0 else "-"
    slices = len(ends)
    return (
        f"  {label:<22} {len(gens):>5} {g.get('cov_bits', '-'):>6} "
        f"{g.get('corpus_size', '-'):>6} {g.get('violations', '-'):>5} "
        f"{slices:>6}  {split}"
    )


def render_farm(groups) -> str:
    """The multi-tenant frame: one row per (stream, tenant) group plus
    the shared program-cache accounting (pure function, like
    :func:`render`)."""
    lines = [
        "== campaign_top (farm) ==",
        f"  {'tenant':<22} {'gens':>5} {'cov':>6} {'corpus':>6} "
        f"{'viol':>5} {'slices':>6}  last-gen wall",
    ]
    summary = None
    for label, records in groups:
        s = next((r for r in reversed(records)
                  if r.get("event") == "flight_summary"), None)
        if s is not None:
            summary = s
        if any(r.get("event") == "generation" for r in records):
            lines.append(_tenant_row(label, records))
    cache = (summary or {}).get("gen_cache")
    if cache:
        lines.append(
            f"gen cache {cache.get('entries', '?')}/{cache.get('max', '?')} "
            f"programs resident, {cache.get('evictions', 0)} evictions"
        )
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="campaign telemetry JSONL(s) to tail")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    args = ap.parse_args()
    while True:
        groups = group_streams(args.paths)
        if len(groups) == 1:
            frame = render(groups[0][1], args.paths[0])
        else:
            frame = render_farm(groups)
        if args.once:
            print(frame)
            return 0
        # clear + home, then the frame (plain ANSI keeps deps at zero)
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        if len(groups) == 1 and any(
            r.get("event") == "campaign_end" for r in groups[0][1]
        ):
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
