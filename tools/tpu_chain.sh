#!/bin/bash
# Full TPU artifact chain, highest-value first. The tunnel historically
# survives ~5-15 min after recovering and tends to wedge DURING long
# compiles, so every step is small, banks its artifact the moment it
# completes, and all children share one persistent XLA compile cache
# (/tmp/jax_bench_cache) — a retry after a mid-compile wedge replays
# the finished compiles from cache and only re-exposes the tunnel to
# the one compile that killed it. A re-run (the watcher retries on a
# nonzero exit) resumes at the first missing artifact.
# Called by tpu_watch.sh; safe to run by hand.
# Usage: tools/tpu_chain.sh [stamp]   (default r05)
set -u
cd "$(dirname "$0")/.."
STAMP="${1:-r05}"
case "$STAMP" in
  *.jsonl|*/*) echo "usage: tpu_chain.sh [stamp] — got a path: $STAMP" >&2; exit 2 ;;
esac
MARK="/tmp/tpu_chain_${STAMP}"
fail=0
log() { echo "$(date -u +%H:%M:%S) chain: $*" >&2; }

# Run ONE bench.py child and bank its row iff it measured on the
# requested platform (a wedge mid-run silently degrades jax to CPU,
# and banking that would spend the TPU window on numbers the CPU
# fallback already provides).
bench_row() {  # name seeds steps platform [out_file]
  local name="$1" seeds="$2" steps="$3" platform="$4"
  local out="${5:-ROW_${STAMP}_${name}.json}"
  if [ -f "$out" ]; then
    log "row $name already banked, skipping"
    return 0
  fi
  log "bench row $name ($platform)"
  if BENCH_CHILD="$name" BENCH_PLATFORM="$platform" BENCH_SEEDS="$seeds" \
     BENCH_STEPS="$steps" timeout 600 python bench.py \
     > "$out.tmp" 2>> /tmp/bench_watch.err \
     && tail -1 "$out.tmp" | grep -q '"value"'; then
    if [ "$platform" = default ] \
        && tail -1 "$out.tmp" | grep -q '"platform": "cpu"'; then
      rm -f "$out.tmp"
      log "row $name degraded to CPU, not banked"
      return 1
    fi
    mv "$out.tmp" "$out"
    log "row $name banked"
    return 0
  fi
  rm -f "$out.tmp"
  log "row $name FAILED"
  return 1
}

# ---- Step 0: the headline cell alone, FIRST: raft @65,536 seeds
# through the sized-dispatch harness. Guarantees the single number the
# verdicts ask for even if the tunnel dies minutes later.
if ! bench_row raft 65536 600 default "RAFT_TPU_${STAMP}.json"; then
  log "raft headline failed/degraded, aborting chain"
  exit 1
fi

# ---- Step 1: cross-backend determinism certificate (the artifact of
# record for BASELINE's trace-divergence metric; three verdicts have
# asked for a fresh one). Promoted above the remaining bench cells:
# if the window dies after this step, the round still has its headline
# AND its determinism certificate. 256 seeds keeps the 16 compiles
# small; the compile cache makes a retry cheap.
if [ -f "${MARK}.cross.done" ]; then
  log "cross-backend already banked, skipping"
elif [ -f "${MARK}.cross.realfail" ]; then
  # a previous run failed WITH the accelerator alive — a deterministic
  # failure (divergence/script bug), not a wedge; retrying every
  # window would block all later steps forever. Leave it for a human.
  log "cross-backend previously failed with tunnel alive, skipping (see ${MARK}.cross.realfail)"
  fail=1
else
  log "cross-backend determinism"
  if timeout 2100 python examples/cross_backend_check.py 256 CROSS_BACKEND.json \
      >> /tmp/bench_watch.err 2>&1; then
    touch "${MARK}.cross.done"
    log "CROSS_BACKEND banked"
  else
    rc=$?
    # distinguish wedge (probe dead -> exit, watcher resumes here)
    # from deterministic failure (probe alive -> record + move on)
    if BENCH_CHILD=probe BENCH_PLATFORM=default timeout 90 python bench.py \
        2>/dev/null | grep -q '"ok": true'; then
      echo "rc=$rc with accelerator alive at $(date -u +%H:%M:%S)" \
        > "${MARK}.cross.realfail"
      log "cross-backend FAILED deterministically (rc=$rc), continuing chain"
      fail=1
    else
      log "cross-backend failed with tunnel wedged (rc=$rc), aborting for retry"
      exit 1
    fi
  fi
fi

# ---- Step 2: the remaining bench cells, ONE CONFIG AT A TIME, each
# banked to its own row file the moment it completes (the round-5
# session-2 wedge ate two finished TPU cells because the monolithic
# bench step validated only the final file). Config table mirrors
# bench.py CONFIGS; pingpong is the deliberately-CPU single-seed
# latency config and needs no tunnel.
rows_ok=1
bench_row pingpong 1 300 cpu || rows_ok=0  # CPU by design, no tunnel needed
for spec in "microbench 1024 1100" "raftlog 16384 4000" \
            "kvchaos 4096 900" "broadcast 16384 500"; do
  # shellcheck disable=SC2086
  if ! bench_row $spec default; then
    # first degraded TPU row means the tunnel just wedged — don't burn
    # 600 s timeouts on the remaining rows against a dead backend
    rows_ok=0
    log "TPU row failed, skipping remaining rows this window"
    break
  fi
done
if [ "$rows_ok" != 1 ]; then
  # abort rather than burn sweep/profile/vmem timeouts on a backend
  # that just proved wedged — the watcher re-probes and resumes here
  log "bench rows incomplete, aborting chain (resume re-enters at the missing row)"
  exit 1
fi

# Assemble the full-bench artifact from the headline + banked rows:
# bench.py owns the schema (child rows in CONFIGS order + the parent
# summary line with vs_baseline) — BENCH_ASSEMBLE reuses its code.
if [ ! -f "BENCH_TPU_${STAMP}.jsonl" ]; then
  if BENCH_ASSEMBLE="raft=RAFT_TPU_${STAMP}.json,microbench=ROW_${STAMP}_microbench.json,pingpong=ROW_${STAMP}_pingpong.json,broadcast=ROW_${STAMP}_broadcast.json,kvchaos=ROW_${STAMP}_kvchaos.json,raftlog=ROW_${STAMP}_raftlog.json" \
      python bench.py > "BENCH_TPU_${STAMP}.jsonl.tmp" 2>> /tmp/bench_watch.err; then
    mv "BENCH_TPU_${STAMP}.jsonl.tmp" "BENCH_TPU_${STAMP}.jsonl"
    log "BENCH_TPU_${STAMP}.jsonl assembled from banked rows"
  else
    rm -f "BENCH_TPU_${STAMP}.jsonl.tmp"
    log "assembly FAILED"
    exit 1
  fi
fi

# (A raft@262,144 "bonus" cell was considered here and dropped: the
# scaling sweep below already measures that exact cell with the same
# sized-dispatch instrument, and an extra 600 s step ahead of the
# unbanked artifacts would contradict highest-value-first ordering.)

# ---- Step 3: scaling sweep. A step is banked only if its marker AND
# artifact exist AND the artifact really ran on the accelerator.
if [ -f "${MARK}.sweep.done" ] && [ -f "SWEEP_TPU_${STAMP}.jsonl" ] \
    && ! grep -q '"platform": "cpu"' SCALING_SWEEP.json; then
  log "sweep already banked, skipping"
else
  log "scaling sweep"
  # rotate away a pre-resume-format partial file (its rows lack the
  # "platform" field, are not resumable, and would duplicate cells)
  if [ -f "SWEEP_TPU_${STAMP}.jsonl" ] \
      && grep -q '"config"' "SWEEP_TPU_${STAMP}.jsonl" \
      && ! grep -q '"platform"' "SWEEP_TPU_${STAMP}.jsonl"; then
    mv "SWEEP_TPU_${STAMP}.jsonl" "SWEEP_TPU_${STAMP}.jsonl.preresume"
    log "rotated pre-resume-format sweep rows aside"
  fi
  # append + --resume: ~27 cells cannot fit one 5-15 min window; rows
  # banked by earlier windows are reused, only missing cells measure.
  # Success requires BOTH artifacts free of CPU rows — the jsonl is the
  # raw material consumers may quote, not just SCALING_SWEEP.json.
  if timeout 3000 python examples/scaling_sweep.py SCALING_SWEEP.json \
      --resume "SWEEP_TPU_${STAMP}.jsonl" \
      >> "SWEEP_TPU_${STAMP}.jsonl" 2>> /tmp/bench_watch.err \
      && ! grep -q '"platform": "cpu"' SCALING_SWEEP.json \
      && ! grep -q '"platform": "cpu"' "SWEEP_TPU_${STAMP}.jsonl"; then
    touch "${MARK}.sweep.done"
    log "sweep banked"
  else
    log "sweep FAILED or on CPU (partial rows kept for resume)"
    fail=1
  fi
fi

# ---- Step 4: step-ablation profile.
if [ -f "${MARK}.profile.done" ] && [ -f "PROFILE_TPU_${STAMP}.jsonl" ] \
    && head -1 "PROFILE_TPU_${STAMP}.jsonl" | grep -vq '"platform": "cpu"'; then
  log "profile already banked, skipping"
else
  log "step ablation profile"
  if timeout 1800 python examples/profile_step.py 65536 \
      > "PROFILE_TPU_${STAMP}.jsonl" 2>> /tmp/bench_watch.err \
      && head -1 "PROFILE_TPU_${STAMP}.jsonl" | grep -vq '"platform": "cpu"'; then
    touch "${MARK}.profile.done"
    log "profile banked"
  else
    log "profile FAILED or on CPU (partial rows kept)"
    fail=1
  fi
fi

# ---- Step 5: vmem kernel head-to-head (exploratory: pallas may not
# compile on this backend at all — a failure here doesn't fail the
# chain).
if [ -f "${MARK}.vmem.done" ] && [ -f "VMEM_TPU_${STAMP}.jsonl" ]; then
  log "vmem probe already banked, skipping"
else
  log "vmem kernel head-to-head"
  if timeout 900 python examples/vmem_probe.py 65536 64 2048 \
      > "VMEM_TPU_${STAMP}.jsonl" 2>> /tmp/bench_watch.err \
      && head -1 "VMEM_TPU_${STAMP}.jsonl" | grep -vq '"platform": "cpu"'; then
    touch "${MARK}.vmem.done"
    log "vmem probe banked"
  else
    log "vmem probe failed or on CPU (non-fatal)"
  fi
fi

log "done (fail=$fail)"
exit "$fail"
