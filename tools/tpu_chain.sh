#!/bin/bash
# Full TPU artifact chain, highest-value first (the tunnel historically
# survives ~15 min after recovering): headline bench -> cross-backend
# determinism -> scaling sweep -> step ablation. Every step banks its
# artifact and a done-marker as it completes, so a mid-chain wedge
# keeps the wins already banked and a re-run (the watcher retries on a
# nonzero exit) resumes at the first missing step instead of repeating
# finished ones. Called by tpu_watch.sh; safe to run by hand.
# Usage: tools/tpu_chain.sh [stamp]   (default r04)
set -u
cd "$(dirname "$0")/.."
STAMP="${1:-r04}"
case "$STAMP" in
  *.jsonl|*/*) echo "usage: tpu_chain.sh [stamp] — got a path: $STAMP" >&2; exit 2 ;;
esac
MARK="/tmp/tpu_chain_${STAMP}"
fail=0

# Step 0 — the headline cell alone, FIRST: raft @65,536 seeds through
# the sized-dispatch harness (~3-5 min incl. compile). The tunnel
# historically survives ~15 min after recovering; the full bench below
# needs ~25. Banking this one cell first guarantees the single number
# three rounds of verdicts have asked for even if the tunnel dies
# minutes later.
if [ -f "RAFT_TPU_${STAMP}.json" ]; then
  echo "$(date -u +%H:%M:%S) chain: raft headline already banked, skipping" >&2
else
  echo "$(date -u +%H:%M:%S) chain: raft headline cell" >&2
  if BENCH_CHILD=raft BENCH_PLATFORM=default BENCH_SEEDS=65536 \
     BENCH_STEPS=600 timeout 600 python bench.py \
     > "RAFT_TPU_${STAMP}.json.tmp" 2>> /tmp/bench_watch.err \
     && tail -1 "RAFT_TPU_${STAMP}.json.tmp" | grep -q '"value"' \
     && ! tail -1 "RAFT_TPU_${STAMP}.json.tmp" | grep -q '"platform": "cpu"'; then
    mv "RAFT_TPU_${STAMP}.json.tmp" "RAFT_TPU_${STAMP}.json"
    echo "$(date -u +%H:%M:%S) chain: raft headline banked:" >&2
    tail -1 "RAFT_TPU_${STAMP}.json" >&2
  else
    rm -f "RAFT_TPU_${STAMP}.json.tmp"
    echo "$(date -u +%H:%M:%S) chain: raft headline failed/degraded, aborting chain" >&2
    exit 1
  fi
fi

if [ -f "BENCH_TPU_${STAMP}.jsonl" ]; then
  echo "$(date -u +%H:%M:%S) chain: bench already banked, skipping" >&2
else
  echo "$(date -u +%H:%M:%S) chain: bench" >&2
  BENCH_BUDGET=1500 python bench.py > "BENCH_TPU_${STAMP}.jsonl.tmp" \
    2>> /tmp/bench_watch.err
  if tail -1 "BENCH_TPU_${STAMP}.jsonl.tmp" | grep -vq '"platform": "cpu"'; then
    mv "BENCH_TPU_${STAMP}.jsonl.tmp" "BENCH_TPU_${STAMP}.jsonl"
    echo "$(date -u +%H:%M:%S) chain: TPU bench banked" >&2
  else
    rm -f "BENCH_TPU_${STAMP}.jsonl.tmp"
    echo "$(date -u +%H:%M:%S) chain: bench degraded to CPU, aborting chain" >&2
    exit 1
  fi
fi

if [ -f "${MARK}.cross.done" ]; then
  echo "$(date -u +%H:%M:%S) chain: cross-backend already banked, skipping" >&2
else
  echo "$(date -u +%H:%M:%S) chain: cross-backend determinism" >&2
  # outer timeout > the script's own 2x900s subprocess budget
  if timeout 2100 python examples/cross_backend_check.py 256 CROSS_BACKEND.json \
      >> /tmp/bench_watch.err 2>&1; then
    touch "${MARK}.cross.done"
    echo "$(date -u +%H:%M:%S) chain: CROSS_BACKEND banked" >&2
  else
    echo "$(date -u +%H:%M:%S) chain: cross-backend FAILED (rc=$?)" >&2
    fail=1
  fi
fi

# a step is banked only if its marker AND artifact exist AND the
# artifact really ran on the accelerator — a mid-chain wedge silently
# degrades jax to CPU, and banking that would spend the TPU window on
# numbers the CPU fallback already provides
if [ -f "${MARK}.sweep.done" ] && [ -f "SWEEP_TPU_${STAMP}.jsonl" ] \
    && ! grep -q '"platform": "cpu"' SCALING_SWEEP.json; then
  echo "$(date -u +%H:%M:%S) chain: sweep already banked, skipping" >&2
else
  echo "$(date -u +%H:%M:%S) chain: scaling sweep" >&2
  if timeout 3000 python examples/scaling_sweep.py SCALING_SWEEP.json \
      > "SWEEP_TPU_${STAMP}.jsonl" 2>> /tmp/bench_watch.err \
      && ! grep -q '"platform": "cpu"' SCALING_SWEEP.json; then
    touch "${MARK}.sweep.done"
    echo "$(date -u +%H:%M:%S) chain: sweep banked" >&2
  else
    echo "$(date -u +%H:%M:%S) chain: sweep FAILED or on CPU (partial rows kept)" >&2
    fail=1
  fi
fi

if [ -f "${MARK}.profile.done" ] && [ -f "PROFILE_TPU_${STAMP}.jsonl" ] \
    && head -1 "PROFILE_TPU_${STAMP}.jsonl" | grep -vq '"platform": "cpu"'; then
  echo "$(date -u +%H:%M:%S) chain: profile already banked, skipping" >&2
else
  echo "$(date -u +%H:%M:%S) chain: step ablation profile" >&2
  if timeout 1800 python examples/profile_step.py 65536 \
      > "PROFILE_TPU_${STAMP}.jsonl" 2>> /tmp/bench_watch.err \
      && head -1 "PROFILE_TPU_${STAMP}.jsonl" | grep -vq '"platform": "cpu"'; then
    touch "${MARK}.profile.done"
    echo "$(date -u +%H:%M:%S) chain: profile banked" >&2
  else
    echo "$(date -u +%H:%M:%S) chain: profile FAILED or on CPU (partial rows kept)" >&2
    fail=1
  fi
fi

if [ -f "${MARK}.vmem.done" ] && [ -f "VMEM_TPU_${STAMP}.jsonl" ]; then
  echo "$(date -u +%H:%M:%S) chain: vmem probe already banked, skipping" >&2
else
  echo "$(date -u +%H:%M:%S) chain: vmem kernel head-to-head" >&2
  if timeout 900 python examples/vmem_probe.py 65536 64 2048 \
      > "VMEM_TPU_${STAMP}.jsonl" 2>> /tmp/bench_watch.err \
      && head -1 "VMEM_TPU_${STAMP}.jsonl" | grep -vq '"platform": "cpu"'; then
    touch "${MARK}.vmem.done"
    echo "$(date -u +%H:%M:%S) chain: vmem probe banked" >&2
  else
    # exploratory: pallas may not compile on this backend at all —
    # a failure here doesn't fail the chain
    echo "$(date -u +%H:%M:%S) chain: vmem probe failed or on CPU (non-fatal)" >&2
  fi
fi

echo "$(date -u +%H:%M:%S) chain: done (fail=$fail)" >&2
exit "$fail"
